"""The S3 REST wire surface: versioning, versions listing,
versionId object ops, XML ACLs, lifecycle, multipart — the round-4
gateway features at the reference's HTTP boundary
(src/rgw/rgw_rest_s3.cc:868-960 versioning, :2176-2209 ACL,
:2628 multipart; rgw_acl_s3.cc XML grammar), replayed through the
pure ``S3Frontend.handle()`` plus a cross-user matrix over real
sockets."""
import xml.etree.ElementTree as ET

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rgw import RGWLite, S3Frontend, serve
from ceph_tpu.rgw.http import sign_v2


def _local(tag):
    return tag.rsplit("}", 1)[-1]


def _find(el, name):
    for child in el:
        if _local(child.tag) == name:
            return child
    return None


def _findall(el, name):
    return [c for c in el if _local(c.tag) == name]


def _text(el, name, default=""):
    c = _find(el, name)
    return (c.text or "") if c is not None else default


def _code(body):
    """The S3 <Error><Code> element — asserts must check THIS, not a
    substring of the body (the Message echoes the reason too)."""
    return _text(ET.fromstring(body), "Code")


class S3Rest:
    """Signs v2 and speaks straight to handle() (no socket)."""

    DATE = "Thu, 01 Jan 2026 00:00:00 GMT"

    def __init__(self, fe, user):
        self.fe = fe
        self.user = user

    def req(self, method, path, body=b"", query=None, headers=None):
        hdrs = dict(headers or {})
        hdrs["Date"] = self.DATE
        sig = sign_v2(self.user["secret_key"], method, path, hdrs,
                      query or {})
        hdrs["Authorization"] = \
            f"AWS {self.user['access_key']}:{sig}"
        return self.fe.handle(method, path, hdrs, body, query or {})

    def xml(self, method, path, **kw):
        status, hdrs, body = self.req(method, path, **kw)
        assert status == 200, (status, body)
        return ET.fromstring(body)


@pytest.fixture()
def rest():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rgw.meta", size=3, pg_num=8)
    c.create_replicated_pool("rgw.data", size=3, pg_num=8)
    g = RGWLite(c.client("client.rgw"), "rgw.meta", "rgw.data")
    alice = g.create_user("alice", "Alice Doe")
    bob = g.create_user("bob", "Bob Roe")
    fe = S3Frontend(g)
    a, b = S3Rest(fe, alice), S3Rest(fe, bob)
    st, _, _ = a.req("PUT", "/b")
    assert st == 200
    return c, g, fe, a, b


def test_rest_versioning_suite(rest):
    """The gateway versioning matrix (test_rgw_versioning.py
    test_versioning_suite) replayed at the HTTP boundary."""
    c, g, fe, a, b = rest
    # never-versioned: empty VersioningConfiguration
    root = a.xml("GET", "/b", query={"versioning": ""})
    assert _local(root.tag) == "VersioningConfiguration"
    assert _find(root, "Status") is None
    # enable via the reference's XML request shape
    st, _, _ = a.req(
        "PUT", "/b", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Enabled</Status>"
             b"</VersioningConfiguration>")
    assert st == 200
    root = a.xml("GET", "/b", query={"versioning": ""})
    assert _text(root, "Status") == "Enabled"
    # two puts -> two version ids on the wire
    st, h1, _ = a.req("PUT", "/b/k", body=b"version-one")
    st, h2, _ = a.req("PUT", "/b/k", body=b"version-two")
    v1, v2 = h1["x-amz-version-id"], h2["x-amz-version-id"]
    assert v1 != v2
    # current GET answers newest and names its version
    st, h, body = a.req("GET", "/b/k")
    assert (st, body) == (200, b"version-two")
    assert h["x-amz-version-id"] == v2
    # versionId= reaches both
    st, _, body = a.req("GET", "/b/k", query={"versionId": v1})
    assert (st, body) == (200, b"version-one")
    # ?versions listing: newest first, IsLatest on the head
    root = a.xml("GET", "/b", query={"versions": ""})
    vers = _findall(root, "Version")
    assert [_text(v, "VersionId") for v in vers] == [v2, v1]
    assert [_text(v, "IsLatest") for v in vers] == ["true", "false"]
    # unversioned DELETE pushes a marker and says so in headers
    st, h, _ = a.req("DELETE", "/b/k")
    assert st == 204 and h["x-amz-delete-marker"] == "true"
    marker_vid = h["x-amz-version-id"]
    st, _, _ = a.req("GET", "/b/k")
    assert st == 404
    root = a.xml("GET", "/b", query={"versions": ""})
    markers = _findall(root, "DeleteMarker")
    assert len(markers) == 1
    assert _text(markers[0], "VersionId") == marker_vid
    # deleting the MARKER undeletes
    st, _, _ = a.req("DELETE", "/b/k", query={"versionId":
                                              marker_vid})
    assert st == 204
    st, _, body = a.req("GET", "/b/k")
    assert (st, body) == (200, b"version-two")
    # permanent delete of newest exposes predecessor
    st, _, _ = a.req("DELETE", "/b/k", query={"versionId": v2})
    assert st == 204
    st, _, body = a.req("GET", "/b/k")
    assert (st, body) == (200, b"version-one")
    # HEAD on a bad version
    st, _, _ = a.req("HEAD", "/b/k", query={"versionId": "nope"})
    assert st == 404


def test_rest_versioning_malformed_and_nochange(rest):
    c, g, fe, a, b = rest
    st, _, body = a.req("PUT", "/b", query={"versioning": ""},
                        body=b"<wat/>")
    assert st == 400 and b"MalformedXML" in body
    st, _, body = a.req(
        "PUT", "/b", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Sideways</Status>"
             b"</VersioningConfiguration>")
    assert st == 400
    # Status absent = VersioningNotChanged (rgw_rest_s3.cc parser)
    st, _, _ = a.req("PUT", "/b", query={"versioning": ""},
                     body=b"<VersioningConfiguration/>")
    assert st == 200
    assert g.get_bucket_versioning("b") is None


def test_rest_acl_xml_roundtrip(rest):
    """GET ?acl emits the reference policy grammar; PUT ?acl parses
    it back; a GET->PUT round trip is a fixed point."""
    c, g, fe, a, b = rest
    a.req("PUT", "/b/secret", body=b"alice-only")
    # bob can't read yet
    st, _, _ = b.req("GET", "/b/secret")
    assert st == 403
    # grant bob READ via the XML grammar
    policy = (
        '<AccessControlPolicy '
        'xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
        "<Owner><ID>alice</ID></Owner><AccessControlList>"
        '<Grant><Grantee xmlns:xsi="http://www.w3.org/2001/'
        'XMLSchema-instance" xsi:type="CanonicalUser">'
        "<ID>bob</ID></Grantee>"
        "<Permission>READ</Permission></Grant>"
        "</AccessControlList></AccessControlPolicy>")
    st, _, _ = a.req("PUT", "/b", query={"acl": ""},
                     body=policy.encode())
    assert st == 200
    st, _, body = b.req("GET", "/b/secret")
    assert (st, body) == (200, b"alice-only")
    # GET ?acl: owner + materialized FULL_CONTROL + bob grant
    root = a.xml("GET", "/b", query={"acl": ""})
    assert _local(root.tag) == "AccessControlPolicy"
    owner = _find(root, "Owner")
    assert _text(owner, "ID") == "alice"
    assert _text(owner, "DisplayName") == "Alice Doe"
    acl = _find(root, "AccessControlList")
    grants = _findall(acl, "Grant")
    got = [(_text(_find(gr, "Grantee"), "ID"),
            _text(gr, "Permission")) for gr in grants]
    assert got == [("alice", "FULL_CONTROL"), ("bob", "READ")]
    # round trip: PUT the exact GET body, nothing changes
    st, _, body1 = a.req("GET", "/b", query={"acl": ""})
    st, _, _ = a.req("PUT", "/b", query={"acl": ""}, body=body1)
    assert st == 200
    st, _, body2 = a.req("GET", "/b", query={"acl": ""})
    assert body1 == body2
    # group grants serialize as the reference's AllUsers URI
    st, _, _ = a.req("PUT", "/b", query={"acl": ""}, headers={
        "x-amz-acl": "public-read"})
    assert st == 200
    root = a.xml("GET", "/b", query={"acl": ""})
    uris = [_text(_find(gr, "Grantee"), "URI")
            for gr in _findall(_find(root, "AccessControlList"),
                               "Grant")]
    assert ("http://acs.amazonaws.com/groups/global/AllUsers"
            in uris)
    # malformed policies bounce with the S3 code
    st, _, body = a.req("PUT", "/b", query={"acl": ""},
                        body=b"<AccessControlPolicy><oops>")
    assert st == 400 and _code(body) == "MalformedACLError"
    st, _, body = a.req(
        "PUT", "/b", query={"acl": ""},
        body=b"<AccessControlPolicy><AccessControlList>"
             b"<Grant><Grantee xsi:type=\"CanonicalUser\" "
             b"xmlns:xsi=\"x\"><ID>bob</ID></Grantee>"
             b"<Permission>RULE</Permission></Grant>"
             b"</AccessControlList></AccessControlPolicy>")
    assert st == 400 and _code(body) == "MalformedACLError"


def test_rest_object_acl(rest):
    c, g, fe, a, b = rest
    a.req("PUT", "/b/o", body=b"data")
    st, _, _ = b.req("GET", "/b/o")
    assert st == 403
    # object-level grant without touching the bucket policy
    st, _, _ = a.req("PUT", "/b/o", query={"acl": ""},
                     headers={"x-amz-acl": "public-read"})
    assert st == 200
    st, _, body = b.req("GET", "/b/o")
    assert (st, body) == (200, b"data")
    root = a.xml("GET", "/b/o", query={"acl": ""})
    assert _text(_find(root, "Owner"), "ID") == "alice"
    # canned ACL directly on upload
    st, _, _ = a.req("PUT", "/b/o2", body=b"x",
                     headers={"x-amz-acl": "public-read"})
    assert st == 200
    st, _, body = b.req("GET", "/b/o2")
    assert (st, body) == (200, b"x")


def test_rest_versioned_uploader_owns_object(rest):
    """A WRITE grantee's PUT to a VERSIONED bucket records the
    uploader as object owner at entry level — so the follow-up
    x-amz-acl application (and later ACL reads) see bob, not the
    bucket owner."""
    c, g, fe, a, b = rest
    st, _, _ = a.req(
        "PUT", "/b", query={"versioning": ""},
        body=b"<VersioningConfiguration><Status>Enabled</Status>"
             b"</VersioningConfiguration>")
    assert st == 200
    g.put_bucket_acl("b", grants=[{"grantee": "bob",
                                   "permission": "WRITE"}])
    st, _, _ = b.req("PUT", "/b/bk", body=b"bob-data",
                     headers={"x-amz-acl": "public-read"})
    assert st == 200
    root = b.xml("GET", "/b/bk", query={"acl": ""})
    assert _text(_find(root, "Owner"), "ID") == "bob"


def test_rest_multipart(rest):
    """Initiate / parts / listing / manifest-validated complete /
    abort, all through the wire shapes (rgw_rest_s3.cc:2628)."""
    c, g, fe, a, b = rest
    root = a.xml("POST", "/b/big", query={"uploads": ""})
    assert _local(root.tag) == "InitiateMultipartUploadResult"
    uid = _text(root, "UploadId")
    assert uid
    # parts arrive out of order
    st, h2, _ = a.req("PUT", "/b/big", body=b"-part-two",
                      query={"uploadId": uid, "partNumber": "2"})
    st, h1, _ = a.req("PUT", "/b/big", body=b"part-one",
                      query={"uploadId": uid, "partNumber": "1"})
    assert st == 200
    # ?uploads bucket listing shows it in flight
    root = a.xml("GET", "/b", query={"uploads": ""})
    ups = _findall(root, "Upload")
    assert [( _text(u, "Key"), _text(u, "UploadId")) for u in ups] \
        == [("big", uid)]
    # uploadId GET lists parts ascending
    root = a.xml("GET", "/b/big", query={"uploadId": uid})
    parts = _findall(root, "Part")
    assert [_text(p, "PartNumber") for p in parts] == ["1", "2"]
    assert [_text(p, "ETag") for p in parts] == \
        [h1["ETag"], h2["ETag"]]
    # complete with a wrong etag -> InvalidPart, nothing committed
    bad = (f"<CompleteMultipartUpload><Part><PartNumber>1"
           f"</PartNumber><ETag>\"beef\"</ETag></Part>"
           f"</CompleteMultipartUpload>")
    st, _, body = a.req("POST", "/b/big", body=bad.encode(),
                        query={"uploadId": uid})
    assert st == 400 and _code(body) == "InvalidPart"
    # out-of-order manifest -> InvalidPartOrder
    oo = ("<CompleteMultipartUpload>"
          f"<Part><PartNumber>2</PartNumber><ETag>{h2['ETag']}"
          "</ETag></Part>"
          f"<Part><PartNumber>1</PartNumber><ETag>{h1['ETag']}"
          "</ETag></Part></CompleteMultipartUpload>")
    st, _, body = a.req("POST", "/b/big", body=oo.encode(),
                        query={"uploadId": uid})
    assert st == 400 and _code(body) == "InvalidPartOrder"
    # duplicate part numbers are not "sorted" either (strictness)
    dup = ("<CompleteMultipartUpload>"
           f"<Part><PartNumber>1</PartNumber><ETag>{h1['ETag']}"
           "</ETag></Part>"
           f"<Part><PartNumber>1</PartNumber><ETag>{h1['ETag']}"
           "</ETag></Part></CompleteMultipartUpload>")
    st, _, body = a.req("POST", "/b/big", body=dup.encode(),
                        query={"uploadId": uid})
    assert st == 400 and _code(body) == "InvalidPartOrder"
    # proper complete
    ok = ("<CompleteMultipartUpload>"
          f"<Part><PartNumber>1</PartNumber><ETag>{h1['ETag']}"
          "</ETag></Part>"
          f"<Part><PartNumber>2</PartNumber><ETag>{h2['ETag']}"
          "</ETag></Part></CompleteMultipartUpload>")
    root = a.xml("POST", "/b/big", body=ok.encode(),
                 query={"uploadId": uid})
    assert _local(root.tag) == "CompleteMultipartUploadResult"
    st, _, body = a.req("GET", "/b/big")
    assert (st, body) == (200, b"part-one-part-two")
    # the upload is gone
    st, _, body = a.req("GET", "/b/big", query={"uploadId": uid})
    assert st == 404 and _code(body) == "NoSuchUpload"
    # abort path
    root = a.xml("POST", "/b/tmp", query={"uploads": ""})
    uid2 = _text(root, "UploadId")
    a.req("PUT", "/b/tmp", body=b"zzz",
          query={"uploadId": uid2, "partNumber": "1"})
    st, _, _ = a.req("DELETE", "/b/tmp", query={"uploadId": uid2})
    assert st == 204
    st, _, _ = a.req("GET", "/b/tmp", query={"uploadId": uid2})
    assert st == 404


def test_rest_lifecycle(rest):
    c, g, fe, a, b = rest
    st, _, body = a.req("GET", "/b", query={"lifecycle": ""})
    assert st == 404 and _code(body) == "NoSuchLifecycleConfiguration"
    cfg = ("<LifecycleConfiguration><Rule><ID>expire-logs</ID>"
           "<Prefix>logs/</Prefix><Status>Enabled</Status>"
           "<Expiration><Days>30</Days></Expiration>"
           "<NoncurrentVersionExpiration><NoncurrentDays>5"
           "</NoncurrentDays></NoncurrentVersionExpiration>"
           "</Rule></LifecycleConfiguration>")
    st, _, _ = a.req("PUT", "/b", query={"lifecycle": ""},
                     body=cfg.encode())
    assert st == 200
    assert g.get_bucket_lifecycle("b") == [
        {"id": "expire-logs", "prefix": "logs/",
         "status": "Enabled", "expiration_days": 30,
         "noncurrent_days": 5}]
    root = a.xml("GET", "/b", query={"lifecycle": ""})
    rule = _find(root, "Rule")
    assert _text(rule, "ID") == "expire-logs"
    assert _text(_find(rule, "Expiration"), "Days") == "30"
    # a rule with no action is the gateway's MissingAction
    st, _, _ = a.req(
        "PUT", "/b", query={"lifecycle": ""},
        body=b"<LifecycleConfiguration><Rule><Prefix>x</Prefix>"
             b"<Status>Enabled</Status></Rule>"
             b"</LifecycleConfiguration>")
    assert st == 400
    st, _, _ = a.req("DELETE", "/b", query={"lifecycle": ""})
    assert st == 204
    st, _, _ = a.req("GET", "/b", query={"lifecycle": ""})
    assert st == 404


def test_rest_bucket_delete_is_policy_gated(rest):
    """Bucket DELETE rides the ACL engine (rgw_op.cc:2828-2832),
    not a raw owner comparison — matching the rest of the wire."""
    c, g, fe, a, b = rest
    st, _, _ = b.req("DELETE", "/b")
    assert st == 403
    # FULL_CONTROL grantee may delete, like the reference's policy
    # check (owner comparison alone would say no)
    g.put_bucket_acl("b", grants=[{"grantee": "bob",
                                   "permission": "FULL_CONTROL"}])
    st, _, _ = b.req("DELETE", "/b")
    assert st == 204
    st, _, _ = a.req("GET", "/b")
    assert st == 404


def test_rest_cross_user_matrix_over_sockets(rest):
    """The cross-user allow/deny matrix via real HTTP connections:
    every subresource speaks the same ACL engine."""
    import http.client

    c, g, fe, a, b = rest
    srv, port = serve(fe)
    try:
        def req(client, method, path, body=b"", headers=None):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=10)
            hdrs = dict(headers or {})
            hdrs["Date"] = client.DATE
            qs = path.split("?", 1)[1] if "?" in path else ""
            query = dict(kv.partition("=")[::2] for kv in qs.split("&")
                         if kv)
            sig = sign_v2(client.user["secret_key"], method,
                          path.split("?")[0], hdrs, query)
            hdrs["Authorization"] = \
                f"AWS {client.user['access_key']}:{sig}"
            conn.request(method, path, body=body, headers=hdrs)
            r = conn.getresponse()
            data = r.read()
            conn.close()
            return r.status, dict(r.getheaders()), data
        st, _, _ = req(a, "PUT", "/m")
        assert st == 200
        st, _, _ = req(a, "PUT", "/m/k")
        assert st == 200
        # bob: no READ -> versions listing and versioning denied
        st, _, _ = req(b, "GET", "/m?versions")
        assert st == 403
        st, _, _ = req(b, "GET", "/m?versioning")
        assert st == 403
        # bob: no WRITE -> multipart initiate denied
        st, _, _ = req(b, "POST", "/m/x?uploads")
        assert st == 403
        # bob: no WRITE_ACP -> can't grant himself access
        st, _, _ = req(b, "PUT", "/m?acl",
                       headers={"x-amz-acl": "public-read-write"})
        assert st == 403
        # alice opens it up; bob's ops flip to allowed
        st, _, _ = req(a, "PUT", "/m?acl",
                       headers={"x-amz-acl": "public-read-write"})
        assert st == 200
        st, _, _ = req(b, "GET", "/m?versions")
        assert st == 200
        st, h, data = req(b, "POST", "/m/x?uploads")
        assert st == 200
        uid = _text(ET.fromstring(data), "UploadId")
        st, _, _ = req(b, "PUT", f"/m/x?uploadId={uid}&partNumber=1",
                       body=b"bobpart")
        assert st == 200
        st, _, _ = req(b, "POST", f"/m/x?uploadId={uid}",
                       body=b"<CompleteMultipartUpload><Part>"
                            b"<PartNumber>1</PartNumber></Part>"
                            b"</CompleteMultipartUpload>")
        assert st == 200
        st, _, data = req(b, "GET", "/m/x")
        assert (st, data) == (200, b"bobpart")
        # bob still can't read ACLs (READ_ACP wasn't granted)
        st, _, _ = req(b, "GET", "/m?acl")
        assert st == 403
    finally:
        srv.shutdown()
