"""Mesh-sharded EC path: multi-device parity with the host oracle.

conftest.py forces an 8-device virtual CPU platform, so these genuinely
exercise the (stripe, shard) shardings and the digest collective.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ceph_tpu.gf.matrices import gf_gen_rs_matrix
from ceph_tpu.ec.rs_codec import MatrixRSCodec
from ceph_tpu.parallel import (
    make_mesh, mesh_shape_for, ShardedRS, pipeline_step,
    example_pipeline_args)


def test_mesh_shape_factoring():
    assert mesh_shape_for(8) == (4, 2)
    assert mesh_shape_for(1) == (1, 1)
    assert mesh_shape_for(7) == (7, 1)
    assert mesh_shape_for(4, max_shard=4) == (1, 4)


@pytest.mark.parametrize("n", [1, 2, 8])
def test_sharded_encode_matches_host(n):
    k, m, s, c = 8, 4, 16, 512
    mat = gf_gen_rs_matrix(k + m, k)
    host = MatrixRSCodec(mat)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(s, k, c), dtype=np.uint8)
    sharded = ShardedRS(mat, make_mesh(n))
    got = sharded.encode(data)
    expect = np.stack([host.encode(d) for d in data])
    assert np.array_equal(got, expect)


def test_sharded_decode_recovers_data():
    k, m, s, c = 4, 2, 8, 256
    mat = gf_gen_rs_matrix(k + m, k)
    rng = np.random.default_rng(8)
    data = rng.integers(0, 256, size=(s, k, c), dtype=np.uint8)
    sharded = ShardedRS(mat, make_mesh(8))
    coding = sharded.encode(data)
    # lose chunks 0 and 2; survivors 1,3,4,5
    srcs = [1, 3, 4, 5]
    all_chunks = np.concatenate([data, coding], axis=1)
    survivors = all_chunks[:, srcs, :]
    rec = sharded.decode_data(survivors, srcs, [0, 2])
    assert np.array_equal(rec[:, 0], data[:, 0])
    assert np.array_equal(rec[:, 1], data[:, 2])


def test_survivor_sharded_decode_xor_allreduce():
    """Contraction-sharded decode: each device holds a SLICE of the k
    survivors (no chip sees them all); the GF(2) reduction crosses the
    mesh as one psum-then-parity collective.  Byte-identical to the
    replicated-survivor decode and the host oracle."""
    k, m, s, c = 8, 4, 16, 256
    mat = gf_gen_rs_matrix(k + m, k)
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, size=(s, k, c), dtype=np.uint8)
    sharded = ShardedRS(mat, make_mesh(8))     # (4, 2) mesh
    coding = sharded.encode(data)
    allc = np.concatenate([data, coding], axis=1)
    srcs = [1, 2, 3, 5, 6, 7, 8, 10]           # lose 0, 4, 9, 11
    survivors = allc[:, srcs, :]
    want = [0, 4]
    via_collective = sharded.decode_data_survivor_sharded(
        survivors, srcs, want)
    via_replicated = sharded.decode_data(survivors, srcs, want)
    assert np.array_equal(via_collective, via_replicated)
    assert np.array_equal(via_collective[:, 0], data[:, 0])
    assert np.array_equal(via_collective[:, 1], data[:, 4])
    # a k not divisible by the shard axis is refused, not mis-sharded
    bad = ShardedRS(gf_gen_rs_matrix(5 + 2, 5), make_mesh(8))
    sv5 = np.zeros((8, 5, 64), np.uint8)
    with pytest.raises(ValueError):
        bad.decode_data_survivor_sharded(sv5, [0, 1, 2, 3, 4], [5])


def test_reshard_stripes_to_chunks_all_to_all():
    """The encode->distribution layout switch rides one all_to_all
    over the stripe axis (sequence<->head resharding analog): values
    are IDENTICAL, only the sharding moves."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ceph_tpu.parallel.mesh import STRIPE_AXIS

    k, m, s, c = 8, 4, 16, 256
    mat = gf_gen_rs_matrix(k + m, k)
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(s, k, c), dtype=np.uint8)
    sharded = ShardedRS(mat, make_mesh(8))     # stripe axis size 4
    coding = sharded.encode(data)
    allc = np.concatenate([data, coding], axis=1)
    out = sharded.reshard_stripes_to_chunks(jnp.asarray(allc))
    assert np.array_equal(np.asarray(out), allc)
    # the output really is chunk-sharded over the stripe axis
    want = NamedSharding(sharded.mesh, P(None, STRIPE_AXIS, None))
    assert out.sharding.is_equivalent_to(want, ndim=3)
    with pytest.raises(ValueError):
        sharded.reshard_stripes_to_chunks(
            jnp.zeros((8, 5, 64), jnp.uint8))   # 5 % 4 != 0


def test_pipeline_step_8dev():
    mesh = make_mesh(8)
    args = example_pipeline_args(mesh, s=8, k=8, m=4, c=256)
    with mesh:
        chunks, digests = jax.jit(pipeline_step)(*args)
    chunks = np.asarray(chunks)
    data = np.asarray(args[0])
    assert np.array_equal(chunks[:, :8, :], data)
    mat = gf_gen_rs_matrix(12, 8)
    host = MatrixRSCodec(mat)
    expect = np.stack([host.encode(d) for d in data])
    assert np.array_equal(chunks[:, 8:, :], expect)
    # the digest collective must match the same fold done in numpy
    c = chunks.shape[2]
    w = (np.arange(c, dtype=np.uint64) * 0x01000193 + 0x811C9DC5) \
        .astype(np.uint32)
    expect_digests = (chunks.astype(np.uint64) * w[None, None, :]) \
        .sum(axis=(0, 2)).astype(np.uint32)
    assert np.array_equal(np.asarray(digests), expect_digests)


def test_graft_entry_contract():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as ge
    fn, example_args = ge.entry()
    out = jax.jit(fn)(*example_args)
    assert out.shape == (16, 4, 4096)
    ge.dryrun_multichip(8)


def test_sharded_crush_resolve_matches_host_oracle():
    """PGs sharded over the full 8-device mesh resolve identically to
    the exact host mapper; the packed output is genuinely distributed."""
    import numpy as np
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    from ceph_tpu.parallel import make_mesh
    from ceph_tpu.parallel.crush import sharded_fast_rule

    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    n_osds, per = 40, 4
    for h in range(n_osds // per):
        osds = list(range(h * per, (h + 1) * per))
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"h{h}", osds,
                                   [0x10000] * per, id=-(h + 2)))
    cw.set_max_devices(n_osds)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x10000 * per] * len(hosts), id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    mesh = make_mesh(8)
    sf = sharded_fast_rule(cw.crush, rno, 3, mesh)
    xs = np.arange(1000, dtype=np.uint32)
    w = np.full(n_osds, 0x10000, dtype=np.uint32)
    w[7] = 0
    res, cnt = sf.map_batch(xs, w)
    wl = [int(v) for v in w]
    for x in range(0, 1000, 13):
        expect = cw.do_rule(rno, int(x), 3, wl)
        got = [int(v) for v in res[x, :cnt[x]]]
        assert got == expect, (x, got, expect)
    # the resolve output is actually sharded across devices
    packed = sf.resolve_device(w)
    assert len(packed.sharding.device_set) == 8


def test_sharded_crush_nonuniform_exact64_parity():
    """Regression: the sharded candidate build must go through
    FastRule._run_candidates so the exact64 draw traces under x64 —
    a direct _cand_jit call silently truncates the u64 tables to 32
    bits and produces wrong placements with risky=False."""
    import numpy as np
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    from ceph_tpu.parallel import make_mesh
    from ceph_tpu.parallel.crush import ShardedFastRule

    rng = np.random.default_rng(3)
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts, osd = [], 0
    for h in range(8):
        osds = list(range(osd, osd + 4))
        osd += 4
        ws = [int(w) for w in rng.integers(0x9000, 0x22000, 4)]
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"h{h}",
                                   osds, ws, id=-(h + 2)))
    cw.set_max_devices(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x30000] * 8, id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    sf = ShardedFastRule(cw.crush, rno, 3, make_mesh(8))
    assert sf.fr._exact64        # non-uniform weights: exact64 is on
    xs = np.arange(640, dtype=np.uint32)
    w = [0x10000] * osd
    res, cnt = sf.map_batch(xs, np.asarray(w, np.uint32))
    for x in range(640):
        expect = cw.do_rule(rno, int(x), 3, list(w))
        assert [int(v) for v in res[x, :cnt[x]]] == expect, x


# ---- multichip completion fence (ROADMAP follow-up) -------------------------
def test_drain_sharded_touches_every_shard():
    """The mesh fence fetches one element from EVERY addressable shard
    of the last output — per-device completion proof, not just a
    block_until_ready acknowledgement."""
    from ceph_tpu.parallel import drain_sharded
    k, m, s, c = 8, 4, 16, 256
    mat = gf_gen_rs_matrix(k + m, k)
    sharded = ShardedRS(mat, make_mesh(8))
    data = np.random.default_rng(0).integers(
        0, 256, size=(s, k, c), dtype=np.uint8)
    out = sharded.encode_device(jnp.asarray(data))
    n = sharded.drain(out)
    assert n == len(out.addressable_shards) == 8
    # byte parity survives the fence (drain must not mutate)
    assert np.asarray(out).tobytes() == \
        MatrixRSCodec(mat).encode(
            np.ascontiguousarray(data.transpose(1, 0, 2)).reshape(
                k, s * c)).reshape(m, s, c).transpose(1, 0, 2).tobytes()
    # host values fall back to the single-device drain
    assert drain_sharded(np.arange(4)) == 1


def test_mesh_roofline_scales_with_devices():
    """A mesh-wide reading is judged against the MESH's physics: chip
    peaks scale by device count, so a throughput that is impossible for
    one chip but fine for eight is not flagged."""
    from ceph_tpu.bench.roofline import EC_ENCODE_K8M4, validate_reading
    from ceph_tpu.parallel import mesh_roofline
    mesh = make_mesh(8)
    single = validate_reading(10.0, EC_ENCODE_K8M4, "cpu", "", 1)
    meshwide = mesh_roofline(10.0, EC_ENCODE_K8M4, mesh, platform="cpu")
    assert meshwide["peak_tops"] == 8 * single["peak_tops"]
    assert meshwide["peak_hbm_gibs"] == 8 * single["peak_hbm_gibs"]
    # 30 GiB/s implies ~16.4 int8 TOPS: impossible on one generous-cpu
    # chip (2 TOPS), within an 8-chip mesh's 16... just over: use 25
    hot = validate_reading(25.0, EC_ENCODE_K8M4, "cpu", "", 1)
    assert hot["suspect"]
    cool = mesh_roofline(25.0, EC_ENCODE_K8M4, mesh, platform="cpu")
    assert not cool["suspect"]
    assert ShardedRS(gf_gen_rs_matrix(12, 8), mesh).roofline(
        25.0, EC_ENCODE_K8M4)["verdict"] == "ok"
