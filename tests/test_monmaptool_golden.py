"""monmaptool + ceph-authtool cram parity: replay the reference's
ENTIRE recorded CLI transcripts (src/test/cli/monmaptool/*.t,
src/test/cli/ceph-authtool/*.t) through the mini-cram interpreter —
every command line, output byte, and exit code.

manpage.t (needs the groff-built man page) is the only exclusion.
"""
import os

import pytest

import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402

MONDIR = "/root/reference/src/test/cli/monmaptool"
AUTHDIR = "/root/reference/src/test/cli/ceph-authtool"
OSDDIR = "/root/reference/src/test/cli/osdmaptool"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(MONDIR), reason="reference cram files unavailable")

def _ts(d):
    # listdir must not run at import when the reference tree is absent —
    # the skipif mark only guards test execution, not module collection
    return sorted(os.listdir(d)) if os.path.isdir(d) else []


MON_TS = [t for t in _ts(MONDIR) if t.endswith(".t")]
# manpage.t greps the installed troff page — packaging, not behavior
AUTH_TS = [t for t in _ts(AUTHDIR)
           if t.endswith(".t") and t != "manpage.t"]
# upmap.t / upmap-out.t / test-map-pgs.t are replayed (in richer
# assertion form) by test_osdmaptool_golden.py already
OSD_TS = [t for t in _ts(OSDDIR)
          if t.endswith(".t")
          and t not in ("upmap.t", "upmap-out.t",
                        "test-map-pgs.t")]


@pytest.mark.parametrize("tname", MON_TS)
def test_monmaptool_cram(tname, tmp_path):
    assert_cram(os.path.join(MONDIR, tname), str(tmp_path))


@pytest.mark.parametrize("tname", AUTH_TS)
def test_authtool_cram(tname, tmp_path):
    assert_cram(os.path.join(AUTHDIR, tname), str(tmp_path))


@pytest.mark.parametrize("tname", OSD_TS)
def test_osdmaptool_cram(tname, tmp_path):
    """The whole-file replays of the osdmaptool cram suite (tree,
    create-print, create-racks, clobber, pool, crush, error paths,
    help) — every command, output byte, and exit code."""
    assert_cram(os.path.join(OSDDIR, tname), str(tmp_path))
