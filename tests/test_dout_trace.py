"""Leveled logging (dout/Log), kernel tracing, and arch probing."""
import pytest

from ceph_tpu.arch import probe
from ceph_tpu.common import g_kernel_timer, get_log
from ceph_tpu.common.config import ConfigProxy
from ceph_tpu.common.dout import Dout, dlog, register_config_observers


@pytest.fixture(autouse=True)
def clean_log():
    get_log().clear()
    g_kernel_timer.reset()
    g_kernel_timer.enable(False)
    yield
    get_log().clear()


def test_gather_vs_log_levels():
    log = get_log()
    log.parse_level("osd", "1/5")
    dlog("osd", 1, "visible")
    dlog("osd", 5, "gathered-only")
    dlog("osd", 6, "dropped")
    lines = log.dump_recent()
    assert any("visible" in ln for ln in lines)
    assert any("gathered-only" in ln for ln in lines)
    assert not any("dropped" in ln for ln in lines)


def test_ring_is_bounded():
    log = get_log()
    log.parse_level("osd", "0/5")
    for i in range(11000):
        dlog("osd", 1, f"e{i}")
    assert len(log.recent) == 10000
    # oldest entries evicted, newest retained
    assert log.dump_recent(1)[0].endswith("e10999")


def test_subsys_filter_and_who_prefix():
    log = get_log()
    d = Dout("pg", "osd.3")
    d(1, "peering started")
    dlog("mon", 1, "epoch 5")
    pg_lines = log.dump_recent(0, "pg")
    assert len(pg_lines) == 1 and "osd.3" in pg_lines[0]


def test_config_observer_updates_levels():
    cfg = ConfigProxy()
    register_config_observers(cfg)
    log = get_log()
    cfg.set_val("debug_crush", "10/20")
    assert log.levels["crush"] == (10, 20)
    dlog("crush", 15, "deep detail")
    assert any("deep detail" in ln for ln in log.dump_recent())


def test_kernel_timer_disabled_is_passthrough():
    calls = []
    out = g_kernel_timer.timed("k", lambda: calls.append(1) or 42)
    assert out == 42 and g_kernel_timer.dump() == {}


def test_kernel_timer_records_when_enabled():
    g_kernel_timer.enable()
    g_kernel_timer.timed("k", lambda: 1)
    g_kernel_timer.timed("k", lambda: 2)
    d = g_kernel_timer.dump()
    assert d["k"]["calls"] == 2 and d["k"]["total_s"] >= 0
    assert "avg_ms" in d["k"]


def test_kernel_timer_hooks_in_device_backend():
    import numpy as np
    from ceph_tpu.gf.matrices import gf_gen_rs_matrix
    from ceph_tpu.ops.gf_matmul import DeviceRSBackend
    g_kernel_timer.enable()
    be = DeviceRSBackend(gf_gen_rs_matrix(6, 4))
    data = np.zeros((2, 4, 64), dtype=np.uint8)
    be.encode(data)
    assert g_kernel_timer.dump()["gf_encode"]["calls"] == 1


def test_arch_probe_shape():
    p = probe()
    assert p["platform"] in ("cpu", "tpu", "gpu", "none")
    assert isinstance(p["n_devices"], int) and p["n_devices"] >= 1
    assert p["x64"] is True          # CPU mesh in tests supports x64
    assert isinstance(p["native"], bool)
    # cached second call returns the same dict
    assert probe() is p


def test_cluster_admin_log_and_trace_commands():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=3)
    c.create_ec_pool("lp", k=2, m=1, plugin="isa", pg_num=4)
    cl = c.client("client.l")
    cl.write_full("lp", "o1", b"x" * 1000)
    out = c.admin_socket.execute("log dump", {"subsys": "osd"})
    assert isinstance(out["lines"], list)
    c.admin_socket.execute("log set", {"subsys": "osd", "level": "20/20"})
    assert get_log().levels["osd"] == (20, 20)
    c.admin_socket.execute("kernel tracing", {"on": "1"})
    cl.write_full("lp", "o2", b"y" * 1000)
    kt = c.admin_socket.execute("kernel timings")
    encodes = sum(v.get("calls", 0) for n, v in kt.items()
                  if n.startswith("ec_encode_batch"))
    assert encodes >= 1
    ap = c.admin_socket.execute("arch probe")
    assert ap["platform"] == "cpu"


def test_osd_map_events_logged():
    from ceph_tpu.cluster import MiniCluster
    get_log().parse_level("osd", "1/10")
    c = MiniCluster(n_osds=3)
    c.create_ec_pool("lg", k=2, m=1, plugin="isa", pg_num=4)
    lines = get_log().dump_recent(0, "osd")
    assert any("handle_osd_map" in ln for ln in lines)


def test_tracing_kernels_config_option_enables_timer():
    cfg = ConfigProxy()
    register_config_observers(cfg)
    cfg.set_val("tracing_kernels", "true")
    assert g_kernel_timer.enabled
    cfg.set_val("tracing_kernels", "false")
    assert not g_kernel_timer.enabled
