"""radosgw-admin's user-administration surface (rgw_admin.cc verbs):
suspend/enable with frontend refusal, additional keys authenticating
at both signature flavors, admin caps, user quotas enforced on put,
bucket link/unlink ownership moves, and user stats accounting."""
import io
import json
from contextlib import redirect_stdout

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rgw import S3Frontend
from ceph_tpu.rgw.gateway import RGWError, RGWLite
from ceph_tpu.rgw.http import sign_v2, sign_v4
from ceph_tpu.tools.rgw_admin import run


@pytest.fixture()
def env():
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("m", size=3, pg_num=8)
    c.create_replicated_pool("d", size=3, pg_num=8)
    cl = c.client("client.rgw")
    g = RGWLite(cl, "m", "d")
    alice = g.create_user("alice", "Alice")
    return c, cl, g, alice


def _admin(cl, *argv):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = run(None, cl, list(argv), meta_pool="m", data_pool="d")
    return rc, buf.getvalue()


def _v2req(fe, user, method, path, body=b"", secret=None, key=None):
    hdrs = {"Date": "now"}
    ak = key or user["access_key"]
    sk = secret or user["secret_key"]
    hdrs["Authorization"] = \
        f"AWS {ak}:{sign_v2(sk, method, path, hdrs, {})}"
    return fe.handle(method, path, hdrs, body, {})


def test_suspend_enable_refuses_requests(env):
    c, cl, g, alice = env
    fe = S3Frontend(g)
    assert _v2req(fe, alice, "PUT", "/b")[0] == 200
    rc, _ = _admin(cl, "user", "suspend", "--uid", "alice")
    assert rc == 0
    st, _, body = _v2req(fe, alice, "GET", "/b")
    assert st == 403 and b"UserSuspended" in body
    rc, _ = _admin(cl, "user", "enable", "--uid", "alice")
    assert rc == 0
    assert _v2req(fe, alice, "GET", "/b")[0] == 200


def test_additional_keys_authenticate(env):
    c, cl, g, alice = env
    fe = S3Frontend(g)
    assert _v2req(fe, alice, "PUT", "/b")[0] == 200
    rc, out = _admin(cl, "key", "create", "--uid", "alice")
    assert rc == 0
    key = json.loads(out)
    # the NEW key signs v2...
    st, _, _ = _v2req(fe, alice, "GET", "/b",
                      secret=key["secret_key"],
                      key=key["access_key"])
    assert st == 200
    # ...and v4
    hdrs = {"Host": "s3.local"}
    hdrs["Authorization"] = sign_v4(key["access_key"],
                                    key["secret_key"], "GET", "/b",
                                    hdrs, {}, b"")
    assert fe.handle("GET", "/b", hdrs, b"", {})[0] == 200
    # key rm revokes it
    rc, _ = _admin(cl, "key", "rm", "--uid", "alice",
                   "--access-key", key["access_key"])
    assert rc == 0
    st, _, _ = _v2req(fe, alice, "GET", "/b",
                      secret=key["secret_key"],
                      key=key["access_key"])
    assert st == 403


def test_caps_add_rm(env):
    c, cl, g, alice = env
    rc, out = _admin(cl, "caps", "add", "--uid", "alice",
                     "--caps", "users=read,write;buckets=read")
    assert rc == 0
    caps = json.loads(out)
    assert caps == {"users": "read,write", "buckets": "read"}
    rc, out = _admin(cl, "caps", "rm", "--uid", "alice",
                     "--caps", "users=")
    assert rc == 0 and json.loads(out) == {"buckets": "read"}


def test_user_quota_enforced_on_put(env):
    c, cl, g, alice = env
    g.create_bucket("alice", "qb")
    g.put_object("qb", "one", b"x" * 1000, actor="alice")
    rc, _ = _admin(cl, "quota", "set", "--uid", "alice",
                   "--max-size", "1500", "--quota-scope", "user")
    assert rc == 0
    rc, _ = _admin(cl, "quota", "enable", "--uid", "alice")
    assert rc == 0
    with pytest.raises(RGWError) as ei:
        g.put_object("qb", "two", b"y" * 1000, actor="alice")
    assert "QuotaExceeded" in str(ei.value)
    # small writes under the limit still land
    g.put_object("qb", "small", b"z" * 100, actor="alice")
    rc, _ = _admin(cl, "quota", "disable", "--uid", "alice")
    assert rc == 0
    g.put_object("qb", "two", b"y" * 1000, actor="alice")
    # stats reflect the aggregate
    rc, out = _admin(cl, "user", "stats", "--uid", "alice")
    assert rc == 0 and json.loads(out)["size"] >= 2100


def test_suspension_covers_swift_frontend(env):
    c, cl, g, alice = env
    from ceph_tpu.rgw.http import SwiftFrontend
    sw = SwiftFrontend(g)
    st, hdrs, _ = sw.handle("GET", "/auth/v1.0", {
        "X-Auth-User": "alice:swift",
        "X-Auth-Key": alice["secret_key"]}, b"", {})
    assert st == 204
    token = hdrs["X-Auth-Token"]
    g.create_bucket("alice", "swb")
    ok = sw.handle("GET", "/v1/AUTH_alice/swb",
                   {"X-Auth-Token": token}, b"", {})
    assert ok[0] in (200, 204)
    g.modify_user("alice", suspended=True)
    st, _, body = sw.handle("GET", "/v1/AUTH_alice/swb",
                            {"X-Auth-Token": token}, b"", {})
    assert st == 403 and b"suspended" in body


def test_max_buckets_enforced(env):
    c, cl, g, alice = env
    g.modify_user("alice", max_buckets=2)
    g.create_bucket("alice", "b1")
    g.create_bucket("alice", "b2")
    with pytest.raises(RGWError):
        g.create_bucket("alice", "b3")
    # linking counts against the cap too
    bob = g.create_user("bob")
    g.create_bucket("bob", "bb")
    with pytest.raises(RGWError):
        g.link_bucket("bb", "alice")


def test_quota_covers_multipart_staging(env):
    c, cl, g, alice = env
    g.create_bucket("alice", "mp")
    g.set_user_quota("alice", max_size=1000, enabled=True)
    up = g.initiate_multipart("mp", "big", actor="alice")
    with pytest.raises(RGWError) as ei:
        g.upload_part("mp", "big", up, 1, b"x" * 2000, actor="alice")
    assert "QuotaExceeded" in str(ei.value)


def test_caps_rm_subtracts_perms(env):
    c, cl, g, alice = env
    g.user_caps("alice", add="users=read,write")
    assert g.user_caps("alice", rm="users=write") == \
        {"users": "read"}
    assert g.user_caps("alice", rm="users=read") == {}


def test_bucket_link_unlink(env):
    c, cl, g, alice = env
    bob = g.create_user("bob", "Bob")
    g.create_bucket("alice", "shared")
    rc, _ = _admin(cl, "bucket", "link", "--bucket", "shared",
                   "--uid", "bob")
    assert rc == 0
    assert g.get_bucket("shared")["owner"] == "bob"
    assert "shared" in g.get_user("bob")["buckets"]
    assert "shared" not in g.get_user("alice")["buckets"]
    rc, _ = _admin(cl, "bucket", "unlink", "--bucket", "shared",
                   "--uid", "bob")
    assert rc == 0
    assert g.get_bucket("shared")["owner"] == ""
    assert "shared" not in g.get_user("bob")["buckets"]
