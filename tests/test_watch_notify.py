"""Watch/notify: interest registration + broadcast with acks.

PrimaryLogPG's watch/notify effects (Watch.cc, MWatchNotify.h) scoped
to the in-process fabric: watchers register on the primary, notify
fans out, acks gate completion, dead watchers time out via the tick.
"""
import pytest

from ceph_tpu.cluster import MiniCluster


@pytest.fixture()
def cluster():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("wn", size=3, pg_num=8)
    return c


def test_notify_reaches_watchers_and_collects_replies(cluster):
    c = cluster
    alice = c.client("client.alice")
    bob = c.client("client.bob")
    carol = c.client("client.carol")
    alice.write_full("wn", "obj", b"x")
    got_a, got_b = [], []
    ca = alice.watch("wn", "obj", lambda nid, p: (got_a.append(p),
                                                 b"from-alice")[1])
    cb = bob.watch("wn", "obj", lambda nid, p: (got_b.append(p),
                                                b"from-bob")[1])
    replies = carol.notify("wn", "obj", b"hello")
    assert got_a == [b"hello"] and got_b == [b"hello"]
    assert sorted(replies.values()) == [b"from-alice", b"from-bob"]
    # unwatch: bob stops hearing
    bob.unwatch("wn", "obj", cb)
    replies = carol.notify("wn", "obj", b"again")
    assert got_b == [b"hello"]
    assert list(replies.values()) == [b"from-alice"]
    alice.unwatch("wn", "obj", ca)
    assert carol.notify("wn", "obj", b"silence") == {}


def test_notify_with_no_watchers_completes_immediately(cluster):
    cl = cluster.client("client.solo")
    cl.write_full("wn", "lonely", b"x")
    assert cl.notify("wn", "lonely", b"anyone?") == {}


def test_notifier_does_not_hear_its_own_notify(cluster):
    cl = cluster.client("client.self")
    cl.write_full("wn", "obj", b"x")
    heard = []
    cl.watch("wn", "obj", lambda nid, p: heard.append(p))
    replies = cl.notify("wn", "obj", b"echo?")
    assert heard == [] and replies == {}


def test_dead_watcher_times_out(cluster):
    c = cluster
    alice = c.client("client.alice")
    bob = c.client("client.bob")
    alice.write_full("wn", "obj", b"x")
    bob.watch("wn", "obj", lambda nid, p: b"late")
    # bob's messenger goes dark (blackhole the entity)
    c.network.down.add("client.bob")
    replies = alice.notify("wn", "obj", b"ping", timeout=5)
    # the dead watcher is skipped up front (down set) -> no stall
    assert replies == {}


def test_watch_on_ec_pool(cluster):
    c = cluster
    c.create_ec_pool("wnec", k=2, m=1, plugin="isa", pg_num=4)
    a = c.client("client.a")
    b = c.client("client.b")
    a.write_full("wnec", "obj", b"payload")
    got = []
    a.watch("wnec", "obj", lambda nid, p: (got.append(p), b"ok")[1])
    replies = b.notify("wnec", "obj", b"ec-notify")
    assert got == [b"ec-notify"]
    assert list(replies.values()) == [b"ok"]


def test_watch_survives_primary_failover(cluster):
    """Watches re-register with the new primary after a map change
    (the client-side linger resend)."""
    c = cluster
    a = c.client("client.wa")
    b = c.client("client.wb")
    a.write_full("wn", "cfg", b"x")
    heard = []
    a.watch("wn", "cfg", lambda nid, p: (heard.append(p), b"ok")[1])
    _pg, primary = a._calc_target(a.lookup_pool("wn"), "cfg")
    c.kill_osd(primary)
    for _ in range(6):
        c.tick(dt=6.0)
    c.network.pump()
    replies = b.notify("wn", "cfg", b"after-failover")
    assert heard == [b"after-failover"]
    assert list(replies.values()) == [b"ok"]
