"""rgw versioning + lifecycle + ACLs (rgw_rados versioned ops,
rgw_lc.cc, rgw_acl_s3.cc at lite scale)."""
import time

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rgw import RGWLite
from ceph_tpu.rgw.gateway import RGWError


@pytest.fixture()
def rgw():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rgw.meta", size=3, pg_num=8)
    c.create_replicated_pool("rgw.data", size=3, pg_num=8)
    g = RGWLite(c.client("client.rgw"), "rgw.meta", "rgw.data")
    g.create_user("alice")
    g.create_user("bob")
    g.create_bucket("alice", "b")
    return c, g


def test_versioning_suite(rgw):
    """The S3 versioning behavior matrix: PUTx2 / GET?versionId /
    DELETE marker / restore."""
    c, g = rgw
    g.put_bucket_versioning("b", "enabled")
    assert g.get_bucket_versioning("b") == "enabled"
    v1 = g.put_object("b", "k", b"version-one")
    v2 = g.put_object("b", "k", b"version-two")
    assert v1["vid"] != v2["vid"]
    # current GET = newest; explicit versionId reaches both
    assert g.get_object("b", "k") == b"version-two"
    assert g.get_object("b", "k", version_id=v1["vid"]) == b"version-one"
    assert g.get_object("b", "k", version_id=v2["vid"]) == b"version-two"
    vers = [v for v in g.list_object_versions("b") if v["key"] == "k"]
    assert [v["version_id"] for v in vers] == [v2["vid"], v1["vid"]]
    assert vers[0]["is_latest"] and not vers[1]["is_latest"]
    # DELETE without versionId pushes a marker: key vanishes from GET
    # and ListObjects, data stays
    d = g.delete_object("b", "k")
    assert d["delete_marker"]
    with pytest.raises(RGWError):
        g.get_object("b", "k")
    assert all(e["name"] != "k"
               for e in g.list_objects("b")["contents"])
    assert g.get_object("b", "k", version_id=v1["vid"]) == b"version-one"
    # deleting the MARKER restores the previous current (undelete)
    g.delete_object("b", "k", version_id=d["version_id"])
    assert g.get_object("b", "k") == b"version-two"
    # permanently deleting the newest exposes its predecessor
    g.delete_object("b", "k", version_id=v2["vid"])
    assert g.get_object("b", "k") == b"version-one"


def test_preversioning_objects_become_null_version(rgw):
    c, g = rgw
    g.put_object("b", "old", b"before-versioning")
    g.put_bucket_versioning("b", "enabled")
    v2 = g.put_object("b", "old", b"after-versioning")
    assert g.get_object("b", "old") == b"after-versioning"
    assert g.get_object("b", "old",
                        version_id="null") == b"before-versioning"
    vers = [v for v in g.list_object_versions("b")
            if v["key"] == "old"]
    assert [v["version_id"] for v in vers] == [v2["vid"], "null"]


def test_suspended_versioning_overwrites_null(rgw):
    c, g = rgw
    g.put_bucket_versioning("b", "enabled")
    v1 = g.put_object("b", "k", b"kept")
    g.put_bucket_versioning("b", "suspended")
    g.put_object("b", "k", b"null-1")
    g.put_object("b", "k", b"null-2")        # overwrites the null slot
    vers = [v for v in g.list_object_versions("b") if v["key"] == "k"]
    assert [v["version_id"] for v in vers] == ["null", v1["vid"]]
    assert g.get_object("b", "k") == b"null-2"
    assert g.get_object("b", "k", version_id=v1["vid"]) == b"kept"


def test_lifecycle_expiration(rgw):
    c, g = rgw
    now = time.time()
    g.put_object("b", "logs/old", b"ancient")
    g.put_object("b", "logs/new", b"fresh")
    g.put_object("b", "keep/x", b"outside prefix")
    g.put_bucket_lifecycle("b", [{"id": "r1", "prefix": "logs/",
                                  "status": "Enabled",
                                  "expiration_days": 7}])
    # nothing is old enough yet
    rep = g.lc_process(now=now + 86400)
    assert rep["b"]["expired"] == 0
    # 8 "days" later the old prefix objects expire; others survive
    rep = g.lc_process(now=now + 8 * 86400)
    assert rep["b"]["expired"] == 2
    with pytest.raises(RGWError):
        g.get_object("b", "logs/old")
    assert g.get_object("b", "keep/x") == b"outside prefix"


def test_lifecycle_noncurrent_expiration_versioned(rgw):
    c, g = rgw
    now = time.time()
    g.put_bucket_versioning("b", "enabled")
    v1 = g.put_object("b", "k", b"v1")
    v2 = g.put_object("b", "k", b"v2")
    g.put_bucket_lifecycle("b", [{"id": "nc", "prefix": "",
                                  "status": "Enabled",
                                  "noncurrent_days": 3}])
    rep = g.lc_process(now=now + 4 * 86400)
    assert rep["b"]["noncurrent_removed"] == 1
    vers = [v for v in g.list_object_versions("b") if v["key"] == "k"]
    assert [v["version_id"] for v in vers] == [v2["vid"]]
    assert g.get_object("b", "k") == b"v2"


def test_acl_cross_user_matrix(rgw):
    """Owner / grantee / everyone / authenticated across read+write."""
    c, g = rgw
    g.put_object("b", "o", b"secret", actor="alice")
    # default private: bob denied read and write
    with pytest.raises(RGWError):
        g.get_object("b", "o", actor="bob")
    with pytest.raises(RGWError):
        g.put_object("b", "x", b"nope", actor="bob")
    # owner always passes
    assert g.get_object("b", "o", actor="alice") == b"secret"
    # explicit READ grant to bob on the OBJECT
    g.put_object_acl("b", "o", grants=[{"grantee": "bob",
                                        "permission": "READ"}],
                     actor="alice")
    assert g.get_object("b", "o", actor="bob") == b"secret"
    with pytest.raises(RGWError):          # read grant is not write
        g.put_object("b", "o", b"clobber", actor="bob")
    # canned public-read on the bucket: anonymous read works,
    # anonymous write still denied
    g.put_bucket_acl("b", canned="public-read", actor="alice")
    assert g.list_objects("b", actor="bob")["contents"]
    with pytest.raises(RGWError):
        g.put_object("b", "y", b"nope", actor="bob")
    # public-read-write opens puts to authenticated non-owners
    g.put_bucket_acl("b", canned="public-read-write", actor="alice")
    assert g.put_object("b", "y", b"ok", actor="bob")["size"] == 2
    # only the owner may change ACLs
    with pytest.raises(RGWError):
        g.put_bucket_acl("b", canned="private", actor="bob")
    # acl read surface
    acl = g.get_bucket_acl("b", actor="alice")
    assert acl["owner"] == "alice"
    assert {"grantee": "*", "permission": "WRITE"} in acl["grants"]


def test_gc_accounts_for_all_versions(rgw):
    c, g = rgw
    g.put_bucket_versioning("b", "enabled")
    g.put_object("b", "k", b"v1" * 100)
    g.put_object("b", "k", b"v2" * 100)
    rep = g.gc(repair=False)
    assert rep["orphan_objects"] == []     # every version referenced


def test_gc_never_reaps_versions_behind_delete_marker(rgw):
    """Keys hidden by a delete marker still own live noncurrent data;
    gc must walk the RAW index (not the marker-filtered listing) or a
    repair pass permanently destroys restorable versions."""
    c, g = rgw
    g.put_bucket_versioning("b", "enabled")
    v1 = g.put_object("b", "k", b"restorable-data")
    d = g.delete_object("b", "k")           # marker hides the key
    rep = g.gc(repair=True)
    assert rep["orphan_objects"] == []
    # restore by removing the marker: the data must still be there
    g.delete_object("b", "k", version_id=d["version_id"])
    assert g.get_object("b", "k") == b"restorable-data"
    assert g.get_object("b", "k", version_id=v1["vid"]) == \
        b"restorable-data"


def test_http_frontend_enforces_acls(rgw):
    """Cross-user access over the HTTP surface: the frontend passes
    the authenticated actor into the gateway's ACL engine instead of
    the old owner-only check."""
    import http.client

    from ceph_tpu.rgw import S3Frontend, serve
    from ceph_tpu.rgw.http import _sign_v2

    c, g = rgw
    alice = g.get_user("alice")
    bob = g.get_user("bob")
    fe = S3Frontend(g)
    srv, port = serve(fe)
    try:
        def req(method, path, body=b"", sign_as=alice):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            date = "now"
            sig = _sign_v2(sign_as["secret_key"], method, date,
                           path.split("?")[0])
            conn.request(method, path, body, {
                "Date": date,
                "Authorization": f"AWS {sign_as['access_key']}:{sig}"})
            r = conn.getresponse()
            out = r.read()
            conn.close()
            return r.status, out

        assert req("PUT", "/b/doc", b"private bytes")[0] == 200
        # bob: denied read/list/write on the private bucket
        assert req("GET", "/b/doc", sign_as=bob)[0] == 403
        assert req("GET", "/b", sign_as=bob)[0] == 403
        assert req("PUT", "/b/intruder", b"x", sign_as=bob)[0] == 403
        assert req("DELETE", "/b/doc", sign_as=bob)[0] == 403
        # a READ grant opens GET but not PUT/DELETE
        g.put_bucket_acl("b", canned="public-read", actor="alice")
        st, out = req("GET", "/b/doc", sign_as=bob)
        assert (st, out) == (200, b"private bytes")
        assert req("GET", "/b", sign_as=bob)[0] == 200
        assert req("PUT", "/b/intruder", b"x", sign_as=bob)[0] == 403
        # owner still writes
        assert req("PUT", "/b/doc2", b"ok")[0] == 200
    finally:
        srv.shutdown()
