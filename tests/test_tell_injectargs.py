"""Runtime reconfiguration: `ceph tell ... injectargs`, `ceph daemon
<who> <asok cmd>`, and the admin socket's config set/get — the
md_config_t::set_val + observer-notification flow (`ceph daemon X
config set` role)."""
import json

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common.config import g_conf
from ceph_tpu.tools.ceph_cli import main


@pytest.fixture()
def env(tmp_path):
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("p", pg_num=8)
    d = str(tmp_path / "ck")
    c.checkpoint(d)
    saved = dict(g_conf.values)
    saved_obs = {k: list(v) for k, v in g_conf.observers.items()}
    yield c, d
    g_conf.values = saved              # module-global: restore
    g_conf.observers = saved_obs       # incl. any observers we added


def test_asok_config_set_get_and_observer(env):
    c, d = env
    fired = []
    g_conf.add_observer("osd_heartbeat_grace",
                        lambda n, v: fired.append((n, v)))
    out = c.admin_socket.execute("config set",
                                 {"name": "osd_heartbeat_grace",
                                  "value": "42.5"})
    assert out["success"] and out["osd_heartbeat_grace"] == 42.5
    assert fired == [("osd_heartbeat_grace", 42.5)]
    got = c.admin_socket.execute("config get",
                                 {"name": "osd_heartbeat_grace"})
    assert got["osd_heartbeat_grace"] == 42.5
    with pytest.raises(ValueError):
        c.admin_socket.execute("config set", {"name": "nope",
                                              "value": "1"})


def test_cli_tell_injectargs(env, capsys):
    _, d = env
    rc = main(["--cluster", d, "tell", "osd.0", "injectargs",
               "--osd-heartbeat-grace", "33", "--debug_osd=9/9"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["osd_heartbeat_grace"] == 33.0
    assert doc["debug_osd"] == "9/9"

    # the reference's single-quoted-string form
    rc = main(["--cluster", d, "tell", "osd.0", "injectargs",
               "--osd-heartbeat-grace 21"])
    assert rc == 0
    assert json.loads(
        capsys.readouterr().out)["osd_heartbeat_grace"] == 21.0

    # error contracts: unknown option, missing value, bad token
    assert main(["--cluster", d, "tell", "osd.0", "injectargs",
                 "--no-such-option", "1"]) == 1
    assert main(["--cluster", d, "tell", "osd.0", "injectargs",
                 "--osd-heartbeat-grace"]) == 1
    assert main(["--cluster", d, "tell", "osd.0", "injectargs",
                 "oops"]) == 1
    assert main(["--cluster", d, "tell", "osd.0"]) == 1


def test_osd_command_wire(env):
    """'ceph tell osd.N' over the message fabric: MCommand to a LIVE
    daemon, MCommandReply back (config mutation fires observers in
    the daemon's process; here in-process, over TCP in
    test_vstart_process.py)."""
    c, _ = env
    cl = c.client("client.t")
    out = cl.osd_command(0, "config get", name="osd_heartbeat_grace")
    assert out["osd_heartbeat_grace"] == 20.0
    out = cl.osd_command(0, "injectargs",
                         opts={"osd_heartbeat_grace": "31"})
    assert out["osd_heartbeat_grace"] == 31.0
    out = cl.osd_command(0, "perf dump")
    assert isinstance(out, dict) and out
    out = cl.osd_command(0, "dump_ops_in_flight")
    assert "ops" in out
    with pytest.raises(ValueError):
        cl.osd_command(0, "no-such-command")
    with pytest.raises(ValueError):
        cl.osd_command(0, "injectargs", opts={"nope": "1"})


def test_cli_daemon_asok_commands(env, capsys):
    _, d = env
    # both shell forms: quoted single token and separate words
    for form in (["config show"], ["config", "show"]):
        rc = main(["--cluster", d, "daemon", "mon.a", *form])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert "osd_heartbeat_grace" in doc
    rc = main(["--cluster", d, "daemon", "osd.0",
               "config", "get", "name=osd_heartbeat_grace"])
    assert rc == 0
    assert main(["--cluster", d, "daemon", "osd.0",
                 "no-such-cmd"]) == 1
    # bad value surfaces as an error, not a traceback
    assert main(["--cluster", d, "tell", "osd.0", "injectargs",
                 "--osd-heartbeat-grace", "notanum"]) == 1
    # unknown option via config get is an explicit refusal
    assert main(["--cluster", d, "daemon", "osd.0",
                 "config", "get", "name=nope"]) == 1
