"""Pool deletion: OSDs purge the deleted pool's PGs and data.

Reference flow ('osd pool delete' -> OSDMonitor, OSDs remove PGs via
PG::on_removal on consuming the epoch): data objects and collections
disappear from every store, stale pg_temp/upmap entries are cleaned,
cache-tier participants are refused, and the name is reusable.
"""
import pytest

from ceph_tpu.cluster import MiniCluster


def test_delete_pool_purges_everything():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("keep", size=2, pg_num=8)
    c.create_ec_pool("doomed", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.d")
    for i in range(12):
        cl.write_full("doomed", f"o{i}", b"x" * 500)
        cl.write_full("keep", f"k{i}", b"y" * 100)
    # collections for the doomed pool exist before
    doomed_pid = c.mon.osdmap.lookup_pg_pool_name("doomed")
    pre = sum(1 for osd in c.osds.values()
              for cid in osd.store.list_collections()
              if cid.startswith(f"{doomed_pid}."))
    assert pre > 0
    c.delete_pool("doomed")
    c.tick(3)
    # every doomed collection purged from every store; keep intact
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            assert not cid.startswith(f"{doomed_pid}.")
    assert cl.read("keep", "k3") == b"y" * 100
    # client ops on the dead pool fail at pool lookup after refresh
    with pytest.raises(KeyError):
        cl.lookup_pool("doomed")
    # a client holding the resolved pool id gets a clean ENOENT, not a
    # KeyError out of target calculation
    with pytest.raises(IOError) as ei:
        cl._submit(doomed_pid, "o1", "read")
    assert getattr(ei.value, "errno", None) == 2

    # the name is immediately reusable with fresh PGs
    c.create_replicated_pool("doomed", size=2, pg_num=8)
    assert cl.write_full("doomed", "fresh", b"new") == 0
    assert cl.read("doomed", "fresh") == b"new"


def test_delete_pool_guards_and_cleanup():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("base", size=2, pg_num=8)
    c.create_replicated_pool("cache", size=2, pg_num=8)
    c.mon.add_cache_tier("base", "cache")
    c.publish()
    with pytest.raises(ValueError):
        c.delete_pool("cache")           # tier participant
    with pytest.raises(ValueError):
        c.delete_pool("base")
    with pytest.raises(KeyError):
        c.delete_pool("nope")
    # stale placement state of a deleted pool is swept from the map
    c.create_replicated_pool("tmp", size=2, pg_num=8)
    from ceph_tpu.osdmap.types import pg_t
    pid = c.mon.osdmap.lookup_pg_pool_name("tmp")
    c.mon.osdmap.pg_temp[pg_t(pid, 0)] = [0, 1]
    c.mon.osdmap.pg_upmap_items[pg_t(pid, 1)] = [(0, 1)]
    c.delete_pool("tmp")
    assert not any(pg.pool == pid for pg in c.mon.osdmap.pg_temp)
    assert not any(pg.pool == pid for pg in c.mon.osdmap.pg_upmap_items)


def test_delete_pool_survives_restart():
    import tempfile
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("gone", size=2, pg_num=8)
    cl = c.client("client.r")
    cl.write_full("gone", "o", b"bye")
    c.delete_pool("gone")
    c.tick(3)
    d = tempfile.mkdtemp()
    c.checkpoint(d)
    c2 = MiniCluster.restore(d)
    pid_absent = c2.mon.osdmap.lookup_pg_pool_name("gone")
    assert pid_absent < 0
    for osd in c2.osds.values():
        assert not any("gone" in cid for cid in
                       osd.store.list_collections())
