"""Multi-active MDS: subtree authority by rank, export pins, the
journaled handoff, forward-based client routing, per-rank failover.

The reference runs multiple active ranks with subtree authority
partitioning (src/mds/Migrator.cc export/import, MDBalancer.cc;
export pins via the ceph.dir.pin vxattr, CInode::get_export_pin) and
the MDSMonitor's per-rank fsmap (src/mon/MDSMonitor.cc).  Lite form:
static pins partition the namespace; the pin write is the journaled
handoff; MClientReply(MDS_FORWARD) routes clients to the auth rank.
"""
import json

import pytest

from ceph_tpu.cephfs import FsError
from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.mds import MDSDaemon
from ceph_tpu.mds.server import MDS_FORWARD
from ceph_tpu.msg.messages import CEPH_CAP_FILE_BUFFER, MMDSBeacon


@pytest.fixture()
def world():
    """Two actives (rank 0 + rank 1) and two clients on one fabric."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    a = MDSDaemon(c.network, c.client("client.mdsa"), "mds.a",
                  mkfs=True, rank=0)
    b = MDSDaemon(c.network, c.client("client.mdsb"), "mds.b",
                  rank=1)
    ranks = {0: "mds.a", 1: "mds.b"}
    a.set_mds_map(ranks)
    b.set_mds_map(ranks)
    fa = RemoteCephFS(c.client("client.a"), mds_name="mds.a")
    fb = RemoteCephFS(c.client("client.b"), mds_name="mds.a")
    fa._drive = lambda: (a.process(), b.process(), fb.process())
    fb._drive = lambda: (a.process(), b.process(), fa.process())
    return c, a, b, fa, fb


def test_two_actives_serve_disjoint_subtrees(world):
    """The done-criterion: both ranks serve concurrently, each
    authoritative for its own subtree; requests sent to the wrong
    rank are forwarded, and each rank journals ONLY its own ops."""
    c, a, b, fa, fb = world
    fa.mkdir("/teamA")
    fa.mkdir("/teamB")
    fa.set_dir_pin("/teamB", 1)
    j_a = a.journal._next_tid
    j_b = b.journal._next_tid
    # client A works under /teamA (rank 0), client B under /teamB
    # (rank 1) — concurrently interleaved
    fa.create("/teamA/x")
    fb.create("/teamB/y")
    fa.write("/teamA/x", b"rank-zero", 0)
    fb.write("/teamB/y", b"rank-one", 0)
    assert fa.read("/teamB/y") == b"rank-one"     # cross-visibility
    assert fb.read("/teamA/x") == b"rank-zero"
    # rank 1 journaled the /teamB mutations; rank 0 never saw them
    assert b.journal._next_tid > j_b
    assert ({json.loads(e)["args"].get("path", "")
             for e in dict(a.journal.scan_entries()).values()
             if json.loads(e).get("op") == "create"} &
            {"/teamB/y"}) == set()
    # the client LEARNED the auth and now goes direct (hint cached)
    assert fb._auth_hint.get("/teamB") == "mds.b"
    # direct-to-wrong-rank gets a forward, not an error: a fresh
    # client aimed at rank 1 still reaches rank 0's subtree
    fc = RemoteCephFS(c.client("client.c"), mds_name="mds.b")
    fc._drive = lambda: (a.process(), b.process())
    assert fc.stat("/teamA/x")["size"] == 9
    assert fc._auth_hint.get("/teamA") == "mds.a"


def test_forward_reply_shape(world):
    """The wire shape: ops for a pinned subtree answered MDS_FORWARD
    with the rank and (when known) the daemon name."""
    c, a, b, fa, fb = world
    fa.mkdir("/pinned")
    fa.set_dir_pin("/pinned", 1)
    from ceph_tpu.msg.messages import MClientRequest

    class Probe:
        def __init__(self):
            self.replies = []

        def ms_fast_dispatch(self, msg):
            self.replies.append(msg)

    probe = Probe()
    mess = c.network.create_messenger("client.probe")
    mess.add_dispatcher_head(probe)
    mess.send_message(MClientRequest(
        tid=1, op="mkdir", args={"path": "/pinned/sub"},
        reqid="probe#1"), "mds.a")
    c.network.pump()
    a.process()
    c.network.pump()
    assert len(probe.replies) == 1
    rep = probe.replies[0]
    assert rep.result == MDS_FORWARD
    assert rep.data == {"forward_rank": 1, "mds": "mds.b"}


def test_pin_to_absent_rank_is_ignored(world):
    """A pin naming a rank outside the mds_map is inherited over —
    the reference ignores export_pins beyond max_mds the same way."""
    c, a, b, fa, fb = world
    fa.mkdir("/d")
    fa.set_dir_pin("/d", 7)           # no rank 7 anywhere
    fa.create("/d/f")                 # rank 0 serves it, no forward
    assert fa._auth_hint.get("/d") is None
    assert fa.exists("/d/f")


def test_subtree_handoff_drains_caps(world):
    """Repinning a subtree with a buffered writer drains the caps
    FIRST: the writer's data is flushed durable before authority
    moves, so the new rank never sees an unknown writer."""
    c, a, b, fa, fb = world
    fa.mkdir("/mig")
    fh = fb.open("/mig/f", "w")
    assert fh.caps & CEPH_CAP_FILE_BUFFER
    fh.write(b"buffered-under-rank0", 0)
    assert a.fs.stat("/mig/f")["size"] == 0    # still only in buffer
    fa.set_dir_pin("/mig", 1)                  # the journaled handoff
    # the drain flushed B's buffer before the pin committed
    assert a.fs.stat("/mig/f")["size"] == 20
    assert fh.caps == 0
    # authority actually moved: rank 1 journals the next mutation
    j_b = b.journal._next_tid
    fb.create("/mig/g")
    assert b.journal._next_tid > j_b
    assert fa.read("/mig/f") == b"buffered-under-rank0"


def test_release_reaches_issuing_rank(world):
    """close() must release caps at the RANK that issued them — an
    ino-addressed release to the default rank would leave the real
    issuer recording a phantom holder forever."""
    c, a, b, fa, fb = world
    fa.mkdir("/pin1")
    fa.set_dir_pin("/pin1", 1)
    fh = fa.open("/pin1/f", "w")
    ino = fh.inode["ino"]
    assert fa.caps_held(b, ino) if hasattr(fa, "caps_held") else \
        b.caps.get(ino)                       # rank 1 issued the caps
    fh.close()
    assert not b.caps.get(ino)                # and rank 1 released
    # a later repin must not park on a phantom holder
    fa.set_dir_pin("/pin1", 0)
    assert fa.exists("/pin1/f")


def test_drain_finds_renamed_open_handle(world):
    """A file renamed into a subtree while its handle is open must
    still be drained by set_dir_pin (cap bookkeeping follows the
    namespace, not the open-time path)."""
    c, a, b, fa, fb = world
    fa.mkdir("/stay")
    fa.mkdir("/move")
    fh = fb.open("/stay/f", "w")
    fh.write(b"renamed-while-open", 0)
    fa.rename("/stay/f", "/move/f")
    assert a.fs.stat("/move/f")["size"] == 0      # still buffered
    fa.set_dir_pin("/move", 1)                    # must drain fh
    assert a.fs.stat("/move/f")["size"] == 18
    assert fa.read("/move/f") == b"renamed-while-open"


def test_rename_out_of_authority_drains_caps(world):
    """A rename whose DESTINATION is another rank's subtree drains the
    open handle first — otherwise the moved file's caps would be
    stranded where no future subtree drain could find them."""
    c, a, b, fa, fb = world
    fa.mkdir("/ours")
    fa.mkdir("/theirs")
    fa.set_dir_pin("/theirs", 1)
    fh = fb.open("/ours/f", "w")
    fh.write(b"crossing-over", 0)
    assert a.fs.stat("/ours/f")["size"] == 0      # buffered only
    fa.rename("/ours/f", "/theirs/f")             # rank 0 executes
    # the drain flushed before the rename moved it out of rank 0
    assert fh.caps == 0
    assert fa.read("/theirs/f") == b"crossing-over"
    assert not a.caps                              # nothing stranded


def test_cross_subtree_rename_crash_safe(world):
    """Rename from rank 0's subtree into rank 1's: executed by the
    SOURCE auth as ONE journaled event — a crash between journal and
    apply replays it; a third incarnation changes nothing."""
    c, a, b, fa, fb = world
    fa.mkdir("/src")
    fa.mkdir("/dst")
    fa.set_dir_pin("/dst", 1)
    fa.create("/src/f")
    fa.write("/src/f", b"crossing", 0)
    # live path first: the rename is served by /src's auth (rank 0)
    fa.rename("/src/f", "/dst/f")
    assert fa.read("/dst/f") == b"crossing"
    assert not fa.exists("/src/f")
    # crash window: journaled on rank 0, never applied
    a.journal.append(json.dumps(
        {"op": "rename",
         "args": {"src": "/dst/f", "dst": "/src/f2"}}).encode())
    a2 = MDSDaemon(c.network, c.client("client.mdsa2"), "mds.a",
                   rank=0)
    a2.set_mds_map({0: "mds.a", 1: "mds.b"})
    f2 = RemoteCephFS(c.client("client.a2"), mds_name="mds.a")
    f2._drive = lambda: (a2.process(), b.process())
    assert f2.exists("/src/f2") and not f2.exists("/dst/f")
    assert f2.read("/src/f2") == b"crossing"
    # idempotent on a third incarnation
    a3 = MDSDaemon(c.network, c.client("client.mdsa3"), "mds.a",
                   rank=0)
    assert a3.fs.exists("/src/f2") and not a3.fs.exists("/dst/f")
    assert not any(a3.fs.fsck().values())


def test_per_rank_journals_are_separate(world):
    c, a, b, fa, fb = world
    assert a.journal.meta_oid != b.journal.meta_oid


def _beacon(c, name):
    c.network.send(name, c.mon.name, MMDSBeacon(name=name))
    c.network.pump()


def test_fsmap_ranks_and_per_rank_failover():
    """MDSMonitor-lite with max_mds=2: two actives hold ranks 0/1, a
    silent rank fails over to the standby WITHOUT touching the other
    rank, and 'ceph fs status' shows the rank table."""
    c = MiniCluster(n_osds=3)
    c.mon.fs_set_max_mds(2)
    _beacon(c, "mds.x")
    _beacon(c, "mds.y")
    _beacon(c, "mds.z")
    st = c.mon.fs_status()
    assert st["max_mds"] == 2
    assert st["ranks"] == {"0": "mds.x", "1": "mds.y"}
    assert st["active"] == ["mds.x", "mds.y"]
    assert st["standby"] == ["mds.z"]
    # rank 1 goes silent: beacons keep coming from x and z only
    from ceph_tpu.mon import monitor as monitor_mod
    for _ in range(6):
        c.tick(dt=monitor_mod.MDS_BEACON_GRACE / 3)
        _beacon(c, "mds.x")
        _beacon(c, "mds.z")
    st = c.mon.fs_status()
    assert st["ranks"]["0"] == "mds.x"        # rank 0 untouched
    assert st["ranks"]["1"] == "mds.z"        # standby took rank 1
    assert st["mds"]["mds.y"]["state"] == "failed"
    # the deposed daemon beacons again: rejoins as standby
    _beacon(c, "mds.y")
    st = c.mon.fs_status()
    assert st["mds"]["mds.y"]["state"] == "standby"


def test_fs_set_max_mds_grow_and_shrink():
    c = MiniCluster(n_osds=3)
    _beacon(c, "mds.x")
    _beacon(c, "mds.y")
    st = c.mon.fs_status()
    assert st["ranks"] == {"0": "mds.x"}      # max_mds=1 default
    assert st["standby"] == ["mds.y"]
    # grow: the live standby is promoted into rank 1 immediately
    c.mon.fs_set_max_mds(2)
    st = c.mon.fs_status()
    assert st["ranks"] == {"0": "mds.x", "1": "mds.y"}
    # shrink: rank 1 is deactivated back to standby
    c.mon.fs_set_max_mds(1)
    st = c.mon.fs_status()
    assert st["ranks"] == {"0": "mds.x"}
    assert st["mds"]["mds.y"]["state"] == "standby"
