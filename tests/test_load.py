"""Traffic harness + per-client dmClock QoS + admission control.

The QoS PR's acceptance gates (docs/QOS.md):

- the traffic-harness smoke drives >= 8 concurrent synthetic clients
  over the real messenger/client stack in tier-1: every op completes
  byte-exact and every client's latency PerfHistogram carries samples;
- the per-client dmClock lane converges to weight-proportional shares
  under saturating demand (2:1 within +-10%), honors a reservation
  floor for a low-weight client, and caps a greedy client at its limit
  — all in the deterministic virtual-clock mode (no wall time in any
  decision);
- admission control sheds, never wedges: with
  ``osd_op_queue_admission_max`` exceeded the queue depth stays
  bounded, throttled clients retry, and every op still completes.

The ``slow``-marked soak drives ~1M ops through the same harness.
"""
import os

import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.common.work_queue import (
    CLASS_CLIENT, ClientDmClock, MClockQueue,
    l_qos_admission_rejections, qos_perf_counters,
)
from ceph_tpu.load import TrafficSpec, hist_percentiles, run_traffic


def _boot(n_osds=4, pg_num=8):
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=n_osds)
    c.create_replicated_pool("load", size=3, pg_num=pg_num)
    return c


@pytest.fixture
def clean_qos_conf():
    yield
    for name in ("osd_op_queue_admission_max",
                 "osd_op_queue_throttle_window",
                 "osd_op_queue_batch_intake",
                 "osd_mclock_client_overrides",
                 "osd_mclock_client_weight",
                 "osd_mclock_client_reservation",
                 "osd_mclock_client_limit"):
        g_conf.rm_val(name)


# ---- tier-1 traffic-harness smoke ------------------------------------------

def test_traffic_smoke_eight_clients_byte_exact():
    """Acceptance: >= 8 concurrent synthetic clients over the real
    client stack, every op completes byte-exact, per-client latency
    histograms non-empty."""
    c = _boot()
    res = run_traffic(c, TrafficSpec(n_clients=8, ops_per_client=32,
                                     read_fraction=0.5))
    assert res.byte_exact, res.errors[:5]
    assert res.total_ops == res.completed == 8 * 32
    assert len(res.per_client) == 8
    from ceph_tpu.trace import g_perf_histograms
    for name, st in res.per_client.items():
        assert st["completed"] == 32
        assert st["p99"] > 0.0, (name, st)
        hist = g_perf_histograms.get(name, "client_op_latency_histogram")
        assert hist.total_count >= 32
    # ops flowed through the client-tier lanes: the op-queue dump
    # shows per-client dequeue accounting on some shard
    deq = [cl for osd in c.osds.values()
           for sh in osd.op_wq.dump().values()
           for cl in sh.get("clients", {}).get(
               CLASS_CLIENT, {}).get("dequeues", {})]
    assert any(d.startswith("client.load") for d in deq), deq[:5]


def test_traffic_open_loop_zipf_mixed_sizes():
    """Open-loop arrivals with hot-key skew and a size mix complete
    byte-exact too (the arrival-process knobs all exercise)."""
    c = _boot()
    res = run_traffic(c, TrafficSpec(
        n_clients=8, ops_per_client=24, read_fraction=0.6,
        mode="open", rate=4.0, zipf_theta=1.2,
        object_sizes=((256, 0.6), (8192, 0.4)), seed=7))
    assert res.byte_exact, res.errors[:5]
    assert res.rounds > 1           # arrivals spread over rounds


def test_traffic_on_ec_pool():
    """The harness drives the EC write path under concurrency (the
    contention every perf PR since the async pipeline is measured
    under)."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=5)
    c.create_ec_pool("load", k=3, m=2, pg_num=8)
    res = run_traffic(c, TrafficSpec(n_clients=8, ops_per_client=8,
                                     read_fraction=0.5,
                                     object_sizes=((2048, 1.0),)))
    assert res.byte_exact, res.errors[:5]


# ---- per-client dmClock (deterministic virtual clock) ----------------------

def test_client_dmclock_weight_shares_converge_2_to_1():
    """Acceptance: under 2:1 weights with saturating demand, observed
    dequeue shares converge to 2:1 within +-10%."""
    q = ClientDmClock()
    q.set_client_tags("heavy", 0.0, 2.0, 0.0)
    q.set_client_tags("light", 0.0, 1.0, 0.0)
    for i in range(600):
        q.push("heavy", ("h", i))
        q.push("light", ("l", i))
    got = {"h": 0, "l": 0}
    for _ in range(600):            # both stay backlogged throughout
        got[q.pop()[0]] += 1
    share = got["h"] / got["l"]
    assert 1.8 <= share <= 2.2, got


def test_client_dmclock_reservation_floor_holds():
    """A low-weight client with a reservation keeps its floor against
    a high-weight greedy one: res=200 (ops per 1000 client-tier pops)
    must yield >= ~20% of dequeues despite a 1:50 weight ratio."""
    q = ClientDmClock()
    q.set_client_tags("meek", 200.0, 1.0, 0.0)
    q.set_client_tags("greedy", 0.0, 50.0, 0.0)
    for i in range(1000):
        q.push("meek", ("m", i))
        q.push("greedy", ("g", i))
    got = {"m": 0, "g": 0}
    for _ in range(1000):
        got[q.pop()[0]] += 1
    assert got["m"] >= 180, got     # floor held (within quantization)
    assert got["g"] >= 700, got     # and the rest went by weight


def test_client_dmclock_limit_caps_greedy_client():
    """limit=300 (per 1000 pops) caps a huge-weight client while
    others are backlogged; work conservation lifts the cap only when
    no one else has ops."""
    q = ClientDmClock()
    q.set_client_tags("capped", 0.0, 100.0, 300.0)
    q.set_client_tags("other", 0.0, 1.0, 0.0)
    for i in range(1000):
        q.push("capped", ("c", i))
        q.push("other", ("o", i))
    got = {"c": 0, "o": 0}
    for _ in range(1000):
        got[q.pop()[0]] += 1
    assert got["c"] <= 360, got     # capped near 30%
    # drain the rest: with "other" empty the cap must not strand work
    while len(q):
        assert q.pop() is not None


def test_client_tier_rides_inside_class_tier_with_overrides(
        clean_qos_conf):
    """End-to-end through MClockQueue: class arbitration unchanged on
    the outside, per-client weights from osd_mclock_client_overrides
    deciding WHICH client's op goes when the client class is picked."""
    g_conf.set_val("osd_mclock_client_overrides",
                   "client.a:0:3:0,client.b:0:1:0")
    q = MClockQueue()
    for i in range(400):
        q.enqueue(CLASS_CLIENT, ("a", i), client="client.a")
        q.enqueue(CLASS_CLIENT, ("b", i), client="client.b")
    got = {"a": 0, "b": 0}
    for _ in range(400):
        got[q.dequeue()[0]] += 1
    share = got["a"] / max(got["b"], 1)
    assert 2.6 <= share <= 3.4, got
    # injectargs semantics: changing the option re-parses immediately —
    # a FRESH queue under the new string shares evenly
    g_conf.set_val("osd_mclock_client_overrides",
                   "client.a:0:1:0,client.b:0:1:0")
    q2 = MClockQueue()
    for i in range(200):
        q2.enqueue(CLASS_CLIENT, ("a", i), client="client.a")
        q2.enqueue(CLASS_CLIENT, ("b", i), client="client.b")
    got2 = {"a": 0, "b": 0}
    for _ in range(200):
        got2[q2.dequeue()[0]] += 1
    assert abs(got2["a"] - got2["b"]) <= 20, got2


def test_unkeyed_ops_keep_fifo_behavior():
    """Ops enqueued with no client entity share the '' lane in pure
    FIFO — exactly the pre-client behavior (scrub/recovery items)."""
    q = MClockQueue()
    for i in range(50):
        q.enqueue(CLASS_CLIENT, i)
    assert [q.dequeue() for _ in range(50)] == list(range(50))


# ---- overload admission control --------------------------------------------

def test_admission_sheds_never_wedges(clean_qos_conf):
    """Acceptance: with osd_op_queue_admission_max exceeded, queue
    depth stays bounded, throttled clients retry, every op
    completes."""
    c = _boot()
    g_conf.set_val("osd_op_queue_admission_max", 12)
    res = run_traffic(c, TrafficSpec(
        n_clients=8, ops_per_client=32, read_fraction=0.4,
        mode="open", rate=8.0, seed=11))
    assert res.admission_rejections > 0, "admission never fired"
    assert res.throttle_events > 0
    assert res.max_intake_depth <= 12, res.max_intake_depth
    assert res.byte_exact, res.errors[:5]
    assert res.completed == res.total_ops == 8 * 32


def test_admission_exempts_internal_clients(clean_qos_conf):
    """Daemon-internal ops (tier traffic from other OSDs) bypass the
    throttle: only 'client.*' entities are shed."""
    from ceph_tpu.msg.messages import MOSDOp
    c = _boot()
    g_conf.set_val("osd_op_queue_admission_max", 1)
    osd = c.osds[0]
    before = qos_perf_counters().get(l_qos_admission_rejections)
    # an op from another OSD at depth >= max must still be admitted
    msg = MOSDOp(tid=1, pool=0, oid="x", pgid=(0, 0), op="read")
    msg.src = "osd.1"
    assert osd._admit_op(msg) is True
    msg2 = MOSDOp(tid=2, pool=0, oid="x", pgid=(0, 0), op="read")
    msg2.src = "client.x"
    # fill the queue past the cap, then the client op is shed
    osd.op_wq.enqueue((0, 0), CLASS_CLIENT, ("noop",))
    assert osd._admit_op(msg2) is False
    assert qos_perf_counters().get(
        l_qos_admission_rejections) == before + 1
    # drain the dummy item so later tests see an empty queue
    osd.op_wq.drain(lambda item: None)


def test_rados_client_retries_throttle_replies(clean_qos_conf):
    """The stock RadosClient transparently retries an admission
    throttle (EAGAIN + retry_after) without burning its map-refresh
    attempts."""
    c = _boot()
    cl = c.client("client.throttle")
    # every FIRST intake of a burst sheds at depth >= 1 only while
    # something is queued; with admission_max=1 and batch intake off,
    # the op is admitted at depth 0 — so force a shed by pre-throttling
    g_conf.set_val("osd_op_queue_admission_max", 1)
    g_conf.set_val("osd_op_queue_batch_intake", True)
    assert cl.write_full("load", "obj", b"x" * 500) == 0
    g_conf.rm_val("osd_op_queue_batch_intake")
    g_conf.rm_val("osd_op_queue_admission_max")
    assert cl.read("load", "obj") == b"x" * 500


# ---- per-client wait-time observability ------------------------------------

def test_per_client_wait_histogram_on_perf_dump():
    c = _boot()
    cl = c.client("client.wait")
    assert cl.write_full("load", "o", b"w" * 1000) == 0
    from ceph_tpu.trace import g_perf_histograms
    dump = g_perf_histograms.dump("client.wait")
    hist = dump.get("client.wait", {}).get(
        "client_queue_wait_latency_histogram")
    assert hist is not None and hist["count"] >= 1
    # admin-socket surface too
    out = c.admin_socket.execute(
        "perf histogram dump",
        args={"logger": "client.wait",
              "name": "client_queue_wait_latency_histogram"})
    assert out["client.wait"][
        "client_queue_wait_latency_histogram"]["count"] >= 1


def test_hist_percentiles_shape():
    from ceph_tpu.trace import PerfHistogram, latency_axes
    h = PerfHistogram(latency_axes())
    for v in (50, 150, 350, 900, 20000):
        h.inc(v)
    p = hist_percentiles(h)
    assert set(p) == {"p50", "p99", "p999"}
    assert 0 < p["p50"] <= p["p99"] <= p["p999"]


# ---- the million-op soak ---------------------------------------------------

@pytest.mark.slow
def test_traffic_soak_million_ops():
    """~1M ops through the harness (8 closed-loop clients, read-heavy
    mix, small payloads): every op completes byte-exact and the
    scheduler state drains clean.  CEPH_TPU_SOAK_OPS scales it down
    for spot-checking."""
    total = int(os.environ.get("CEPH_TPU_SOAK_OPS", 1_000_000))
    per_client = max(1, total // 8)
    c = _boot(n_osds=4, pg_num=8)
    res = run_traffic(c, TrafficSpec(
        n_clients=8, ops_per_client=per_client, read_fraction=0.8,
        window=8, keys_per_client=64,
        object_sizes=((128, 0.7), (1024, 0.3)),
        max_rounds=10_000_000, tick_every=1024,
        keep_completions=False),
        progress=lambda rnd, done: print(
            f"[soak] round {rnd}: {done} ops", flush=True))
    assert res.byte_exact, res.errors[:10]
    assert res.completed == 8 * per_client
    assert all(len(o.op_wq) == 0 for o in c.osds.values())
