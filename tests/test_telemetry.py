"""mgr telemetry rollup: cluster-merged percentiles, time-series
rings, and SLO burn-rate health.

Covers the telemetry PR's contracts: the merged cluster p99 is EXACTLY
the percentile of the union of the per-daemon bucket counts (same
edges, no re-bucketing error); the SLO engine fires on sustained
breach, clears with hysteresis, and never flaps on a single-tick
spike; `tpu status` / `telemetry dump` / the Prometheus
``ceph_cluster_*`` families all render from one shared rollup
snapshot; and an SLO breach under real harness load raises the
``TPU_SLO_*`` health checks at runtime and clears after the load
subsides.
"""
import re

import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.mgr.telemetry import (SLO_ADMISSION, SLO_COPY, SLO_OPLAT,
                                    Telemetry)
from ceph_tpu.trace import g_perf_histograms, latency_axes
from ceph_tpu.trace.histogram import (PerfHistogram, hist_percentiles,
                                      merge_axis0, merged_percentiles,
                                      percentiles_from_counts)
from ceph_tpu.trace.oplat import stage_hist_name

SLO_OPTS = ("mgr_slo_oplat_p99_usec", "mgr_slo_copies_per_op_max",
            "mgr_slo_admission_rate_max", "mgr_slo_fast_window_s",
            "mgr_slo_slow_window_s", "mgr_slo_sustain_ticks",
            "mgr_slo_clear_ticks", "mgr_telemetry_retention",
            "osd_op_queue_admission_max")


@pytest.fixture
def clean_slo_conf():
    yield
    for name in SLO_OPTS:
        g_conf.rm_val(name)


class FakeMgr:
    """The health surface the SLO engine drives (Manager duck-type)."""

    def __init__(self):
        self.health_checks = {}
        self.log = []

    def _cluster_log(self, level, message):
        self.log.append((level, message))


# ---- merge core ------------------------------------------------------------
def test_merged_cluster_percentiles_equal_union_percentiles():
    """Property: for random per-daemon distributions, the telemetry
    rollup's merged cluster percentile equals the percentile computed
    over the union of the per-daemon bucket counts — exact, because
    same-named families share one edge layout."""
    rng = np.random.default_rng(20260804)
    for trial in range(20):
        n_daemons = int(rng.integers(2, 6))
        hists = [PerfHistogram(latency_axes()) for _ in range(n_daemons)]
        for h in hists:
            for _ in range(int(rng.integers(1, 200))):
                h.inc(float(rng.lognormal(6.0, 2.0)))
        edges, counts = merge_axis0(hists)
        # the union, computed independently of the merge core
        union = [0] * len(counts)
        for h in hists:
            for i, c in enumerate(h.marginal_axis0()):
                union[i] += c
        assert counts == union, trial
        got = merged_percentiles(hists)
        want = percentiles_from_counts(union, edges, (0.5, 0.99, 0.999))
        assert got == want, trial
        # every quantile answer is one of the shared edges (exactness:
        # no daemon's sample can land between two daemons' buckets)
        assert all(v in edges or v == 0.0 for v in got.values())


def test_merge_refuses_mismatched_edges():
    from ceph_tpu.trace.histogram import PerfHistogramAxis
    a = PerfHistogram(latency_axes())
    b = PerfHistogram([PerfHistogramAxis("latency_usec", min=0,
                                         quant_size=7, buckets=32)])
    with pytest.raises(ValueError):
        merge_axis0([a, b])


def test_hist_percentiles_is_the_shared_implementation():
    """Satellite receipt: the load harness re-exports the ONE
    percentile implementation from trace.histogram (no second
    cumulative-walk copy left to drift)."""
    from ceph_tpu.load import traffic
    assert traffic.hist_percentiles is hist_percentiles
    h = PerfHistogram(latency_axes())
    for v in (50, 150, 900, 20000):
        h.inc(v)
    p = hist_percentiles(h)
    assert set(p) == {"p50", "p99", "p999"}
    assert 0 < p["p50"] <= p["p99"] <= p["p999"]


# ---- ring + rollup ---------------------------------------------------------
def test_ring_bounded_by_retention(clean_slo_conf):
    g_conf.set_val("mgr_telemetry_retention", 5)
    tel = Telemetry()
    for t in range(20):
        tel.collect(float(t))
    assert len(tel._ring) == 5
    assert tel._ring[-1]["t"] == 19.0
    # stale/duplicate clocks are no-ops, not ring churn
    tel.collect(3.0)
    tel.collect(19.0)
    assert len(tel._ring) == 5 and tel._ring[-1]["t"] == 19.0


def test_rollup_window_isolates_run_from_process_history(clean_slo_conf):
    """The boot baseline sample makes window deltas run-scoped: a
    fresh Telemetry sees only samples recorded AFTER its baseline,
    not the process-global histogram history."""
    h = g_perf_histograms.get("osd.telrollup",
                              stage_hist_name("device_call"),
                              latency_axes)
    for _ in range(50):
        h.inc(100.0)                    # pre-history
    tel = Telemetry()
    tel.collect(0.0)                    # baseline
    for _ in range(10):
        h.inc(820000.0)                 # the "run"
    tel.collect(10.0)
    roll = tel.rollup(window_s=100.0)
    st = roll["oplat"]["device_call"]
    assert st["count"] == 10, st        # not 60
    assert st["p99"] > 100000.0


# ---- SLO engine ------------------------------------------------------------
def _slo_conf(oplat="", copies=0.0, admission=0.0):
    g_conf.set_val("mgr_slo_oplat_p99_usec", oplat)
    g_conf.set_val("mgr_slo_copies_per_op_max", copies)
    g_conf.set_val("mgr_slo_admission_rate_max", admission)
    g_conf.set_val("mgr_slo_fast_window_s", 5.0)
    g_conf.set_val("mgr_slo_slow_window_s", 20.0)
    g_conf.set_val("mgr_slo_sustain_ticks", 2)
    g_conf.set_val("mgr_slo_clear_ticks", 2)


def test_slo_fires_on_sustained_breach_only(clean_slo_conf):
    _slo_conf(oplat="device_call:1000")
    tel, mgr = Telemetry(), FakeMgr()
    h = g_perf_histograms.get("osd.sloA",
                              stage_hist_name("device_call"),
                              latency_axes)
    tel.tick(mgr, 0.0)                  # baseline
    for _ in range(4):
        h.inc(50000.0)
    tel.tick(mgr, 1.0)                  # breach tick 1: streak 1
    assert SLO_OPLAT not in mgr.health_checks
    for _ in range(4):
        h.inc(50000.0)
    tel.tick(mgr, 2.0)                  # breach tick 2: raises
    assert SLO_OPLAT in mgr.health_checks
    assert "device_call" in mgr.health_checks[SLO_OPLAT]
    assert any(lv == "WRN" and SLO_OPLAT in m for lv, m in mgr.log)
    st = tel.slo_state()[SLO_OPLAT]
    assert st["state"] == "breach" and st["burn_fast"] >= 1.0


def test_slo_never_flaps_on_single_tick_spike(clean_slo_conf):
    _slo_conf(oplat="device_call:1000")
    tel, mgr = Telemetry(), FakeMgr()
    h = g_perf_histograms.get("osd.sloB",
                              stage_hist_name("device_call"),
                              latency_axes)
    tel.tick(mgr, 0.0)
    h.inc(800000.0)                     # one huge spike, one tick
    tel.tick(mgr, 1.0)
    for t in (2.0, 3.0, 4.0, 5.0, 6.0):
        tel.tick(mgr, t)                # quiet ticks follow
        assert SLO_OPLAT not in mgr.health_checks, t
    assert not any(lv == "WRN" for lv, _m in mgr.log)


def test_slo_clears_with_hysteresis(clean_slo_conf):
    _slo_conf(oplat="device_call:1000")
    tel, mgr = Telemetry(), FakeMgr()
    h = g_perf_histograms.get("osd.sloC",
                              stage_hist_name("device_call"),
                              latency_axes)
    tel.tick(mgr, 0.0)
    for t in (1.0, 2.0, 3.0):
        for _ in range(4):
            h.inc(50000.0)
        tel.tick(mgr, t)
    assert SLO_OPLAT in mgr.health_checks
    tel.tick(mgr, 4.0)                  # clean tick 1: still raised
    assert SLO_OPLAT in mgr.health_checks, "cleared without hysteresis"
    tel.tick(mgr, 5.0)                  # clean tick 2: clears
    assert SLO_OPLAT not in mgr.health_checks
    assert any(lv == "INF" and SLO_OPLAT in m for lv, m in mgr.log)
    assert tel.slo_state()[SLO_OPLAT]["state"] == "ok"


def test_slo_copy_and_admission_objectives(clean_slo_conf):
    """The copy-budget and admission-rate objectives judge counter
    deltas: copies/op from devprof+oplat, rejections/s from qos."""
    from ceph_tpu.common.work_queue import (l_qos_admission_rejections,
                                            qos_perf_counters)
    from ceph_tpu.trace import g_devprof
    from ceph_tpu.trace.oplat import g_oplat
    _slo_conf(copies=2.0, admission=1.0)
    tel, mgr = Telemetry(), FakeMgr()
    tel.tick(mgr, 0.0)
    for t in (1.0, 2.0, 3.0):
        for _ in range(10):             # 10 ops, 50 copies: 5/op > 2
            g_oplat.note_op()
        for _ in range(50):
            g_devprof.account_host_copy("telemetry.test", 64)
        qos_perf_counters().inc(l_qos_admission_rejections, 30)
        tel.tick(mgr, t)                # 30 rejections/s > 1/s
    assert SLO_COPY in mgr.health_checks
    assert SLO_ADMISSION in mgr.health_checks
    # objective removed at runtime -> check torn down on next tick
    g_conf.set_val("mgr_slo_copies_per_op_max", 0.0)
    tel.tick(mgr, 4.0)
    assert SLO_COPY not in mgr.health_checks
    assert SLO_ADMISSION in mgr.health_checks


def test_reset_while_breaching_cannot_strand_the_health_check(
        clean_slo_conf):
    """`telemetry reset` while a check is active wipes the streak
    state; the next evaluation must reconcile — health() and
    slo_state() may never disagree forever."""
    _slo_conf(oplat="device_call:1000")
    tel, mgr = Telemetry(), FakeMgr()
    h = g_perf_histograms.get("osd.sloD",
                              stage_hist_name("device_call"),
                              latency_axes)
    tel.tick(mgr, 0.0)
    for t in (1.0, 2.0, 3.0):
        for _ in range(4):
            h.inc(50000.0)
        tel.tick(mgr, t)
    assert SLO_OPLAT in mgr.health_checks
    tel.reset()
    tel.tick(mgr, 4.0)                  # quiet tick post-reset
    assert SLO_OPLAT not in mgr.health_checks, \
        "reset stranded the raised health check"
    assert tel.slo_state()[SLO_OPLAT]["state"] == "ok"
    # the nastier ordering: reset AND objective disabled before the
    # next tick — no verdict and no streak state remain, only the
    # invariant sweep can pop the raised check
    for t in (5.0, 6.0, 7.0):
        for _ in range(4):
            h.inc(50000.0)
        tel.tick(mgr, t)
    assert SLO_OPLAT in mgr.health_checks
    tel.reset()
    g_conf.set_val("mgr_slo_oplat_p99_usec", "")
    tel.tick(mgr, 8.0)
    assert SLO_OPLAT not in mgr.health_checks, \
        "reset + objective removal stranded the raised health check"


# ---- surfaces --------------------------------------------------------------
@pytest.fixture(scope="module")
def rollup_cluster():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("tel", size=3, pg_num=8)
    cl = c.client("client.tel")
    for i in range(8):
        assert cl.write_full("tel", f"o{i}", b"t" * 4000) == 0
    c.tick(dt=1.0, rounds=2)
    return c


def test_dump_and_exposition_render_one_snapshot(rollup_cluster):
    """Satellite: `telemetry dump` and the Prometheus scrape render
    from ONE shared rollup function — every cluster gauge value in
    the exposition equals the dump's figure for it."""
    c = rollup_cluster
    dump = c.admin_socket.execute("telemetry dump")
    text = c.admin_socket.execute("prometheus metrics")
    assert dump["oplat_p99_usec"], "no oplat stages in the rollup"
    got_p99 = {}
    for line in text.splitlines():
        m = re.fullmatch(
            r'ceph_cluster_oplat_p99_usec\{stage="(\w+)"\} (\S+)', line)
        if m:
            got_p99[m.group(1)] = float(m.group(2))
    assert got_p99 == dump["oplat_p99_usec"]
    got_rates = {}
    for line in text.splitlines():
        m = re.fullmatch(r"ceph_cluster_rate_(\w+) (\S+)", line)
        if m:
            got_rates[m.group(1)] = float(m.group(2))
    assert got_rates == dump["rates"]
    # the single-pane status draws from the same snapshot too
    st = c.admin_socket.execute("tpu status")
    assert st["cluster_p99_usec"] == dump["oplat_p99_usec"]
    assert st["rates"] == dump["rates"]
    assert st["health"].startswith("HEALTH_")
    assert st["breakers_open"] == []


def test_telemetry_dump_shape_and_reset(rollup_cluster):
    c = rollup_cluster
    d = c.admin_socket.execute("telemetry dump")
    assert d["samples"] >= 2 and d["span_s"] > 0
    assert d["rates"]["ops"] > 0
    # cluster-merged family percentiles: the OSD write family merged
    # across daemons is one number, not one per daemon
    fam = d["families"]["op_w_latency_in_bytes_histogram"]
    assert fam["count"] >= 8 and fam["p99"] >= fam["p50"]
    assert set(d["objectives"]) == {"oplat_p99_usec",
                                    "copies_per_op_max",
                                    "admission_rate_max"}
    out = c.admin_socket.execute("telemetry reset")
    assert out == {"reset": True}
    d2 = c.admin_socket.execute("telemetry dump")
    assert d2["samples"] == 0 and d2["families"] == {}
    # next tick repopulates (reset drops rings, not the histograms)
    c.tick(dt=1.0)
    assert c.admin_socket.execute("telemetry dump")["samples"] == 1


# ---- the load-harness acceptance scenario ---------------------------------
def test_slo_breach_under_load_raises_and_clears(clean_slo_conf):
    """Acceptance: abusive-client saturation raises TPU_SLO_ADMISSION
    and TPU_SLO_OPLAT at runtime (mgr ticks DURING the run), `tpu
    status` shows the breaching stage's cluster p99, and both checks
    clear after the load subsides."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.load import TrafficSpec, run_traffic
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("load", size=3, pg_num=8)
    g_conf.set_val("osd_op_queue_admission_max", 8)
    g_conf.set_val("mgr_slo_admission_rate_max", 0.1)
    g_conf.set_val("mgr_slo_oplat_p99_usec", "class_queue:1000")
    g_conf.set_val("mgr_slo_fast_window_s", 4.0)
    g_conf.set_val("mgr_slo_slow_window_s", 16.0)
    res = run_traffic(c, TrafficSpec(
        n_clients=8, ops_per_client=16, mode="open", rate=4.0,
        rate_multipliers=(10.0,), tick_every=4))
    assert res.byte_exact, res.errors[:4]
    assert res.admission_rejections > 0
    health = c.health()
    assert SLO_ADMISSION in health and SLO_OPLAT in health, health
    st = c.admin_socket.execute("tpu status")
    assert st["slo"][SLO_ADMISSION] == "breach"
    assert st["slo"][SLO_OPLAT] == "breach"
    # the single pane names the breaching stage's cluster p99
    assert st["cluster_p99_usec"]["class_queue"] > 1000.0
    assert st["rates"]["admission_rejections"] > 0.1
    # load subsides: quiet ticks roll the windows clean and the
    # hysteresis clears both checks
    for _ in range(10):
        c.tick(dt=2.0)
        if "TPU_SLO" not in c.health():
            break
    health = c.health()
    assert SLO_ADMISSION not in health and SLO_OPLAT not in health, \
        health
    st = c.admin_socket.execute("tpu status")
    assert st["slo"][SLO_ADMISSION] == "ok"
    assert st["slo"][SLO_OPLAT] == "ok"
