"""Durable OSD restart over real processes: kill -9, then boot a NEW
process on the same data directory and prove it recovered its history.

The reference flow: qa/tasks/ceph_manager.py:195 kill_osd + :373
revive_osd against daemons whose stores survive on disk; on boot the
OSD mounts the store, replays its journal and re-peers with its PG
logs intact (src/osd/OSD.cc:2469 init).  Here the WALStore
(ceph_tpu/os_store/walstore.py) provides the journal: writes acked
while the daemon was alive must be present after a SIGKILL + remount,
and writes the daemon MISSED while dead must arrive by log-based
recovery once it rejoins."""
import time

import numpy as np
import pytest

from ceph_tpu.osdmap import pg_t
from ceph_tpu.vstart import ProcessCluster

NONE = 0x7FFFFFFF


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = ProcessCluster(
        n_osds=4,
        pool={"type": "replicated", "name": "p", "pg_num": 4, "size": 3},
        heartbeat_interval=1.0, heartbeat_grace=4.0,
        down_out_interval=600.0,        # never auto-out: the osd comes BACK
        data_root=str(tmp_path_factory.mktemp("osd_data")))
    yield c
    c.close()


def _acting(cl, oid):
    pgid, primary = cl._calc_target(cl.lookup_pool("p"), oid)
    *_, acting, ap = cl.osdmap.pg_to_up_acting_osds(pg_t(*pgid))
    return [o for o in acting if o != NONE], ap


def _wait_state(c, cl, osd_id, up: bool, timeout=45.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        c.pump_for(1.0)
        cl.mon.send_full_map(cl.name)
        c.network.pump()
        if cl.osdmap.is_up(osd_id) == up:
            return True
    return False


def _retry_write(cl, pool, oid, data, tries=30):
    for _ in range(tries):
        if cl.write_full(pool, oid, data) == 0:
            return 0
        time.sleep(0.5)
    return -1


def test_kill9_restart_recovers_from_disk(cluster):
    c = cluster
    cl = c.client()
    c.wait_healthy(cl)
    rng = np.random.default_rng(11)
    data1 = rng.integers(0, 256, 20000, dtype=np.uint8).tobytes()
    assert _retry_write(cl, "p", "obj1", data1) == 0
    assert cl.read("p", "obj1") == data1

    # under heavy host load a daemon's heartbeats can momentarily lapse
    # past the grace (MOSDBoot re-ups it); wait for the full acting set
    # instead of sampling one instant
    deadline = time.monotonic() + 60
    while True:
        acting, primary = _acting(cl, "obj1")
        if len(acting) == 3:
            break
        assert time.monotonic() < deadline, f"acting stuck at {acting}"
        time.sleep(1.0)
        cl.mon.send_full_map(cl.name)
        cl.network.pump()
    victim = next(o for o in acting if o != primary)
    c.kill_osd(victim)
    assert _wait_state(c, cl, victim, up=False), "victim never marked down"

    # degraded write the victim MISSES (replicated size=3 min_size=2)
    data2 = rng.integers(0, 256, 15000, dtype=np.uint8).tobytes()
    assert _retry_write(cl, "p", "obj2", data2) == 0

    # boot a NEW process on the same port + data dir: WAL replay + boot
    # message; the mon marks it back up
    c.restart_osd(victim)
    assert _wait_state(c, cl, victim, up=True), \
        "rebooted daemon never marked up"
    c.pump_for(8.0)                      # re-peer + log-based catch-up

    # acked-before-kill data survived the SIGKILL on the victim's disk,
    # and the missed write arrived by recovery: prove both by removing
    # every OTHER original replica and reading through what remains
    others = [o for o in acting if o != victim]
    for o in others:
        c.kill_osd(o)
        assert _wait_state(c, cl, o, up=False), f"osd.{o} never down"
    deadline = time.monotonic() + 45
    got1 = got2 = None
    while time.monotonic() < deadline:
        c.pump_for(1.0)
        cl.mon.send_full_map(cl.name)
        c.network.pump()
        try:
            got1 = cl.read("p", "obj1")
            got2 = cl.read("p", "obj2")
        except Exception:
            got1 = got2 = None
        if got1 == data1 and got2 == data2:
            break
    assert got1 == data1, "pre-kill write lost across SIGKILL+remount"
    assert got2 == data2, "missed write never recovered to the " \
        "rebooted daemon"
