"""librbd-lite: image I/O, snapshots, clones, flatten, CLI.

Mirrors the reference's librbd unit surface (src/test/librbd) at lite
scale: striping correctness incl. sparse reads, snapshot read/rollback
via selfmanaged snapcs, COW clone copyup + parent fall-through, flatten
severing the parent link, and directory/children index consistency via
the server-side cls_rbd methods.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rbd import Image, RBD, RBDError

ORDER = 12                      # 4 KiB objects keep the tests tiny
OBJ = 1 << ORDER


@pytest.fixture()
def env():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rbd", size=3, pg_num=8)
    cl = c.client("client.rbd")
    return c, cl, RBD(cl)


def test_create_list_rename_remove(env):
    c, cl, rbd = env
    rbd.create("rbd", "a", 10 * OBJ, ORDER)
    rbd.create("rbd", "b", 4 * OBJ, ORDER)
    assert rbd.list("rbd") == ["a", "b"]
    with pytest.raises(RBDError):
        rbd.create("rbd", "a", OBJ, ORDER)      # name collision
    rbd.rename("rbd", "b", "c")
    assert rbd.list("rbd") == ["a", "c"]
    rbd.remove("rbd", "c")
    assert rbd.list("rbd") == ["a"]


def test_io_striping_and_sparse(env):
    c, cl, rbd = env
    rbd.create("rbd", "img", 8 * OBJ, ORDER)
    img = Image(cl, "rbd", "img")
    # a write spanning three objects
    payload = bytes(range(256)) * ((2 * OBJ + 512) // 256)
    img.write(OBJ // 2, payload)
    assert img.read(OBJ // 2, len(payload)) == payload
    # sparse regions read as zeros, including whole absent objects
    assert img.read(6 * OBJ, 100) == b"\x00" * 100
    assert img.read(0, 16) == b"\x00" * 16
    # reads clip at the image end
    assert len(img.read(8 * OBJ - 10, 1000)) == 10
    with pytest.raises(RBDError):
        img.write(8 * OBJ - 1, b"xx")           # past the end


def test_discard_and_resize(env):
    c, cl, rbd = env
    rbd.create("rbd", "img", 4 * OBJ, ORDER)
    img = Image(cl, "rbd", "img")
    img.write(0, b"A" * (4 * OBJ))
    img.discard(OBJ, OBJ)                       # whole object
    img.discard(10, 20)                         # sub-object hole
    assert img.read(OBJ, OBJ) == b"\x00" * OBJ
    assert img.read(10, 20) == b"\x00" * 20
    assert img.read(30, 10) == b"A" * 10
    img.resize(2 * OBJ + 100)
    assert img.size() == 2 * OBJ + 100
    assert img.read(2 * OBJ, 200) == b"A" * 100
    img.resize(4 * OBJ)                         # grow back: sparse zeros
    assert img.read(2 * OBJ + 100, 100) == b"\x00" * 100
    assert img.read(3 * OBJ, OBJ) == b"\x00" * OBJ


def test_snapshots_read_and_rollback(env):
    c, cl, rbd = env
    rbd.create("rbd", "img", 4 * OBJ, ORDER)
    img = Image(cl, "rbd", "img")
    img.write(0, b"one" * 100)
    img.snap_create("s1")
    img.write(0, b"two" * 200)
    img.write(2 * OBJ, b"later-object")
    assert Image(cl, "rbd", "img", snapshot="s1").read(0, 300) == \
        b"one" * 100
    # object created after the snap reads as zeros at the snap
    assert Image(cl, "rbd", "img", snapshot="s1").read(
        2 * OBJ, 12) == b"\x00" * 12
    assert img.read(0, 600) == b"two" * 200
    img.snap_rollback("s1")
    assert img.read(0, 300) == b"one" * 100
    assert img.read(300, 300) == b"\x00" * 300  # post-snap bytes gone
    assert img.read(2 * OBJ, 12) == b"\x00" * 12
    # snapshots pin removal until deleted
    with pytest.raises(RBDError):
        rbd.remove("rbd", "img")
    img.snap_remove("s1")
    rbd.remove("rbd", "img")
    assert rbd.list("rbd") == []


def test_snapshot_size_view(env):
    c, cl, rbd = env
    rbd.create("rbd", "img", 4 * OBJ, ORDER)
    img = Image(cl, "rbd", "img")
    img.write(0, b"x" * OBJ)
    img.snap_create("small")
    img.resize(8 * OBJ)
    img.write(5 * OBJ, b"grown")
    snap = Image(cl, "rbd", "img", snapshot="small")
    assert snap.size() == 4 * OBJ
    assert snap.read(0, OBJ) == b"x" * OBJ
    assert snap.read(5 * OBJ, 5) == b""         # beyond snap size
    with pytest.raises(RBDError):
        snap.write(0, b"nope")                  # read-only view


def test_clone_copyup_flatten(env):
    c, cl, rbd = env
    rbd.create("rbd", "parent", 4 * OBJ, ORDER)
    parent = Image(cl, "rbd", "parent")
    parent.write(0, b"P" * OBJ)
    parent.write(2 * OBJ, b"Q" * 100)
    parent.snap_create("base")
    with pytest.raises(RBDError):
        rbd.clone("rbd", "parent", "base", "rbd", "child")  # unprotected
    parent.snap_protect("base")
    rbd.clone("rbd", "parent", "base", "rbd", "child")
    child = Image(cl, "rbd", "child")
    # reads fall through to the parent snap
    assert child.read(0, OBJ) == b"P" * OBJ
    assert child.read(2 * OBJ, 100) == b"Q" * 100
    # parent head changes must NOT leak into the child
    parent.write(0, b"Z" * OBJ)
    assert child.read(0, OBJ) == b"P" * OBJ
    # copyup: a partial child write preserves surrounding parent bytes
    child.write(10, b"child-bytes")
    assert child.read(0, 10) == b"P" * 10
    assert child.read(10, 11) == b"child-bytes"
    assert child.read(21, OBJ - 21) == b"P" * (OBJ - 21)
    # snap protection is pinned by the child
    with pytest.raises(RBDError):
        parent.snap_unprotect("base")
    child.flatten()
    assert child.parent() is None
    assert child.read(2 * OBJ, 100) == b"Q" * 100
    parent.snap_unprotect("base")
    parent.snap_remove("base")
    # the flattened child stands alone even after the parent dies
    rbd.remove("rbd", "parent")
    assert child.read(0, 10) == b"P" * 10
    assert child.read(10, 11) == b"child-bytes"


def test_clone_discard_stays_hole(env):
    """A discard inside the parent overlap must not re-expose parent
    bytes (librbd whiteout semantics for clone discards)."""
    c, cl, rbd = env
    rbd.create("rbd", "parent", 4 * OBJ, ORDER)
    parent = Image(cl, "rbd", "parent")
    parent.write(0, b"P" * (2 * OBJ))
    parent.snap_create("base")
    parent.snap_protect("base")
    rbd.clone("rbd", "parent", "base", "rbd", "child")
    child = Image(cl, "rbd", "child")
    # whole-object discard on an untouched (parent-backed) object
    child.discard(0, OBJ)
    assert child.read(0, OBJ) == b"\x00" * OBJ
    # sub-object discard on an absent child object: copyup + zero
    child.discard(OBJ + 100, 50)
    assert child.read(OBJ + 100, 50) == b"\x00" * 50
    assert child.read(OBJ, 100) == b"P" * 100          # rest preserved
    assert child.read(OBJ + 150, 100) == b"P" * 100
    # discard after copyup behaves the same
    child.write(10, b"x")
    child.discard(0, OBJ)
    assert child.read(0, OBJ) == b"\x00" * OBJ
    # beyond the overlap whole-object discard still removes outright
    child.write(3 * OBJ, b"tail")
    child.discard(3 * OBJ, OBJ)
    assert child.read(3 * OBJ, 4) == b"\x00" * 4


def test_copyup_race_does_not_smear_parent_bytes(env):
    """Two clients racing the first write to a clone object: the loser
    of the copyup race must NOT re-write parent bytes over the winner's
    committed data (exclusive-create guard on the copyup vector)."""
    c, cl, rbd = env
    rbd.create("rbd", "parent", 2 * OBJ, ORDER)
    parent = Image(cl, "rbd", "parent")
    parent.write(0, b"P" * OBJ)
    parent.snap_create("base")
    parent.snap_protect("base")
    rbd.clone("rbd", "parent", "base", "rbd", "child")
    a = Image(cl, "rbd", "child")
    b = Image(c.client("client.rbd2"), "rbd", "child")
    a.write(0, b"AAAA")                  # wins the copyup
    # force b into the stale stat-then-copyup window
    b._needs_copyup = lambda objno: True
    b.write(100, b"BBBB")
    assert a.read(0, 4) == b"AAAA"       # not smeared back to parent
    assert a.read(100, 4) == b"BBBB"
    assert a.read(4, 8) == b"P" * 8


def test_snapc_rejected_on_pool_snap_pool(env):
    """A client snapc on a pool-snapshot pool is refused (EINVAL) both
    client-side and by the OSD."""
    import pytest as _pytest
    c, cl, rbd = env
    cl.write_full("rbd", "o", b"v1")
    cl.snap_create("rbd", "ps1")
    with _pytest.raises(ValueError):
        cl.set_write_ctx("rbd", 1, [1])
    # force it past the client guard: the OSD still rejects
    cl._write_snapc[cl.lookup_pool("rbd")] = (1, [])
    assert cl.write_full("rbd", "o", b"v2") == -22
    cl._write_snapc.clear()
    assert cl.read("rbd", "o", snap="ps1") == b"v1"


def test_ec_data_pool(env):
    """Image data on an EC pool, metadata in the replicated pool — the
    librbd data-pool feature (EC pools cannot hold omap, so headers
    must stay in an omap-capable pool, here as in the reference)."""
    c, cl, rbd = env
    c.create_ec_pool("ecdata", k=2, m=1, plugin="isa", pg_num=8)
    rbd.create("rbd", "vm", 8 * OBJ, ORDER, data_pool="ecdata")
    img = Image(cl, "rbd", "vm")
    assert img.data_pool == "ecdata"
    img.write(0, b"ec-backed-bytes" * 100)
    assert img.read(0, 15) == b"ec-backed-bytes"
    # the data objects really are in the EC pool
    assert cl.read("ecdata", img._obj(0), length=15) == b"ec-backed-bytes"
    with pytest.raises(IOError):
        cl.read("rbd", img._obj(0))
    # snapshots allocate ids on the DATA pool and clone there
    img.snap_create("s1")
    img.write(0, b"overwritten-now")
    assert Image(cl, "rbd", "vm", snapshot="s1").read(0, 15) == \
        b"ec-backed-bytes"
    img.snap_remove("s1")
    rbd.remove("rbd", "vm")
    assert rbd.list("rbd") == []
    # cls omap methods on the EC pool itself fail loudly (EOPNOTSUPP)
    ret, _ = cl.exec("ecdata", "rbd_directory", "rbd", "dir_add_image",
                     b'{"name": "x", "id": "y"}')
    assert ret == -95


def test_rbd_cli(env, tmp_path, capsys):
    c, cl, rbd = env
    from ceph_tpu.tools import rbd_cli
    run = lambda *a: rbd_cli.run(c, cl, ["-p", "rbd", *a])
    run("create", "disk", "--size", str(4 * OBJ), "--order", str(ORDER))
    img = Image(cl, "rbd", "disk")
    img.write(0, b"cli-payload")
    run("snap", "create", "disk@s1")
    img.write(0, b"overwritten")
    run("snap", "rollback", "disk@s1")
    assert img.read(0, 11) == b"cli-payload"
    run("export", "disk", str(tmp_path / "out.bin"))
    data = (tmp_path / "out.bin").read_bytes()
    assert data[:11] == b"cli-payload" and len(data) == 4 * OBJ
    run("import", str(tmp_path / "out.bin"), "disk2")
    assert Image(cl, "rbd", "disk2").read(0, 11) == b"cli-payload"
    run("ls")
    out = capsys.readouterr().out
    assert "disk" in out and "disk2" in out


def test_du(env, capsys):
    """rbd du: sparse images cost only their written objects; snapshots
    report their own point-in-time usage."""
    c, cl, rbd = env
    rbd.create("rbd", "sparse", 16 * OBJ, ORDER)
    img = Image(cl, "rbd", "sparse")
    assert img.du() == {"provisioned": 16 * OBJ, "used": 0}
    img.write(0, b"x" * 100)
    img.write(10 * OBJ, b"y" * OBJ)
    du = img.du()
    assert du["provisioned"] == 16 * OBJ
    assert du["used"] == 100 + OBJ
    img.snap_create("s")
    img.write(0, b"z" * OBJ)             # grow object 0 post-snap
    assert img.du()["used"] == 2 * OBJ
    snap_du = Image(cl, "rbd", "sparse", snapshot="s").du()
    assert snap_du["used"] == 100 + OBJ  # point-in-time usage
    from ceph_tpu.tools import rbd_cli
    import json as _json
    assert rbd_cli.run(c, cl, ["-p", "rbd", "du", "sparse"]) == 0
    assert _json.loads(capsys.readouterr().out)["used"] == 2 * OBJ
    assert rbd_cli.run(c, cl, ["-p", "rbd", "du", "sparse@s"]) == 0
    assert _json.loads(capsys.readouterr().out)["used"] == 100 + OBJ
