"""SHEC plugin: shingled matrix structure, recovery sweep, minimum reads.

Mirrors the reference's TestErasureCodeShec* suites: parameter validation,
matrix shingle structure, all-erasure-combination recovery up to c losses,
and the reduced-read minimum_to_decode property that motivates SHEC.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import plugin_registry
from ceph_tpu.ec.shec import shec_coding_matrix, MULTIPLE, SINGLE


def make(k=4, m=3, c=2, technique="multiple"):
    return plugin_registry.factory("shec", {
        "plugin": "shec", "k": str(k), "m": str(m), "c": str(c),
        "technique": technique})


def payload(n=8192, seed=3):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()


def test_defaults():
    codec = plugin_registry.factory("shec", {"plugin": "shec"})
    assert codec.get_chunk_count() == 7      # k=4 + m=3
    assert codec.get_data_chunk_count() == 4


def test_parameter_validation():
    with pytest.raises(ValueError):
        make(4, 2, 3)       # c > m
    with pytest.raises(ValueError):
        make(13, 3, 2)      # k > 12
    with pytest.raises(ValueError):
        make(3, 4, 2)       # m > k
    with pytest.raises(ValueError):
        plugin_registry.factory("shec", {"plugin": "shec", "k": "4"})


def test_matrix_is_shingled():
    mat = shec_coding_matrix(8, 4, 3, MULTIPLE)
    assert mat.shape == (4, 8)
    # shingling zeroes a window in at least some parity rows (a group with
    # c == m legitimately keeps full rows, ErasureCodeShec.cc:505-522)
    assert (mat == 0).any()
    # single technique: uniform windows, all rows same weight
    mats = shec_coding_matrix(8, 4, 3, SINGLE)
    weights = [(mats[i] != 0).sum() for i in range(4)]
    assert len(set(weights)) == 1


def test_roundtrip_no_erasure():
    codec = make()
    data = payload()
    enc = codec.encode(set(range(7)), data)
    assert codec.decode_concat(enc)[:len(data)] == data


@pytest.mark.parametrize("k,m,c", [(4, 3, 2), (6, 4, 3), (4, 2, 1)])
def test_all_c_erasure_combinations_recover(k, m, c):
    codec = make(k, m, c)
    n = k + m
    data = payload(4096)
    enc = codec.encode(set(range(n)), data)
    for lost in itertools.combinations(range(n), c):
        have = {i: enc[i] for i in range(n) if i not in lost}
        got = codec.decode(set(range(k)), have)
        out = b"".join(got[i].tobytes() for i in range(k))
        assert out[:len(data)] == data, f"lost={lost}"


def test_minimum_to_decode_reads_fewer_than_k():
    # SHEC's selling point: single-chunk repair reads a shingle window,
    # not all k chunks
    codec = make(8, 4, 3)
    avail = set(range(1, 12))
    minimum = set(codec.minimum_to_decode({0}, avail))
    assert len(minimum) < 8
    # and the minimum actually suffices to decode chunk 0
    data = payload(8192)
    enc = codec.encode(set(range(12)), data)
    have = {i: enc[i] for i in minimum}
    got = codec.decode({0}, have)
    np.testing.assert_array_equal(got[0], enc[0])


def test_minimum_to_decode_no_erasure():
    codec = make()
    assert set(codec.minimum_to_decode({1, 2}, set(range(7)))) == {1, 2}


def test_parity_reconstruction():
    codec = make()
    data = payload()
    enc = codec.encode(set(range(7)), data)
    # lose a parity chunk; decode should regenerate it bit-exactly
    have = {i: enc[i] for i in range(7) if i != 5}
    got = codec.decode({5}, have)
    np.testing.assert_array_equal(got[5], enc[5])


def test_beyond_c_failures_often_unrecoverable():
    # SHEC is not MDS: some (c+1)-erasure patterns must fail
    codec = make(4, 3, 2)
    data = payload(4096)
    enc = codec.encode(set(range(7)), data)
    failures = 0
    for lost in itertools.combinations(range(7), 3):
        have = {i: enc[i] for i in range(7) if i not in lost}
        try:
            got = codec.decode(set(range(4)), have)
            out = b"".join(got[i].tobytes() for i in range(4))
            assert out[:len(data)] == data
        except IOError:
            failures += 1
    assert failures > 0


def test_device_backend_byte_identical():
    """VERDICT #7: shec through the device backend (encode, batched
    encode, batched signature-cached decode) equals the host path."""
    import numpy as np
    from ceph_tpu.ec import plugin_registry
    prof = {"k": "4", "m": "3", "c": "2"}
    host = plugin_registry.factory("shec", dict(prof, backend="host"))
    dev = plugin_registry.factory("shec", dict(prof, backend="tpu"))
    rng = np.random.default_rng(88)
    data = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
    n = host.get_chunk_count()
    eh = host.encode(set(range(n)), data)
    ed = dev.encode(set(range(n)), data)
    for i in range(n):
        np.testing.assert_array_equal(eh[i], ed[i], err_msg=f"chunk {i}")
    for gone in ([0], [2, 5], [1, 6]):
        have = {i: ed[i] for i in range(n) if i not in gone}
        dh = host.decode(set(gone), {i: eh[i] for i in have})
        dd = dev.decode(set(gone), have)
        for i in gone:
            np.testing.assert_array_equal(dh[i], dd[i], err_msg=str(gone))
    # batched stripe entries (ecutil shapes): encode_batch + decode_batch
    k = 4
    C = 512
    stripes = rng.integers(0, 256, (6, k, C), dtype=np.uint8)
    cb_h = host.encode_batch(stripes)
    cb_d = dev.encode_batch(stripes)
    np.testing.assert_array_equal(cb_h, cb_d)
    chunks = {i: stripes[:, i] for i in range(k)}
    chunks.update({k + i: cb_d[:, i] for i in range(3)})
    del chunks[1], chunks[5]
    got = dev.decode_batch(chunks, [1, 5])
    np.testing.assert_array_equal(got[1], stripes[:, 1])
    np.testing.assert_array_equal(got[5], cb_h[:, 5 - k])
