"""Cross-validation against the REFERENCE's own generated mappings.

ADVICE r1 #4: the self-generated corpus pins stability but not upstream
bit-compatibility.  These fixtures close that gap: the reference tree
ships cram tests whose expected outputs were produced by the reference
crushtool itself (src/test/cli/crushtool/*.t) — text crushmaps compiled
and evaluated by the C implementation.  We parse the SAME text maps with
our compiler, evaluate with our mapper, and require every mapping to
match the reference's recorded output byte-for-byte:

- set-choose.t: 36864 mappings — 6 rules (chained choose / chooseleaf /
  set-choose variants) x 2 numreps x 1024 x values x 3 osd-weight
  vectors, over straw(v1) buckets.
- bad-mappings.t / test-map-firstn-indep.t: firstn + indep short-result
  expectations incl. CRUSH_ITEM_NONE padding.

Provenance: expected outputs are read directly from the reference tree
at test time (REF_CLI below), not copied into this repo.
"""
import os
import re

import pytest

from ceph_tpu.crush.compiler import CrushCompiler
from ceph_tpu.crush.mapper import crush_do_rule

REF_CLI = "/root/reference/src/test/cli/crushtool"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_CLI), reason="reference tree not mounted")

_RULE_HDR = re.compile(r"rule (\d+) \(\S+\), x = (\d+)\.\.(\d+), "
                       r"numrep = (\d+)\.\.(\d+)")
_MAPPING = re.compile(r"CRUSH rule (\d+) x (\d+) \[([\d,]*)\]")
_BAD = re.compile(r"bad mapping rule (\d+) x (\d+) num_rep (\d+) "
                  r"result \[([\d,]*)\]")
_WEIGHT = re.compile(r"--weight (\d+) ([.\d]+)")


def _compile_text(path):
    with open(path) as f:
        return CrushCompiler().compile(f.read())


def _parse_runs(t_path):
    """Split a .t into crushtool --test runs: [(weights, expectations)]
    where expectations = list of (rule, numrep, x, result-list)."""
    runs = []
    current = None
    pending = None  # (rule, x_min, x_max, nr_min, nr_max, seen-count)
    with open(t_path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("$ crushtool") and "--test" in line:
                current = {"weights": _WEIGHT.findall(line), "maps": []}
                runs.append(current)
                pending = None
                continue
            if current is None:
                continue
            m = _RULE_HDR.match(line)
            if m:
                pending = tuple(int(g) for g in m.groups())
                nr_min = pending[3]
                current["maps"].append((nr_min, []))
                continue
            m = _MAPPING.match(line)
            if m and pending is not None:
                rule, x = int(m.group(1)), int(m.group(2))
                result = [int(v) for v in m.group(3).split(",")] \
                    if m.group(3) else []
                current["maps"][-1][1].append((rule, x, result))
    return runs


def _weights_vector(weight_args, n_devices):
    w = [0x10000] * n_devices
    for dev, val in weight_args:
        w[int(dev)] = int(float(val) * 0x10000)
    return w


def test_set_choose_mappings_match_reference():
    """Every mapping the reference crushtool recorded for the straw(v1)
    chained-choose map must come out of our compiler+mapper identically."""
    cw = _compile_text(os.path.join(REF_CLI, "set-choose.crushmap.txt"))
    m = cw.crush
    runs = _parse_runs(os.path.join(REF_CLI, "set-choose.t"))
    assert len(runs) == 3
    total = 0
    for run in runs:
        w = _weights_vector(run["weights"], m.max_devices)
        for nr_min, block in run["maps"]:
            # each block covers numrep = nr_min..nr_max in x-order batches
            per_x = {}
            for rule, x, result in block:
                per_x.setdefault((rule, x), []).append(result)
            for (rule, x), results in per_x.items():
                for i, expect in enumerate(results):
                    numrep = nr_min + i
                    got = crush_do_rule(m, rule, x, numrep, w)
                    assert got == expect, (
                        f"rule {rule} x {x} numrep {numrep} w={run['weights']}: "
                        f"{got} != {expect}")
                    total += 1
    assert total == 36864, total


@pytest.mark.parametrize("t_name,map_name", [
    ("bad-mappings.t", "bad-mappings.crushmap.txt"),
    ("test-map-firstn-indep.t", "test-map-firstn-indep.txt"),
])
def test_bad_mappings_match_reference(t_name, map_name):
    """Short-result expectations (firstn truncation, indep NONE holes)
    recorded by the reference crushtool."""
    cw = _compile_text(os.path.join(REF_CLI, map_name))
    m = cw.crush
    w = [0x10000] * m.max_devices
    checked = 0
    with open(os.path.join(REF_CLI, t_name)) as f:
        for line in f:
            mm = _BAD.match(line.strip())
            if not mm:
                continue
            rule, x, numrep = (int(mm.group(i)) for i in range(1, 4))
            expect = [int(v) for v in mm.group(4).split(",")] \
                if mm.group(4) else []
            got = crush_do_rule(m, rule, x, numrep, w)
            assert got == expect, (rule, x, numrep, got, expect)
            checked += 1
    assert checked >= 2, checked


_SET_FLAG = re.compile(r"--set-([a-z-]+) (\d+)")
_FLAG_ATTR = {
    "choose-local-tries": "choose_local_tries",
    "choose-local-fallback-tries": "choose_local_fallback_tries",
    "choose-total-tries": "choose_total_tries",
    "chooseleaf-descend-once": "chooseleaf_descend_once",
    "chooseleaf-vary-r": "chooseleaf_vary_r",
    "chooseleaf-stable": "chooseleaf_stable",
    "straw-calc-version": "straw_calc_version",
}


def _run_binary_fixture(t_name: str, map_name: str, stride: int = 1):
    """Replay a cram fixture that evaluates a BINARY reference crushmap:
    decode it with our codec, apply the command's --set-* tunables and
    --weight vector, and compare every recorded mapping."""
    from ceph_tpu.crush.binfmt import decode_crushmap
    t_path = os.path.join(REF_CLI, t_name)
    total = 0
    m = w = None
    nr_min = 1
    seen: dict = {}
    with open(t_path) as f:
        for raw in f:
            line = raw.strip()
            if line.startswith("$ crushtool") and "--test" in line:
                mm = re.search(r'-i "\$TESTDIR/([^"]+)"', line)
                assert mm and mm.group(1) == map_name, line
                with open(os.path.join(REF_CLI, map_name), "rb") as bf:
                    m = decode_crushmap(bf.read()).crush
                for flag, val in _SET_FLAG.findall(line):
                    setattr(m, _FLAG_ATTR[flag], int(val))
                w = _weights_vector(_WEIGHT.findall(line), m.max_devices)
                continue
            hdr = _RULE_HDR.match(line)
            if hdr:
                nr_min = int(hdr.group(4))
                seen = {}
                continue
            mm = _MAPPING.match(line)
            if mm and m is not None:
                rule, x = int(mm.group(1)), int(mm.group(2))
                # numrep = header minimum + how many sweeps of this x we
                # have already passed (results can be SHORTER than
                # numrep, so len(result) is not a substitute)
                numrep = nr_min + seen.get((rule, x), 0)
                seen[(rule, x)] = seen.get((rule, x), 0) + 1
                if x % stride:
                    continue
                expect = [int(v) for v in mm.group(3).split(",")] \
                    if mm.group(3) else []
                got = crush_do_rule(m, rule, x, numrep, w)
                assert got == expect, (t_name, rule, x, numrep, got,
                                       expect)
                total += 1
    return total


# stride subsamples the recorded x values to bound suite runtime (the
# heavy maps cost ~10-45 ms per exact host evaluation); every file still
# contributes hundreds of cross-checked mappings per run
@pytest.mark.parametrize("t_name,map_name,stride", [
    ("test-map-legacy-tunables.t", "test-map-a.crushmap", 16),
    ("test-map-bobtail-tunables.t", "test-map-a.crushmap", 16),
    ("test-map-firefly-tunables.t", "test-map-vary-r.crushmap", 16),
    ("test-map-hammer-tunables.t",
     "test-map-hammer-tunables.crushmap", 16),
    ("test-map-jewel-tunables.t", "test-map-jewel-tunables.crushmap", 16),
    ("test-map-indep.t", "test-map-indep.crushmap", 16),
    ("test-map-tries-vs-retries.t",
     "test-map-tries-vs-retries.crushmap", 16),
    ("test-map-vary-r-0.t", "test-map-vary-r.crushmap", 16),
    ("test-map-vary-r-1.t", "test-map-vary-r.crushmap", 16),
    ("test-map-vary-r-2.t", "test-map-vary-r.crushmap", 16),
    ("test-map-vary-r-3.t", "test-map-vary-r.crushmap", 16),
    ("test-map-vary-r-4.t", "test-map-vary-r.crushmap", 16),
])
def test_binary_fixture_mappings_match_reference(t_name, map_name, stride):
    """Binary maps produced by the reference crushtool, decoded by our
    codec, must map identically across every tunables profile the
    reference recorded (legacy/bobtail/firefly/hammer/jewel, indep,
    tries-vs-retries, vary-r 0..4)."""
    total = _run_binary_fixture(t_name, map_name, stride)
    assert total > 100, total


def test_set_choose_mappings_on_device_legacy_path():
    """The SAME 36864 recorded reference mappings, evaluated by the
    DEVICE legacy fast path (ops/crush_legacy.py: straw v1 draws, local
    tries, perm fallback, chooseleaf machine) instead of the host
    interpreter — VERDICT r2 #3's reference-golden-on-device criterion."""
    import numpy as np
    from ceph_tpu.ops.crush_legacy import LegacyFastRule

    cw = _compile_text(os.path.join(REF_CLI, "set-choose.crushmap.txt"))
    m = cw.crush
    runs = _parse_runs(os.path.join(REF_CLI, "set-choose.t"))
    assert len(runs) == 3
    # group expectations by (rule, numrep) -> {x: result}
    grouped = {}
    for ri, run in enumerate(runs):
        for nr_min, block in run["maps"]:
            per_x = {}
            for rule, x, result in block:
                per_x.setdefault((rule, x), []).append(result)
            for (rule, x), results in per_x.items():
                for i, expect in enumerate(results):
                    grouped.setdefault((ri, rule, nr_min + i),
                                       {})[x] = expect
    rules = {}
    total = 0
    residuals = []
    for (ri, rule, numrep), per_x in sorted(grouped.items()):
        key = (rule, numrep)
        if key not in rules:
            rules[key] = LegacyFastRule(m, rule, numrep)
        fr = rules[key]
        w = _weights_vector(runs[ri]["weights"], m.max_devices)
        xs = np.asarray(sorted(per_x), dtype=np.uint32)
        out, cnt = fr.map_batch(xs, w)
        residuals.append(fr.residual_fraction)
        for i, x in enumerate(xs):
            got = [int(v) for v in out[i, :cnt[i]]]
            assert got == per_x[int(x)], (
                f"run {ri} rule {rule} numrep {numrep} x {x}: "
                f"{got} != {per_x[int(x)]}")
            total += 1
    assert total == 36864, total
    # the point is DEVICE evaluation: the host replay must be a rare
    # escape hatch, not the engine
    assert max(residuals) < 0.05, residuals


def test_legacy_device_path_with_dead_slots():
    """Heavy-out weight vectors kill whole slots, driving the
    chooseleaf recursion's outpos behind the attempt index — the device
    machine must track the reference exactly."""
    import numpy as np
    from ceph_tpu.crush.mapper import crush_do_rule
    from ceph_tpu.ops.crush_legacy import LegacyFastRule

    cw = _compile_text(os.path.join(REF_CLI, "set-choose.crushmap.txt"))
    m = cw.crush
    xs = np.arange(160, dtype=np.uint32)
    rng = np.random.default_rng(13)
    bad = 0
    for rule in (2, 5):              # the chooseleaf rules
        fr = LegacyFastRule(m, rule, 3)
        for trial in range(4):
            w = [0x10000] * m.max_devices
            for d in rng.choice(m.max_devices, size=7, replace=False):
                w[int(d)] = 0 if trial % 2 else 0x2000
            out, cnt = fr.map_batch(xs, w)
            for x in range(len(xs)):
                exp = crush_do_rule(m, rule, int(x), 3, w)
                if [int(v) for v in out[x, :cnt[x]]] != exp:
                    bad += 1
    assert bad == 0, bad
