"""CephFS directory quotas + file layouts.

The reference enforces dir quotas at the client against the ancestor
quota-realm chain (src/client/Client.cc:4627 handle_quota,
:9137/:11502 is_quota_{bytes,files}_exceeded -> EDQUOT) and fixes a
file's layout (ceph.file.layout.* vxattrs, Client.cc:11645) from the
nearest ancestor dir layout at create.  Lite split: file-count
quotas gate dentry creation at the metadata authority, byte quotas
gate the client's data path using the realm chain cached at open.
"""
import pytest

from ceph_tpu.cephfs import FsError
from ceph_tpu.cephfs.cls_fs import file_oid
from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.mds import MDSDaemon

EDQUOT = -122


@pytest.fixture()
def world():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    c.create_replicated_pool("fastpool", size=3, pg_num=8)
    mds = MDSDaemon(c.network, c.client("client.mds"), "mds.0",
                    mkfs=True)
    fs = RemoteCephFS(c.client("client.a"))
    fs._drive = lambda: mds.process()
    return c, mds, fs


def test_max_files_quota_edquot(world):
    c, mds, fs = world
    fs.mkdir("/proj")
    fs.set_quota("/proj", max_files=3)
    fs.create("/proj/a")
    fs.mkdir("/proj/sub")                 # dirs count too (rsubdirs)
    fs.create("/proj/sub/b")              # 3rd dentry in the realm
    with pytest.raises(FsError) as e:
        fs.create("/proj/c")
    assert e.value.result == EDQUOT
    with pytest.raises(FsError) as e:
        fs.mkdir("/proj/sub/d")           # nested path, same realm
    assert e.value.result == EDQUOT
    # outside the realm is unaffected
    fs.create("/free")
    # deleting frees the slot
    fs.unlink("/proj/a")
    fs.create("/proj/c")
    # hardlinks consume a dentry too
    with pytest.raises(FsError) as e:
        fs.hardlink("/proj/c", "/proj/link")
    assert e.value.result == EDQUOT


def test_max_bytes_quota_on_data_path(world):
    c, mds, fs = world
    fs.mkdir("/cap")
    fs.set_quota("/cap", max_bytes=100)
    fh = fs.open("/cap/f", "w")
    fh.write(b"x" * 60, 0)                # under quota, buffered
    with pytest.raises(FsError) as e:
        fh.write(b"y" * 60, 60)           # 120 > 100
    assert e.value.result == EDQUOT
    fh.close()
    # write-through path enforces too
    with pytest.raises(FsError) as e:
        fs.write("/cap/g", b"z" * 200, 0)
    assert e.value.result == EDQUOT
    # and the failed write-through did not leak caps: a fresh open
    # of the same file proceeds without a revoke stall
    fs.write("/cap/g", b"ok", 0)
    assert fs.read("/cap/g") == b"ok"
    # truncate growth through the MDS is gated as well
    with pytest.raises(FsError) as e:
        fs.truncate("/cap/f", 500)
    assert e.value.result == EDQUOT


def test_ancestor_chain_outer_quota_wins(world):
    c, mds, fs = world
    fs.mkdir("/outer")
    fs.mkdir("/outer/inner")
    fs.set_quota("/outer", max_bytes=50)
    fs.set_quota("/outer/inner", max_bytes=1000)   # laxer inside
    with pytest.raises(FsError) as e:
        fs.write("/outer/inner/f", b"b" * 200, 0)
    assert e.value.result == EDQUOT


def test_quota_survives_mds_failover(world):
    """Quotas are journaled metadata: a replacement MDS incarnation
    keeps enforcing them (the VERDICT's failover criterion)."""
    c, mds, fs = world
    fs.mkdir("/q")
    fs.set_quota("/q", max_files=1)
    fs.create("/q/only")
    mds2 = MDSDaemon(c.network, c.client("client.mds2"), "mds.0")
    fs2 = RemoteCephFS(c.client("client.b"))
    fs2._drive = lambda: mds2.process()
    with pytest.raises(FsError) as e:
        fs2.create("/q/two")
    assert e.value.result == EDQUOT
    assert fs2.get_quota("/q")[0]["max_files"] == 1
    # clearing re-opens the gate
    fs2.set_quota("/q", max_files=0)
    fs2.create("/q/two")


def test_open_create_and_rename_ride_quota(world):
    """The two creation paths the review flagged: O_CREAT via
    open('w') and rename-into-realm both hit the max_files gate."""
    c, mds, fs = world
    fs.mkdir("/q")
    fs.set_quota("/q", max_files=1)
    fs.create("/q/only")
    with pytest.raises(FsError) as e:
        fs.open("/q/second", "w")             # O_CREAT path
    assert e.value.result == EDQUOT
    fs.create("/outside")
    with pytest.raises(FsError) as e:
        fs.rename("/outside", "/q/in")        # absorb-into-realm
    assert e.value.result == EDQUOT
    # byte-quota absorbs a moved subtree too
    fs.mkdir("/b")
    fs.set_quota("/b", max_bytes=50)
    fs.mkdir("/big")
    fs.write("/big/payload", b"m" * 200, 0)
    with pytest.raises(FsError) as e:
        fs.rename("/big", "/b/big")
    assert e.value.result == EDQUOT
    # a rename WITHIN one realm is not double-counted
    fs.write("/b/f", b"n" * 40, 0)
    fs.rename("/b/f", "/b/g")


def test_dir_layout_fields_merge(world):
    c, mds, fs = world
    fs.mkdir("/m")
    fs.set_layout("/m", order=16)
    fs.set_layout("/m", pool="fastpool")      # must keep order=16
    assert fs.get_layout("/m") == {"order": 16, "pool": "fastpool"}


def test_layout_inheritance_and_pool_placement(world):
    """ceph.dir.layout fixes new files' object size AND data pool;
    bytes actually land in the layout pool."""
    c, mds, fs = world
    fs.mkdir("/fast")
    fs.set_layout("/fast", order=12, pool="fastpool")
    assert fs.get_layout("/fast") == {"order": 12, "pool": "fastpool"}
    ino = fs.create("/fast/f")
    assert fs.get_layout("/fast/f") == {"order": 12,
                                        "pool": "fastpool"}
    payload = bytes(range(256)) * 24          # 6 KiB -> 2 objs @4KiB
    fs.write("/fast/f", payload, 0)
    assert fs.read("/fast/f") == payload
    cl = c.client("client.check")
    # the objects live in fastpool (order 12 -> 4 KiB stripes), and
    # NOT in the default data pool
    assert len(cl.read("fastpool", file_oid(ino, 0))) == 4096
    assert len(cl.read("fastpool", file_oid(ino, 1))) == 2048
    with pytest.raises(IOError):
        cl.read("fsdata", file_oid(ino, 0))
    # files created elsewhere keep the default layout
    fs.create("/plain")
    assert fs.get_layout("/plain")["pool"] is None


def test_file_layout_only_while_empty(world):
    c, mds, fs = world
    fs.create("/empty")
    fs.set_layout("/empty", order=13)          # empty: allowed
    assert fs.get_layout("/empty")["order"] == 13
    fs.write("/data", b"bytes", 0)
    with pytest.raises(FsError) as e:
        fs.set_layout("/data", order=13)       # has data: EINVAL
    assert e.value.result == -22
