"""Device GF(2^8) bit-matmul kernels vs the host oracle — byte parity.

Runs on the virtual CPU mesh in tests; the same code path runs on TPU.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import plugin_registry
from ceph_tpu.ec.rs_codec import MatrixRSCodec
from ceph_tpu.gf.matrices import gf_gen_rs_matrix, gf_gen_cauchy1_matrix
from ceph_tpu.ops.gf_matmul import DeviceRSBackend


@pytest.mark.parametrize("k,m,gen", [
    (4, 2, gf_gen_rs_matrix),
    (8, 4, gf_gen_rs_matrix),
    (6, 3, gf_gen_cauchy1_matrix),
])
def test_device_encode_matches_host(k, m, gen):
    matrix = gen(k + m, k)
    host = MatrixRSCodec(matrix)
    dev = DeviceRSBackend(matrix)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(5, k, 256), dtype=np.uint8)
    got = dev.encode(data)
    assert got.shape == (5, m, 256)
    for s in range(5):
        want = host.encode(data[s])
        np.testing.assert_array_equal(got[s], want)


def test_device_decode_matches_host():
    k, m = 8, 4
    matrix = gf_gen_rs_matrix(k + m, k)
    host = MatrixRSCodec(matrix)
    dev = DeviceRSBackend(matrix)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(3, k, 128), dtype=np.uint8)
    coding = dev.encode(data)
    full = np.concatenate([data, coding], axis=1)  # (S, k+m, C)
    for gone in itertools.combinations(range(k + m), 2):
        srcs = sorted(set(range(k + m)) - set(gone))[:k]
        survivors = full[:, srcs, :]
        want_rows = [i for i in gone if i < k]
        if not want_rows:
            continue
        rec = dev.decode_data(survivors, srcs, want_rows)
        for s in range(3):
            chunks = {i: full[s, i] for i in srcs}
            out = host.decode(chunks, want_rows)
            for idx, i in enumerate(want_rows):
                np.testing.assert_array_equal(rec[s, idx], out[i])


def test_tpu_plugin_single_stripe_parity():
    """ErasureCodeTpu chunks == isa host chunks, byte-identical."""
    prof = {"k": "4", "m": "2"}
    host = plugin_registry.factory("isa", {**prof, "backend": "host"})
    tpu = plugin_registry.factory("tpu", prof)
    rng = np.random.default_rng(2)
    payload = rng.integers(0, 256, size=4096, dtype=np.uint8).tobytes()
    want = set(range(6))
    enc_h = host.encode(want, payload)
    enc_t = tpu.encode(want, payload)
    for i in want:
        np.testing.assert_array_equal(enc_h[i], enc_t[i])


def test_tpu_plugin_batch_roundtrip():
    tpu = plugin_registry.factory("tpu", {"k": "8", "m": "4"})
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(16, 8, 512), dtype=np.uint8)
    coding = tpu.encode_batch(data)
    assert coding.shape == (16, 4, 512)
    # erase shards 1 and 9 (one data, one coding) across the whole batch
    chunks = {i: (data[:, i] if i < 8 else coding[:, i - 8])
              for i in range(12) if i not in (1, 9)}
    out = tpu.decode_batch(chunks, [1, 9])
    np.testing.assert_array_equal(out[1], data[:, 1])
    np.testing.assert_array_equal(out[9], coding[:, 1])


def test_tpu_plugin_batch_coding_only_recovery():
    # all data chunks survive; only a coding shard is lost (the most common
    # repair) — regression for the skipped-reencode bug
    tpu = plugin_registry.factory("tpu", {"k": "3", "m": "2"})
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, size=(4, 3, 64), dtype=np.uint8)
    coding = tpu.encode_batch(data)
    chunks = {i: data[:, i] for i in range(3)}
    chunks[4] = coding[:, 1]
    out = tpu.decode_batch(chunks, [3])
    np.testing.assert_array_equal(out[3], coding[:, 0])


def test_pallas_kernel_parity_with_xla_path():
    """ops/gf_pallas.py (fused unpack->MXU->pack in VMEM) must be
    byte-identical to the XLA dot_general path.  The A/B on hardware
    measured the XLA path ~3x faster (2754 vs 920 GiB/s at k=8,m=4,
    1 MiB chunks), so XLA remains the default executor; the kernel is
    kept as the measured alternative."""
    import numpy as np
    import jax.numpy as jnp
    from ceph_tpu.ops.gf_matmul import gf_bit_matmul
    from ceph_tpu.ops.gf_pallas import gf_bit_matmul_pallas, \
        pallas_supported
    from ceph_tpu.gf.matrices import gf_gen_rs_matrix
    from ceph_tpu.gf.tables import expand_to_bitmatrix

    rng = np.random.default_rng(9)
    for (s, k, m, c) in [(4, 8, 4, 512), (1, 4, 2, 128), (3, 6, 3, 1152)]:
        assert pallas_supported(c)
        data = jnp.asarray(rng.integers(0, 256, (s, k, c), dtype=np.uint8))
        mat = gf_gen_rs_matrix(k + m, k)
        bits = jnp.asarray(expand_to_bitmatrix(mat[k:]).astype(np.int8))
        a = np.asarray(gf_bit_matmul(data, bits))
        b = np.asarray(gf_bit_matmul_pallas(data, bits))
        np.testing.assert_array_equal(a, b, err_msg=str((s, k, m, c)))
    assert not pallas_supported(96)  # below the minimum tile
