"""PG splitting: pg_num growth, local splits, pgp_num migration.

The reference splits PGs when pg_num grows (OSD::split_pgs,
PG::split_into, PGLog::split_into): ceph_stable_mod keeps a parent's ps
stable while objects whose hash lands in a child ps move to it, and —
with pgp_num unchanged — children colocate with their parents (pps uses
pgp_num), so the split is purely local.  Raising pgp_num afterwards
migrates children through ordinary peering/backfill.  These tests
verify object placement matches the map after splits, data survives
end-to-end (replicated + EC + snapshots), writes work post-split, a
restarted OSD catches up on a split it slept through, and pgp_num
migration converges.
"""
from __future__ import annotations

import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osdmap import pg_t

NONE = 0x7FFFFFFF


def _settle(c, rounds=8):
    for _ in range(rounds):
        c.network.pump()
        c.run_recovery()


def _split(c, pool_name, pg_num):
    c.mon.set_pool_pg_num(pool_name, pg_num)
    c.publish()
    _settle(c)


def _objects(rng, n, tag):
    return {f"{tag}{i}": rng.integers(0, 256, 2000 + 37 * i,
                                      dtype=np.uint8).tobytes()
            for i in range(n)}


def test_replicated_split_moves_objects_and_keeps_data():
    c = MiniCluster(n_osds=6)
    c.create_replicated_pool("p", size=3, pg_num=8)
    cl = c.client()
    rng = np.random.default_rng(5)
    blobs = _objects(rng, 40, "o")
    for oid, data in blobs.items():
        assert cl.write_full("p", oid, data) == 0
    pid = c.mon.osdmap.lookup_pg_pool_name("p")
    _split(c, "p", 16)
    pool = c.mon.osdmap.pools[pid]
    assert pool.pg_num == 16 and pool.pgp_num == 8
    # every object readable, and stored under its NEW pg on every OSD
    moved = 0
    for oid, data in blobs.items():
        assert cl.read("p", oid) == data
        ps = pool.raw_pg_to_pg(c.mon.osdmap.map_to_pg(pid, oid)).ps
        if ps >= 8:
            moved += 1
        for osd in c.osds.values():
            for cps in range(16):
                cid = f"{pid}.{cps}"
                if not osd.store.collection_exists(cid):
                    continue
                held = [h.oid for h in osd.store.list_objects(cid)
                        if h.oid == oid]
                if held:
                    assert cps == ps, \
                        f"{oid} in pg {cps}, belongs in {ps}"
    assert moved > 0, "hash never landed in a child (bad test seed)"
    # post-split writes and overwrites land in the children
    blobs2 = _objects(rng, 20, "n")
    for oid, data in blobs2.items():
        assert cl.write_full("p", oid, data) == 0
        assert cl.read("p", oid) == data
    some = next(iter(blobs))
    assert cl.write_full("p", some, b"rewritten") == 0
    assert cl.read("p", some) == b"rewritten"


def test_ec_split_shards_and_recovery():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("e", k=2, m=1, plugin="isa", pg_num=4,
                     failure_domain="osd")
    cl = c.client()
    rng = np.random.default_rng(9)
    blobs = _objects(rng, 30, "x")
    for oid, data in blobs.items():
        assert cl.write_full("e", oid, data) == 0
    _split(c, "e", 8)
    for oid, data in blobs.items():
        assert cl.read("e", oid) == data
    # degraded read + recovery still work on split children
    pid = c.mon.osdmap.lookup_pg_pool_name("e")
    pool = c.mon.osdmap.pools[pid]
    oid = next(o for o in blobs
               if pool.raw_pg_to_pg(
                   c.mon.osdmap.map_to_pg(pid, o)).ps >= 4)
    pg = pool.raw_pg_to_pg(c.mon.osdmap.map_to_pg(pid, oid))
    *_, acting, primary = c.mon.osdmap.pg_to_up_acting_osds(pg)
    victim = next(o for o in acting if o != primary and o != NONE)
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    assert cl.read("e", oid) == blobs[oid]      # degraded read
    c.mark_osd_out(victim)                      # re-place + backfill
    _settle(c, rounds=12)
    data2 = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    assert cl.write_full("e", oid, data2) == 0
    assert cl.read("e", oid) == data2


def test_split_preserves_snapshots_and_clones():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client()
    rng = np.random.default_rng(3)
    blobs = _objects(rng, 16, "s")
    for oid, data in blobs.items():
        assert cl.write_full("p", oid, data) == 0
    c.pool_snap_create("p", "snap1")
    new = {oid: rng.integers(0, 256, 1500, dtype=np.uint8).tobytes()
           for oid in blobs}
    for oid, data in new.items():
        assert cl.write_full("p", oid, data) == 0
    _split(c, "p", 8)
    for oid in blobs:
        assert cl.read("p", oid) == new[oid]
        assert cl.read("p", oid, snap="snap1") == blobs[oid], \
            f"snap read of {oid} lost across split"


def test_restarted_osd_catches_up_on_missed_split():
    """An OSD down across the split epoch must split its local layout
    on restart (the persisted per-PG pg_num attr)."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client()
    rng = np.random.default_rng(8)
    blobs = _objects(rng, 24, "r")
    for oid, data in blobs.items():
        assert cl.write_full("p", oid, data) == 0
    victim = 0
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    _split(c, "p", 8)
    for oid, data in blobs.items():       # degraded, split reads fine
        assert cl.read("p", oid) == data
    c.restart_osd(victim)
    _settle(c, rounds=12)
    pid = c.mon.osdmap.lookup_pg_pool_name("p")
    pool = c.mon.osdmap.pools[pid]
    osd = c.osds[victim]
    # the restarted OSD's layout reflects the new pg_num: no object
    # sits in a parent collection that belongs to a child
    for oid in blobs:
        ps = pool.raw_pg_to_pg(c.mon.osdmap.map_to_pg(pid, oid)).ps
        for cps in range(8):
            cid = f"{pid}.{cps}"
            if osd.store.collection_exists(cid) and any(
                    h.oid == oid for h in osd.store.list_objects(cid)):
                assert cps == ps, \
                    f"osd.{victim}: {oid} in {cps}, belongs in {ps}"
    for oid, data in blobs.items():
        assert cl.read("p", oid) == data


def test_pgp_num_increase_migrates_children():
    """Phase 2: raising pgp_num gives children their own CRUSH
    placement; the realignment machinery moves the data and reads keep
    working from the new acting sets."""
    c = MiniCluster(n_osds=6)
    c.create_replicated_pool("p", size=3, pg_num=8)
    cl = c.client()
    rng = np.random.default_rng(4)
    blobs = _objects(rng, 30, "m")
    for oid, data in blobs.items():
        assert cl.write_full("p", oid, data) == 0
    _split(c, "p", 16)
    pid = c.mon.osdmap.lookup_pg_pool_name("p")
    before = {ps: c.mon.osdmap.pg_to_up_acting_osds(pg_t(pid, ps))[2]
              for ps in range(16)}
    c.mon.set_pool_pgp_num("p", 16)
    c.publish()
    for _ in range(10):
        c.tick(dt=1.0)
        _settle(c, rounds=4)
    after = {ps: c.mon.osdmap.pg_to_up_acting_osds(pg_t(pid, ps))[2]
             for ps in range(16)}
    assert any(before[ps] != after[ps] for ps in range(8, 16)), \
        "pgp_num increase moved no child placements"
    for oid, data in blobs.items():
        assert cl.read("p", oid) == data
    for oid in list(blobs)[:8]:
        assert cl.write_full("p", oid, b"post-migrate") == 0
        assert cl.read("p", oid) == b"post-migrate"


def test_ec_pgp_migration_to_disjoint_acting_converges():
    """The hard case: pgp_num growth can hand an EC child PG an acting
    set sharing NO member with the data holders.  The mon's pg_temp
    priming keeps the old members serving, realign pushes each shard
    (with its version) to the new up members and waits for acks, and
    the recovery probe clears debts the log-delta can't see — without
    any one of those, this wedges with reads returning EIO forever."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("e", k=2, m=1, plugin="isa", pg_num=4,
                     failure_domain="osd")
    cl = c.client()
    rng = np.random.default_rng(42)
    blobs = {f"o{i}": rng.integers(0, 256, 4096,
                                   dtype=np.uint8).tobytes()
             for i in range(10)}
    for oid, d in blobs.items():
        assert cl.write_full("e", oid, d) == 0
    _split(c, "e", 16)
    c.mon.set_pool_pgp_num("e", 16)
    c.publish()
    for _ in range(12):
        c.tick(dt=1.0)
        _settle(c, rounds=3)
    assert not c.mon.osdmap.pg_temp, \
        f"pins never cleared: {dict(c.mon.osdmap.pg_temp)}"
    for oid, d in blobs.items():
        assert cl.read("e", oid) == d
    for oid in list(blobs)[:4]:
        assert cl.write_full("e", oid, b"after-migration") == 0
        assert cl.read("e", oid) == b"after-migration"


def test_mon_guards():
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("p", size=2, pg_num=8)
    with pytest.raises(ValueError):
        c.mon.set_pool_pg_num("p", 4)          # no merging
    with pytest.raises(ValueError):
        c.mon.set_pool_pgp_num("p", 16)        # pgp > pg
    with pytest.raises(KeyError):
        c.mon.set_pool_pg_num("nope", 16)
