"""Mon-store disaster recovery (src/tools/rebuild_mondb.cc role):
every OSD persists each applied osdmap incremental in its meta
collection, so a LOST mon store is reconstructed from the union of
the surviving OSDs' histories — and the restored cluster still
serves the data."""
import os

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.tools.rebuild_mondb import collect_incrementals, main


def _build(tmp_path, n_osds=4):
    c = MiniCluster(n_osds=n_osds)
    c.create_replicated_pool("p", pg_num=8)
    cl = c.client("client.x")
    cl.write_full("p", "obj", b"survives the mon-store loss")
    c.mark_osd_out(3)
    d = str(tmp_path / "ck")
    c.checkpoint(d)
    return c, d


def test_osds_persist_map_history(tmp_path):
    c, d = _build(tmp_path)
    incs = collect_incrementals(d)
    assert sorted(incs) == list(range(1, c.mon.osdmap.epoch + 1))
    # pool creation and the out-marking are both in the history
    assert any(i.get("new_pools") for i in incs.values())
    assert any(i.get("new_weight") for i in incs.values())


def test_rebuild_restores_cluster_and_data(tmp_path):
    c, d = _build(tmp_path)
    epoch = c.mon.osdmap.epoch
    os.unlink(os.path.join(d, "mon.json"))       # the disaster
    assert main([d]) == 0
    c2 = MiniCluster.restore(d)
    assert c2.mon.osdmap.epoch == epoch
    assert not c2.mon.osdmap.is_in(3)
    assert "p" in c2.mon.osdmap.pool_name.values()
    got = c2.client("client.y").read("p", "obj")
    assert bytes(got) == b"survives the mon-store loss"
    # the rebuilt cluster keeps working: new writes land
    c2.client("client.y").write_full("p", "obj2", b"post-DR write")
    assert bytes(c2.client("client.y").read("p", "obj2")) == \
        b"post-DR write"


def test_union_across_osds(tmp_path):
    """A single OSD's history can have holes (it was down for an
    epoch); the union across OSDs still reconstructs everything."""
    from ceph_tpu.os_store.memstore import MemStore, Transaction
    c, d = _build(tmp_path)
    # damage osd.0's history: drop one epoch from ITS meta collection
    path = os.path.join(d, "osd.0.store")
    store = MemStore.load(path)
    metas = [ho for ho in store.list_objects("meta")]
    t = Transaction()
    t.remove("meta", metas[0])
    store.queue_transaction(t)
    store.save(path)
    os.unlink(os.path.join(d, "mon.json"))
    assert main([d]) == 0                # other osds fill the hole
    c2 = MiniCluster.restore(d)
    assert bytes(c2.client("client.y").read("p", "obj")) == \
        b"survives the mon-store loss"


def test_error_contracts(tmp_path):
    c, d = _build(tmp_path)
    # refuses to clobber an existing store without --force
    assert main([d]) == 1
    assert main([d, "--force"]) == 0
    # custom mon roster lands in the rebuilt monmap
    os.unlink(os.path.join(d, "mon.json"))
    assert main([d, "--mon", "alpha=127.0.0.1:6800"]) == 0
    from ceph_tpu.tools.monstore_tool import MonStore
    st = MonStore(d)
    assert [n for n, _ in st.monmap().ranks()] == ["alpha"]
    assert main([str(tmp_path / "empty")]) == 1
    assert main([]) == 1
    assert main([d, "--bogus"]) == 1
