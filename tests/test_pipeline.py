"""Async EC write pipeline: non-blocking dispatch futures with a
per-PG in-flight window (the perf_opt PR's acceptance gates).

- byte-identity: a cluster running depth-8 pipelined writes stores
  shard bodies byte-identical to a depth-1 (synchronous) twin across
  randomized (k, m, technique, size) mixes, single submitter thread;
- per-oid ordering: a later write to the same oid never overtakes an
  earlier one, pipelined or not;
- backpressure: the window never exceeds ec_pipeline_depth — a full
  window force-flushes inline instead of parking the submitter;
- continuation-path fault injection: a device error surfacing inside
  the batched encode still trips the breaker / CPU fallback and the
  client op completes;
- peering: a continuation resolving after on_change drops its fan-out
  (no writes into a dead acting set);
- regression guard: with depth > 1 no blocking ``result()`` runs on
  the EC write path — completion is continuation-driven end to end.
"""
import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import DispatchFuture, g_dispatcher
from ceph_tpu.osd.ec_backend import (
    l_pipeline_backpressure, l_pipeline_stale_drops,
    l_pipeline_submitted, pipeline_perf_counters,
)


@pytest.fixture
def pipeline_conf():
    """Every test leaves the dispatcher drained and the pipeline/
    dispatch options at their defaults."""
    yield
    g_dispatcher.flush()
    for name in ("ec_pipeline_depth", "ec_dispatch_batch_max",
                 "ec_dispatch_batch_window_us", "ec_dispatch_queue_max",
                 "ec_subwrite_retry_timeout", "ec_subwrite_retry_max"):
        g_conf.rm_val(name)


def _pipe_on(depth=8, batch_max=64):
    g_conf.set_val("ec_pipeline_depth", depth)
    g_conf.set_val("ec_dispatch_batch_window_us", 200_000)
    g_conf.set_val("ec_dispatch_batch_max", batch_max)


def _pipe_off():
    for name in ("ec_pipeline_depth", "ec_dispatch_batch_window_us",
                 "ec_dispatch_batch_max"):
        g_conf.rm_val(name)


# the randomized pool mix: (pool name, plugin, k, m, technique)
POOLS = [
    ("pp_tpu32", "tpu", 3, 2, "reed_sol_van"),
    ("pp_isa42", "isa", 4, 2, "reed_sol_van"),
    ("pp_isa32c", "isa", 3, 2, "cauchy"),
]


def _boot_pools():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    for name, plugin, k, m, technique in POOLS:
        c.create_ec_pool(name, k=k, m=m, plugin=plugin, pg_num=4,
                         extra_profile={"technique": technique})
    return c, c.client("client.pipe")


def _run_workload(c, cl, rng):
    """Single-thread randomized write/overwrite/append mix; returns
    {(pool, oid): expected bytes}."""
    expected = {}
    for name, _p, k, _m, _t in POOLS:
        for i in range(4):
            oid = f"o{i}"
            body = bytes(rng.integers(0, 256, 1000 + 977 * i * k,
                                      dtype=np.uint8))
            assert cl.write_full(name, oid, body) == 0, (name, oid)
            expected[(name, oid)] = body
        # overwrite + rmw splice + append ride the same pipeline
        body = bytes(rng.integers(0, 256, 5000, dtype=np.uint8))
        assert cl.write_full(name, "o0", body) == 0
        expected[(name, "o0")] = body
        patch = bytes(rng.integers(0, 256, 800, dtype=np.uint8))
        assert cl.write(name, "o1", patch, 300) == 0
        b = bytearray(expected[(name, "o1")])
        b[300:300 + len(patch)] = patch
        expected[(name, "o1")] = bytes(b)
        tail = bytes(rng.integers(0, 256, 700, dtype=np.uint8))
        assert cl.append(name, "o2", tail) == 0
        expected[(name, "o2")] = expected[(name, "o2")] + tail
    return expected


def _ec_shard_bodies(c):
    """(osd, cid, oid) -> stored shard bytes for every EC collection."""
    out = {}
    for i, osd in c.osds.items():
        for cid in osd.store.list_collections():
            if "_meta" in cid or "s" not in cid.split(".")[-1]:
                continue
            for ho in osd.store.list_objects(cid):
                out[(i, cid, str(ho))] = osd.store.read(cid, ho)
    return out


def _read_via_backend(c, pg, oid):
    """Whole-object fetch through the owning PG's backend (direct
    backend submits bypass CRUSH placement, so client reads would
    route to a different PG)."""
    out = {}

    def done(res, body, _size, _attrs):
        out["res"], out["body"] = res, body

    pg.backend.object_state(oid, done)
    c.network.pump()
    assert out["res"] == 0, (oid, out)
    return out["body"]


def test_pipelined_writes_byte_identical_to_depth1(pipeline_conf):
    """The tentpole gate: the SAME single-thread workload on a depth-8
    pipelined cluster and a depth-1 synchronous twin ends with every
    object readable byte-exact AND every stored EC shard body
    byte-identical — the continuation conversion may change when
    encodes run, never what they produce."""
    results = {}
    for label, piped in (("sync", False), ("piped", True)):
        if piped:
            _pipe_on(depth=8)
        else:
            _pipe_off()
        c, cl = _boot_pools()
        expected = _run_workload(c, cl, np.random.default_rng(41))
        for (name, oid), body in expected.items():
            assert cl.read(name, oid) == body, (label, name, oid)
        results[label] = (expected, _ec_shard_bodies(c))
        g_dispatcher.flush()
    exp_s, shards_s = results["sync"]
    exp_p, shards_p = results["piped"]
    assert exp_s == exp_p
    assert set(shards_s) == set(shards_p)
    diff = [k for k in shards_s if shards_s[k] != shards_p[k]]
    assert not diff, f"shard bodies diverged: {diff[:5]}"
    # the pipelined leg actually used the async path
    assert pipeline_perf_counters().get(l_pipeline_submitted) > 0


def test_per_oid_ordering_under_interleaved_writes(pipeline_conf):
    """A later write to the same oid must not overtake an earlier one:
    submit A1, B1, A2 without pumping (all three encodes pipelined),
    then drain — completions observe A1 < A2 and the final body is
    A2's."""
    _pipe_on(depth=8)
    c, cl = _boot_pools()
    name = POOLS[0][0]
    assert cl.write_full(name, "ord", b"seed" * 300) == 0
    pid = cl.lookup_pool(name)
    pgid, primary = cl._calc_target(pid, "ord")
    pg = c.osds[primary].pgs[pgid]
    order = []
    a1 = b"1" * 2400
    b1 = b"b" * 1200
    a2 = b"2" * 3000
    pg.backend.submit_transaction("ord", a1,
                                  lambda r: order.append(("a1", r)))
    pg.backend.submit_transaction("other", b1,
                                  lambda r: order.append(("b1", r)))
    pg.backend.submit_transaction("ord", a2,
                                  lambda r: order.append(("a2", r)))
    # nothing completed yet: submission was non-blocking
    assert [o for o, _r in order] == []
    c.network.pump()
    assert ("a1", 0) in order and ("a2", 0) in order
    assert order.index(("a1", 0)) < order.index(("a2", 0)), order
    assert cl.read(name, "ord") == a2
    assert _read_via_backend(c, pg, "other") == b1


def test_window_backpressure_bounds_inflight(pipeline_conf):
    """The per-PG window never exceeds ec_pipeline_depth: the submit
    that would overflow force-flushes the scheduler inline (counter
    moves, earlier continuations run) and the high-water mark stays at
    the configured depth."""
    _pipe_on(depth=2, batch_max=64)     # batch_max never triggers
    c, cl = _boot_pools()
    name = POOLS[0][0]
    pid = cl.lookup_pool(name)
    pgid, primary = cl._calc_target(pid, "w0")
    pg = c.osds[primary].pgs[pgid]
    be = pg.backend
    pc = pipeline_perf_counters()
    bp0 = pc.get(l_pipeline_backpressure)
    high = [0]
    done = []
    for i in range(6):
        be.submit_transaction(f"bp{i}", bytes([i]) * 1500,
                              lambda r, i=i: done.append((i, r)))
        high[0] = max(high[0], be.pipeline_inflight)
    assert high[0] <= 2, f"window exceeded depth: {high[0]}"
    assert pc.get(l_pipeline_backpressure) > bp0
    c.network.pump()
    assert sorted(i for i, r in done if r == 0) == list(range(6))
    for i in range(6):
        assert _read_via_backend(c, pg, f"bp{i}") == bytes([i]) * 1500


def test_continuation_device_error_trips_breaker_and_completes(
        pipeline_conf):
    """Fault injection on the continuation path: a device error inside
    the batched encode (resolved via add_done_callback, not result())
    must retry/trip exactly like the synchronous path — the op
    completes from the byte-identical CPU twin and the client never
    sees the failure."""
    from ceph_tpu.fault import (fault_perf_counters, g_breakers,
                                g_faults)
    from ceph_tpu.fault.registry import l_fault_cpu_fallbacks
    _pipe_on(depth=4)
    g_conf.set_val("ec_device_retry_backoff_us", 0)
    g_conf.set_val("ec_breaker_threshold", 2)
    try:
        c, cl = _boot_pools()
        name = POOLS[0][0]
        pc = fault_perf_counters()
        fb0 = pc.get(l_fault_cpu_fallbacks)
        g_faults.inject("device.encode_batch", mode="always")
        body = b"f" * 9000
        assert cl.write_full(name, "faulty", body) == 0
        g_faults.clear()
        assert cl.read(name, "faulty") == body
        assert pc.get(l_fault_cpu_fallbacks) > fb0, \
            "continuation-path device error did not reach the CPU twin"
        assert g_breakers.degraded(), "breaker never tripped"
    finally:
        g_faults.clear()
        g_breakers.reset()
        for opt in ("ec_device_retry_backoff_us",
                    "ec_breaker_threshold"):
            g_conf.rm_val(opt)


def test_stale_continuation_dropped_after_on_change(pipeline_conf):
    """A continuation resolving AFTER peering's on_change must not fan
    out sub-writes into the dead interval: the encode completes as a
    no-op and the stale-drop counter records it."""
    _pipe_on(depth=8)
    c, cl = _boot_pools()
    name = POOLS[0][0]
    pid = cl.lookup_pool(name)
    pgid, primary = cl._calc_target(pid, "stale")
    pg = c.osds[primary].pgs[pgid]
    be = pg.backend
    pc = pipeline_perf_counters()
    sd0 = pc.get(l_pipeline_stale_drops)
    replied = []
    be.submit_transaction("stale", b"s" * 2000, replied.append)
    assert be.pipeline_inflight == 1
    be.on_change()                      # interval change mid-encode
    q0 = len(c.network.queue)
    g_dispatcher.flush()                # encode resolves now
    assert pc.get(l_pipeline_stale_drops) == sd0 + 1
    assert be.pipeline_inflight == 0
    assert len(c.network.queue) == q0, \
        "stale continuation fanned out sub-writes"
    assert replied == []                # client resends via Objecter


def test_no_blocking_result_on_pipelined_write_path(pipeline_conf,
                                                    monkeypatch):
    """Regression guard (CI satellite): with ec_pipeline_depth > 1 the
    OSD op-thread EC write path must never block on a dispatch
    future's result() — every result() during a pure-write workload
    must find the future already resolved (continuation-driven
    completion).  The guard itself is proven live by a queued future
    tripping it."""
    calls = {"blocking": 0}
    orig = DispatchFuture.result

    def guarded(self, timeout=None):
        if not self.done():
            calls["blocking"] += 1
            raise AssertionError(
                "blocking result() on the pipelined write path")
        return orig(self, timeout)

    _pipe_on(depth=8)
    c, cl = _boot_pools()
    monkeypatch.setattr(DispatchFuture, "result", guarded)
    for i in range(6):
        body = bytes([65 + i]) * (2000 + 500 * i)
        assert cl.write_full(POOLS[0][0], f"nb{i}", body) == 0
    monkeypatch.setattr(DispatchFuture, "result", orig)
    assert calls["blocking"] == 0
    for i in range(6):
        assert cl.read(POOLS[0][0], f"nb{i}") \
            == bytes([65 + i]) * (2000 + 500 * i)
    # negative control: the guard DOES fire on a genuinely queued
    # future, so the zero count above is meaningful
    monkeypatch.setattr(DispatchFuture, "result", guarded)
    from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
    from ceph_tpu.osd.ecutil import stripe_info_t
    impl = ErasureCodeTpu()
    impl.init({"k": "2", "m": "1", "technique": "reed_sol_van"})
    fut = g_dispatcher.submit_encode(
        stripe_info_t(2, 2048), impl,
        np.zeros(2048, dtype=np.uint8), {0, 1, 2})
    if not fut.done():                  # queued in the window
        with pytest.raises(AssertionError):
            guarded(fut)
    monkeypatch.setattr(DispatchFuture, "result", orig)
    g_dispatcher.flush()


def test_pipelined_writes_with_threaded_op_queue(pipeline_conf):
    """With a real op thread-pool the continuation must not mutate PG
    state on the flusher's thread (it may hold another PG's op_lock):
    delivery re-enters through the sharded op queue and runs under
    pg.op_lock.  Concurrent-ish writes across PGs stay byte-exact."""
    g_conf.set_val("osd_op_num_threads", 2)
    try:
        _pipe_on(depth=4)
        c, cl = _boot_pools()
        name = POOLS[0][0]
        bodies = {f"t{i}": bytes([97 + i]) * (1500 + 400 * i)
                  for i in range(8)}
        for oid, body in bodies.items():
            assert cl.write_full(name, oid, body) == 0, oid
        for oid, body in bodies.items():
            assert cl.read(name, oid) == body, oid
        for osd in c.osds.values():
            osd.shutdown()
    finally:
        g_conf.rm_val("osd_op_num_threads")


def test_idle_resend_cap_leaves_budget_for_tick_retries(pipeline_conf):
    """The fabric's idle kick re-fires every pump, so an unreachable
    shard must not burn the whole ec_subwrite_retry_max budget in one
    call — idle rounds cap at 2, and the PACED tick retries recover
    the write once the link heals, with no map change needed."""
    c, cl = _boot_pools()
    name = POOLS[0][0]
    pid = cl.lookup_pool(name)
    pgid, primary = cl._calc_target(pid, "cap")
    pg = c.osds[primary].pgs[pgid]
    acting = pg.acting_shards()
    victim = next(o for s, o in acting.items() if o != primary)
    c.network.blackhole(f"osd.{primary}", f"osd.{victim}")
    done = []
    pg.backend.submit_transaction("cap", b"C" * 3000, done.append)
    c.network.pump()
    wr = next(iter(pg.backend.inflight_writes.values()))
    assert wr.resends == 2, f"idle kick burned {wr.resends} rounds"
    assert not done
    c.network.blackhole(f"osd.{primary}", f"osd.{victim}", on=False)
    for _ in range(3):
        c.tick(dt=4.0)
    assert done == [0], done
    assert _read_via_backend(c, pg, "cap") == b"C" * 3000


def test_subwrite_resend_timer_unwedges_pipeline(pipeline_conf):
    """ROADMAP robustness follow-up: a dropped EC sub-op write no
    longer wedges the per-oid pipeline — the resend timer (driven by
    the tick and the fabric's idle kick) completes the op, and the
    shard-side replay is version-deduped (no double-apply)."""
    from ceph_tpu.fault import g_faults
    from ceph_tpu.osd.ec_backend import l_pipeline_subwrite_resends
    c, cl = _boot_pools()
    name = POOLS[0][0]
    pc = pipeline_perf_counters()
    rs0 = pc.get(l_pipeline_subwrite_resends)
    try:
        # drop the write fan-out twice (different shards), then let the
        # resend timer recover — the op must still ack
        g_faults.inject("msg.drop", mode="nth", n=2, count=2,
                        match="MOSDECSubOpWrite ")
        body = b"retry" * 1000
        assert cl.write_full(name, "dropped", body) == 0
        assert pc.get(l_pipeline_subwrite_resends) > rs0
        assert cl.read(name, "dropped") == body
        # queue drained: nothing left in flight on the write's PG
        pid = cl.lookup_pool(name)
        pgid, primary = cl._calc_target(pid, "dropped")
        assert not c.osds[primary].pgs[pgid].backend.inflight_writes
    finally:
        g_faults.clear()
