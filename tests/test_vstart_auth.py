"""Auth-enabled multi-process cluster: every byte signed with cephx.

The reference's standalone tier runs with cephx on by default
(qa/standalone/ceph-helpers.sh run_mon; src/auth/cephx); here the
ProcessCluster generates a keyring, every daemon bootstraps its tickets
from the mon-process KDC over the wire, and all subsequent frames —
client ops, EC sub-writes, heartbeats, map pushes — carry session-key
signatures.  A successful write/read proves the full handshake chain;
the spoof check proves enforcement is actually on.
"""
import struct
import time

import numpy as np
import pytest

from ceph_tpu.vstart import ProcessCluster


@pytest.fixture(scope="module")
def cluster():
    c = ProcessCluster(
        n_osds=3,
        pool={"name": "p", "pg_num": 4,
              "profile": {"plugin": "isa", "k": "2", "m": "1"}},
        heartbeat_interval=1.0, heartbeat_grace=4.0, auth=True)
    yield c
    c.close()


def test_auth_cluster_end_to_end(cluster):
    c = cluster
    cl = c.client()
    assert cl.osdmap.epoch > 0, "no map from the mon process"
    c.wait_healthy(cl)
    assert c.network.auth.client.authenticated()
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, 24000, dtype=np.uint8).tobytes()
    r = -1
    for _ in range(30):
        r = cl.write_full("p", "obj", data)
        if r == 0:
            break
        time.sleep(0.5)
    assert r == 0
    assert cl.read("p", "obj") == data


def test_auth_cluster_rejects_unauthenticated_injection(cluster):
    """A raw TCP frame with no handshake/signature must not reach the
    mon's dispatcher: poke an un-authed MMonSubscribe at the mon port
    and verify nothing about the cluster reacts (and the keyed client
    still works afterwards)."""
    import socket as sk
    c = cluster
    from ceph_tpu.msg.messages import MMonSubscribe
    from ceph_tpu.msg.wire import encode_message
    msg = MMonSubscribe()
    msg.src = "osd.0"
    payload = encode_message(msg)
    dname = b"mon"
    frame = struct.pack("<I H B", len(payload), len(dname), 0) \
        + dname + payload
    raw = sk.create_connection(tuple(c.directory["mon"]), timeout=5.0)
    raw.sendall(frame + b"\x00" * 8)
    time.sleep(1.0)
    raw.close()
    cl = c.client()
    c.wait_healthy(cl)          # cluster unbothered, client still keyed
    # self-sufficient: write-then-read here (xdist may run this test
    # before the module's write test, on a different worker)
    r = -1
    for attempt in range(30):
        r = cl.write_full("p", "inj-probe", b"still-keyed")
        if r == 0:
            break
        time.sleep(0.5)
    assert r == 0, f"probe write never landed: {r}"
    assert cl.read("p", "inj-probe") == b"still-keyed"
