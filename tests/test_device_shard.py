"""DeviceShard — device-resident memstore shard bodies + LRU budget.

The device-resident shard store (os_store/device_shard.py): a shard
body written through ``Transaction.write_shard`` stays in HBM as a
``DeviceShard`` handle until a host read lazily materializes it, and
the process-wide ``g_device_budget`` LRU demotes cold shards to host
bytes when resident bytes exceed ``os_memstore_device_bytes_max``.
Byte-granular memstore splices (write/zero/truncate) materialize first,
so storage semantics are identical to the host-bytes representation.
"""
import gc

import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.os_store import DeviceShard, g_device_budget
from ceph_tpu.os_store.device_shard import memstore_device_perf_counters
from ceph_tpu.os_store.memstore import MemStore, Transaction, hobject_t
from ceph_tpu.utils.crc32c import crc32c

jnp = pytest.importorskip("jax.numpy")


@pytest.fixture(autouse=True)
def _budget(request):
    """A large residency budget per test (overridable via marker) and a
    drained process-wide LRU afterwards, so tests never see each
    other's resident bytes."""
    saved = g_conf.values.get("os_memstore_device_bytes_max")
    g_conf.set_val("os_memstore_device_bytes_max", 1 << 20)
    yield
    if saved is None:
        g_conf.rm_val("os_memstore_device_bytes_max")
    else:
        g_conf.set_val("os_memstore_device_bytes_max", saved)
    gc.collect()


def make_shard(data: bytes) -> DeviceShard:
    dev = jnp.asarray(np.frombuffer(data, dtype=np.uint8))
    return DeviceShard(dev, len(data), crc32c(data))


def payload(n: int, seed: int = 0) -> bytes:
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


# ---- the handle itself ------------------------------------------------------
def test_materialize_is_byte_identical_and_lazy():
    data = payload(4096, seed=1)
    sh = make_shard(data)
    assert sh.is_resident and len(sh) == 4096
    assert sh.device_array() is not None
    before = memstore_device_perf_counters().dump()["materializations"]
    assert sh.materialize() == data
    assert bytes(sh) == data                # later coercions are free
    after = memstore_device_perf_counters().dump()["materializations"]
    assert after == before + 1              # exactly one accounted d2h
    assert not sh.is_resident
    assert sh.device_array() is None        # HBM handle dropped


def test_budget_tracks_admission_and_finalize():
    base = g_device_budget.resident_bytes()
    sh = make_shard(payload(2048, seed=2))
    assert g_device_budget.resident_bytes() == base + 2048
    del sh
    gc.collect()
    # the weakref finalizer returned the dropped shard's bytes without
    # any explicit unregister call (the store just forgot the object)
    assert g_device_budget.resident_bytes() == base


def test_lru_demotes_coldest_shard_over_budget():
    g_conf.set_val("os_memstore_device_bytes_max", 100)
    before = memstore_device_perf_counters().dump()["demotions"]
    old = make_shard(payload(64, seed=3))
    new = make_shard(payload(64, seed=4))   # 128 > 100: evict the LRU
    assert not old.is_resident              # demoted, not lost
    assert new.is_resident
    assert old.materialize() == payload(64, seed=3)
    after = memstore_device_perf_counters().dump()["demotions"]
    assert after == before + 1


def test_touch_refreshes_lru_order():
    g_conf.set_val("os_memstore_device_bytes_max", 150)
    a = make_shard(payload(64, seed=5))
    b = make_shard(payload(64, seed=6))
    g_device_budget.touch(a)                # a is now the hottest
    c = make_shard(payload(64, seed=7))     # over budget: b is coldest
    assert a.is_resident and c.is_resident
    assert not b.is_resident


def test_demote_preserves_bytes_and_crc():
    data = payload(512, seed=8)
    sh = make_shard(data)
    sh.demote()
    assert not sh.is_resident
    assert bytes(sh) == data
    assert crc32c(bytes(sh)) == sh.crc
    sh.demote()                             # idempotent


# ---- memstore integration ---------------------------------------------------
def _store_with_shard(data: bytes):
    store = MemStore()
    ho = hobject_t("obj", 0)
    t = Transaction()
    t.create_collection("c")
    t.write_shard("c", ho, make_shard(data))
    store.queue_transaction(t)
    return store, ho


def test_write_shard_stores_handle_and_stat_stays_resident():
    data = payload(4096, seed=9)
    store, ho = _store_with_shard(data)
    body = store.colls["c"][ho].data
    assert isinstance(body, DeviceShard)
    assert store.stat("c", ho) == 4096      # len() — no d2h
    assert body.is_resident


def test_read_shard_returns_handle_then_read_materializes():
    data = payload(4096, seed=10)
    store, ho = _store_with_shard(data)
    got = store.read_shard("c", ho)
    assert isinstance(got, DeviceShard) and got.is_resident
    assert store.read("c", ho) == data      # the lazy materialization
    assert not got.is_resident
    assert store.read("c", ho, offset=100, length=200) \
        == data[100:300]


def test_splice_after_residency_matches_host_semantics():
    data = payload(1024, seed=11)
    store, ho = _store_with_shard(data)
    twin = MemStore()
    t = Transaction()
    t.create_collection("c")
    t.write("c", ho, 0, data)
    twin.queue_transaction(t)
    for s in (store, twin):
        t = Transaction()
        t.write("c", ho, 512, b"X" * 16)
        t.zero("c", ho, 0, 8)
        t.truncate("c", ho, 900)
        s.queue_transaction(t)
    assert store.read("c", ho) == twin.read("c", ho)
    assert store.stat("c", ho) == 900


def test_save_load_roundtrip_materializes_resident_body(tmp_path):
    data = payload(2048, seed=12)
    store, ho = _store_with_shard(data)
    path = str(tmp_path / "store.bin")
    store.save(path)
    assert MemStore.load(path).read("c", ho) == data
