"""Self-managed snapshots: mon id allocation + client write SnapContexts.

The librados selfmanaged_snap_* surface (librados/IoCtxImpl.cc
selfmanaged_snap_create / selfmanaged_snap_set_write_ctx; MOSDOp.h snapc):
the mon only allocates/retires snap ids; which snapshots an object
belongs to is decided by the SnapContext each client attaches to its
writes.  Clone-on-write, read-at-snap and trimming ride the same PG
snapset machinery as pool snaps (PrimaryLogPG make_writeable).
"""
import pytest

from ceph_tpu.client import ObjectOperation
from ceph_tpu.cluster import MiniCluster


def make(fixture):
    if fixture == "ec":
        c = MiniCluster(n_osds=6)
        c.create_ec_pool("sm", k=2, m=1, plugin="isa", pg_num=8)
    else:
        c = MiniCluster(n_osds=4)
        c.create_replicated_pool("sm", size=3, pg_num=8)
    return c, c.client("client.sm")


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_snapc_clone_and_read_at_snap(fixture):
    c, cl = make(fixture)
    cl.write_full("sm", "img", b"generation-one")
    s1 = cl.selfmanaged_snap_create("sm")
    cl.set_write_ctx("sm", s1, [s1])
    cl.write_full("sm", "img", b"generation-two!")
    assert cl.read("sm", "img") == b"generation-two!"
    assert cl.read("sm", "img", snap=s1) == b"generation-one"
    # second write under the same ctx must not re-clone
    cl.write_full("sm", "img", b"generation-three")
    assert cl.read("sm", "img", snap=s1) == b"generation-one"


def test_no_ctx_means_no_clone():
    c, cl = make("rep")
    cl.write_full("sm", "o", b"v1")
    s1 = cl.selfmanaged_snap_create("sm")
    # the snap exists but this client never put it in a write ctx:
    # the write must NOT clone (snapshots are client-defined)
    cl.write_full("sm", "o", b"v2")
    assert cl.read("sm", "o", snap=s1) == b"v2"


def test_layered_snaps_and_remove_trims():
    c, cl = make("rep")
    cl.write_full("sm", "o", b"v1")
    s1 = cl.selfmanaged_snap_create("sm")
    cl.set_write_ctx("sm", s1, [s1])
    cl.write_full("sm", "o", b"v2")
    s2 = cl.selfmanaged_snap_create("sm")
    cl.set_write_ctx("sm", s2, [s1, s2])
    cl.write_full("sm", "o", b"v3")
    assert cl.read("sm", "o", snap=s1) == b"v1"
    assert cl.read("sm", "o", snap=s2) == b"v2"
    assert cl.read("sm", "o") == b"v3"
    # retire s1: its clone becomes garbage once the trimmer runs
    cl.selfmanaged_snap_remove("sm", s1)
    c.tick(40)
    assert cl.read("sm", "o", snap=s2) == b"v2"
    assert cl.read("sm", "o") == b"v3"
    # the trim is observable two ways: reading at the retired id now
    # resolves past its tombstone to the next clone (v2, not v1), and
    # no OSD store still holds the s1 clone object
    assert cl.read("sm", "o", snap=s1) == b"v2"
    clone_suffix = f"\x00snap\x00{s1}"
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for hoid in osd.store.list_objects(cid):
                assert not str(hoid.oid).endswith(clone_suffix)


def test_vector_and_delete_honor_snapc():
    c, cl = make("rep")
    cl.omap_set("sm", "o", {"k": b"old"})
    s1 = cl.selfmanaged_snap_create("sm")
    cl.set_write_ctx("sm", s1, [s1])
    op = ObjectOperation().omap_set({"k": b"new"})
    r, _ = cl.operate("sm", "o", op)
    assert r == 0
    assert cl.omap_get("sm", "o")["k"] == b"new"
    # delete under a snapc leaves the snapshot readable
    cl.write_full("sm", "gone", b"payload")
    s2 = cl.selfmanaged_snap_create("sm")
    cl.set_write_ctx("sm", s2, [s1, s2])
    cl.remove("sm", "gone")
    with pytest.raises(IOError):
        cl.read("sm", "gone")
    assert cl.read("sm", "gone", snap=s2) == b"payload"


def test_mode_mixing_refused():
    c, cl = make("rep")
    cl.selfmanaged_snap_create("sm")
    with pytest.raises(ValueError):
        cl.snap_create("sm", "poolsnap")
    c2 = MiniCluster(n_osds=3)
    c2.create_replicated_pool("ps", size=2, pg_num=8)
    cl2 = c2.client("client.x")
    cl2.snap_create("ps", "s")
    with pytest.raises(ValueError):
        cl2.selfmanaged_snap_create("ps")
    # retiring a live pool-mode snapshot through the selfmanaged door
    # would corrupt it — refused like the reference's EINVAL
    with pytest.raises(ValueError):
        cl2.selfmanaged_snap_remove("ps", 1)


def test_bad_write_ctx_rejected():
    c, cl = make("rep")
    s1 = cl.selfmanaged_snap_create("sm")
    with pytest.raises(ValueError):
        cl.set_write_ctx("sm", 0, [s1])          # seq below newest snap
    with pytest.raises(ValueError):
        cl.set_write_ctx("sm", s1, [s1, s1])     # duplicate ids
