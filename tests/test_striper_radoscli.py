"""RadosStriper (libradosstriper analog) + the rados CLI."""
import json
import struct

import pytest

from ceph_tpu.client.striper import RadosStriper
from ceph_tpu.cluster import MiniCluster


@pytest.fixture(scope="module")
def env():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("st", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.st")
    return c, cl


def striper(cl, **kw):
    kw.setdefault("stripe_unit", 128)
    kw.setdefault("stripe_count", 3)
    kw.setdefault("object_size", 512)
    return RadosStriper(cl, "st", **kw)


def test_striped_write_read_roundtrip(env):
    c, cl = env
    s = striper(cl)
    data = bytes(range(256)) * 20          # 5120 B: many objects/sets
    assert s.write_full("big", data) == 0
    assert s.stat("big") == len(data)
    assert s.read("big") == data
    # ranged reads crossing unit/object/set boundaries
    for off, ln in [(0, 100), (100, 300), (500, 128), (120, 9),
                    (1020, 2000), (5000, 200)]:
        assert s.read("big", off, ln) == data[off:off + ln], (off, ln)


def test_striped_objects_land_across_backing_objects(env):
    c, cl = env
    s = striper(cl)
    s.write_full("spread", b"z" * 2000)
    # backing objects follow the {soid}.{objectno:016x} convention
    assert cl.stat("st", "spread." + "0" * 16) > 0
    assert cl.stat("st", f"spread.{1:016x}") > 0


def test_striped_overwrite_and_append(env):
    c, cl = env
    s = striper(cl)
    s.write_full("ov", b"A" * 1000)
    assert s.write("ov", b"B" * 50, offset=400) == 0
    body = s.read("ov")
    assert body[400:450] == b"B" * 50 and body[:400] == b"A" * 400
    assert s.append("ov", b"C" * 10) == 0
    assert s.stat("ov") == 1010
    assert s.read("ov")[-10:] == b"C" * 10


def test_striped_sparse_and_truncate(env):
    c, cl = env
    s = striper(cl)
    s.write("sparse", b"tail", offset=3000)
    assert s.stat("sparse") == 3004
    body = s.read("sparse")
    assert body[:3000] == b"\0" * 3000 and body[3000:] == b"tail"
    # shrink across object boundaries, then regrow with zeros
    s.write_full("tr", bytes(range(256)) * 8)   # 2048
    assert s.truncate("tr", 700) == 0
    assert s.stat("tr") == 700
    assert s.read("tr") == (bytes(range(256)) * 8)[:700]
    assert s.truncate("tr", 900) == 0
    got = s.read("tr")
    assert got[:700] == (bytes(range(256)) * 8)[:700]
    assert got[700:] == b"\0" * 200


def test_striped_remove(env):
    c, cl = env
    s = striper(cl)
    s.write_full("gone", b"x" * 3000)
    assert s.remove("gone") == 0
    with pytest.raises(IOError):
        s.stat("gone")
    # backing objects are gone too
    with pytest.raises(IOError):
        cl.read("st", "gone." + "0" * 16)


def test_rados_cli_roundtrip(tmp_path, capsys):
    from ceph_tpu.tools import rados as rados_cli
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rp", size=3, pg_num=8)
    cl = c.client("client.seed")
    cl.write_full("rp", "hello", b"cli-bytes")
    ckpt = str(tmp_path / "ckpt")
    c.checkpoint(ckpt)

    def run(*argv):
        rc = rados_cli.main(["--cluster", ckpt, *argv])
        return rc, capsys.readouterr().out

    rc, out = run("df")
    assert rc == 0 and "rp" in out
    rc, out = run("ls", "rp")
    assert rc == 0 and "hello" in out.splitlines()
    rc, out = run("stat", "rp", "hello")
    assert json.loads(out)["size"] == 9
    # put / get
    src = tmp_path / "src.bin"
    src.write_bytes(b"from-a-file")
    rc, _ = run("put", "rp", "up", str(src))
    assert rc == 0
    dst = tmp_path / "dst.bin"
    rc, _ = run("get", "rp", "up", str(dst))
    assert rc == 0 and dst.read_bytes() == b"from-a-file"
    # snaps through the CLI survive re-checkpointing
    rc, _ = run("mksnap", "rp", "s1")
    assert rc == 0
    src.write_bytes(b"changed!")
    rc, _ = run("put", "rp", "up", str(src))
    assert rc == 0
    rc, out = run("lssnap", "rp")
    assert "s1" in out
    rc, _ = run("rollback", "rp", "up", "s1")
    assert rc == 0
    rc, _ = run("get", "rp", "up", str(dst))
    assert dst.read_bytes() == b"from-a-file"
    # xattrs + rm
    rc, _ = run("setxattr", "rp", "up", "owner", "zoe")
    assert rc == 0
    rc, out = run("listxattr", "rp", "up")
    assert "owner" in out
    rc, _ = run("rm", "rp", "up")
    assert rc == 0
    rc, out = run("ls", "rp")
    assert "up" not in out.splitlines()


def test_ceph_cli_status_surfaces(tmp_path, capsys):
    from ceph_tpu.tools import ceph_cli
    c = MiniCluster(n_osds=4)
    c.create_ec_pool("cp", k=2, m=1, plugin="isa", pg_num=4)
    cl = c.client("client.c")
    cl.write_full("cp", "o", b"bytes" * 100)
    ckpt = str(tmp_path / "ck")
    c.checkpoint(ckpt)

    def run(*argv):
        rc = ceph_cli.main(["--cluster", ckpt, *argv])
        return rc, capsys.readouterr().out

    rc, out = run("status")
    st = json.loads(out)
    assert rc == 0 and st["num_osds"] == 4 and st["pools"] == 1
    rc, out = run("health")
    assert rc == 0 and out.strip()
    rc, out = run("osd", "tree")
    assert rc == 0 and "osd.0" in out and "root" in out
    rc, out = run("osd", "df")
    assert rc == 0 and out.count("\n") >= 5
    rc, out = run("pg", "stat")
    assert rc == 0 and sum(json.loads(out).values()) == 4
    rc, out = run("pg", "dump")
    assert rc == 0 and "acting=" in out and "last_deep_scrub=" in out
    one_pgid = out.split()[0]
    rc, out = run("pg", "scrub")
    st = json.loads(out)
    assert rc == 0 and st["scrubbed"] == 4 and st["deep"] is False
    rc, out = run("pg", "deep-scrub", one_pgid)
    st = json.loads(out)
    assert rc == 0 and st["scrubbed"] == 1 and st["deep"] is True
    rc, out = run("df")
    assert "cp" in out


def test_truncate_grow_after_failed_shrink_reads_zeros(env):
    """Even if a shrink's backing trim were lost, a later grow must not
    resurrect destroyed bytes: the trim mark forces a re-trim bounded
    by min(new size, old size)."""
    import struct as _s
    from ceph_tpu.client.striper import SIZE_XATTR, TRIM_XATTR
    c, cl = env
    s = striper(cl)
    s.write_full("gz", b"D" * 1000)
    # simulate a shrink whose backing trim never happened: size says 0,
    # mark says 1000, data still on the shelves
    first = "gz." + "0" * 16
    cl.setxattr("st", first, SIZE_XATTR, _s.pack("<Q", 0))
    cl.setxattr("st", first, TRIM_XATTR, _s.pack("<Q", 1000))
    # grow: the destroyed bytes must come back as zeros, not "D"
    assert s.truncate("gz", 600) == 0
    assert s.read("gz") == b"\0" * 600


def test_remove_after_failed_shrink_deletes_orphans(env):
    """remove() honors the trim high-water mark: backing objects in
    (size, mark] left by a shrink that died mid-trim are deleted too,
    so a recreated striped object cannot resurrect their bytes."""
    import struct as _s
    from ceph_tpu.client.striper import SIZE_XATTR, TRIM_XATTR
    c, cl = env
    s = striper(cl)
    s.write_full("orph", b"D" * 2000)
    first = "orph." + "0" * 16
    # simulate a shrink to 100 whose backing trims never ran
    cl.setxattr("st", first, SIZE_XATTR, _s.pack("<Q", 100))
    cl.setxattr("st", first, TRIM_XATTR, _s.pack("<Q", 2000))
    assert s.remove("orph") == 0
    # every backing object across the full 2000-byte span must be gone
    for objectno in range(4):       # 2000 B / 512 B object_size
        with pytest.raises(IOError):
            cl.stat("st", f"orph.{objectno:016x}")
    # recreate small, grow into the old span: holes must read as zeros
    assert s.write_full("orph", b"x" * 10) == 0
    assert s.truncate("orph", 1500) == 0
    assert s.read("orph") == b"x" * 10 + b"\0" * 1490


def test_pg_query(tmp_path):
    """ceph pg <pgid> query: one pg's peering/log state as json, with
    the canonical hex pgid rendering (pg_t)."""
    import io
    import json
    from contextlib import redirect_stdout, redirect_stderr

    from ceph_tpu.tools import ceph_cli

    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("qp", pg_num=16)
    c.client("client.q").write_full("qp", "obj", b"querydata")
    ckpt = str(tmp_path / "ck")
    c.checkpoint(ckpt)

    def run(*args):
        out = io.StringIO()
        with redirect_stdout(out), redirect_stderr(out):
            rc = ceph_cli.main(["--cluster", ckpt, *args])
        return rc, out.getvalue()

    rc, out = run("pg", "dump")
    assert rc == 0
    pgid = out.split()[0]
    total = 0
    for line in out.splitlines():
        pid = line.split("\t")[0]
        for args in (("pg", "query", pid), ("pg", pid, "query")):
            rc, qout = run(*args)
            assert rc == 0, (args, qout)
            doc = json.loads(qout)
            assert doc["pgid"] == pid and doc["state"]
            assert "last_update" in doc and "log_entries" in doc
            assert doc["acting"] and \
                doc["acting_primary"] in doc["acting"]
        total += doc["objects_on_primary"]
    # per-pg object counts sum to the ONE object written (prefix
    # over-matching 0.1 vs 0.10 would overcount)
    assert total == 1, total
    rc, out = run("pg", "query", "9.ff")
    assert rc == 1 and "does not exist" in out
    rc, out = run("pg", "query")
    assert rc == 1 and "usage" in out


def test_pool_admin_verbs(tmp_path):
    """ceph osd pool create/set/rm (MonCommands.h): mutations persist
    to the checkpoint; rm requires the reference's double-name +
    --yes-i-really-really-mean-it confirmation."""
    import io
    from contextlib import redirect_stdout, redirect_stderr

    from ceph_tpu.tools import ceph_cli

    c = MiniCluster(n_osds=6)
    ckpt = str(tmp_path / "ck")
    c.checkpoint(ckpt)

    def run(*args):
        out = io.StringIO()
        with redirect_stdout(out), redirect_stderr(out):
            rc = ceph_cli.main(["--cluster", ckpt, *args])
        return rc, out.getvalue()

    assert run("osd", "pool", "create", "rp", "16")[0] == 0
    assert run("osd", "pool", "create", "ep", "8", "erasure")[0] == 0
    assert run("osd", "pool", "set", "rp", "size", "2")[0] == 0
    assert run("osd", "pool", "set", "rp", "quota_max_bytes",
               "1048576")[0] == 0
    rc, out = run("osd", "pool", "ls", "detail")
    assert rc == 0 and "size 2" in out and "max_bytes 1048576" in out
    # rm refuses casual deletion
    rc, out = run("osd", "pool", "rm", "rp")
    assert rc == 1 and "PERMANENTLY" in out
    rc, out = run("osd", "pool", "rm", "rp", "nope",
                  "--yes-i-really-really-mean-it")
    assert rc == 1
    assert run("osd", "pool", "rm", "rp", "rp",
               "--yes-i-really-really-mean-it")[0] == 0
    rc, out = run("osd", "pool", "ls")
    assert "rp" not in out and "ep" in out
    # usage errors
    assert run("osd", "pool", "create", "x")[0] == 1
    assert run("osd", "pool", "create", "x", "0")[0] == 1
    assert run("osd", "pool", "create", "x", "8", "wat")[0] == 1
    assert run("osd", "pool", "set", "ep", "nope", "1")[0] == 1
    # duplicate create = success without a shadow pool (reference)
    rc, out = run("osd", "pool", "create", "ep", "8")
    assert rc == 0 and "already exists" in out
    # rm of a missing pool errors cleanly
    rc, out = run("osd", "pool", "rm", "gone", "gone",
                  "--yes-i-really-really-mean-it")
    assert rc == 1 and "failed" in out
    # invalid size combinations are refused
    assert run("osd", "pool", "set", "ep", "min_size", "99")[0] == 1
    assert run("osd", "pool", "set", "ep", "size", "0")[0] == 1
    # pg_num growth COMMITS an epoch: a restored cluster's osds
    # instantiate the split pgs and serve objects hashed into them
    assert run("osd", "pool", "set", "ep", "pg_num", "16")[0] == 0
    assert run("osd", "pool", "set", "ep", "pgp_num", "16")[0] == 0
    # the mutations persisted: the restored cluster serves the EC pool
    c2 = MiniCluster.restore(ckpt)
    assert c2.mon.osdmap.pools[
        c2.mon.osdmap.lookup_pg_pool_name("ep")].pg_num == 16
    cl = c2.client("client.v")
    for i in range(8):          # span the split pg range
        assert cl.write_full("ep", f"o{i}", b"x%d" % i) == 0
        assert bytes(cl.read("ep", f"o{i}")) == b"x%d" % i


def test_osd_admin_verbs(tmp_path):
    """ceph osd out/in/reweight: epoch-committing osd state admin
    that a restored cluster observes."""
    import io
    from contextlib import redirect_stdout, redirect_stderr

    from ceph_tpu.tools import ceph_cli

    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", pg_num=8)
    ckpt = str(tmp_path / "ck")
    c.checkpoint(ckpt)

    def run(*args):
        out = io.StringIO()
        with redirect_stdout(out), redirect_stderr(out):
            rc = ceph_cli.main(["--cluster", ckpt, *args])
        return rc, out.getvalue()

    assert run("osd", "out", "2")[0] == 0
    c2 = MiniCluster.restore(ckpt)
    assert not c2.mon.osdmap.is_in(2)
    # a repeat out is a NO-OP (no epoch churn)
    e0 = c2.mon.osdmap.epoch
    rc, out = run("osd", "out", "2")
    assert rc == 0 and "already" in out
    assert MiniCluster.restore(ckpt).mon.osdmap.epoch == e0
    assert run("osd", "in", "osd.2")[0] == 0
    c2 = MiniCluster.restore(ckpt)
    assert c2.mon.osdmap.is_in(2)
    assert run("osd", "reweight", "1", "0.5")[0] == 0
    c2 = MiniCluster.restore(ckpt)
    assert c2.mon.osdmap.osd_weight[1] == 0x8000
    # out then in RESTORES the reweight override (old_weight memo)
    assert run("osd", "out", "1")[0] == 0
    assert run("osd", "in", "1")[0] == 0
    c2 = MiniCluster.restore(ckpt)
    assert c2.mon.osdmap.osd_weight[1] == 0x8000
    # error contracts
    assert run("osd", "out", "99")[0] == 1
    assert run("osd", "out", "dso.2")[0] == 1
    assert run("osd", "reweight", "1", "7")[0] == 1
    assert run("osd", "reweight", "1")[0] == 1
    assert run("osd", "out")[0] == 1
