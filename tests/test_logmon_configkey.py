"""LogMonitor + ConfigKeyService: paxos-replicated mon services.

The reference runs several PaxosServices over one Paxos instance
(src/mon/PaxosService.h): LogMonitor commits daemons' clog entries into
a replicated history (src/mon/LogMonitor.cc) and ConfigKeyService keeps
a replicated key-value store (src/mon/ConfigKeyService.cc).  Here both
ride the same consensus as the OSDMap: their payloads travel inside
committed Incrementals, so they are exactly as failover-proof as the
map itself.
"""
import json

from ceph_tpu.cluster import MiniCluster


def test_cluster_log_records_events():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("lp", size=3, pg_num=4)
    c.network.pump()
    msgs = [e[3] for e in c.mon.cluster_log]
    assert any("pool 'lp' created" in m for m in msgs)
    c.mark_osd_down(2)
    msgs = [e[3] for e in c.mon.cluster_log]
    assert any("osd.2 marked down" in m for m in msgs)
    # level filter
    wrn = c.mon.log_last(50, level="WRN")
    assert wrn and all(e[2] == "WRN" for e in wrn)


def test_config_key_roundtrip_and_persistence(tmp_path):
    c = MiniCluster(n_osds=3)
    c.mon.config_key_set("mgr/balancer/mode", "upmap")
    c.mon.config_key_set("rgw/zone", "us-east")
    c.network.pump()
    assert c.mon.config_key_get("mgr/balancer/mode") == "upmap"
    c.mon.config_key_rm("rgw/zone")
    assert c.mon.config_key_get("rgw/zone") is None
    assert c.mon.config_key_dump() == {"mgr/balancer/mode": "upmap"}
    # state is rebuilt from the committed epoch history on restore
    c.checkpoint(str(tmp_path / "ck"))
    c2 = MiniCluster.restore(str(tmp_path / "ck"))
    assert c2.mon.config_key_get("mgr/balancer/mode") == "upmap"
    assert c2.mon.config_key_get("rgw/zone") is None
    # and the cluster log history came back too
    assert c2.mon.cluster_log == c.mon.cluster_log


def test_services_replicate_to_peons_and_survive_failover():
    c = MiniCluster(n_osds=4, n_mons=3)
    c.create_replicated_pool("p", size=3, pg_num=4)
    c.mon.config_key_set("flag/one", "1")
    c.mon.log_entry("admin", "INF", "hello quorum")
    c.mon.flush_log()
    c.network.pump()
    for m in c.mons:
        assert m.config_key_get("flag/one") == "1"
        assert any(e[3] == "hello quorum" for e in m.cluster_log)
    # leader dies: the successor still has both services' state
    c.kill_mon(0)
    for _ in range(6):
        c.tick(dt=6.0)
    leader = c.mon
    assert leader.name != "mon.0"
    assert leader.config_key_get("flag/one") == "1"
    assert any(e[3] == "hello quorum" for e in leader.cluster_log)
    # and keeps committing new service state
    leader.config_key_set("flag/two", "2")
    c.network.pump()
    for m in c.mons:
        if m.name == "mon.0":
            continue
        assert m.config_key_get("flag/two") == "2"


def test_scrub_inconsistency_reaches_cluster_log():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    assert cl.write_full("p", "obj", b"clean bytes" * 50) == 0
    # corrupt one NON-primary replica at rest, then deep-scrub
    _pg, primary = cl._calc_target(cl.lookup_pool("p"), "obj")
    hit = 0
    for osd in c.osds.values():
        if osd.osd_id == primary:
            continue
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj" and hit == 0:
                    osd.store.colls[cid][ho].data[3] ^= 0xFF
                    hit += 1
    assert hit == 1
    c.scrub(deep=True)
    c.tick()        # the tick flushes pending clog entries
    errs = c.mon.log_last(20, level="ERR")
    assert any("scrub" in e[3] and "inconsistent" in e[3] for e in errs)
    assert cl.read("p", "obj") == b"clean bytes" * 50


def test_osd_clog_survives_mon_death_without_duplicates():
    """Daemons broadcast clog to every mon (a single-target send dies
    with that mon); the leader dedups the fan-in so the entry commits
    exactly once.  With mon.0 dead, the entry must still land."""
    c = MiniCluster(n_osds=4, n_mons=3)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    assert cl.write_full("p", "obj", b"payload" * 40) == 0
    c.kill_mon(0)
    for _ in range(6):
        c.tick(dt=6.0)
    assert c.mon.name != "mon.0"
    _pg, primary = cl._calc_target(cl.lookup_pool("p"), "obj")
    c.osds[primary].clog("ERR", "synthetic inconsistency report")
    c.network.pump()
    c.tick()
    hits = [e for e in c.mon.cluster_log
            if e[3] == "synthetic inconsistency report"]
    assert len(hits) == 1, hits


def test_ceph_cli_log_and_config_key(tmp_path, capsys):
    from ceph_tpu.tools import ceph_cli
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("p", size=2, pg_num=4)
    c.mon.config_key_set("a/b", "c")
    c.network.pump()
    ckpt = str(tmp_path / "ck")
    c.checkpoint(ckpt)

    def run(*argv):
        rc = ceph_cli.main(["--cluster", ckpt, *argv])
        return rc, capsys.readouterr().out

    rc, out = run("log", "last", "50")
    assert rc == 0 and "pool 'p' created" in out
    rc, out = run("config-key", "dump")
    assert rc == 0 and json.loads(out) == {"a/b": "c"}
    rc, out = run("config-key", "get", "a/b")
    assert rc == 0 and out.strip() == "c"
    rc, _ = run("config-key", "exists", "a/b")
    assert rc == 0
    rc, _ = run("config-key", "get", "missing")
    assert rc == 1
    rc, _ = run("log", "tail")
    assert rc == 1
    rc, _ = run("log", "last", "abc")
    assert rc == 1
