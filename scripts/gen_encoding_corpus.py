"""Regenerate the archived encoding corpus
(tests/corpus/encodings/*.bin) from the dencoder registry's generated
test instances — the ceph-object-corpus role: blobs written by one
version of the framework must keep decoding in every later version
(tests/test_encoding_corpus.py enforces it).

Run ONLY when an encoding change is intentional; the diff of the
regenerated blobs is the reviewable record of what changed.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.tools.dencoder import _registry  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "..", "tests", "corpus",
                   "encodings")


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    # drop stale blobs first: a rename/removal must not leave orphans
    # that fail the corpus test after a documented regeneration
    for old in os.listdir(OUT):
        if old.endswith(".bin"):
            os.unlink(os.path.join(OUT, old))
    reg = _registry()
    n = 0
    for name, h in reg.items():
        for i, t in enumerate(h.tests(), 1):
            safe = name.replace(":", "_")
            with open(os.path.join(OUT, f"{safe}.{i}.bin"), "wb") as f:
                f.write(h.encode(t))
            n += 1
    print(f"archived {n} blobs for {len(reg)} types")


if __name__ == "__main__":
    main()
