#!/usr/bin/env bash
# Repo lint entry point: the invariant analyzer's full-tree pass.
#
#   scripts/lint.sh            # whole ceph_tpu/ tree (~2 s)
#   scripts/lint.sh --changed  # git-diff-scoped fast mode
#   scripts/lint.sh --rule no-bare-lock ceph_tpu/osd
#
# Exit 0 = clean, 1 = violations.  The same pass gates tier-1 via
# tests/test_static_analysis.py.  Catalog + pragma/allowlist policy:
# docs/ANALYSIS.md.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m ceph_tpu.analysis "$@"
