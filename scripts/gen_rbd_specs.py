"""Derive the rbd command-spec table from the reference's recorded
help transcript (src/test/cli/rbd/help.t) and emit
ceph_tpu/tools/rbd_specs.py.

The transcript IS the contract: usage lines give option order,
required-ness and positional arity; the detailed sections give short
names, arg-ness and description text (kept with the reference's own
line breaks so re-rendering through rbd_optfmt reproduces the bytes).
Run: python scripts/gen_rbd_specs.py [--check]
"""
from __future__ import annotations

import re
import sys
import os

REF = "/root/reference/src/test/cli/rbd/help.t"
OUT = os.path.join(os.path.dirname(__file__), "..",
                   "ceph_tpu", "tools", "rbd_specs.py")


def load_blocks():
    lines = [l[2:] if l.startswith("  ") else l
             for l in open(REF).read().splitlines()]
    # global help section: between "$ rbd --help" and the loop command
    gstart = next(i for i, l in enumerate(lines)
                  if l.startswith("usage: rbd <command>"))
    gend = next(i for i, l in enumerate(lines)
                if l.startswith("$ rbd help | grep"))
    global_help = lines[gstart:gend]
    blocks, cur = {}, None
    for l in lines[gend + 1:]:
        if l.startswith("rbd help ") and not l.startswith("rbd help |"):
            cur = l[len("rbd help "):]
            blocks[cur] = []
        elif cur is not None:
            blocks[cur].append(l)
    return global_help, blocks


def parse_command_list(global_help):
    """name -> (alias tuple or None, wrapped description)."""
    out = {}
    in_list = False
    cur = None
    for l in global_help:
        if l.startswith("Positional arguments:"):
            in_list = True
            continue
        if l.startswith("Optional arguments:"):
            break
        if not in_list or l.strip() in ("", "<command>"):
            continue
        m = re.match(r"^    ([a-z][a-z-]*(?: [a-z-]+)*)"
                     r"(?: \(([^)]+)\))?(?:\s+(.*))?$", l)
        if m and not l.startswith("      "):
            name = m.group(1)
            alias = tuple(m.group(2).split()) if m.group(2) else None
            out[name] = alias
            cur = name
    return out


def parse_usage(block):
    """-> (spec_words, ordered option tokens w/ required flag,
    positionals w/ variadic flag)."""
    usage_lines = []
    i = 0
    while i < len(block) and (i == 0 or block[i].startswith(" ")):
        usage_lines.append(block[i])
        i += 1
    flat = ""
    for l in usage_lines:
        flat += l.strip() + " "
    m = re.match(r"usage: rbd ((?:[a-z0-9-]+ )+)", flat)
    words = []
    rest = flat[len("usage: rbd "):]
    toks = rest.split()
    spec = []
    j = 0
    while j < len(toks) and re.fullmatch(r"[a-z0-9-]+", toks[j]):
        spec.append(toks[j])
        j += 1
    opts = []       # (long, required)
    poss = []       # (name, variadic)
    rest2 = " ".join(toks[j:])
    for tok in re.finditer(
            r"\[--([a-z0-9_-]+)(?: <[^>]+>)?\]"
            r"|--([a-z0-9_-]+) <[^>]+>"
            r"|\[<([a-z0-9-]+)> \.\.\.\]"
            r"|<([a-z0-9-]+)>", rest2):
        if tok.group(1):
            opts.append((tok.group(1), False))
        elif tok.group(2):
            opts.append((tok.group(2), True))
        elif tok.group(3):
            poss[-1] = (poss[-1][0], True)
        else:
            poss.append((tok.group(4), False))
    return spec, opts, poss, i


def parse_detailed(block, start):
    """-> description, {pos name: desc}, {long: (short, has_arg, desc)},
    extra_help."""
    i = start
    while i < len(block) and block[i] == "":
        i += 1
    desc = block[i] if i < len(block) else ""
    i += 1
    pos_desc, opt_desc = {}, {}
    extra = []
    section = None
    entries = []    # (kind, key, short, has_arg, desclines)
    cur = None
    while i < len(block):
        l = block[i]
        if l == "Positional arguments":
            section = "pos"
            cur = None
        elif l == "Optional arguments":
            section = "opt"
            cur = None
        elif section and l.startswith("  ") and not l.startswith("   "):
            if section == "pos":
                m = re.match(r"^  <([a-z0-9-]+)>\s*(.*)$", l)
                cur = ["pos", m.group(1), None, False,
                       [m.group(2)] if m.group(2) else []]
            else:
                m = re.match(r"^  (?:-(\w) \[ )?--([a-z0-9_-]+)(?: \])?"
                             r"( arg)?\s*(.*)$", l)
                cur = ["opt", m.group(2), m.group(1),
                       bool(m.group(3)), [m.group(4)] if m.group(4) else []]
            entries.append(cur)
        elif section and l.startswith("   ") and cur is not None:
            cur[4].append(l.strip())
        elif section == "opt" and l == "":
            # blank after the optional block: anything further is the
            # action's extra help (e.g. the Image Features legend)
            if i + 1 < len(block) and block[i + 1] != "":
                extra = [x for x in block[i + 1:]]
                while extra and extra[-1] == "":
                    extra.pop()
            break
        i += 1
    for kind, key, short, has_arg, dl in entries:
        text = "\n".join(dl)
        if kind == "pos":
            pos_desc[key] = text
        else:
            opt_desc[key] = (short, has_arg, text)
    return desc, pos_desc, opt_desc, "\n".join(extra)


def main():
    global_help, blocks = load_blocks()
    aliases = parse_command_list(global_help)
    specs = []
    for name, block in blocks.items():
        spec, opts, poss, di = parse_usage(block)
        desc, pos_desc, opt_desc, extra = parse_detailed(block, di)
        entry = {
            "spec": tuple(spec),
            "alias": aliases.get(name),
            "desc": desc,
            "positionals": [
                (pname, pos_desc.get(pname, ""), var)
                for pname, var in poss],
            "options": [
                (opt_desc[long][0], long, opt_desc[long][1], req,
                 opt_desc[long][2])
                for long, req in opts],
            "help": extra,
        }
        specs.append(entry)
    with open(OUT, "w") as f:
        f.write('"""rbd command-spec table (generated by '
                'scripts/gen_rbd_specs.py\nfrom the reference\'s '
                'recorded help transcript '
                'src/test/cli/rbd/help.t --\nthe transcript is the '
                'contract; regenerate rather than hand-edit).\n\n'
                'Entry: spec words, alias words or None, one-line '
                'description,\npositionals [(name, desc, variadic)], '
                'options [(short, long,\nhas_arg, required, desc)], '
                'extra help text.\n"""\n\n')
        f.write("SPECS = [\n")
        for e in specs:
            f.write("    {\n")
            for k in ("spec", "alias", "desc"):
                f.write(f"        {k!r}: {e[k]!r},\n")
            f.write("        'positionals': [\n")
            for p in e["positionals"]:
                f.write(f"            {p!r},\n")
            f.write("        ],\n        'options': [\n")
            for o in e["options"]:
                f.write(f"            {o!r},\n")
            f.write("        ],\n")
            f.write(f"        'help': {e['help']!r},\n")
            f.write("    },\n")
        f.write("]\n")
    print(f"wrote {len(specs)} specs to {OUT}")


if __name__ == "__main__":
    main()
