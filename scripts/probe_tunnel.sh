#!/bin/bash
# TPU-tunnel liveness probe loop: one line per attempt in PROBE_r05.log
# (timestamp, outcome) — the auditable record of accelerator
# availability during the round.
LOG=/root/repo/PROBE_r05.log
while true; do
  ts=$(date -u +"%Y-%m-%dT%H:%M:%SZ")
  out=$(timeout 60 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
  rc=$?
  if [ "$rc" = 0 ] && [ -n "$out" ] && [ "$out" != "cpu" ]; then
    echo "$ts LIVE $out" >> "$LOG"
  else
    echo "$ts DEAD rc=$rc" >> "$LOG"
  fi
  sleep 240
done
