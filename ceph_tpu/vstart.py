"""vstart-lite: a REAL multi-process cluster on localhost TCP sockets.

The reference's integration tier runs mon/mgr/osd daemons as separate
processes on localhost ports (src/vstart.sh;
qa/standalone/ceph-helpers.sh run_mon/run_osd) and thrashes them with
kill -9 (qa/tasks/ceph_manager.py:195 kill_osd).  This module is that
tier for ceph_tpu: ``python -m ceph_tpu.vstart mon|osd ...`` daemon
entrypoints over the TCP messenger (msg/tcp.py), plus a
``ProcessCluster`` harness that spawns one mon process and N OSD
processes, hands out wire-connected clients, and SIGKILLs daemons.

Every byte — client ops, EC sub-writes, peering queries, heartbeats,
failure reports, map publications — crosses real process boundaries
through the framed wire codec; nothing shortcuts through shared memory.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pin_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    # the reference's vstart.sh runs every daemon with lockdep=1: the
    # debug tier is exactly where the lock-order witness should be
    # armed (CEPH_TPU_LOCKDEP=0 opts a run out)
    if os.environ.get("CEPH_TPU_LOCKDEP", "1") != "0":
        from .common.lockdep import lockdep_enable
        lockdep_enable(True)


# ---- daemon mains ----------------------------------------------------------

def mon_main(args) -> None:
    """Monitor daemon: bootstrap the map, create the requested pool,
    serve subscriptions/failure reports forever.

    Multi-mon (--peers): rank 0 bootstraps, wins the initial election
    (lowest rank, Elector.cc), commits the initial epochs through paxos
    and only then reports READY; peons serve elections/replication from
    boot.  Any mon may later lead — all of them register the osd
    subscriptions so a post-failover leader publishes to everyone."""
    _pin_cpu()
    from .mon import Monitor
    from .mon import monitor as monitor_mod
    from .msg.tcp import TcpNetwork

    directory = json.loads(args.directory)
    auth = None
    if args.keyring:
        from .msg.tcp import TcpAuth
        auth = TcpAuth(args.name, args.keyring, kdc=True)
    net = TcpNetwork(("127.0.0.1", args.port),
                     {k: tuple(v) for k, v in directory.items()},
                     auth=auth, entity=args.name)
    peers = [p for p in args.peers.split(",") if p]
    if args.mon_grace:
        monitor_mod.MON_PING_GRACE = args.mon_grace
    if args.mds_grace:
        monitor_mod.MDS_BEACON_GRACE = args.mds_grace
    # real addresses -> a real MonMap (the roster as a first-class
    # epoched map, not just config; mon/MonMap.h role)
    import uuid as _uuid

    from .mon.monmap import MonMap
    roster = sorted({args.name, *peers})
    addrs = {n: directory.get(n, ("127.0.0.1", 0)) for n in roster}
    # deterministic over the roster+addresses: every mon process of
    # this cluster computes the SAME fsid
    monmap = MonMap(fsid=str(_uuid.uuid5(
        _uuid.NAMESPACE_URL, "ceph-tpu://" + ",".join(
            f"{n}={h}:{p}" for n, (h, p) in sorted(addrs.items())))))
    monmap.epoch = 1
    for n, (host, port) in addrs.items():
        monmap.add(n, f"{host}:{port}/0")
    mon = Monitor(net, name=args.name, rank=args.rank, peers=peers,
                  monmap=monmap)
    if args.down_out_interval:
        mon.down_out_interval = args.down_out_interval
    for i in range(args.n_osds):
        mon.subscribe(f"osd.{i}")
    if args.rank == 0 and not args.rejoin:
        mon.bootstrap(args.n_osds, osds_per_host=1)
        if peers:
            # win the initial election and seat the full quorum before
            # committing anything (peons were spawned first)
            mon.start_election()
            deadline = time.monotonic() + 60.0
            while not (mon.is_leader()
                       and len(mon.quorum) == len(peers) + 1):
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"initial mon quorum never formed: "
                        f"ee={mon.election_epoch} lr={mon.leader_rank} "
                        f"q={sorted(mon.quorum)}")
                net.pump(quiesce=0.02, deadline=0.2)
                mon.tick(time.monotonic())
        if args.pool:
            spec = json.loads(args.pool)
            if spec.get("type") == "replicated":
                mon.create_replicated_pool(spec["name"], size=spec["size"],
                                           pg_num=spec["pg_num"])
            else:
                mon.create_ec_profile("vprof", spec["profile"])
                mon.create_ec_pool(spec["name"], "vprof",
                                   pg_num=spec["pg_num"])
        mon.publish()
        net.pump()
        if peers:
            # drain the paxos pipeline: READY must mean the initial
            # epochs are COMMITTED quorum-wide, not merely proposed
            deadline = time.monotonic() + 60.0
            while mon._inflight is not None or mon._pending_proposals:
                if time.monotonic() > deadline:
                    raise RuntimeError("initial epochs never committed")
                net.pump(quiesce=0.02, deadline=0.2)
                mon.tick(time.monotonic())
        for i in range(args.n_osds):
            mon.send_full_map(f"osd.{i}")
    if args.rejoin:
        # a RESTARTED mon (mon_thrash revival): boot empty and force
        # an election — the collect/LAST recovery teaches whichever
        # side is behind (an empty rank-0 leader pulls the peers'
        # full committed history via OP_LAST deltas)
        mon.start_election()
    print("READY", flush=True)
    trace = os.environ.get("VSTART_MON_TRACE")
    last_trace = 0.0
    while True:
        net.pump(quiesce=0.02, deadline=0.5)
        mon.tick(time.monotonic())
        if trace and time.monotonic() - last_trace > 1.0:
            last_trace = time.monotonic()
            print(f"TRACE {mon.name} ee={mon.election_epoch} "
                  f"lr={mon.leader_rank} q={sorted(mon.quorum)} "
                  f"ep={mon.osdmap.epoch} ninc={len(mon.incrementals)} "
                  f"unc={mon._uncommitted is not None} "
                  f"infl={mon._inflight is not None} "
                  f"pend={len(mon._pending_proposals)}",
                  file=sys.stderr, flush=True)


def osd_main(args) -> None:
    """OSD daemon: dispatch loop + heartbeat ticks + recovery rounds."""
    _pin_cpu()
    from .msg.tcp import TcpNetwork
    from .osd import osd as osd_mod

    if args.heartbeat_grace:
        osd_mod.HEARTBEAT_GRACE = args.heartbeat_grace
    if args.debug:
        from .common.config import g_conf
        from .common.dout import _log
        for s in ("osd", "pg", "recovery"):
            g_conf.set_val(f"debug_{s}", f"{args.debug}/{args.debug}")
        _log.stderr_level = args.debug
    directory = json.loads(args.directory)
    auth = None
    if args.keyring:
        from .msg.tcp import TcpAuth
        auth = TcpAuth(f"osd.{args.id}", args.keyring)
    net = TcpNetwork(("127.0.0.1", args.port),
                     {k: tuple(v) for k, v in directory.items()},
                     auth=auth, entity=f"osd.{args.id}")
    if auth is not None:
        # fetch tickets + rotating keys BEFORE serving, so inbound
        # authorizers (peer OSDs, the mon) can be verified from boot
        for _ in range(50):
            if net.authenticate():
                break
            time.sleep(0.2)
    store = None
    if args.data_dir:
        # durable boot (OSD::init, osd/OSD.cc:2469): mount the WAL
        # store — a rebooted daemon replays its journal and resumes
        # with its PG logs/data intact, so recovery is log-based
        from .os_store.walstore import mount_store
        store = mount_store(args.data_dir)
    mon_names = [m for m in (args.mon_names or "mon").split(",") if m]
    daemon = osd_mod.OSD(net, args.id, mon_name=mon_names[0],
                         store=store, mon_names=mon_names)
    # boot subscription: the mon's startup map pushes predate this
    # process's listener, so ask for the full history explicitly
    # (MonClient::sub_want("osdmap") at OSD::init) — from EVERY mon,
    # so a post-failover leader keeps publishing to us
    from .msg.messages import MMonSubscribe
    for m in mon_names:
        net.send(daemon.name, m, MMonSubscribe())
    print("READY", flush=True)
    interval = args.heartbeat_interval or osd_mod.HEARTBEAT_INTERVAL
    # warm-up: the first tick waits one full interval so sibling
    # daemons still booting don't read as silent peers
    last_tick = time.monotonic()
    while True:
        net.pump(quiesce=0.02, deadline=0.5)
        now = time.monotonic()
        if now - last_tick >= interval:
            daemon.tick(now)
            last_tick = now
        daemon.run_recovery()


def mds_main(args) -> None:
    """MDS daemon: metadata authority over the wire (mds/server.py).
    Creates the fs pools through mon wire commands on first boot; a
    rebooted daemon finds them and REPLAYS its journal."""
    _pin_cpu()
    from .client.mon_client import MonClient
    from .client.rados import RadosClient
    from .msg.tcp import TcpNetwork

    directory = json.loads(args.directory)
    auth = None
    if args.keyring:
        from .msg.tcp import TcpAuth
        auth = TcpAuth(args.name, args.keyring)
    net = TcpNetwork(("127.0.0.1", args.port),
                     {k: tuple(v) for k, v in directory.items()},
                     auth=auth, entity=args.name)
    mon_names = [m for m in (args.mon_names or "mon").split(",") if m]
    # the FULL roster: an mds must keep reading the fsmap (its
    # promotion/fencing signal) across mon failures, hunting like the
    # reference MonClient
    rados = RadosClient(net, MonClient(net, mon_names[0],
                                       mon_names=mon_names),
                        args.name)
    # wait for a map with every osd up before touching pools
    deadline = time.monotonic() + 120.0
    while True:
        net.pump(quiesce=0.05, deadline=0.3)
        rados.mon.send_full_map(args.name)
        net.pump(quiesce=0.05, deadline=0.3)
        m = rados.osdmap
        if m.max_osd >= args.n_osds and \
                all(m.is_up(o) for o in range(args.n_osds)):
            break
        if time.monotonic() > deadline:
            raise RuntimeError("mds never saw a healthy map")
        time.sleep(0.2)
    for pool in (args.metadata_pool, args.data_pool):
        try:
            rados.mon_command("create_replicated_pool", name=pool,
                              size=min(3, args.n_osds), pg_num=8)
        except (ValueError, IOError):
            pass                    # exists (reboot) — reuse it
    from .cephfs.cls_fs import ROOT_INO, dir_oid
    from .mds import MDSDaemon
    # the fresh pools' PGs keep settling for a while after creation:
    # wait until the metadata pool actually ANSWERS (ENOENT or data —
    # either means servable).  Freshness is decided AFTER promotion:
    # another mds may create the fs while we stand by.
    deadline = time.monotonic() + 120.0
    while True:
        try:
            rados.stat(args.metadata_pool, dir_oid(ROOT_INO))
            break
        except IOError as e:
            if getattr(e, "errno", None) == 2:
                break               # pool serves, no fs yet
            if time.monotonic() > deadline:
                raise RuntimeError("fs pools never became servable")
            net.pump(quiesce=0.05, deadline=0.3)
            time.sleep(0.3)
    # ---- fsmap membership: beacon as standby until the MDSMonitor
    # names us active (first joiner activates immediately; later ones
    # stand by and take over on the active's beacon-grace failover) ----
    from .msg.messages import MMDSBeacon

    def beacon(state: str) -> None:
        for m in mon_names:
            net.send(args.name, m, MMDSBeacon(name=args.name,
                                              state=state))

    def fs_state():
        """(my_rank or None, rank->name) from the replicated fsmap."""
        try:
            st = rados.mon_command("fs_status")
        except (IOError, ValueError):
            return None, {}
        if not st:
            return None, {}
        ranks = {int(r): n for r, n in
                 (st.get("ranks") or {}).items()}
        e = (st.get("mds") or {}).get(args.name)
        if e and e.get("state") == "active" \
                and e.get("rank") is not None:
            return int(e["rank"]), ranks
        return None, ranks

    beacon("standby")
    print("READY", flush=True)
    last_beacon = 0.0
    my_rank = None
    while my_rank is None:
        my_rank, _ranks = fs_state()
        if my_rank is not None:
            break
        net.pump(quiesce=0.05, deadline=0.3)
        if time.monotonic() - last_beacon > 1.0:
            beacon("standby")
            last_beacon = time.monotonic()
        time.sleep(0.2)

    # promoted (or first): initialize and serve.  Probe freshness NOW —
    # if another mds was active before us, IT created the fs and we
    # must open + REPLAY, not mkfs.  Transient errors retry (a stale
    # False would journal.open() a journal that never existed).
    # Rank 0 is the fs creator; a promoted rank > 0 WAITS for the fs
    # (rank 0's mkfs) and never creates it.
    fresh = None
    deadline = time.monotonic() + 120.0
    last_slide = 0.0

    def keepalive() -> None:
        # EVERY promoted daemon (rank 0 doing mkfs included) must
        # keep beaconing while it initializes — a silent active is
        # grace-failed by the mon and its rank reseated under it,
        # which on a slow host means dual mkfs writers
        nonlocal last_beacon
        if time.monotonic() - last_beacon > 1.0:
            beacon("active")
            last_beacon = time.monotonic()

    while fresh is None:
        keepalive()
        try:
            rados.stat(args.metadata_pool, dir_oid(ROOT_INO))
            fresh = False
        except IOError as e:
            if getattr(e, "errno", None) == 2:
                if my_rank == 0:
                    fresh = True
                    continue
                # a promoted rank > 0 must outwait a SLOW rank 0, not
                # just a dead one: while the fsmap still shows a
                # rank-0 incumbent its mkfs is in progress somewhere,
                # so the deadline keeps sliding (loaded-host runs
                # exceeded a fixed 120 s before rank 0 finished).
                # The status poll rides the same 1 s cadence as the
                # beacons — the slide needs no finer granularity.
                if time.monotonic() - last_slide > 1.0:
                    last_slide = time.monotonic()
                    _r, ranks = fs_state()
                    if 0 in ranks:
                        deadline = max(deadline, last_slide + 120.0)
                if time.monotonic() > deadline:
                    raise RuntimeError("rank 0 never created the fs")
                else:
                    net.pump(quiesce=0.05, deadline=0.3)
                    time.sleep(0.3)
            elif time.monotonic() > deadline:
                raise
            else:
                net.pump(quiesce=0.05, deadline=0.3)
                time.sleep(0.3)
    mds = None
    while mds is None:
        keepalive()
        try:
            mds = MDSDaemon(net, rados, args.name,
                            metadata_pool=args.metadata_pool,
                            data_pool=args.data_pool, mkfs=fresh,
                            rank=my_rank)
        except IOError:
            # some PG of the fresh pools still settling; mkfs/journal
            # creation is idempotent, so just try again
            if time.monotonic() > deadline:
                raise
            net.pump(quiesce=0.05, deadline=0.3)
            time.sleep(0.5)
    # seed the rank map BEFORE serving — with an empty map a freshly
    # promoted rank treats other ranks' subtrees as its own and
    # answers ENOENT where it must FORWARD, so a transient fs_status
    # failure here cannot be shrugged off; a SEPARATE loop from the
    # construction retry so an IOError mid-seed cannot skip it
    seeded = False
    while not seeded:
        keepalive()
        try:
            _r, ranks0 = fs_state()
            if ranks0:
                mds.set_mds_map(ranks0)
                seeded = True
                continue
        except IOError:
            pass
        if time.monotonic() > deadline:
            raise RuntimeError("fsmap never readable before serving")
        net.pump(quiesce=0.05, deadline=0.3)
        time.sleep(0.3)
    last_beacon = 0.0
    last_fence_check = time.monotonic()
    while True:
        net.pump(quiesce=0.02, deadline=0.3)
        mds.process()
        now = time.monotonic()
        if now - last_beacon > 1.0:
            mds.beacon(mon_names)
            last_beacon = now
        if now - last_fence_check > 2.0:
            last_fence_check = now
            rank_now, ranks = fs_state()
            if ranks:
                mds.set_mds_map(ranks)
            # FENCED whenever a REAL fsmap read no longer shows us
            # holding our rank — reassigned (beacon-grace failover),
            # demoted (max_mds shrink), or dropped.  Two writers on
            # one rank journal would corrupt it — suicide and let the
            # harness restart us as a standby (MDSDaemon::respawn).
            # An empty ranks dict is a transient mon read failure,
            # never a fence signal.
            if ranks and ranks.get(my_rank) != args.name:
                print(f"fenced: rank {my_rank} is now "
                      f"{ranks.get(my_rank) or 'unheld'}; exiting",
                      file=sys.stderr, flush=True)
                os._exit(0)
        mds.tick(now)


# ---- harness ---------------------------------------------------------------

def _free_ports(n: int) -> List[int]:
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class ProcessCluster:
    """Spawn mon + N OSDs as real processes; clients live in the
    calling process and speak TCP like everyone else."""

    def __init__(self, n_osds: int = 6, pool: Optional[dict] = None,
                 heartbeat_interval: float = 1.0,
                 heartbeat_grace: float = 4.0,
                 down_out_interval: float = 5.0,
                 client_names: Tuple[str, ...] = ("client.x",),
                 auth: bool = False,
                 data_root: Optional[str] = None,
                 n_mons: int = 1,
                 mon_grace: float = 4.0,
                 n_mds: int = 0,
                 mds_grace: float = 5.0):
        self.n_osds = n_osds
        self.n_mons = n_mons
        self.n_mds = n_mds
        self.mds_grace = mds_grace
        self.mon_grace = mon_grace
        # single-mon clusters keep the historical name "mon"
        self.mon_names = (["mon"] if n_mons == 1
                          else [f"mon.{r}" for r in range(n_mons)])
        self.data_root = data_root
        if data_root:
            os.makedirs(data_root, exist_ok=True)
        self.keyring_path: Optional[str] = None
        self._tmpdir: Optional[str] = None
        if auth:
            import tempfile
            from .auth import Keyring
            self._tmpdir = tempfile.mkdtemp(prefix="ceph_tpu_auth_")
            kr = Keyring()
            for m in self.mon_names:
                kr.create(m)
            for i in range(n_osds):
                kr.create(f"osd.{i}")
            for i in range(n_mds):
                kr.create(f"mds.{i}")
            for name in client_names:
                kr.create(name)
            self.keyring_path = os.path.join(self._tmpdir, "keyring")
            kr.save(self.keyring_path)
        self.client_names = client_names
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.procs: Dict[str, subprocess.Popen] = {}
        self.network = None
        # the reserve-then-close port probe (_free_ports) races other
        # processes between close() and the daemon's rebind; a loser
        # dies instantly with EADDRINUSE, so ONE respawn with fresh
        # ports absorbs the collision without masking slow failures
        for attempt in (0, 1):
            ports = _free_ports(n_osds + n_mons + n_mds + 1)
            self.mon_ports = ports[:n_mons]
            self.mon_port = self.mon_ports[0]
            self.client_port = ports[n_mons]
            self.osd_ports = ports[n_mons + 1:n_mons + 1 + n_osds]
            self.mds_ports = ports[n_mons + 1 + n_osds:]
            directory: Dict[str, Tuple[str, int]] = {}
            for r, m in enumerate(self.mon_names):
                directory[m] = ("127.0.0.1", self.mon_ports[r])
            for name in client_names:
                directory[name] = ("127.0.0.1", self.client_port)
            for i in range(n_osds):
                directory[f"osd.{i}"] = ("127.0.0.1", self.osd_ports[i])
            for i in range(n_mds):
                directory[f"mds.{i}"] = ("127.0.0.1", self.mds_ports[i])
            self.directory = directory
            dir_json = json.dumps({k: list(v)
                                   for k, v in directory.items()})
            try:
                self._spawn(n_osds, dir_json, env, pool,
                            heartbeat_interval, heartbeat_grace,
                            down_out_interval)
                break
            except Exception as e:
                # a bind-race loser DIES (its traceback is on our
                # inherited stderr); a daemon that is alive but
                # unready timed out instead — that is a genuine
                # failure a respawn would only mask, so don't retry it
                a_daemon_died = any(p.poll() is not None
                                    for p in self.procs.values())
                if attempt or not a_daemon_died:
                    self.close()
                    raise
                print(f"ProcessCluster: spawn attempt failed with a "
                      f"dead daemon ({e}); retrying once on fresh "
                      f"ports (EADDRINUSE port-probe race)",
                      file=sys.stderr, flush=True)
                # kill whatever booted and retry on fresh ports
                for p in self.procs.values():
                    try:
                        p.kill()
                        p.wait(timeout=5)
                    except Exception:
                        pass
                self.procs.clear()
                if self.network is not None:
                    self.network.close()
                    self.network = None

    def _spawn(self, n_osds, dir_json, env, pool, heartbeat_interval,
               heartbeat_grace, down_out_interval) -> None:
        keyring_args = (["--keyring", self.keyring_path]
                        if self.keyring_path else [])
        peers_of = {m: ",".join(n for n in self.mon_names if n != m)
                    for m in self.mon_names}
        self._mon_args = {"dir_json": dir_json, "env": env,
                          "pool": pool, "n_osds": n_osds,
                          "down_out_interval": down_out_interval,
                          "keyring_args": keyring_args,
                          "peers_of": peers_of}


        # peons first (they serve the election rank 0 must win); rank 0
        # reports READY only after the initial epochs are committed
        # quorum-wide
        for r in range(1, self.n_mons):
            self._spawn_mon(r, with_pool=False)
        for r in range(1, self.n_mons):
            self._await_ready(self.mon_names[r])
        self._spawn_mon(0, with_pool=True)
        self._await_ready(self.mon_names[0])
        # spawn every osd CONCURRENTLY: a sequential boot staggers the
        # daemons' first heartbeats past the grace window and the
        # cluster marks itself down before it finishes starting
        self._osd_args = {"dir_json": dir_json, "env": env,
                          "heartbeat_interval": heartbeat_interval,
                          "heartbeat_grace": heartbeat_grace,
                          "keyring_args": keyring_args}
        for i in range(n_osds):
            self._spawn_osd(i)
        for i in range(n_osds):
            self._await_ready(f"osd.{i}")
        for i in range(self.n_mds):
            self._spawn_mds(i)
        for i in range(self.n_mds):
            # the mds waits for a healthy map + creates/opens the fs
            # pools before READY, which can take a while
            self._await_ready(f"mds.{i}", timeout=240.0)
        from .msg.tcp import TcpNetwork
        cl_auth = None
        if self.keyring_path:
            from .msg.tcp import TcpAuth
            cl_auth = TcpAuth(self.client_names[0], self.keyring_path)
        self.network = TcpNetwork(("127.0.0.1", self.client_port),
                                  self.directory, auth=cl_auth)

    def _spawn_mds(self, i: int) -> None:
        a = self._osd_args
        self.procs[f"mds.{i}"] = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.vstart", "mds",
             "--name", f"mds.{i}", "--port", str(self.mds_ports[i]),
             "--directory", a["dir_json"],
             "--mon-names", ",".join(self.mon_names),
             "--n-osds", str(self.n_osds),
             *a["keyring_args"]],
            stdout=subprocess.PIPE, text=True, cwd=REPO, env=a["env"])

    def kill_mds(self, i: int = 0) -> None:
        """kill -9 the mds daemon (the MDS failover drill)."""
        p = self.procs[f"mds.{i}"]
        p.send_signal(signal.SIGKILL)
        p.wait()

    def restart_mds(self, i: int = 0) -> None:
        """Fresh mds process on the same port: it finds the existing
        pools and REPLAYS the MDS journal."""
        old = self.procs.get(f"mds.{i}")
        if old is not None and old.poll() is None:
            raise RuntimeError(f"mds.{i} is still running")
        self._spawn_mds(i)
        self._await_ready(f"mds.{i}", timeout=240.0)

    def _await_ready(self, name: str, timeout: float = 120.0) -> None:
        import select
        proc = self.procs[name]
        r, _, _ = select.select([proc.stdout], [], [], timeout)
        if not r:
            raise RuntimeError(f"{name} did not report READY in "
                               f"{timeout}s")
        line = proc.stdout.readline()
        if line.strip() != "READY":
            raise RuntimeError(f"{name} failed to start: {line!r}")

    def client(self, name: str = "client.x",
               mon_name: Optional[str] = None):
        """Wire client; ``mon_name`` picks which mon it is bound to
        (subscriptions + wire commands — commands relay to the leader
        from any mon, so binding to a peon is fine)."""
        from .client.mon_client import MonClient
        from .client.rados import RadosClient
        return RadosClient(
            self.network,
            MonClient(self.network, mon_name or self.mon_names[0]), name)

    def _spawn_mon(self, rank: int, with_pool: bool,
                   rejoin: bool = False) -> None:
        a = self._mon_args
        name = self.mon_names[rank]
        pool = a["pool"]
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.vstart", "mon",
             "--port", str(self.mon_ports[rank]),
             "--n-osds", str(a["n_osds"]),
             "--directory", a["dir_json"],
             "--name", name, "--rank", str(rank),
             "--peers", a["peers_of"][name],
             "--mon-grace", str(self.mon_grace),
             "--mds-grace", str(self.mds_grace),
             "--down-out-interval", str(a["down_out_interval"]),
             "--pool", json.dumps(pool) if (pool and with_pool)
             else "",
             *(["--rejoin"] if rejoin else []),
             *a["keyring_args"]],
            stdout=subprocess.PIPE, text=True, cwd=REPO,
            env=a["env"])

    def restart_mon(self, rank: int) -> None:
        """Fresh mon process on the same port: boots EMPTY, rejoins
        the quorum, and is taught the committed history through the
        collect/LAST recovery (mon_thrash's revive step)."""
        old = self.procs.get(self.mon_names[rank])
        if old is not None and old.poll() is None:
            old.kill()
            old.wait()
        self._spawn_mon(rank, with_pool=False, rejoin=True)
        self._await_ready(self.mon_names[rank], timeout=120.0)

    def kill_mon(self, rank: int) -> None:
        """kill -9 a monitor daemon (the leader-failure drill)."""
        p = self.procs[self.mon_names[rank]]
        p.send_signal(signal.SIGKILL)
        p.wait()

    def wait_healthy(self, cl, timeout: float = 60.0) -> None:
        """Block until the map shows every osd up (daemons can still be
        booting/re-booting when the first client appears)."""
        end = time.monotonic() + timeout
        while time.monotonic() < end:
            self.network.pump(quiesce=0.05, deadline=0.3)
            cl.mon.send_full_map(cl.name)
            self.network.pump(quiesce=0.05, deadline=0.3)
            m = cl.osdmap
            if m.max_osd == self.n_osds and \
                    all(m.is_up(o) for o in range(self.n_osds)):
                return
            time.sleep(0.2)
        raise RuntimeError("cluster never became healthy")

    def _spawn_osd(self, i: int) -> None:
        a = self._osd_args
        data_args = ([]
                     if not self.data_root else
                     ["--data-dir",
                      os.path.join(self.data_root, f"osd.{i}")])
        self.procs[f"osd.{i}"] = subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.vstart", "osd",
             "--id", str(i), "--port", str(self.osd_ports[i]),
             "--directory", a["dir_json"],
             "--mon-names", ",".join(self.mon_names),
             "--heartbeat-interval", str(a["heartbeat_interval"]),
             "--heartbeat-grace", str(a["heartbeat_grace"]),
             *a["keyring_args"], *data_args],
            stdout=subprocess.PIPE, text=True, cwd=REPO, env=a["env"])

    def kill_osd(self, osd_id: int) -> None:
        """kill -9 the daemon process (ceph_manager.py:195)."""
        p = self.procs[f"osd.{osd_id}"]
        p.send_signal(signal.SIGKILL)
        p.wait()

    def restart_osd(self, osd_id: int) -> None:
        """Boot a fresh daemon process on the same port + data dir
        (ceph_manager.py:373 revive_osd): with a data_root, the new
        process remounts its WALStore and rejoins with its history."""
        old = self.procs.get(f"osd.{osd_id}")
        if old is not None and old.poll() is None:
            raise RuntimeError(f"osd.{osd_id} is still running")
        self._spawn_osd(osd_id)
        self._await_ready(f"osd.{osd_id}")

    def pump_for(self, seconds: float) -> None:
        """Keep the client-side socket drained while the daemons work."""
        end = time.monotonic() + seconds
        while time.monotonic() < end:
            self.network.pump(quiesce=0.05, deadline=0.3)

    def close(self) -> None:
        for p in self.procs.values():
            try:
                p.kill()
            except OSError:
                pass
        for p in self.procs.values():
            try:
                p.wait(timeout=5)
            except Exception:
                pass
        if self.network is not None:
            self.network.close()
        if self._tmpdir:
            import shutil
            shutil.rmtree(self._tmpdir, ignore_errors=True)


def main(argv=None) -> None:
    import argparse
    ap = argparse.ArgumentParser(prog="ceph_tpu.vstart")
    sub = ap.add_subparsers(dest="role", required=True)
    pm = sub.add_parser("mon")
    pm.add_argument("--port", type=int, required=True)
    pm.add_argument("--n-osds", type=int, required=True)
    pm.add_argument("--directory", required=True)
    pm.add_argument("--name", default="mon")
    pm.add_argument("--rank", type=int, default=0)
    pm.add_argument("--peers", default="")
    pm.add_argument("--mon-grace", type=float, default=0.0)
    pm.add_argument("--mds-grace", type=float, default=0.0)
    pm.add_argument("--pool", default="")
    pm.add_argument("--rejoin", action="store_true")
    pm.add_argument("--down-out-interval", type=float, default=0.0)
    pm.add_argument("--keyring", default="")
    po = sub.add_parser("osd")
    po.add_argument("--id", type=int, required=True)
    po.add_argument("--port", type=int, required=True)
    po.add_argument("--directory", required=True)
    po.add_argument("--mon-names", default="mon")
    po.add_argument("--heartbeat-interval", type=float, default=0.0)
    po.add_argument("--heartbeat-grace", type=float, default=0.0)
    po.add_argument("--keyring", default="")
    po.add_argument("--data-dir", default="")
    po.add_argument("--debug", type=int,
                    default=int(os.environ.get("VSTART_DEBUG", "0")))
    pd = sub.add_parser("mds")
    pd.add_argument("--name", default="mds.0")
    pd.add_argument("--port", type=int, required=True)
    pd.add_argument("--directory", required=True)
    pd.add_argument("--mon-names", default="mon")
    pd.add_argument("--n-osds", type=int, required=True)
    pd.add_argument("--metadata-pool", default="fsmeta")
    pd.add_argument("--data-pool", default="fsdata")
    pd.add_argument("--keyring", default="")
    args = ap.parse_args(argv)
    if args.role == "mon":
        mon_main(args)
    elif args.role == "mds":
        mds_main(args)
    else:
        osd_main(args)


if __name__ == "__main__":
    main()
