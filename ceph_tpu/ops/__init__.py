"""Device kernels (JAX/XLA/Pallas) for the compute hot paths."""
