"""Device kernels (JAX/XLA; optional Pallas variants) for the compute hot paths."""
