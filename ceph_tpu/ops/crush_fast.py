"""Candidate-table CRUSH mapper — the loop-free device fast path.

The generic loop kernel (crush_kernels.py) replicates crush_do_rule's
data-dependent retry loops directly; under vmap every lane pays for the
worst lane, which measures ~100x off the <50 ms target.  This module uses
the TPU-native formulation instead:

1. *Candidate tables* (the FLOPs): for every x and every retry index r the
   rule could consume, evaluate the full descent (root → failure domain →
   leaf) as pure batched tensor ops — rjenkins hashes plus one straw2 draw
   per level — with ALL retry lanes flattened into one (X*R) batch so the
   whole phase is a single fused walk.  Two draw implementations:

   - **exact-i32 quotient tables** (the common case): when a bucket's item
     weights are uniform (w identical, ≥ 0x10000) the reference draw
     ``div64_s64(crush_ln(u) - 2^48, w)`` is a pure function of u, so a
     per-w 64K i32 table of ``floor(G(u)/w) - 2^31`` reproduces the s64
     ordering *and* its truncation ties exactly (argmin, first index wins
     — mapper.c:322-367's strict-greater update).  Integer-exact: no
     risk analysis, no residuals.
   - **f32 + risk flags** (fallback): non-uniform weights, per-position
     weight sets (choose_args), or pathological w < 0x10000 use
     ``argmin(f32(G) * f32(1/w))`` with a conservative float-error guard;
     ambiguous lanes are flagged for exact replay.

2. *Resolution* (cheap): replay the exact firstn/indep retry semantics
   (mapper.c:443-636, :638-790) as a statically unrolled sequence of masked
   vector ops over the precomputed candidates — collision tests, weight
   rejection, slot fills.  Candidates depend only on the *topology* (bucket
   ids/weights), not on the per-epoch osd reweight vector, so they are
   cached on device across map_batch calls: an epoch change (osd out/down,
   reweight) re-runs only this phase.

3. *Residuals* (exactness escape hatch): flagged lanes — zero on
   integer-table maps, well under 1% otherwise — are recomputed with the
   bit-exact native C++ batch evaluator (Python interpreter fallback), so
   the combined result equals crush_do_rule on every input.

Scope: straw2 maps, layered hierarchies (every descent path from the take
root crosses the same bucket types at the same depths), jewel-style
tunables (stable chooseleaf for firstn; local tries 0), and single-choose
rules of the add_simple_rule shape.  Everything else falls back to the
loop kernel or the host.
"""
from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crush.constants import (
    CRUSH_ITEM_NONE, CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R, CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)
from ..arch import enable_x64
from ..crush.ln import crush_ln_np
from ..crush.mapper import crush_do_rule
from ..crush.types import CrushMap
from ..trace.devprof import g_devprof
from .crush_kernels import CompiledCrushMap, compile_map, hash32_2, hash32_3

NONE = CRUSH_ITEM_NONE


class UnsupportedRule(ValueError):
    pass


def _build_g_table() -> np.ndarray:
    """G[u] = 2^48 - crush_ln(u) for every 16-bit u (exact int64).

    The straw2 draw argmax over draw = -floor(G/w) (mapper.c:322-367)
    becomes a table gather plus a compare.
    """
    us = np.arange(0x10000, dtype=np.uint32)
    g = (np.uint64(1) << np.uint64(48)) - crush_ln_np(us)
    return g.astype(np.int64)


_G_EXACT = _build_g_table()
_G_F32 = jnp.asarray(_G_EXACT.astype(np.float64).astype(np.float32))

# conservative relative error of q = f32(G) * f32(1/w): G rounding (2^-24)
# + inv rounding (2^-24) + product rounding (2^-24) -> |q-Q|/Q <= ~3*2^-24
# per candidate; the two-candidate gap test sums both sides' bounds, so
# (q1+q2)*2^-22 covers (q1*err + q2*err) with >2x margin.
_REL_ERR = np.float32(2 ** -22)
# floor(q) ties break by index in the reference; candidates within +-TIE
# of each other could tie after truncation
_TIE_PAD = np.float32(2.0)

# minimum uniform weight eligible for the exact quotient-table path:
# floor(G_max / w) must fit the biased-i32 encoding (G_max = 2^48)
_QTABLE_MIN_W = 0x10000
_QBIAS = np.int64(1) << np.int64(31)


def _quotient_table(w: int) -> np.ndarray:
    """i32 table T[u] = floor(G(u)/w) - 2^31, order- and tie-exact.

    Valid for w >= 0x10000: quotients fit 32 unsigned bits except the
    unique u=0 entry (G=2^48, q=2^48/w may hit exactly 2^32), which is
    clamped by 1 — safe because the runner-up G is 2^48 - 2^44, far more
    than w below the clamp boundary for every w <= 2^31.
    """
    q = _G_EXACT // np.int64(w)
    q = np.minimum(q, (np.int64(1) << np.int64(32)) - 1)
    return (q - _QBIAS).astype(np.int32)


def _is_out_batch(dev_weight, items, x):
    w = dev_weight[jnp.maximum(items, 0)]
    h = hash32_2(x, items) & jnp.uint32(0xFFFF)
    return jnp.where(w >= 0x10000, False, jnp.where(w == 0, True, h >= w))


def _layer_path(m: CrushMap, root: int, target_type: int) -> int:
    """Verify the hierarchy under *root* is layered toward *target_type*;
    returns the number of choose levels needed to reach it."""
    return _layer_path_frontier(m, [root], target_type)


def _layer_path_frontier(m: CrushMap, roots: List[int],
                         target_type: int) -> int:
    depth = 0
    frontier = list(roots)
    while True:
        child_types = set()
        for b in frontier:
            bk = m.bucket(b)
            if bk is None or bk.size == 0:
                raise UnsupportedRule("empty/dangling bucket in path")
            for it in bk.items:
                if it >= 0:
                    child_types.add(0)
                else:
                    sb = m.bucket(it)
                    if sb is None:
                        raise UnsupportedRule("dangling bucket ref")
                    child_types.add(sb.type)
        if len(child_types) != 1:
            raise UnsupportedRule("mixed child types: not layered")
        ct = child_types.pop()
        depth += 1
        if ct == target_type:
            return depth
        if ct == 0:
            raise UnsupportedRule("reached devices before target type")
        next_frontier = []
        for b in frontier:
            next_frontier.extend(m.bucket(b).items)
        frontier = next_frontier
        if depth > 10:
            raise UnsupportedRule("hierarchy too deep")


def _advance(m: CrushMap, frontier: List[int]) -> List[int]:
    """One level down: the sub-buckets the frontier's draws can land in."""
    nxt: List[int] = []
    for b in frontier:
        nxt.extend(i for i in m.bucket(b).items if i < 0)
    return nxt


def _level_frontiers(m: CrushMap, root: int, n_levels: int) -> List[List[int]]:
    """Bucket-id frontier feeding each of the n_levels draws under root."""
    out = []
    frontier = [root]
    for _ in range(n_levels):
        out.append(list(frontier))
        frontier = _advance(m, frontier)
    return out


class FastRule:
    """Compiled single-choose rule: take root; choose[leaf] {firstn,indep}
    n type T; emit."""

    def __init__(self, C: CompiledCrushMap, ruleno: int, result_max: int,
                 tries_cap: int = 4, leaf_tries_cap: int = 4,
                 choose_args=None, exact64: Optional[bool] = None):
        m = C.map
        self.ruleno = ruleno
        self.choose_args = choose_args
        rule = m.rules[ruleno]
        if rule is None:
            raise UnsupportedRule(f"no rule {ruleno}")
        choose_tries = m.choose_total_tries + 1
        leaf_tries = 0
        vary_r = m.chooseleaf_vary_r
        stable = m.chooseleaf_stable
        take = None
        chooses: List = []
        for step in rule.steps:
            if step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    choose_tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    leaf_tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
                if step.arg1 >= 0:
                    vary_r = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
                if step.arg1 >= 0:
                    stable = step.arg1
            elif step.op in (CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                             CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
                if step.arg1 > 0:
                    raise UnsupportedRule("local tries")
            elif step.op == CRUSH_RULE_TAKE:
                if take is not None:
                    raise UnsupportedRule("multiple takes")
                take = step.arg1
            elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                             CRUSH_RULE_CHOOSELEAF_FIRSTN,
                             CRUSH_RULE_CHOOSE_INDEP,
                             CRUSH_RULE_CHOOSELEAF_INDEP):
                chooses.append(step)
            elif step.op == CRUSH_RULE_EMIT:
                pass
            else:
                raise UnsupportedRule(f"op {step.op}")
        if take is None or not chooses or take >= 0:
            raise UnsupportedRule("rule shape")
        # chained choose steps (set-choose.t shapes): every step but the
        # last selects buckets — resolvable from topology alone, so the
        # whole chain lives in the cached candidate phase; only the last
        # step (devices / chooseleaf) depends on the weight vector
        self.mid_stages: List[dict] = []
        if len(chooses) > 2:
            # a third step's slot room depends on the second's dynamic
            # truncation — not modeled; host fallback
            raise UnsupportedRule("more than two choose steps")
        for step in chooses[:-1]:
            if step.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                           CRUSH_RULE_CHOOSELEAF_INDEP):
                raise UnsupportedRule("chooseleaf before the last step")
            if step.arg2 == 0:
                raise UnsupportedRule("device choose before the last step")
            n = step.arg1
            if n <= 0:
                n += result_max
            if n <= 0:
                raise UnsupportedRule("numrep")
            self.mid_stages.append({
                "firstn": step.op == CRUSH_RULE_CHOOSE_FIRSTN,
                # numrep keeps the step's r spacing; the step can only
                # FILL min(numrep, result_max) slots (out_size room)
                "numrep": n, "slots": min(n, result_max),
                "type": step.arg2,
            })
        choose = chooses[-1]
        self.firstn = choose.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                    CRUSH_RULE_CHOOSELEAF_FIRSTN)
        self.leafy = choose.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                   CRUSH_RULE_CHOOSELEAF_INDEP)
        numrep = choose.arg1
        if numrep <= 0:
            numrep += result_max
        if numrep <= 0:
            raise UnsupportedRule("numrep")
        self.numrep = min(numrep, result_max) if not self.firstn else numrep
        self.target_type = choose.arg2
        if self.firstn:
            if self.leafy and not stable:
                # rep' for the leaf draw depends on the dynamic success
                # count without the stable tunable (mapper.c:545)
                raise UnsupportedRule("firstn chooseleaf needs stable=1")
        # firstn indexes weight sets by the DYNAMIC success count
        # (mapper.c:513 passes outpos as the choose_args position, and
        # outpos only advances on success) — so with per-position
        # weight sets the candidates must be materialized for every
        # position the walk could be at; resolution gathers the lane's
        # actual outpos.  indep passes the invocation's constant
        # starting outpos (0 from crush_do_rule) for main draws and
        # rep for leaf draws (mapper.c:723,777), which the pos vector
        # already threads — no extra axis needed.
        self.posP = min(C.npos, self.numrep) if self.firstn else 1
        if self.leafy:
            if leaf_tries:
                recurse = leaf_tries
            elif self.firstn:
                recurse = 1 if m.chooseleaf_descend_once else choose_tries
            else:
                recurse = 1
        else:
            recurse = 1
        self.take = take
        self.vary_r = vary_r
        self.tries = choose_tries
        self.recurse_tries = recurse
        self.n_rounds = min(tries_cap + 1, choose_tries)
        self.n_leaf = min(leaf_tries_cap + 1, recurse)
        # per-stage descent depths along the (validated layered) tree;
        # self.depth stays the TOTAL main depth so the per-level
        # quotient-table eligibility below is unchanged
        frontier = [take]
        base = 0
        self.parents = 1          # lanes per x feeding the last stage
        for st in self.mid_stages:
            # same dynamic-position treatment per stage (each choose
            # step invocation restarts outpos at 0, crush_do_rule
            # passes j=0 per parent)
            st["posP"] = min(C.npos, st["numrep"]) if st["firstn"] else 1
            d = _layer_path_frontier(m, frontier, st["type"])
            st["depth"] = d
            st["base_level"] = base
            st["tries"] = choose_tries
            st["n_rounds"] = min(tries_cap + 1, choose_tries)
            base += d
            for _ in range(d):
                frontier = _advance(m, frontier)
            self.parents *= st["slots"]
        self.base_level = base
        self.depth = base + _layer_path_frontier(m, frontier,
                                                 self.target_type)
        self.last_depth = self.depth - self.base_level
        self.leaf_depth = 0
        if self.leafy and self.target_type != 0:
            # depth below a failure-domain bucket, validated layered
            frontier = [take]
            for _ in range(self.depth):
                nxt = []
                for b in frontier:
                    nxt.extend(i for i in m.bucket(b).items)
                frontier = nxt
            if all(i >= 0 for i in frontier):
                self.leaf_depth = 0
            else:
                self.leaf_depth = _layer_path(m, frontier[0], 0)
                for f in frontier:
                    if _layer_path(m, f, 0) != self.leaf_depth:
                        raise UnsupportedRule("uneven leaf depth")
        self.C = C
        self.result_max = result_max
        self._build_quotient_tables()
        # non-quotient-table levels (non-uniform weights, choose_args,
        # small w) draw EXACTLY with the u64 table-gather + divide —
        # the same div64_s64 the loop kernel runs on device — instead
        # of the f32 approximation, killing the residual-replay tail.
        # One-time cost in the cached candidate phase; the per-epoch
        # resolve stays 32-bit.  Opt out (or auto-fallback when a
        # backend can't lower u64 divide) -> f32 + risk flags.
        if exact64 is None:
            exact64 = os.environ.get("CEPH_TPU_CRUSH_EXACT64",
                                     "1") != "0"
        self._exact64 = exact64 and not all(self._lvl_int)
        self._cand_key: Optional[bytes] = None
        self._cand = None
        self._cand_jit = jax.jit(self._candidates)
        self._resolve_jit = jax.jit(self._resolve)
        self._packed_jit = jax.jit(self._resolve_packed)
        self._delta_jit = jax.jit(self._delta, static_argnums=2)
        # per-epoch delta state: device packed result of the previous epoch
        # plus the host-side exact mirror it corresponds to
        self._prev_packed = None
        self._host_out: Optional[np.ndarray] = None
        self._host_counts: Optional[np.ndarray] = None
        self.delta_cap = 8192

    # ---- exact integer draw tables ----------------------------------------
    def _build_quotient_tables(self) -> None:
        """Per-level eligibility + shared per-w i32 quotient tables.

        A level draws with exact integer tables iff every bucket its
        frontier can present has uniform item weights >= _QTABLE_MIN_W and
        no per-position weight set overrides them.
        """
        m = self.C.map
        n_main = self.depth
        n_leaf_lvls = self.leaf_depth if self.leaf_depth else (
            1 if (self.leafy and self.target_type != 0) else 0)
        frontiers = _level_frontiers(m, self.take, n_main)
        if n_leaf_lvls:
            # leaf levels start below every failure-domain bucket
            fd_buckets = _level_frontiers(m, self.take, n_main + 1)[n_main]
            # merge frontiers across all failure-domain roots per level
            merged: List[List[int]] = [[] for _ in range(n_leaf_lvls)]
            for fd in fd_buckets:
                for li, lvl in enumerate(
                        _level_frontiers(m, fd, n_leaf_lvls)):
                    merged[li].extend(lvl)
            frontiers = frontiers + merged
        self.total_levels = len(frontiers)

        w_to_idx = {}
        tables: List[np.ndarray] = []
        nb = self.C.nbuckets
        bucket_qidx = np.zeros(nb, dtype=np.int32)
        lvl_int: List[bool] = []
        # any choose_args disables the integer path: weight_set entries
        # override item_weights even with a single position (npos==1),
        # and the quotient tables are built from raw topology weights
        use_pos_weights = self.C.npos > 1 or self.choose_args is not None
        for lvl in frontiers:
            ok = not use_pos_weights
            for bid in lvl:
                b = m.bucket(bid)
                ws = list(b.item_weights)
                if not ws or min(ws) != max(ws) or ws[0] < _QTABLE_MIN_W:
                    ok = False
                    break
            if ok:
                for bid in lvl:
                    b = m.bucket(bid)
                    w = int(b.item_weights[0])
                    if w not in w_to_idx:
                        w_to_idx[w] = len(tables)
                        tables.append(_quotient_table(w))
                    bucket_qidx[-1 - bid] = w_to_idx[w]
            lvl_int.append(ok)
        self._lvl_int = lvl_int
        if tables:
            self._qtables = jnp.asarray(np.stack(tables))
            self._bucket_qidx = jnp.asarray(bucket_qidx)
        else:
            self._qtables = None
            self._bucket_qidx = None

    # ---- device draws ------------------------------------------------------
    def _straw2_int(self, bidx, x, r):
        """Exact integer straw2 via the quotient table: argmin with
        first-index tie-break == the reference's strict-greater update."""
        C = self.C
        ids = C.hash_ids[bidx]                   # (N, S)
        u = hash32_3(x[:, None], ids, r[:, None]) & jnp.uint32(0xFFFF)
        q = self._qtables[self._bucket_qidx[bidx][:, None],
                          u.astype(jnp.int32)]  # (N, S)
        valid = C.lane[None, :] < C.sizes[bidx][:, None]
        q = jnp.where(valid, q, jnp.int32(0x7FFFFFFF))
        win = jnp.argmin(q, axis=1)
        items = jnp.take_along_axis(C.items[bidx], win[:, None], axis=1)[:, 0]
        return items, jnp.zeros(x.shape, dtype=bool)

    def _straw2_exact64(self, bidx, x, r, pos):
        """Bit-exact straw2 for arbitrary (incl. per-position) weights:
        q = (2^48 - crush_ln(u)) // w in integer 64-bit, argmin with
        first-index tie-break == mapper.c:322-367's strict-greater
        update over div64_s64 draws.  Requires an enable_x64 trace
        scope (prepare_candidates provides it)."""
        C = self.C
        ids = C.hash_ids[bidx]                   # (N, S)
        w = C.weights[jnp.minimum(pos, C.npos - 1), bidx]  # (N, S) u32
        u = hash32_3(x[:, None], ids, r[:, None]) & jnp.uint32(0xFFFF)
        # constant converted at use site so the int64 table survives
        # only inside the x64 trace (crush_kernels.py's convention)
        g = jnp.asarray(_G_EXACT)[u.astype(jnp.int32)]
        valid = (C.lane[None, :] < C.sizes[bidx][:, None]) & (w > 0)
        q = jnp.where(valid,
                      g // jnp.maximum(w, 1).astype(jnp.int64),
                      jnp.int64(1) << jnp.int64(62))
        win = jnp.argmin(q, axis=1)
        items = jnp.take_along_axis(C.items[bidx], win[:, None],
                                    axis=1)[:, 0]
        return items, jnp.zeros(x.shape, dtype=bool)

    def _straw2_f32(self, bidx, x, r, pos):
        """f32 draw with exactness guard: lanes whose top-two draws are
        within the float error bound (or the integer floor-tie window) get
        risky=True and are re-evaluated exactly by the caller."""
        C = self.C
        ids = C.hash_ids[bidx]                   # (N, S)
        invw = C.inv_weights[jnp.minimum(pos, C.npos - 1), bidx]  # (N, S)
        u = hash32_3(x[:, None], ids, r[:, None]) & jnp.uint32(0xFFFF)
        g = _G_F32[u.astype(jnp.int32)]
        valid = (C.lane[None, :] < C.sizes[bidx][:, None]) & (invw > 0)
        q = jnp.where(valid, g * invw, jnp.float32(np.inf))
        win = jnp.argmin(q, axis=1)
        q1 = jnp.min(q, axis=1)
        q2 = jnp.min(jnp.where(jax.nn.one_hot(win, q.shape[1], dtype=bool),
                               jnp.float32(np.inf), q), axis=1)
        finite1 = jnp.isfinite(q1)
        finite2 = jnp.isfinite(q2)
        risky = finite1 & finite2 & \
            ((q2 - q1) <= (q1 + q2) * _REL_ERR + _TIE_PAD)
        items = jnp.take_along_axis(C.items[bidx], win[:, None], axis=1)[:, 0]
        return items, risky

    def _descend(self, x, start_bidx, r, pos, base_level: int, depth: int):
        """Fixed-depth descent for a flat batch of lanes: (N,) bucket idx
        -> (N,) item at the target layer, plus the accumulated
        exactness-risk flag.  r is constant through the walk
        (mapper.c:498-520); each level statically picks the integer or f32
        draw."""
        item = None
        bidx = start_bidx
        risky = jnp.zeros(x.shape, dtype=bool)
        for d in range(depth):
            if self._lvl_int[base_level + d]:
                item, rk = self._straw2_int(bidx, x, r)
            elif self._exact64:
                item, rk = self._straw2_exact64(bidx, x, r, pos)
            else:
                item, rk = self._straw2_f32(bidx, x, r, pos)
            risky = risky | rk
            bidx = jnp.maximum(-1 - item, 0)
        return item, risky

    # ---- intermediate (bucket-choosing) stages ----------------------------
    def _mid_candidates(self, st: dict, xl, roots, valid):
        """Candidates + collision-only resolution for one intermediate
        choose step over N parent lanes: returns sel (N, numrep) items
        (NONE-filled for invalid/failed), risky (N,)."""
        N = xl.shape[0]
        n = st["numrep"]
        slots = st["slots"]
        rounds = st["n_rounds"]
        P = st.get("posP", 1)
        if st["firstn"]:
            R = n + rounds - 1
        else:
            R = n * rounds
        r_col = jnp.arange(R, dtype=jnp.uint32)
        if P > 1:
            # per-position candidates: the draw at retry r depends on
            # which weight_set position (the dynamic outpos) it runs at
            xf = jnp.broadcast_to(xl[None, None, :], (R, P, N)).reshape(-1)
            rf = jnp.broadcast_to(r_col[:, None, None],
                                  (R, P, N)).reshape(-1)
            bf = jnp.broadcast_to(roots[None, None, :],
                                  (R, P, N)).reshape(-1)
            pf = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.int32)[None, :, None],
                (R, P, N)).reshape(-1)
            item, risky_f = self._descend(xf, bf, rf, pf,
                                          st["base_level"], st["depth"])
            cand = item.reshape(R, P, N)
            risky = jnp.any(risky_f.reshape(R, P, N), axis=(0, 1))
        else:
            xf = jnp.broadcast_to(xl[None, :], (R, N)).reshape(-1)
            rf = jnp.broadcast_to(r_col[:, None], (R, N)).reshape(-1)
            bf = jnp.broadcast_to(roots[None, :], (R, N)).reshape(-1)
            pos0 = jnp.zeros((R * N,), dtype=jnp.int32)
            item, risky_f = self._descend(xf, bf, rf, pos0,
                                          st["base_level"], st["depth"])
            cand = item.reshape(R, N)
            risky = jnp.any(risky_f.reshape(R, N), axis=0)
        lanes = jnp.arange(N)
        if st["firstn"]:
            # all numrep ATTEMPTS run (slot = attempt; the reference's
            # outpos append == stable compaction); the room truncation
            # to `slots` happens at fan-out below
            outs = jnp.full((N, n), NONE, dtype=jnp.int32)
            for j in range(n):
                if P > 1:
                    # outpos == successes so far == filled slots < j
                    pos = jnp.minimum(jnp.sum(outs != NONE, axis=1),
                                      P - 1)
                done = jnp.zeros((N,), dtype=bool)
                for ftotal in range(rounds):
                    c_r = cand[j + ftotal]
                    item = c_r[pos, lanes] if P > 1 else c_r
                    coll = jnp.any(outs == item[:, None], axis=1)
                    take = ~coll & ~done
                    outs = outs.at[:, j].set(
                        jnp.where(take, item, outs[:, j]))
                    done = done | ~coll
                if rounds < st["tries"]:
                    risky = risky | ~done
            # firstn feeds the next step COMPACTLY (wsize entries)
            order = jnp.argsort((outs == NONE).astype(jnp.int32),
                                axis=1, stable=True)
            outs = jnp.take_along_axis(outs, order, axis=1)[:, :slots]
        else:
            UNDEF = jnp.int32(0x7FFFFFFE)
            outs = jnp.full((N, slots), UNDEF, dtype=jnp.int32)
            for ftotal in range(rounds):
                for rep in range(slots):
                    item = cand[rep + n * ftotal]
                    unfilled = outs[:, rep] == UNDEF
                    coll = jnp.any(outs == item[:, None], axis=1)
                    take = unfilled & ~coll
                    outs = outs.at[:, rep].set(
                        jnp.where(take, item, outs[:, rep]))
            if rounds < st["tries"]:
                risky = risky | jnp.any(outs == UNDEF, axis=1)
            outs = jnp.where(outs == UNDEF, NONE, outs)
        outs = jnp.where(valid[:, None], outs, NONE)
        return outs, risky

    # ---- candidate phase (topology-only; cached across epochs) -------------
    def _candidates(self, xs):
        """One flattened descent over all (x, parent, retry) lanes.

        Returns cand (R, N) failure-domain items, leaf (R, L, N) leaf
        items (all-NONE when not leafy), risky (X,), valid (N,), and the
        per-lane x vector (N,), where N = X * parents (the intermediate
        stages' fan-out; 1 for single-choose rules)."""
        x = xs.astype(jnp.uint32)
        X = xs.shape[0]
        xl = x
        roots = jnp.full((X,), -1 - self.take, dtype=jnp.int32)
        valid = jnp.ones((X,), dtype=bool)
        risky_lanes = jnp.zeros((X,), dtype=bool)
        for st in self.mid_stages:
            sel, rk = self._mid_candidates(st, xl, roots, valid)
            risky_lanes = risky_lanes | rk
            n = st["slots"]
            # expand lanes: each parent slot becomes a lane
            risky_lanes = jnp.repeat(risky_lanes, n)
            xl = jnp.repeat(xl, n)
            valid = (jnp.repeat(valid, n)) & (sel.reshape(-1) != NONE)
            roots = jnp.maximum(-1 - sel.reshape(-1), 0)
        N = X * self.parents
        P = self.posP
        if self.firstn:
            R = self.numrep + self.n_rounds - 1
        else:
            R = self.numrep * self.n_rounds
        r_col = jnp.arange(R, dtype=jnp.uint32)
        if P > 1:
            # firstn + per-position weight sets: the draw at retry r
            # depends on the dynamic outpos (see __init__) — flatten a
            # position axis into the descent; resolution gathers the
            # lane's actual position.  cand (R, P, N), leaf (R, L, P, N).
            xf = jnp.broadcast_to(xl[None, None, :], (R, P, N)).reshape(-1)
            rf = jnp.broadcast_to(r_col[:, None, None],
                                  (R, P, N)).reshape(-1)
            root = jnp.broadcast_to(roots[None, None, :],
                                    (R, P, N)).reshape(-1)
            pf = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.int32)[None, :, None],
                (R, P, N)).reshape(-1)
        else:
            xf = jnp.broadcast_to(xl[None, :], (R, N)).reshape(-1)
            rf = jnp.broadcast_to(r_col[:, None], (R, N)).reshape(-1)
            root = jnp.broadcast_to(roots[None, :], (R, N)).reshape(-1)
            pf = jnp.zeros((R * N,), dtype=jnp.int32)
        item, risky_f = self._descend(xf, root, rf, pf,
                                      self.base_level, self.last_depth)
        rk_main = None
        if P > 1:
            # per-draw risk, NOT folded: resolution flags a lane only
            # when a draw it actually EXAMINES (at its dynamic
            # position) was risky — flagging any-position risk would
            # replay ~P times more lanes than necessary
            rk_main = risky_f.reshape(R, P, N)
            cand = item.reshape(R, P, N)
        else:
            risky_lanes = risky_lanes | jnp.any(risky_f.reshape(R, N),
                                                axis=0)
            cand = item.reshape(R, N)

        def finish(leaf, risky_lanes, rk_leaf=None):
            if P > 1:
                # lane-level mid-stage risk + the per-draw tensors
                return (cand, leaf,
                        (risky_lanes, rk_main, rk_leaf), valid, xl)
            risky = jnp.any(risky_lanes.reshape(-1, self.parents), axis=1)
            return cand, leaf, risky, valid, xl

        L = self.n_leaf
        lshape = (R, L, P, N) if P > 1 else (R, L, N)
        zero_lrisk = (jnp.zeros(lshape, dtype=bool) if P > 1 else None)
        if not self.leafy:
            return finish(jnp.full(lshape, NONE, dtype=jnp.int32),
                          risky_lanes, zero_lrisk)
        if self.leaf_depth == 0 and self.target_type == 0:
            # chooseleaf over devices: every leaf attempt is the item itself
            if P > 1:
                # the "leaf draw" IS the main draw: its risk too
                return finish(
                    jnp.broadcast_to(cand[:, None, :, :], lshape),
                    risky_lanes,
                    jnp.broadcast_to(rk_main[:, None, :, :], lshape))
            return finish(jnp.broadcast_to(cand[:, None, :], lshape),
                          risky_lanes)
        # leaf attempts: one flattened batch over lshape
        M = R * P * N if P > 1 else R * N
        if self.firstn:
            sub_r = (rf >> jnp.uint32(self.vary_r - 1)) if self.vary_r \
                else jnp.zeros_like(rf)
            # leaf draw position = the parent step's outpos
            # (mapper.c:561-562: the recursion inherits outpos, and the
            # leaf bucket_choose passes it) — the materialized p axis
            lpos = pf
        else:
            rep = rf % jnp.uint32(self.numrep)
            sub_r = rep + rf  # + numrep*ft2 added per attempt below
            lpos = rep.astype(jnp.int32)
        bidx = jnp.maximum(-1 - item, 0)
        depth = self.leaf_depth if self.leaf_depth else 1
        xleaf = jnp.broadcast_to(xf[None, :], (L, M)).reshape(-1)
        bl = jnp.broadcast_to(bidx[None, :], (L, M)).reshape(-1)
        pl = jnp.broadcast_to(lpos[None, :], (L, M)).reshape(-1)
        ft2 = jnp.arange(L, dtype=jnp.uint32)
        if self.firstn:
            rl = (sub_r[None, :] + ft2[:, None]).reshape(-1)
        else:
            rl = (sub_r[None, :] +
                  jnp.uint32(self.numrep) * ft2[:, None]).reshape(-1)
        lv, lrisky = self._descend(xleaf, bl, rl, pl, self.depth, depth)
        if P > 1:
            leaf = jnp.transpose(lv.reshape(L, R, P, N), (1, 0, 2, 3))
            rk_leaf = jnp.transpose(lrisky.reshape(L, R, P, N),
                                    (1, 0, 2, 3))
            return finish(leaf, risky_lanes, rk_leaf)
        risky_lanes = risky_lanes | jnp.any(lrisky.reshape(L, R, N),
                                            axis=(0, 1))
        leaf = jnp.transpose(lv.reshape(L, R, N), (1, 0, 2))
        return finish(leaf, risky_lanes)

    # ---- resolution phase (per weight vector; cheap) -----------------------
    def _resolve(self, cand, leaf, risky, valid, xl, x, dev_weight):
        """Per-lane resolution: sel (N, numrep) plus residual (X,) —
        a lane's unresolved state rolls up to its x, which replays on
        the host whole."""
        rk_main = rk_leaf = None
        if self.posP > 1:
            risky_lanes, rk_main, rk_leaf = risky
        else:
            risky_lanes = jnp.repeat(risky, self.parents)
        if self.firstn:
            sel, lres = self._resolve_firstn(cand, leaf, risky_lanes,
                                             xl, dev_weight,
                                             rk_main, rk_leaf)
        else:
            # per-parent slot room (crush_do_rule: out_size =
            # min(numrep, result_max - osize), osize advancing only
            # over present parents): slots past the room are never
            # filled by the reference, so retries must not see them
            # as collision targets
            vp = valid.reshape(-1, self.parents).astype(jnp.int32)
            vbefore = jnp.cumsum(vp, axis=1) - vp
            room = jnp.clip(self.result_max - vbefore * self.numrep,
                            0, self.numrep).reshape(-1)
            sel, lres = self._resolve_indep(cand, leaf, risky_lanes,
                                            xl, dev_weight, room)
        sel = jnp.where(valid[:, None], sel, NONE)
        lres = lres & valid
        if self.posP > 1:
            # mid-stage risk must survive even on INVALID lanes (their
            # NONE may itself be the wrong answer), so OR the unmasked
            # lane-level risk back in before the per-x rollup
            residual = jnp.any(
                (lres | risky_lanes).reshape(-1, self.parents), axis=1)
        else:
            residual = risky | jnp.any(
                lres.reshape(-1, self.parents), axis=1)
        return sel, residual

    def _resolve_firstn(self, cand, leaf, risky, x, dev_weight,
                        rk_main=None, rk_leaf=None):
        """firstn: slot j retries r = j + ftotal (mapper.c:493-495); leafy
        failures consume an outer retry (descend_once semantics).

        With per-position weight sets (posP > 1) the candidate arrays
        carry a position axis and each lane gathers at its dynamic
        outpos — the success count so far (mapper.c:513/620-621:
        position == outpos, advancing only on success)."""
        P = self.posP
        if P > 1:
            R = cand.shape[0]
            X = cand.shape[2]
        else:
            R, X = cand.shape
        lanes = jnp.arange(X)
        numrep = self.numrep
        x = x.astype(jnp.uint32)
        residual = risky
        outs = jnp.full((X, numrep), NONE, dtype=jnp.int32)
        leaves = jnp.full((X, numrep), NONE, dtype=jnp.int32)
        for j in range(numrep):
            if P > 1:
                pos = jnp.minimum(jnp.sum(outs != NONE, axis=1), P - 1)
            done = jnp.zeros((X,), dtype=bool)
            for ftotal in range(self.n_rounds):
                r = j + ftotal
                item = cand[r][pos, lanes] if P > 1 else cand[r]
                rdraw = rk_main[r][pos, lanes] if P > 1 else None
                coll = jnp.any(outs == item[:, None], axis=1)
                if self.leafy:
                    # first acceptable leaf attempt, if any
                    lok = jnp.zeros((X,), dtype=bool)
                    lsel = jnp.full((X,), NONE, dtype=jnp.int32)
                    lres = jnp.zeros((X,), dtype=bool)
                    for ft2 in range(self.n_leaf):
                        lf = leaf[r, ft2][pos, lanes] if P > 1 \
                            else leaf[r, ft2]
                        if P > 1:
                            rdraw = rdraw | rk_leaf[r, ft2][pos, lanes]
                        lcoll = jnp.any(leaves == lf[:, None], axis=1)
                        lrej = _is_out_batch(dev_weight, lf, x)
                        good = ~lok & ~lcoll & ~lrej
                        lsel = jnp.where(good, lf, lsel)
                        lok = lok | good
                    # couldn't prove failure within the cap?
                    if self.n_leaf < self.recurse_tries:
                        lres = ~lok
                    ok = ~coll & lok
                    maybe_more = lres
                else:
                    rej = (_is_out_batch(dev_weight, item, x)
                           if self.target_type == 0
                           else jnp.zeros((X,), dtype=bool))
                    ok = ~coll & ~rej
                    lsel = item
                    maybe_more = jnp.zeros((X,), dtype=bool)
                if rdraw is not None:
                    # a risky draw EXAMINED at this lane's position
                    # taints everything from here on
                    residual = residual | (rdraw & ~done)
                take = ok & ~done & ~residual
                outs = outs.at[:, j].set(jnp.where(take, item, outs[:, j]))
                leaves = leaves.at[:, j].set(
                    jnp.where(take, lsel, leaves[:, j]))
                residual = residual | (maybe_more & ~done)
                done = done | ok
            # not done within the materialized rounds, but the reference
            # would keep trying -> must defer to the host
            if self.n_rounds < self.tries:
                residual = residual | ~done
        sel = leaves if self.leafy else outs
        return sel, residual

    def _resolve_indep(self, cand, leaf, risky, x, dev_weight,
                       room=None):
        """indep rounds: r = rep + numrep*ftotal; UNDEF slots retry,
        dead ends become NONE (mapper.c:638-790).  *room* (per-lane)
        caps how many slots this parent may fill when the result is
        narrower than parents*numrep."""
        R, X = cand.shape
        numrep = self.numrep
        x = x.astype(jnp.uint32)
        UNDEF = jnp.int32(0x7FFFFFFE)  # CRUSH_ITEM_UNDEF; never a real item
        outs = jnp.full((X, numrep), UNDEF, dtype=jnp.int32)
        leaves = jnp.full((X, numrep), UNDEF, dtype=jnp.int32)
        residual = risky
        for ftotal in range(self.n_rounds):
            for rep in range(numrep):
                r = rep + numrep * ftotal
                item = cand[r]
                unfilled = outs[:, rep] == UNDEF
                if room is not None:
                    unfilled = unfilled & (jnp.int32(rep) < room)
                coll = jnp.any(outs == item[:, None], axis=1)
                if self.leafy:
                    lok = jnp.zeros((X,), dtype=bool)
                    lsel = jnp.full((X,), NONE, dtype=jnp.int32)
                    for ft2 in range(self.n_leaf):
                        lf = leaf[r, ft2]
                        lrej = _is_out_batch(dev_weight, lf, x)
                        good = ~lok & ~lrej
                        lsel = jnp.where(good, lf, lsel)
                        lok = lok | good
                    if self.n_leaf < self.recurse_tries:
                        residual = residual | (unfilled & ~coll & ~lok)
                    ok = ~coll & lok
                else:
                    rej = (_is_out_batch(dev_weight, item, x)
                           if self.target_type == 0
                           else jnp.zeros((X,), dtype=bool))
                    ok = ~coll & ~rej
                    lsel = item
                take = unfilled & ok
                outs = outs.at[:, rep].set(
                    jnp.where(take, item, outs[:, rep]))
                leaves = leaves.at[:, rep].set(
                    jnp.where(take, lsel, leaves[:, rep]))
        undef = outs == UNDEF
        if room is not None:
            undef = undef & (jnp.arange(numrep)[None, :] < room[:, None])
        unfinished = jnp.any(undef, axis=1)
        if self.n_rounds < self.tries:
            residual = residual | unfinished
        outs = jnp.where(outs == UNDEF, NONE, outs)
        leaves = jnp.where(leaves == UNDEF, NONE, leaves)
        sel = leaves if self.leafy else outs
        return sel, residual

    # ---- device-side compaction + delta fetch ------------------------------
    def _resolve_packed(self, cand, leaf, risky, valid, xl, x, dev_weight):
        """Resolve, compact and pack ON DEVICE: one (X, result_max+1) i32.

        Columns [0, result_max) are the compacted result slots (EMIT
        semantics: firstn drops NONE gaps in slot order, indep keeps
        holes within a parent's block but drops absent parents' blocks);
        the last column is ``count | residual << 16``.  A single small
        array means the per-epoch host fetch is one transfer — the
        tunnel/PCIe round trip, not the resolve, is the remap wall floor.
        """
        sel, residual = self._resolve(cand, leaf, risky, valid, xl, x,
                                      dev_weight)
        P = self.parents
        X = sel.shape[0] // P
        R = self.result_max
        nr = self.numrep
        if self.firstn:
            # per-parent picks concatenate compactly in the reference
            # (outpos appends): a stable global compaction of the
            # (P*numrep)-wide row is the same sequence
            wide = sel.reshape(X, P * nr)
            order = jnp.argsort((wide == NONE).astype(jnp.int32), axis=1,
                                stable=True)
            compact = jnp.take_along_axis(wide, order, axis=1)
            if compact.shape[1] < R:
                compact = jnp.pad(compact,
                                  ((0, 0), (0, R - compact.shape[1])),
                                  constant_values=NONE)
            out = compact[:, :R]
            counts = jnp.minimum(jnp.sum(wide != NONE, axis=1), R)
        else:
            # indep keeps holes, but a parent that was never chosen
            # contributes NOTHING (crush_do_rule skips absent buckets):
            # drop absent parents' blocks, keep block order stable
            sel3 = sel.reshape(X, P, nr)
            vp = valid.reshape(X, P)
            order = jnp.argsort((~vp).astype(jnp.int32), axis=1,
                                stable=True)
            sel3 = jnp.take_along_axis(sel3, order[:, :, None], axis=1)
            wide = sel3.reshape(X, P * nr)
            if wide.shape[1] < R:
                wide = jnp.pad(wide, ((0, 0), (0, R - wide.shape[1])),
                               constant_values=NONE)
            out = wide[:, :R]
            counts = jnp.minimum(
                jnp.sum(vp, axis=1, dtype=jnp.int32) * nr, R)
        tail = counts.astype(jnp.int32) | (residual.astype(jnp.int32) << 16)
        return jnp.concatenate([out, tail[:, None]], axis=1)

    def _delta(self, packed, prev, cap: int):
        """Changed-row extraction vs the previous epoch's packed result.

        A row is "changed" if any packed column differs OR either epoch
        flagged it residual (a residual row's device value is a guess; its
        exact value can move even when the guess doesn't, so it must be
        replayed whenever the weight vector changes).  Returns one flat
        i32 buffer [n_changed, n_residual, idx[cap], rows[cap * (R+1)]]
        so the whole per-epoch result is a single device->host transfer.
        """
        R = self.result_max
        res_new = (packed[:, R] >> 16) != 0
        res_prev = (prev[:, R] >> 16) != 0
        changed = jnp.any(packed != prev, axis=1) | res_new | res_prev
        n = jnp.sum(changed, dtype=jnp.int32)
        idx = jnp.nonzero(changed, size=cap, fill_value=0)[0]
        rows = packed[idx]
        return jnp.concatenate([
            jnp.stack([n, jnp.sum(res_new, dtype=jnp.int32)]),
            idx.astype(jnp.int32),
            rows.reshape(-1),
        ])

    def _replay_exact(self, idxs: np.ndarray, xs: np.ndarray,
                      weight, out: np.ndarray, counts: np.ndarray) -> None:
        """Overwrite the given lanes with the bit-exact mapping (native
        C++ batch evaluator; Python interpreter fallback)."""
        if len(idxs) == 0:
            return
        w32 = np.asarray(weight, dtype=np.uint32)
        try:
            nm = self._native_mapper()
            rout, rlens = nm.do_rule_batch(
                self.ruleno, xs[idxs].astype(np.int64),
                self.result_max, w32)
            out[idxs] = np.where(
                np.arange(self.result_max)[None, :] < rlens[:, None],
                rout.astype(np.int32), NONE)
            counts[idxs] = rlens
            return
        except Exception:
            pass
        m = self.C.map
        wl = [int(v) for v in w32]
        for i in idxs:
            res = crush_do_rule(m, self.ruleno, int(xs[i]),
                                self.result_max, wl, self.choose_args)
            out[i, :] = NONE
            out[i, :len(res)] = res
            counts[i] = len(res)

    # ---- public -----------------------------------------------------------
    def prepare_candidates(self, xs: np.ndarray) -> None:
        """Compute (or reuse) the device candidate tables for this xs
        batch.  Topology-only: reused across weight vectors/epochs."""
        xs = np.asarray(xs, dtype=np.uint32)
        key = hashlib.sha1(xs.tobytes()).digest()
        if self._cand_key != key:
            g_devprof.install_compile_listener()
            g_devprof.account_h2d("crush.candidates", xs.nbytes)
            with g_devprof.stage("crush.candidates"):
                xd = jnp.asarray(xs)
                self._cand = jax.block_until_ready(
                    self._run_candidates(xd))
            self._cand_x = xd
            self._cand_key = key
            self._prev_packed = None
            self._host_out = None
            self._host_counts = None

    def _run_candidates(self, xd):
        """The candidate trace; exact64 draws need an x64 scope.  A
        backend that cannot lower the u64 divide drops to the f32 +
        risk-flag draw (correctness preserved via residual replay)."""
        if not self._exact64:
            return self._cand_jit(xd)
        try:
            with enable_x64():
                return self._cand_jit(xd)
        except Exception as e:
            # only an UNIMPLEMENTED-class lowering failure means the
            # backend can't do u64 divide; transient transport errors
            # must propagate or they'd silently downgrade exactness
            msg = str(e)
            if not any(s in msg for s in ("UNIMPLEMENTED",
                                          "Unimplemented",
                                          "not supported",
                                          "Unsupported")):
                raise
            from ..common.dout import dlog
            dlog("crush", 0,
                 "exact64 draw unavailable on this backend "
                 f"({type(e).__name__}); falling back to f32 + "
                 "residual replay")
            self._exact64_fallback = msg[:200]
            self._exact64 = False
            self._cand_jit = jax.jit(self._candidates)  # fresh trace
            return self._cand_jit(xd)

    def resolve_device(self, weight) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Device-resident resolution against the cached candidates:
        (sel, residual) device arrays.  The per-epoch remap call —
        requires prepare_candidates/map_batch to have run for the batch.
        Not exact on its own: residual lanes still need host replay."""
        if self._cand is None:
            raise RuntimeError("no candidate tables; call "
                               "prepare_candidates(xs) first")
        if isinstance(weight, jnp.ndarray):
            wd = weight
        else:
            w32 = np.asarray(weight, dtype=np.uint32)
            g_devprof.account_h2d("crush.resolve", w32.nbytes)
            wd = jnp.asarray(w32)
        with g_devprof.stage("crush.resolve"):
            return self._resolve_jit(*self._cand, self._cand_x, wd)

    def map_batch(self, xs: np.ndarray, weight: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """Map every x; exact.  Returns (results [X, numrep], counts [X]).

        Candidates are cached on device keyed by the xs batch: calling
        again with the same xs (the whole-map remap on every epoch) only
        re-runs the cheap resolution phase with the new weight vector.
        """
        xs = np.asarray(xs, dtype=np.uint32)
        w32 = np.asarray(weight, dtype=np.uint32)
        self.prepare_candidates(xs)
        R = self.result_max
        X = xs.shape[0]
        g_devprof.account_h2d("crush.map_batch", w32.nbytes)
        wd = jnp.asarray(w32)
        from ..common.kernel_trace import g_kernel_timer
        with g_devprof.stage("crush.map_batch"):
            packed = g_kernel_timer.timed(
                "crush_resolve", self._packed_jit, *self._cand,
                self._cand_x, wd)
        cap = min(self.delta_cap, X)
        if self._prev_packed is not None and self._host_out is not None:
            # per-epoch fast path: fetch only the rows that changed since
            # the previous weight vector (plus residual guesses, which
            # must be re-verified) and patch the host mirror in place.
            with g_devprof.stage("crush.map_batch"):
                flat = np.asarray(self._delta_jit(packed,
                                                  self._prev_packed,
                                                  cap))
            g_devprof.account_d2h("crush.map_batch", flat.nbytes)
            n_changed = int(flat[0])
            self._residual_frac = int(flat[1]) / X
            if n_changed <= cap:
                out, counts = self._host_out, self._host_counts
                if n_changed:
                    idxs = flat[2:2 + n_changed].copy()
                    rows = flat[2 + cap:].reshape(cap, R + 1)[:n_changed]
                    out[idxs] = rows[:, :R]
                    counts[idxs] = rows[:, R] & 0xFFFF
                    replay = idxs[(rows[:, R] >> 16) != 0]
                    self._replay_exact(replay, xs, w32, out, counts)
                self._prev_packed = packed
                return out.copy(), counts.copy()
            # overflow: fall through to a full fetch (and grow the cap so
            # sustained churny workloads stop overflowing)
            self.delta_cap = min(2 * self.delta_cap, max(X, 1))
        full = np.asarray(packed)
        g_devprof.account_d2h("crush.map_batch", full.nbytes)
        out = full[:, :R].copy()
        counts = (full[:, R] & 0xFFFF).astype(np.int32)
        residual = (full[:, R] >> 16) != 0
        # exactness escape hatch: recompute flagged lanes exactly.  The
        # C++ batch evaluator replays them ~100x faster than the Python
        # interpreter (OSDMapMapping.h:17's ParallelPGMapper role),
        # choose_args included (serialized into the blob); Python only
        # when the native lib is absent.
        self._residual_frac = float(residual.mean())
        self._replay_exact(np.nonzero(residual)[0], xs, w32, out, counts)
        self._prev_packed = packed
        self._host_out = out
        self._host_counts = counts
        return out.copy(), counts.copy()

    def _native_mapper(self):
        nm = getattr(self, "_nm", None)
        if nm is None:
            from ..native import NativeCrushMapper
            nm = self._nm = NativeCrushMapper(self.C.map,
                                              self.choose_args)
        return nm

    @property
    def residual_fraction(self) -> float:
        return getattr(self, "_residual_frac", 0.0)

    @property
    def integer_exact_levels(self) -> List[bool]:
        """Per-level flag: True = draws use the exact i32 quotient table."""
        return list(self._lvl_int)


def compile_fast_rule(m: CrushMap, ruleno: int, result_max: int,
                      choose_args=None, **kw) -> FastRule:
    C = compile_map(m, choose_args)
    return FastRule(C, ruleno, result_max, choose_args=choose_args, **kw)
