"""Candidate-table CRUSH mapper — the loop-free device fast path.

The generic loop kernel (crush_kernels.py) replicates crush_do_rule's
data-dependent retry loops directly; under vmap every lane pays for the
worst lane, which measures ~100x off the <50 ms target.  This module uses
the TPU-native formulation instead:

1. *Candidate tables* (the FLOPs): for every x and every retry index r the
   rule could consume, evaluate the full descent (root → failure domain →
   leaf) as pure batched tensor ops — rjenkins hashes, crush_ln LUT gathers
   and the fixed-point divide over (X, R, fanout) lanes, argmin-reduced.
   No loops, no lane divergence; this is where the device wins.
2. *Resolution* (cheap): replay the exact firstn/indep retry semantics
   (mapper.c:443-636, :638-790) as a statically unrolled sequence of masked
   vector ops over the precomputed candidates — collision tests, weight
   rejection, slot fills.  A bounded number of retries is materialized;
   any lane that would need more is flagged.
3. *Residuals* (exactness escape hatch): flagged lanes — typically well
   under 1% — are recomputed with the bit-exact host interpreter, so the
   combined result equals crush_do_rule on every input.

Scope: straw2 maps, layered hierarchies (every descent path from the take
root crosses the same bucket types at the same depths), jewel-style
tunables (stable chooseleaf for firstn; local tries 0), and single-choose
rules of the add_simple_rule shape.  Everything else falls back to the
loop kernel or the host.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crush.constants import (
    CRUSH_ITEM_NONE, CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R, CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)
from ..crush.ln import crush_ln_np
from ..crush.mapper import crush_do_rule
from ..crush.types import CrushMap
from .crush_kernels import CompiledCrushMap, compile_map, hash32_2, hash32_3

NONE = CRUSH_ITEM_NONE


class UnsupportedRule(ValueError):
    pass


def _build_g_table() -> np.ndarray:
    """G[u] = 2^48 - crush_ln(u) for every 16-bit u, as float32.

    The straw2 draw argmax over -(G/w) (mapper.c:322-367) becomes a single
    table gather plus a reciprocal multiply — no 64-bit math on device.
    """
    us = np.arange(0x10000, dtype=np.uint32)
    g = (np.uint64(1) << np.uint64(48)) - crush_ln_np(us)
    return g.astype(np.float64).astype(np.float32)


_G_F32 = jnp.asarray(_build_g_table())

# conservative relative error of q = f32(G) * f32(1/w): G rounding (2^-24)
# + inv rounding (2^-24) + product rounding (2^-24), padded
_REL_ERR = np.float32(2 ** -20)
# floor(q) ties break by index in the reference; candidates within +-TIE
# of each other could tie after truncation
_TIE_PAD = np.float32(2.0)


def _straw2_batch(C: CompiledCrushMap, bidx, x, r: int, position: int):
    """Straw2 winners for a batch of buckets: bidx (X,), x (X,) -> (X,).

    f32 fast evaluation of argmin(G(u)/w) with an exactness guard: lanes
    whose top-two draws are within the float error bound (or the integer
    floor-tie window) get risky=True and are re-evaluated on the host by
    the caller.  Everything here is u32 hashing, one 64K-entry gather and
    f32 multiplies — TPU-friendly lanes, no u64.
    """
    ids = C.hash_ids[bidx]           # (X, S)
    invw = C.inv_weights[min(position, C.npos - 1)][bidx]  # (X, S) f32
    u = hash32_3(x[:, None], ids, jnp.uint32(r)) & jnp.uint32(0xFFFF)
    g = _G_F32[u.astype(jnp.int32)]
    valid = (C.lane[None, :] < C.sizes[bidx][:, None]) & (invw > 0)
    q = jnp.where(valid, g * invw, jnp.float32(np.inf))
    win = jnp.argmin(q, axis=1)
    q1 = jnp.min(q, axis=1)
    q2 = jnp.min(jnp.where(jax.nn.one_hot(win, q.shape[1], dtype=bool),
                           jnp.float32(np.inf), q), axis=1)
    finite1 = jnp.isfinite(q1)
    finite2 = jnp.isfinite(q2)
    risky = finite1 & finite2 & \
        ((q2 - q1) <= (q1 + q2) * _REL_ERR + _TIE_PAD)
    items = jnp.take_along_axis(C.items[bidx], win[:, None], axis=1)[:, 0]
    return items, risky


def _is_out_batch(dev_weight, items, x):
    w = dev_weight[jnp.maximum(items, 0)]
    h = hash32_2(x, items) & jnp.uint32(0xFFFF)
    return jnp.where(w >= 0x10000, False, jnp.where(w == 0, True, h >= w))


def _layer_path(m: CrushMap, root: int, target_type: int) -> int:
    """Verify the hierarchy under *root* is layered toward *target_type*;
    returns the number of choose levels needed to reach it."""
    depth = 0
    frontier = [root]
    while True:
        child_types = set()
        for b in frontier:
            bk = m.bucket(b)
            if bk is None or bk.size == 0:
                raise UnsupportedRule("empty/dangling bucket in path")
            for it in bk.items:
                if it >= 0:
                    child_types.add(0)
                else:
                    sb = m.bucket(it)
                    if sb is None:
                        raise UnsupportedRule("dangling bucket ref")
                    child_types.add(sb.type)
        if len(child_types) != 1:
            raise UnsupportedRule("mixed child types: not layered")
        ct = child_types.pop()
        depth += 1
        if ct == target_type:
            return depth
        if ct == 0:
            raise UnsupportedRule("reached devices before target type")
        next_frontier = []
        for b in frontier:
            next_frontier.extend(m.bucket(b).items)
        frontier = next_frontier
        if depth > 10:
            raise UnsupportedRule("hierarchy too deep")


class FastRule:
    """Compiled single-choose rule: take root; choose[leaf] {firstn,indep}
    n type T; emit."""

    def __init__(self, C: CompiledCrushMap, ruleno: int, result_max: int,
                 tries_cap: int = 4, leaf_tries_cap: int = 4,
                 choose_args=None):
        m = C.map
        self.ruleno = ruleno
        self.choose_args = choose_args
        rule = m.rules[ruleno]
        if rule is None:
            raise UnsupportedRule(f"no rule {ruleno}")
        choose_tries = m.choose_total_tries + 1
        leaf_tries = 0
        vary_r = m.chooseleaf_vary_r
        stable = m.chooseleaf_stable
        take = None
        choose = None
        for step in rule.steps:
            if step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    choose_tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    leaf_tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
                if step.arg1 >= 0:
                    vary_r = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
                if step.arg1 >= 0:
                    stable = step.arg1
            elif step.op in (CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                             CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
                if step.arg1 > 0:
                    raise UnsupportedRule("local tries")
            elif step.op == CRUSH_RULE_TAKE:
                if take is not None:
                    raise UnsupportedRule("multiple takes")
                take = step.arg1
            elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                             CRUSH_RULE_CHOOSELEAF_FIRSTN,
                             CRUSH_RULE_CHOOSE_INDEP,
                             CRUSH_RULE_CHOOSELEAF_INDEP):
                if choose is not None:
                    raise UnsupportedRule("chained choose steps")
                choose = step
            elif step.op == CRUSH_RULE_EMIT:
                pass
            else:
                raise UnsupportedRule(f"op {step.op}")
        if take is None or choose is None or take >= 0:
            raise UnsupportedRule("rule shape")
        self.firstn = choose.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                    CRUSH_RULE_CHOOSELEAF_FIRSTN)
        self.leafy = choose.op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                   CRUSH_RULE_CHOOSELEAF_INDEP)
        numrep = choose.arg1
        if numrep <= 0:
            numrep += result_max
        if numrep <= 0:
            raise UnsupportedRule("numrep")
        self.numrep = min(numrep, result_max) if not self.firstn else numrep
        self.target_type = choose.arg2
        if self.firstn:
            if self.leafy and not stable:
                # rep' for the leaf draw depends on the dynamic success
                # count without the stable tunable (mapper.c:545)
                raise UnsupportedRule("firstn chooseleaf needs stable=1")
            if C.npos > 1:
                raise UnsupportedRule("firstn with per-position weight sets")
        if self.leafy:
            if leaf_tries:
                recurse = leaf_tries
            elif self.firstn:
                recurse = 1 if m.chooseleaf_descend_once else choose_tries
            else:
                recurse = 1
        else:
            recurse = 1
        self.take = take
        self.vary_r = vary_r
        self.tries = choose_tries
        self.recurse_tries = recurse
        self.n_rounds = min(tries_cap + 1, choose_tries)
        self.n_leaf = min(leaf_tries_cap + 1, recurse)
        self.depth = _layer_path(m, take, self.target_type)
        self.leaf_depth = 0
        if self.leafy and self.target_type != 0:
            # depth below a failure-domain bucket, validated layered
            frontier = [take]
            for _ in range(self.depth):
                nxt = []
                for b in frontier:
                    nxt.extend(i for i in m.bucket(b).items)
                frontier = nxt
            if all(i >= 0 for i in frontier):
                self.leaf_depth = 0
            else:
                self.leaf_depth = _layer_path(m, frontier[0], 0)
                for f in frontier:
                    if _layer_path(m, f, 0) != self.leaf_depth:
                        raise UnsupportedRule("uneven leaf depth")
        self.C = C
        self.result_max = result_max
        self._jit = jax.jit(self._device_map)

    # ---- device pass ------------------------------------------------------
    def _descend(self, x, start_bidx, r: int, position: int, depth: int):
        """Fixed-depth descent: (X,) bucket idx -> (X,) item at the target
        layer, plus the accumulated exactness-risk flag.  r is constant
        through the walk (mapper.c:498-520)."""
        item = None
        bidx = start_bidx
        risky = jnp.zeros(x.shape, dtype=bool)
        for _ in range(depth):
            item, rk = _straw2_batch(self.C, bidx, x, r, position)
            risky = risky | rk
            bidx = jnp.maximum(-1 - item, 0)
        return item, risky

    def _leaf_of(self, x, host_item, r: int, rep_static: int):
        """One leaf attempt below a chosen failure-domain bucket."""
        if self.leaf_depth == 0 and self.target_type == 0:
            return host_item, jnp.zeros(x.shape, dtype=bool)
        bidx = jnp.maximum(-1 - host_item, 0)
        depth = self.leaf_depth if self.leaf_depth else 1
        pos = rep_static if not self.firstn else 0
        return self._descend(x, bidx, r, pos, depth)

    def _device_map(self, xs, dev_weight):
        x = xs.astype(jnp.uint32)
        root_idx = jnp.full((xs.shape[0],), -1 - self.take, dtype=jnp.int32)
        if self.firstn:
            return self._resolve_firstn(x, root_idx, dev_weight)
        return self._resolve_indep(x, root_idx, dev_weight)

    def _resolve_firstn(self, x, root_idx, dev_weight):
        """firstn: slot j retries r = j + ftotal (mapper.c:493-495); leafy
        failures consume an outer retry (descend_once semantics)."""
        X = x.shape[0]
        numrep, R = self.numrep, self.numrep + self.n_rounds - 1
        # candidate tables: descent + single leaf attempt per r.  any
        # float-ambiguous draw anywhere in a lane's tables flags the lane
        # for exact host recomputation (conservative, ~1e-6 of lanes)
        residual = jnp.zeros((X,), dtype=bool)
        cand = []
        leaf = []
        for r in range(R):
            item, rk = self._descend(x, root_idx, r, 0, self.depth)
            residual = residual | rk
            cand.append(item)
            if self.leafy:
                sub_r = (r >> (self.vary_r - 1)) if self.vary_r else 0
                lf = []
                for ft2 in range(self.n_leaf):
                    lv, lrk = self._leaf_of(x, item, sub_r + ft2, 0)
                    residual = residual | lrk
                    lf.append(lv)
                leaf.append(lf)
        outs = jnp.full((X, numrep), NONE, dtype=jnp.int32)
        leaves = jnp.full((X, numrep), NONE, dtype=jnp.int32)
        for j in range(numrep):
            done = jnp.zeros((X,), dtype=bool)
            for ftotal in range(self.n_rounds):
                r = j + ftotal
                item = cand[r]
                coll = jnp.any(outs == item[:, None], axis=1)
                if self.leafy:
                    # first acceptable leaf attempt, if any
                    lok = jnp.zeros((X,), dtype=bool)
                    lsel = jnp.full((X,), NONE, dtype=jnp.int32)
                    lres = jnp.zeros((X,), dtype=bool)
                    for ft2 in range(self.n_leaf):
                        lf = leaf[r][ft2]
                        lcoll = jnp.any(leaves == lf[:, None], axis=1)
                        lrej = _is_out_batch(dev_weight, lf, x)
                        good = ~lok & ~lcoll & ~lrej
                        lsel = jnp.where(good, lf, lsel)
                        lok = lok | good
                    # couldn't prove failure within the cap?
                    if self.n_leaf < self.recurse_tries:
                        lres = ~lok
                    ok = ~coll & lok
                    maybe_more = lres
                else:
                    rej = (_is_out_batch(dev_weight, item, x)
                           if self.target_type == 0
                           else jnp.zeros((X,), dtype=bool))
                    ok = ~coll & ~rej
                    lsel = item
                    maybe_more = jnp.zeros((X,), dtype=bool)
                take = ok & ~done & ~residual
                outs = outs.at[:, j].set(jnp.where(take, item, outs[:, j]))
                leaves = leaves.at[:, j].set(
                    jnp.where(take, lsel, leaves[:, j]))
                residual = residual | (maybe_more & ~done)
                done = done | ok
            # not done within the materialized rounds, but the reference
            # would keep trying -> must defer to the host
            if self.n_rounds < self.tries:
                residual = residual | ~done
        sel = leaves if self.leafy else outs
        return sel, residual

    def _resolve_indep(self, x, root_idx, dev_weight):
        """indep rounds: r = rep + numrep*ftotal; UNDEF slots retry,
        dead ends become NONE (mapper.c:638-790)."""
        X = x.shape[0]
        numrep = self.numrep
        UNDEF = jnp.int32(0x7FFFFFFE)  # CRUSH_ITEM_UNDEF; never a real item
        outs = jnp.full((X, numrep), UNDEF, dtype=jnp.int32)
        leaves = jnp.full((X, numrep), UNDEF, dtype=jnp.int32)
        residual = jnp.zeros((X,), dtype=bool)
        for ftotal in range(self.n_rounds):
            for rep in range(numrep):
                r = rep + numrep * ftotal
                item, rk = self._descend(x, root_idx, r, 0, self.depth)
                residual = residual | rk
                unfilled = outs[:, rep] == UNDEF
                coll = jnp.any(outs == item[:, None], axis=1)
                if self.leafy:
                    lok = jnp.zeros((X,), dtype=bool)
                    lsel = jnp.full((X,), NONE, dtype=jnp.int32)
                    for ft2 in range(self.n_leaf):
                        r2 = rep + r + numrep * ft2
                        lf, lrk = self._leaf_of(x, item, r2, rep)
                        residual = residual | lrk
                        lrej = _is_out_batch(dev_weight, lf, x)
                        good = ~lok & ~lrej
                        lsel = jnp.where(good, lf, lsel)
                        lok = lok | good
                    if self.n_leaf < self.recurse_tries:
                        residual = residual | (unfilled & ~coll & ~lok)
                    ok = ~coll & lok
                else:
                    rej = (_is_out_batch(dev_weight, item, x)
                           if self.target_type == 0
                           else jnp.zeros((X,), dtype=bool))
                    ok = ~coll & ~rej
                    lsel = item
                take = unfilled & ok
                outs = outs.at[:, rep].set(
                    jnp.where(take, item, outs[:, rep]))
                leaves = leaves.at[:, rep].set(
                    jnp.where(take, lsel, leaves[:, rep]))
        unfinished = jnp.any(outs == UNDEF, axis=1)
        if self.n_rounds < self.tries:
            residual = residual | unfinished
        outs = jnp.where(outs == UNDEF, NONE, outs)
        leaves = jnp.where(leaves == UNDEF, NONE, leaves)
        sel = leaves if self.leafy else outs
        return sel, residual

    # ---- public -----------------------------------------------------------
    def map_batch(self, xs: np.ndarray, weight: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """Map every x; exact.  Returns (results [X, numrep], counts [X])."""
        xs = np.asarray(xs, dtype=np.uint32)
        w32 = np.asarray(weight, dtype=np.uint32)
        sel, residual = self._jit(jnp.asarray(xs), jnp.asarray(w32))
        sel = np.asarray(sel)
        residual = np.asarray(residual)
        out = np.full((xs.shape[0], self.result_max), NONE, dtype=np.int32)
        counts = np.zeros(xs.shape[0], dtype=np.int32)
        if self.firstn:
            # compact successes in slot order (do_rule EMIT semantics)
            for j in range(sel.shape[1]):
                col = sel[:, j]
                ok = col != NONE
                idx = counts.copy()
                place = ok & (idx < self.result_max)
                out[np.arange(out.shape[0])[place], idx[place]] = col[place]
                counts += place.astype(np.int32)
        else:
            n = min(sel.shape[1], self.result_max)
            out[:, :n] = sel[:, :n]
            counts[:] = n
        # exactness escape hatch: recompute flagged lanes on the host
        self._residual_frac = float(residual.mean())
        if residual.any():
            m = self.C.map
            wl = [int(v) for v in weight]
            for i in np.nonzero(residual)[0]:
                res = crush_do_rule(m, self.ruleno, int(xs[i]),
                                    self.result_max, wl, self.choose_args)
                out[i, :] = NONE
                out[i, :len(res)] = res
                counts[i] = len(res)
        return out, counts

    @property
    def residual_fraction(self) -> float:
        return getattr(self, "_residual_frac", 0.0)


def compile_fast_rule(m: CrushMap, ruleno: int, result_max: int,
                      choose_args=None, **kw) -> FastRule:
    C = compile_map(m, choose_args)
    return FastRule(C, ruleno, result_max, choose_args=choose_args, **kw)
