"""Legacy-tunables CRUSH fast path (straw v1, local tries, perm fallback).

The candidate-table mapper (crush_fast.py) targets jewel-style tunables,
where every retry is a fresh full descent and r is constant through the
walk.  Pre-bobtail maps — the reference's own golden fixtures
(src/test/cli/crushtool/set-choose.t) among them — run with
``choose_local_tries``/``choose_local_fallback_tries`` > 0: a collision
or rejection first retries AT the failing bucket (flocal++, same
descent), falls back to an exhaustive permutation draw once flocal
crosses ``size>>1``/fallback thresholds (mapper.c bucket_perm_choose),
and only then re-descends.  That breaks the one-retry-one-descent
flattening, so this module uses a different TPU formulation:

1. *Dense draw tables* (topology-only): for every lane (x), every bucket
   b and every retry value r < RMAX, precompute both the bucket's normal
   draw ``T[x, b, r]`` (straw v1 u48 multiply or straw2 s64 quotient —
   exact int64 math under jax x64) and its permutation draw
   ``P[x, b, r]``.  Buckets are few and RMAX is bounded by
   tries + the local window, so the tables are tiny.

2. *Unrolled retry state machine* (per epoch): crush_choose_firstn's
   retry_descent/retry_bucket/perm-fallback loop (mapper.c:443-636)
   becomes a masked vector program over (ftotal, flocal, descent-start)
   integer state; each step gathers its draw from T/P by (bucket, r).
   The chooseleaf recursion (descend_once / chooseleaf_tries) runs as a
   nested, fully-materialized sub-machine — its try count is bounded by
   recurse_tries + the local window, so leaf failure is always proven
   on device.

3. *Residual escape hatch*: lanes that exhaust the materialized outer
   tries (RT < choose_total_tries) are replayed with the host
   interpreter, exactly like crush_fast's residuals.

Scope: firstn steps (indep never had local retries — jewel semantics
apply and crush_fast handles them), single take, chained chooses,
chooseleaf depth 1, vary_r == 0.  This is a correctness/coverage path:
production jewel+ maps keep using crush_fast's cached-candidate design.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crush.constants import (
    CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE,
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_EMIT, CRUSH_RULE_SET_CHOOSELEAF_STABLE,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES, CRUSH_RULE_SET_CHOOSELEAF_VARY_R,
    CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)
from ..arch import enable_x64
from ..crush.mapper import crush_do_rule
from ..crush.types import CrushMap
from .crush_fast import UnsupportedRule, _G_EXACT, _layer_path_frontier
from .crush_kernels import CompiledCrushMap, hash32_2, hash32_3

NONE = CRUSH_ITEM_NONE
S64_MIN = -(1 << 63)


class LegacyFastRule:
    """Device evaluation of a firstn rule under legacy tunables."""

    def __init__(self, m: CrushMap, ruleno: int, result_max: int,
                 tries_cap: int = 64):
        self.C = CompiledCrushMap(m, allow_legacy=True)
        self.m = m
        self.ruleno = ruleno
        self.result_max = result_max
        rule = m.rules[ruleno]
        if rule is None:
            raise UnsupportedRule(f"no rule {ruleno}")
        self.tries = m.choose_total_tries + 1
        self.local_retries = m.choose_local_tries
        self.local_fallback = m.choose_local_fallback_tries
        leaf_tries = 0
        vary_r = m.chooseleaf_vary_r
        stable = m.chooseleaf_stable
        take = None
        chooses: List = []
        for step in rule.steps:
            if step.op == CRUSH_RULE_SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    self.tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    leaf_tries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES:
                if step.arg1 >= 0:
                    self.local_retries = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                if step.arg1 >= 0:
                    self.local_fallback = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
                if step.arg1 >= 0:
                    vary_r = step.arg1
            elif step.op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
                if step.arg1 >= 0:
                    stable = step.arg1
            elif step.op == CRUSH_RULE_TAKE:
                if take is not None:
                    raise UnsupportedRule("multiple takes")
                take = step.arg1
            elif step.op in (CRUSH_RULE_CHOOSE_FIRSTN,
                             CRUSH_RULE_CHOOSELEAF_FIRSTN):
                chooses.append(step)
            elif step.op == CRUSH_RULE_EMIT:
                pass
            else:
                raise UnsupportedRule(f"op {step.op}")
        if take is None or take >= 0 or not chooses:
            raise UnsupportedRule("rule shape")
        if vary_r:
            raise UnsupportedRule("legacy machine with vary_r")
        self.stable = stable
        self.take = take
        # per-stage: depth along the layered tree, numrep, leafiness
        self.stages: List[dict] = []
        frontier = [take]
        for si, step in enumerate(chooses):
            leafy = step.op == CRUSH_RULE_CHOOSELEAF_FIRSTN
            if leafy and si != len(chooses) - 1:
                raise UnsupportedRule("chooseleaf before the last step")
            n = step.arg1
            if n <= 0:
                n += result_max
            if n <= 0:
                raise UnsupportedRule("numrep")
            d = _layer_path_frontier(m, frontier, step.arg2)
            st = {"numrep": n, "type": step.arg2, "depth": d,
                  "leafy": leafy}
            if leafy:
                if step.arg2 == 0:
                    st["leaf_depth"] = 0
                else:
                    nxt = list(frontier)
                    for _ in range(d):
                        nxt = [i for b in nxt
                               for i in m.bucket(b).items if i < 0]
                    if not nxt:
                        st["leaf_depth"] = 0
                    else:
                        ld = _layer_path_frontier(m, nxt, 0)
                        if ld != 1:
                            raise UnsupportedRule("legacy leaf depth > 1")
                        st["leaf_depth"] = 1
                if leaf_tries:
                    st["recurse"] = leaf_tries
                elif m.chooseleaf_descend_once:
                    st["recurse"] = 1
                else:
                    st["recurse"] = self.tries
            self.stages.append(st)
            for _ in range(d):
                frontier = [i for b in frontier
                            for i in m.bucket(b).items if i < 0]
        # the local-retry window is an exact bound, not a cap: flocal
        # may reach size + fallback before a descent is forced
        smax = int(self.C.max_size)
        self.kl = smax + self.local_fallback + 1
        self.rt = min(tries_cap, self.tries)
        max_slot = max(st["numrep"] for st in self.stages)
        max_leaf = max((st.get("recurse", 0) + self.kl
                        for st in self.stages if st.get("leafy")),
                       default=0)
        self.rmax = max_slot + self.rt + self.kl + max_leaf + 2
        self._tables_x: Optional[bytes] = None
        self._resolve_jit = jax.jit(self._resolve_all)

    # ---- draw tables -------------------------------------------------------
    def _draw_tables(self, xs):
        """T[x, b, r], P[x, b, r]: normal and permutation draws for
        every bucket and retry value, exact int64."""
        C = self.C
        nb, S = C.nbuckets, C.max_size
        R = self.rmax
        X = xs.shape[0]
        x = xs.astype(jnp.uint32)
        bidx = jnp.arange(nb, dtype=jnp.int32)
        r = jnp.arange(R, dtype=jnp.uint32)
        # normal draw: (X, nb, R)
        ids = C.hash_ids                        # (nb, S)
        u = hash32_3(x[:, None, None, None], ids[None, :, None, :],
                     r[None, None, :, None]) & jnp.uint32(0xFFFF)
        valid = (jnp.arange(S)[None, :] < C.sizes[:, None])  # (nb, S)
        is2 = jnp.asarray(self.C.algs == CRUSH_BUCKET_STRAW2)  # (nb,)
        # straw v1: draw = u16 * straws (fits 48 bits)
        d1 = u.astype(jnp.int64) * C.straws[None, :, None, :].astype(
            jnp.int64)
        # straw2: draw = -((2^48 - crush_ln(u)) // w)  (s64 trunc-to-0)
        g = jnp.asarray(_G_EXACT)[u.astype(jnp.int32)]
        w = C.weights[0][None, :, None, :].astype(jnp.int64)
        d2 = jnp.where(w > 0, -(g // jnp.maximum(w, 1)),
                       jnp.int64(S64_MIN))
        draw = jnp.where(is2[None, :, None, None], d2, d1)
        draw = jnp.where(valid[None, :, None, :], draw,
                         jnp.int64(S64_MIN))
        win = jnp.argmax(draw, axis=3)          # first max wins
        T = jnp.take_along_axis(
            jnp.broadcast_to(C.items[None, :, None, :], draw.shape),
            win[..., None], axis=3)[..., 0]
        # permutation draw (bucket_perm_choose, mapper.c:76-131): a
        # Fisher-Yates prefix keyed on (bucket id, x); the prefix length
        # pr = r % size differs per retry column, so swap step p applies
        # only to columns with pr >= p
        sizes = C.sizes                          # (nb,)
        bucket_id = (-1 - bidx).astype(jnp.uint32)
        pr = jnp.where(sizes[None, :, None] > 0,
                       r[None, None, :].astype(jnp.int32)
                       % jnp.maximum(sizes[None, :, None], 1), 0)
        pr = jnp.broadcast_to(pr, (X, nb, R))
        perm = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32),
                                (X, nb, R, S))
        for p in range(S):
            sz = jnp.broadcast_to(sizes[None, :, None], (X, nb, R))
            act = (jnp.int32(p) < sz - 1) & (jnp.int32(p) <= pr) & (sz > 0)
            h = hash32_3(x[:, None], bucket_id[None, :], jnp.uint32(p))
            i = (h % jnp.maximum(sizes[None, :] - p, 1)
                 .astype(jnp.uint32)).astype(jnp.int32)       # (X, nb)
            tgt = jnp.broadcast_to(
                jnp.minimum(jnp.int32(p) + i, S - 1)[:, :, None],
                (X, nb, R))
            do = act & jnp.broadcast_to((i > 0)[:, :, None], (X, nb, R))
            vp = perm[..., p]
            vt = jnp.take_along_axis(perm, tgt[..., None],
                                     axis=3)[..., 0]
            lane = jnp.arange(S, dtype=jnp.int32)
            perm = jnp.where((lane == tgt[..., None]) & do[..., None],
                             vp[..., None], perm)
            perm = perm.at[..., p].set(jnp.where(do, vt, vp))
        slot = jnp.take_along_axis(perm, pr[..., None], axis=3)[..., 0]
        P = jnp.take_along_axis(
            jnp.broadcast_to(C.items[None, :, None, :], (X, nb, R, S)),
            jnp.clip(slot, 0, S - 1)[..., None], axis=3)[..., 0]
        return T, P

    # ---- the retry state machine ------------------------------------------
    def _is_out(self, dev_weight, items, x):
        w = dev_weight[jnp.maximum(items, 0)]
        h = hash32_2(x, items.astype(jnp.uint32)) & jnp.uint32(0xFFFF)
        return jnp.where(w >= 0x10000, False,
                         jnp.where(w == 0, True, h >= w))

    def _gather(self, table, b, r):
        """table (N, nb, R) gathered at per-lane (bucket idx, retry)."""
        N = b.shape[0]
        lane = jnp.arange(N)
        return table[lane, b, jnp.clip(r, 0, self.rmax - 1)]

    def _upper(self, T, roots, slot_r, depth):
        """Pure descent through depth-1 intervening levels at constant
        retry slot_r: returns the bottom bucket idx."""
        b = roots
        for _ in range(max(depth - 1, 0)):
            item = self._gather(T, b, slot_r)
            b = jnp.maximum(-1 - item, 0)
        return b

    def _leaf_machine(self, st, T, P, xl, host_item, op, leaves,
                      dev_weight):
        """chooseleaf recursion (depth 1, vary_r=0): pick ONE device
        from *host_item* avoiding the out2 collisions in *leaves*;
        fully materialized — returns (ok, item).  r = op + ftotal
        (stable pins op to 0)."""
        N = xl.shape[0]
        hb = jnp.maximum(-1 - host_item, 0)
        hsz = self.C.sizes[hb]
        base_r = jnp.zeros((N,), jnp.int32) if self.stable \
            else op.astype(jnp.int32)
        steps = st["recurse"] + self.kl

        def body(_, carry):
            ft, fl, done, dead, pick = carry
            active = ~done & ~dead
            use_perm = (self.local_fallback > 0) & \
                (fl >= (hsz >> 1)) & (fl > self.local_fallback)
            r = base_r + ft
            it_n = self._gather(T, hb, r)
            it_p = self._gather(P, hb, r)
            item = jnp.where(use_perm, it_p, it_n)
            coll = jnp.any(leaves == item[:, None], axis=1)
            rej = self._is_out(dev_weight, item, xl) | (hsz == 0)
            ok = active & ~coll & ~rej
            pick = jnp.where(ok, item, pick)
            done = done | ok
            fail = active & ~ok
            ft2, fl2 = ft + 1, fl + 1
            local = fail & ((coll & (fl2 <= self.local_retries))
                            | ((self.local_fallback > 0)
                               & (fl2 <= hsz + self.local_fallback)))
            desc = fail & ~local & (ft2 < st["recurse"])
            ft = jnp.where(fail, ft2, ft)
            fl = jnp.where(local, fl2, jnp.where(desc, 0, fl))
            dead = dead | (fail & ~local & ~desc)
            return ft, fl, done, dead, pick

        z = jnp.zeros((N,), jnp.int32)
        f = jnp.zeros((N,), bool)
        ft, fl, done, dead, pick = jax.lax.fori_loop(
            0, steps, body,
            (z, z, f, f, jnp.full((N,), NONE, jnp.int32)))
        return done, pick

    def _stage_machine(self, st, T, P, xl, roots, valid, dev_weight):
        """One firstn choose step for N parent lanes: returns
        (outs (N, numrep) — leaf devices when leafy else stage items,
        residual (N,))."""
        N = xl.shape[0]
        n = st["numrep"]
        outs = jnp.full((N, n), NONE, jnp.int32)      # collision scope
        sel = jnp.full((N, n), NONE, jnp.int32)       # emitted values
        residual = jnp.zeros((N,), bool)
        leafy = st.get("leafy", False)
        for j in range(n):

            def body(_, carry, j=j):
                outs, sel, residual, F, ft, fl, done, dead = carry
                active = valid & ~done & ~dead & ~residual
                slot_rF = jnp.int32(j) + F
                bbot = self._upper(T, roots, slot_rF, st["depth"])
                bsz = self.C.sizes[bbot]
                use_perm = (self.local_fallback > 0) & \
                    (fl >= (bsz >> 1)) & (fl > self.local_fallback)
                r = jnp.int32(j) + ft
                it_n = self._gather(T, bbot, r)
                it_p = self._gather(P, bbot, r)
                item = jnp.where(use_perm, it_p, it_n)
                coll = jnp.any(outs == item[:, None], axis=1)
                if leafy:
                    # the recursion's base r is outpos — the count of
                    # SUCCESSFUL slots so far, not the attempt index
                    # (mapper.py _choose_firstn passes outpos; a dead
                    # earlier slot leaves outpos behind j)
                    op = jnp.sum((outs[:, :j] != NONE).astype(jnp.int32),
                                 axis=1) if j else jnp.zeros((N,),
                                                             jnp.int32)
                    lok, lpick = self._leaf_machine(
                        st, T, P, xl, item, op, sel, dev_weight)
                    rej = ~lok
                elif st["type"] == 0:
                    lpick = item
                    rej = self._is_out(dev_weight, item, xl) | (bsz == 0)
                else:
                    lpick = item
                    rej = bsz == 0
                ok = active & ~coll & ~rej
                outs = outs.at[:, j].set(
                    jnp.where(ok, item, outs[:, j]))
                sel = sel.at[:, j].set(
                    jnp.where(ok, lpick if leafy else item, sel[:, j]))
                done = done | ok
                fail = active & ~ok
                ft2, fl2 = ft + 1, fl + 1
                local = fail & ((coll & (fl2 <= self.local_retries))
                                | ((self.local_fallback > 0)
                                   & (fl2 <= bsz + self.local_fallback)))
                desc = fail & ~local & (ft2 < self.tries)
                dead = dead | (fail & ~local & ~desc)
                ft = jnp.where(fail, ft2, ft)
                fl = jnp.where(local, fl2, jnp.where(desc, 0, fl))
                F = jnp.where(desc, ft2, F)
                # past the materialized window the device cannot
                # continue, but the reference would: defer to the host.
                # With rt == tries the step count covers every legal
                # path (local retries overshoot tries by at most the
                # window, which the step count and rmax both include).
                over = (ft >= self.rt) if self.rt < self.tries \
                    else jnp.zeros_like(done)
                residual = residual | (active & ~done & ~dead
                                       & (over | (r >= self.rmax - 1)))
                return outs, sel, residual, F, ft, fl, done, dead

            z = jnp.zeros((N,), jnp.int32)
            f = jnp.zeros((N,), bool)
            outs, sel, residual, _F, _ft, _fl, done, dead = \
                jax.lax.fori_loop(0, self.rt + self.kl, body,
                                  (outs, sel, residual, z, z, z, f, f))
            residual = residual | (valid & ~done & ~dead)
        return sel, residual

    def _resolve_all(self, xs, dev_weight):
        """Full rule evaluation: every stage's machine, chained."""
        X = xs.shape[0]
        x = xs.astype(jnp.uint32)
        T, P = self._draw_tables(xs)
        xl = x
        roots = jnp.full((X,), -1 - self.take, dtype=jnp.int32)
        valid = jnp.ones((X,), bool)
        residual = jnp.zeros((X,), bool)
        parents = 1
        Tl, Pl = T, P
        for si, st in enumerate(self.stages):
            sel, res = self._stage_machine(st, Tl, Pl, xl, roots, valid,
                                           dev_weight)
            residual = residual | jnp.any(
                res.reshape(X, -1), axis=1)
            if si == len(self.stages) - 1:
                final = sel
                break
            # firstn chains compactly: successes first, order kept
            order = jnp.argsort((sel == NONE).astype(jnp.int32), axis=1,
                                stable=True)
            sel = jnp.take_along_axis(sel, order, axis=1)
            n = st["numrep"]
            xl = jnp.repeat(xl, n)
            valid = jnp.repeat(valid, n) & (sel.reshape(-1) != NONE)
            roots = jnp.maximum(-1 - sel.reshape(-1), 0)
            Tl = jnp.repeat(Tl, n, axis=0)
            Pl = jnp.repeat(Pl, n, axis=0)
            parents *= n
        nr = final.shape[1]
        wide = final.reshape(X, parents * nr)
        order = jnp.argsort((wide == NONE).astype(jnp.int32), axis=1,
                            stable=True)
        compact = jnp.take_along_axis(wide, order, axis=1)
        R = self.result_max
        if compact.shape[1] < R:
            compact = jnp.pad(compact, ((0, 0), (0, R - compact.shape[1])),
                              constant_values=NONE)
        out = compact[:, :R]
        counts = jnp.minimum(jnp.sum(wide != NONE, axis=1), R)
        return out, counts.astype(jnp.int32), residual

    # ---- public ------------------------------------------------------------
    def map_batch(self, xs: np.ndarray, weight) -> Tuple[np.ndarray,
                                                         np.ndarray]:
        xs = np.asarray(xs, dtype=np.uint32)
        w32 = np.asarray(weight, dtype=np.uint32)
        with enable_x64():
            out_d, cnt_d, res_d = self._resolve_jit(jnp.asarray(xs),
                                                    jnp.asarray(w32))
        out = np.asarray(out_d).astype(np.int32).copy()
        counts = np.asarray(cnt_d).astype(np.int32).copy()
        residual = np.asarray(res_d)
        self._residual_frac = float(residual.mean())
        wl = [int(v) for v in w32]
        for i in np.nonzero(residual)[0]:
            r = crush_do_rule(self.m, self.ruleno, int(xs[i]),
                              self.result_max, wl)
            out[i, :] = NONE
            out[i, :len(r)] = r
            counts[i] = len(r)
        return out, counts

    @property
    def residual_fraction(self) -> float:
        return getattr(self, "_residual_frac", 0.0)
