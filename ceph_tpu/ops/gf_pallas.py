"""Fused GF(2^8) bit-matmul as a Pallas TPU kernel.

The XLA path (ops/gf_matmul.gf_bit_matmul) materializes the 8x bit
expansion — (S, C, k*8) int8 — between the unpack and the dot, which XLA
may round-trip through HBM.  This kernel keeps the whole
unpack -> MXU matmul -> parity -> pack chain in VMEM: the grid tiles
(stripe, chunk-column) space, each program unpacks a (k, TILE_C) uint8
block to (k*8, TILE_C) bits, hits the MXU against the (m*8, k*8)
transposed bit-matrix, and packs the parity bits straight back to
(m, TILE_C) bytes.  HBM traffic is exactly input-bytes + output-bytes.

Role: the ec_encode_data hot loop (src/erasure-code/isa/
ErasureCodeIsa.cc:128) re-done as a hand-written TPU kernel.  The A/B on
hardware (k=8, m=4, 1 MiB chunks) measured this kernel at ~920 GiB/s vs
~2754 GiB/s for the XLA dot_general path — XLA's own fusion of the
unpack/pack already wins, so ops/gf_matmul.gf_bit_matmul remains the
default executor and this kernel is kept as the measured, byte-identical
alternative (tests/test_gf_matmul_device.py pins parity).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# lane-dim tile; chunk sizes are 64B-aligned (SIMD_ALIGN) and usually
# large (128 KiB in the headline bench) — pick the biggest tile that
# divides C
_TILES = (4096, 2048, 1024, 512, 256, 128)


def _kernel(data_ref, bmt_ref, out_ref, *, k: int, m: int):
    """One (stripe, C-tile) program: data (1, k, T) u8 -> out (1, m, T) u8."""
    d = data_ref[0]                                    # (k, T) uint8
    shifts = jax.lax.broadcasted_iota(jnp.int32, (k, 8, d.shape[1]), 1)
    bits = ((d[:, None, :].astype(jnp.int32) >> shifts) & 1)
    bits = bits.astype(jnp.int8).reshape(k * 8, d.shape[1])
    acc = jax.lax.dot_general(
        bmt_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)              # (m*8, T)
    par = (acc & 1).reshape(m, 8, d.shape[1])
    weights = jax.lax.broadcasted_iota(jnp.int32, (m, 8, d.shape[1]), 1)
    out = (par << weights).sum(axis=1)
    out_ref[0] = out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _run(data, bmt, *, interpret=False):
    s, k, c = data.shape
    m8 = bmt.shape[0]
    m = m8 // 8
    tile = next((t for t in _TILES if c % t == 0), None)
    assert tile is not None, c
    grid = (s, c // tile)
    return pl.pallas_call(
        functools.partial(_kernel, k=k, m=m),
        out_shape=jax.ShapeDtypeStruct((s, m, c), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m8, bmt.shape[1]), lambda i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, m, tile), lambda i, j: (i, 0, j),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(data, bmt)


def pallas_supported(c: int) -> bool:
    return any(c % t == 0 for t in _TILES)


def gf_bit_matmul_pallas(data: jnp.ndarray, bitmat: jnp.ndarray
                         ) -> jnp.ndarray:
    """data (S, k, C) uint8, bitmat (k*8, m*8) int8 -> (S, m, C) uint8.

    Same contract as ops/gf_matmul.gf_bit_matmul.  Interprets on
    non-TPU backends (tests' virtual CPU mesh) so parity is testable
    anywhere.
    """
    interpret = jax.devices()[0].platform != "tpu"
    bmt = jnp.transpose(bitmat, (1, 0))                # (m*8, k*8)
    return _run(data, bmt, interpret=interpret)
