"""GF(2^8) Reed-Solomon coding as MXU matmuls.

TPU-first formulation: GF(2^8) multiplication by a constant is linear over
GF(2), so the whole (m x k) GF(2^8) coding matrix expands to a (k*8 x m*8)
0/1 matrix B (ceph_tpu.gf.tables.expand_to_bitmatrix).  Encoding a batch of
stripes is then:

    bits(S, C, k*8) = unpack(data)            # shifts + masks, fuses in XLA
    acc(S, C, m*8)  = bits @ B                # int8 matmul on the MXU
    coding          = pack(acc & 1)           # parity of the popcount

No per-byte table gathers (which do not vectorize on the VPU), no scalar
loops, static shapes throughout — this is the design that lets XLA tile the
work onto the systolic array.  The same machinery executes decode: the
host inverts the k x k survivor matrix (tiny), expands it to bits, and the
device runs the identical matmul.  Replaces the reference's SIMD paths
(isa-l ec_encode_data, src/erasure-code/isa/ErasureCodeIsa.cc:128;
jerasure_matrix_encode, jerasure/ErasureCodeJerasure.cc:155).

The batched stripe dimension S is the data-parallel axis: under a
``jax.sharding.Mesh`` the same jitted function runs SPMD with S sharded
across devices (see ceph_tpu.parallel).
"""
from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Dict, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..common.lockdep import DebugLock
from ..gf.tables import expand_to_bitmatrix
from ..gf.matrices import gf_invert_matrix
from ..trace.devprof import g_devprof


@functools.lru_cache(maxsize=1)
def device_available() -> bool:
    """True when the default JAX backend is an accelerator."""
    try:
        return jax.devices()[0].platform not in ("cpu",)
    except Exception:
        return False


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """uint8 (..., n) -> (..., n*8) bits, LSB-first."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*x.shape[:-1], x.shape[-1] * 8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., n*8) bits -> uint8 (..., n), LSB-first."""
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    b = bits.reshape(*bits.shape[:-1], bits.shape[-1] // 8, 8)
    return (b * weights).sum(axis=-1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def gf_bit_matmul(data: jnp.ndarray, bitmat: jnp.ndarray) -> jnp.ndarray:
    """data (S, k, C) uint8, bitmat (k*8, r*8) int8 -> (S, r, C) uint8.

    The contraction runs as an int8 matmul with int32 accumulation; the low
    bit of each accumulator is the GF(2) (XOR) sum.
    """
    s, k, c = data.shape
    r8 = bitmat.shape[1]
    d = jnp.transpose(data, (0, 2, 1))          # (S, C, k)
    bits = _unpack_bits(d).astype(jnp.int8)     # (S, C, k*8)
    acc = jax.lax.dot_general(
        bits, bitmat,
        dimension_numbers=(((2,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)        # (S, C, r*8)
    parity = (acc & 1).astype(jnp.uint8)
    out = _pack_bits(parity)                     # (S, C, r)
    return jnp.transpose(out, (0, 2, 1))         # (S, r, C)


@functools.partial(jax.jit, static_argnames=("w",))
def gfw_bit_matmul(data: jnp.ndarray, bitmat: jnp.ndarray,
                   w: int) -> jnp.ndarray:
    """GF(2^w) word-layout coding as the same MXU 0/1 matmul.

    data (S, k, C) uint8 viewed as little-endian w-bit words, bitmat
    (k*w, r*w) int8 companion expansion -> (S, r, C) uint8.  Each word
    unpacks to its w bits (LE byte order makes word bit b*8+i = bit i of
    byte b), the contraction runs over k*w bit lanes, and the parity low
    bit packs back into words.  w=8 degenerates to gf_bit_matmul.
    """
    s, k, c = data.shape
    ws = w // 8
    W = c // ws
    d = jnp.transpose(data.reshape(s, k, W, ws), (0, 2, 1, 3))  # (S,W,k,ws)
    bits = _unpack_bits(d.reshape(s, W, k * ws)).reshape(
        s, W, k, w).reshape(s, W, k * w).astype(jnp.int8)
    acc = jax.lax.dot_general(
        bits, bitmat,
        dimension_numbers=(((2,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)            # (S, W, r*w)
    parity = (acc & 1).astype(jnp.uint8)
    out = _pack_bits(parity)                         # (S, W, r*ws)
    r = bitmat.shape[1] // w
    out = jnp.transpose(out.reshape(s, W, r, ws), (0, 2, 1, 3))
    return out.reshape(s, r, c)


def expand_to_bitmatrix_w(coding: np.ndarray, w: int) -> np.ndarray:
    """(m, k) GF(2^w) coefficients -> (k*w, m*w) 0/1 matrix in the
    d @ B convention gfw_bit_matmul consumes (gf/tables.py
    expand_to_bitmatrix generalized via the companion representation)."""
    from ..gf.bitmatrix import element_bitmatrix
    mm, kk = coding.shape
    out = np.zeros((kk * w, mm * w), dtype=np.uint8)
    for r in range(mm):
        for c in range(kk):
            bm = element_bitmatrix(int(coding[r, c]), w)
            out[c * w:(c + 1) * w, r * w:(r + 1) * w] = bm.T
    return out


class DeviceWordRSBackend:
    """Device executor for a (k+m, k) GF(2^w) word-layout code."""

    def __init__(self, encode_matrix: np.ndarray, w: int):
        rows, k = encode_matrix.shape
        self.k = k
        self.m = rows - k
        self.w = w
        self.matrix = encode_matrix.astype(np.int64)
        bits = expand_to_bitmatrix_w(self.matrix[k:], w)
        self._enc_bits = jnp.asarray(bits.astype(np.int8))

    def encode(self, data: np.ndarray) -> np.ndarray:
        """(S, k, C) uint8 -> (S, m, C) coding chunks."""
        g_devprof.install_compile_listener()
        g_devprof.account_h2d("gf_matmul.encode_w", data.nbytes)
        with g_devprof.stage("gf_matmul.encode_w"):
            out = np.asarray(gfw_bit_matmul(jnp.asarray(data),
                                            self._enc_bits, self.w))
        g_devprof.account_d2h("gf_matmul.encode_w", out.nbytes)
        return out


class DeviceRSBackend:
    """Device-side executor for one (k+m, k) systematic code."""

    def __init__(self, encode_matrix: np.ndarray):
        rows, k = encode_matrix.shape
        self.k = k
        self.m = rows - k
        self.matrix = encode_matrix.astype(np.uint8)
        enc_bits = expand_to_bitmatrix(self.matrix[k:])
        self._enc_bits = jnp.asarray(enc_bits.astype(np.int8))
        # bounded like the host codec's signature cache (mirrors
        # ErasureCodeIsaTableCache's 2516-entry LRU)
        self._decode_bits_cache: "OrderedDict[tuple, jnp.ndarray]" = OrderedDict()
        self._cache_lock = DebugLock("gf_matmul::decode_bits_cache")

    # -- encode -------------------------------------------------------------
    def encode(self, data: np.ndarray) -> np.ndarray:
        """(S, k, C) uint8 -> (S, m, C) coding chunks (numpy round-trip).

        THE host↔device boundary of the EC write path: the whole
        batch crosses up, the coding chunks cross back.  Both legs are
        accounted per call-site by the device-flow profiler (counter
        bumps only — no sync is added; the ``jnp.asarray`` /
        ``np.asarray`` pair was always the copy)."""
        from ..common.kernel_trace import g_kernel_timer
        g_devprof.install_compile_listener()
        g_devprof.account_h2d("gf_matmul.encode", data.nbytes)
        with g_devprof.stage("gf_matmul.encode"):
            out = g_kernel_timer.timed(
                "gf_encode", lambda:
                np.asarray(self.encode_device(jnp.asarray(data))))
        g_devprof.account_d2h("gf_matmul.encode", out.nbytes)
        return out

    def encode_device(self, data: jnp.ndarray) -> jnp.ndarray:
        """Device-resident variant; composes under jit/shard_map."""
        return gf_bit_matmul(data, self._enc_bits)

    @property
    def enc_bits(self) -> jnp.ndarray:
        """The expanded 0/1 coding matrix on device — the operand the
        fused encode+crc kernel (ops/resident) composes with."""
        return self._enc_bits

    # -- decode -------------------------------------------------------------
    def _decode_bits_for(self, srcs: Tuple[int, ...],
                         want_rows: Tuple[int, ...]) -> jnp.ndarray:
        key = (srcs, want_rows)
        with self._cache_lock:
            hit = self._decode_bits_cache.get(key)
            if hit is not None:
                self._decode_bits_cache.move_to_end(key)
                return hit
        sub = self.matrix[list(srcs), :]
        inv = gf_invert_matrix(sub)              # data = inv @ survivors
        rows = inv[list(want_rows), :]
        bits_np = expand_to_bitmatrix(rows).astype(np.int8)
        g_devprof.account_h2d("gf_matmul.decode_bits", bits_np.nbytes)
        bits = jnp.asarray(bits_np)
        with self._cache_lock:
            self._decode_bits_cache[key] = bits
            from ..ec.rs_codec import DECODE_CACHE_ENTRIES
            if len(self._decode_bits_cache) > DECODE_CACHE_ENTRIES:
                self._decode_bits_cache.popitem(last=False)
        return bits

    def decode_data(self, survivors: np.ndarray, srcs: Sequence[int],
                    want_rows: Sequence[int]) -> np.ndarray:
        """survivors (S, k, C) stacked in ``srcs`` order -> the requested
        data rows (S, len(want_rows), C)."""
        bits = self._decode_bits_for(tuple(srcs), tuple(want_rows))
        g_devprof.install_compile_listener()
        g_devprof.account_h2d("gf_matmul.decode", survivors.nbytes)
        with g_devprof.stage("gf_matmul.decode"):
            out = np.asarray(gf_bit_matmul(jnp.asarray(survivors), bits))
        g_devprof.account_d2h("gf_matmul.decode", out.nbytes)
        return out
