"""Fused device-resident EC encode: GF matmul + crc32c, zero body d2h.

One jitted program takes the (S, k, C) stripe batch and produces BOTH
the per-shard concatenated bodies (still on device) and their crc32c
digests (ops/crc32c_device, bit-identical to ``utils/crc32c.py``).  The
only device->host traffic on the whole encode->store path is the 4*n
bytes of CRC scalars — the fetch that used to be every shard body so
the host could hash it.  Shard layout matches the host path exactly:
body i is chunk i of every stripe concatenated (``allc[:, i, :]``
flattened), so stored bytes and HashInfo digests are byte-identical to
a residency-off twin by construction.

The bodies come back as per-shard ``DeviceShard`` handles ready to be
queued through ``Transaction.write_shard`` (os_store/device_shard).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..os_store.device_shard import DeviceShard
from ..trace.devprof import g_devprof
from .crc32c_device import _tables, crc_core
from .gf_matmul import DeviceRSBackend, gf_bit_matmul


@jax.jit
def _fused_encode_crc(stripes: jnp.ndarray, enc_bits: jnp.ndarray,
                      tables: jnp.ndarray):
    """(S, k, C) uint8 -> ((n, S*C) shard bodies, (n,) uint32 crcs)."""
    coding = gf_bit_matmul(stripes, enc_bits)            # (S, m, C)
    allsh = jnp.concatenate([stripes, coding], axis=1)   # (S, n, C)
    n = allsh.shape[1]
    bodies = jnp.transpose(allsh, (1, 0, 2)).reshape(n, -1)
    return bodies, crc_core(bodies, tables)


def resident_capable(ec_impl) -> bool:
    """True when *ec_impl*'s device path is the plain row-independent
    matrix matmul on raw chunks — the only layout the fused kernel
    models.  Word/bitmatrix/regenerating codecs (transformed layouts,
    non-identity chunk mappings) take the classic path."""
    if ec_impl.get_chunk_mapping():
        return False
    if not getattr(ec_impl, "mesh_row_shardable", False):
        return False
    if not hasattr(ec_impl, "device"):
        return False
    try:
        return isinstance(ec_impl.device(), DeviceRSBackend)
    except Exception:
        return False


def encode_resident_shards(ec_impl, stripes: np.ndarray) \
        -> Optional[Dict[int, DeviceShard]]:
    """Encode a (S, k, C) stripe batch into device-resident shards.

    Returns shard id -> ``DeviceShard`` for ALL n shards, or None when
    the codec's layout rules the fused kernel out.  The h2d of the
    stripe batch and the one 4*n-byte CRC fetch are the accounted
    entirety of this path's host<->device traffic; the CRC fetch also
    serves as the encode's completion fence (no block_until_ready)."""
    if not resident_capable(ec_impl):
        return None
    backend: DeviceRSBackend = ec_impl.device()
    g_devprof.install_compile_listener()
    g_devprof.account_h2d("ec.encode_resident", stripes.nbytes)
    with g_devprof.stage("ec.encode_resident"):
        bodies, crcs = _fused_encode_crc(
            jnp.asarray(stripes), backend.enc_bits, _tables())
        crcs_np = np.asarray(crcs)
    g_devprof.account_d2h("ec.crc_fetch", crcs_np.nbytes)
    S, _k, C = stripes.shape
    length = S * C
    return {i: DeviceShard(bodies[i], length, int(crcs_np[i]))
            for i in range(bodies.shape[0])}
