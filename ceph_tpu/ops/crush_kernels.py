"""Vmapped device CRUSH mapper — all PGs in one jitted call.

This is the TPU twin of the host interpreter (ceph_tpu/crush/mapper.py,
semantics of reference src/crush/mapper.c:883-1087).  A (CrushMap, rule) pair
is *compiled* on the host into dense tensors — per-bucket item/weight tables
padded to the max fanout, the crush_ln LUTs, the device in/out weight vector —
and the rule's step program is unrolled at trace time into a fixed tensor
program evaluated for every input x (PG) in one vmapped call:

- straw2 draw: rjenkins hash32_3 in uint32 lanes, crush_ln via two 256-entry
  LUT gathers, the fixed-point s64 division, first-wins argmax
  (mapper.c:322-367) — bit-identical winners.
- firstn/indep retry semantics: the exact r' = rep + parent_r + ftotal
  (firstn) / rep + parent_r + numrep*ftotal (indep) sequences as bounded
  `lax.while_loop`s, collision/out-rejection/NONE conventions preserved
  (mapper.c:443-636, :638-790).
- chooseleaf recursion (vary_r, stable tunables) as a nested bounded loop.

Scope (checked by `compile_map`, everything else falls back to the host
mapper): straw2 buckets only (the modern default since hammer) and
bobtail+ tunables (choose_local_tries == choose_local_fallback_tries == 0).
Rules may chain TAKE / CHOOSE / CHOOSELEAF / SET_* / EMIT steps arbitrarily.

64-bit note: the straw2 divide is exact u64 math, which requires jax x64
mode *during tracing*.  Rather than flipping the global ``jax_enable_x64``
flag at import (a surprising process-wide side effect), the public entry
point (``DeviceCrushMapper.map_batch``) scopes it with the
``jax.enable_x64`` context manager; module-level constants stay numpy so
nothing 64-bit is materialized outside that scope.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crush.constants import (
    CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE, CRUSH_ITEM_UNDEF,
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_STABLE, CRUSH_RULE_SET_CHOOSELEAF_TRIES,
    CRUSH_RULE_SET_CHOOSELEAF_VARY_R, CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE,
)
from ..arch import enable_x64
from ..crush.ln import LL_NP, RH_LH_NP
from ..crush.types import CrushMap

MAX_DESCENT = 12  # > CRUSH_MAX_DEPTH (crush.h:26)
_U64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)
_LN_BIAS = np.uint64(0x1000000000000)  # 2^48 (mapper.c:342)

_SEED = np.uint32(1315423911)
_PAD1 = np.uint32(231232)
_PAD2 = np.uint32(1232)


# ---- rjenkins in uint32 lanes (crush/hash.c) ------------------------------

def _mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> 13)
    b = b - c; b = b - a; b = b ^ (a << 8)
    c = c - a; c = c - b; c = c ^ (b >> 13)
    a = a - b; a = a - c; a = a ^ (c >> 12)
    b = b - c; b = b - a; b = b ^ (a << 16)
    c = c - a; c = c - b; c = c ^ (b >> 5)
    a = a - b; a = a - c; a = a ^ (c >> 3)
    b = b - c; b = b - a; b = b ^ (a << 10)
    c = c - a; c = c - b; c = c ^ (b >> 15)
    return a, b, c


def hash32_2(a, b):
    a = a.astype(jnp.uint32); b = b.astype(jnp.uint32)
    h = _SEED ^ a ^ b
    x = jnp.broadcast_to(_PAD1, a.shape)
    y = jnp.broadcast_to(_PAD2, a.shape)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash32_3(a, b, c):
    a = a.astype(jnp.uint32); b = b.astype(jnp.uint32)
    c = c.astype(jnp.uint32)
    a, b, c = jnp.broadcast_arrays(a, b, c)
    h = _SEED ^ a ^ b ^ c
    x = jnp.broadcast_to(_PAD1, h.shape)
    y = jnp.broadcast_to(_PAD2, h.shape)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


# ---- crush_ln LUT evaluation (mapper.c:243-290) ---------------------------

def _ln_tables():
    """u64 log LUTs as jnp constants, created at use site so the uint64
    conversion happens inside the caller's enable_x64 scope.  Deliberately
    uncached: under a jit trace the result is a tracer that must not leak
    into module state; XLA folds the constants per compiled program."""
    return jnp.asarray(RH_LH_NP), jnp.asarray(LL_NP)


def crush_ln_dev(u):
    """2^44*log2(u+1) fixed point; u: uint32 in [0, 0xffff]."""
    _RH_LH, _LL = _ln_tables()
    x = (u + jnp.uint32(1)).astype(jnp.uint32)
    blen = jnp.uint32(32) - lax.clz(x & jnp.uint32(0x1FFFF))
    need = (x & jnp.uint32(0x18000)) == 0
    bits = jnp.where(need, jnp.uint32(16) - blen, jnp.uint32(0))
    x = x << bits
    iexpon = jnp.where(need, jnp.uint32(15) - bits, jnp.uint32(15))
    index1 = ((x >> 8) << 1).astype(jnp.int32)
    rh = _RH_LH[index1 - 256]
    lh = _RH_LH[index1 + 1 - 256]
    xl64 = (x.astype(jnp.uint64) * rh) >> jnp.uint64(48)
    index2 = (xl64 & jnp.uint64(0xFF)).astype(jnp.int32)
    ll = _LL[index2]
    return ((iexpon.astype(jnp.uint64) << jnp.uint64(44))
            + ((lh + ll) >> jnp.uint64(4)))


# ---- compiled map ---------------------------------------------------------

class CompiledCrushMap:
    """Dense-tensor form of a straw2 CrushMap (+choose_args) for the device.

    Buckets are indexed by ``-1 - id``.  ``weights`` carries the per-position
    straw2 weight sets (crush.h:273 crush_choose_arg); position 0 is the
    plain item_weights when no choose_args are attached.
    """

    def __init__(self, m: CrushMap,
                 choose_args: Optional[Sequence] = None,
                 allow_legacy: bool = False):
        """``allow_legacy`` additionally admits straw(v1) buckets and
        pre-bobtail local-tries tunables — consumed only by the legacy
        fast path (ops/crush_legacy.py), which models those semantics;
        the plain loop kernel does not."""
        nb = len(m.buckets)
        S = max((b.size for b in m.buckets if b is not None), default=1)
        S = max(S, 1)
        items = np.full((nb, S), CRUSH_ITEM_NONE, dtype=np.int32)
        hash_ids = np.zeros((nb, S), dtype=np.int32)
        sizes = np.zeros(nb, dtype=np.int32)
        types = np.zeros(nb, dtype=np.int32)
        npos = 1
        if choose_args is not None:
            for arg in choose_args:
                if arg is not None and arg.weight_set:
                    npos = max(npos, len(arg.weight_set))
        weights = np.zeros((npos, nb, S), dtype=np.uint32)
        algs = np.zeros(nb, dtype=np.int32)
        straws = np.zeros((nb, S), dtype=np.uint32)
        for bi, b in enumerate(m.buckets):
            if b is None:
                continue
            if b.size and b.alg != CRUSH_BUCKET_STRAW2:
                from ..crush.constants import CRUSH_BUCKET_STRAW
                if not (allow_legacy and b.alg == CRUSH_BUCKET_STRAW):
                    raise ValueError(
                        "device mapper supports straw2 buckets only")
                straws[bi, :b.size] = np.asarray(b.straws,
                                                 dtype=np.uint32)
            algs[bi] = b.alg
            sizes[bi] = b.size
            types[bi] = b.type
            items[bi, :b.size] = b.items
            hash_ids[bi, :b.size] = b.items
            for it in b.items:
                if it >= 0 and it >= m.max_devices:
                    raise ValueError("bucket item beyond max_devices")
                if it < 0 and m.bucket(it) is None:
                    raise ValueError("dangling bucket reference")
            w = np.asarray(b.item_weights, dtype=np.uint32)
            weights[:, bi, :b.size] = w[None, :]
            arg = None
            if choose_args is not None and bi < len(choose_args):
                arg = choose_args[bi]
            if arg is not None:
                if arg.weight_set:
                    for p in range(npos):
                        ws = arg.weight_set[min(p, len(arg.weight_set) - 1)]
                        weights[p, bi, :b.size] = np.asarray(
                            ws.weights, dtype=np.uint32)
                if arg.ids:
                    hash_ids[bi, :b.size] = arg.ids
        if not allow_legacy and (m.choose_local_tries
                                 or m.choose_local_fallback_tries):
            raise ValueError("device mapper requires bobtail+ tunables "
                             "(choose_local_*_tries == 0)")
        self.map = m
        self.nbuckets = nb
        self.max_size = S
        self.npos = npos
        self.algs = np.asarray(algs)
        # straw(v1) scalers only matter to the legacy path; don't pay a
        # device transfer of zeros on every production compile
        self.straws = jnp.asarray(straws) if allow_legacy else None
        self.items = jnp.asarray(items)
        self.hash_ids = jnp.asarray(hash_ids)
        self.sizes = jnp.asarray(sizes)
        self.types = jnp.asarray(types)
        self.weights = jnp.asarray(weights)
        # f32 reciprocals for the fast-path draw (crush_fast.py); 0 marks
        # zero-weight lanes
        with np.errstate(divide="ignore"):
            inv = np.where(weights > 0, 1.0 / weights.astype(np.float64),
                           0.0)
        self.inv_weights = jnp.asarray(inv.astype(np.float32))
        self.lane = jnp.arange(S, dtype=jnp.int32)


def _straw2_choose(C: CompiledCrushMap, bidx, x, r, position):
    """First-wins straw2 argmax over one bucket row (mapper.c:322-367)."""
    ids = C.hash_ids[bidx]
    out_items = C.items[bidx]
    pos = jnp.minimum(position, C.npos - 1)
    ws = C.weights[pos, bidx]
    u = hash32_3(x, ids, r) & jnp.uint32(0xFFFF)
    # draw = -((2^48 - ln) / w); argmax(draw) == first-wins argmin(q)
    q_num = _LN_BIAS - crush_ln_dev(u)
    valid = (C.lane < C.sizes[bidx]) & (ws > 0)
    q = jnp.where(valid, q_num // jnp.maximum(ws, 1).astype(jnp.uint64),
                  _U64_MAX)
    return out_items[jnp.argmin(q)]


_OK, _DEAD, _EMPTY = 0, 1, 2


def _descend(C: CompiledCrushMap, item, x, r, position, target_type):
    """Walk down from *item* until an item of *target_type* is reached.

    Mirrors the itemtype-mismatch descent in both choosers (mapper.c:498-520,
    :691-713): r is constant during the walk.  Returns (item, status) with
    status _DEAD for a wrong-type dead end and _EMPTY for an empty bucket.

    Do-while semantics: the reference always draws one item from the
    starting bucket before any type test (crush_bucket_choose precedes the
    itemtype check, mapper.c:487-498), so a choose step whose target type
    equals the take bucket's own type still descends one level rather than
    returning the take bucket itself.
    """
    def itype(it):
        return jnp.where(it >= 0, 0, C.types[jnp.maximum(-1 - it, 0)])

    def cond(st):
        it, status, depth = st
        return ((status == _OK) & (itype(it) != target_type)
                & (depth < MAX_DESCENT))

    def body(st):
        it, status, depth = st
        dead = it >= 0  # device of the wrong type: no sub-bucket
        bidx = jnp.maximum(-1 - it, 0)
        empty = C.sizes[bidx] == 0
        nxt = _straw2_choose(C, bidx, x, r, position)
        it2 = jnp.where(dead | empty, it, nxt)
        status2 = jnp.where(dead, _DEAD, jnp.where(empty, _EMPTY, status))
        return it2, status2, depth + 1

    first = body((item, jnp.int32(_OK), jnp.int32(0)))
    it, status, depth = lax.while_loop(cond, body, first)
    status = jnp.where((status == _OK) & (itype(it) != target_type),
                       _DEAD, status)
    return it, status


def _is_out(dev_weight, item, x):
    """Weight-based rejection of a device (mapper.c:407-441)."""
    w = dev_weight[jnp.maximum(item, 0)]
    h = hash32_2(x, item) & jnp.uint32(0xFFFF)
    return jnp.where(w >= 0x10000, False,
                     jnp.where(w == 0, True, h >= w))


# ---- choosers (scalar-x; vmapped by the executor) -------------------------

def _choose_firstn(C, dev_weight, take_item, take_ok, x, numrep, target_type,
                   tries, recurse_tries, recurse_to_leaf, vary_r, stable):
    """crush_choose_firstn with bobtail+ tunables (mapper.c:443-636).

    With choose_local_tries == choose_local_fallback_tries == 0 every
    reject/collision restarts the descent from the take bucket with
    ftotal+1 — exactly the modern tunable profiles.  Returns per-slot
    (items, leaves); failed slots hold CRUSH_ITEM_NONE.
    """
    NONE = jnp.int32(CRUSH_ITEM_NONE)
    outs = jnp.full(numrep, NONE)
    out2s = jnp.full(numrep, NONE)
    nsucc = jnp.int32(0)

    for slot in range(numrep):
        rep = jnp.int32(slot)

        def leaf_choose(item, r, nsucc_now, out2s_now):
            """The recursive numrep=1 call (mapper.c:541-558)."""
            sub_r = (r >> (vary_r - 1)) if vary_r else jnp.int32(0)
            rep_in = jnp.int32(0) if stable else nsucc_now

            def lcond(st):
                ft2, leaf, done = st
                return (~done) & (ft2 < recurse_tries)

            def lbody(st):
                ft2, leaf, done = st
                r2 = rep_in + sub_r + ft2
                cand, status = _descend(C, item, x, r2, nsucc_now, 0)
                coll = jnp.any(out2s_now == cand)
                rej = _is_out(dev_weight, cand, x)
                good = (status == _OK) & ~coll & ~rej
                return (ft2 + 1, jnp.where(good, cand, leaf), good)

            _, leaf, ok = lax.while_loop(
                lcond, lbody, (jnp.int32(0), NONE, jnp.bool_(False)))
            return leaf, ok

        def scond(st):
            ftotal, item, leaf, success, aborted = st
            return (~success) & (~aborted) & (ftotal < tries)

        def sbody(st):
            ftotal, item, leaf, success, aborted = st
            r = rep + ftotal
            cand, status = _descend(C, take_item, x, r, nsucc, target_type)
            coll = jnp.any(outs == cand)
            base_rej = (_is_out(dev_weight, cand, x)
                        if target_type == 0 else jnp.bool_(False))
            if recurse_to_leaf:
                lf, lok = leaf_choose(cand, r, nsucc, out2s)
                lf = jnp.where(cand >= 0, cand, lf)
                lok = jnp.where(cand >= 0, True, lok)
                reject = ~lok | base_rej
            else:
                lf = cand
                reject = base_rej
            ok_now = (status == _OK) & ~coll & ~reject
            dead = status == _DEAD
            return (ftotal + 1,
                    jnp.where(ok_now, cand, item),
                    jnp.where(ok_now, lf, leaf),
                    ok_now,
                    dead)

        init = (jnp.int32(0), NONE, NONE, jnp.bool_(False), ~take_ok)
        _, item, leaf, success, _ = lax.while_loop(scond, sbody, init)
        outs = outs.at[slot].set(jnp.where(success, item, NONE))
        out2s = out2s.at[slot].set(jnp.where(success, leaf, NONE))
        nsucc = nsucc + success.astype(jnp.int32)
    return outs, out2s


def _choose_indep(C, dev_weight, take_item, take_ok, x, out_size, numrep,
                  target_type, tries, recurse_tries, recurse_to_leaf,
                  parent_r, position):
    """crush_choose_indep rounds (mapper.c:638-790): UNDEF slots are retried
    with r' = rep + parent_r + numrep*ftotal until tries are exhausted, dead
    ends become CRUSH_ITEM_NONE immediately."""
    NONE = jnp.int32(CRUSH_ITEM_NONE)
    UNDEF = jnp.int32(CRUSH_ITEM_UNDEF)
    outs = jnp.where(take_ok, jnp.full(out_size, UNDEF),
                     jnp.full(out_size, NONE))
    out2s = jnp.full(out_size, UNDEF)

    def leaf_indep(item, r_parent, rep):
        """Inner left=1 recursion (mapper.c:725-741); UNDEF → NONE on exit."""
        def lcond(st):
            ft2, leaf = st
            return (leaf == UNDEF) & (ft2 < recurse_tries)

        def lbody(st):
            ft2, leaf = st
            r2 = rep + r_parent + numrep * ft2
            cand, status = _descend(C, item, x, r2, rep, 0)
            rej = _is_out(dev_weight, cand, x)
            good = (status == _OK) & ~rej
            dead = status == _DEAD
            return (ft2 + 1,
                    jnp.where(good, cand, jnp.where(dead, NONE, leaf)))

        _, leaf = lax.while_loop(lcond, lbody, (jnp.int32(0), UNDEF))
        return jnp.where(leaf == UNDEF, NONE, leaf)

    def rcond(st):
        outs, out2s, ftotal = st
        return jnp.any(outs == UNDEF) & (ftotal < tries)

    def rbody(st):
        outs, out2s, ftotal = st
        for slot in range(out_size):
            rep = jnp.int32(slot)
            unfilled = outs[slot] == UNDEF
            r = rep + parent_r + numrep * ftotal
            cand, status = _descend(C, take_item, x, r, position, target_type)
            coll = jnp.any(outs == cand)
            if recurse_to_leaf:
                sub = leaf_indep(cand, r, rep)
                # a device chosen directly becomes its own leaf
                # (mapper.c:736-739)
                leaf = jnp.where(cand >= 0, cand, sub)
                leaf_fail = jnp.where(cand >= 0, False, sub == NONE)
            else:
                leaf = cand
                leaf_fail = jnp.bool_(False)
            rej = (_is_out(dev_weight, cand, x)
                   if target_type == 0 else jnp.bool_(False))
            dead = status == _DEAD
            good = (status == _OK) & ~coll & ~leaf_fail & ~rej
            new_item = jnp.where(dead, NONE, jnp.where(good, cand, UNDEF))
            new_leaf = jnp.where(dead, NONE, jnp.where(good, leaf, UNDEF))
            outs = outs.at[slot].set(jnp.where(unfilled, new_item, outs[slot]))
            out2s = out2s.at[slot].set(
                jnp.where(unfilled, new_leaf, out2s[slot]))
        return outs, out2s, ftotal + 1

    outs, out2s, _ = lax.while_loop(
        rcond, rbody, (outs, out2s, jnp.int32(0)))
    outs = jnp.where(outs == UNDEF, NONE, outs)
    out2s = jnp.where(out2s == UNDEF, NONE, out2s)
    return outs, out2s


# ---- rule executor --------------------------------------------------------

class DeviceCrushMapper:
    """Evaluates one rule for a batch of x values on the device.

    The rule's steps are unrolled at trace time (crush rules are short
    programs, mapper.c:899-1087); slot lists thread (value, present) pairs
    between steps the way do_rule's w/o vectors do, and EMIT compacts
    present slots in order.
    """

    def __init__(self, compiled: CompiledCrushMap, ruleno: int,
                 result_max: int,
                 choose_args: Optional[Sequence] = None):
        m = compiled.map
        rule = m.rules[ruleno]
        if rule is None:
            raise ValueError(f"no rule {ruleno}")
        self.C = compiled
        self.rule = rule
        self.result_max = result_max
        self._fn = jax.jit(jax.vmap(self._one_x, in_axes=(0, None)))

    def _one_x(self, x, dev_weight):
        C, m, result_max = self.C, self.C.map, self.result_max
        x = x.astype(jnp.uint32)
        NONE = jnp.int32(CRUSH_ITEM_NONE)

        choose_tries = m.choose_total_tries + 1  # mapper.c:905 off-by-one
        choose_leaf_tries = 0
        vary_r = m.chooseleaf_vary_r
        stable = m.chooseleaf_stable

        slots: List[Tuple] = []   # (value tracer, present tracer)
        emitted: List[Tuple] = []

        for step in self.rule.steps:
            op = step.op
            if op == CRUSH_RULE_TAKE:
                ok = (0 <= step.arg1 < m.max_devices
                      or m.bucket(step.arg1) is not None)
                if ok:
                    slots = [(jnp.int32(step.arg1), jnp.bool_(True))]
            elif op == CRUSH_RULE_SET_CHOOSE_TRIES:
                if step.arg1 > 0:
                    choose_tries = step.arg1
            elif op == CRUSH_RULE_SET_CHOOSELEAF_TRIES:
                if step.arg1 > 0:
                    choose_leaf_tries = step.arg1
            elif op in (CRUSH_RULE_SET_CHOOSE_LOCAL_TRIES,
                        CRUSH_RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
                if step.arg1 > 0:
                    raise ValueError("local tries unsupported on device")
            elif op == CRUSH_RULE_SET_CHOOSELEAF_VARY_R:
                if step.arg1 >= 0:
                    vary_r = step.arg1
            elif op == CRUSH_RULE_SET_CHOOSELEAF_STABLE:
                if step.arg1 >= 0:
                    stable = step.arg1
            elif op in (CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                        CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_CHOOSELEAF_INDEP):
                firstn = op in (CRUSH_RULE_CHOOSE_FIRSTN,
                                CRUSH_RULE_CHOOSELEAF_FIRSTN)
                leafy = op in (CRUSH_RULE_CHOOSELEAF_FIRSTN,
                               CRUSH_RULE_CHOOSELEAF_INDEP)
                numrep = step.arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                new_slots: List[Tuple] = []
                for (val, present) in slots:
                    # devices / NONE inputs contribute nothing (do_rule
                    # skips w[i] without a bucket)
                    ok = present & (val < 0)
                    if firstn:
                        if choose_leaf_tries:
                            recurse = choose_leaf_tries
                        elif m.chooseleaf_descend_once:
                            recurse = 1
                        else:
                            recurse = choose_tries
                        outs, out2s = _choose_firstn(
                            C, dev_weight, val, ok, x, numrep, step.arg2,
                            choose_tries, recurse, leafy, vary_r, stable)
                        sel = out2s if leafy else outs
                        for j in range(numrep):
                            v = sel[j]
                            new_slots.append((v, ok & (v != NONE)))
                    else:
                        recurse = choose_leaf_tries if choose_leaf_tries else 1
                        out_size = min(numrep, result_max)
                        outs, out2s = _choose_indep(
                            C, dev_weight, val, ok, x, out_size, numrep,
                            step.arg2, choose_tries, recurse, leafy,
                            jnp.int32(0), jnp.int32(0))
                        sel = out2s if leafy else outs
                        for j in range(out_size):
                            # indep emits NONE holes, but they are still
                            # skipped by any chained choose step
                            new_slots.append((sel[j], ok))
                slots = new_slots
            elif op == CRUSH_RULE_EMIT:
                emitted.extend(slots)
                slots = []

        if not emitted:
            return (jnp.full(result_max, NONE), jnp.int32(0))
        vals = jnp.stack([v for v, _ in emitted])
        present = jnp.stack([p for _, p in emitted])
        pos = jnp.cumsum(present.astype(jnp.int32)) - 1
        result = jnp.full(result_max, NONE)
        write = present & (pos < result_max)
        result = result.at[jnp.where(write, pos, result_max)].set(
            jnp.where(write, vals, NONE), mode="drop")
        count = jnp.minimum(jnp.sum(present.astype(jnp.int32)), result_max)
        return result, count

    def map_batch(self, xs: np.ndarray, weight: np.ndarray):
        """Map all xs; returns (results [X, result_max] int32, counts [X])."""
        with enable_x64():
            xs = jnp.asarray(np.asarray(xs, dtype=np.uint32))
            w = jnp.asarray(np.asarray(weight, dtype=np.uint32))
            res, cnt = self._fn(xs, w)
        return res, cnt


def compile_map(m: CrushMap, choose_args=None) -> CompiledCrushMap:
    """Host-side compilation; raises ValueError if unsupported on device."""
    return CompiledCrushMap(m, choose_args)
