"""crc32c (Castagnoli) as a jitted device kernel, fused into EC encode.

Bit-identical to ``utils/crc32c.py`` (Ceph's conventions: seed -1, no
final inversion) so a digest computed on-device can be compared against
a stored HashInfo digest or re-checked by the host path at any time.

Formulation: slicing-by-8 with eight host-precomputed 256-entry uint32
tables (the classic Intel construction — table k advances the CRC past
k+1 bytes).  The body consumes the buffer as 8-byte little-endian words
in a ``fori_loop`` and finishes the non-word-aligned tail byte-at-a-time.
The buffer LENGTH is a *traced* operand over a fixed padded shape, so
one compiled program serves every length that fits the pad — the
0..4097 property sweep compiles once, and the fused encode kernel can
vmap it across all n shards of a stripe batch.

Gathers from (8, 256) tables do not tile onto the MXU the way the GF
matmul does, but the CRC runs on the VPU *after* the encode inside the
same jit, overlapping the epilogue with the systolic work — and the
whole point is what it deletes: the d2h of every shard body that the
host hash used to force.
"""
from __future__ import annotations

import functools
import threading
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..common.lockdep import DebugLock
from ..trace.devprof import g_devprof
from ..utils.crc32c import _TABLE


@functools.lru_cache(maxsize=1)
def _slicing_tables_np() -> np.ndarray:
    """(8, 256) uint32: row 0 is the byte table, row k advances k+1 bytes."""
    t = np.zeros((8, 256), dtype=np.uint32)
    t[0] = _TABLE
    for k in range(1, 8):
        t[k] = t[0][t[k - 1] & 0xFF] ^ (t[k - 1] >> np.uint32(8))
    return t


_tables_dev: Optional[jnp.ndarray] = None
_tables_lock = DebugLock("crc32c_device::tables")


def _tables() -> jnp.ndarray:
    """The slicing tables as a device array (uploaded once, accounted)."""
    global _tables_dev
    if _tables_dev is not None:
        return _tables_dev
    with _tables_lock:
        if _tables_dev is None:
            host = _slicing_tables_np()
            g_devprof.account_h2d("crc32c.tables", host.nbytes)
            _tables_dev = jnp.asarray(host)
    return _tables_dev


def device_crc_available() -> bool:
    """True when jax can run the kernel at all (any backend)."""
    try:
        return bool(jax.devices())
    except Exception:
        return False


def _crc_one(padded: jnp.ndarray, length: jnp.ndarray,
             tables: jnp.ndarray) -> jnp.ndarray:
    """CRC of ``padded[:length]``; padded is 1-D uint8, len % 8 == 0.

    ``length`` is traced: the word loop and the tail loop both carry
    dynamic trip counts, so one compile covers every length <= the pad.
    """
    words = padded.reshape(-1, 8).astype(jnp.uint32)
    length = length.astype(jnp.uint32)
    nwords = length // 8

    def word_body(i, c):
        w = words[i]
        lo = c ^ (w[0] | (w[1] << 8) | (w[2] << 16) | (w[3] << 24))
        return (tables[7][lo & 0xFF]
                ^ tables[6][(lo >> 8) & 0xFF]
                ^ tables[5][(lo >> 16) & 0xFF]
                ^ tables[4][(lo >> 24) & 0xFF]
                ^ tables[3][w[4]]
                ^ tables[2][w[5]]
                ^ tables[1][w[6]]
                ^ tables[0][w[7]])

    c = jax.lax.fori_loop(jnp.uint32(0), nwords, word_body,
                          jnp.uint32(0xFFFFFFFF))

    flat = padded.astype(jnp.uint32)

    def byte_body(i, c):
        return tables[0][(c ^ flat[i]) & 0xFF] ^ (c >> 8)

    return jax.lax.fori_loop(nwords * 8, length, byte_body, c)


_crc_batch = jax.jit(jax.vmap(_crc_one, in_axes=(0, 0, None)))


def crc_core(bodies: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    """(n, L) uint8 device bodies -> (n,) uint32 CRCs; jit-composable.

    Pads each row to a word multiple inside the trace (static shape
    math) and runs the vmapped traced-length core, so fusing this after
    an encode adds no host round-trip.
    """
    n, L = bodies.shape
    pad = (-L) % 8
    if pad:
        bodies = jnp.pad(bodies, ((0, 0), (0, pad)))
    lengths = jnp.full((n,), L, dtype=jnp.uint32)
    return jax.vmap(_crc_one, in_axes=(0, 0, None))(bodies, lengths, tables)


def crc32c_device_batch(arr2d) -> np.ndarray:
    """Host entry: (n, L) uint8 -> (n,) python-side uint32 CRCs.

    The single (n * 4)-byte fetch is the caller's to account; this is
    the standalone verify/scrub entry, not the fused encode path.
    """
    a = np.ascontiguousarray(np.asarray(arr2d, dtype=np.uint8))
    n, L = a.shape
    pad = (-L) % 8
    if pad:
        a = np.pad(a, ((0, 0), (0, pad)))
    lengths = jnp.full((n,), L, dtype=jnp.uint32)
    out = _crc_batch(jnp.asarray(a), lengths, _tables())
    return np.asarray(out)


@jax.jit
def _crc_dev_one(dev: jnp.ndarray, tables: jnp.ndarray) -> jnp.ndarray:
    return crc_core(dev[None, :], tables)[0]


def crc32c_of_device_array(dev) -> int:
    """CRC of a 1-D uint8 DEVICE array without fetching the body: the
    kernel runs where the bytes live and only the 4-byte scalar comes
    back (accounted at ``crc32c.verify_fetch``) — the scrub/read-verify
    path for still-resident shards."""
    out = np.asarray(_crc_dev_one(dev, _tables()))
    g_devprof.account_d2h("crc32c.verify_fetch", out.nbytes)
    return int(out)


def crc32c_device_padded(padded2d, lengths) -> np.ndarray:
    """Property-test entry: (n, L8) uint8 + per-row traced lengths.

    One compile for the whole 0..4097 sweep when every call reuses the
    same padded shape.
    """
    a = np.ascontiguousarray(np.asarray(padded2d, dtype=np.uint8))
    assert a.shape[1] % 8 == 0
    ln = jnp.asarray(np.asarray(lengths, dtype=np.uint32))
    out = _crc_batch(jnp.asarray(a), ln, _tables())
    return np.asarray(out)
