"""The mgr's damped feedback controller: SLO streaks in, ONE bounded
knob step out (docs/CONTROL.md).

PRs 10–15 built both halves of a control loop and never connected
them: the telemetry SLO burn-rate engine (``TPU_SLO_*`` sustain/clear
hysteresis) is a sensor, and the QoS/recovery/mesh options —
``osd_mclock_*``, ``osd_op_queue_admission_max``,
``osd_recovery_max_active``, ``ec_mesh_rateless_tasks`` — are
actuators that all take live config injection.  This module is the
wire between them: :meth:`Controller.step` runs once per mgr tick
(after ``Telemetry.tick`` so the streak state is fresh) and actuates
AT MOST one bounded step per tick on the one knob its policy map
holds responsible, through the SAME ``set_checked`` path injectargs
uses, so every daemon sees the move exactly as if an operator typed
it.

Stability is structural, not tuned:

- every knob has a floor and a ceiling (built-in, operator-overridable
  via ``mgr_control_bounds``) and a move is clamped into them;
- a knob rests ``mgr_control_cooldown_ticks`` after any move —
  at most one step per cooldown window per knob;
- successive same-direction steps shrink geometrically
  (``mgr_control_damping``), so a persistent breach converges on a
  value instead of slamming between bounds;
- a step clamped into the value it started from is NOT a move
  (anti-windup: a breach pinning a knob at its bound accrues no
  ledger entries, no cooldowns, no state);
- the first tighten on a knob records the pre-episode baseline; when
  the pressure clears (the check's own clear hysteresis) the
  controller walks the knob back toward that baseline, and disabling
  the controller mid-episode restores every engaged knob immediately
  (tear-down) — no half-applied knob survives ``mgr_control_enable
  = false``.

With ``mgr_control_enable`` off (the default) :meth:`Controller.step`
returns before sensing anything: the mgr is today's observer by
construction, not by configuration distance.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ..common.config import g_conf
from ..common.lockdep import DebugLock
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.journal import g_journal

# wasted coded blocks per launched block (per sense window) above
# which the rateless width is judged uneconomical while skew is quiet;
# the healthy parity fraction at the auto width (~2/(size+2)) sits
# below it, so narrowing only triggers on widened-but-idle protection
WASTE_RATIO_MAX = 0.30
# consecutive controller ticks a skew / waste signal must hold before
# the straggler reflex moves (its own sustain hysteresis — the mesh
# health check flaps more than a width decision should)
STRAGGLER_STREAK = 2

# ---- perf counters (perf dump / Prometheus ceph_daemon_control_*) ----
CONTROL_FIRST = 94000
l_ctl_ticks = 94001              # enabled controller evaluations
l_ctl_moves = 94002              # actuations applied (any direction)
l_ctl_tightens = 94003           # breach-direction moves
l_ctl_restores = 94004           # toward-baseline moves (episode decay)
l_ctl_pinned = 94005             # steps suppressed at a bound
l_ctl_retries = 94006            # actuation re-attempts within a tick
l_ctl_failures = 94007           # actuations dropped past the retry budget
l_ctl_episodes = 94008           # episodes opened (first tighten on a knob)
l_ctl_reverts = 94009            # knobs restored by disable/reset tear-down
l_ctl_skipped_cooldown = 94010   # reflex wishes parked by a resting knob
l_ctl_engaged = 94011            # gauge: knobs currently off-baseline
l_ctl_enabled = 94012            # gauge: master enable as last evaluated
CONTROL_LAST = 94020

_ctl_pc: Optional[PerfCounters] = None
_ctl_pc_lock = DebugLock("control_pc::init")


def control_perf_counters() -> PerfCounters:
    """The control plane's counter logger (perf dump / Prometheus
    ``ceph_daemon_control_*``)."""
    global _ctl_pc
    if _ctl_pc is not None:
        return _ctl_pc
    with _ctl_pc_lock:
        if _ctl_pc is None:
            b = PerfCountersBuilder("control", CONTROL_FIRST,
                                    CONTROL_LAST)
            b.add_u64_counter(l_ctl_ticks, "ticks",
                              "controller evaluations while enabled")
            b.add_u64_counter(l_ctl_moves, "moves",
                              "bounded knob actuations applied")
            b.add_u64_counter(l_ctl_tightens, "tightens",
                              "breach-direction moves")
            b.add_u64_counter(l_ctl_restores, "restores",
                              "toward-baseline moves after a clear")
            b.add_u64_counter(l_ctl_pinned, "pinned",
                              "steps suppressed because the knob sits "
                              "at its bound (anti-windup)")
            b.add_u64_counter(l_ctl_retries, "actuate_retries",
                              "actuation re-attempts within one tick "
                              "(fault site control.actuate)")
            b.add_u64_counter(l_ctl_failures, "actuate_failures",
                              "actuations dropped after the bounded "
                              "retry budget")
            b.add_u64_counter(l_ctl_episodes, "episodes",
                              "control episodes opened (first tighten "
                              "records the baseline)")
            b.add_u64_counter(l_ctl_reverts, "teardown_reverts",
                              "knobs restored to baseline by disable/"
                              "reset tear-down")
            b.add_u64_counter(l_ctl_skipped_cooldown, "skipped_cooldown",
                              "reflex wishes parked because the "
                              "responsible knob was resting")
            b.add_u64(l_ctl_engaged, "engaged_knobs",
                      "knobs currently moved off their episode "
                      "baseline")
            b.add_u64(l_ctl_enabled, "enabled",
                      "master enable as last evaluated by a tick")
            _ctl_pc = b.create_perf_counters()
    return _ctl_pc


class _Move:
    __slots__ = ("knob", "cur", "new", "restore", "reflex", "reason")

    def __init__(self, knob: str, cur: float, new: float, restore: bool,
                 reflex: str, reason: str):
        self.knob = knob
        self.cur = cur
        self.new = new
        self.restore = restore
        self.reflex = reflex
        self.reason = reason


class _Knob:
    """One controlled dial: how to read its live value, how to encode
    a new value into a config injection, its built-in bounds, and its
    step shape.  ``kind``:

    - ``"int"`` / ``"float"``: multiplicative half-steps
      (``cur * 0.5 * scale`` with the episode's damping scale);
    - ``"unit"``: +-1 per move (the rateless width — already minimal);
    - ``"cap"``: like ``float`` but 0 means uncapped, and the first
      tighten IMPOSES the cap at the ceiling.
    """

    __slots__ = ("name", "kind", "floor", "ceiling", "get", "encode")

    def __init__(self, name: str, kind: str,
                 floor: Callable[["Controller"], Optional[float]],
                 ceiling: Callable[["Controller"], Optional[float]],
                 get: Callable[["Controller"], Optional[float]],
                 encode: Callable[["Controller", float],
                                  Tuple[str, Any]]):
        self.name = name
        self.kind = kind
        self.floor = floor
        self.ceiling = ceiling
        self.get = get
        self.encode = encode


def _parse_triples(src: str) -> Dict[str, Tuple[float, float, float]]:
    """'key:a:b:c[,key:...]' -> {key: (a, b, c)}; malformed entries
    are dropped (the same tolerance the dmClock parsers apply)."""
    out: Dict[str, Tuple[float, float, float]] = {}
    for part in str(src or "").replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.rsplit(":", 3)
        if len(bits) != 4:
            continue
        try:
            out[bits[0]] = (float(bits[1]), float(bits[2]),
                            float(bits[3]))
        except ValueError:
            continue
    return out


def _encode_triples(d: Dict[str, Tuple[float, float, float]]) -> str:
    return ",".join(f"{k}:{v[0]:g}:{v[1]:g}:{v[2]:g}"
                    for k, v in sorted(d.items()))


def _client_defaults() -> Tuple[float, float, float]:
    return (float(g_conf.get_val("osd_mclock_client_reservation")),
            float(g_conf.get_val("osd_mclock_client_weight")),
            float(g_conf.get_val("osd_mclock_client_limit")))


def _client_overrides() -> Dict[str, Tuple[float, float, float]]:
    return _parse_triples(g_conf.get_val("osd_mclock_client_overrides"))


def _abuser_lane(ctrl: "Controller") -> Optional[str]:
    return ctrl._abuser


def _get_lane_field(ctrl: "Controller", field: int) -> Optional[float]:
    lane = _abuser_lane(ctrl)
    if lane is None:
        return None
    return _client_overrides().get(lane, _client_defaults())[field]


def _encode_lane_field(ctrl: "Controller", field: int,
                       value: float) -> Tuple[str, Any]:
    lane = _abuser_lane(ctrl)
    ov = _client_overrides()
    cur = list(ov.get(lane, _client_defaults()))
    cur[field] = value
    ov[lane] = (cur[0], cur[1], cur[2])
    return "osd_mclock_client_overrides", _encode_triples(ov)


def _recovery_class_tags() -> Tuple[float, float, float]:
    from ..common.work_queue import CLASS_RECOVERY, DEFAULT_TAGS
    ov = _parse_triples(g_conf.get_val("osd_mclock_class_overrides"))
    return ov.get(CLASS_RECOVERY, DEFAULT_TAGS[CLASS_RECOVERY])


def _encode_recovery_weight(ctrl: "Controller",
                            value: float) -> Tuple[str, Any]:
    from ..common.work_queue import CLASS_RECOVERY
    ov = _parse_triples(g_conf.get_val("osd_mclock_class_overrides"))
    res, _w, lim = _recovery_class_tags()
    ov[CLASS_RECOVERY] = (res, value, lim)
    return "osd_mclock_class_overrides", _encode_triples(ov)


def _mesh_size() -> Optional[int]:
    from ..mesh import g_mesh
    mesh = g_mesh.topology()
    return mesh.size if mesh is not None else None


def _get_rateless_tasks(ctrl: "Controller") -> Optional[float]:
    opt = int(g_conf.get_val("ec_mesh_rateless_tasks") or 0)
    if opt > 0:
        return float(opt)
    size = _mesh_size()
    return float(size + 2) if size else None


def _opt_get(name: str) -> Callable[["Controller"], Optional[float]]:
    return lambda _ctrl: float(g_conf.get_val(name) or 0)


def _opt_encode(name: str, cast) -> Callable[["Controller", float],
                                             Tuple[str, Any]]:
    return lambda _ctrl, v: (name, cast(v))


CONTROL_KNOBS: Dict[str, _Knob] = {
    # -- admission / abusive-client reflex --------------------------------
    "client_lane_weight": _Knob(
        "client_lane_weight", "float",
        floor=lambda _c: 0.05, ceiling=lambda _c: 100.0,
        get=lambda c: _get_lane_field(c, 1),
        encode=lambda c, v: _encode_lane_field(c, 1, v)),
    "client_lane_limit": _Knob(
        "client_lane_limit", "cap",
        floor=lambda _c: 20.0, ceiling=lambda _c: 500.0,
        get=lambda c: _get_lane_field(c, 2),
        encode=lambda c, v: _encode_lane_field(c, 2, v)),
    "osd_op_queue_admission_max": _Knob(
        "osd_op_queue_admission_max", "int",
        floor=lambda _c: 8, ceiling=lambda _c: 4096,
        get=_opt_get("osd_op_queue_admission_max"),
        encode=_opt_encode("osd_op_queue_admission_max", int)),
    # -- recovery-vs-client reflex ----------------------------------------
    "osd_recovery_max_active": _Knob(
        "osd_recovery_max_active", "int",
        floor=lambda _c: 1, ceiling=lambda _c: 64,
        get=_opt_get("osd_recovery_max_active"),
        encode=_opt_encode("osd_recovery_max_active", int)),
    "recovery_class_weight": _Knob(
        "recovery_class_weight", "float",
        floor=lambda _c: 10.0, ceiling=lambda _c: 400.0,
        get=lambda _c: _recovery_class_tags()[1],
        encode=_encode_recovery_weight),
    # -- straggler economics reflex ---------------------------------------
    "ec_mesh_rateless_tasks": _Knob(
        "ec_mesh_rateless_tasks", "unit",
        floor=lambda _c: (lambda s: s + 1 if s else None)(_mesh_size()),
        ceiling=lambda _c: (lambda s: 2 * s if s else None)(_mesh_size()),
        get=_get_rateless_tasks,
        encode=_opt_encode("ec_mesh_rateless_tasks", int)),
}

# deterministic evaluation/restore order: the reflex priority order
KNOB_ORDER = ("client_lane_weight", "client_lane_limit",
              "osd_op_queue_admission_max", "osd_recovery_max_active",
              "recovery_class_weight", "ec_mesh_rateless_tasks")

# which pressure signal must be CLEAR before a knob restores toward
# its baseline (the rateless width has no restore: the waste-economics
# narrowing is its decay path)
_CLEAR_GROUP = {
    "client_lane_weight": "adm_breach",
    "client_lane_limit": "adm_breach",
    "osd_op_queue_admission_max": "adm_breach",
    "osd_recovery_max_active": "oplat_breach",
    "recovery_class_weight": "oplat_breach",
}


class Controller:
    """The damped SLO feedback controller driven off ``Manager.tick``.

    One instance per Manager; all state is in-memory and resets with
    the mgr (a restored cluster starts with a quiet controller — the
    config it would have restored is already persisted in g_conf)."""

    def __init__(self):
        self._tick = 0
        self._knobs: Dict[str, Dict[str, Any]] = {}
        self._ledger: Deque[Dict[str, Any]] = deque()
        self._abuser: Optional[str] = None
        self._last_qw: Optional[Dict[str, int]] = None
        self._last_recovery: Optional[int] = None
        self._last_rateless: Optional[Tuple[int, int]] = None
        self._skew_streak = 0
        self._waste_streak = 0
        self._moves_total = 0

    # ---- options --------------------------------------------------------
    def _opts(self) -> Dict[str, Any]:
        return {
            "enable": bool(g_conf.get_val("mgr_control_enable")),
            "cooldown": max(0, int(
                g_conf.get_val("mgr_control_cooldown_ticks"))),
            "damping": min(1.0, max(0.01, float(
                g_conf.get_val("mgr_control_damping")))),
            "ledger": max(1, int(
                g_conf.get_val("mgr_control_ledger_size"))),
            "retries": max(0, int(
                g_conf.get_val("mgr_control_actuate_retries"))),
            "bounds": _parse_bounds(
                g_conf.get_val("mgr_control_bounds")),
        }

    def _bounds(self, knob: str,
                opts: Dict[str, Any]) -> Tuple[Optional[float],
                                               Optional[float]]:
        spec = CONTROL_KNOBS[knob]
        floor, ceiling = spec.floor(self), spec.ceiling(self)
        op = opts["bounds"].get(knob)
        if op is not None:
            floor = op[0] if op[0] is not None else floor
            ceiling = op[1] if op[1] is not None else ceiling
        return floor, ceiling

    def _state(self, knob: str) -> Dict[str, Any]:
        st = self._knobs.get(knob)
        if st is None:
            st = self._knobs[knob] = {"cooldown": 0, "scale": 1.0,
                                      "dir": 0, "baseline": None,
                                      "moves": 0}
        return st

    # ---- the tick -------------------------------------------------------
    def step(self, mgr, now: float = 0.0) -> None:
        """Runs every mgr tick, after Telemetry.tick.  Disabled =
        return before sensing (the twin-cluster property: an off
        controller is bit-identical to no controller), except that a
        disable LANDING mid-episode tears the episode down first."""
        opts = self._opts()
        if not opts["enable"]:
            if any(st["baseline"] is not None
                   for st in self._knobs.values()):
                self.teardown(mgr, reason="mgr_control_enable off")
            return
        pc = control_perf_counters()
        pc.set(l_ctl_enabled, 1)
        self._tick += 1
        pc.inc(l_ctl_ticks)
        for st in self._knobs.values():
            if st["cooldown"] > 0:
                st["cooldown"] -= 1
        sig = self._sense(mgr)
        move = None
        for reflex in (self._admission_reflex, self._recovery_reflex,
                       self._straggler_reflex, self._restore_reflex):
            move = reflex(sig, opts)
            if move is not None:
                break
        if move is not None:
            self._actuate(mgr, move, opts, now)
        pc.set(l_ctl_engaged,
               sum(1 for st in self._knobs.values()
                   if st["baseline"] is not None))

    # ---- sensors --------------------------------------------------------
    def _sense(self, mgr) -> Dict[str, Any]:
        slo = mgr.telemetry.slo_state()

        def breach(check: str) -> bool:
            st = slo.get(check)
            return bool(st and st.get("state") == "breach")

        from ..mgr.telemetry import SLO_ADMISSION, SLO_OPLAT
        sig: Dict[str, Any] = {
            "adm_breach": breach(SLO_ADMISSION),
            "oplat_breach": breach(SLO_OPLAT),
        }
        # recovery storm: repair activity since the last tick, or
        # rounds in flight right now
        from ..recovery import recovery_perf_counters
        rd = recovery_perf_counters().dump()
        rsum = int(rd.get("repair_rounds", 0)) \
            + int(rd.get("fullstripe_rounds", 0)) \
            + int(rd.get("push_bytes", 0))
        sig["storm"] = bool(rd.get("active", 0)) or (
            self._last_recovery is not None
            and rsum > self._last_recovery)
        self._last_recovery = rsum
        # straggler economics: mesh skew health vs wasted-block ratio
        from ..mesh import rateless_perf_counters
        rl = rateless_perf_counters().dump()
        wasted = int(rl.get("wasted_blocks", 0))
        coded = int(rl.get("coded_tasks", 0))
        waste_ratio = None
        if self._last_rateless is not None:
            dc = coded - self._last_rateless[1]
            if dc > 0:
                waste_ratio = (wasted - self._last_rateless[0]) / dc
        self._last_rateless = (wasted, coded)
        skew = "TPU_MESH_SKEW" in getattr(mgr, "health_checks", {})
        if skew:
            self._skew_streak += 1
            self._waste_streak = 0
        else:
            self._skew_streak = 0
            if waste_ratio is None:
                pass              # no coded traffic this tick: hold
            elif waste_ratio >= WASTE_RATIO_MAX:
                self._waste_streak += 1
            else:
                self._waste_streak = 0
        sig["skew_streak"] = self._skew_streak
        sig["waste_streak"] = self._waste_streak
        sig["abuser"] = self._sense_abuser()
        return sig

    def _sense_abuser(self) -> Optional[str]:
        """The client lane whose queue-wait histogram grew the most
        since the last tick — the dmClock tier's own per-entity ledger
        (osd.py registers one histogram per client lane).  Sticky: an
        episode keeps its abuser until its knobs restore.  The first
        enabled tick only BASELINES the counts (like the recovery and
        rateless sensors): history predating the controller must not
        read as one giant delta."""
        from ..trace import g_perf_histograms
        counts: Dict[str, int] = {}
        for (logger, name), h in g_perf_histograms.items():
            if name == "client_queue_wait_latency_histogram" \
                    and logger.startswith("client"):
                counts[logger] = counts.get(logger, 0) + h.total_count
        if self._last_qw is None:
            self._last_qw = counts
            return None
        best, best_delta = None, 0
        for lane in sorted(counts):
            delta = counts[lane] - self._last_qw.get(lane, 0)
            if delta > best_delta:
                best, best_delta = lane, delta
        self._last_qw = counts
        return best

    # ---- reflexes -------------------------------------------------------
    def _admission_reflex(self, sig, opts) -> Optional[_Move]:
        if not sig["adm_breach"]:
            return None
        if self._abuser is None:
            self._abuser = sig["abuser"]
        why = "TPU_SLO_ADMISSION burning"
        if self._abuser is not None:
            why += f"; abuser {self._abuser}"
            mv = self._tighten("client_lane_weight", "admission",
                               why, opts)
            if mv is not None:
                return mv
            mv = self._tighten("client_lane_limit", "admission",
                               why, opts)
            if mv is not None:
                return mv
        return self._tighten("osd_op_queue_admission_max", "admission",
                             why, opts)

    def _recovery_reflex(self, sig, opts) -> Optional[_Move]:
        if not (sig["oplat_breach"] and sig["storm"]):
            return None
        why = "TPU_SLO_OPLAT burning during a recovery storm"
        mv = self._tighten("osd_recovery_max_active", "recovery",
                           why, opts)
        if mv is not None:
            return mv
        return self._tighten("recovery_class_weight", "recovery",
                             why, opts)

    def _straggler_reflex(self, sig, opts) -> Optional[_Move]:
        from ..mesh.rateless import rateless_opts
        if not rateless_opts()[0]:
            return None
        if sig["skew_streak"] >= STRAGGLER_STREAK:
            return self._step("ec_mesh_rateless_tasks", +1, False,
                              "straggler",
                              f"TPU_MESH_SKEW sustained "
                              f"{sig['skew_streak']} ticks: widen",
                              opts)
        if sig["waste_streak"] >= STRAGGLER_STREAK:
            return self._step("ec_mesh_rateless_tasks", -1, False,
                              "straggler",
                              f"wasted_blocks ratio >= "
                              f"{WASTE_RATIO_MAX:g} with skew quiet "
                              f"{sig['waste_streak']} ticks: narrow",
                              opts)
        return None

    def _restore_reflex(self, sig, opts) -> Optional[_Move]:
        for knob in KNOB_ORDER:
            st = self._knobs.get(knob)
            if st is None or st["baseline"] is None:
                continue
            group = _CLEAR_GROUP.get(knob)
            if group is None or sig[group]:
                continue
            if st["cooldown"] > 0:
                control_perf_counters().inc(l_ctl_skipped_cooldown)
                continue
            spec = CONTROL_KNOBS[knob]
            cur = spec.get(self)
            if cur is None:
                continue
            base = st["baseline"]
            if cur == base:
                self._close_episode(knob)
                continue
            new = _halfway(spec.kind, cur, base)
            check = "TPU_SLO_ADMISSION" if group == "adm_breach" \
                else "TPU_SLO_OPLAT"
            return _Move(knob, cur, new, True, "restore",
                         f"{check} clear: restoring toward {base:g}")
        return None

    # ---- stepping -------------------------------------------------------
    def _tighten(self, knob: str, reflex: str, reason: str,
                 opts) -> Optional[_Move]:
        return self._step(knob, -1, False, reflex, reason, opts)

    def _step(self, knob: str, direction: int, restore: bool,
              reflex: str, reason: str, opts) -> Optional[_Move]:
        pc = control_perf_counters()
        st = self._state(knob)
        if st["cooldown"] > 0:
            pc.inc(l_ctl_skipped_cooldown)
            return None
        spec = CONTROL_KNOBS[knob]
        cur = spec.get(self)
        if cur is None:
            return None           # knob not actuatable right now
        floor, ceiling = self._bounds(knob, opts)
        if floor is None or ceiling is None:
            return None
        new = _stepped(spec.kind, cur, direction, st["scale"], ceiling)
        new = min(max(new, floor), ceiling)
        if spec.kind in ("int", "unit"):
            new = float(int(new))
        elif abs(new - cur) < 0.01 * max(abs(cur), 1e-9) \
                and not (spec.kind == "cap" and cur <= 0):
            # a float knob damped below a 1% step has converged: treat
            # it as pinned so the reflex escalates to its next knob
            # instead of micro-stepping forever
            pc.inc(l_ctl_pinned)
            g_journal.emit("mgr", "control_pinned", knob=knob,
                           reflex=reflex)
            return None
        if new == cur:
            pc.inc(l_ctl_pinned)
            g_journal.emit("mgr", "control_pinned", knob=knob,
                           reflex=reflex)
            return None           # anti-windup: pinned at a bound
        return _Move(knob, cur, new, restore, reflex, reason)

    # ---- actuation ------------------------------------------------------
    def _actuate(self, mgr, move: _Move, opts, now: float) -> bool:
        from ..fault import InjectedFault, g_faults
        pc = control_perf_counters()
        spec = CONTROL_KNOBS[move.knob]
        opt_name, opt_value = spec.encode(self, move.new)
        attempts = 0
        while True:
            try:
                g_faults.check("control.actuate",
                               f"{move.knob}={move.new:g} ({opt_name})")
                g_conf.set_checked(opt_name, opt_value)
                break
            except (InjectedFault, ValueError) as e:
                attempts += 1
                if attempts > opts["retries"]:
                    # bounded: drop the whole move; no cooldown is
                    # charged, so the next tick re-derives and retries
                    # — the controller cannot wedge on a dead path
                    pc.inc(l_ctl_failures)
                    mgr._cluster_log(
                        "WRN",
                        f"control: actuation dropped after "
                        f"{attempts} attempts: {move.knob} "
                        f"{move.cur:g} -> {move.new:g} ({e})")
                    return False
                pc.inc(l_ctl_retries)
        st = self._state(move.knob)
        if st["baseline"] is None and not move.restore:
            st["baseline"] = move.cur
            pc.inc(l_ctl_episodes)
        direction = 1 if move.new > move.cur else -1
        st["scale"] = st["scale"] * opts["damping"] \
            if direction == st["dir"] else 1.0
        st["dir"] = direction
        st["cooldown"] = opts["cooldown"]
        st["moves"] += 1
        self._moves_total += 1
        pc.inc(l_ctl_moves)
        pc.inc(l_ctl_restores if move.restore else l_ctl_tightens)
        if move.restore and st["baseline"] is not None \
                and move.new == st["baseline"]:
            self._close_episode(move.knob)
        self._ledger.append({
            "tick": self._tick, "clock": round(float(now), 3),
            "knob": move.knob, "option": opt_name,
            "reflex": move.reflex, "from": move.cur, "to": move.new,
            "reason": move.reason})
        while len(self._ledger) > opts["ledger"]:
            self._ledger.popleft()
        mgr._cluster_log(
            "INF", f"control: {move.reflex}: {move.knob} "
                   f"{move.cur:g} -> {move.new:g} ({move.reason})")
        g_journal.emit("mgr", "control_actuate", knob=move.knob,
                       option=opt_name, reflex=move.reflex,
                       restore=move.restore,
                       **{"from": move.cur, "to": move.new})
        return True

    def _close_episode(self, knob: str) -> None:
        st = self._state(knob)
        st["baseline"] = None
        st["dir"] = 0
        st["scale"] = 1.0
        if knob in ("client_lane_weight", "client_lane_limit") and \
                all(self._knobs.get(k, {}).get("baseline") is None
                    for k in ("client_lane_weight",
                              "client_lane_limit")):
            self._abuser = None

    # ---- tear-down / reset ----------------------------------------------
    def teardown(self, mgr, reason: str = "disabled") -> int:
        """Restore every engaged knob to its episode baseline NOW (one
        direct injection each — no fault gate, no cooldown: a disable
        must always land) and drop all episode state.  Returns the
        number of knobs restored."""
        pc = control_perf_counters()
        restored = 0
        for knob in KNOB_ORDER:
            st = self._knobs.get(knob)
            if st is None or st["baseline"] is None:
                continue
            spec = CONTROL_KNOBS[knob]
            base = st["baseline"]
            was = spec.get(self)
            try:
                opt_name, opt_value = spec.encode(self, base)
                g_conf.set_checked(opt_name, opt_value)
            except (ValueError, KeyError):
                opt_name = "?"
            self._ledger.append({
                "tick": self._tick, "clock": 0.0, "knob": knob,
                "option": opt_name, "reflex": "teardown",
                "from": was, "to": base,
                "reason": reason})
            mgr._cluster_log(
                "INF", f"control: teardown: {knob} restored to "
                       f"{base:g} ({reason})")
            pc.inc(l_ctl_reverts)
            g_journal.emit("mgr", "control_restore", knob=knob,
                           to=base, reason=reason)
            restored += 1
            st.update(baseline=None, dir=0, scale=1.0, cooldown=0)
        self._abuser = None
        self._skew_streak = self._waste_streak = 0
        pc.set(l_ctl_engaged, 0)
        pc.set(l_ctl_enabled,
               1 if bool(g_conf.get_val("mgr_control_enable")) else 0)
        return restored

    def reset(self, mgr) -> int:
        """Tear down any episode, then forget history: ledger, tick
        count, sense caches.  The asok ``control reset`` verb."""
        restored = self.teardown(mgr, reason="reset")
        self._ledger.clear()
        self._tick = 0
        self._last_qw = None
        self._last_recovery = None
        self._last_rateless = None
        return restored

    # ---- observability --------------------------------------------------
    @property
    def moves_total(self) -> int:
        return self._moves_total

    def dump(self) -> Dict[str, Any]:
        """The ``tpu control dump`` asok pane."""
        opts = self._opts()
        knobs: Dict[str, Any] = {}
        for name in KNOB_ORDER:
            spec = CONTROL_KNOBS[name]
            st = self._knobs.get(name, {"cooldown": 0, "scale": 1.0,
                                        "dir": 0, "baseline": None,
                                        "moves": 0})
            floor, ceiling = self._bounds(name, opts)
            knobs[name] = {
                "value": spec.get(self),
                "baseline": st["baseline"],
                "floor": floor, "ceiling": ceiling,
                "cooldown": st["cooldown"],
                "step_scale": st["scale"],
                "moves": st["moves"],
            }
        return {
            "enabled": opts["enable"],
            "tick": self._tick,
            "abuser": self._abuser or "",
            "moves_total": self._moves_total,
            "options": {
                "cooldown_ticks": opts["cooldown"],
                "damping": opts["damping"],
                "ledger_size": opts["ledger"],
                "actuate_retries": opts["retries"],
                "bounds": str(g_conf.get_val("mgr_control_bounds")
                              or ""),
            },
            "knobs": knobs,
            "ledger": list(self._ledger),
        }


def _parse_bounds(src) -> Dict[str, Tuple[Optional[float],
                                          Optional[float]]]:
    """'knob:floor:ceiling[,knob:...]' -> {knob: (floor, ceiling)};
    an empty field keeps the built-in bound, malformed entries drop."""
    out: Dict[str, Tuple[Optional[float], Optional[float]]] = {}
    for part in str(src or "").replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.rsplit(":", 2)
        if len(bits) != 3 or bits[0] not in CONTROL_KNOBS:
            continue
        try:
            floor = float(bits[1]) if bits[1] else None
            ceiling = float(bits[2]) if bits[2] else None
        except ValueError:
            continue
        out[bits[0]] = (floor, ceiling)
    return out


def _stepped(kind: str, cur: float, direction: int, scale: float,
             ceiling: float) -> float:
    """One damped step from *cur*.  Multiplicative half-steps scaled
    by the episode's geometric damping; ``unit`` knobs move one."""
    if kind == "unit":
        return cur + direction
    if kind == "cap" and cur <= 0 and direction < 0:
        return ceiling            # impose the cap at the ceiling
    if kind == "int":
        step = max(1.0, float(int(abs(cur) * 0.5 * scale)))
        return cur + direction * step
    return cur * (1.0 + direction * 0.5 * scale)


def _halfway(kind: str, cur: float, base: float) -> float:
    """One restore step: half the remaining gap toward *base*, with a
    snap when the gap is small — restores converge in O(log) moves and
    can never overshoot the baseline."""
    gap = base - cur
    if kind in ("int", "unit"):
        if abs(gap) <= 1:
            return float(base)
        return float(int(cur + (1 if gap > 0 else -1)
                         * max(1, abs(int(gap)) // 2)))
    if kind == "cap" and base <= 0:
        return float(base)        # un-impose the cap in one move
    if abs(gap) * 0.5 <= max(abs(base) * 0.05, 1e-9):
        return float(base)
    return cur + gap * 0.5
