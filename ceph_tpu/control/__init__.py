"""ceph_tpu/control — the damped SLO-driven self-tuning control plane.

See controller.py (docs/CONTROL.md for the policy map and runbook).
"""
from .controller import (CONTROL_KNOBS, Controller, control_perf_counters,
                         l_ctl_enabled, l_ctl_engaged, l_ctl_episodes,
                         l_ctl_failures, l_ctl_moves, l_ctl_pinned,
                         l_ctl_restores, l_ctl_retries, l_ctl_reverts,
                         l_ctl_skipped_cooldown, l_ctl_ticks,
                         l_ctl_tightens)

__all__ = [
    "CONTROL_KNOBS", "Controller", "control_perf_counters",
    "l_ctl_enabled", "l_ctl_engaged", "l_ctl_episodes", "l_ctl_failures",
    "l_ctl_moves", "l_ctl_pinned", "l_ctl_restores", "l_ctl_retries",
    "l_ctl_reverts", "l_ctl_skipped_cooldown", "l_ctl_ticks",
    "l_ctl_tightens",
]
