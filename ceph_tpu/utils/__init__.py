from .str_hash import (
    CEPH_STR_HASH_LINUX, CEPH_STR_HASH_RJENKINS, ceph_str_hash,
    ceph_str_hash_linux, ceph_str_hash_rjenkins,
)

__all__ = [
    "CEPH_STR_HASH_LINUX", "CEPH_STR_HASH_RJENKINS", "ceph_str_hash",
    "ceph_str_hash_linux", "ceph_str_hash_rjenkins",
]
