"""crc32c (Castagnoli) with Ceph's conventions.

Ceph computes raw crc32c updates with no pre/post inversion and seeds with
-1 (reference include/crc32c.h, common/crc32c*.cc SSE4/table paths).  The
native C++ path (ceph_tpu.native) is preferred; this table-driven fallback
is bit-identical and keeps the dependency optional.
"""
from __future__ import annotations

import numpy as np

_POLY = 0x82F63B78  # reflected CRC-32C polynomial


def _build_table() -> np.ndarray:
    t = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        t[i] = c
    return t


_TABLE = _build_table()


def crc32c_sw(data, crc: int = 0xFFFFFFFF) -> int:
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data.astype(np.uint8)
    c = np.uint32(crc)
    for b in buf.tobytes():
        c = _TABLE[(int(c) ^ b) & 0xFF] ^ (int(c) >> 8)
        c = np.uint32(c)
    return int(c)


def crc32c(data, crc: int = 0xFFFFFFFF) -> int:
    """Native when built, software otherwise; same bits either way."""
    try:
        from ..native import crc32c as native_crc32c, native_available
        if native_available():
            return native_crc32c(
                data if isinstance(data, (bytes, np.ndarray))
                else bytes(data), crc)
    except Exception:
        pass
    return crc32c_sw(data, crc)
