"""Object-name hashes (reference src/common/ceph_hash.cc).

``ceph_str_hash_rjenkins`` is the object→ps hash (12-byte block Jenkins mix
seeded with the golden ratio, length folded into c); ``ceph_str_hash_linux``
is the legacy dcache hash.  Both are host-side — object-name hashing is cheap
and happens at the client/PG boundary, never in the device hot loop.
"""
from __future__ import annotations

from ..crush.hash import M32, _mix

CEPH_STR_HASH_LINUX = 0x1
CEPH_STR_HASH_RJENKINS = 0x2


def ceph_str_hash_rjenkins(data) -> int:
    k = bytes(data, "utf-8") if isinstance(data, str) else bytes(data)
    length = len(k)
    a = b = 0x9E3779B9
    c = 0
    i = 0
    n = length
    while n >= 12:
        a = (a + (k[i] | k[i+1] << 8 | k[i+2] << 16 | k[i+3] << 24)) & M32
        b = (b + (k[i+4] | k[i+5] << 8 | k[i+6] << 16 | k[i+7] << 24)) & M32
        c = (c + (k[i+8] | k[i+9] << 8 | k[i+10] << 16 | k[i+11] << 24)) & M32
        a, b, c = _mix(a, b, c)
        i += 12
        n -= 12
    c = (c + length) & M32
    # tail bytes; byte 0 of c is reserved for the length
    if n >= 11: c = (c + (k[i+10] << 24)) & M32
    if n >= 10: c = (c + (k[i+9] << 16)) & M32
    if n >= 9:  c = (c + (k[i+8] << 8)) & M32
    if n >= 8:  b = (b + (k[i+7] << 24)) & M32
    if n >= 7:  b = (b + (k[i+6] << 16)) & M32
    if n >= 6:  b = (b + (k[i+5] << 8)) & M32
    if n >= 5:  b = (b + k[i+4]) & M32
    if n >= 4:  a = (a + (k[i+3] << 24)) & M32
    if n >= 3:  a = (a + (k[i+2] << 16)) & M32
    if n >= 2:  a = (a + (k[i+1] << 8)) & M32
    if n >= 1:  a = (a + k[i]) & M32
    a, b, c = _mix(a, b, c)
    return c


def ceph_str_hash_linux(data) -> int:
    k = bytes(data, "utf-8") if isinstance(data, str) else bytes(data)
    h = 0
    for ch in k:
        h = ((h + (ch << 4) + (ch >> 4)) * 11) & M32
    return h


def ceph_str_hash(type: int, data) -> int:
    if type == CEPH_STR_HASH_LINUX:
        return ceph_str_hash_linux(data)
    if type == CEPH_STR_HASH_RJENKINS:
        return ceph_str_hash_rjenkins(data)
    raise ValueError(f"unknown hash type {type}")
