"""MemStore — transactional in-memory ObjectStore.

Mirrors the reference's test backend (src/os/memstore/MemStore.{h,cc}) and
the ObjectStore transaction model (src/os/ObjectStore.h): collections (one
per PG shard) hold objects with byte data, xattrs and omap; mutations are
queued as Transactions whose ops apply atomically and in order.  BlueStore's
block/WAL machinery is host-I/O out of scope for a TPU build (SURVEY.md
§2.9) — this is the durability stand-in that keeps the OSD data path
honest: every shard write and recovery push lands here through the same
Transaction ABI the reference uses.

Device-resident shard bodies: an object's ``data`` may be a
``DeviceShard`` (os_store/device_shard.py) instead of a bytearray — a
whole-body handle written via ``Transaction.write_shard`` that stays in
HBM until a host read materializes it (the accounted
``memstore.fetch_shard`` d2h).  ``stat``/``save`` work unchanged via
``len()``/``bytes()``; any byte-granular mutation (write/zero/truncate)
materializes first, so splicing semantics are identical either way.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np
from ..common.lockdep import DebugRLock
from .device_shard import DeviceShard, g_device_budget


@dataclass(frozen=True, order=True)
class hobject_t:
    """Object identity inside a collection (simplified hobject)."""
    oid: str
    shard: int = -1  # EC shard id, -1 = whole/replicated

    def __str__(self):
        return f"{self.oid}" if self.shard < 0 else f"{self.oid}({self.shard})"


class _Object:
    __slots__ = ("data", "attrs", "omap")

    def __init__(self):
        self.data = bytearray()
        self.attrs: Dict[str, bytes] = {}
        self.omap: Dict[str, bytes] = {}


# transaction op codes (subset of ObjectStore::Transaction ops)
OP_TOUCH = "touch"
OP_WRITE = "write"
OP_WRITE_SHARD = "write_shard"  # whole-body replace, handle-typed
OP_ZERO = "zero"
OP_TRUNCATE = "truncate"
OP_REMOVE = "remove"
OP_SETATTR = "setattr"
OP_RMATTR = "rmattr"
OP_OMAP_SETKEYS = "omap_setkeys"
OP_OMAP_RMKEYS = "omap_rmkeys"
OP_MKCOLL = "mkcoll"
OP_RMCOLL = "rmcoll"


class Transaction:
    """Ordered batch of mutations applied atomically
    (os/ObjectStore.h Transaction)."""

    def __init__(self):
        self.ops: List[Tuple] = []

    def touch(self, cid: str, oid: hobject_t):
        self.ops.append((OP_TOUCH, cid, oid))

    def write(self, cid: str, oid: hobject_t, offset: int, data):
        self.ops.append((OP_WRITE, cid, oid, offset, bytes(data)))

    def write_shard(self, cid: str, oid: hobject_t, shard):
        """Replace the whole object body with *shard* (a ``DeviceShard``
        handle or host bytes) without coercing — the zero-copy write
        path's store op: a resident body is queued and applied with no
        byte movement at all."""
        self.ops.append((OP_WRITE_SHARD, cid, oid, shard))

    def zero(self, cid: str, oid: hobject_t, offset: int, length: int):
        self.ops.append((OP_ZERO, cid, oid, offset, length))

    def truncate(self, cid: str, oid: hobject_t, size: int):
        self.ops.append((OP_TRUNCATE, cid, oid, size))

    def remove(self, cid: str, oid: hobject_t):
        self.ops.append((OP_REMOVE, cid, oid))

    def setattr(self, cid: str, oid: hobject_t, name: str, value: bytes):
        self.ops.append((OP_SETATTR, cid, oid, name, bytes(value)))

    def rmattr(self, cid: str, oid: hobject_t, name: str):
        self.ops.append((OP_RMATTR, cid, oid, name))

    def omap_setkeys(self, cid: str, oid: hobject_t,
                     keys: Dict[str, bytes]):
        self.ops.append((OP_OMAP_SETKEYS, cid, oid, dict(keys)))

    def omap_rmkeys(self, cid: str, oid: hobject_t, keys: List[str]):
        self.ops.append((OP_OMAP_RMKEYS, cid, oid, list(keys)))

    def create_collection(self, cid: str):
        self.ops.append((OP_MKCOLL, cid))

    def remove_collection(self, cid: str):
        self.ops.append((OP_RMCOLL, cid))

    def append(self, other: "Transaction"):
        self.ops.extend(other.ops)

    def empty(self) -> bool:
        return not self.ops


_MAGIC = b"CTPUSTOR"
_VERSION = 1


class MemStore:
    def __init__(self):
        self.colls: Dict[str, Dict[hobject_t, _Object]] = {}
        self.committed_txns = 0
        self._write_lock = DebugRLock("MemStore::write_lock")

    # ---- lifecycle / durability -------------------------------------------
    def mount(self) -> None:
        pass

    def umount(self) -> None:
        pass

    def save(self, path: str) -> None:
        """Persist every collection to *path* (length-prefixed binary; the
        BlueStore-durability stand-in: checkpoint = this file, resume =
        ``MemStore.load``)."""
        import struct as _s

        def pstr(b: bytes) -> bytes:
            return _s.pack("<I", len(b)) + b

        out = [_MAGIC, _s.pack("<IQ", _VERSION, self.committed_txns),
               _s.pack("<I", len(self.colls))]
        for cid in sorted(self.colls):
            coll = self.colls[cid]
            out.append(pstr(cid.encode()))
            out.append(_s.pack("<I", len(coll)))
            for ho in sorted(coll):
                o = coll[ho]
                out.append(pstr(ho.oid.encode()))
                out.append(_s.pack("<i", ho.shard))
                out.append(pstr(bytes(o.data)))
                out.append(_s.pack("<I", len(o.attrs)))
                for k in sorted(o.attrs):
                    out.append(pstr(k.encode()))
                    out.append(pstr(o.attrs[k]))
                out.append(_s.pack("<I", len(o.omap)))
                for k in sorted(o.omap):
                    out.append(pstr(k.encode()))
                    out.append(pstr(o.omap[k]))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(b"".join(out))
        os.replace(tmp, path)  # atomic like a journal commit

    @classmethod
    def load(cls, path: str) -> "MemStore":
        import struct as _s
        with open(path, "rb") as f:
            buf = f.read()
        if buf[:8] != _MAGIC:
            raise ValueError(f"{path}: not a ceph_tpu store file")
        pos = 8
        version, txns = _s.unpack_from("<IQ", buf, pos)
        pos += 12
        if version != _VERSION:
            raise ValueError(f"{path}: store version {version}")

        def rstr() -> bytes:
            nonlocal pos
            (n,) = _s.unpack_from("<I", buf, pos)
            pos += 4
            b = buf[pos:pos + n]
            pos += n
            return b

        store = cls()
        store.committed_txns = txns
        (ncolls,) = _s.unpack_from("<I", buf, pos)
        pos += 4
        for _ in range(ncolls):
            cid = rstr().decode()
            (nobjs,) = _s.unpack_from("<I", buf, pos)
            pos += 4
            coll: Dict[hobject_t, _Object] = {}
            for _o in range(nobjs):
                oid = rstr().decode()
                (shard,) = _s.unpack_from("<i", buf, pos)
                pos += 4
                obj = _Object()
                obj.data = bytearray(rstr())
                (nattrs,) = _s.unpack_from("<I", buf, pos)
                pos += 4
                for _a in range(nattrs):
                    k = rstr().decode()
                    obj.attrs[k] = rstr()
                (nomap,) = _s.unpack_from("<I", buf, pos)
                pos += 4
                for _m in range(nomap):
                    k = rstr().decode()
                    obj.omap[k] = rstr()
                coll[hobject_t(oid, shard)] = obj
            store.colls[cid] = coll
        return store

    # ---- transactions -----------------------------------------------------
    def queue_transaction(self, t: Transaction) -> None:
        """Apply atomically; invalid ops raise before any mutation.

        Thread-safe for writers (the threaded op queue commits from
        worker threads; the reference ObjectStore is too): the whole
        stage-and-swap runs under a mutex, while readers see either the
        old or the new dict via the atomic reference swap."""
        with self._write_lock:
            # stage (deep-clone) only the collections this transaction
            # touches; untouched ones share by reference — the swap
            # below is still one atomic rebind for readers, and the
            # critical section stops scaling with the WHOLE store
            touched = {op[1] for op in t.ops if len(op) > 1}
            staged = dict(self.colls)
            for cid in touched:
                coll = self.colls.get(cid)
                if coll is not None:
                    staged[cid] = {o: self._clone(obj)
                                   for o, obj in coll.items()}
            self._apply(staged, t)
            self.colls = staged
            self.committed_txns += 1

    @staticmethod
    def _clone(obj: _Object) -> _Object:
        c = _Object()
        # a DeviceShard is immutable-by-convention (mutations replace
        # the whole body or materialize first) — clones share the
        # handle so staging a touched collection moves no device bytes
        c.data = obj.data if isinstance(obj.data, DeviceShard) \
            else bytearray(obj.data)
        c.attrs = dict(obj.attrs)
        c.omap = dict(obj.omap)
        return c

    @staticmethod
    def _mutable(o: _Object) -> bytearray:
        """The object's body as a spliceable bytearray; a resident
        shard materializes first (byte-granular edits need bytes)."""
        if isinstance(o.data, DeviceShard):
            o.data = bytearray(o.data.materialize())
        return o.data

    def _apply(self, colls, t: Transaction) -> None:
        def coll(cid):
            if cid not in colls:
                raise KeyError(f"no collection {cid}")
            return colls[cid]

        def obj(cid, oid, create=False):
            c = coll(cid)
            if oid not in c:
                if not create:
                    raise KeyError(f"no object {oid} in {cid}")
                c[oid] = _Object()
            return c[oid]

        for op in t.ops:
            code = op[0]
            if code == OP_MKCOLL:
                colls.setdefault(op[1], {})
            elif code == OP_RMCOLL:
                colls.pop(op[1], None)
            elif code == OP_TOUCH:
                obj(op[1], op[2], create=True)
            elif code == OP_WRITE:
                _, cid, oid, offset, data = op
                o = obj(cid, oid, create=True)
                buf = self._mutable(o)
                end = offset + len(data)
                if len(buf) < end:
                    buf.extend(b"\0" * (end - len(buf)))
                buf[offset:end] = data
            elif code == OP_WRITE_SHARD:
                _, cid, oid, shard = op
                obj(cid, oid, create=True).data = shard
            elif code == OP_ZERO:
                _, cid, oid, offset, length = op
                o = obj(cid, oid, create=True)
                buf = self._mutable(o)
                end = offset + length
                if len(buf) < end:
                    buf.extend(b"\0" * (end - len(buf)))
                buf[offset:end] = b"\0" * length
            elif code == OP_TRUNCATE:
                _, cid, oid, size = op
                o = obj(cid, oid, create=True)
                buf = self._mutable(o)
                if len(buf) > size:
                    del buf[size:]
                else:
                    buf.extend(b"\0" * (size - len(buf)))
            elif code == OP_REMOVE:
                coll(op[1]).pop(op[2], None)
            elif code == OP_SETATTR:
                _, cid, oid, name, value = op
                obj(cid, oid, create=True).attrs[name] = value
            elif code == OP_RMATTR:
                _, cid, oid, name = op
                obj(cid, oid).attrs.pop(name, None)
            elif code == OP_OMAP_SETKEYS:
                _, cid, oid, keys = op
                obj(cid, oid, create=True).omap.update(keys)
            elif code == OP_OMAP_RMKEYS:
                _, cid, oid, keys = op
                o = obj(cid, oid)
                for k in keys:
                    o.omap.pop(k, None)
            else:
                raise ValueError(f"unknown op {code}")

    # ---- reads ------------------------------------------------------------
    def collection_exists(self, cid: str) -> bool:
        return cid in self.colls

    def list_collections(self) -> List[str]:
        return sorted(self.colls)

    def exists(self, cid: str, oid: hobject_t) -> bool:
        return oid in self.colls.get(cid, {})

    def _maybe_corrupt(self, cid: str, oid: hobject_t,
                       o: _Object) -> None:
        """Fault site ``store.shard_corrupt``: flip one stored body
        byte (bitrot) — works on resident handles and host bytes alike
        so the crc EIO path is testable in both representations."""
        from ..fault import g_faults  # lazy: fault imports trace
        if not g_faults.site_armed("store.shard_corrupt"):
            return
        if not g_faults.should_fire("store.shard_corrupt",
                                    f"{cid}/{oid}"):
            return
        d = o.data
        if isinstance(d, DeviceShard):
            d.corrupted()
        elif len(d):
            d[0] ^= 0x01

    def read(self, cid: str, oid: hobject_t, offset: int = 0,
             length: int = 0) -> bytes:
        o = self.colls[cid][oid]
        self._maybe_corrupt(cid, oid, o)
        d = o.data
        if isinstance(d, DeviceShard):
            d = d.materialize()
        if length == 0:
            length = len(d) - offset
        return bytes(d[offset:offset + length])

    def read_shard(self, cid: str, oid: hobject_t):
        """The whole body WITHOUT forcing host bytes: a resident
        ``DeviceShard`` comes back as the handle itself (LRU-touched);
        host-bytes bodies come back as bytes.  The zero-copy read path
        for in-process fabrics."""
        o = self.colls[cid][oid]
        self._maybe_corrupt(cid, oid, o)
        d = o.data
        if isinstance(d, DeviceShard):
            g_device_budget.touch(d)
            return d
        return bytes(d)

    def stat(self, cid: str, oid: hobject_t) -> int:
        return len(self.colls[cid][oid].data)

    def getattr(self, cid: str, oid: hobject_t, name: str) -> bytes:
        return self.colls[cid][oid].attrs[name]

    def getattrs(self, cid: str, oid: hobject_t) -> Dict[str, bytes]:
        return dict(self.colls[cid][oid].attrs)

    def omap_get(self, cid: str, oid: hobject_t) -> Dict[str, bytes]:
        return dict(self.colls[cid][oid].omap)

    def list_objects(self, cid: str) -> List[hobject_t]:
        return sorted(self.colls.get(cid, {}))
