"""WALStore — crash-consistent, file-backed ObjectStore.

The durability tier between MemStore (tests) and the reference's
BlueStore (src/os/bluestore/BlueStore.cc, out of scope per SURVEY.md
§2.9 item 9): every committed Transaction is framed, checksummed and
appended to a write-ahead log before it is applied in memory, exactly
the journal-then-apply contract FileStore keeps with its journal
(src/os/filestore/FileJournal.{h,cc}: entry = header + payload + crc,
replay stops at the first torn record).  Mounting replays the newest
checkpoint plus the WAL suffix, so an OSD process killed with -9
resumes from its own data directory and recovers by PG-log delta
instead of full backfill (src/osd/OSD.cc:2469 init: mount store, read
superblock, load PGs).

Layout of a store directory:

    superblock.json   store identity + format version (OSDSuperblock)
    checkpoint.bin    full-store snapshot (MemStore.save format); its
                      committed_txns field is the WAL sequence fence
    wal.bin           append-only records: seq-stamped, crc32c-framed
                      encoded Transactions

Crash consistency: records are applied only if the length and crc
check out AND the sequence is the expected successor; the first torn
or corrupt record ends replay (everything before it is intact because
appends are ordered).  Checkpointing writes the snapshot via
tmp+rename first, then truncates the WAL — a crash between the two
leaves stale WAL records whose seq <= the checkpoint fence; replay
skips them.

fsync policy: records are always flushed to the OS (surviving process
kill -9, the thrash-suite case, ceph_manager.py:195).  ``fsync=True``
additionally fdatasyncs per commit for power-loss durability, the
journal's J_SYNC mode — off by default because every test harness here
only ever kills processes, not the host.
"""
from __future__ import annotations

import json
import os
import struct
from typing import Dict, List, Optional, Tuple

from ..utils.crc32c import crc32c
from .memstore import (MemStore, Transaction, hobject_t, OP_TOUCH, OP_WRITE,
                       OP_ZERO, OP_TRUNCATE, OP_REMOVE, OP_SETATTR,
                       OP_RMATTR, OP_OMAP_SETKEYS, OP_OMAP_RMKEYS,
                       OP_MKCOLL, OP_RMCOLL)

_REC_MAGIC = 0x57414C52          # "WALR"
_SB_VERSION = 1
_HDR = struct.Struct("<IQII")    # magic, seq, payload len, payload crc32c

# stable one-byte codes for the op vocabulary (the string names stay the
# in-memory representation; the WAL is a binary format)
_OP_CODES = {
    OP_TOUCH: 1, OP_WRITE: 2, OP_ZERO: 3, OP_TRUNCATE: 4, OP_REMOVE: 5,
    OP_SETATTR: 6, OP_RMATTR: 7, OP_OMAP_SETKEYS: 8, OP_OMAP_RMKEYS: 9,
    OP_MKCOLL: 10, OP_RMCOLL: 11,
}
_OP_NAMES = {v: k for k, v in _OP_CODES.items()}


def _pstr(b: bytes) -> bytes:
    return struct.pack("<I", len(b)) + b


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def u8(self) -> int:
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.buf, self.pos)
        self.pos += 4
        return v

    def i32(self) -> int:
        (v,) = struct.unpack_from("<i", self.buf, self.pos)
        self.pos += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from("<Q", self.buf, self.pos)
        self.pos += 8
        return v

    def pstr(self) -> bytes:
        n = self.u32()
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise ValueError("truncated string")
        self.pos += n
        return b


def encode_txn(t: Transaction) -> bytes:
    """Binary Transaction encoding (ObjectStore::Transaction::encode
    analog, os/Transaction.cc): op count then per-op tagged fields."""
    out = [struct.pack("<I", len(t.ops))]
    for op in t.ops:
        code = _OP_CODES[op[0]]
        out.append(struct.pack("<B", code))
        if code in (10, 11):                       # mkcoll / rmcoll
            out.append(_pstr(op[1].encode()))
            continue
        _, cid, oid = op[0], op[1], op[2]
        out.append(_pstr(cid.encode()))
        out.append(_pstr(oid.oid.encode()))
        out.append(struct.pack("<i", oid.shard))
        if code == 2:                              # write
            out.append(struct.pack("<Q", op[3]))
            out.append(_pstr(op[4]))
        elif code == 3:                            # zero
            out.append(struct.pack("<QQ", op[3], op[4]))
        elif code == 4:                            # truncate
            out.append(struct.pack("<Q", op[3]))
        elif code == 6:                            # setattr
            out.append(_pstr(op[3].encode()))
            out.append(_pstr(op[4]))
        elif code == 7:                            # rmattr
            out.append(_pstr(op[3].encode()))
        elif code == 8:                            # omap_setkeys
            out.append(struct.pack("<I", len(op[3])))
            for k in sorted(op[3]):
                out.append(_pstr(k.encode()))
                out.append(_pstr(op[3][k]))
        elif code == 9:                            # omap_rmkeys
            out.append(struct.pack("<I", len(op[3])))
            for k in op[3]:
                out.append(_pstr(k.encode()))
    return b"".join(out)


def decode_txn(buf: bytes) -> Transaction:
    r = _Reader(buf)
    n = r.u32()
    t = Transaction()
    for _ in range(n):
        code = r.u8()
        name = _OP_NAMES.get(code)
        if name is None:
            raise ValueError(f"unknown wal op code {code}")
        if code in (10, 11):
            t.ops.append((name, r.pstr().decode()))
            continue
        cid = r.pstr().decode()
        oid = hobject_t(r.pstr().decode(), r.i32())
        if code == 2:
            off = r.u64()
            t.ops.append((name, cid, oid, off, r.pstr()))
        elif code == 3:
            off = r.u64()
            t.ops.append((name, cid, oid, off, r.u64()))
        elif code == 4:
            t.ops.append((name, cid, oid, r.u64()))
        elif code == 6:
            k = r.pstr().decode()
            t.ops.append((name, cid, oid, k, r.pstr()))
        elif code == 7:
            t.ops.append((name, cid, oid, r.pstr().decode()))
        elif code == 8:
            cnt = r.u32()
            kv = {}
            for _k in range(cnt):
                k = r.pstr().decode()
                kv[k] = r.pstr()
            t.ops.append((name, cid, oid, kv))
        elif code == 9:
            cnt = r.u32()
            t.ops.append((name, cid, oid,
                          [r.pstr().decode() for _k in range(cnt)]))
        else:                                      # touch / remove
            t.ops.append((name, cid, oid))
    return t


class WALStore(MemStore):
    """File-backed MemStore: journal first, apply second."""

    WAL_MAX_BYTES = 8 << 20       # checkpoint + truncate past this

    def __init__(self, directory: str, fsync: bool = False,
                 wal_max_bytes: Optional[int] = None):
        super().__init__()
        self.dir = directory
        self.fsync = fsync
        self.wal_max_bytes = (wal_max_bytes if wal_max_bytes is not None
                              else self.WAL_MAX_BYTES)
        self._wal_f = None
        self._wal_size = 0

    # ---- paths -------------------------------------------------------------
    @property
    def _sb_path(self) -> str:
        return os.path.join(self.dir, "superblock.json")

    @property
    def _ckpt_path(self) -> str:
        return os.path.join(self.dir, "checkpoint.bin")

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.dir, "wal.bin")

    # ---- lifecycle ---------------------------------------------------------
    def mount(self) -> None:
        """Create-or-recover: load the checkpoint, replay the WAL suffix,
        open the log for appending (OSD::init's store->mount)."""
        os.makedirs(self.dir, exist_ok=True)
        if os.path.exists(self._sb_path):
            with open(self._sb_path) as f:
                sb = json.load(f)
            if sb.get("version") != _SB_VERSION:
                raise ValueError(
                    f"{self.dir}: superblock version {sb.get('version')}")
        else:
            with open(self._sb_path, "w") as f:
                json.dump({"version": _SB_VERSION, "type": "walstore"}, f)
        fence = 0
        if os.path.exists(self._ckpt_path):
            snap = MemStore.load(self._ckpt_path)
            self.colls = snap.colls
            self.committed_txns = snap.committed_txns
            fence = snap.committed_txns
        replayed, frontier = self._replay_wal(fence)
        self.committed_txns = max(self.committed_txns, replayed)
        # cut the log AT the recovery frontier: appending after torn
        # garbage would strand every post-recovery record behind bytes
        # the next replay refuses to cross (FileJournal does the same —
        # committed_up_to defines where the journal restarts)
        if os.path.exists(self._wal_path) and \
                frontier != os.path.getsize(self._wal_path):
            with open(self._wal_path, "r+b") as f:
                f.truncate(frontier)
        self._wal_f = open(self._wal_path, "ab")
        self._wal_size = self._wal_f.tell()

    def umount(self) -> None:
        """Checkpoint and close (clean shutdown; reopening replays
        nothing)."""
        if self._wal_f is None:
            return
        self._checkpoint()
        self._wal_f.close()
        self._wal_f = None

    def _replay_wal(self, fence: int) -> Tuple[int, int]:
        """Apply WAL records with seq > fence, in order.  Returns
        (last seq applied-or-skipped, byte offset of the recovery
        frontier).  Replay ends at the first torn, corrupt, gapped or
        unappliable record — everything past that offset is garbage the
        caller truncates away."""
        if not os.path.exists(self._wal_path):
            return fence, 0
        with open(self._wal_path, "rb") as f:
            buf = f.read()
        pos, seq = 0, fence
        while pos + _HDR.size <= len(buf):
            magic, rseq, ln, crc = _HDR.unpack_from(buf, pos)
            if magic != _REC_MAGIC:
                return seq, pos
            payload = buf[pos + _HDR.size:pos + _HDR.size + ln]
            if len(payload) != ln or crc32c(payload) != crc:
                return seq, pos                    # torn tail
            if rseq <= fence:
                pos += _HDR.size + ln
                continue                           # pre-checkpoint record
            if rseq != seq + 1:
                return seq, pos                    # sequence gap
            try:
                t = decode_txn(payload)
                MemStore.queue_transaction(self, t)
            except Exception:
                # undecodable or unappliable (a record the writer
                # itself rolled back but crashed before truncating):
                # recovery stops here, never raises out of mount
                return seq, pos
            pos += _HDR.size + ln
            self.committed_txns = seq = rseq
        return seq, pos

    # ---- commits -----------------------------------------------------------
    def queue_transaction(self, t: Transaction) -> None:
        if self._wal_f is None:
            # unmounted use degrades to MemStore semantics (tests build
            # stores before wiring directories)
            MemStore.queue_transaction(self, t)
            return
        with self._write_lock:
            payload = encode_txn(t)
            seq = self.committed_txns + 1
            rec = _HDR.pack(_REC_MAGIC, seq, len(payload),
                            crc32c(payload)) + payload
            pos0 = self._wal_size
            self._wal_f.write(rec)
            self._wal_f.flush()
            try:
                MemStore.queue_transaction(self, t)  # may raise pre-apply
            except Exception:
                # invalid transaction: rewind the journal so the failed
                # record can't poison replay (its seq will be reused by
                # the next good commit)
                self._wal_f.truncate(pos0)
                self._wal_f.seek(pos0)
                self._wal_f.flush()
                raise
            if self.fsync:
                os.fsync(self._wal_f.fileno())
            self._wal_size = pos0 + len(rec)
            assert self.committed_txns == seq
            if self._wal_size >= self.wal_max_bytes:
                self._checkpoint()

    def _checkpoint(self) -> None:
        """Snapshot-then-truncate: MemStore.save is already atomic via
        tmp+rename; only after the rename lands is the WAL cut.  In
        fsync mode the snapshot (file + directory entry) must be ON
        DISK before the cut, or power loss right after the truncate
        could lose everything up to the fence."""
        self.save(self._ckpt_path)
        if self.fsync:
            fd = os.open(self._ckpt_path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            dfd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        self._wal_f.close()
        self._wal_f = open(self._wal_path, "wb")
        if self.fsync:
            os.fsync(self._wal_f.fileno())
        self._wal_size = 0

    # ---- fsck --------------------------------------------------------------
    def fsck(self) -> Dict:
        """Offline consistency report (BlueStore::fsck analog): walk the
        checkpoint and every WAL record, verify framing + crc + sequence
        continuity.  Safe on a mounted or unmounted directory."""
        report: Dict = {"checkpoint": None, "wal_records": 0,
                        "wal_torn_tail": False, "wal_errors": [],
                        "ok": True}
        fence = 0
        if os.path.exists(self._ckpt_path):
            try:
                snap = MemStore.load(self._ckpt_path)
                fence = snap.committed_txns
                report["checkpoint"] = {
                    "seq": fence,
                    "collections": len(snap.colls),
                    "objects": sum(len(c) for c in snap.colls.values()),
                }
            except Exception as e:
                report["checkpoint"] = {"error": repr(e)}
                report["ok"] = False
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                buf = f.read()
            pos, seq = 0, None
            while pos < len(buf):
                if pos + _HDR.size > len(buf):
                    report["wal_torn_tail"] = True
                    break
                magic, rseq, ln, crc = _HDR.unpack_from(buf, pos)
                payload = buf[pos + _HDR.size:pos + _HDR.size + ln]
                if magic != _REC_MAGIC or len(payload) != ln \
                        or crc32c(payload) != crc:
                    report["wal_torn_tail"] = True
                    break
                if seq is not None and rseq != seq + 1:
                    report["wal_errors"].append(
                        f"seq gap {seq} -> {rseq}")
                    report["ok"] = False
                try:
                    decode_txn(payload)
                except Exception as e:
                    report["wal_errors"].append(
                        f"seq {rseq}: undecodable ({e!r})")
                    report["ok"] = False
                seq = rseq
                report["wal_records"] += 1
                pos += _HDR.size + ln
        return report


def mount_store(directory: str, fsync: bool = False) -> WALStore:
    s = WALStore(directory, fsync=fsync)
    s.mount()
    return s
