"""Device-resident shard bodies for MemStore.

A ``DeviceShard`` is a shard body that never made the device->host trip:
an on-device array handle plus its length and the crc32c the fused
encode kernel computed before any d2h (ops/crc32c_device).  MemStore
stores the handle as the object's data; the body is lazily materialized
to host bytes on the first host read (an *accounted* d2h at the
``memstore.fetch_shard`` call site), so a write's encode->store path
moves zero body bytes and a read-hot shard stays in HBM until a client
actually fetches it.

Residency is bounded: every live resident shard is registered with the
process-wide ``g_device_budget`` LRU.  When resident bytes exceed
``os_memstore_device_bytes_max`` the coldest shards are *demoted* —
copied down to host bytes (accounted at ``memstore.demote_shard``) and
dropped from HBM.  The budget holds weak references only, so a shard
that MemStore discards (truncate, overwrite, collection teardown)
releases its bytes without any unregister call.

All state transitions (resident -> host) happen under the budget's one
named lock; ``materialize`` is therefore safe to race from scrub, read,
and eviction at once — exactly one d2h happens.
"""
from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

from ..common.config import g_conf
from ..common.lockdep import DebugLock
from ..trace.devprof import g_devprof

# ---- perf counters (perf dump / Prometheus memstore_device_*) --------------
MEMSTORE_DEVICE_FIRST = 96100
l_msd_resident_bytes = 96101    # gauge: device-resident shard bytes
l_msd_resident_shards = 96102   # gauge: device-resident shard count
l_msd_materializations = 96103  # lazy first-host-read materializations
l_msd_demotions = 96104         # budget-pressure demotions to host bytes
l_msd_crc_device = 96105        # HashInfo digests taken from the fused
                                # device CRC (no host hashing)
l_msd_crc_host = 96106          # HashInfo digests hashed on host bytes
MEMSTORE_DEVICE_LAST = 96110

_msd_pc = None
_msd_pc_lock = DebugLock("memstore_device_pc::init")


def memstore_device_perf_counters():
    """The device-resident shard store's counter logger (perf dump /
    Prometheus ``ceph_daemon_memstore_device_*``)."""
    global _msd_pc
    if _msd_pc is not None:
        return _msd_pc
    with _msd_pc_lock:
        if _msd_pc is None:
            from ..common.perf_counters import PerfCountersBuilder
            b = PerfCountersBuilder("memstore_device",
                                    MEMSTORE_DEVICE_FIRST,
                                    MEMSTORE_DEVICE_LAST)
            b.add_u64(l_msd_resident_bytes, "resident_bytes",
                      "device-resident shard body bytes (HBM)")
            b.add_u64(l_msd_resident_shards, "resident_shards",
                      "device-resident shard bodies")
            b.add_u64_counter(l_msd_materializations, "materializations",
                              "resident shards materialized to host "
                              "bytes on first host read")
            b.add_u64_counter(l_msd_demotions, "demotions",
                              "resident shards demoted to host bytes "
                              "by the os_memstore_device_bytes_max "
                              "LRU budget")
            b.add_u64_counter(l_msd_crc_device, "crc_device",
                              "shard digests taken from the fused "
                              "device CRC kernel")
            b.add_u64_counter(l_msd_crc_host, "crc_host",
                              "shard digests hashed from host bytes")
            _msd_pc = b.create_perf_counters()
    return _msd_pc


class DeviceShardBudget:
    """LRU byte budget over all live device-resident shards.

    Weak entries keyed by shard identity; ``weakref.finalize`` returns
    the bytes of shards the store simply dropped.  Eviction collects
    victims under the lock and demotes them outside it (demotion
    re-enters the lock to transition the shard's state).
    """

    def __init__(self):
        self.lock = DebugLock("DeviceShardBudget::lock")
        # id(shard) -> (weakref, nbytes); insertion order = LRU order
        self._entries: "OrderedDict[int, Tuple[weakref.ref, int]]" = \
            OrderedDict()
        self._bytes = 0

    # -- gauges --------------------------------------------------------------
    def _publish_locked(self) -> None:
        pc = memstore_device_perf_counters()
        pc.set(l_msd_resident_bytes, self._bytes)
        pc.set(l_msd_resident_shards, len(self._entries))

    def resident_bytes(self) -> int:
        with self.lock:
            return self._bytes

    def resident_shards(self) -> int:
        with self.lock:
            return len(self._entries)

    # -- membership ----------------------------------------------------------
    def admit(self, shard: "DeviceShard") -> None:
        sid = id(shard)
        with self.lock:
            if sid not in self._entries:
                self._entries[sid] = (weakref.ref(shard), shard.length)
                self._bytes += shard.length
                self._publish_locked()
        weakref.finalize(shard, self._finalized, sid)
        self._evict_over_budget()

    def touch(self, shard: "DeviceShard") -> None:
        with self.lock:
            if id(shard) in self._entries:
                self._entries.move_to_end(id(shard))

    def _remove_locked(self, sid: int) -> None:
        ent = self._entries.pop(sid, None)
        if ent is not None:
            self._bytes -= ent[1]
            self._publish_locked()

    def _finalized(self, sid: int) -> None:
        with self.lock:
            ent = self._entries.get(sid)
            # the slot may have been recycled onto a live newcomer
            if ent is not None and ent[0]() is None:
                self._remove_locked(sid)

    # -- eviction ------------------------------------------------------------
    def _evict_over_budget(self) -> None:
        limit = int(g_conf.get_val("os_memstore_device_bytes_max"))
        if limit <= 0:
            return
        while True:
            victim = None
            with self.lock:
                if self._bytes <= limit or not self._entries:
                    return
                sid, (ref, _nb) = next(iter(self._entries.items()))
                victim = ref()
                if victim is None:
                    self._remove_locked(sid)
                    continue
            victim.demote()


g_device_budget = DeviceShardBudget()


class DeviceShard:
    """One shard body living in HBM: array handle + length + crc.

    ``bytes(shard)`` / ``len(shard)`` make it drop-in where MemStore
    slices object data, so ``stat``/``save``/host reads work unchanged —
    the bytes() coercion IS the accounted lazy materialization.
    """

    __slots__ = ("_dev", "_host", "length", "crc", "__weakref__")

    def __init__(self, dev, length: int, crc: int):
        self._dev = dev
        self._host: Optional[bytes] = None
        self.length = int(length)
        self.crc = int(crc)
        g_device_budget.admit(self)

    @property
    def is_resident(self) -> bool:
        return self._host is None

    def __len__(self) -> int:
        return self.length

    def device_array(self):
        """The live device handle, or None once materialized/demoted."""
        return self._dev

    def _to_host_locked(self) -> bytes:
        host = np.asarray(self._dev, dtype=np.uint8).tobytes()
        assert len(host) == self.length
        self._host = host
        self._dev = None
        g_device_budget._remove_locked(id(self))
        return host

    def materialize(self) -> bytes:
        """Host bytes; the first call is THE d2h of this shard's life
        (accounted at ``memstore.fetch_shard``), later calls are free."""
        if self._host is not None:
            return self._host
        with g_device_budget.lock:
            if self._host is not None:
                return self._host
            host = self._to_host_locked()
        g_devprof.account_d2h("memstore.fetch_shard", self.length)
        memstore_device_perf_counters().inc(l_msd_materializations)
        return host

    def __bytes__(self) -> bytes:
        return self.materialize()

    def demote(self) -> None:
        """Budget-pressure copy-down: same transition as materialize,
        accounted as a demotion (``memstore.demote_shard``)."""
        if self._host is not None:
            return
        with g_device_budget.lock:
            if self._host is not None:
                return
            self._to_host_locked()
        g_devprof.account_d2h("memstore.demote_shard", self.length)
        memstore_device_perf_counters().inc(l_msd_demotions)

    def corrupted(self) -> "DeviceShard":
        """Flip one body byte in place (fault injection: the stored crc
        goes stale, exactly like bitrot under a host-bytes store)."""
        if self.length == 0:
            return self
        with g_device_budget.lock:
            if self._host is not None:
                rot = bytearray(self._host)
                rot[0] ^= 0x01
                self._host = bytes(rot)
            else:
                import jax.numpy as jnp
                self._dev = self._dev.at[0].set(
                    self._dev[0] ^ jnp.uint8(1))
        return self
