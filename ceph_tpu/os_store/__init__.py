from .memstore import MemStore, Transaction, hobject_t

__all__ = ["MemStore", "Transaction", "hobject_t"]
