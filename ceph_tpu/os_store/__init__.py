from .device_shard import (DeviceShard, g_device_budget,
                           memstore_device_perf_counters)
from .memstore import MemStore, Transaction, hobject_t
from .walstore import WALStore, mount_store

__all__ = ["MemStore", "Transaction", "hobject_t", "WALStore",
           "mount_store", "DeviceShard", "g_device_budget",
           "memstore_device_perf_counters"]


def parse_pg_from_cid(cid: str):
    """(pool, ps) from a PG collection name, or None for non-PG
    collections (the 'meta' map-history collection, malformed names).
    Collection grammar: "{pool}.{ps}[s{shard}][_meta]" — THE one
    parser shared by the OSD's stray scan and the offline tools."""
    body = cid[:-5] if cid.endswith("_meta") else cid
    tail = body.split(".")[-1]
    if "s" in tail:
        body = body[:body.rindex("s")]
    try:
        pool_s, ps_s = body.split(".")
        return int(pool_s), int(ps_s)
    except ValueError:
        return None
