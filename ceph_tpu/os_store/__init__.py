from .memstore import MemStore, Transaction, hobject_t
from .walstore import WALStore, mount_store

__all__ = ["MemStore", "Transaction", "hobject_t", "WALStore",
           "mount_store"]
