"""Platform/feature probing and backend dispatch — the src/arch/ analog.

The reference probes CPU features once at startup (arch/probe.cc sets
ceph_arch_intel_sse42 etc.) and SIMD code paths branch on the flags
(e.g. crc32c picks the SSE4 implementation).  The TPU-native analog
probes the accelerator and host capabilities once, and the compute
backends consult the flags instead of re-deriving them:

- ``platform``/``device_kind``/``n_devices``: what jax will run on.
- ``x64``: whether 64-bit integer lanes work (the exact straw2 kernel
  needs s64 draws; the CPU backend always has it, TPU does too but the
  probe proves it).
- ``pallas``: whether Pallas TPU kernels can compile here.
- ``native``: the C++ helper library (crush evaluator + GF region
  coder, native/*.cpp) is built and loadable.

Probing jax initializes the backend, which over a tunnelled device can
be slow or hang — so everything is lazy and cached, and `probe()`
never raises (absent features read False).

CLI: ``python -m ceph_tpu.arch`` prints the probe as one JSON line
(the "ceph features"-style introspection surface).
"""
from __future__ import annotations

import json
from typing import Any, Dict

_cache: Dict[str, Any] = {}


def enable_x64():
    """The x64-trace context manager, wherever this jax release keeps
    it: top-level ``jax.enable_x64`` on newer releases,
    ``jax.experimental.enable_x64`` on 0.4.x.  Every exact-s64/u64
    kernel trace goes through here so one jax upgrade can't silently
    break the integer-exact paths."""
    import jax
    fn = getattr(jax, "enable_x64", None)
    if fn is None:
        from jax.experimental import enable_x64 as fn
    return fn(True)


def probe(refresh: bool = False) -> Dict[str, Any]:
    global _cache
    if _cache and not refresh:
        return _cache
    out: Dict[str, Any] = {
        "platform": "none", "device_kind": "", "n_devices": 0,
        "x64": False, "pallas": False, "native": False,
    }
    try:
        from .native import native_available
        out["native"] = bool(native_available())
    except Exception:
        pass
    try:
        import jax
        devs = jax.devices()
        out["platform"] = devs[0].platform
        out["device_kind"] = getattr(devs[0], "device_kind", "")
        out["n_devices"] = len(devs)
    except Exception:
        _cache = out
        return out
    try:
        import jax.numpy as jnp
        import numpy as np
        with enable_x64():
            # one-shot capability probe, memoized in _cache
            # lint: allow[jit-cache-hygiene]
            v = jax.jit(lambda a: a * a)(
                jnp.asarray(np.int64(3_000_000_019)))
            out["x64"] = int(v) == 3_000_000_019 ** 2
    except Exception:
        out["x64"] = False
    out["pallas"] = _probe_pallas(out["platform"])
    _cache = out
    return out


def _probe_pallas(platform: str) -> bool:
    """Pallas compiles only on real TPU (the interpreter path on CPU is
    not a production backend)."""
    if platform != "tpu":
        return False
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except Exception:
        return False


def have(feature: str) -> bool:
    return bool(probe().get(feature))


if __name__ == "__main__":
    print(json.dumps(probe()))
