"""cls_rgw-lite: server-side bucket-index methods (src/cls/rgw/
cls_rgw.cc in the reference).

The reference keeps each bucket's object listing in index objects
(``.dir.<bucket_id>``) and mutates them with a two-phase protocol:
``bucket_prepare_op`` marks an in-flight mutation under a unique tag,
the gateway writes the data objects, then ``bucket_complete_op``
commits (or cancels) the entry.  A gateway crash between the phases
leaves only a pending marker — never a listing entry pointing at
missing data.  Same protocol here over the index object's omap:

  entry_<name>    -> JSON object metadata (committed listing entry)
  pending_<tag>   -> JSON {name, op}     (in-flight marker)
"""
from __future__ import annotations

import json

from ..osd.cls import CLS_METHOD_WR, ClsContext, register_cls_method


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _parse(inp: bytes):
    try:
        return json.loads(inp.decode()) if inp else {}
    except ValueError:
        return {}


@register_cls_method("rgw", "bucket_prepare_op", CLS_METHOD_WR)
def _prepare(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    ctx.omap_set({f"pending_{req['tag']}":
                  _j({"name": req["name"], "op": req["op"]})})
    return 0, b""


@register_cls_method("rgw", "bucket_complete_op", CLS_METHOD_WR)
def _complete(ctx: ClsContext, inp: bytes):
    """Commit the prepared mutation: install/remove the listing entry
    and drop the pending marker.  -ECANCELED if the tag is unknown
    (e.g. a racing suggest-cleanup already cancelled it)."""
    req = _parse(inp)
    tag = f"pending_{req['tag']}"
    om = ctx.omap_get()
    if tag not in om:
        return -125, b""
    if req["op"] == "put":
        ctx.omap_set({f"entry_{req['name']}": _j(req["meta"])})
    elif req["op"] == "del":
        ctx.omap_rm_keys([f"entry_{req['name']}"])
    ctx.omap_rm_keys([tag])
    return 0, b""


@register_cls_method("rgw", "bucket_cancel_op", CLS_METHOD_WR)
def _cancel(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    ctx.omap_rm_keys([f"pending_{req['tag']}"])
    return 0, b""


@register_cls_method("rgw", "bucket_list")
def _list(ctx: ClsContext, inp: bytes):
    """Listing with prefix/marker/max_keys, server-side like
    cls_rgw_bucket_list so huge buckets never ship their whole omap."""
    if not ctx.exists:
        # a LOST index object must answer ESTALE, never "empty
        # bucket" — gc would purge a live bucket's data otherwise
        return -116, b""
    req = _parse(inp)
    prefix = req.get("prefix", "")
    marker = req.get("marker", "")
    maxk = int(req.get("max_keys", 1000))
    om = ctx.omap_get()
    names = sorted(k[len("entry_"):] for k in om
                   if k.startswith("entry_"))
    out, truncated = [], False
    for n in names:
        if n <= marker or not n.startswith(prefix):
            continue
        if len(out) >= maxk:
            truncated = True
            break
        out.append({"name": n, **json.loads(om[f"entry_{n}"])})
    return 0, _j({"entries": out, "truncated": truncated})


@register_cls_method("rgw", "bucket_get_entry")
def _get_entry(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    v = ctx.omap_get().get(f"entry_{req['name']}")
    if v is None:
        return -2, b""
    return 0, bytes(v)


@register_cls_method("rgw", "bucket_stats")
def _stats(ctx: ClsContext, inp: bytes):
    if not ctx.exists:
        return -116, b""      # lost index: unknowable, not empty
    om = ctx.omap_get()
    entries = [json.loads(v) for k, v in om.items()
               if k.startswith("entry_")]
    return 0, _j({"num_objects": len(entries),
                  "size_bytes": sum(e.get("size", 0) for e in entries),
                  "pending_ops": sum(1 for k in om
                                     if k.startswith("pending_"))})
