"""rgw-lite: S3-shaped object gateway (src/rgw + src/cls/rgw at lite
scale).

Importing registers the ``rgw`` object class (two-phase bucket-index
methods); ``gateway.RGWLite`` is the RGWRados-role core and
``http.S3Frontend``/``http.serve`` the path-style S3 REST frontend.
"""
from . import cls_rgw  # noqa: F401  (registers the cls methods)
from .gateway import RGWError, RGWLite
from .http import S3Frontend, SwiftFrontend, serve

__all__ = ["RGWError", "RGWLite", "S3Frontend", "SwiftFrontend",
           "serve"]
