"""S3 path-style HTTP frontend for rgw-lite (the civetweb/beast
frontend role, src/rgw/rgw_main.cc + rgw_rest_s3.cc at lite scale).

Speaks the S3 subset the gateway implements over path-style URLs
(``/bucket``, ``/bucket/key``): bucket PUT/GET/DELETE, object
PUT/GET/HEAD/DELETE/POST, ListObjectsV1/V2, and the subresources the
reference routes in rgw_rest_s3.cc: ``?versioning`` (GET/PUT,
rgw_rest_s3.cc:868-960), ``?versions`` (ListObjectVersions),
``versionId=`` on object GET/HEAD/DELETE, ``?acl`` (GET/PUT bucket +
object policy XML, rgw_rest_s3.cc:2176-2209 / rgw_acl_s3.cc
grammar), ``?lifecycle`` (GET/PUT/DELETE), and multipart
(``?uploads`` POST/GET, ``uploadId=`` PUT/POST/GET/DELETE,
rgw_rest_s3.cc:2628).  Auth speaks both reference header flavors
(rgw_auth_s3.cc): AWS signature v2 with full canonicalization
(content-md5/content-type/date-or-x-amz-date, sorted x-amz-*
headers, and the signed-subresource canonical resource) and AWS
signature v4 (``AWS4-HMAC-SHA256``: canonical request over the
SignedHeaders list, credential-scope HMAC key chain, and
x-amz-content-sha256 payload verification incl. UNSIGNED-PAYLOAD).

``handle()`` is a pure request->response function (testable without
sockets); ``serve()`` wraps it in a threaded stdlib HTTPServer.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import re
import time as _time
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple
from xml.sax.saxutils import escape

from . import acl_xml
from .gateway import RGWError, RGWLite


def _sign_v2(secret: str, method: str, date: str, path: str) -> str:
    """Legacy helper: the v2 string-to-sign with every optional
    section empty (kept for callers that sign bare requests)."""
    sts = f"{method}\n\n\n{date}\n{path}"
    mac = hmac.new(secret.encode(), sts.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


# the subresources that participate in the v2 canonical resource, in
# the reference's sorted order (rgw_auth_s3.cc:23-48
# signed_subresources)
SIGNED_SUBRESOURCES = (
    "acl", "cors", "delete", "lifecycle", "location", "logging",
    "notification", "partNumber", "policy", "requestPayment",
    "response-cache-control", "response-content-disposition",
    "response-content-encoding", "response-content-language",
    "response-content-type", "response-expires", "tagging", "torrent",
    "uploadId", "uploads", "versionId", "versioning", "versions",
    "website")


def _canon_amz_headers(headers: Dict[str, str]) -> str:
    """x-amz-* headers, lowercased keys, sorted, "k:v\\n" each
    (rgw_auth_s3.cc get_canon_amz_hdr over the meta map)."""
    metas = sorted((k.lower(), v.strip()) for k, v in headers.items()
                   if k.lower().startswith("x-amz-"))
    return "".join(f"{k}:{v}\n" for k, v in metas)


def _canon_resource(path: str, query: Dict[str, str]) -> str:
    """path + the signed subresources present in the query, '?'/'&'
    joined, '=value' only when non-empty (get_canon_resource)."""
    out = path
    initial = True
    for sub in SIGNED_SUBRESOURCES:
        if sub not in query:
            continue
        out += "?" if initial else "&"
        initial = False
        out += sub
        if query[sub]:
            out += "=" + query[sub]
    return out


def string_to_sign_v2(method: str, path: str, headers: Dict[str, str],
                      query: Dict[str, str]) -> str:
    """The full v2 canonical header string
    (rgw_create_s3_canonical_header): Date drops to empty when
    x-amz-date is supplied."""
    h = {k.lower(): v for k, v in headers.items()}
    date = "" if "x-amz-date" in h else h.get("date", "")
    return (f"{method}\n{h.get('content-md5', '')}\n"
            f"{h.get('content-type', '')}\n{date}\n"
            f"{_canon_amz_headers(headers)}"
            f"{_canon_resource(path, query)}")


def sign_v2(secret: str, method: str, path: str,
            headers: Optional[Dict[str, str]] = None,
            query: Optional[Dict[str, str]] = None) -> str:
    sts = string_to_sign_v2(method, path, headers or {}, query or {})
    mac = hmac.new(secret.encode(), sts.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


# ---- AWS signature v4 (rgw_auth_s3.cc:400-760) --------------------------

def _uri_quote(s: str, safe: str = "-_.~") -> str:
    out = []
    for ch in s.encode():
        c = chr(ch)
        # ASCII-only: non-ASCII bytes must always %-escape (AWS v4
        # canonical URI encoding; unicode alnum chars don't count)
        if ch < 0x80 and (c.isalnum() or c in safe):
            out.append(c)
        else:
            out.append("%%%02X" % ch)
    return "".join(out)


def v4_canonical_request(method: str, path: str,
                         query: Dict[str, str],
                         headers: Dict[str, str],
                         signed_headers: List[str],
                         payload_hash: str) -> str:
    h = {k.lower(): v for k, v in headers.items()}
    cq = "&".join(
        f"{_uri_quote(k)}={_uri_quote(v)}"
        for k, v in sorted(query.items()))
    ch = "".join(f"{name}:{' '.join(h.get(name, '').split())}\n"
                 for name in signed_headers)
    return "\n".join([method, _uri_quote(path, safe="/-_.~"), cq, ch,
                      ";".join(signed_headers), payload_hash])


def v4_signature(secret: str, amz_date: str, scope: str,
                 canonical_request: str) -> str:
    """AWS4-HMAC-SHA256: chained signing key over the credential
    scope, then HMAC of the string-to-sign (get_v4_signing_key /
    get_v4_signature)."""
    sts = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])
    key = ("AWS4" + secret).encode()
    for part in scope.split("/"):
        key = hmac.new(key, part.encode(), hashlib.sha256).digest()
    return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()


def sign_v4(access_key: str, secret: str, method: str, path: str,
            headers: Dict[str, str],
            query: Optional[Dict[str, str]] = None,
            body: bytes = b"", region: str = "default",
            unsigned_payload: bool = False) -> str:
    """Client-side convenience: returns the Authorization header value
    for a v4-signed request (x-amz-date and x-amz-content-sha256 must
    already be in *headers*; this fills them if absent)."""
    amz_date = headers.get("x-amz-date")
    if amz_date is None:
        amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
        headers["x-amz-date"] = amz_date
    if "x-amz-content-sha256" not in headers:
        headers["x-amz-content-sha256"] = (
            "UNSIGNED-PAYLOAD" if unsigned_payload
            else hashlib.sha256(body).hexdigest())
    scope = f"{amz_date[:8]}/{region}/s3/aws4_request"
    signed = sorted(k.lower() for k in headers
                    if k.lower() in ("host", "content-type",
                                     "content-md5")
                    or k.lower().startswith("x-amz-"))
    creq = v4_canonical_request(method, path, query or {}, headers,
                                signed, headers["x-amz-content-sha256"])
    sig = v4_signature(secret, amz_date, scope, creq)
    return (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")


def _err(status: int, code: str, message: str = "") -> Tuple[int, Dict,
                                                             bytes]:
    body = (f'<?xml version="1.0"?><Error><Code>{escape(code)}</Code>'
            f"<Message>{escape(message or code)}</Message></Error>")
    return status, {"Content-Type": "application/xml"}, body.encode()


_ERRNO_TO_S3 = {
    -2: (404, "NoSuchKey"),
    -13: (403, "AccessDenied"),
    -17: (409, "BucketAlreadyExists"),
    -22: (400, "InvalidArgument"),
    -39: (409, "BucketNotEmpty"),
}

# gateway reasons that ARE S3 error codes ride through verbatim (the
# reference maps op_ret -> rgw_http_errors the same way)
_CODE_RE = re.compile(r"^[A-Z][A-Za-z]+$")


def _rgw_err(e: RGWError) -> Tuple[int, Dict, bytes]:
    status, code = _ERRNO_TO_S3.get(e.result, (500, "InternalError"))
    # RGWError's str is "rgw <api>: <result> <reason>"; when the
    # reason IS an S3 code (NoSuchUpload, InvalidPart, ...) it rides
    # through verbatim like the reference's rgw_http_errors mapping
    reason = str(e).rsplit(" ", 1)[-1]
    if _CODE_RE.match(reason):
        code = reason
    return _err(status, code, str(e))


def _iso8601(ts: float) -> str:
    return _time.strftime("%Y-%m-%dT%H:%M:%S.000Z", _time.gmtime(ts))


# namespace-insensitive XML helpers shared with the ACL grammar
_xml_local = acl_xml._local
_xml_find = acl_xml._find


def _xml_text(el, name, default: str = "") -> str:
    child = _xml_find(el, name)
    return (child.text or "").strip() if child is not None else default


class S3Frontend:
    def __init__(self, rgw: RGWLite):
        self.rgw = rgw

    # ---- auth --------------------------------------------------------------
    def _authenticate(self, method: str, path: str,
                      headers: Dict[str, str], query: Dict[str, str],
                      body: bytes) -> Optional[Dict]:
        """Header auth, v2 (``AWS AK:sig``, full canonicalization incl.
        content headers, x-amz-*, and signed subresources) or v4
        (``AWS4-HMAC-SHA256 Credential=.., SignedHeaders=..,
        Signature=..``) — rgw_auth_s3.cc's two header flavors."""
        auth = headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256 "):
            return self._authenticate_v4(method, path, headers, query,
                                         body, auth)
        if not auth.startswith("AWS ") or ":" not in auth[4:]:
            return None
        access_key, sig = auth[4:].split(":", 1)
        user = self.rgw.user_by_access_key(access_key)
        if user is None:
            return None
        secret = self.rgw.secret_for_key(user, access_key)
        want = sign_v2(secret, method, path, headers, query)
        return user if hmac.compare_digest(want, sig) else None

    def _authenticate_v4(self, method: str, path: str,
                         headers: Dict[str, str],
                         query: Dict[str, str], body: bytes,
                         auth: str) -> Optional[Dict]:
        fields: Dict[str, str] = {}
        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        cred = fields.get("Credential", "")
        signed = [s for s in fields.get("SignedHeaders", "").split(";")
                  if s]
        sig = fields.get("Signature", "")
        # access_key/YYYYMMDD/region/service/aws4_request
        # (rgw_auth_s3.cc:419-427)
        bits = cred.split("/")
        if len(bits) != 5 or bits[4] != "aws4_request" or not signed \
                or not sig:
            return None
        access_key, scope = bits[0], "/".join(bits[1:])
        user = self.rgw.user_by_access_key(access_key)
        if user is None:
            return None
        secret = self.rgw.secret_for_key(user, access_key)
        h = {k.lower(): v for k, v in headers.items()}
        amz_date = h.get("x-amz-date", "")
        if not amz_date.startswith(bits[1]):
            return None                # credential date != request date
        payload_hash = h.get("x-amz-content-sha256",
                             "UNSIGNED-PAYLOAD")
        if payload_hash == "STREAMING-AWS4-HMAC-SHA256-PAYLOAD":
            # chunked uploads need per-chunk signature verification
            # (the reference's AWSv4ComplMulti); accepting the body
            # unverified would be an integrity hole, so refuse
            return None
        if payload_hash != "UNSIGNED-PAYLOAD":
            if payload_hash != hashlib.sha256(body).hexdigest():
                return None            # body does not match its hash
        creq = v4_canonical_request(method, path, query, headers,
                                    signed, payload_hash)
        want = v4_signature(secret, amz_date, scope, creq)
        return user if hmac.compare_digest(want, sig) else None

    # ---- request router ----------------------------------------------------
    def handle(self, method: str, path: str,
               headers: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, str], bytes]:
        headers = headers or {}
        query = query or {}
        user = self._authenticate(method, path.split("?")[0], headers,
                                  query, body)
        if user is None:
            return _err(403, "AccessDenied", "bad or missing signature")
        if user.get("suspended"):
            # the reference's RGW_USER_SUSPENDED refusal
            return _err(403, "UserSuspended", "account suspended")
        parts = path.split("?")[0].strip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        try:
            if not bucket:
                return self._list_buckets(user)
            if not key:
                return self._bucket_op(method, user, bucket, query,
                                       body, headers)
            return self._object_op(method, user, bucket, key, body,
                                   query, headers)
        except RGWError as e:
            return _rgw_err(e)
        except ValueError as e:
            msg = str(e)
            code = msg.split(":", 1)[0] if _CODE_RE.match(
                msg.split(":", 1)[0]) else "InvalidArgument"
            return _err(400, code, msg)
        except Exception as e:      # a handler thread must always reply
            return _err(500, "InternalError", repr(e))

    # ---- display names for ACL XML -----------------------------------------
    def _display_names(self, *uids: Optional[str]) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for uid in uids:
            if not uid or uid in ("*", "auth") or uid in out:
                continue
            try:
                dn = self.rgw.get_user(uid).get("display_name")
            except RGWError:
                continue
            if dn:
                out[uid] = dn
        return out

    def _acl_response(self, policy: Dict) -> Tuple[int, Dict, bytes]:
        uids = [policy.get("owner")] + \
            [g["grantee"] for g in policy.get("grants", [])]
        xml = acl_xml.policy_to_xml(policy.get("owner"),
                                    policy.get("grants", []),
                                    self._display_names(*uids))
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    @staticmethod
    def _acl_request(headers: Dict[str, str], body: bytes
                     ) -> Tuple[Optional[str], Optional[List[Dict]]]:
        """PUT ?acl input: an XML policy body, else the x-amz-acl
        canned header (the reference accepts both; body wins)."""
        if body.strip():
            _owner, grants = acl_xml.policy_from_xml(body)
            return None, grants
        canned = headers.get("x-amz-acl") or \
            headers.get("X-Amz-Acl") or "private"
        return canned, None

    def _list_buckets(self, user):
        names = "".join(f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                        for n in self.rgw.list_buckets(user["uid"]))
        xml = (f'<?xml version="1.0"?><ListAllMyBucketsResult>'
               f"<Buckets>{names}</Buckets></ListAllMyBucketsResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    def _bucket_op(self, method, user, bucket, query, body, headers):
        actor = user["uid"]
        if "versioning" in query:
            return self._versioning_op(method, actor, bucket, body)
        if "versions" in query:
            return self._list_versions(method, actor, bucket, query)
        if "acl" in query:
            return self._bucket_acl_op(method, actor, bucket, body,
                                       headers)
        if "lifecycle" in query:
            return self._lifecycle_op(method, actor, bucket, body)
        if "uploads" in query and method == "GET":
            return self._list_uploads(actor, bucket)
        if method == "PUT":
            self.rgw.create_bucket(user["uid"], bucket)
            return 200, {}, b""
        if method == "DELETE":
            # policy-gated like every other op (RGWDeleteBucket goes
            # through verify_bucket_permission, not a raw owner check)
            self.rgw.delete_bucket(bucket, actor=actor)
            return 204, {}, b""
        if method == "GET":
            # ACL-gated (bucket READ), not owner-gated: public-read
            # buckets list for any authenticated caller
            v2 = query.get("list-type") == "2"
            marker = (query.get("continuation-token")
                      or query.get("start-after", "")) if v2 \
                else query.get("marker", "")
            res = self.rgw.list_objects(
                bucket, prefix=query.get("prefix", ""),
                delimiter=query.get("delimiter", ""),
                marker=marker,
                max_keys=int(query.get("max-keys", "1000")),
                actor=user["uid"])
            items = "".join(
                f"<Contents><Key>{escape(e['name'])}</Key>"
                f"<Size>{e['size']}</Size>"
                f'<ETag>"{e["etag"]}"</ETag></Contents>'
                for e in res["contents"])
            cps = "".join(
                f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
                f"</CommonPrefixes>"
                for p in res["common_prefixes"])
            extra = ""
            if v2:
                count = len(res["contents"]) + len(res["common_prefixes"])
                extra = f"<KeyCount>{count}</KeyCount>"
                if res["truncated"] and res.get("next_marker"):
                    tok = escape(res["next_marker"])
                    extra += (f"<NextContinuationToken>{tok}"
                              f"</NextContinuationToken>")
            xml = (f'<?xml version="1.0"?><ListBucketResult>'
                   f"<Name>{escape(bucket)}</Name>"
                   f"<IsTruncated>{str(res['truncated']).lower()}"
                   f"</IsTruncated>{extra}{items}{cps}"
                   f"</ListBucketResult>")
            return 200, {"Content-Type": "application/xml"}, xml.encode()
        return _err(405, "MethodNotAllowed")

    # ---- ?versioning (rgw_rest_s3.cc:868-960) ------------------------------
    def _versioning_op(self, method, actor, bucket, body):
        if method == "GET":
            status = self.rgw.get_bucket_versioning(bucket,
                                                    actor=actor)
            inner = "" if status is None else \
                f"<Status>{status.capitalize()}</Status>"
            xml = (f'<?xml version="1.0"?>'
                   f'<VersioningConfiguration xmlns="{acl_xml.XMLNS}">'
                   f"{inner}</VersioningConfiguration>")
            return 200, {"Content-Type": "application/xml"}, \
                xml.encode()
        if method == "PUT":
            try:
                root = ET.fromstring(body)
            except ET.ParseError:
                return _err(400, "MalformedXML")
            if _xml_local(root.tag) != "VersioningConfiguration":
                return _err(400, "MalformedXML")
            status = _xml_text(root, "Status")
            if not status:      # VersioningNotChanged
                return 200, {}, b""
            if status.lower() not in ("enabled", "suspended"):
                return _err(400, "MalformedXML",
                            f"bad Status {status!r}")
            self.rgw.put_bucket_versioning(bucket, status.lower(),
                                           actor=actor)
            return 200, {}, b""
        return _err(405, "MethodNotAllowed")

    # ---- ?versions (ListObjectVersions) ------------------------------------
    def _list_versions(self, method, actor, bucket, query):
        if method != "GET":
            return _err(405, "MethodNotAllowed")
        vers = self.rgw.list_object_versions(
            bucket, prefix=query.get("prefix", ""), actor=actor)
        items = []
        for v in vers:
            tag = "DeleteMarker" if v["delete_marker"] else "Version"
            fields = (f"<Key>{escape(v['key'])}</Key>"
                      f"<VersionId>{escape(v['version_id'])}"
                      f"</VersionId>"
                      f"<IsLatest>{str(v['is_latest']).lower()}"
                      f"</IsLatest>"
                      f"<LastModified>{_iso8601(v['mtime'])}"
                      f"</LastModified>")
            if not v["delete_marker"]:
                fields += (f'<ETag>"{v["etag"]}"</ETag>'
                           f"<Size>{v['size']}</Size>")
            items.append(f"<{tag}>{fields}</{tag}>")
        xml = (f'<?xml version="1.0"?>'
               f'<ListVersionsResult xmlns="{acl_xml.XMLNS}">'
               f"<Name>{escape(bucket)}</Name>"
               f"<Prefix>{escape(query.get('prefix', ''))}</Prefix>"
               f"{''.join(items)}</ListVersionsResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    # ---- ?acl (bucket) -----------------------------------------------------
    def _bucket_acl_op(self, method, actor, bucket, body, headers):
        if method == "GET":
            return self._acl_response(
                self.rgw.get_bucket_acl(bucket, actor=actor))
        if method == "PUT":
            canned, grants = self._acl_request(headers, body)
            self.rgw.put_bucket_acl(bucket, canned=canned,
                                    grants=grants, actor=actor)
            return 200, {}, b""
        return _err(405, "MethodNotAllowed")

    # ---- ?lifecycle --------------------------------------------------------
    def _lifecycle_op(self, method, actor, bucket, body):
        if method == "GET":
            rules = self.rgw.get_bucket_lifecycle(bucket, actor=actor)
            if not rules:
                return _err(404, "NoSuchLifecycleConfiguration")
            items = []
            for r in rules:
                inner = ""
                if r.get("id"):
                    inner += f"<ID>{escape(r['id'])}</ID>"
                inner += (f"<Prefix>{escape(r.get('prefix', ''))}"
                          f"</Prefix>"
                          f"<Status>{r.get('status', 'Enabled')}"
                          f"</Status>")
                if r.get("expiration_days"):
                    inner += (f"<Expiration><Days>"
                              f"{r['expiration_days']}</Days>"
                              f"</Expiration>")
                if r.get("noncurrent_days"):
                    inner += (f"<NoncurrentVersionExpiration>"
                              f"<NoncurrentDays>"
                              f"{r['noncurrent_days']}"
                              f"</NoncurrentDays>"
                              f"</NoncurrentVersionExpiration>")
                items.append(f"<Rule>{inner}</Rule>")
            xml = (f'<?xml version="1.0"?>'
                   f'<LifecycleConfiguration xmlns="{acl_xml.XMLNS}">'
                   f"{''.join(items)}</LifecycleConfiguration>")
            return 200, {"Content-Type": "application/xml"}, \
                xml.encode()
        if method == "PUT":
            try:
                root = ET.fromstring(body)
            except ET.ParseError:
                return _err(400, "MalformedXML")
            rules = []
            for rule in root:
                if _xml_local(rule.tag) != "Rule":
                    continue
                r: Dict = {"id": _xml_text(rule, "ID"),
                           "prefix": _xml_text(rule, "Prefix"),
                           "status": _xml_text(rule, "Status",
                                               "Enabled")}
                exp = _xml_find(rule, "Expiration")
                if exp is not None:
                    r["expiration_days"] = int(_xml_text(exp, "Days",
                                                         "0"))
                non = _xml_find(rule, "NoncurrentVersionExpiration")
                if non is not None:
                    r["noncurrent_days"] = int(
                        _xml_text(non, "NoncurrentDays", "0"))
                rules.append(r)
            if not rules:
                return _err(400, "MalformedXML", "no Rule")
            self.rgw.put_bucket_lifecycle(bucket, rules, actor=actor)
            return 200, {}, b""
        if method == "DELETE":
            self.rgw.delete_bucket_lifecycle(bucket, actor=actor)
            return 204, {}, b""
        return _err(405, "MethodNotAllowed")

    # ---- ?uploads listing --------------------------------------------------
    def _list_uploads(self, actor, bucket):
        ups = self.rgw.list_multipart_uploads(bucket, actor=actor)
        items = "".join(
            f"<Upload><Key>{escape(u['key'])}</Key>"
            f"<UploadId>{escape(u['upload_id'])}</UploadId></Upload>"
            for u in ups)
        xml = (f'<?xml version="1.0"?>'
               f'<ListMultipartUploadsResult xmlns="{acl_xml.XMLNS}">'
               f"<Bucket>{escape(bucket)}</Bucket>{items}"
               f"</ListMultipartUploadsResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    # ---- objects -----------------------------------------------------------
    def _object_op(self, method, user, bucket, key, body, query,
                   headers):
        # policy decisions live in the gateway's ACL engine (canned
        # ACLs + grants, rgw_acl_s3.cc role): the frontend just
        # supplies the authenticated actor
        actor = user["uid"]
        if "acl" in query:
            return self._object_acl_op(method, actor, bucket, key,
                                       body, headers)
        if "uploads" in query and method == "POST":
            upload_id = self.rgw.initiate_multipart(bucket, key,
                                                    actor=actor)
            xml = (f'<?xml version="1.0"?>'
                   f'<InitiateMultipartUploadResult '
                   f'xmlns="{acl_xml.XMLNS}">'
                   f"<Bucket>{escape(bucket)}</Bucket>"
                   f"<Key>{escape(key)}</Key>"
                   f"<UploadId>{upload_id}</UploadId>"
                   f"</InitiateMultipartUploadResult>")
            return 200, {"Content-Type": "application/xml"}, \
                xml.encode()
        if "uploadId" in query:
            return self._multipart_op(method, actor, bucket, key,
                                      body, query)
        vid = query.get("versionId")
        if method == "PUT":
            meta = self.rgw.put_object(bucket, key, body, actor=actor)
            hdrs = {"ETag": f'"{meta["etag"]}"'}
            if meta.get("vid"):
                hdrs["x-amz-version-id"] = meta["vid"]
            canned = headers.get("x-amz-acl") or \
                headers.get("X-Amz-Acl")
            if canned:
                # object-level canned ACL on upload; the actor just
                # became the owner, so this cannot be denied
                self.rgw.put_object_acl(bucket, key, canned=canned,
                                        actor=actor)
            return 200, hdrs, b""
        if method == "GET":
            data = self.rgw.get_object(bucket, key, version_id=vid,
                                       actor=actor)
            meta = self.rgw.head_object(bucket, key, version_id=vid)
            hdrs = {"Content-Type": meta["content_type"],
                    "ETag": f'"{meta["etag"]}"'}
            if meta.get("vid"):
                hdrs["x-amz-version-id"] = meta["vid"]
            return 200, hdrs, data
        if method == "HEAD":
            meta = self.rgw.head_object(bucket, key, version_id=vid,
                                        actor=actor)
            if meta.get("delete_marker"):
                return _err(405, "MethodNotAllowed",
                            "delete marker")   # S3's 405 on marker HEAD
            hdrs = {"Content-Length": str(meta["size"]),
                    "ETag": f'"{meta["etag"]}"'}
            if meta.get("vid"):
                hdrs["x-amz-version-id"] = meta["vid"]
            return 200, hdrs, b""
        if method == "DELETE":
            res = self.rgw.delete_object(bucket, key, version_id=vid,
                                         actor=actor)
            hdrs = {}
            if res.get("version_id"):
                hdrs["x-amz-version-id"] = res["version_id"]
            if res.get("delete_marker"):
                hdrs["x-amz-delete-marker"] = "true"
            return 204, hdrs, b""
        return _err(405, "MethodNotAllowed")

    def _object_acl_op(self, method, actor, bucket, key, body,
                       headers):
        if method == "GET":
            return self._acl_response(
                self.rgw.get_object_acl(bucket, key, actor=actor))
        if method == "PUT":
            canned, grants = self._acl_request(headers, body)
            self.rgw.put_object_acl(bucket, key, canned=canned,
                                    grants=grants, actor=actor)
            return 200, {}, b""
        return _err(405, "MethodNotAllowed")

    def _multipart_op(self, method, actor, bucket, key, body, query):
        upload_id = query["uploadId"]
        if method == "PUT" and "partNumber" in query:
            etag = self.rgw.upload_part(bucket, key, upload_id,
                                        int(query["partNumber"]),
                                        body, actor=actor)
            return 200, {"ETag": f'"{etag}"'}, b""
        if method == "POST":
            try:
                root = ET.fromstring(body)
            except ET.ParseError:
                return _err(400, "MalformedXML")
            parts = []
            for p in root:
                if _xml_local(p.tag) != "Part":
                    continue
                parts.append({
                    "part_number": int(_xml_text(p, "PartNumber",
                                                 "0")),
                    "etag": _xml_text(p, "ETag").strip('"')})
            meta = self.rgw.complete_multipart(bucket, key, upload_id,
                                               parts=parts,
                                               actor=actor)
            xml = (f'<?xml version="1.0"?>'
                   f'<CompleteMultipartUploadResult '
                   f'xmlns="{acl_xml.XMLNS}">'
                   f"<Location>/{escape(bucket)}/{escape(key)}"
                   f"</Location>"
                   f"<Bucket>{escape(bucket)}</Bucket>"
                   f"<Key>{escape(key)}</Key>"
                   f'<ETag>"{meta["etag"]}"</ETag>'
                   f"</CompleteMultipartUploadResult>")
            return 200, {"Content-Type": "application/xml"}, \
                xml.encode()
        if method == "GET":
            parts = self.rgw.list_parts(bucket, key, upload_id,
                                        actor=actor)
            items = "".join(
                f"<Part><PartNumber>{p['part_number']}</PartNumber>"
                f'<ETag>"{p["etag"]}"</ETag>'
                f"<Size>{p['size']}</Size></Part>"
                for p in parts)
            xml = (f'<?xml version="1.0"?>'
                   f'<ListPartsResult xmlns="{acl_xml.XMLNS}">'
                   f"<Bucket>{escape(bucket)}</Bucket>"
                   f"<Key>{escape(key)}</Key>"
                   f"<UploadId>{upload_id}</UploadId>{items}"
                   f"</ListPartsResult>")
            return 200, {"Content-Type": "application/xml"}, \
                xml.encode()
        if method == "DELETE":
            self.rgw.abort_multipart(bucket, key, upload_id,
                                     actor=actor)
            return 204, {}, b""
        return _err(405, "MethodNotAllowed")


def serve(frontend: S3Frontend, port: int = 0):
    """Threaded stdlib HTTP server; returns (server, port).  Call
    ``server.shutdown()`` + ``server.server_close()`` when done."""
    from ..common.http_serve import serve_frontend
    return serve_frontend(frontend.handle, port)


class SwiftFrontend:
    """Swift-dialect REST frontend (rgw_rest_swift.cc role): the same
    RGWLite core behind OpenStack-Swift paths.

    - ``GET /auth/v1.0`` with ``X-Auth-User: <uid>:swift`` and
      ``X-Auth-Key: <secret_key>`` answers ``X-Auth-Token`` (a
      stateless HMAC over the uid, so any frontend instance validates
      it) and ``X-Storage-Url`` (``/v1/AUTH_<uid>``).
    - ``/v1/AUTH_<uid>/<container>[/<object>]``: container PUT/GET
      (plain-text or ``format=json`` listings)/DELETE, object
      PUT/GET/HEAD/DELETE.  Swift names buckets "containers" and
      accounts map to rgw users (RGWSwift).
    """

    def __init__(self, rgw: RGWLite):
        self.rgw = rgw

    def _token_for(self, user: Dict) -> str:
        mac = hmac.new(user["secret_key"].encode(),
                       f"swift:{user['uid']}".encode(), hashlib.sha1)
        return f"AUTH_tk{mac.hexdigest()}"

    def _user_for_token(self, uid: str, token: str) -> Optional[Dict]:
        try:
            user = self.rgw.get_user(uid)
        except RGWError:
            return None
        if hmac.compare_digest(self._token_for(user), token or ""):
            return user
        return None

    def handle(self, method: str, path: str,
               headers: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, str], bytes]:
        headers = headers or {}
        query = query or {}
        if path.startswith("/auth/v1.0"):
            xuser = headers.get("X-Auth-User", "")
            uid = xuser.split(":", 1)[0]
            try:
                user = self.rgw.get_user(uid)
            except RGWError:
                return 401, {}, b"invalid user"
            if not hmac.compare_digest(
                    headers.get("X-Auth-Key", ""),
                    user["secret_key"]):
                return 401, {}, b"invalid key"
            return 204, {"X-Auth-Token": self._token_for(user),
                         "X-Storage-Url": f"/v1/AUTH_{uid}"}, b""
        if not path.startswith("/v1/AUTH_"):
            return 404, {}, b"not found"
        parts = path[len("/v1/AUTH_"):].split("/", 2)
        uid = parts[0]
        user = self._user_for_token(uid, headers.get("X-Auth-Token"))
        if user is None:
            return 401, {}, b"bad token"
        if user.get("suspended"):
            # same refusal as the S3 frontend (RGW_USER_SUSPENDED):
            # suspension covers EVERY frontend
            return 403, {}, b"account suspended"
        container = parts[1] if len(parts) > 1 and parts[1] else ""
        obj = parts[2] if len(parts) > 2 else ""
        try:
            if not container:
                if method == "GET":      # account listing
                    names = self.rgw.list_buckets(uid)
                    if not names:
                        return 204, {}, b""
                    return (200, {"Content-Type": "text/plain"},
                            ("\n".join(names) + "\n").encode())
                return 405, {}, b""
            if not obj:
                return self._container_op(method, user, container,
                                          query)
            return self._object_op(method, user, container, obj, body)
        except RGWError as e:
            status = {-2: 404, -17: 202, -39: 409,
                      -13: 403}.get(e.result, 500)
            return status, {}, str(e).encode()
        except ValueError as e:
            return 412, {}, str(e).encode()   # Swift's bad-param code
        except Exception as e:    # a handler thread must always reply
            return 500, {}, repr(e).encode()

    def _check_owner(self, user: Dict, container: str) -> None:
        if self.rgw.get_bucket(container)["owner"] != user["uid"]:
            raise RGWError("acl", -13, "forbidden")

    def _container_op(self, method, user, container, query):
        import json as _json
        if method == "PUT":
            try:
                self.rgw.create_bucket(user["uid"], container)
            except RGWError as e:
                if e.result != -17:
                    raise
                return 202, {}, b""      # existed: Swift says Accepted
            return 201, {}, b""
        if method == "DELETE":
            self._check_owner(user, container)
            self.rgw.delete_bucket(container)
            return 204, {}, b""
        if method == "HEAD":
            self._check_owner(user, container)
            stats = self.rgw.bucket_stats(container)
            return 204, {"X-Container-Object-Count":
                         str(stats["num_objects"])}, b""
        if method == "GET":
            self._check_owner(user, container)
            res = self.rgw.list_objects(
                container, prefix=query.get("prefix", ""),
                delimiter=query.get("delimiter", ""),
                marker=query.get("marker", ""),
                max_keys=int(query.get("limit", "10000")))
            if query.get("format") == "json":
                out = _json.dumps(
                    [{"name": e["name"], "bytes": e["size"],
                      "hash": e["etag"]} for e in res["contents"]] +
                    [{"subdir": p} for p in res["common_prefixes"]])
                return 200, {"Content-Type": "application/json"}, \
                    out.encode()
            names = [e["name"] for e in res["contents"]] + \
                res["common_prefixes"]
            return 200, {"Content-Type": "text/plain"}, \
                ("\n".join(names) + ("\n" if names else "")).encode()
        return 405, {}, b""

    def _object_op(self, method, user, container, obj, body):
        if method == "PUT":
            self._check_owner(user, container)
            meta = self.rgw.put_object(container, obj, body)
            return 201, {"Etag": meta["etag"]}, b""
        if method == "GET":
            self._check_owner(user, container)
            data = self.rgw.get_object(container, obj)
            meta = self.rgw.head_object(container, obj)
            return 200, {"Content-Type": meta["content_type"],
                         "Etag": meta["etag"]}, data
        if method == "HEAD":
            self._check_owner(user, container)
            meta = self.rgw.head_object(container, obj)
            return 200, {"Content-Length": str(meta["size"]),
                         "Etag": meta["etag"]}, b""
        if method == "DELETE":
            self._check_owner(user, container)
            self.rgw.delete_object(container, obj)
            return 204, {}, b""
        return 405, {}, b""
