"""S3 path-style HTTP frontend for rgw-lite (the civetweb/beast
frontend role, src/rgw/rgw_main.cc + rgw_rest_s3.cc at lite scale).

Speaks the S3 subset the gateway implements over path-style URLs
(``/bucket``, ``/bucket/key``): bucket PUT/GET/DELETE, object
PUT/GET/HEAD/DELETE, ListObjectsV1 query args (prefix/marker/
delimiter/max-keys) with XML responses, and AWS signature v2-style
auth: ``Authorization: AWS <access_key>:<sig>`` where sig =
base64(HMAC-SHA1(secret, method\\n\\n\\ndate\\npath)) — the reference's
v2 string-to-sign with the optional header sections empty.

``handle()`` is a pure request->response function (testable without
sockets); ``serve()`` wraps it in a threaded stdlib HTTPServer.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
from typing import Dict, Optional, Tuple
from xml.sax.saxutils import escape

from .gateway import RGWError, RGWLite


def _sign_v2(secret: str, method: str, date: str, path: str) -> str:
    sts = f"{method}\n\n\n{date}\n{path}"
    mac = hmac.new(secret.encode(), sts.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def _err(status: int, code: str, message: str = "") -> Tuple[int, Dict,
                                                             bytes]:
    body = (f'<?xml version="1.0"?><Error><Code>{escape(code)}</Code>'
            f"<Message>{escape(message or code)}</Message></Error>")
    return status, {"Content-Type": "application/xml"}, body.encode()


_ERRNO_TO_S3 = {
    -2: (404, "NoSuchKey"),
    -13: (403, "AccessDenied"),
    -17: (409, "BucketAlreadyExists"),
    -39: (409, "BucketNotEmpty"),
}


class S3Frontend:
    def __init__(self, rgw: RGWLite):
        self.rgw = rgw

    # ---- auth --------------------------------------------------------------
    def _authenticate(self, method: str, path: str,
                      headers: Dict[str, str]) -> Optional[Dict]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("AWS ") or ":" not in auth[4:]:
            return None
        access_key, sig = auth[4:].split(":", 1)
        user = self.rgw.user_by_access_key(access_key)
        if user is None:
            return None
        want = _sign_v2(user["secret_key"], method,
                        headers.get("Date", ""), path)
        return user if hmac.compare_digest(want, sig) else None

    # ---- request router ----------------------------------------------------
    def handle(self, method: str, path: str,
               headers: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, str], bytes]:
        headers = headers or {}
        query = query or {}
        user = self._authenticate(method, path.split("?")[0], headers)
        if user is None:
            return _err(403, "AccessDenied", "bad or missing signature")
        parts = path.split("?")[0].strip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        try:
            if not bucket:
                return self._list_buckets(user)
            if not key:
                return self._bucket_op(method, user, bucket, query)
            return self._object_op(method, user, bucket, key, body)
        except RGWError as e:
            status, code = _ERRNO_TO_S3.get(e.result,
                                            (500, "InternalError"))
            return _err(status, code, str(e))
        except ValueError as e:
            return _err(400, "InvalidArgument", str(e))
        except Exception as e:      # a handler thread must always reply
            return _err(500, "InternalError", repr(e))

    def _owner_check(self, user: Dict, bucket: str) -> None:
        if self.rgw.get_bucket(bucket)["owner"] != user["uid"]:
            raise RGWError("acl", -13, "AccessDenied")

    def _list_buckets(self, user):
        names = "".join(f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                        for n in self.rgw.list_buckets(user["uid"]))
        xml = (f'<?xml version="1.0"?><ListAllMyBucketsResult>'
               f"<Buckets>{names}</Buckets></ListAllMyBucketsResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    def _bucket_op(self, method, user, bucket, query):
        if method == "PUT":
            self.rgw.create_bucket(user["uid"], bucket)
            return 200, {}, b""
        if method == "DELETE":
            self._owner_check(user, bucket)
            self.rgw.delete_bucket(bucket)
            return 204, {}, b""
        if method == "GET":
            # ACL-gated (bucket READ), not owner-gated: public-read
            # buckets list for any authenticated caller
            v2 = query.get("list-type") == "2"
            marker = (query.get("continuation-token")
                      or query.get("start-after", "")) if v2 \
                else query.get("marker", "")
            res = self.rgw.list_objects(
                bucket, prefix=query.get("prefix", ""),
                delimiter=query.get("delimiter", ""),
                marker=marker,
                max_keys=int(query.get("max-keys", "1000")),
                actor=user["uid"])
            items = "".join(
                f"<Contents><Key>{escape(e['name'])}</Key>"
                f"<Size>{e['size']}</Size>"
                f'<ETag>"{e["etag"]}"</ETag></Contents>'
                for e in res["contents"])
            cps = "".join(
                f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
                f"</CommonPrefixes>"
                for p in res["common_prefixes"])
            extra = ""
            if v2:
                count = len(res["contents"]) + len(res["common_prefixes"])
                extra = f"<KeyCount>{count}</KeyCount>"
                if res["truncated"] and res.get("next_marker"):
                    tok = escape(res["next_marker"])
                    extra += (f"<NextContinuationToken>{tok}"
                              f"</NextContinuationToken>")
            xml = (f'<?xml version="1.0"?><ListBucketResult>'
                   f"<Name>{escape(bucket)}</Name>"
                   f"<IsTruncated>{str(res['truncated']).lower()}"
                   f"</IsTruncated>{extra}{items}{cps}"
                   f"</ListBucketResult>")
            return 200, {"Content-Type": "application/xml"}, xml.encode()
        return _err(405, "MethodNotAllowed")

    def _object_op(self, method, user, bucket, key, body):
        # policy decisions live in the gateway's ACL engine (canned
        # ACLs + grants, rgw_acl_s3.cc role): the frontend just
        # supplies the authenticated actor
        actor = user["uid"]
        if method == "PUT":
            meta = self.rgw.put_object(bucket, key, body, actor=actor)
            return 200, {"ETag": f'"{meta["etag"]}"'}, b""
        if method == "GET":
            data = self.rgw.get_object(bucket, key, actor=actor)
            meta = self.rgw.head_object(bucket, key)
            return 200, {"Content-Type": meta["content_type"],
                         "ETag": f'"{meta["etag"]}"'}, data
        if method == "HEAD":
            meta = self.rgw.head_object(bucket, key, actor=actor)
            return 200, {"Content-Length": str(meta["size"]),
                         "ETag": f'"{meta["etag"]}"'}, b""
        if method == "DELETE":
            self.rgw.delete_object(bucket, key, actor=actor)
            return 204, {}, b""
        return _err(405, "MethodNotAllowed")


def serve(frontend: S3Frontend, port: int = 0):
    """Threaded stdlib HTTP server; returns (server, port).  Call
    ``server.shutdown()`` when done."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qsl, urlparse

    # the in-process rados client/fabric is not thread-safe; requests
    # from concurrent connections serialize here (the reference runs a
    # real thread pool over a thread-safe RGWRados)
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def _run(self, method):
            u = urlparse(self.path)
            ln = int(self.headers.get("Content-Length", "0") or 0)
            body = self.rfile.read(ln) if ln else b""
            with lock:
                status, hdrs, out = frontend.handle(
                    method, u.path, dict(self.headers), body,
                    dict(parse_qsl(u.query)))
            self.send_response(status)
            for k, v in hdrs.items():
                self.send_header(k, v)
            if "Content-Length" not in hdrs:
                self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            if method != "HEAD":
                self.wfile.write(out)

        def do_GET(self):
            self._run("GET")

        def do_PUT(self):
            self._run("PUT")

        def do_DELETE(self):
            self._run("DELETE")

        def do_HEAD(self):
            self._run("HEAD")

        def log_message(self, *a):      # keep test output clean
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]


class SwiftFrontend:
    """Swift-dialect REST frontend (rgw_rest_swift.cc role): the same
    RGWLite core behind OpenStack-Swift paths.

    - ``GET /auth/v1.0`` with ``X-Auth-User: <uid>:swift`` and
      ``X-Auth-Key: <secret_key>`` answers ``X-Auth-Token`` (a
      stateless HMAC over the uid, so any frontend instance validates
      it) and ``X-Storage-Url`` (``/v1/AUTH_<uid>``).
    - ``/v1/AUTH_<uid>/<container>[/<object>]``: container PUT/GET
      (plain-text or ``format=json`` listings)/DELETE, object
      PUT/GET/HEAD/DELETE.  Swift names buckets "containers" and
      accounts map to rgw users (RGWSwift).
    """

    def __init__(self, rgw: RGWLite):
        self.rgw = rgw

    def _token_for(self, user: Dict) -> str:
        mac = hmac.new(user["secret_key"].encode(),
                       f"swift:{user['uid']}".encode(), hashlib.sha1)
        return f"AUTH_tk{mac.hexdigest()}"

    def _user_for_token(self, uid: str, token: str) -> Optional[Dict]:
        try:
            user = self.rgw.get_user(uid)
        except RGWError:
            return None
        if hmac.compare_digest(self._token_for(user), token or ""):
            return user
        return None

    def handle(self, method: str, path: str,
               headers: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, str], bytes]:
        headers = headers or {}
        query = query or {}
        if path.startswith("/auth/v1.0"):
            xuser = headers.get("X-Auth-User", "")
            uid = xuser.split(":", 1)[0]
            try:
                user = self.rgw.get_user(uid)
            except RGWError:
                return 401, {}, b"invalid user"
            if not hmac.compare_digest(
                    headers.get("X-Auth-Key", ""),
                    user["secret_key"]):
                return 401, {}, b"invalid key"
            return 204, {"X-Auth-Token": self._token_for(user),
                         "X-Storage-Url": f"/v1/AUTH_{uid}"}, b""
        if not path.startswith("/v1/AUTH_"):
            return 404, {}, b"not found"
        parts = path[len("/v1/AUTH_"):].split("/", 2)
        uid = parts[0]
        user = self._user_for_token(uid, headers.get("X-Auth-Token"))
        if user is None:
            return 401, {}, b"bad token"
        container = parts[1] if len(parts) > 1 and parts[1] else ""
        obj = parts[2] if len(parts) > 2 else ""
        try:
            if not container:
                if method == "GET":      # account listing
                    names = self.rgw.list_buckets(uid)
                    if not names:
                        return 204, {}, b""
                    return (200, {"Content-Type": "text/plain"},
                            ("\n".join(names) + "\n").encode())
                return 405, {}, b""
            if not obj:
                return self._container_op(method, user, container,
                                          query)
            return self._object_op(method, user, container, obj, body)
        except RGWError as e:
            status = {-2: 404, -17: 202, -39: 409,
                      -13: 403}.get(e.result, 500)
            return status, {}, str(e).encode()
        except ValueError as e:
            return 412, {}, str(e).encode()   # Swift's bad-param code
        except Exception as e:    # a handler thread must always reply
            return 500, {}, repr(e).encode()

    def _check_owner(self, user: Dict, container: str) -> None:
        if self.rgw.get_bucket(container)["owner"] != user["uid"]:
            raise RGWError("acl", -13, "forbidden")

    def _container_op(self, method, user, container, query):
        import json as _json
        if method == "PUT":
            try:
                self.rgw.create_bucket(user["uid"], container)
            except RGWError as e:
                if e.result != -17:
                    raise
                return 202, {}, b""      # existed: Swift says Accepted
            return 201, {}, b""
        if method == "DELETE":
            self._check_owner(user, container)
            self.rgw.delete_bucket(container)
            return 204, {}, b""
        if method == "HEAD":
            self._check_owner(user, container)
            stats = self.rgw.bucket_stats(container)
            return 204, {"X-Container-Object-Count":
                         str(stats["num_objects"])}, b""
        if method == "GET":
            self._check_owner(user, container)
            res = self.rgw.list_objects(
                container, prefix=query.get("prefix", ""),
                delimiter=query.get("delimiter", ""),
                marker=query.get("marker", ""),
                max_keys=int(query.get("limit", "10000")))
            if query.get("format") == "json":
                out = _json.dumps(
                    [{"name": e["name"], "bytes": e["size"],
                      "hash": e["etag"]} for e in res["contents"]] +
                    [{"subdir": p} for p in res["common_prefixes"]])
                return 200, {"Content-Type": "application/json"}, \
                    out.encode()
            names = [e["name"] for e in res["contents"]] + \
                res["common_prefixes"]
            return 200, {"Content-Type": "text/plain"}, \
                ("\n".join(names) + ("\n" if names else "")).encode()
        return 405, {}, b""

    def _object_op(self, method, user, container, obj, body):
        if method == "PUT":
            self._check_owner(user, container)
            meta = self.rgw.put_object(container, obj, body)
            return 201, {"Etag": meta["etag"]}, b""
        if method == "GET":
            self._check_owner(user, container)
            data = self.rgw.get_object(container, obj)
            meta = self.rgw.head_object(container, obj)
            return 200, {"Content-Type": meta["content_type"],
                         "Etag": meta["etag"]}, data
        if method == "HEAD":
            self._check_owner(user, container)
            meta = self.rgw.head_object(container, obj)
            return 200, {"Content-Length": str(meta["size"]),
                         "Etag": meta["etag"]}, b""
        if method == "DELETE":
            self._check_owner(user, container)
            self.rgw.delete_object(container, obj)
            return 204, {}, b""
        return 405, {}, b""
