"""S3 path-style HTTP frontend for rgw-lite (the civetweb/beast
frontend role, src/rgw/rgw_main.cc + rgw_rest_s3.cc at lite scale).

Speaks the S3 subset the gateway implements over path-style URLs
(``/bucket``, ``/bucket/key``): bucket PUT/GET/DELETE, object
PUT/GET/HEAD/DELETE, ListObjectsV1 query args (prefix/marker/
delimiter/max-keys) with XML responses, and AWS signature v2-style
auth: ``Authorization: AWS <access_key>:<sig>`` where sig =
base64(HMAC-SHA1(secret, method\\n\\n\\ndate\\npath)) — the reference's
v2 string-to-sign with the optional header sections empty.

``handle()`` is a pure request->response function (testable without
sockets); ``serve()`` wraps it in a threaded stdlib HTTPServer.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
from typing import Dict, Optional, Tuple
from xml.sax.saxutils import escape

from .gateway import RGWError, RGWLite


def _sign_v2(secret: str, method: str, date: str, path: str) -> str:
    sts = f"{method}\n\n\n{date}\n{path}"
    mac = hmac.new(secret.encode(), sts.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


def _err(status: int, code: str, message: str = "") -> Tuple[int, Dict,
                                                             bytes]:
    body = (f'<?xml version="1.0"?><Error><Code>{escape(code)}</Code>'
            f"<Message>{escape(message or code)}</Message></Error>")
    return status, {"Content-Type": "application/xml"}, body.encode()


_ERRNO_TO_S3 = {
    -2: (404, "NoSuchKey"),
    -13: (403, "AccessDenied"),
    -17: (409, "BucketAlreadyExists"),
    -39: (409, "BucketNotEmpty"),
}


class S3Frontend:
    def __init__(self, rgw: RGWLite):
        self.rgw = rgw

    # ---- auth --------------------------------------------------------------
    def _authenticate(self, method: str, path: str,
                      headers: Dict[str, str]) -> Optional[Dict]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("AWS ") or ":" not in auth[4:]:
            return None
        access_key, sig = auth[4:].split(":", 1)
        user = self.rgw.user_by_access_key(access_key)
        if user is None:
            return None
        want = _sign_v2(user["secret_key"], method,
                        headers.get("Date", ""), path)
        return user if hmac.compare_digest(want, sig) else None

    # ---- request router ----------------------------------------------------
    def handle(self, method: str, path: str,
               headers: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, str], bytes]:
        headers = headers or {}
        query = query or {}
        user = self._authenticate(method, path.split("?")[0], headers)
        if user is None:
            return _err(403, "AccessDenied", "bad or missing signature")
        parts = path.split("?")[0].strip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        try:
            if not bucket:
                return self._list_buckets(user)
            if not key:
                return self._bucket_op(method, user, bucket, query)
            return self._object_op(method, user, bucket, key, body)
        except RGWError as e:
            status, code = _ERRNO_TO_S3.get(e.result,
                                            (500, "InternalError"))
            return _err(status, code, str(e))
        except ValueError as e:
            return _err(400, "InvalidArgument", str(e))
        except Exception as e:      # a handler thread must always reply
            return _err(500, "InternalError", repr(e))

    def _owner_check(self, user: Dict, bucket: str) -> None:
        if self.rgw.get_bucket(bucket)["owner"] != user["uid"]:
            raise RGWError("acl", -13, "AccessDenied")

    def _list_buckets(self, user):
        names = "".join(f"<Bucket><Name>{escape(n)}</Name></Bucket>"
                        for n in self.rgw.list_buckets(user["uid"]))
        xml = (f'<?xml version="1.0"?><ListAllMyBucketsResult>'
               f"<Buckets>{names}</Buckets></ListAllMyBucketsResult>")
        return 200, {"Content-Type": "application/xml"}, xml.encode()

    def _bucket_op(self, method, user, bucket, query):
        if method == "PUT":
            self.rgw.create_bucket(user["uid"], bucket)
            return 200, {}, b""
        if method == "DELETE":
            self._owner_check(user, bucket)
            self.rgw.delete_bucket(bucket)
            return 204, {}, b""
        if method == "GET":
            self._owner_check(user, bucket)
            v2 = query.get("list-type") == "2"
            marker = (query.get("continuation-token")
                      or query.get("start-after", "")) if v2 \
                else query.get("marker", "")
            res = self.rgw.list_objects(
                bucket, prefix=query.get("prefix", ""),
                delimiter=query.get("delimiter", ""),
                marker=marker,
                max_keys=int(query.get("max-keys", "1000")))
            items = "".join(
                f"<Contents><Key>{escape(e['name'])}</Key>"
                f"<Size>{e['size']}</Size>"
                f'<ETag>"{e["etag"]}"</ETag></Contents>'
                for e in res["contents"])
            cps = "".join(
                f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
                f"</CommonPrefixes>"
                for p in res["common_prefixes"])
            extra = ""
            if v2:
                count = len(res["contents"]) + len(res["common_prefixes"])
                extra = f"<KeyCount>{count}</KeyCount>"
                if res["truncated"] and res.get("next_marker"):
                    tok = escape(res["next_marker"])
                    extra += (f"<NextContinuationToken>{tok}"
                              f"</NextContinuationToken>")
            xml = (f'<?xml version="1.0"?><ListBucketResult>'
                   f"<Name>{escape(bucket)}</Name>"
                   f"<IsTruncated>{str(res['truncated']).lower()}"
                   f"</IsTruncated>{extra}{items}{cps}"
                   f"</ListBucketResult>")
            return 200, {"Content-Type": "application/xml"}, xml.encode()
        return _err(405, "MethodNotAllowed")

    def _object_op(self, method, user, bucket, key, body):
        if method == "PUT":
            self._owner_check(user, bucket)
            meta = self.rgw.put_object(bucket, key, body)
            return 200, {"ETag": f'"{meta["etag"]}"'}, b""
        if method == "GET":
            self._owner_check(user, bucket)
            data = self.rgw.get_object(bucket, key)
            meta = self.rgw.head_object(bucket, key)
            return 200, {"Content-Type": meta["content_type"],
                         "ETag": f'"{meta["etag"]}"'}, data
        if method == "HEAD":
            self._owner_check(user, bucket)
            meta = self.rgw.head_object(bucket, key)
            return 200, {"Content-Length": str(meta["size"]),
                         "ETag": f'"{meta["etag"]}"'}, b""
        if method == "DELETE":
            self._owner_check(user, bucket)
            self.rgw.delete_object(bucket, key)
            return 204, {}, b""
        return _err(405, "MethodNotAllowed")


def serve(frontend: S3Frontend, port: int = 0):
    """Threaded stdlib HTTP server; returns (server, port).  Call
    ``server.shutdown()`` when done."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qsl, urlparse

    # the in-process rados client/fabric is not thread-safe; requests
    # from concurrent connections serialize here (the reference runs a
    # real thread pool over a thread-safe RGWRados)
    lock = threading.Lock()

    class Handler(BaseHTTPRequestHandler):
        def _run(self, method):
            u = urlparse(self.path)
            ln = int(self.headers.get("Content-Length", "0") or 0)
            body = self.rfile.read(ln) if ln else b""
            with lock:
                status, hdrs, out = frontend.handle(
                    method, u.path, dict(self.headers), body,
                    dict(parse_qsl(u.query)))
            self.send_response(status)
            for k, v in hdrs.items():
                self.send_header(k, v)
            if "Content-Length" not in hdrs:
                self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            if method != "HEAD":
                self.wfile.write(out)

        def do_GET(self):
            self._run("GET")

        def do_PUT(self):
            self._run("PUT")

        def do_DELETE(self):
            self._run("DELETE")

        def do_HEAD(self):
            self._run("HEAD")

        def log_message(self, *a):      # keep test output clean
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, srv.server_address[1]
