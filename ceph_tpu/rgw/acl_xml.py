"""S3 AccessControlPolicy XML grammar (rgw_acl_s3.cc role).

Emits and parses the reference's ACL XML shape
(``/root/reference/src/rgw/rgw_acl_s3.cc``):

- ``RGWAccessControlPolicy_S3::to_xml`` (rgw_acl_s3.cc:436-443):
  ``<AccessControlPolicy xmlns=NS><Owner>..</Owner>
  <AccessControlList>..</AccessControlList></AccessControlPolicy>``
- ``ACLGrant_S3::to_xml`` (rgw_acl_s3.cc:210-244): ``<Grant><Grantee
  xmlns:xsi=.. xsi:type="CanonicalUser|Group">..</Grantee>
  <Permission>..</Permission></Grant>`` with CanonicalUser carrying
  ``<ID>``/``<DisplayName>`` and Group a ``<URI>``.
- group URIs (rgw_acl_s3.cc:18-19): AllUsers / AuthenticatedUsers.

The gateway's internal grant form is ``{"grantee": uid|"*"|"auth",
"permission": PERM}`` (gateway.py ``_grants_allow``); this module is
the bidirectional bridge between that and the wire XML.
"""
from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Tuple
from xml.sax.saxutils import escape

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
XSI = "http://www.w3.org/2001/XMLSchema-instance"
URI_ALL_USERS = "http://acs.amazonaws.com/groups/global/AllUsers"
URI_AUTH_USERS = \
    "http://acs.amazonaws.com/groups/global/AuthenticatedUsers"

PERMISSIONS = ("READ", "WRITE", "READ_ACP", "WRITE_ACP",
               "FULL_CONTROL")


def _grant_xml(grantee: str, display: Optional[str],
               permission: str) -> str:
    if grantee == "*":
        gt, inner = "Group", f"<URI>{URI_ALL_USERS}</URI>"
    elif grantee == "auth":
        gt, inner = "Group", f"<URI>{URI_AUTH_USERS}</URI>"
    else:
        gt = "CanonicalUser"
        inner = f"<ID>{escape(grantee)}</ID>"
        if display:
            inner += f"<DisplayName>{escape(display)}</DisplayName>"
    return (f'<Grant><Grantee xmlns:xsi="{XSI}" xsi:type="{gt}">'
            f"{inner}</Grantee>"
            f"<Permission>{permission}</Permission></Grant>")


def policy_to_xml(owner: Optional[str], grants: List[Dict],
                  display_names: Optional[Dict[str, str]] = None
                  ) -> str:
    """Serialize an owner + gateway-form grant list.  Like the
    reference's create_canned, the owner's implicit FULL_CONTROL is
    materialized as the first grant (S3 clients expect to see it)."""
    display_names = display_names or {}
    out = [f'<AccessControlPolicy xmlns="{XMLNS}">']
    if owner:
        out.append(f"<Owner><ID>{escape(owner)}</ID>")
        dn = display_names.get(owner)
        if dn:
            out.append(f"<DisplayName>{escape(dn)}</DisplayName>")
        out.append("</Owner>")
    out.append("<AccessControlList>")
    if owner:
        out.append(_grant_xml(owner, display_names.get(owner),
                              "FULL_CONTROL"))
    for g in grants:
        out.append(_grant_xml(g["grantee"],
                              display_names.get(g["grantee"]),
                              g["permission"]))
    out.append("</AccessControlList></AccessControlPolicy>")
    return "".join(out)


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def _find(el, name):
    for child in el:
        if _local(child.tag) == name:
            return child
    return None


def policy_from_xml(data: bytes) -> Tuple[Optional[str], List[Dict]]:
    """Parse policy XML back to (owner_id, gateway-form grants).

    The owner's own FULL_CONTROL grant (which policy_to_xml
    materializes) is folded back into the implicit-owner form so a
    GET->PUT round trip is stable.  Unknown grantee types (e.g.
    AmazonCustomerByEmail) and permissions raise ValueError, the
    reference's -EINVAL path."""
    try:
        root = ET.fromstring(data)
    except ET.ParseError as e:
        raise ValueError(f"MalformedACLError: {e}")
    if _local(root.tag) != "AccessControlPolicy":
        raise ValueError("MalformedACLError: not an "
                         "AccessControlPolicy")
    owner = None
    owner_el = _find(root, "Owner")
    if owner_el is not None:
        id_el = _find(owner_el, "ID")
        if id_el is not None and id_el.text:
            owner = id_el.text
    grants: List[Dict] = []
    acl_el = _find(root, "AccessControlList")
    for grant in (acl_el if acl_el is not None else ()):
        if _local(grant.tag) != "Grant":
            continue
        grantee_el = _find(grant, "Grantee")
        perm_el = _find(grant, "Permission")
        if grantee_el is None or perm_el is None:
            raise ValueError("MalformedACLError: incomplete Grant")
        perm = (perm_el.text or "").strip().upper()
        if perm not in PERMISSIONS:
            raise ValueError(f"MalformedACLError: bad permission "
                             f"{perm!r}")
        gtype = (grantee_el.get(f"{{{XSI}}}type")
                 or grantee_el.get("type") or "")
        if gtype == "Group":
            uri_el = _find(grantee_el, "URI")
            uri = (uri_el.text or "") if uri_el is not None else ""
            if uri == URI_ALL_USERS:
                who = "*"
            elif uri == URI_AUTH_USERS:
                who = "auth"
            else:
                raise ValueError(f"MalformedACLError: unknown group "
                                 f"URI {uri!r}")
        elif gtype == "CanonicalUser":
            id_el = _find(grantee_el, "ID")
            if id_el is None or not id_el.text:
                raise ValueError("MalformedACLError: CanonicalUser "
                                 "without ID")
            who = id_el.text
        else:
            raise ValueError(f"MalformedACLError: unsupported grantee "
                             f"type {gtype!r}")
        if owner is not None and who == owner \
                and perm == "FULL_CONTROL":
            continue            # implicit-owner fold-back
        grants.append({"grantee": who, "permission": perm})
    return owner, grants
