"""rgw-lite: S3-shaped object gateway over rados (src/rgw, 122k LoC in
the reference, at lite scale).

Storage layout mirrors the reference's: user and bucket-entrypoint
records in a metadata pool (``user.<uid>``, ``bucket.<name>``), one
index object per bucket (``.dir.<bucket_id>``) mutated through the
two-phase cls_rgw protocol, and object payloads chunked into the data
pool under ``<bucket_id>_<name>[.chunk.N]`` with a manifest in the
index entry (RGWObjManifest role).  Multipart uploads stage parts
under a ``_multipart_`` namespace and stitch a manifest at complete,
like RGWMultipart*.

S3 object versioning (version stacks with delete markers, suspended
mode, GET/DELETE ?versionId), bucket lifecycle (expiration +
noncurrent-version expiration, the `lc process` pass) and S3 ACLs
(canned ACLs + grant lists with owner/grantee/permission checks) are
implemented below at the same lite scale (rgw_rados versioned ops,
rgw_lc.cc, rgw_acl_s3.cc roles).  Scope-outs vs the reference: the
ACL XML wire grammar (grants are structured dicts) and the civetweb
frontend (the ``http`` module provides a threaded stdlib server
speaking the S3 path-style subset with AWS v2-style HMAC auth).
"""
from __future__ import annotations

import hashlib
import json
import secrets
import time
from typing import Dict, List, Optional

from ..client.rados import RadosClient
from . import cls_rgw  # noqa: F401

CHUNK = 4 << 20                   # rgw_max_chunk_size default (4 MiB)


class RGWError(IOError):
    def __init__(self, api: str, result: int, reason: str = ""):
        super().__init__(f"rgw {api}: {result} {reason}".rstrip())
        self.result = result


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _absent(e: IOError) -> bool:
    return getattr(e, "errno", None) == 2


class RGWLite:
    """The gateway core (RGWRados role): all state in rados."""

    def __init__(self, client: RadosClient, meta_pool: str,
                 data_pool: str):
        self.client = client
        self.mpool = meta_pool
        self.dpool = data_pool

    # ---- cls / meta helpers ------------------------------------------------
    def _exec(self, pool: str, oid: str, method: str, payload=None
              ) -> bytes:
        ret, out = self.client.exec(pool, oid, "rgw", method,
                                    _j(payload or {}))
        if ret < 0:
            raise RGWError(method, ret)
        return out

    def _meta_get(self, oid: str) -> Optional[Dict]:
        try:
            return json.loads(self.client.read(self.mpool, oid))
        except IOError as e:
            if _absent(e):
                return None
            raise

    # ---- users (RGWUser / radosgw-admin user create) -----------------------
    def create_user(self, uid: str, display_name: str = "") -> Dict:
        if self._meta_get(f"user.{uid}") is not None:
            raise RGWError("create_user", -17)
        user = {"uid": uid, "display_name": display_name or uid,
                "access_key": secrets.token_hex(10),
                "secret_key": secrets.token_hex(20),
                "buckets": []}
        self._save_user(user)
        self._meta_index(f"user.{uid}", True)
        return user

    def get_user(self, uid: str) -> Dict:
        u = self._meta_get(f"user.{uid}")
        if u is None:
            raise RGWError("get_user", -2)
        return u

    def delete_user(self, uid: str) -> None:
        """Remove a user (radosgw-admin user rm): refused while the
        user still owns buckets."""
        u = self.get_user(uid)
        if u["buckets"]:
            raise RGWError("delete_user", -39, "user owns buckets")
        self.client.remove(self.mpool, f"user.{uid}")
        self._meta_index(f"user.{uid}", False)

    def list_users(self) -> List[str]:
        return [oid[len("user."):] for oid in self._meta_list("user.")]

    def _save_user(self, user: Dict) -> None:
        self.client.write_full(self.mpool, f"user.{user['uid']}",
                               _j(user))

    def _save_bucket(self, bucket: Dict) -> None:
        self.client.write_full(self.mpool,
                               f"bucket.{bucket['name']}", _j(bucket))

    def modify_user(self, uid: str, display_name: Optional[str] = None,
                    suspended: Optional[bool] = None,
                    max_buckets: Optional[int] = None) -> Dict:
        """radosgw-admin user modify / suspend / enable: a suspended
        user's requests are refused at the frontends (the reference's
        RGW_USER_SUSPENDED check)."""
        u = self.get_user(uid)
        if display_name is not None:
            u["display_name"] = display_name
        if suspended is not None:
            u["suspended"] = bool(suspended)
        if max_buckets is not None:
            u["max_buckets"] = int(max_buckets)
        self._save_user(u)
        return u

    def user_add_key(self, uid: str) -> Dict:
        """radosgw-admin key create: an ADDITIONAL key pair; every
        key authenticates the user (RGWUserInfo access_keys map)."""
        u = self.get_user(uid)
        key = {"access_key": secrets.token_hex(10),
               "secret_key": secrets.token_hex(20)}
        u.setdefault("keys", []).append(key)
        self._save_user(u)
        return key

    def user_rm_key(self, uid: str, access_key: str) -> None:
        """radosgw-admin key rm (the primary key is not removable
        here — the reference also refuses removing the last key)."""
        u = self.get_user(uid)
        keys = u.get("keys", [])
        kept = [k for k in keys if k["access_key"] != access_key]
        if len(kept) == len(keys):
            raise RGWError("user_rm_key", -2, "no such key")
        u["keys"] = kept
        self._save_user(u)

    def user_caps(self, uid: str, add: Optional[str] = None,
                  rm: Optional[str] = None) -> Dict[str, str]:
        """radosgw-admin caps add/rm: admin capability strings like
        'users=read,write' (RGWUserCaps grammar)."""
        u = self.get_user(uid)
        caps = dict(u.get("caps", {}))
        for spec, is_add in ((add, True), (rm, False)):
            if not spec:
                continue
            for part in spec.split(";"):
                kind, _, perms = part.strip().partition("=")
                if not kind:
                    continue
                if is_add:
                    caps[kind] = perms or "read"
                elif not perms:
                    caps.pop(kind, None)       # rm the whole kind
                else:
                    # subtract only the listed perms
                    # (RGWUserCaps::remove)
                    have = [p for p in caps.get(kind, "").split(",")
                            if p]
                    left = [p for p in have
                            if p not in perms.split(",")]
                    if left:
                        caps[kind] = ",".join(left)
                    else:
                        caps.pop(kind, None)
        u["caps"] = caps
        self._save_user(u)
        return caps

    def set_user_quota(self, uid: str,
                       max_size: Optional[int] = None,
                       max_objects: Optional[int] = None,
                       enabled: Optional[bool] = None) -> Dict:
        """radosgw-admin quota set/enable/disable --quota-scope=user:
        checked on every put against the user's aggregate usage."""
        u = self.get_user(uid)
        q = dict(u.get("quota", {}))
        if max_size is not None:
            q["max_size"] = int(max_size)
        if max_objects is not None:
            q["max_objects"] = int(max_objects)
        if enabled is not None:
            q["enabled"] = bool(enabled)
        u["quota"] = q
        self._save_user(u)
        return q

    def user_stats(self, uid: str) -> Dict:
        """radosgw-admin user stats: aggregate usage across every
        owned bucket (the quota subsystem's accounting)."""
        u = self.get_user(uid)
        size = objects = 0
        for b in u.get("buckets", []):
            try:
                st = self.bucket_stats(b)
            except RGWError:
                continue
            size += int(st.get("size_bytes", 0))
            objects += int(st.get("num_objects", 0))
        return {"uid": uid, "size": size, "num_objects": objects}

    def _check_user_quota(self, uid: Optional[str],
                          incoming: int) -> None:
        if not uid:
            return
        try:
            u = self.get_user(uid)
        except RGWError:
            return
        q = u.get("quota", {})
        if not q.get("enabled"):
            return
        # aggregate walk with early exit (the reference amortizes this
        # with RGWQuotaCache; at lite scale the walk stops as soon as
        # either limit is provably exceeded)
        max_size = q.get("max_size", 0)
        max_objects = q.get("max_objects", 0)
        size, objects = incoming, 1
        for b in u.get("buckets", []):
            try:
                st = self.bucket_stats(b)
            except RGWError:
                continue
            size += int(st.get("size_bytes", 0))
            objects += int(st.get("num_objects", 0))
            if (max_size > 0 and size > max_size) or \
                    (max_objects > 0 and objects > max_objects):
                raise RGWError("put_object", -122, "QuotaExceeded")
        if (max_size > 0 and size > max_size) or \
                (max_objects > 0 and objects > max_objects):
            raise RGWError("put_object", -122, "QuotaExceeded")

    def link_bucket(self, bucket: str, uid: str) -> None:
        """radosgw-admin bucket link: move ownership to *uid*."""
        b = self.get_bucket(bucket)
        new_owner = self.get_user(uid)
        old = b.get("owner")
        if old == uid:
            return
        mb = int(new_owner.get("max_buckets", 0) or 0)
        if mb > 0 and len(new_owner.get("buckets", [])) >= mb:
            raise RGWError("link_bucket", -24, "TooManyBuckets")
        if old:
            try:
                ou = self.get_user(old)
                ou["buckets"] = [x for x in ou["buckets"]
                                 if x != bucket]
                self._save_user(ou)
            except RGWError:
                pass
        b["owner"] = uid
        self._save_bucket(b)
        if bucket not in new_owner["buckets"]:
            new_owner["buckets"].append(bucket)
            self._save_user(new_owner)

    def unlink_bucket(self, bucket: str, uid: str) -> None:
        """radosgw-admin bucket unlink: detach from the user (the
        bucket keeps existing, ownerless)."""
        b = self.get_bucket(bucket)
        if b.get("owner") != uid:
            raise RGWError("unlink_bucket", -22,
                           "bucket not linked to that user")
        u = self.get_user(uid)
        u["buckets"] = [x for x in u["buckets"] if x != bucket]
        self._save_user(u)
        b["owner"] = ""
        self._save_bucket(b)

    def bucket_stats(self, bucket: str) -> Dict:
        """Bucket entry + index stats (radosgw-admin bucket stats)."""
        b = self.get_bucket(bucket)
        stats = json.loads(self._exec(
            self.mpool, self._index_oid(b["id"]), "bucket_stats"))
        return {**b, **stats}

    def user_by_access_key(self, access_key: str) -> Optional[Dict]:
        # lite linear scan (the reference keeps a key->uid index object)
        for oid in self._meta_list("user."):
            u = self._meta_get(oid)
            if u is None:
                continue
            if u["access_key"] == access_key or any(
                    k["access_key"] == access_key
                    for k in u.get("keys", [])):
                return u
        return None

    def secret_for_key(self, user: Dict, access_key: str) -> str:
        """The secret matching *access_key* (primary or additional)."""
        if user["access_key"] == access_key:
            return user["secret_key"]
        for k in user.get("keys", []):
            if k["access_key"] == access_key:
                return k["secret_key"]
        raise RGWError("secret_for_key", -2, "no such key")

    def _meta_list(self, prefix: str) -> List[str]:
        try:
            om = self.client.omap_get(self.mpool, "rgw_meta_index")
        except IOError as e:
            if not _absent(e):
                raise
            om = {}
        return sorted(k for k in om if k.startswith(prefix))

    def _meta_index(self, key: str, add: bool) -> None:
        if add:
            self.client.omap_set(self.mpool, "rgw_meta_index",
                                 {key: b"1"})
        else:
            self.client.omap_rm_keys(self.mpool, "rgw_meta_index",
                                     [key])

    # ---- buckets -----------------------------------------------------------
    def _index_oid(self, bucket_id: str) -> str:
        return f".dir.{bucket_id}"

    def create_bucket(self, uid: str, name: str) -> Dict:
        user = self.get_user(uid)
        mb = int(user.get("max_buckets", 0) or 0)
        if mb > 0 and len(user.get("buckets", [])) >= mb:
            raise RGWError("create_bucket", -24, "TooManyBuckets")
        if self._meta_get(f"bucket.{name}") is not None:
            raise RGWError("create_bucket", -17, "BucketAlreadyExists")
        bid = secrets.token_hex(8)
        bucket = {"name": name, "id": bid, "owner": uid,
                  "created": time.time()}
        self._save_bucket(bucket)
        self.client.create(self.mpool, self._index_oid(bid),
                           exclusive=False)
        user["buckets"] = sorted(set(user["buckets"]) | {name})
        self._save_user(user)
        return bucket

    def get_bucket(self, name: str) -> Dict:
        b = self._meta_get(f"bucket.{name}")
        if b is None:
            raise RGWError("get_bucket", -2, "NoSuchBucket")
        return b

    def delete_bucket(self, name: str,
                      actor: Optional[str] = None) -> None:
        """RGWDeleteBucket::verify_permission checks bucket policy
        (rgw_op.cc:2828-2832), not raw ownership — a FULL_CONTROL/
        WRITE grantee may delete; actor None = admin bypass."""
        b = self.get_bucket(name)
        self._check_bucket_access(b, actor, "WRITE")
        stats = json.loads(self._exec(self.mpool,
                                      self._index_oid(b["id"]),
                                      "bucket_stats"))
        if stats["num_objects"]:
            raise RGWError("delete_bucket", -39, "BucketNotEmpty")
        self.client.remove(self.mpool, self._index_oid(b["id"]))
        self.client.remove(self.mpool, f"bucket.{name}")
        owner = self._meta_get(f"user.{b['owner']}")
        if owner:
            owner["buckets"] = [x for x in owner["buckets"] if x != name]
            self._save_user(owner)

    def list_buckets(self, uid: str) -> List[str]:
        return list(self.get_user(uid)["buckets"])

    # ---- objects -----------------------------------------------------------
    def _data_oid(self, bucket_id: str, name: str) -> str:
        # distinct o_/c_/mp_ namespaces: a key can never collide with
        # another key's chunk or multipart staging objects (the
        # reference's __shadow_ namespace escaping, rgw_obj::set_ns)
        return f"{bucket_id}_o_{name}"

    def _write_chunked(self, base_oid: str, data: bytes) -> List[str]:
        """Payload -> head object + .chunk.N tail objects (manifest)."""
        oids = []
        for i in range(0, max(len(data), 1), CHUNK):
            oid = base_oid if i == 0 else \
                base_oid.replace("_o_", "_c_", 1) + f".{i // CHUNK}"
            r = self.client.write_full(self.dpool, oid,
                                       data[i:i + CHUNK])
            if r < 0:
                raise RGWError("put_object", r)
            oids.append(oid)
        return oids

    def put_object(self, bucket: str, name: str, data: bytes,
                   content_type: str = "binary/octet-stream",
                   actor: Optional[str] = None) -> Dict:
        """Two-phase put: index prepare -> data chunks -> index
        complete.  A crash mid-way leaves a pending marker and garbage
        chunks, but never a listing entry for unreadable data.

        On a VERSIONED bucket every put pushes a new version onto the
        key's stack (suspended mode overwrites the 'null' slot), like
        RGWRados versioned object ops."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE")
        # storage quota charges the bucket OWNER (RGWQuotaHandler)
        self._check_user_quota(b.get("owner"), len(data))
        vstate = b.get("versioning")
        idx = self._index_oid(b["id"])
        cur = None
        try:
            cur = self._raw_entry(b, name)
        except RGWError:
            pass
        tag = secrets.token_hex(8)
        self._exec(self.mpool, idx, "bucket_prepare_op",
                   {"tag": tag, "name": name, "op": "put"})
        vid = None
        if vstate == "enabled":
            vid = secrets.token_hex(8)
        elif vstate == "suspended" or (cur is not None
                                       and "versions" in cur):
            vid = "null"
        try:
            chunks = self._write_chunked(
                self._vdata_oid(b["id"], name, vid), data)
        except Exception:
            self._exec(self.mpool, idx, "bucket_cancel_op", {"tag": tag})
            raise
        vrec = {"size": len(data),
                "etag": hashlib.md5(data).hexdigest(),
                "mtime": time.time(), "content_type": content_type,
                "chunks": len(chunks)}
        if actor is not None:
            vrec["owner"] = actor
        replaced: List[Dict] = []
        if vid is None:
            meta = vrec
        else:
            vrec["vid"] = vid
            stack = self._version_stack(b, name, cur)
            # replacing the null slot drops its old data — EXCEPT the
            # oids the new write just reused (a same-slot overwrite
            # shares the base oids; only a legacy-null's unsuffixed
            # objects and shrink-stranded tails actually go)
            replaced = [v for v in stack if v["vid"] == vid]
            stack = [vrec] + [v for v in stack if v["vid"] != vid]
            meta = {"versions": stack}
            meta.update(self._current_summary(stack))
        self._exec(self.mpool, idx, "bucket_complete_op",
                   {"tag": tag, "name": name, "op": "put", "meta": meta})
        if vid is not None:
            # replaced-null data goes only AFTER the index committed
            # (index-first: a crash never leaves a listed version
            # pointing at deleted chunks), minus oids the new write
            # reused
            new_oids = set(chunks)
            for old in replaced:
                for oid in self._vrec_chunk_oids(b, name, old):
                    if oid not in new_oids:
                        self.client.remove(self.dpool, oid)
        if vid is None and cur is not None:
            # a shrinking unversioned overwrite strands the old tail
            # chunks; collect them now (the reference defers to GC)
            for oid in self._chunk_oids(b["id"], name,
                                        cur.get("chunks", 0)
                                        )[len(chunks):]:
                self.client.remove(self.dpool, oid)
        return dict(vrec)

    # ---- versioning plumbing (RGWRados versioned objects) ------------
    def _vdata_oid(self, bid: str, name: str,
                   vid: Optional[str]) -> str:
        base = self._data_oid(bid, name)
        # '#v#' cannot appear in the o_/c_/mp_ escaping, so version
        # payloads never collide with another key's objects
        return base if vid is None else f"{base}#v#{vid}"

    def _vrec_chunk_oids(self, b: Dict, name: str, vrec: Dict):
        base = self._vdata_oid(b["id"], name,
                               None if vrec.get("legacy")
                               else vrec["vid"])
        return [base if i == 0 else
                base.replace("_o_", "_c_", 1) + f".{i}"
                for i in range(vrec.get("chunks", 0))]

    def _raw_entry(self, b: Dict, name: str) -> Dict:
        try:
            return json.loads(self._exec(
                self.mpool, self._index_oid(b["id"]),
                "bucket_get_entry", {"name": name}))
        except RGWError as e:
            if e.result == -2:
                raise RGWError("head_object", -2, "NoSuchKey")
            raise

    def _version_stack(self, b: Dict, name: str,
                       cur: Optional[Dict]) -> List[Dict]:
        """The key's existing versions, newest first; a pre-versioning
        entry is wrapped as the implicit 'null' version whose data
        lives at the unsuffixed oids (the reference's plain->versioned
        transition)."""
        if cur is None:
            return []
        if "versions" in cur:
            return list(cur["versions"])
        legacy = dict(cur)
        legacy.update({"vid": "null", "legacy": True})
        return [legacy]

    @staticmethod
    def _current_summary(stack: List[Dict]) -> Dict:
        """Denormalized current-version fields kept on the entry so
        unversioned readers (stats, listings) stay meaningful."""
        if not stack:
            return {}
        cur = stack[0]
        out = {"size": 0 if cur.get("delete_marker")
               else cur.get("size", 0),
               "etag": cur.get("etag", ""),
               "mtime": cur.get("mtime", 0.0),
               "content_type": cur.get("content_type",
                                       "binary/octet-stream"),
               "chunks": 0 if cur.get("delete_marker")
               else cur.get("chunks", 0),
               "delete_marker": bool(cur.get("delete_marker"))}
        # the uploader owns the object (RGWRados sets the attr owner
        # to the writing user): surface the current version's owner
        # at entry level so _check_object_access sees it
        if "owner" in cur:
            out["owner"] = cur["owner"]
        return out

    def put_bucket_versioning(self, bucket: str, status: str,
                              actor: Optional[str] = None) -> None:
        """status: 'enabled' | 'suspended' (S3 PutBucketVersioning;
        versioning can never return to the never-versioned state)."""
        if status not in ("enabled", "suspended"):
            raise RGWError("put_bucket_versioning", -22, "InvalidArg")
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE_ACP")
        b["versioning"] = status
        self._save_bucket(b)

    def get_bucket_versioning(self, bucket: str,
                              actor: Optional[str] = None
                              ) -> Optional[str]:
        # s3GetBucketVersioning maps to READ_ACP in the reference's
        # op_to_perm (rgw_iam_policy.h:102), not plain READ
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ_ACP")
        return b.get("versioning")

    def list_object_versions(self, bucket: str, prefix: str = "",
                             actor: Optional[str] = None
                             ) -> List[Dict]:
        """S3 ListObjectVersions: every version of every key, newest
        first per key, delete markers included."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ")
        out: List[Dict] = []
        marker = ""
        while True:
            raw = json.loads(self._exec(
                self.mpool, self._index_oid(b["id"]), "bucket_list",
                {"prefix": prefix, "marker": marker,
                 "max_keys": 1000}))
            for e in raw["entries"]:
                stack = self._version_stack(b, e["name"], e)
                if not stack:
                    stack = [dict(e, vid="null", legacy=True)]
                for i, v in enumerate(stack):
                    out.append({
                        "key": e["name"], "version_id": v["vid"]
                        if "vid" in v else "null",
                        "is_latest": i == 0,
                        "delete_marker": bool(v.get("delete_marker")),
                        "size": v.get("size", 0),
                        "etag": v.get("etag", ""),
                        "mtime": v.get("mtime", 0.0)})
            if not raw["truncated"] or not raw["entries"]:
                break
            marker = raw["entries"][-1]["name"]
        return out

    def get_object(self, bucket: str, name: str,
                   version_id: Optional[str] = None,
                   actor: Optional[str] = None) -> bytes:
        b = self.get_bucket(bucket)
        cur = self._raw_entry(b, name)
        self._check_object_access(b, cur, actor, "READ")
        if "versions" in cur or version_id is not None:
            stack = self._version_stack(b, name, cur)
            if version_id is None:
                if not stack or stack[0].get("delete_marker"):
                    raise RGWError("get_object", -2, "NoSuchKey")
                vrec = stack[0]
            else:
                vrec = next((v for v in stack
                             if v["vid"] == version_id), None)
                if vrec is None:
                    raise RGWError("get_object", -2, "NoSuchVersion")
                if vrec.get("delete_marker"):
                    raise RGWError("get_object", -2, "DeleteMarker")
            oids = self._vrec_chunk_oids(b, name, vrec)
        else:
            oids = self._chunk_oids(b["id"], name, cur["chunks"])
        return b"".join(self.client.read(self.dpool, oid)
                        for oid in oids)

    def _chunk_oids(self, bid: str, name: str, count: int):
        base = self._data_oid(bid, name)
        return [base if i == 0 else
                base.replace("_o_", "_c_", 1) + f".{i}"
                for i in range(count)]

    def head_object(self, bucket: str, name: str,
                    version_id: Optional[str] = None,
                    actor: Optional[str] = None) -> Dict:
        b = self.get_bucket(bucket)
        cur = self._raw_entry(b, name)
        self._check_object_access(b, cur, actor, "READ")
        if version_id is not None:
            vrec = next((v for v in
                         self._version_stack(b, name, cur)
                         if v["vid"] == version_id), None)
            if vrec is None:
                raise RGWError("head_object", -2, "NoSuchVersion")
            return dict(vrec)
        if cur.get("delete_marker"):
            raise RGWError("head_object", -2, "NoSuchKey")
        if "versions" in cur:
            # present the CURRENT version's fields (callers expect the
            # flat size/etag/content_type shape)
            return dict(cur["versions"][0])
        return cur

    def delete_object(self, bucket: str, name: str,
                      version_id: Optional[str] = None,
                      actor: Optional[str] = None) -> Dict:
        """Index first, data second: a crash mid-delete leaves orphan
        chunks (GC debt) but never a listing entry pointing at deleted
        data — the same invariant direction as put.

        Versioned semantics (S3 DeleteObject): without a version id a
        versioned bucket gets a DELETE MARKER pushed (no data removed);
        with one, that exact version is permanently removed — deleting
        the newest exposes its predecessor (restore)."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE")
        cur = self._raw_entry(b, name)
        idx = self._index_oid(b["id"])
        vstate = b.get("versioning")
        versioned = vstate is not None or "versions" in cur

        def _index_put(meta: Dict) -> None:
            tag = secrets.token_hex(8)
            self._exec(self.mpool, idx, "bucket_prepare_op",
                       {"tag": tag, "name": name, "op": "put"})
            self._exec(self.mpool, idx, "bucket_complete_op",
                       {"tag": tag, "name": name, "op": "put",
                        "meta": meta})

        def _index_del() -> None:
            tag = secrets.token_hex(8)
            self._exec(self.mpool, idx, "bucket_prepare_op",
                       {"tag": tag, "name": name, "op": "del"})
            self._exec(self.mpool, idx, "bucket_complete_op",
                       {"tag": tag, "name": name, "op": "del"})

        if not versioned:
            _index_del()
            for oid in self._chunk_oids(b["id"], name,
                                        cur.get("chunks", 0)):
                self.client.remove(self.dpool, oid)
            return {"delete_marker": False}

        stack = self._version_stack(b, name, cur)
        if version_id is None:
            vid = ("null" if vstate == "suspended"
                   else secrets.token_hex(8))
            marker = {"vid": vid, "delete_marker": True,
                      "mtime": time.time()}
            replaced = [v for v in stack if v["vid"] == vid]
            stack = [marker] + [v for v in stack if v["vid"] != vid]
            meta = {"versions": stack}
            meta.update(self._current_summary(stack))
            _index_put(meta)
            # replaced-slot data only after the index committed
            for old_v in replaced:
                for oid in self._vrec_chunk_oids(b, name, old_v):
                    self.client.remove(self.dpool, oid)
            return {"delete_marker": True, "version_id": vid}
        vrec = next((v for v in stack if v["vid"] == version_id), None)
        if vrec is None:
            raise RGWError("delete_object", -2, "NoSuchVersion")
        stack = [v for v in stack if v["vid"] != version_id]
        if stack:
            meta = {"versions": stack}
            meta.update(self._current_summary(stack))
            _index_put(meta)
        else:
            _index_del()
        for oid in self._vrec_chunk_oids(b, name, vrec):
            self.client.remove(self.dpool, oid)
        return {"delete_marker": bool(vrec.get("delete_marker")),
                "version_id": version_id}

    def list_objects(self, bucket: str, prefix: str = "",
                     delimiter: str = "", marker: str = "",
                     max_keys: int = 1000,
                     actor: Optional[str] = None) -> Dict:
        """S3 ListObjects semantics incl. delimiter rollup into
        CommonPrefixes (RGWRados::cls_bucket_list + RGWListBucket).
        Keys whose CURRENT version is a delete marker are invisible
        here (they only show in list_object_versions)."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ")
        raw = json.loads(self._exec(
            self.mpool, self._index_oid(b["id"]), "bucket_list",
            {"prefix": prefix, "marker": marker,
             "max_keys": max_keys if not delimiter else 100000}))
        raw_last = raw["entries"][-1]["name"] if raw["entries"] else ""
        raw["entries"] = [e for e in raw["entries"]
                          if not e.get("delete_marker")]
        if not delimiter:
            # resume from the last RAW key scanned: a page of
            # marker-current keys must still advance the cursor (an
            # empty next_marker would restart callers from the top)
            return {"contents": raw["entries"], "common_prefixes": [],
                    "truncated": raw["truncated"],
                    "next_marker": raw_last}
        # delimiter rollup with GROUP-atomic pagination: a common
        # prefix is never split across pages (the whole contiguous key
        # group is consumed before the cap applies), so resuming from
        # next_marker never re-emits a prefix
        contents, prefixes = [], []
        entries = raw["entries"]
        next_marker = ""
        i = 0
        truncated = False
        while i < len(entries):
            if len(contents) + len(prefixes) >= max_keys:
                truncated = True
                break
            e = entries[i]
            rest = e["name"][len(prefix):]
            if delimiter in rest:
                cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                marker_before_group = next_marker
                prefixes.append(cp)
                while i < len(entries) and \
                        entries[i]["name"].startswith(cp):
                    next_marker = entries[i]["name"]
                    i += 1
                if i == len(entries) and raw["truncated"]:
                    # the group may continue past the raw fetch cap:
                    # withdraw it from this page and resume BEFORE it,
                    # so no prefix is ever emitted twice — unless the
                    # page would then be EMPTY (one group larger than
                    # the raw cap): emit it and advance past what we
                    # consumed, accepting one possible duplicate over a
                    # livelocked pagination
                    if contents or len(prefixes) > 1:
                        prefixes.pop()
                        next_marker = marker_before_group or marker
                    truncated = True
                    break
            else:
                contents.append(e)
                next_marker = e["name"]
                i += 1
        truncated = truncated or raw["truncated"]
        if not contents and not prefixes and raw_last:
            next_marker = raw_last      # all-markers page: still advance
        return {"contents": contents, "common_prefixes": prefixes,
                "truncated": truncated, "next_marker": next_marker}

    # ---- multipart (RGWMultipart*) -----------------------------------------
    def initiate_multipart(self, bucket: str, name: str,
                           actor: Optional[str] = None) -> str:
        """RGWInitMultipart needs s3PutObject on the bucket
        (rgw_op.cc:5155-5160) — WRITE here."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE")
        upload_id = secrets.token_hex(8)
        meta = {"parts": {}, "key": name}
        if actor is not None:
            meta["owner"] = actor
        self.client.write_full(
            self.mpool, f"multipart.{b['id']}.{name}.{upload_id}",
            _j(meta))
        return upload_id

    def list_multipart_uploads(self, bucket: str,
                               actor: Optional[str] = None
                               ) -> List[Dict]:
        """In-progress uploads for a bucket (RGWListBucketMultiparts
        role), sorted by (key, upload_id)."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ")
        prefix = f"multipart.{b['id']}."
        out = []
        moids = [o for o in self.client.list_objects(self.mpool)
                 if o.startswith(prefix)]
        for moid in moids:
            rest = moid[len(prefix):]
            if "." not in rest:
                continue
            name, upload_id = rest.rsplit(".", 1)
            mp = self._meta_get(moid) or {}
            out.append({"key": name, "upload_id": upload_id,
                        "owner": mp.get("owner")})
        return sorted(out, key=lambda u: (u["key"], u["upload_id"]))

    def list_parts(self, bucket: str, name: str, upload_id: str,
                   actor: Optional[str] = None) -> List[Dict]:
        """Parts uploaded so far (RGWListMultipart role,
        rgw_op.cc:5641-5644), ascending part number."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ")
        mp = self._meta_get(self._mp_meta_oid(b["id"], name,
                                              upload_id))
        if mp is None:
            raise RGWError("list_parts", -2, "NoSuchUpload")
        return [{"part_number": int(pn), "size": p["size"],
                 "etag": p["etag"]}
                for pn, p in sorted(mp["parts"].items(),
                                    key=lambda kv: int(kv[0]))]

    def _mp_meta_oid(self, bid: str, name: str, upload_id: str) -> str:
        return f"multipart.{bid}.{name}.{upload_id}"

    def upload_part(self, bucket: str, name: str, upload_id: str,
                    part_num: int, data: bytes,
                    actor: Optional[str] = None) -> str:
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE")
        # staged parts count against the owner's quota too — without
        # this a quota-limited user could park unbounded data in
        # _multipart_ staging
        self._check_user_quota(b.get("owner"), len(data))
        moid = self._mp_meta_oid(b["id"], name, upload_id)
        mp = self._meta_get(moid)
        if mp is None:
            raise RGWError("upload_part", -2, "NoSuchUpload")
        poid = f"{b['id']}_mp_{name}.{upload_id}.{part_num}"
        r = self.client.write_full(self.dpool, poid, data)
        if r < 0:
            raise RGWError("upload_part", r)
        etag = hashlib.md5(data).hexdigest()
        mp["parts"][str(part_num)] = {"size": len(data), "etag": etag}
        self.client.write_full(self.mpool, moid, _j(mp))
        return etag

    def complete_multipart(self, bucket: str, name: str,
                           upload_id: str,
                           parts: Optional[List[Dict]] = None,
                           actor: Optional[str] = None) -> Dict:
        """Stitch the parts into the final object (copy-concatenate —
        the reference links manifests instead; lite keeps one chunk
        layout for get_object).

        ``parts`` (the client's CompleteMultipartUpload manifest,
        [{'part_number', 'etag'}]) is validated against what was
        uploaded the way RGWCompleteMultipart::execute checks each
        listed part's etag (rgw_op.cc InvalidPart path); None keeps
        the legacy use-everything behavior."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE")
        moid = self._mp_meta_oid(b["id"], name, upload_id)
        mp = self._meta_get(moid)
        if mp is None:
            raise RGWError("complete_multipart", -2, "NoSuchUpload")
        if parts is not None:
            if not parts:
                raise RGWError("complete_multipart", -22,
                               "MalformedXML")
            nums = [p["part_number"] for p in parts]
            # strictly ascending: duplicates are invalid too
            if any(x >= y for x, y in zip(nums, nums[1:])):
                raise RGWError("complete_multipart", -22,
                               "InvalidPartOrder")
            for p in parts:
                have = mp["parts"].get(str(p["part_number"]))
                if have is None or (p.get("etag") and
                                    p["etag"].strip('"') !=
                                    have["etag"]):
                    raise RGWError("complete_multipart", -22,
                                   "InvalidPart")
            use = [str(p["part_number"]) for p in parts]
        else:
            use = sorted(mp["parts"], key=int)
        data = b""
        for pn in use:
            poid = f"{b['id']}_mp_{name}.{upload_id}.{pn}"
            data += self.client.read(self.dpool, poid)
        meta = self.put_object(bucket, name, data, actor=actor)
        self.abort_multipart(bucket, name, upload_id)
        return meta

    def abort_multipart(self, bucket: str, name: str,
                        upload_id: str,
                        actor: Optional[str] = None) -> None:
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE")
        moid = self._mp_meta_oid(b["id"], name, upload_id)
        mp = self._meta_get(moid)
        if mp is None:
            return
        for pn in mp["parts"]:
            self.client.remove(
                self.dpool,
                f"{b['id']}_mp_{name}.{upload_id}.{pn}")
        self.client.remove(self.mpool, moid)


    # ---- ACLs (rgw_acl_s3.cc role; grants as structured dicts) -------------
    CANNED_ACLS = {
        "private": [],
        "public-read": [{"grantee": "*", "permission": "READ"}],
        "public-read-write": [{"grantee": "*", "permission": "READ"},
                              {"grantee": "*", "permission": "WRITE"}],
        "authenticated-read": [{"grantee": "auth",
                                "permission": "READ"}],
    }

    @staticmethod
    def _grants_allow(owner: Optional[str], grants: List[Dict],
                      actor: Optional[str], perm: str) -> bool:
        """The RGWAccessControlPolicy::verify_permission decision:
        owner holds FULL_CONTROL; grants match by grantee (uid,
        'auth' = any authenticated user, '*' = everyone) and
        permission (FULL_CONTROL implies all)."""
        if owner is not None and actor == owner:
            return True
        for g in grants or []:
            who = g.get("grantee")
            if who == "*" or (who == "auth" and actor is not None)                     or (who == actor and actor is not None):
                if g.get("permission") in (perm, "FULL_CONTROL"):
                    return True
        return False

    def _check_bucket_access(self, b: Dict, actor: Optional[str],
                             perm: str) -> None:
        """actor None = the system/admin path (radosgw-admin), which
        bypasses policy like the reference's system uid."""
        if actor is None:
            return
        acl = b.get("acl") or {}
        if not self._grants_allow(b.get("owner"),
                                  acl.get("grants", []), actor, perm):
            raise RGWError("access", -13, "AccessDenied")

    def _check_object_access(self, b: Dict, entry: Dict,
                             actor: Optional[str], perm: str) -> None:
        if actor is None:
            return
        acl = entry.get("acl")
        owner = entry.get("owner", b.get("owner"))
        grants = (acl or {}).get("grants", [])
        if self._grants_allow(owner, grants, actor, perm):
            return
        # fall back to the bucket policy (the reference checks both)
        self._check_bucket_access(b, actor, perm)

    def _resolve_grants(self, canned: Optional[str],
                        grants: Optional[List[Dict]]) -> List[Dict]:
        if canned is not None:
            if canned not in self.CANNED_ACLS:
                raise RGWError("acl", -22, "InvalidCannedACL")
            return list(self.CANNED_ACLS[canned])
        return list(grants or [])

    def put_bucket_acl(self, bucket: str, canned: Optional[str] = None,
                       grants: Optional[List[Dict]] = None,
                       actor: Optional[str] = None) -> None:
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE_ACP")
        b["acl"] = {"grants": self._resolve_grants(canned, grants)}
        self._save_bucket(b)

    def get_bucket_acl(self, bucket: str,
                       actor: Optional[str] = None) -> Dict:
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ_ACP")
        return {"owner": b.get("owner"),
                "grants": (b.get("acl") or {}).get("grants", [])}

    def put_object_acl(self, bucket: str, name: str,
                       canned: Optional[str] = None,
                       grants: Optional[List[Dict]] = None,
                       actor: Optional[str] = None) -> None:
        b = self.get_bucket(bucket)
        cur = self._raw_entry(b, name)
        self._check_object_access(b, cur, actor, "WRITE_ACP")
        cur["acl"] = {"grants": self._resolve_grants(canned, grants)}
        tag = secrets.token_hex(8)
        idx = self._index_oid(b["id"])
        self._exec(self.mpool, idx, "bucket_prepare_op",
                   {"tag": tag, "name": name, "op": "put"})
        self._exec(self.mpool, idx, "bucket_complete_op",
                   {"tag": tag, "name": name, "op": "put", "meta": cur})

    def get_object_acl(self, bucket: str, name: str,
                       actor: Optional[str] = None) -> Dict:
        b = self.get_bucket(bucket)
        cur = self._raw_entry(b, name)
        self._check_object_access(b, cur, actor, "READ_ACP")
        return {"owner": cur.get("owner", b.get("owner")),
                "grants": (cur.get("acl") or {}).get("grants", [])}

    # ---- lifecycle (rgw_lc.cc role) ----------------------------------------
    def put_bucket_lifecycle(self, bucket: str, rules: List[Dict],
                             actor: Optional[str] = None) -> None:
        """rules: [{'id', 'prefix', 'status', 'expiration_days',
        'noncurrent_days'}] (the S3 LifecycleConfiguration subset the
        reference's RGWLC processes most)."""
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE_ACP")
        for r in rules:
            if not (r.get("expiration_days")
                    or r.get("noncurrent_days")):
                raise RGWError("lifecycle", -22, "MissingAction")
        b["lifecycle"] = list(rules)
        self._save_bucket(b)

    def get_bucket_lifecycle(self, bucket: str,
                             actor: Optional[str] = None
                             ) -> List[Dict]:
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "READ_ACP")
        return list(b.get("lifecycle") or [])

    def delete_bucket_lifecycle(self, bucket: str,
                                actor: Optional[str] = None) -> None:
        b = self.get_bucket(bucket)
        self._check_bucket_access(b, actor, "WRITE_ACP")
        b.pop("lifecycle", None)
        self._save_bucket(b)

    def lc_process(self, now: Optional[float] = None) -> Dict:
        """One lifecycle pass over every bucket (radosgw-admin lc
        process / RGWLC::process): expire current objects past
        expiration_days (versioned buckets get delete markers),
        permanently drop noncurrent versions past noncurrent_days,
        and clean up expired-object delete markers left alone on a
        stack."""
        now = time.time() if now is None else now
        report: Dict[str, Dict] = {}
        meta_oids = list(self.client.list_objects(self.mpool))
        for moid in sorted(o for o in meta_oids
                           if o.startswith("bucket.")):
            bname = moid[len("bucket."):]
            try:
                b = self.get_bucket(bname)
            except RGWError:
                continue
            rules = [r for r in (b.get("lifecycle") or [])
                     if r.get("status", "Enabled") == "Enabled"]
            if not rules:
                continue
            stats = {"expired": 0, "noncurrent_removed": 0,
                     "markers_cleaned": 0}
            all_versions = self.list_object_versions(bname)
            per_key_count: Dict[str, int] = {}
            for v in all_versions:
                per_key_count[v["key"]] = \
                    per_key_count.get(v["key"], 0) + 1
            for v in all_versions:
                key = v["key"]
                rule = next((r for r in rules
                             if key.startswith(r.get("prefix", ""))),
                            None)
                if rule is None:
                    continue
                exp = rule.get("expiration_days")
                non = rule.get("noncurrent_days")
                age_days = (now - v["mtime"]) / 86400.0
                if v["is_latest"]:
                    if exp and not v["delete_marker"]                             and age_days >= exp:
                        self.delete_object(bname, key)
                        stats["expired"] += 1
                    elif v["delete_marker"] and exp:
                        # expired-object delete marker: the marker is
                        # the ONLY version left -> remove the entry
                        if per_key_count.get(key, 0) == 1:
                            self.delete_object(
                                bname, key, version_id=v["version_id"])
                            stats["markers_cleaned"] += 1
                elif non and age_days >= non:
                    self.delete_object(bname, key,
                                       version_id=v["version_id"])
                    stats["noncurrent_removed"] += 1
            report[bname] = stats
        return report

    # ---- garbage collection (RGWGC role, src/rgw/rgw_gc.cc) ----------------
    def gc(self, repair: bool = False) -> Dict:
        """Scan for debt the two-phase protocol can leave behind: data
        objects not referenced by any committed index entry or active
        multipart upload (crashed puts, interrupted deletes), and
        uncommitted pending index markers.  With ``repair``, orphans
        are deleted and pending markers cancelled — the rgw gc +
        radosgw-admin gc process role.  Run it quiesced: a put in
        flight legitimately holds a pending marker and unreferenced
        chunks."""
        report = {"orphan_objects": [], "stale_pending": []}
        meta_oids = list(self.client.list_objects(self.mpool))
        bucket_names = [o[len("bucket."):] for o in meta_oids
                        if o.startswith("bucket.")]
        referenced = set()
        known_bids = set()
        pending: list = []
        protected_bids = set()
        for name in bucket_names:
            try:
                b = self.get_bucket(name)
            except RGWError:
                continue
            known_bids.add(b["id"])
            try:
                marker = ""
                while True:          # paginate over the RAW index:
                    # keys whose current is a delete marker are hidden
                    # from ListObjects, but their noncurrent versions'
                    # data is very much alive — gc must see them
                    listing = json.loads(self._exec(
                        self.mpool, self._index_oid(b["id"]),
                        "bucket_list",
                        {"prefix": "", "marker": marker,
                         "max_keys": 10000}))
                    listing["contents"] = listing.pop("entries")
                    for e in listing["contents"]:
                        if "versions" in e:
                            for v in e["versions"]:
                                referenced.update(self._vrec_chunk_oids(
                                    b, e["name"], v))
                        else:
                            referenced.update(self._chunk_oids(
                                b["id"], e["name"],
                                e.get("chunks", 1)))
                    if not listing["truncated"] or \
                            not listing["contents"]:
                        break
                    marker = listing["contents"][-1]["name"]
            except RGWError:
                # index unreadable/lost (ESTALE): this bucket's
                # references are unknowable — its data must never be
                # classified as orphaned
                protected_bids.add(b["id"])
            idx = self._index_oid(b["id"])
            try:
                om = self.client.omap_get(self.mpool, idx)
            except IOError:
                om = {}
            for k in om:
                if k.startswith("pending_"):
                    pending.append((name, idx, k[len("pending_"):]))
        for moid in meta_oids:
            if not moid.startswith("multipart."):
                continue
            mp = self._meta_get(moid)
            if not mp:
                continue
            _, bid, rest = moid.split(".", 2)
            name, upload_id = rest.rsplit(".", 1)
            for pn in mp.get("parts", {}):
                referenced.add(f"{bid}_mp_{name}.{upload_id}.{pn}")
        import re
        rgw_oid = re.compile(r"^[0-9a-f]{16}_(o|c|mp)_")
        # a bucket whose INDEX object exists but whose bucket.<name>
        # meta was unreadable this pass is unknowable — its data must
        # never be purged (the index may reference it); only a bucket
        # with NO index object left (delete_bucket removed it) has
        # truly deleted debris
        index_bids = {o[len(".dir."):] for o in meta_oids
                      if o.startswith(".dir.")}
        for oid in self.client.list_objects(self.dpool):
            if not rgw_oid.match(oid):
                continue             # not an rgw data object
            bid = oid.split("_", 1)[0]
            if bid in protected_bids:
                continue             # meta alive, index unreadable
            if bid in index_bids and bid not in known_bids:
                continue             # index alive, meta unreadable
            if bid in known_bids and oid in referenced:
                continue
            report["orphan_objects"].append(oid)
            if repair:
                self.client.remove(self.dpool, oid)
        for name, idx, tag in pending:
            report["stale_pending"].append([name, tag])
            if repair:
                self._exec(self.mpool, idx, "bucket_cancel_op",
                           {"tag": tag})
        return report
