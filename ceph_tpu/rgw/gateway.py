"""rgw-lite: S3-shaped object gateway over rados (src/rgw, 122k LoC in
the reference, at lite scale).

Storage layout mirrors the reference's: user and bucket-entrypoint
records in a metadata pool (``user.<uid>``, ``bucket.<name>``), one
index object per bucket (``.dir.<bucket_id>``) mutated through the
two-phase cls_rgw protocol, and object payloads chunked into the data
pool under ``<bucket_id>_<name>[.chunk.N]`` with a manifest in the
index entry (RGWObjManifest role).  Multipart uploads stage parts
under a ``_multipart_`` namespace and stitch a manifest at complete,
like RGWMultipart*.

Scope-outs vs the reference: versioning, lifecycle, ACL grammars
beyond owner checks, swift API, and the civetweb frontend (the
``http`` module provides a threaded stdlib server speaking the S3
path-style subset with AWS v2-style HMAC auth instead).
"""
from __future__ import annotations

import hashlib
import json
import secrets
import time
from typing import Dict, List, Optional

from ..client.rados import RadosClient
from . import cls_rgw  # noqa: F401

CHUNK = 4 << 20                   # rgw_max_chunk_size default (4 MiB)


class RGWError(IOError):
    def __init__(self, api: str, result: int, reason: str = ""):
        super().__init__(f"rgw {api}: {result} {reason}".rstrip())
        self.result = result


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _absent(e: IOError) -> bool:
    return getattr(e, "errno", None) == 2


class RGWLite:
    """The gateway core (RGWRados role): all state in rados."""

    def __init__(self, client: RadosClient, meta_pool: str,
                 data_pool: str):
        self.client = client
        self.mpool = meta_pool
        self.dpool = data_pool

    # ---- cls / meta helpers ------------------------------------------------
    def _exec(self, pool: str, oid: str, method: str, payload=None
              ) -> bytes:
        ret, out = self.client.exec(pool, oid, "rgw", method,
                                    _j(payload or {}))
        if ret < 0:
            raise RGWError(method, ret)
        return out

    def _meta_get(self, oid: str) -> Optional[Dict]:
        try:
            return json.loads(self.client.read(self.mpool, oid))
        except IOError as e:
            if _absent(e):
                return None
            raise

    # ---- users (RGWUser / radosgw-admin user create) -----------------------
    def create_user(self, uid: str, display_name: str = "") -> Dict:
        if self._meta_get(f"user.{uid}") is not None:
            raise RGWError("create_user", -17)
        user = {"uid": uid, "display_name": display_name or uid,
                "access_key": secrets.token_hex(10),
                "secret_key": secrets.token_hex(20),
                "buckets": []}
        self.client.write_full(self.mpool, f"user.{uid}", _j(user))
        self._meta_index(f"user.{uid}", True)
        return user

    def get_user(self, uid: str) -> Dict:
        u = self._meta_get(f"user.{uid}")
        if u is None:
            raise RGWError("get_user", -2)
        return u

    def delete_user(self, uid: str) -> None:
        """Remove a user (radosgw-admin user rm): refused while the
        user still owns buckets."""
        u = self.get_user(uid)
        if u["buckets"]:
            raise RGWError("delete_user", -39, "user owns buckets")
        self.client.remove(self.mpool, f"user.{uid}")
        self._meta_index(f"user.{uid}", False)

    def list_users(self) -> List[str]:
        return [oid[len("user."):] for oid in self._meta_list("user.")]

    def bucket_stats(self, bucket: str) -> Dict:
        """Bucket entry + index stats (radosgw-admin bucket stats)."""
        b = self.get_bucket(bucket)
        stats = json.loads(self._exec(
            self.mpool, self._index_oid(b["id"]), "bucket_stats"))
        return {**b, **stats}

    def user_by_access_key(self, access_key: str) -> Optional[Dict]:
        # lite linear scan (the reference keeps a key->uid index object)
        for oid in self._meta_list("user."):
            u = self._meta_get(oid)
            if u and u["access_key"] == access_key:
                return u
        return None

    def _meta_list(self, prefix: str) -> List[str]:
        try:
            om = self.client.omap_get(self.mpool, "rgw_meta_index")
        except IOError as e:
            if not _absent(e):
                raise
            om = {}
        return sorted(k for k in om if k.startswith(prefix))

    def _meta_index(self, key: str, add: bool) -> None:
        if add:
            self.client.omap_set(self.mpool, "rgw_meta_index",
                                 {key: b"1"})
        else:
            self.client.omap_rm_keys(self.mpool, "rgw_meta_index",
                                     [key])

    # ---- buckets -----------------------------------------------------------
    def _index_oid(self, bucket_id: str) -> str:
        return f".dir.{bucket_id}"

    def create_bucket(self, uid: str, name: str) -> Dict:
        user = self.get_user(uid)
        if self._meta_get(f"bucket.{name}") is not None:
            raise RGWError("create_bucket", -17, "BucketAlreadyExists")
        bid = secrets.token_hex(8)
        bucket = {"name": name, "id": bid, "owner": uid,
                  "created": time.time()}
        self.client.write_full(self.mpool, f"bucket.{name}", _j(bucket))
        self.client.create(self.mpool, self._index_oid(bid),
                           exclusive=False)
        user["buckets"] = sorted(set(user["buckets"]) | {name})
        self.client.write_full(self.mpool, f"user.{uid}", _j(user))
        return bucket

    def get_bucket(self, name: str) -> Dict:
        b = self._meta_get(f"bucket.{name}")
        if b is None:
            raise RGWError("get_bucket", -2, "NoSuchBucket")
        return b

    def delete_bucket(self, name: str) -> None:
        b = self.get_bucket(name)
        stats = json.loads(self._exec(self.mpool,
                                      self._index_oid(b["id"]),
                                      "bucket_stats"))
        if stats["num_objects"]:
            raise RGWError("delete_bucket", -39, "BucketNotEmpty")
        self.client.remove(self.mpool, self._index_oid(b["id"]))
        self.client.remove(self.mpool, f"bucket.{name}")
        owner = self._meta_get(f"user.{b['owner']}")
        if owner:
            owner["buckets"] = [x for x in owner["buckets"] if x != name]
            self.client.write_full(self.mpool, f"user.{b['owner']}",
                                   _j(owner))

    def list_buckets(self, uid: str) -> List[str]:
        return list(self.get_user(uid)["buckets"])

    # ---- objects -----------------------------------------------------------
    def _data_oid(self, bucket_id: str, name: str) -> str:
        # distinct o_/c_/mp_ namespaces: a key can never collide with
        # another key's chunk or multipart staging objects (the
        # reference's __shadow_ namespace escaping, rgw_obj::set_ns)
        return f"{bucket_id}_o_{name}"

    def _write_chunked(self, base_oid: str, data: bytes) -> List[str]:
        """Payload -> head object + .chunk.N tail objects (manifest)."""
        oids = []
        for i in range(0, max(len(data), 1), CHUNK):
            oid = base_oid if i == 0 else \
                base_oid.replace("_o_", "_c_", 1) + f".{i // CHUNK}"
            r = self.client.write_full(self.dpool, oid,
                                       data[i:i + CHUNK])
            if r < 0:
                raise RGWError("put_object", r)
            oids.append(oid)
        return oids

    def put_object(self, bucket: str, name: str, data: bytes,
                   content_type: str = "binary/octet-stream") -> Dict:
        """Two-phase put: index prepare -> data chunks -> index
        complete.  A crash mid-way leaves a pending marker and garbage
        chunks, but never a listing entry for unreadable data."""
        b = self.get_bucket(bucket)
        idx = self._index_oid(b["id"])
        try:
            old_chunks = self.head_object(bucket, name)["chunks"]
        except RGWError:
            old_chunks = 0
        tag = secrets.token_hex(8)
        self._exec(self.mpool, idx, "bucket_prepare_op",
                   {"tag": tag, "name": name, "op": "put"})
        try:
            chunks = self._write_chunked(self._data_oid(b["id"], name),
                                         data)
        except Exception:
            self._exec(self.mpool, idx, "bucket_cancel_op", {"tag": tag})
            raise
        meta = {"size": len(data),
                "etag": hashlib.md5(data).hexdigest(),
                "mtime": time.time(), "content_type": content_type,
                "chunks": len(chunks)}
        self._exec(self.mpool, idx, "bucket_complete_op",
                   {"tag": tag, "name": name, "op": "put", "meta": meta})
        # a shrinking overwrite strands the old version's tail chunks;
        # collect them now (the reference defers this to its GC)
        for oid in self._chunk_oids(b["id"], name,
                                    old_chunks)[len(chunks):]:
            self.client.remove(self.dpool, oid)
        return meta

    def get_object(self, bucket: str, name: str) -> bytes:
        b = self.get_bucket(bucket)
        meta = self.head_object(bucket, name)
        parts = []
        for oid in self._chunk_oids(b["id"], name, meta["chunks"]):
            parts.append(self.client.read(self.dpool, oid))
        return b"".join(parts)

    def _chunk_oids(self, bid: str, name: str, count: int):
        base = self._data_oid(bid, name)
        return [base if i == 0 else
                base.replace("_o_", "_c_", 1) + f".{i}"
                for i in range(count)]

    def head_object(self, bucket: str, name: str) -> Dict:
        b = self.get_bucket(bucket)
        try:
            return json.loads(self._exec(
                self.mpool, self._index_oid(b["id"]),
                "bucket_get_entry", {"name": name}))
        except RGWError as e:
            if e.result == -2:
                raise RGWError("head_object", -2, "NoSuchKey")
            raise

    def delete_object(self, bucket: str, name: str) -> None:
        """Index first, data second: a crash mid-delete leaves orphan
        chunks (GC debt) but never a listing entry pointing at deleted
        data — the same invariant direction as put."""
        b = self.get_bucket(bucket)
        meta = self.head_object(bucket, name)
        idx = self._index_oid(b["id"])
        tag = secrets.token_hex(8)
        self._exec(self.mpool, idx, "bucket_prepare_op",
                   {"tag": tag, "name": name, "op": "del"})
        self._exec(self.mpool, idx, "bucket_complete_op",
                   {"tag": tag, "name": name, "op": "del"})
        for oid in self._chunk_oids(b["id"], name, meta["chunks"]):
            self.client.remove(self.dpool, oid)

    def list_objects(self, bucket: str, prefix: str = "",
                     delimiter: str = "", marker: str = "",
                     max_keys: int = 1000) -> Dict:
        """S3 ListObjects semantics incl. delimiter rollup into
        CommonPrefixes (RGWRados::cls_bucket_list + RGWListBucket)."""
        b = self.get_bucket(bucket)
        raw = json.loads(self._exec(
            self.mpool, self._index_oid(b["id"]), "bucket_list",
            {"prefix": prefix, "marker": marker,
             "max_keys": max_keys if not delimiter else 100000}))
        if not delimiter:
            nm = (raw["entries"][-1]["name"] if raw["entries"] else "")
            return {"contents": raw["entries"], "common_prefixes": [],
                    "truncated": raw["truncated"], "next_marker": nm}
        # delimiter rollup with GROUP-atomic pagination: a common
        # prefix is never split across pages (the whole contiguous key
        # group is consumed before the cap applies), so resuming from
        # next_marker never re-emits a prefix
        contents, prefixes = [], []
        entries = raw["entries"]
        next_marker = ""
        i = 0
        truncated = False
        while i < len(entries):
            if len(contents) + len(prefixes) >= max_keys:
                truncated = True
                break
            e = entries[i]
            rest = e["name"][len(prefix):]
            if delimiter in rest:
                cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                marker_before_group = next_marker
                prefixes.append(cp)
                while i < len(entries) and \
                        entries[i]["name"].startswith(cp):
                    next_marker = entries[i]["name"]
                    i += 1
                if i == len(entries) and raw["truncated"]:
                    # the group may continue past the raw fetch cap:
                    # withdraw it from this page and resume BEFORE it,
                    # so no prefix is ever emitted twice — unless the
                    # page would then be EMPTY (one group larger than
                    # the raw cap): emit it and advance past what we
                    # consumed, accepting one possible duplicate over a
                    # livelocked pagination
                    if contents or len(prefixes) > 1:
                        prefixes.pop()
                        next_marker = marker_before_group or marker
                    truncated = True
                    break
            else:
                contents.append(e)
                next_marker = e["name"]
                i += 1
        truncated = truncated or raw["truncated"]
        return {"contents": contents, "common_prefixes": prefixes,
                "truncated": truncated, "next_marker": next_marker}

    # ---- multipart (RGWMultipart*) -----------------------------------------
    def initiate_multipart(self, bucket: str, name: str) -> str:
        b = self.get_bucket(bucket)
        upload_id = secrets.token_hex(8)
        self.client.write_full(
            self.mpool, f"multipart.{b['id']}.{name}.{upload_id}",
            _j({"parts": {}}))
        return upload_id

    def _mp_meta_oid(self, bid: str, name: str, upload_id: str) -> str:
        return f"multipart.{bid}.{name}.{upload_id}"

    def upload_part(self, bucket: str, name: str, upload_id: str,
                    part_num: int, data: bytes) -> str:
        b = self.get_bucket(bucket)
        moid = self._mp_meta_oid(b["id"], name, upload_id)
        mp = self._meta_get(moid)
        if mp is None:
            raise RGWError("upload_part", -2, "NoSuchUpload")
        poid = f"{b['id']}_mp_{name}.{upload_id}.{part_num}"
        r = self.client.write_full(self.dpool, poid, data)
        if r < 0:
            raise RGWError("upload_part", r)
        etag = hashlib.md5(data).hexdigest()
        mp["parts"][str(part_num)] = {"size": len(data), "etag": etag}
        self.client.write_full(self.mpool, moid, _j(mp))
        return etag

    def complete_multipart(self, bucket: str, name: str,
                           upload_id: str) -> Dict:
        """Stitch the parts into the final object (copy-concatenate —
        the reference links manifests instead; lite keeps one chunk
        layout for get_object)."""
        b = self.get_bucket(bucket)
        moid = self._mp_meta_oid(b["id"], name, upload_id)
        mp = self._meta_get(moid)
        if mp is None:
            raise RGWError("complete_multipart", -2, "NoSuchUpload")
        data = b""
        for pn in sorted(mp["parts"], key=int):
            poid = f"{b['id']}_mp_{name}.{upload_id}.{pn}"
            data += self.client.read(self.dpool, poid)
        meta = self.put_object(bucket, name, data)
        self.abort_multipart(bucket, name, upload_id)
        return meta

    def abort_multipart(self, bucket: str, name: str,
                        upload_id: str) -> None:
        b = self.get_bucket(bucket)
        moid = self._mp_meta_oid(b["id"], name, upload_id)
        mp = self._meta_get(moid)
        if mp is None:
            return
        for pn in mp["parts"]:
            self.client.remove(
                self.dpool,
                f"{b['id']}_mp_{name}.{upload_id}.{pn}")
        self.client.remove(self.mpool, moid)


    # ---- garbage collection (RGWGC role, src/rgw/rgw_gc.cc) ----------------
    def gc(self, repair: bool = False) -> Dict:
        """Scan for debt the two-phase protocol can leave behind: data
        objects not referenced by any committed index entry or active
        multipart upload (crashed puts, interrupted deletes), and
        uncommitted pending index markers.  With ``repair``, orphans
        are deleted and pending markers cancelled — the rgw gc +
        radosgw-admin gc process role.  Run it quiesced: a put in
        flight legitimately holds a pending marker and unreferenced
        chunks."""
        report = {"orphan_objects": [], "stale_pending": []}
        meta_oids = list(self.client.list_objects(self.mpool))
        bucket_names = [o[len("bucket."):] for o in meta_oids
                        if o.startswith("bucket.")]
        referenced = set()
        known_bids = set()
        pending: list = []
        protected_bids = set()
        for name in bucket_names:
            try:
                b = self.get_bucket(name)
            except RGWError:
                continue
            known_bids.add(b["id"])
            try:
                marker = ""
                while True:          # paginate: never misread a huge
                    listing = self.list_objects(name, marker=marker,
                                                max_keys=10000)
                    for e in listing["contents"]:
                        referenced.update(self._chunk_oids(
                            b["id"], e["name"], e.get("chunks", 1)))
                    if not listing["truncated"] or \
                            not listing["contents"]:
                        break
                    marker = listing["contents"][-1]["name"]
            except RGWError:
                # index unreadable/lost (ESTALE): this bucket's
                # references are unknowable — its data must never be
                # classified as orphaned
                protected_bids.add(b["id"])
            idx = self._index_oid(b["id"])
            try:
                om = self.client.omap_get(self.mpool, idx)
            except IOError:
                om = {}
            for k in om:
                if k.startswith("pending_"):
                    pending.append((name, idx, k[len("pending_"):]))
        for moid in meta_oids:
            if not moid.startswith("multipart."):
                continue
            mp = self._meta_get(moid)
            if not mp:
                continue
            _, bid, rest = moid.split(".", 2)
            name, upload_id = rest.rsplit(".", 1)
            for pn in mp.get("parts", {}):
                referenced.add(f"{bid}_mp_{name}.{upload_id}.{pn}")
        import re
        rgw_oid = re.compile(r"^[0-9a-f]{16}_(o|c|mp)_")
        # a bucket whose INDEX object exists but whose bucket.<name>
        # meta was unreadable this pass is unknowable — its data must
        # never be purged (the index may reference it); only a bucket
        # with NO index object left (delete_bucket removed it) has
        # truly deleted debris
        index_bids = {o[len(".dir."):] for o in meta_oids
                      if o.startswith(".dir.")}
        for oid in self.client.list_objects(self.dpool):
            if not rgw_oid.match(oid):
                continue             # not an rgw data object
            bid = oid.split("_", 1)[0]
            if bid in protected_bids:
                continue             # meta alive, index unreadable
            if bid in index_bids and bid not in known_bids:
                continue             # index alive, meta unreadable
            if bid in known_bids and oid in referenced:
                continue
            report["orphan_objects"].append(oid)
            if repair:
                self.client.remove(self.dpool, oid)
        for name, idx, tag in pending:
            report["stale_pending"].append([name, tag])
            if repair:
                self._exec(self.mpool, idx, "bucket_cancel_op",
                           {"tag": tag})
        return report
