"""Minimal XOR-parity plugin — test fixture.

Analog of the reference's ErasureCodeExample fixture
(src/test/erasure-code/ErasureCodeExample.h): k data chunks + one XOR parity
chunk, used to exercise the registry and the base-class plumbing.
"""
from __future__ import annotations

import numpy as np

from .matrix_plugin import ErasureCodeMatrixRS
from .rs_codec import MatrixRSCodec


class ErasureCodeExampleXor(ErasureCodeMatrixRS):
    def init(self, profile) -> None:
        super().init(profile)
        self.k = self.to_int("k", profile, 2)
        self.m = 1
        self.sanity_check_k(self.k)
        self._init_backend(profile)
        matrix = np.zeros((self.k + 1, self.k), dtype=np.uint8)
        matrix[:self.k] = np.eye(self.k, dtype=np.uint8)
        matrix[self.k, :] = 1
        self.codec = MatrixRSCodec(matrix)
