"""ErasureCodeInterface — the plugin ABI.

Semantic contract mirrors the reference's abstract interface
(src/erasure-code/ErasureCodeInterface.h:170): systematic codes over
k data + m coding chunks, optional sub-chunks (array codes), chunk
remapping, and minimum_to_decode returning per-shard (offset, count)
sub-chunk lists.

Buffers are numpy uint8 arrays (or bytes) instead of bufferlists; profiles
are plain ``dict[str, str]``.
"""
from __future__ import annotations

import abc
from typing import Dict, List, Sequence, Set, Tuple

ErasureCodeProfile = Dict[str, str]


class ErasureCodeInterface(abc.ABC):
    """Abstract erasure-code codec.

    Chunk/stripe model (reference ErasureCodeInterface.h:39-78): an object is
    split into k equally-sized data chunks; encode() produces m additional
    coding chunks; any k of the k+m chunks suffice to reconstruct.  All codes
    are systematic.
    """

    @abc.abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Initialize from profile; raises ValueError on bad parameters."""

    @abc.abstractmethod
    def get_profile(self) -> ErasureCodeProfile: ...

    @abc.abstractmethod
    def create_rule(self, name: str, crush) -> int:
        """Create a crush rule for this code in *crush* and return rule id."""

    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m."""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Number of sub-chunks per chunk (array codes; 1 for MDS RS)."""
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, object_size: int) -> int:
        """Chunk size for an object of *object_size* bytes (incl. padding)."""

    @abc.abstractmethod
    def minimum_to_decode(
        self, want_to_read: Set[int], available: Set[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        """Chunks (and per-chunk (sub-chunk offset, count) lists) to retrieve
        in order to reconstruct *want_to_read* from *available*.
        Raises IOError if reconstruction is impossible."""

    @abc.abstractmethod
    def minimum_to_decode_with_cost(
        self, want_to_read: Set[int], available: Dict[int, int]
    ) -> Set[int]:
        """Like minimum_to_decode but with per-chunk retrieval costs."""

    @abc.abstractmethod
    def encode(self, want_to_encode: Set[int], data) -> Dict[int, "np.ndarray"]:
        """Split+pad *data*, compute coding chunks, return the requested ones."""

    @abc.abstractmethod
    def encode_chunks(self, want_to_encode: Set[int], encoded) -> None: ...

    @abc.abstractmethod
    def decode(
        self, want_to_read: Set[int], chunks: Dict[int, "np.ndarray"], chunk_size: int = 0
    ) -> Dict[int, "np.ndarray"]: ...

    @abc.abstractmethod
    def decode_chunks(self, want_to_read, chunks, decoded) -> None: ...

    @abc.abstractmethod
    def get_chunk_mapping(self) -> Sequence[int]:
        """Permutation of logical->physical chunk indices (empty = identity)."""

    @abc.abstractmethod
    def decode_concat(self, chunks: Dict[int, "np.ndarray"]) -> bytes:
        """Reconstruct and concatenate the data chunks (trailing pad kept)."""
