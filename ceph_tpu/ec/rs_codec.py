"""Matrix-RS codec over GF(2^8): host (numpy) execution engine.

This is the CPU oracle for the TPU kernels (ceph_tpu/ops/gf_matmul.py).  Both
paths consume the same coding matrices (ceph_tpu.gf.matrices) and must agree
byte-for-byte; tests enforce this with exhaustive erasure sweeps.

Decode strategy (semantics of isa-l/jerasure matrix decoding as used by the
reference plugins, src/erasure-code/isa/ErasureCodeIsa.cc:217-303): pick the
first k surviving chunks in index order, build the k x k sub-matrix of the
encode matrix, invert it, recover missing data rows, and re-encode missing
coding rows.  Decode matrices are cached per erasure signature, mirroring
ErasureCodeIsaTableCache (LRU under mutex, ErasureCodeIsaTableCache.h:48).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..common.lockdep import DebugLock
from ..gf.tables import MUL_TABLE
from ..gf.matrices import gf_invert_matrix, gf_matmul

# Reference cache bound (ErasureCodeIsaTableCache.h:48)
DECODE_CACHE_ENTRIES = 2516


def plan_decode(k: int, available: Sequence[int], want: Sequence[int]):
    """Shared reconstruction plan used by both host and device executors.

    Returns (srcs, want_data, want_coding, missing_data):
    - srcs: the k survivor chunk ids to invert against
    - want_data / want_coding: requested-and-missing chunk ids by kind
    - missing_data: data rows the matvec must recover (includes data rows
      needed solely to re-encode missing coding chunks)
    """
    have = set(available)
    srcs = sorted(have)[:k]
    want_data = [i for i in want if i < k and i not in have]
    want_coding = [i for i in want if i >= k and i not in have]
    missing_data = sorted(
        set(want_data) |
        ({i for i in range(k) if i not in have} if want_coding else set()))
    return srcs, want_data, want_coding, missing_data


def gf_matvec_bytes(matrix_rows: np.ndarray, data: np.ndarray) -> np.ndarray:
    """rows (r, k) x data (k, C) -> (r, C) over GF(2^8), via 64KiB mul table."""
    r, k = matrix_rows.shape
    kk, c = data.shape
    assert k == kk
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(r):
        acc = out[i]
        for j in range(k):
            coeff = int(matrix_rows[i, j])
            if coeff == 0:
                continue
            if coeff == 1:
                acc ^= data[j]
            else:
                acc ^= MUL_TABLE[coeff][data[j]]
    return out


class MatrixRSCodec:
    """Systematic (k+m, k) matrix code executor with signature-cached
    decode.  Subclasses for other fields/layouts (gf/word_codec.py
    GF(2^w) words) override the ``_matvec``/``_invert`` primitives and
    inherit the encode/decode scaffolding unchanged."""

    _matrix_dtype = np.uint8

    def __init__(self, encode_matrix: np.ndarray):
        rows, k = encode_matrix.shape
        self.k = k
        self.m = rows - k
        self.matrix = encode_matrix.astype(self._matrix_dtype)
        self.coding_rows = self.matrix[k:, :]
        self._decode_cache: "OrderedDict[Tuple[int, ...], np.ndarray]" = OrderedDict()
        self._lock = DebugLock("rs_codec::decode_cache")

    # -- field/layout primitives (override points) ---------------------------
    def _matvec(self, rows: np.ndarray, data: np.ndarray) -> np.ndarray:
        return gf_matvec_bytes(rows, data)

    def _invert(self, sub: np.ndarray) -> np.ndarray:
        return gf_invert_matrix(sub)

    # -- encode -------------------------------------------------------------
    def encode(self, data: np.ndarray) -> np.ndarray:
        """data (k, C) uint8 -> coding (m, C) uint8."""
        return self._matvec(self.coding_rows, data)

    # -- decode -------------------------------------------------------------
    def decode_matrix_for(self, available: Sequence[int]) -> Tuple[np.ndarray, List[int]]:
        """Recovery matrix for data chunks given available chunk ids.

        Returns (inv, rows_used): inv (k, k) such that
        data = inv @ stack(chunks[rows_used]).
        """
        srcs = sorted(available)[:self.k]
        key = tuple(srcs)
        with self._lock:
            hit = self._decode_cache.get(key)
            if hit is not None:
                self._decode_cache.move_to_end(key)
                return hit, list(key)
        sub = self.matrix[list(srcs), :]
        inv = self._invert(sub)
        with self._lock:
            self._decode_cache[key] = inv
            if len(self._decode_cache) > DECODE_CACHE_ENTRIES:
                self._decode_cache.popitem(last=False)
        return inv, list(srcs)

    def decode(
        self, chunks: Dict[int, np.ndarray], want: Sequence[int]
    ) -> Dict[int, np.ndarray]:
        """Reconstruct chunk ids in *want* from available *chunks*."""
        if len(chunks) < self.k:
            raise IOError(
                f"need at least k={self.k} chunks, have {len(chunks)}")
        inv, srcs = self.decode_matrix_for(list(chunks))
        src_stack = np.stack([chunks[i] for i in srcs])
        out: Dict[int, np.ndarray] = {}
        _, want_data, want_coding, missing_data = plan_decode(
            self.k, chunks, want)
        if want_data or want_coding:
            # only the data rows actually missing need the matvec; surviving
            # data rows come straight from chunks
            rec = self._matvec(inv[missing_data, :], src_stack)
            data_by_id = dict(zip(missing_data, rec))
            for i in want_data:
                out[i] = data_by_id[i]
            if want_coding:
                data_full = np.stack([
                    chunks[i] if i in chunks else data_by_id[i]
                    for i in range(self.k)])
                rows = self.matrix[want_coding, :]
                cod = self._matvec(rows, data_full)
                for idx, i in enumerate(want_coding):
                    out[i] = cod[idx]
        for i in want:
            if i in chunks:
                out[i] = chunks[i]
        return out
