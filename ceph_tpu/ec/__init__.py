from .interface import ErasureCodeInterface  # noqa: F401
from .base import ErasureCode, SIMD_ALIGN  # noqa: F401
from .registry import (  # noqa: F401
    ErasureCodePluginRegistry,
    instance as plugin_registry,
    create_erasure_code,
)
