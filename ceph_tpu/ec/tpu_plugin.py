"""'tpu' plugin — ErasureCodeTpu: the flagship device codec.

The north-star component: an ErasureCodeInterface-compatible codec whose
encode()/decode() batch stripe chunks into HBM and run the GF(2^8) matrix
multiply as MXU matmuls (ceph_tpu.ops.gf_matmul), replacing the reference's
isa-l/jerasure SIMD paths while staying byte-identical to them.

Profile: k, m, technique=reed_sol_van|cauchy (isa-l matrix semantics, so
chunks match the reference isa plugin bit-for-bit).  Beyond the reference
ABI it adds the batched-stripe entry points ``encode_batch`` /
``decode_batch`` used by ECUtil striping and the benchmark CLI — one device
call for S stripes is where the >=10x throughput target comes from (the
reference encodes stripe-by-stripe on the CPU, osd/ECUtil.cc:120-159).
"""
from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

from .isa import ErasureCodeIsa


class ErasureCodeTpu(ErasureCodeIsa):
    def init(self, profile) -> None:
        profile = dict(profile)
        profile.setdefault("backend", "tpu")
        super().init(profile)

    # ---- batched device API ----------------------------------------------
    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(S, k, C) uint8 -> (S, m, C) coding chunks in one device call."""
        return self.device().encode(data)

    def encode_batch_device(self, data):
        """jnp in/out; composes under jit / Mesh shardings."""
        return self.device().encode_device(data)

    def decode_batch(self, chunks: Dict[int, np.ndarray],
                     want: Sequence[int]) -> Dict[int, np.ndarray]:
        """Reconstruct chunk ids in *want* for a whole batch.

        chunks maps chunk id -> (S, C) arrays; all stripes share the same
        erasure signature (the recovery case: one failed shard across many
        stripes).
        """
        if len(chunks) < self.k:
            raise IOError(
                f"need at least k={self.k} chunks, have {len(chunks)}")
        from .rs_codec import plan_decode
        srcs, want_data, want_coding, missing_data = plan_decode(
            self.k, chunks, want)
        survivors = np.stack([chunks[i] for i in srcs], axis=1)  # (S, k, C)
        out: Dict[int, np.ndarray] = {i: chunks[i] for i in want if i in chunks}
        dev = self.device()
        by_id: Dict[int, np.ndarray] = {}
        if missing_data:
            # only actually-missing data rows go through the device matvec
            rec = dev.decode_data(survivors, srcs, missing_data)
            by_id = {i: rec[:, idx] for idx, i in enumerate(missing_data)}
            for i in want_data:
                out[i] = by_id[i]
        if want_coding:
            data_full = np.stack(
                [chunks[i] if i in chunks else by_id[i]
                 for i in range(self.k)], axis=1)
            coding = dev.encode(data_full)
            for i in want_coding:
                out[i] = coding[:, i - self.k]
        return out
