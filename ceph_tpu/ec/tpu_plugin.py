"""'tpu' plugin — ErasureCodeTpu: the flagship device codec.

The north-star component: an ErasureCodeInterface-compatible codec whose
encode()/decode() batch stripe chunks into HBM and run the GF(2^8) matrix
multiply as MXU matmuls (ceph_tpu.ops.gf_matmul), replacing the reference's
isa-l/jerasure SIMD paths while staying byte-identical to them.

Profile: k, m, technique=reed_sol_van|cauchy (isa-l matrix semantics, so
chunks match the reference isa plugin bit-for-bit).  Beyond the reference
ABI it adds the batched-stripe entry points ``encode_batch`` /
``decode_batch`` used by ECUtil striping and the benchmark CLI — one device
call for S stripes is where the >=10x throughput target comes from (the
reference encodes stripe-by-stripe on the CPU, osd/ECUtil.cc:120-159).
"""
from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

from ..fault import run_device_call
from .isa import ErasureCodeIsa


class ErasureCodeTpu(ErasureCodeIsa):
    """isa-matrix semantics with the device backend on by default; the
    batched stripe entry points (encode_batch/decode_batch) are inherited
    from ErasureCodeMatrixRS and dispatch to the MXU bit-matmul.

    This codec is the dispatch scheduler's primary target
    (ceph_tpu/dispatch): ``signature_family = "isa-matrix"`` (inherited)
    lets concurrent requests against tpu AND host-isa instances of the
    same (technique, k, m) coalesce into ONE padded device call, and the
    pointwise byte layout (``_stripe_block() == 1``) makes the
    scheduler's power-of-two chunk-size padding output-preserving.
    """

    def init(self, profile) -> None:
        profile = dict(profile)
        profile.setdefault("backend", "tpu")
        super().init(profile)

    def encode_batch_device(self, data):
        """jnp in/out; composes under jit / Mesh shardings.  Guarded
        (retry/backoff/watchdog + breaker accounting) but with no host
        fallback — callers want device-resident arrays, so exhaustion
        raises DeviceUnavailable for the driver to handle."""
        return run_device_call(
            self.codec_signature(), "tpu.encode_batch_device",
            lambda: self.device().encode_device(data))

    def decode_batch_device(self, survivors, srcs, want_rows):
        """Batched reconstruction on the device backend: *survivors*
        (S, len(srcs), C) stacked in ``srcs`` order, returns
        (S, len(want_rows), C) — the recovery-path twin of
        ``encode_batch_device`` for mesh/bench drivers."""
        return run_device_call(
            self.codec_signature(), "tpu.decode_batch_device",
            lambda: self.device().decode_data(survivors, tuple(srcs),
                                              tuple(want_rows)))
