"""ErasureCodePluginRegistry — plugin factory registry.

Mirrors the reference's dlopen-based registry semantics
(src/erasure-code/ErasureCodePlugin.cc:126-184): plugins are registered by
name into a lock-guarded singleton, version-checked, and instantiated per
profile.  Here a "plugin" is a Python factory; third-party plugins can
register via ``ErasureCodePluginRegistry.add``.  Preloading
(osd_erasure_code_plugins; reference global/global_init.cc:482) maps to
``preload()``.
"""
from __future__ import annotations

from typing import Callable, Dict

from .interface import ErasureCodeInterface, ErasureCodeProfile

# version handshake analog of __erasure_code_version (ErasureCodePlugin.h:24-27)
PLUGIN_VERSION = "ceph_tpu-ec-1"


class ErasureCodePlugin:
    """Factory wrapper; subclass or pass a callable returning a codec."""

    version = PLUGIN_VERSION

    def __init__(self, factory: Callable[[], ErasureCodeInterface]):
        self._factory = factory

    def make(self, profile: ErasureCodeProfile) -> ErasureCodeInterface:
        codec = self._factory()
        codec.init(dict(profile))
        return codec


class ErasureCodePluginRegistry:
    def __init__(self):
        from ..common.lockdep import DebugLock
        self._lock = DebugLock("ec_registry::plugins")
        self._plugins: Dict[str, ErasureCodePlugin] = {}
        self._load_errors: Dict[str, Exception] = {}
        self.disable_dlclose = True  # parity flag; meaningless here

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise KeyError(f"plugin {name} already registered")
            if plugin.version != PLUGIN_VERSION:
                raise RuntimeError(
                    f"plugin {name} version {plugin.version} does not match "
                    f"expected {PLUGIN_VERSION}")
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin:
        with self._lock:
            self._load_builtin(name)
            if name not in self._plugins:
                if name in self._load_errors:
                    raise ImportError(
                        f"erasure-code plugin {name!r} failed to load: "
                        f"{self._load_errors[name]}")
                raise KeyError(f"unknown erasure-code plugin {name!r}")
            return self._plugins[name]

    def factory(self, name: str, profile: ErasureCodeProfile) -> ErasureCodeInterface:
        return self.get(name).make(profile)

    def preload(self, names) -> None:
        for n in names:
            self.get(n)

    def names(self):
        for n in ("jerasure", "isa", "tpu", "lrc", "shec",
                  "regenerating", "example_xor"):
            self._load_builtin(n)
        return sorted(self._plugins)

    # lazy built-in registration (avoids import cycles; analog of the
    # libec_<name>.so lookup path)
    def _load_builtin(self, name: str) -> None:
        if name in self._plugins:
            return
        try:
            self._load_builtin_unchecked(name)
        except ImportError as e:
            self._load_errors[name] = e

    def _load_builtin_unchecked(self, name: str) -> None:
        factory = None
        if name == "jerasure":
            from .jerasure import ErasureCodeJerasure
            factory = ErasureCodeJerasure
        elif name == "isa":
            from .isa import ErasureCodeIsa
            factory = ErasureCodeIsa
        elif name == "tpu":
            from .tpu_plugin import ErasureCodeTpu
            factory = ErasureCodeTpu
        elif name == "lrc":
            from .lrc import ErasureCodeLrc
            factory = ErasureCodeLrc
        elif name == "shec":
            from .shec import ErasureCodeShec
            factory = ErasureCodeShec
        elif name == "regenerating":
            from .regenerating import ErasureCodeRegenerating
            factory = ErasureCodeRegenerating
        elif name == "example_xor":
            from .example_xor import ErasureCodeExampleXor
            factory = ErasureCodeExampleXor
        if factory is not None:
            self._plugins[name] = ErasureCodePlugin(factory)


instance = ErasureCodePluginRegistry()


def create_erasure_code(profile: ErasureCodeProfile) -> ErasureCodeInterface:
    """mon-style entry point (reference mon/OSDMonitor.cc:5335
    get_erasure_code): profile['plugin'] selects the codec."""
    plugin = profile.get("plugin", "jerasure")
    return instance.factory(plugin, profile)
