"""SHEC — shingled erasure code (k, m, c).

Semantics mirror the reference plugin (src/erasure-code/shec/
ErasureCodeShec.{h,cc}): the coding matrix is a Vandermonde RS matrix with
shingled zero runs so each parity covers only a sliding window of the data
chunks (shec_reedsolomon_coding_matrix, :456-523) — trading durability
margin for recovery bandwidth.  The MULTIPLE technique splits parities into
two shingle groups chosen to minimize the average recovery cost
(shec_calc_recovery_efficiency1, :416-455); SINGLE keeps one group.

Decode searches all 2^m parity subsets for the smallest invertible
recovery system (shec_make_decoding_matrix, :524-700), memoized like the
reference's ErasureCodeShecTableCache; minimum_to_decode runs the same
search in prepare mode and returns exactly the chunks that system reads.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..common.lockdep import DebugLock
from ..gf.matrices import gf_invert_matrix, jerasure_reed_sol_van_matrix
from ..gf.tables import gf_mul_scalar
from .base import ErasureCode, SIMD_ALIGN
from .interface import ErasureCodeProfile

SINGLE = 1
MULTIPLE = 0

DEFAULT_K, DEFAULT_M, DEFAULT_C, DEFAULT_W = 4, 3, 2, 8


def _recovery_efficiency1(k: int, m1: int, m2: int, c1: int, c2: int
                          ) -> float:
    """Average chunks read per single-chunk recovery (reference
    shec_calc_recovery_efficiency1)."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [100000000] * k
    r_e1 = 0.0
    for group_m, group_c, base in ((m1, c1, 0), (m2, c2, m1)):
        for rr in range(group_m):
            start = ((rr * k) // group_m) % k
            end = (((rr + group_c) * k) // group_m) % k
            cost = ((rr + group_c) * k) // group_m - (rr * k) // group_m
            cc = start
            first = True
            while first or cc != end:
                first = False
                r_eff_k[cc] = min(r_eff_k[cc], cost)
                cc = (cc + 1) % k
            r_e1 += cost
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_coding_matrix(k: int, m: int, c: int,
                       technique: int) -> np.ndarray:
    """Vandermonde rows with shingled zero windows
    (shec_reedsolomon_coding_matrix)."""
    if technique == SINGLE:
        m1, c1 = 0, 0
    else:
        best = (-1, -1)
        min_r = 100.0
        for c1_try in range(c // 2 + 1):
            for m1_try in range(m + 1):
                c2t, m2t = c - c1_try, m - m1_try
                if m1_try < c1_try or m2t < c2t:
                    continue
                if (m1_try == 0) != (c1_try == 0):
                    continue
                if (m2t == 0) != (c2t == 0):
                    continue
                r = _recovery_efficiency1(k, m1_try, m2t, c1_try, c2t)
                if min_r - r > np.finfo(float).eps and r < min_r:
                    min_r = r
                    best = (c1_try, m1_try)
        c1, m1 = best
    m2, c2 = m - m1, c - c1
    matrix = jerasure_reed_sol_van_matrix(k, m).astype(np.int64)
    for group_m, group_c, base in ((m1, c1, 0), (m2, c2, m1)):
        for rr in range(group_m):
            end = ((rr * k) // group_m) % k
            start = (((rr + group_c) * k) // group_m) % k
            cc = start
            while cc != end:
                matrix[base + rr, cc] = 0
                cc = (cc + 1) % k
    return matrix.astype(np.uint8)


class ErasureCodeShec(ErasureCode):
    """ErasureCodeShecReedSolomonVandermonde equivalent (w=8 lanes)."""

    _table_cache: Dict[Tuple, np.ndarray] = {}
    _decode_cache: Dict[Tuple, Tuple] = {}
    _cache_lock = DebugLock("shec::table_cache")

    def __init__(self):
        super().__init__()
        self.k = DEFAULT_K
        self.m = DEFAULT_M
        self.c = DEFAULT_C
        self.w = DEFAULT_W
        self.technique = MULTIPLE
        self.matrix: Optional[np.ndarray] = None

    # ---- profile ----------------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self._parse(profile)
        self._prepare()
        super().init(profile)
        self.parse_mapping(profile)

    def _parse(self, profile: ErasureCodeProfile) -> None:
        self._init_backend(profile)
        technique = profile.get("technique", "multiple")
        if technique not in ("single", "multiple"):
            raise ValueError(f"technique={technique} must be single or "
                             "multiple")
        self.technique = SINGLE if technique == "single" else MULTIPLE
        has = [x in profile and profile[x] != "" for x in ("k", "m", "c")]
        if not any(has):
            k, m, c = DEFAULT_K, DEFAULT_M, DEFAULT_C
        elif not all(has):
            raise ValueError("(k, m, c) must all be chosen")
        else:
            k = self.to_int("k", profile, DEFAULT_K)
            m = self.to_int("m", profile, DEFAULT_M)
            c = self.to_int("c", profile, DEFAULT_C)
        # reference MDS-safety limits (ErasureCodeShec.cc:309-333)
        if k <= 0 or m <= 0 or c <= 0:
            raise ValueError(f"(k={k}, m={m}, c={c}) must be positive")
        if m < c:
            raise ValueError(f"c={c} must be <= m={m}")
        if k > 12:
            raise ValueError(f"k={k} must be <= 12")
        if k + m > 20:
            raise ValueError(f"k+m={k+m} must be <= 20")
        if k < m:
            raise ValueError(f"m={m} must be <= k={k}")
        self.k, self.m, self.c = k, m, c
        w = self.to_int("w", profile, DEFAULT_W)
        self.w = w if w in (8, 16, 32) else DEFAULT_W
        if self.w != 8:
            raise ValueError("only w=8 is supported (GF(2^8) lanes)")

    def _prepare(self) -> None:
        key = (self.technique, self.k, self.m, self.c, self.w)
        with self._cache_lock:
            mat = self._table_cache.get(key)
            if mat is None:
                mat = shec_coding_matrix(self.k, self.m, self.c,
                                         self.technique)
                self._table_cache[key] = mat
        self.matrix = mat

    # ---- interface --------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return self.k * self.w * 4  # get_alignment (ErasureCodeShec.cc:266)

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        return padded // self.k

    # ---- decoding-system search (shec_make_decoding_matrix) ---------------
    def _make_decoding_system(self, want: List[int], avails: List[int],
                              prepare: bool):
        """Returns (decoding_matrix, dm_row, dm_column, minimum_mask).

        Searches parity subsets (smallest invertible system wins) exactly
        like the reference, including the want-propagation for erased
        parities and the minimum-chunk accounting.
        """
        k, m = self.k, self.m
        matrix = self.matrix
        want = list(want)
        # an erased wanted parity needs its whole window of data chunks
        for i in range(m):
            if want[k + i] and not avails[k + i]:
                for j in range(k):
                    if matrix[i, j] > 0:
                        want[j] = 1
        ckey = (self.technique, self.k, self.m, self.c, self.w,
                tuple(want), tuple(avails))
        with self._cache_lock:
            hit = self._decode_cache.get(ckey)
        if hit is not None:
            return hit

        mindup = k + 1
        minp = k + 1
        best = None
        for pp in range(1 << m):
            p = [i for i in range(m) if pp & (1 << i)]
            if len(p) > minp:
                continue
            if any(not avails[k + i] for i in p):
                continue
            tmprow = [0] * (k + m)
            tmpcolumn = [0] * k
            for i in range(k):
                if want[i] and not avails[i]:
                    tmpcolumn[i] = 1
            for i in p:
                tmprow[k + i] = 1
                for j in range(k):
                    if matrix[i, j] != 0:
                        tmpcolumn[j] = 1
                        if avails[j] == 1:
                            tmprow[j] = 1
            dup_row = sum(tmprow)
            dup_column = sum(tmpcolumn)
            if dup_row != dup_column:
                continue
            dup = dup_row
            if dup == 0:
                mindup = 0
                best = (np.zeros((0, 0), np.uint8), [], [])
                break
            if dup < mindup:
                rows = [i for i in range(k + m) if tmprow[i]]
                cols = [j for j in range(k) if tmpcolumn[j]]
                tmpmat = np.zeros((dup, dup), dtype=np.uint8)
                for ri, i in enumerate(rows):
                    for ci, j in enumerate(cols):
                        tmpmat[ri, ci] = (1 if i == j else 0) if i < k \
                            else matrix[i - k, j]
                try:
                    inv = gf_invert_matrix(tmpmat)
                except (ValueError, ZeroDivisionError, np.linalg.LinAlgError):
                    continue  # singular: det == 0
                mindup = dup
                minp = len(p)
                best = (inv, rows, cols)
        if best is None:
            raise IOError("shec: can't find recovery matrix")

        inv, rows, cols = best
        minimum = [0] * (k + m)
        for r in rows:
            minimum[r] = 1
        for i in range(k):
            if want[i] and avails[i]:
                minimum[i] = 1
        for i in range(m):
            if want[k + i] and avails[k + i] and not minimum[k + i]:
                if any(matrix[i, j] > 0 and not want[j] for j in range(k)):
                    minimum[k + i] = 1
        result = (inv, rows, cols, minimum)
        with self._cache_lock:
            self._decode_cache[ckey] = result
            if len(self._decode_cache) > 2516:  # reference cache bound
                self._decode_cache.pop(next(iter(self._decode_cache)))
        return result

    def _minimum_to_decode(self, want_to_read: Set[int],
                           available_chunks: Set[int]) -> Set[int]:
        n = self.k + self.m
        for i in want_to_read | available_chunks:
            if i < 0 or i >= n:
                raise ValueError(f"chunk id {i} out of range")
        want = [1 if i in want_to_read else 0 for i in range(n)]
        avails = [1 if i in available_chunks else 0 for i in range(n)]
        *_, minimum = self._make_decoding_system(want, avails, prepare=True)
        return {i for i in range(n) if minimum[i] == 1}

    # ---- device backend (selection inherited from ErasureCode) ------------
    def device(self):
        """DeviceRSBackend over the shingled systematic matrix: the same
        MXU bit-matmul the RS stack uses (VERDICT: the whole plugin stack
        hits the device, not just isa/tpu)."""
        dev = getattr(self, "_device", None)
        if dev is None:
            from ..ops.gf_matmul import DeviceRSBackend
            full = np.zeros((self.k + self.m, self.k), dtype=np.uint8)
            full[:self.k] = np.eye(self.k, dtype=np.uint8)
            full[self.k:] = self.matrix
            dev = self._device = DeviceRSBackend(full)
        return dev

    def _decode_sys_bits(self, key, rows_matrix: np.ndarray):
        """Per-signature device expansion of a recovery subsystem."""
        cache = getattr(self, "_sys_bits", None)
        if cache is None:
            cache = self._sys_bits = {}
        hit = cache.get(key)
        if hit is None:
            from ..gf.tables import expand_to_bitmatrix
            import jax.numpy as jnp
            hit = jnp.asarray(
                expand_to_bitmatrix(rows_matrix).astype(np.int8))
            cache[key] = hit
            if len(cache) > 256:
                cache.pop(next(iter(cache)))
        return hit

    # ---- encode/decode ----------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int], encoded) -> None:
        k, m = self.k, self.m
        data = [encoded[self.chunk_index(i)] for i in range(k)]
        if self._use_device():
            coding = self.device().encode(np.stack(data)[None])[0]
            for i in range(m):
                encoded[self.chunk_index(k + i)][...] = coding[i]
            return
        for i in range(m):
            acc = np.zeros_like(data[0])
            for j in range(k):
                coeff = int(self.matrix[i, j])
                if coeff:
                    acc ^= gf_mul_scalar(coeff, data[j])
            encoded[self.chunk_index(k + i)][...] = acc

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(S, k, C) -> (S, m, C): one device call for all stripes."""
        if self._use_device():
            return self.device().encode(np.ascontiguousarray(data))
        s, k, c = data.shape
        out = np.zeros((s, self.m, c), dtype=np.uint8)
        for i in range(self.m):
            for j in range(k):
                coeff = int(self.matrix[i, j])
                if coeff:
                    out[:, i] ^= gf_mul_scalar(coeff, data[:, j])
        return out

    def decode_batch(self, chunks, want) -> dict:
        """Batched recovery: one signature search, one device matvec for
        all stripes (chunks: *physical* id -> (S, C))."""
        k, m = self.k, self.m
        n = k + m
        # translate physical ids to logical matrix rows (mapping= profiles)
        p2l = {self.chunk_index(i): i for i in range(n)}
        l2p = {l: p for p, l in p2l.items()}
        chunks = {p2l[p]: b for p, b in chunks.items()}
        want = [p2l[p] for p in want]
        erased = [1 if (i not in chunks and i in want) else 0
                  for i in range(n)]
        avails = [1 if i in chunks else 0 for i in range(n)]
        out = {i: chunks[i] for i in want if i in chunks}
        if not any(erased):
            return out
        inv, rows, cols, _ = self._make_decoding_system(
            erased, avails, prepare=False)
        some = next(iter(chunks.values()))
        s, c = some.shape
        full = {i: chunks.get(i) for i in range(n)}
        missing_cols = [i for i in range(len(cols))
                        if not avails[cols[i]]]
        if missing_cols:
            src = np.stack([full[r] for r in rows], axis=1)  # (S, dup, C)
            sysrows = inv[missing_cols, :]
            if self._use_device():
                from ..ops.gf_matmul import gf_bit_matmul
                import jax.numpy as jnp
                key = ("d", tuple(rows), tuple(cols), tuple(missing_cols),
                       tuple(erased))
                bits = self._decode_sys_bits(key, sysrows)
                rec = np.asarray(gf_bit_matmul(jnp.asarray(src), bits))
            else:
                rec = np.zeros((s, len(missing_cols), c), dtype=np.uint8)
                for ri in range(len(missing_cols)):
                    for j in range(len(rows)):
                        coeff = int(sysrows[ri, j])
                        if coeff:
                            rec[:, ri] ^= gf_mul_scalar(coeff, src[:, j])
            for idx, ci in enumerate(missing_cols):
                full[cols[ci]] = rec[:, idx]
        # re-encode erased parities from their (recovered) windows only —
        # non-window data may legitimately remain unrecovered
        for i in range(m):
            if not erased[k + i]:
                continue
            acc = np.zeros((s, c), dtype=np.uint8)
            for j in range(k):
                coeff = int(self.matrix[i, j])
                if coeff:
                    acc ^= gf_mul_scalar(coeff, full[j])
            full[k + i] = acc
        for i in want:
            if full[i] is None:
                raise IOError(f"shec: chunk {i} unrecoverable")
            out[i] = full[i]
        return {l2p[i]: b for i, b in out.items()}

    def decode_chunks(self, want_to_read: Set[int], chunks,
                      decoded) -> None:
        k, m = self.k, self.m
        n = k + m
        # buffers arrive keyed by physical id; the matrix works in logical
        # rows (same translation matrix_plugin does) — shared ndarrays
        # keep in-place writes visible to the caller
        p2l = {self.chunk_index(i): i for i in range(n)}
        chunks = {p2l[p]: b for p, b in chunks.items()}
        decoded = {p2l[p]: b for p, b in decoded.items()}
        want_to_read = {p2l[p] for p in want_to_read}
        erased = [1 if (i not in chunks and i in want_to_read) else 0
                  for i in range(n)]
        avails = [1 if i in chunks else 0 for i in range(n)]
        if not any(erased):
            return
        inv, rows, cols, _ = self._make_decoding_system(
            erased, avails, prepare=False)
        dm_size = len(cols)
        # recover erased data chunks in the subsystem
        for i in range(dm_size):
            if not avails[cols[i]]:
                acc = np.zeros_like(decoded[0])
                for j in range(dm_size):
                    coeff = int(inv[i, j])
                    if coeff:
                        acc ^= gf_mul_scalar(coeff, decoded[rows[j]])
                decoded[cols[i]][...] = acc
        # re-encode erased parities from (now complete) data
        for i in range(m):
            if erased[k + i] and not avails[k + i]:
                acc = np.zeros_like(decoded[0])
                for j in range(k):
                    coeff = int(self.matrix[i, j])
                    if coeff:
                        acc ^= gf_mul_scalar(coeff, decoded[j])
                decoded[k + i][...] = acc
