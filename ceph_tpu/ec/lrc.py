"""LRC — layered locally-repairable erasure code.

Semantics mirror the reference plugin (src/erasure-code/lrc/
ErasureCodeLrc.{h,cc}): a code is a stack of layers, each a chunks_map
string over the global chunk positions ('D' data, 'c' coding, '_' absent)
plus a sub-profile instantiating a delegate codec (default jerasure
reed_sol_van) over just that layer's chunks.  Encode runs the layers bottom
up from the first layer containing all wanted chunks
(ErasureCodeLrc.cc:744-780); decode walks layers in reverse, each layer
repairing what it can and feeding recovered chunks to the layers above
(:783-869); minimum_to_decode prefers cheap local-layer repair before
global (:571-742, the whole point of LRC).  The simple k/m/l form
generates the mapping/layers/crush-steps exactly as parse_kml does
(:294-400).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Set

import numpy as np

from ..crush.constants import (
    CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_SET_CHOOSELEAF_TRIES, CRUSH_RULE_SET_CHOOSE_TRIES,
    CRUSH_RULE_TAKE, PG_POOL_TYPE_ERASURE,
)
from ..crush.types import Rule, RuleStep
from .base import ErasureCode
from .interface import ErasureCodeProfile

DEFAULT_KML = -1


class Layer:
    def __init__(self, chunks_map: str):
        self.chunks_map = chunks_map
        self.profile: ErasureCodeProfile = {}
        self.data: List[int] = []
        self.coding: List[int] = []
        self.chunks: List[int] = []
        self.chunks_as_set: Set[int] = set()
        self.erasure_code = None


class RuleStepSpec:
    def __init__(self, op: str, type: str, n: int):
        self.op = op
        self.type = type
        self.n = n


class ErasureCodeLrc(ErasureCode):
    def __init__(self):
        super().__init__()
        self.layers: List[Layer] = []
        self.chunk_count_ = 0
        self.data_chunk_count_ = 0
        self.rule_steps: List[RuleStepSpec] = \
            [RuleStepSpec("chooseleaf", "host", 0)]

    # ---- profile parsing --------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        profile = dict(profile)
        self._backend_name = profile.get("backend", "")
        self._parse_kml(profile)
        self._parse_rule(profile)
        layers_str = profile.get("layers")
        if not layers_str:
            raise ValueError(f"could not find 'layers' in {profile}")
        try:
            description = json.loads(layers_str)
        except json.JSONDecodeError as e:
            raise ValueError(f"failed to parse layers={layers_str!r}: {e}")
        if not isinstance(description, list):
            raise ValueError(f"layers={layers_str!r} must be a JSON array")
        self._layers_parse(description)
        mapping = profile.get("mapping")
        if not mapping:
            raise ValueError(f"the 'mapping' profile is missing")
        self.data_chunk_count_ = sum(1 for c in mapping if c == "D")
        self.chunk_count_ = len(mapping)
        self._layers_init()
        self._layers_sanity_checks()
        # kml-generated parameters are not exposed to the caller
        # (ErasureCodeLrc.cc:543-549)
        if profile.get("l") and profile["l"] != str(DEFAULT_KML):
            public = dict(profile)
            public.pop("mapping", None)
            public.pop("layers", None)
        else:
            public = profile
        super().init(public)
        self.parse_mapping(profile)

    def _parse_kml(self, profile: Dict[str, str]) -> None:
        k = self.to_int("k", profile, DEFAULT_KML)
        m = self.to_int("m", profile, DEFAULT_KML)
        l = self.to_int("l", profile, DEFAULT_KML)
        if k == DEFAULT_KML and m == DEFAULT_KML and l == DEFAULT_KML:
            return
        if DEFAULT_KML in (k, m, l):
            raise ValueError("all of k, m, l must be set or none of them")
        for generated in ("mapping", "layers", "crush-steps"):
            if generated in profile:
                raise ValueError(
                    f"the {generated} parameter cannot be set "
                    "when k, m, l are set")
        if (k + m) % l:
            raise ValueError("k + m must be a multiple of l")
        groups = (k + m) // l
        if k % groups:
            raise ValueError("k must be a multiple of (k + m) / l")
        if m % groups:
            raise ValueError("m must be a multiple of (k + m) / l")
        kg, mg = k // groups, m // groups
        profile["mapping"] = ("D" * kg + "_" * mg + "_") * groups
        layers = []
        # global layer
        layers.append([("D" * kg + "c" * mg + "_") * groups, ""])
        # local layers
        for i in range(groups):
            s = ""
            for j in range(groups):
                s += ("D" * l + "c") if i == j else ("_" * (l + 1))
            layers.append([s, ""])
        profile["layers"] = json.dumps(layers)
        locality = profile.get("crush-locality", "")
        failure_domain = profile.get("crush-failure-domain", "host")
        if locality:
            self.rule_steps = [RuleStepSpec("choose", locality, groups),
                               RuleStepSpec("chooseleaf", failure_domain,
                                            l + 1)]
        elif failure_domain:
            self.rule_steps = [RuleStepSpec("chooseleaf", failure_domain, 0)]

    def _parse_rule(self, profile: Dict[str, str]) -> None:
        self.rule_root = profile.get("crush-root", "default")
        self.rule_device_class = profile.get("crush-device-class", "")
        steps = profile.get("crush-steps")
        if steps:
            try:
                arr = json.loads(steps)
            except json.JSONDecodeError as e:
                raise ValueError(f"failed to parse crush-steps: {e}")
            self.rule_steps = [RuleStepSpec(op, t, int(n))
                               for op, t, n in arr]

    def _layers_parse(self, description) -> None:
        self.layers = []
        for pos, entry in enumerate(description):
            if not isinstance(entry, list) or not entry:
                raise ValueError(
                    f"element {pos} of layers must be a JSON array")
            if not isinstance(entry[0], str):
                raise ValueError(
                    f"the first element of entry {pos} must be a string")
            layer = Layer(entry[0])
            if len(entry) > 1:
                cfg = entry[1]
                if isinstance(cfg, str):
                    layer.profile = dict(
                        kv.split("=", 1) for kv in cfg.split() if "=" in kv)
                elif isinstance(cfg, dict):
                    layer.profile = {k: str(v) for k, v in cfg.items()}
                else:
                    raise ValueError(
                        f"entry {pos} config must be a string or object")
            self.layers.append(layer)

    def _layers_init(self) -> None:
        from .registry import instance as registry
        for layer in self.layers:
            for position, c in enumerate(layer.chunks_map):
                if c == "D":
                    layer.data.append(position)
                if c == "c":
                    layer.coding.append(position)
                if c in ("c", "D"):
                    layer.chunks_as_set.add(position)
            layer.chunks = layer.data + layer.coding
            layer.profile.setdefault("k", str(len(layer.data)))
            layer.profile.setdefault("m", str(len(layer.coding)))
            layer.profile.setdefault("plugin", "jerasure")
            layer.profile.setdefault("technique", "reed_sol_van")
            # the parent's backend choice flows into every layer so the
            # whole layered code runs on the device path (VERDICT #7)
            if self._backend_name:
                layer.profile.setdefault("backend", self._backend_name)
            layer.erasure_code = registry.factory(
                layer.profile["plugin"], layer.profile)

    def _layers_sanity_checks(self) -> None:
        if len(self.layers) < 1:
            raise ValueError("layers parameter needs at least one layer")
        for layer in self.layers:
            if len(layer.chunks_map) != self.chunk_count_:
                raise ValueError(
                    f"chunks_map {layer.chunks_map!r} must be "
                    f"{self.chunk_count_} characters long")

    # ---- interface --------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.chunk_count_

    def get_data_chunk_count(self) -> int:
        return self.data_chunk_count_

    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].erasure_code.get_chunk_size(object_size)

    def create_rule(self, name: str, crush) -> int:
        """Rule from the crush-steps specs (ErasureCodeLrc.cc:46-115)."""
        if crush.rule_exists(name):
            return -17  # EEXIST
        if not crush.name_exists(self.rule_root):
            return -2   # ENOENT
        root = crush.get_item_id(self.rule_root)
        if self.rule_device_class:
            if not crush.class_exists(self.rule_device_class):
                return -2
            c = crush.get_or_create_class_id(self.rule_device_class)
            shadow = crush.class_bucket.get(root, {}).get(c)
            if shadow is None:
                return -22
            root = shadow
        steps = [RuleStep(CRUSH_RULE_SET_CHOOSELEAF_TRIES, 5, 0),
                 RuleStep(CRUSH_RULE_SET_CHOOSE_TRIES, 100, 0),
                 RuleStep(CRUSH_RULE_TAKE, root, 0)]
        for s in self.rule_steps:
            op = CRUSH_RULE_CHOOSELEAF_INDEP if s.op == "chooseleaf" \
                else CRUSH_RULE_CHOOSE_INDEP
            t = crush.get_type_id(s.type)
            if t < 0:
                return -22
            steps.append(RuleStep(op, s.n, t))
        steps.append(RuleStep(CRUSH_RULE_EMIT, 0, 0))
        rule = Rule(steps=steps, ruleset=-1, type=PG_POOL_TYPE_ERASURE,
                    min_size=3, max_size=self.get_chunk_count())
        rno = crush.add_rule(rule, name)
        rule.ruleset = rno
        return rno

    # ---- minimum_to_decode (the local-repair search) ----------------------
    def _minimum_to_decode(self, want_to_read: Set[int],
                           available_chunks: Set[int]) -> Set[int]:
        erasures_total = set()
        erasures_not_recovered = set()
        erasures_want = set()
        for i in range(self.get_chunk_count()):
            if i not in available_chunks:
                erasures_total.add(i)
                erasures_not_recovered.add(i)
                if i in want_to_read:
                    erasures_want.add(i)

        # case 1: nothing wanted is missing
        if not erasures_want:
            return set(want_to_read)

        # case 2: recover wanted erasures with as few chunks as possible,
        # scanning layers bottom-up (local layers are last == first here)
        minimum: Set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want_to_read & layer.chunks_as_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                layer_minimum = layer_want
            else:
                erasures = layer.chunks_as_set & erasures_not_recovered
                if len(erasures) > \
                        layer.erasure_code.get_coding_chunk_count():
                    # too many erasures for this layer: hope upper layers help
                    continue
                layer_minimum = layer.chunks_as_set - erasures_not_recovered
                for j in erasures:
                    erasures_not_recovered.discard(j)
                    erasures_want.discard(j)
            minimum |= layer_minimum
        if not erasures_want:
            minimum |= set(want_to_read)
            minimum -= erasures_total
            return minimum

        # case 3: recover everything recoverable, hoping it unlocks uppers
        erasures_total = {i for i in range(self.get_chunk_count())
                          if i not in available_chunks}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures_total
            if not layer_erasures:
                continue
            if len(layer_erasures) <= \
                    layer.erasure_code.get_coding_chunk_count():
                erasures_total -= layer_erasures
        if not erasures_total:
            return set(available_chunks)

        raise IOError(
            f"not enough chunks in {sorted(available_chunks)} to read "
            f"{sorted(want_to_read)}")

    # ---- encode/decode ----------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int], encoded) -> None:
        top = len(self.layers)
        for layer in reversed(self.layers):
            top -= 1
            if want_to_encode <= layer.chunks_as_set:
                break
        for layer in self.layers[top:]:
            layer_want: Set[int] = set()
            layer_encoded: Dict[int, np.ndarray] = {}
            for j, c in enumerate(layer.chunks):
                layer_encoded[j] = encoded[c]
                if c in want_to_encode:
                    layer_want.add(j)
            layer.erasure_code.encode_chunks(layer_want, layer_encoded)
            for j, c in enumerate(layer.chunks):
                encoded[c] = layer_encoded[j]

    def encode_batch_full(self, stripes: np.ndarray) -> np.ndarray:
        """(S, k, C) logical data stripes -> (S, n, C) ALL chunks in
        physical position order, every layer's coding computed in one
        batched (device) call per layer (the ECUtil batch entry for
        mapped codes)."""
        s, k, c = stripes.shape
        assert k == self.data_chunk_count_
        n = self.chunk_count_
        buf = np.zeros((s, n, c), dtype=np.uint8)
        for i in range(k):
            buf[:, self.chunk_index(i), :] = stripes[:, i, :]
        for layer in self.layers:
            delegate = layer.erasure_code
            data = np.ascontiguousarray(buf[:, layer.data, :])
            if hasattr(delegate, "encode_batch"):
                coding = delegate.encode_batch(data)
            else:  # pragma: no cover - all shipped delegates batch
                coding = np.stack([
                    np.stack([v for _, v in sorted(delegate.encode(
                        set(range(len(layer.chunks))),
                        data[si].reshape(-1).tobytes()).items())])
                    [len(layer.data):]
                    for si in range(s)])
            for idx, pos in enumerate(layer.coding):
                buf[:, pos, :] = coding[:, idx, :]
        return buf

    def decode_batch(self, chunks, want) -> Dict[int, np.ndarray]:
        """Batched layer-walking recovery (chunks: physical id -> (S, C));
        each layer repairs what it can through its delegate's batched
        decode and feeds recovered chunks upward — the decode_chunks walk
        (ErasureCodeLrc.cc:783-869) vectorized over stripes."""
        n = self.get_chunk_count()
        full: Dict[int, Optional[np.ndarray]] = {
            i: chunks.get(i) for i in range(n)}
        erasures = {i for i in range(n) if full[i] is None}
        want_missing = erasures & set(want)
        if not want_missing:
            return {i: full[i] for i in want}
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if not layer_erasures:
                continue
            if len(layer_erasures) > \
                    layer.erasure_code.get_coding_chunk_count():
                continue
            delegate = layer.erasure_code
            layer_chunks = {j: full[cpos]
                            for j, cpos in enumerate(layer.chunks)
                            if cpos not in erasures}
            want_js = [j for j, cpos in enumerate(layer.chunks)
                       if cpos in erasures]
            try:
                got = delegate.decode_batch(layer_chunks, want_js)
            except IOError:
                continue
            for j, cpos in enumerate(layer.chunks):
                if cpos in erasures and j in got:
                    full[cpos] = got[j]
                    erasures.discard(cpos)
            want_missing = erasures & set(want)
            if not want_missing:
                break
        if want_missing:
            raise IOError(f"unable to read {sorted(want_missing)}")
        return {i: full[i] for i in want}

    def decode_chunks(self, want_to_read: Set[int], chunks,
                      decoded) -> None:
        available = {i for i in range(self.get_chunk_count()) if i in chunks}
        erasures = {i for i in range(self.get_chunk_count())
                    if i not in chunks}
        # start from the actual outstanding erasures so a decode where every
        # layer skips (insufficient chunks) fails loudly instead of passing
        # zero-filled buffers through (the reference returns 0 there because
        # minimum_to_decode is assumed to have vetted the read)
        want_to_read_erasures: Set[int] = erasures & set(want_to_read)
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_as_set & erasures
            if len(layer_erasures) > \
                    layer.erasure_code.get_coding_chunk_count():
                continue  # too many erasures for this layer
            if not layer_erasures:
                continue  # all available already
            layer_want: Set[int] = set()
            layer_chunks: Dict[int, np.ndarray] = {}
            layer_decoded: Dict[int, np.ndarray] = {}
            for j, c in enumerate(layer.chunks):
                # chunks recovered by previous layers flow in via *decoded*
                if c not in erasures:
                    layer_chunks[j] = decoded[c]
                if c in want_to_read:
                    layer_want.add(j)
                layer_decoded[j] = decoded[c]
            layer.erasure_code.decode_chunks(layer_want, layer_chunks,
                                             layer_decoded)
            for j, c in enumerate(layer.chunks):
                decoded[c] = layer_decoded[j]
                erasures.discard(c)
            want_to_read_erasures = erasures & want_to_read
            if not want_to_read_erasures:
                break
        if want_to_read_erasures:
            raise IOError(
                f"unable to read {sorted(want_to_read_erasures)}")
