"""ErasureCode base class: padding, chunk mapping, default minimum_to_decode.

Mirrors the reference base-class semantics (src/erasure-code/ErasureCode.cc):

- ``encode_prepare`` splits an object into k chunks of
  ``get_chunk_size(len)`` bytes, zero-padding the tail chunks
  (ErasureCode.cc:138-173, SIMD_ALIGN=32 at :29).
- ``encode`` = prepare -> encode_chunks -> prune unwanted
  (ErasureCode.cc:175-191).
- ``_decode`` passes through when everything wanted is available, otherwise
  allocates missing buffers and calls decode_chunks (ErasureCode.cc:199-232).
- default ``_minimum_to_decode`` = wanted set if fully available, else the
  first k available chunks in ascending order (ErasureCode.cc:90-124).
- ``chunk_index`` applies the optional ``mapping=`` profile permutation
  (ErasureCode.cc:258-277).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .interface import ErasureCodeInterface, ErasureCodeProfile

SIMD_ALIGN = 32

DEFAULT_RULE_ROOT = "default"
DEFAULT_RULE_FAILURE_DOMAIN = "host"


def as_chunk(buf) -> np.ndarray:
    if isinstance(buf, np.ndarray):
        assert buf.dtype == np.uint8
        return buf
    return np.frombuffer(bytes(buf), dtype=np.uint8)


class ErasureCode(ErasureCodeInterface):
    def __init__(self):
        self._profile: ErasureCodeProfile = {}
        self.chunk_mapping: List[int] = []
        self.rule_root = DEFAULT_RULE_ROOT
        self.rule_failure_domain = DEFAULT_RULE_FAILURE_DOMAIN
        self.rule_device_class = ""
        self.backend_name = "host"

    # ---- profile handling -------------------------------------------------
    def init(self, profile: ErasureCodeProfile) -> None:
        self.rule_root = profile.get("crush-root", DEFAULT_RULE_ROOT)
        self.rule_failure_domain = profile.get(
            "crush-failure-domain", DEFAULT_RULE_FAILURE_DOMAIN)
        self.rule_device_class = profile.get("crush-device-class", "")
        self._profile = dict(profile)

    # ---- execution backend selection (host | tpu | auto) -------------------
    def _init_backend(self, profile: ErasureCodeProfile) -> None:
        self.backend_name = profile.get("backend", "auto")
        if self.backend_name not in ("host", "tpu", "auto"):
            raise ValueError(
                f"backend={self.backend_name} not in host|tpu|auto")

    def _use_device(self) -> bool:
        if self.backend_name == "host":
            return False
        if self.backend_name == "tpu":
            return True
        from ..ops.gf_matmul import device_available
        return device_available()

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    @staticmethod
    def to_int(name: str, profile: ErasureCodeProfile, default: int) -> int:
        v = profile.get(name, None)
        if v is None or v == "":
            return int(default)
        try:
            return int(v)
        except ValueError as e:
            raise ValueError(f"{name}={v} is not a valid number") from e

    @staticmethod
    def to_bool(name: str, profile: ErasureCodeProfile, default: bool) -> bool:
        v = profile.get(name, None)
        if v is None or v == "":
            return default
        return str(v).lower() in ("true", "1", "yes", "on")

    def parse_mapping(self, profile: ErasureCodeProfile) -> None:
        m = profile.get("mapping")
        if m:
            # mapping string like "DD_D...": logical data chunks land on the
            # 'D' positions, logical coding chunks on the remaining positions
            # in order (reference ErasureCode.cc to_mapping)
            data_pos = [i for i, c in enumerate(m) if c == "D"]
            other_pos = [i for i, c in enumerate(m) if c != "D"]
            self.chunk_mapping = data_pos + other_pos

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if len(self.chunk_mapping) > i else i

    def get_chunk_mapping(self) -> Sequence[int]:
        return self.chunk_mapping

    # ---- crush rule -------------------------------------------------------
    def create_rule(self, name: str, crush) -> int:
        from ..crush.constants import PG_POOL_TYPE_ERASURE
        ruleid = crush.add_simple_rule(
            name, self.rule_root, self.rule_failure_domain,
            self.rule_device_class, "indep",
            rule_type=PG_POOL_TYPE_ERASURE)
        if ruleid >= 0:
            crush.set_rule_mask_max_size(ruleid, self.get_chunk_count())
        return ruleid

    @staticmethod
    def sanity_check_k(k: int) -> None:
        if k < 2:
            raise ValueError(f"k={k} must be >= 2")

    # ---- minimum_to_decode ------------------------------------------------
    def _minimum_to_decode(
        self, want_to_read: Set[int], available_chunks: Set[int]
    ) -> Set[int]:
        if want_to_read <= available_chunks:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available_chunks) < k:
            raise IOError("not enough chunks to decode")
        return set(sorted(available_chunks)[:k])

    def minimum_to_decode(
        self, want_to_read: Set[int], available: Set[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        ids = self._minimum_to_decode(want_to_read, available)
        sub = [(0, self.get_sub_chunk_count())]
        return {i: list(sub) for i in sorted(ids)}

    def minimum_to_decode_with_cost(
        self, want_to_read: Set[int], available: Dict[int, int]
    ) -> Set[int]:
        return self._minimum_to_decode(want_to_read, set(available))

    # ---- encode -----------------------------------------------------------
    def encode_prepare(self, raw: np.ndarray) -> Dict[int, np.ndarray]:
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        blocksize = self.get_chunk_size(len(raw))
        if blocksize == 0:  # empty object: k+m empty chunks
            return {self.chunk_index(i): np.zeros(0, dtype=np.uint8)
                    for i in range(k + m)}
        padded_chunks = k - len(raw) // blocksize
        encoded: Dict[int, np.ndarray] = {}
        for i in range(k - padded_chunks):
            encoded[self.chunk_index(i)] = np.array(
                raw[i * blocksize:(i + 1) * blocksize])
        if padded_chunks:
            remainder = len(raw) - (k - padded_chunks) * blocksize
            buf = np.zeros(blocksize, dtype=np.uint8)
            buf[:remainder] = raw[(k - padded_chunks) * blocksize:]
            encoded[self.chunk_index(k - padded_chunks)] = buf
            for i in range(k - padded_chunks + 1, k):
                encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        for i in range(k, k + m):
            encoded[self.chunk_index(i)] = np.zeros(blocksize, dtype=np.uint8)
        return encoded

    def encode(self, want_to_encode: Set[int], data) -> Dict[int, np.ndarray]:
        raw = as_chunk(data)
        encoded = self.encode_prepare(raw)
        self.encode_chunks(want_to_encode, encoded)
        for i in range(self.get_chunk_count()):
            if i not in want_to_encode:
                encoded.pop(i, None)
        return encoded

    # ---- decode -----------------------------------------------------------
    def _decode(
        self, want_to_read: Set[int], chunks: Dict[int, np.ndarray]
    ) -> Dict[int, np.ndarray]:
        have = set(chunks)
        if want_to_read <= have:
            return {i: chunks[i] for i in want_to_read}
        k = self.get_data_chunk_count()
        m = self.get_coding_chunk_count()
        if not chunks:
            raise IOError("no chunks to decode from")
        # insufficiency is the codec's call: layered codes (lrc) can repair
        # from fewer than k global chunks (reference ErasureCode.cc:199-232
        # delegates to decode_chunks)
        blocksize = len(next(iter(chunks.values())))
        decoded: Dict[int, np.ndarray] = {}
        for i in range(k + m):
            if i in chunks:
                decoded[i] = np.array(chunks[i])
            else:
                decoded[i] = np.zeros(blocksize, dtype=np.uint8)
        self.decode_chunks(want_to_read, chunks, decoded)
        return decoded

    def decode(
        self, want_to_read: Set[int], chunks: Dict[int, np.ndarray], chunk_size: int = 0
    ) -> Dict[int, np.ndarray]:
        return self._decode(want_to_read, {i: as_chunk(c) for i, c in chunks.items()})

    def decode_concat(self, chunks: Dict[int, np.ndarray]) -> bytes:
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = self.decode(want, chunks)
        out = b"".join(
            decoded[self.chunk_index(i)].tobytes() for i in range(k))
        return out

    # subclasses must implement:
    #   get_chunk_count / get_data_chunk_count / get_chunk_size
    #   encode_chunks / decode_chunks
    def encode_chunks(self, want_to_encode, encoded):
        raise NotImplementedError

    def decode_chunks(self, want_to_read, chunks, decoded):
        raise NotImplementedError
