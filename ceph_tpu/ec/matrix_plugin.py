"""Shared base for matrix-RS erasure-code plugins (isa / jerasure / tpu).

Wires a ``MatrixRSCodec`` (host oracle) and optionally the TPU device backend
(ceph_tpu.ops.gf_matmul) into the ErasureCode ABI.  The execution backend is
selected by the profile key ``backend=host|tpu|auto`` (auto = TPU when a
device is usable, else host).  Both backends are byte-identical by
construction and by test.
"""
from __future__ import annotations

from typing import Dict, Set

import numpy as np

from .base import ErasureCode
from .rs_codec import MatrixRSCodec


class ErasureCodeMatrixRS(ErasureCode):
    """A systematic matrix code with k data + m coding chunks."""

    def __init__(self):
        super().__init__()
        self.k = 0
        self.m = 0
        self.codec: MatrixRSCodec | None = None
        self.backend_name = "host"
        self._device = None  # lazy DeviceRSBackend

    # -- sizing -------------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return 32

    def get_chunk_size(self, object_size: int) -> int:
        # isa-style: ceil(object_size / k) rounded up to alignment
        # (reference ErasureCodeIsa.cc:65-78)
        alignment = self.get_alignment()
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    # -- backend ------------------------------------------------------------
    def _init_backend(self, profile) -> None:
        self.backend_name = profile.get("backend", "auto")
        if self.backend_name not in ("host", "tpu", "auto"):
            raise ValueError(f"backend={self.backend_name} not in host|tpu|auto")

    def device(self):
        if self._device is None:
            from ..ops.gf_matmul import DeviceRSBackend
            self._device = DeviceRSBackend(self.codec.matrix)
        return self._device

    def _use_device(self) -> bool:
        if self.backend_name == "host":
            return False
        if self.backend_name == "tpu":
            return True
        from ..ops.gf_matmul import device_available
        return device_available()

    def _device_encode(self, data: np.ndarray) -> np.ndarray:
        """(k, C) -> (m, C) on the device backend; codecs with a virtual
        layout (bitmatrix packet codes) override."""
        return self.device().encode(data[None])[0]

    # -- encode/decode ------------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, np.ndarray]) -> None:
        # buffers are keyed by *physical* index (chunk_index); the codec works
        # in logical rows.  mapping= profiles permute the two.
        data = np.stack([encoded[self.chunk_index(i)] for i in range(self.k)])
        if self._use_device():
            coding = self._device_encode(data)
        else:
            coding = self.codec.encode(data)
        for i in range(self.m):
            # fill in place so callers holding references see the parity
            encoded[self.chunk_index(self.k + i)][...] = coding[i]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        n = self.k + self.m
        phys_to_logical = {self.chunk_index(i): i for i in range(n)}
        logical_chunks = {phys_to_logical[p]: buf for p, buf in chunks.items()}
        want = sorted(phys_to_logical[p] for p in range(n)
                      if p in want_to_read or p not in chunks)
        out = self.codec.decode(logical_chunks, want)
        for i, buf in out.items():
            decoded[self.chunk_index(i)][...] = buf
