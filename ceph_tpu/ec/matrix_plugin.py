"""Shared base for matrix-RS erasure-code plugins (isa / jerasure / tpu).

Wires a ``MatrixRSCodec`` (host oracle) and optionally the TPU device backend
(ceph_tpu.ops.gf_matmul) into the ErasureCode ABI.  The execution backend is
selected by the profile key ``backend=host|tpu|auto`` (auto = TPU when a
device is usable, else host).  Both backends are byte-identical by
construction and by test.

Robustness (docs/ROBUSTNESS.md): every device-path call runs through
the fault guard — injection site, bounded retry with backoff, watchdog
deadline, circuit-breaker accounting — and degrades to the
byte-identical host matrix path on ``DeviceUnavailable``, so a device
failure costs throughput, never a client op.  A tripped breaker makes
``_use_device`` route the whole signature to the host path until a
half-open probe restores it.
"""
from __future__ import annotations

from typing import Dict, Set

import numpy as np

from ..fault import (DeviceUnavailable, fault_perf_counters, g_breakers,
                     l_fault_cpu_fallbacks, run_device_call)
from ..trace import g_tracer
from .base import ErasureCode
from .rs_codec import MatrixRSCodec


class ErasureCodeMatrixRS(ErasureCode):
    """A systematic matrix code with k data + m coding chunks."""

    # False when the device backend's data layout differs from whole
    # chunks (bitmatrix packet codes): decode then uses the host path
    _device_decode_supported = True

    # matrix codes are stripe- and block-independent, so the dispatch
    # scheduler (ceph_tpu/dispatch) may coalesce signature-equal
    # requests into one padded device call
    dispatch_batchable = True
    # codecs with byte-identical matrix semantics share a family so
    # their requests group cross-plugin (tpu == isa by construction);
    # None = the concrete class name
    signature_family: "str | None" = None

    # the mesh runtime (ceph_tpu/mesh) may shard this codec's batched
    # encode over the batch axis: true only when encode_batch IS the
    # plain row-independent bit-matmul on raw (S, k, C) chunks.
    # Codecs whose device path transforms the data layout first
    # (jerasure bitmatrix/word codes) override this to False — the
    # mesh plan models the plain matmul only, so sharding a
    # transformed layout would corrupt output.
    @property
    def mesh_row_shardable(self) -> bool:
        return True

    # the mesh runtime may also shard this codec's DECODE: true when
    # decode_batch's device path is the plain inverted-survivor-matrix
    # bit-matmul on raw (S, n_src, C) stacks.  Follows the encode gate
    # for matrix-RS codes (a transformed layout corrupts either way);
    # the regenerating family overrides — its encode is full-output
    # but its ≥d decode and repair solve ARE plain survivor matmuls.
    @property
    def mesh_decode_shardable(self) -> bool:
        return self.mesh_row_shardable and self._device_decode_supported

    def codec_signature(self):
        """The dispatcher's grouping key: everything the coding matrix
        is derived from.  Two impls with equal signatures encode and
        decode byte-identically, so their requests may share a call."""
        return (self.signature_family or type(self).__name__,
                self.k, self.m,
                getattr(self, "technique", ""),
                getattr(self, "w", 0),
                getattr(self, "packetsize", 0),
                tuple(self.chunk_mapping))

    def __init__(self):
        super().__init__()
        self.k = 0
        self.m = 0
        self.codec: MatrixRSCodec | None = None
        self.backend_name = "host"
        self._device = None  # lazy DeviceRSBackend

    # -- sizing -------------------------------------------------------------
    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_alignment(self) -> int:
        return 32

    def get_chunk_size(self, object_size: int) -> int:
        # isa-style: ceil(object_size / k) rounded up to alignment
        # (reference ErasureCodeIsa.cc:65-78)
        alignment = self.get_alignment()
        chunk_size = (object_size + self.k - 1) // self.k
        modulo = chunk_size % alignment
        if modulo:
            chunk_size += alignment - modulo
        return chunk_size

    # -- backend (selection inherited from ErasureCode) ----------------------
    def _use_device(self) -> bool:
        """Backend selection gated by the signature's circuit breaker:
        an open breaker routes every call to the host matrix path
        (byte-identical by construction) until the half-open probe
        window lets a device call through to test recovery."""
        if not super()._use_device():
            return False
        return g_breakers.allow_device(self.codec_signature())

    def _note_cpu_fallback(self, site: str) -> None:
        fault_perf_counters().inc(l_fault_cpu_fallbacks)
        g_tracer.event("cpu_fallback", site=site)

    def device(self):
        if self._device is None:
            from ..ops.gf_matmul import DeviceRSBackend
            self._device = DeviceRSBackend(self.codec.matrix)
        return self._device

    def _device_encode(self, data: np.ndarray) -> np.ndarray:
        """(k, C) -> (m, C) on the device backend; codecs with a virtual
        layout (bitmatrix packet codes) override."""
        return self.device().encode(data[None])[0]

    def _device_encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(S, k, C) -> (S, m, C) on the device backend."""
        return self.device().encode(data)

    def _stripe_block(self) -> int:
        """Per-stripe chunk-size granularity required for batch flattening
        (1 = pointwise byte codes; jerasure overrides for packet/word
        layouts whose blocks must not span stripe boundaries)."""
        return 1

    # -- batched stripe API (ECUtil striping, osd/ECUtil.cc:120-159) --------
    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(S, k, C) uint8 -> (S, m, C) coding chunks; ONE device call for
        all S stripes (the whole point vs the reference's stripe loop).
        Host fallback flattens stripes into the byte axis — valid because
        each stripe's C is a whole number of code blocks."""
        s, k, c = data.shape
        if c % self._stripe_block():
            # flattening would let code blocks span stripe boundaries and
            # S*C could mask the misalignment — reject it loudly (ECUtil's
            # get_chunk_size always produces aligned stripes)
            raise ValueError(
                f"stripe chunk size {c} is not a multiple of the code "
                f"block ({self._stripe_block()} bytes)")
        from ..common.kernel_trace import g_kernel_timer
        if self._use_device():
            data_c = np.ascontiguousarray(data)
            try:
                return run_device_call(
                    self.codec_signature(), "device.encode_batch",
                    lambda: g_kernel_timer.timed(
                        "ec_encode_batch", self._device_encode_batch,
                        data_c))
            except DeviceUnavailable:
                self._note_cpu_fallback("device.encode_batch")

        def host():
            flat = np.ascontiguousarray(data.transpose(1, 0, 2)).reshape(
                k, s * c)
            coding = self.codec.encode(flat)
            return np.ascontiguousarray(
                coding.reshape(self.m, s, c).transpose(1, 0, 2))

        return g_kernel_timer.timed("ec_encode_batch_host", host)

    def decode_batch(self, chunks: Dict[int, np.ndarray],
                     want) -> Dict[int, np.ndarray]:
        """Reconstruct chunk ids in *want* for a whole batch.

        chunks maps chunk id -> (S, C); all stripes share one erasure
        signature (the recovery shape: one failed shard, many stripes).
        """
        if len(chunks) < self.k:
            raise IOError(
                f"need at least k={self.k} chunks, have {len(chunks)}")
        from .rs_codec import plan_decode
        # callers key by physical chunk id; the codec works in logical rows
        n = self.k + self.m
        p2l = {self.chunk_index(i): i for i in range(n)}
        l2p = {l: p for p, l in p2l.items()}
        chunks = {p2l[p]: b for p, b in chunks.items()}
        want_phys = list(want)
        want = [p2l[p] for p in want_phys]
        srcs, want_data, want_coding, missing_data = plan_decode(
            self.k, chunks, want)

        # meshed degraded read: the survivor matmul shards across the
        # chip mesh (rateless-protected, its own mesh.decode_batch
        # guard) BEFORE the single-device guard below — computed here,
        # outside device_path, so the two fault guards never nest.
        # None (mesh off, codec not shardable, or guard exhausted)
        # keeps today's single-device path by construction.
        mesh_rec = None
        if missing_data and self._use_device() and \
                self._device_decode_supported:
            from ..mesh import g_mesh
            survivors = np.stack([chunks[i] for i in srcs], axis=1)
            mesh_rec = g_mesh.decode_stacked(self, survivors, srcs,
                                             missing_data)

        def device_path() -> Dict[int, np.ndarray]:
            out: Dict[int, np.ndarray] = {i: chunks[i] for i in want
                                          if i in chunks}
            dev = self.device()
            by_id: Dict[int, np.ndarray] = {}
            if missing_data:
                if mesh_rec is not None:
                    rec = mesh_rec
                else:
                    survivors = np.stack([chunks[i] for i in srcs],
                                         axis=1)
                    rec = dev.decode_data(survivors, srcs,
                                          missing_data)
                by_id = {i: rec[:, idx]
                         for idx, i in enumerate(missing_data)}
                for i in want_data:
                    out[i] = by_id[i]
            if want_coding:
                data_full = np.stack(
                    [chunks[i] if i in chunks else by_id[i]
                     for i in range(self.k)], axis=1)
                coding = dev.encode(data_full)
                for i in want_coding:
                    out[i] = coding[:, i - self.k]
            return {l2p[i]: b for i, b in out.items()}

        if self._use_device() and self._device_decode_supported and \
                hasattr(self.device(), "decode_data"):
            try:
                return run_device_call(self.codec_signature(),
                                       "device.decode_batch",
                                       device_path)
            except DeviceUnavailable:
                self._note_cpu_fallback("device.decode_batch")
        # host: flatten stripes into the byte axis (blocks never span
        # stripes because each stripe's C is a whole number of blocks)
        out = {i: chunks[i] for i in want if i in chunks}
        some = next(iter(chunks.values()))
        s, c = some.shape
        if c % self._stripe_block():
            raise ValueError(
                f"stripe chunk size {c} is not a multiple of the code "
                f"block ({self._stripe_block()} bytes)")
        flat = {i: np.ascontiguousarray(b).reshape(s * c)
                for i, b in chunks.items()}
        dec = self.codec.decode(flat, list(want))
        for i in want:
            out[i] = np.ascontiguousarray(dec[i]).reshape(s, c)
        return {l2p[i]: b for i, b in out.items()}

    # -- encode/decode ------------------------------------------------------
    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, np.ndarray]) -> None:
        # buffers are keyed by *physical* index (chunk_index); the codec works
        # in logical rows.  mapping= profiles permute the two.
        data = np.stack([encoded[self.chunk_index(i)] for i in range(self.k)])
        if self._use_device():
            try:
                coding = run_device_call(
                    self.codec_signature(), "device.encode_chunks",
                    lambda: self._device_encode(data))
            except DeviceUnavailable:
                self._note_cpu_fallback("device.encode_chunks")
                coding = self.codec.encode(data)
        else:
            coding = self.codec.encode(data)
        for i in range(self.m):
            # fill in place so callers holding references see the parity
            encoded[self.chunk_index(self.k + i)][...] = coding[i]

    def decode_chunks(self, want_to_read: Set[int],
                      chunks: Dict[int, np.ndarray],
                      decoded: Dict[int, np.ndarray]) -> None:
        n = self.k + self.m
        phys_to_logical = {self.chunk_index(i): i for i in range(n)}
        logical_chunks = {phys_to_logical[p]: buf for p, buf in chunks.items()}
        want = sorted(phys_to_logical[p] for p in range(n)
                      if p in want_to_read or p not in chunks)
        out = self.codec.decode(logical_chunks, want)
        for i, buf in out.items():
            decoded[self.chunk_index(i)][...] = buf
