"""'isa' plugin: RS codec with isa-l matrix semantics.

Reproduces the reference isa plugin's coding matrices and parameter rules
(src/erasure-code/isa/ErasureCodeIsa.cc): technique=reed_sol_van selects the
isa-l Vandermonde generator (gf_gen_rs_matrix, :383-386) with the MDS safety
clamps (k<=32, m<=4, k<=21 when m=4; :330-361); technique=cauchy selects
gf_gen_cauchy1_matrix.  Alignment = EC_ISA_ADDRESS_ALIGNMENT (32,
src/erasure-code/isa/xor_op.h:28).  The m=1 parity chunk equals the XOR of
the data chunks (the reference's region_xor fast path, xor_op.cc:54-130) —
that falls out of the Vandermonde matrix's all-ones first coding row.
"""
from __future__ import annotations

import logging

from ..gf.matrices import gf_gen_rs_matrix, gf_gen_cauchy1_matrix
from .matrix_plugin import ErasureCodeMatrixRS
from .rs_codec import MatrixRSCodec

log = logging.getLogger(__name__)

DEFAULT_K = 7
DEFAULT_M = 3


class ErasureCodeIsa(ErasureCodeMatrixRS):
    # isa-matrix semantics: the tpu plugin inherits this family, so isa
    # and tpu requests of equal (technique, k, m) coalesce into one
    # dispatch batch (they are byte-identical by construction + test)
    signature_family = "isa-matrix"

    def __init__(self):
        super().__init__()
        self.technique = "reed_sol_van"

    def init(self, profile) -> None:
        super().init(profile)
        self.parse_mapping(profile)
        self.technique = profile.get("technique", "reed_sol_van")
        if self.technique not in ("reed_sol_van", "cauchy"):
            raise ValueError(f"technique={self.technique} must be "
                             "reed_sol_van or cauchy")
        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.sanity_check_k(self.k)
        if self.technique == "reed_sol_van":
            # MDS safety clamps, mirroring ErasureCodeIsa.cc:330-361
            if self.k > 32:
                log.warning("Vandermonde: k=%d > 32, reverting to k=32", self.k)
                self.k = 32
            if self.m > 4:
                log.warning("Vandermonde: m=%d > 4, reverting to m=4", self.m)
                self.m = 4
            if self.m == 4 and self.k > 21:
                log.warning("Vandermonde: k=%d > 21 with m=4, reverting to "
                            "k=21", self.k)
                self.k = 21
        self._init_backend(profile)
        if self.technique == "cauchy":
            matrix = gf_gen_cauchy1_matrix(self.k + self.m, self.k)
        else:
            matrix = gf_gen_rs_matrix(self.k + self.m, self.k)
        self.codec = MatrixRSCodec(matrix)
        self._profile.update({"k": str(self.k), "m": str(self.m),
                              "technique": self.technique})
