"""'regenerating' plugin — product-matrix regenerating codes (arXiv
1412.3022 lineage; construction of Rashmi-Shah-Kumar).

Repair-optimal codec family for the recovery/backfill hot path: where a
classic RS code repairs ONE lost shard by reading k whole chunks and
re-encoding, a product-matrix regenerating code repairs it by reading a
β-sub-chunk *contribution* from each of d helper shards — repair
bandwidth ~d·β·L instead of k·α·L (docs/RECOVERY.md has the math).

Two techniques behind one construction:

- ``pm_mbr`` (default, any k <= d <= n-1): minimum-bandwidth
  regenerating.  α = d sub-chunks per shard, β = 1, message size
  B = k·d − k(k−1)/2 sub-chunks.  Message matrix M (d×d, symmetric)
  = [[S, T], [Tᵗ, 0]] with S (k×k) symmetric and T (k×(d−k)); shard i
  stores Ψ_i·M.  Repair of shard f moves exactly d sub-chunks — ONE
  shard's worth of bytes — regardless of k.
- ``pm_msr`` (d = 2(k−1)): minimum-storage regenerating (MDS rate).
  α = k−1, B = k·α; M (2α×α) = [[S1], [S2]] with S1, S2 symmetric.
  Repair moves d·β = d sub-chunks = d·chunk/(d−k+1) bytes.

Ψ (n×d) is Vandermonde over GF(2^8) on evaluation points chosen so the
λ_i = x_i^α are pairwise distinct (the MSR pairwise decode inverts
[[1,λ_i],[1,λ_j]]); any d rows of Ψ and any α rows of Φ = Ψ[:, :α] are
then independent by the Vandermonde argument, which is the whole
correctness requirement of the construction.

Execution: encode and the ≥d-survivor decode are plain GF(2^8) matrix
multiplies, so they ride the EXISTING device machinery — a
``DeviceRSBackend`` built on [[I_d], [Ψ]] runs the bit-matmul on the
MXU (byte-identical to the MUL_TABLE host twin by the gf_matmul
tests), every device call goes through the fault guard and the
signature circuit breaker, and the dispatch scheduler coalesces
signature-equal encodes (own ``pm-regen`` family — never grouped with
RS-matrix codes).  The code is NOT systematic (no shard stores raw
object bytes — the defining trade of the product-matrix family), so
the codec flags ``requires_whole_object_rw`` and the EC backend routes
ranged reads and rmw through whole-object cycles.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

import numpy as np

from ..gf.matrices import gf_invert_matrix
from ..gf.tables import MUL_TABLE, gf_pow
from ..fault import DeviceUnavailable, run_device_call
from .matrix_plugin import ErasureCodeMatrixRS
from .rs_codec import MatrixRSCodec, gf_matvec_bytes

DEFAULT_K = 4
DEFAULT_M = 2
# sub-chunk unit (bytes) when the profile doesn't pin one; chunk
# geometry is α·unit per stripe, stripe width B·unit (docs/RECOVERY.md)
DEFAULT_SUBCHUNK_UNIT = 512


def _select_points(n: int, alpha: int) -> List[int]:
    """n evaluation points x_i in GF(256)* whose α-th powers are
    pairwise distinct (λ_i = x_i^α must differ for the MSR pairwise
    solve; the α-th power map is 255/gcd(α,255)-to-one, so a greedy
    scan suffices for any practical n)."""
    pts: List[int] = []
    seen = set()
    v = 1
    while len(pts) < n and v < 256:
        lam = gf_pow(v, max(alpha, 1))
        if lam not in seen:
            pts.append(v)
            seen.add(lam)
        v += 1
    if len(pts) < n:
        raise ValueError(
            f"cannot place n={n} nodes with distinct lambda over "
            f"GF(256) at alpha={alpha}")
    return pts


class ErasureCodeRegenerating(ErasureCodeMatrixRS):
    """Product-matrix MBR/MSR codec behind the ErasureCode ABI."""

    signature_family = "pm-regen"
    dispatch_batchable = True
    # all-output codec: encode_batch consumes prepared message matrices
    # and yields EVERY shard row (no systematic passthrough rows)
    dispatch_full_output = True
    # non-systematic: shard bytes are Ψ·M projections, so chunk-offset
    # arithmetic on logical offsets is meaningless — the EC backend
    # reads/rmws whole objects for this codec
    requires_whole_object_rw = True
    _device_decode_supported = True

    @property
    def mesh_row_shardable(self) -> bool:
        # the mesh plan models the systematic coding-rows matmul; the
        # full-output Ψ projection doesn't fit it — flushes degrade to
        # the single-device path (still guarded, still batched)
        return False

    @property
    def mesh_decode_shardable(self) -> bool:
        # ...but the READ side fits exactly: the ≥d decode and the d×d
        # repair solve are plain inverted-survivor matmuls over
        # [[I],[Ψ]] rows — the same shape the mesh decode plan models
        # for RS-matrix codes, so they shard (and rateless-protect)
        # across the chips despite the encode gate above
        return self._device_decode_supported

    def __init__(self):
        super().__init__()
        self.technique = "pm_mbr"
        self.d = 0
        self.alpha = 0       # sub-chunks stored per shard
        self.beta = 1        # sub-chunks a helper contributes to repair
        self.B = 0           # message sub-chunks per stripe
        self.rows = 0        # message-matrix rows (= d)
        self.cols = 0        # message-matrix cols (= α)
        self.subchunk_unit = DEFAULT_SUBCHUNK_UNIT
        self.psi: np.ndarray = None          # (n, d) encoding matrix
        self._lambda: np.ndarray = None      # λ_i = Ψ[i, α]
        self._idx_map: np.ndarray = None     # (rows, cols) -> msg index
        self._take: np.ndarray = None
        self._zero_mask: np.ndarray = None

    # ---- profile ----------------------------------------------------------
    def init(self, profile) -> None:
        super().init(profile)
        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.sanity_check_k(self.k)
        n = self.k + self.m
        self.technique = profile.get("technique", "pm_mbr")
        if self.technique not in ("pm_mbr", "pm_msr"):
            raise ValueError(f"technique={self.technique} must be "
                             "pm_mbr or pm_msr")
        if self.technique == "pm_msr":
            default_d = 2 * (self.k - 1)
        else:
            default_d = min(n - 1, self.k + 2)
        self.d = self.to_int("d", profile, default_d)
        if self.technique == "pm_msr":
            if self.d != 2 * (self.k - 1):
                raise ValueError(
                    f"pm_msr requires d = 2(k-1) = {2 * (self.k - 1)}, "
                    f"got d={self.d}")
            self.alpha = self.k - 1
        else:
            if not (self.k <= self.d <= n - 1):
                raise ValueError(
                    f"pm_mbr requires k <= d <= n-1 "
                    f"({self.k} <= {self.d} <= {n - 1})")
            self.alpha = self.d
        if self.d > n - 1:
            raise ValueError(f"d={self.d} needs n-1={n - 1} helpers")
        self.subchunk_unit = self.to_int("subchunk", profile,
                                         self._default_unit())
        if self.subchunk_unit <= 0:
            raise ValueError("subchunk must be positive")
        if profile.get("mapping"):
            raise ValueError(
                "regenerating codes do not support mapping= layouts")
        if profile.get("stripe_unit"):
            # chunk geometry is codec-defined (stripe = B·subchunk);
            # silently ignoring an operator's stripe_unit would be a
            # no-op knob — reject it and point at the real one
            raise ValueError(
                "regenerating codes derive their stripe width from "
                "subchunk= (B x subchunk bytes); stripe_unit= does "
                "not apply")
        self._init_backend(profile)
        self._build_matrices()
        # host twin + device backend on the stacked [[I_d], [Ψ]] code:
        # MatrixRSCodec rows 0..d-1 are the message rows, d..d+n-1 the
        # stored shard rows — the existing decode machinery then covers
        # the ≥d-survivor row reconstruction for free
        full = np.vstack([np.eye(self.rows, dtype=np.uint8), self.psi])
        self.codec = MatrixRSCodec(full)
        self._profile.update({"k": str(self.k), "m": str(self.m),
                              "d": str(self.d),
                              "technique": self.technique})

    @staticmethod
    def _default_unit() -> int:
        from ..common.config import g_conf
        try:
            v = int(g_conf.get_val("ec_regen_subchunk_unit"))
        except Exception:
            v = 0
        return v or DEFAULT_SUBCHUNK_UNIT

    def _build_matrices(self) -> None:
        k, d, alpha = self.k, self.d, self.alpha
        n = k + self.m
        pts = _select_points(n, alpha)
        self.psi = np.array(
            [[gf_pow(x, j) for j in range(d)] for x in pts],
            dtype=np.uint8)
        self._lambda = np.array([gf_pow(x, alpha) for x in pts],
                                dtype=np.uint8)
        if self.technique == "pm_msr":
            rows, cols = 2 * alpha, alpha
            idx = np.full((rows, cols), -1, dtype=np.int64)
            c = 0
            for half in range(2):                 # S1 then S2
                base = half * alpha
                for i in range(alpha):
                    for j in range(i, alpha):
                        idx[base + i][j] = idx[base + j][i] = c
                        c += 1
        else:
            rows = cols = d
            idx = np.full((rows, cols), -1, dtype=np.int64)
            c = 0
            for i in range(k):                    # S (k×k symmetric)
                for j in range(i, k):
                    idx[i][j] = idx[j][i] = c
                    c += 1
            for i in range(k):                    # T / Tᵗ
                for j in range(k, d):
                    idx[i][j] = idx[j][i] = c
                    c += 1
        self.B = c
        self.rows, self.cols = rows, cols
        self._idx_map = idx
        self._take = np.maximum(idx, 0).ravel()
        self._zero_mask = (idx < 0).ravel()

    # ---- geometry ---------------------------------------------------------
    def codec_signature(self):
        return (self.signature_family, self.k, self.m, self.technique,
                self.d, self.subchunk_unit, ())

    def get_sub_chunk_count(self) -> int:
        return self.alpha

    def sub_chunk_bytes(self, object_size: int) -> int:
        """Sub-chunk width L for a standalone object: the message holds
        B sub-chunks, aligned like the matrix codecs' chunks."""
        alignment = self.get_alignment()
        L = (object_size + self.B - 1) // self.B
        rem = L % alignment
        if rem:
            L += alignment - rem
        return max(L, alignment)

    def get_chunk_size(self, object_size: int) -> int:
        return self.alpha * self.sub_chunk_bytes(object_size)

    def preferred_stripe_width(self) -> int:
        """Pool stripe width: one message (B sub-chunks) per stripe."""
        return self.B * self.subchunk_unit

    def make_stripe_info(self, stripe_width: int):
        """Codec-geometry stripe info for the EC backend: logical
        stripe = B·L bytes, stored chunk = α·L bytes (≠ width/k — the
        non-systematic trade)."""
        from ..osd.ecutil import stripe_info_t
        if stripe_width % self.B:
            raise ValueError(
                f"stripe width {stripe_width} is not a multiple of the "
                f"message size B={self.B}")
        L = stripe_width // self.B
        si = stripe_info_t.__new__(stripe_info_t)
        si.stripe_width = stripe_width
        si.chunk_size = self.alpha * L
        return si

    # ---- message-matrix assembly ------------------------------------------
    def _sub_l(self, chunk_size: int) -> int:
        assert chunk_size % self.cols == 0, \
            f"chunk {chunk_size} not a multiple of {self.cols} sub-chunks"
        return chunk_size // self.cols

    def regen_prepare_batch(self, payload, n_stripes: int) -> np.ndarray:
        """Flat payload (S·B·L bytes) -> batched message matrices
        (S, rows, cols·L) — the dispatcher's pre-matmul assembly hook
        (a host gather; the matmul that follows is columnwise
        independent, so bucket padding stays output-preserving)."""
        buf = payload if isinstance(payload, np.ndarray) \
            else np.frombuffer(bytes(payload), dtype=np.uint8)
        S = n_stripes
        L = len(buf) // (S * self.B)
        assert S * self.B * L == len(buf)
        data = buf.reshape(S, self.B, L)
        m = data[:, self._take, :]
        m[:, self._zero_mask, :] = 0
        return np.ascontiguousarray(
            m.reshape(S, self.rows, self.cols * L))

    def _message_to_rows(self, msg: np.ndarray, S: int,
                         L: int) -> np.ndarray:
        """Message blocks (B, S·L) -> M in shard-chunk byte order
        (rows, S·C) for row-reconstruction matvecs."""
        m = msg[self._take, :]
        m[self._zero_mask, :] = 0
        m = m.reshape(self.rows, self.cols, S, L)
        return np.ascontiguousarray(
            m.transpose(0, 2, 1, 3).reshape(self.rows,
                                            S * self.cols * L))

    # ---- encode -----------------------------------------------------------
    def encode_batch(self, m_batch: np.ndarray) -> np.ndarray:
        """Batched message matrices (S, rows, C) -> ALL shard chunks
        (S, n, C) in one Ψ projection (full-output contract; the
        dispatcher slices per-request rows/columns back out)."""
        s, rows, c = m_batch.shape
        assert rows == self.rows
        from ..common.kernel_trace import g_kernel_timer
        if self._use_device():
            data_c = np.ascontiguousarray(m_batch)
            try:
                return run_device_call(
                    self.codec_signature(), "device.encode_batch",
                    lambda: g_kernel_timer.timed(
                        "ec_regen_encode_batch",
                        self._device_encode_batch, data_c))
            except DeviceUnavailable:
                self._note_cpu_fallback("device.encode_batch")

        def host():
            flat = np.ascontiguousarray(
                m_batch.transpose(1, 0, 2)).reshape(rows, s * c)
            allc = gf_matvec_bytes(self.psi, flat)
            return np.ascontiguousarray(
                allc.reshape(self.k + self.m, s, c).transpose(1, 0, 2))

        return g_kernel_timer.timed("ec_regen_encode_batch_host", host)

    def encode(self, want_to_encode: Set[int], data) -> Dict[int, np.ndarray]:
        from .base import as_chunk
        raw = as_chunk(data)
        L = self.sub_chunk_bytes(len(raw))
        padded = np.zeros(self.B * L, dtype=np.uint8)
        padded[:len(raw)] = raw
        allc = self.encode_batch(self.regen_prepare_batch(padded, 1))
        return {i: np.ascontiguousarray(allc[0, i, :])
                for i in want_to_encode}

    def encode_chunks(self, want_to_encode: Set[int],
                      encoded: Dict[int, np.ndarray]) -> None:
        raise NotImplementedError(
            "regenerating codes are whole-stripe: use encode()")

    # ---- decode -----------------------------------------------------------
    def minimum_to_decode(
        self, want_to_read: Set[int], available: Set[int]
    ) -> Dict[int, List[Tuple[int, int]]]:
        """The repair API surface: a single-shard repair query (one
        wanted, missing shard with ≥d helpers up) answers with d helper
        shards at β=1 sub-chunks each — ~d·chunk/α bytes on the wire
        instead of k whole chunks.  Any other query follows the base
        any-k semantics (all shards are equivalent: the code has no
        systematic set)."""
        missing = set(want_to_read) - set(available)
        if len(want_to_read) == 1 and missing:
            helpers = sorted(set(available) - set(want_to_read))
            if len(helpers) >= self.d:
                return {h: [(0, self.beta)] for h in helpers[:self.d]}
        return super().minimum_to_decode(want_to_read, available)

    def _structured_message(self, chunks: Dict[int, np.ndarray]
                            ) -> Tuple[np.ndarray, int, int]:
        """Message blocks (B, S·L) from the first k available shard
        chunks — the below-d decode the product-matrix structure
        exists for.  Host reference path (pure MUL_TABLE math)."""
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise IOError(
                f"need at least k={self.k} chunks, have {len(avail)}")
        K = avail[:self.k]
        some = np.asarray(chunks[K[0]])
        S, C = (1, some.shape[0]) if some.ndim == 1 else some.shape
        L = self._sub_l(C)
        # (k, cols, S·L): sub-chunk blocks per selected shard row
        R = np.stack([
            np.ascontiguousarray(
                np.asarray(chunks[i], dtype=np.uint8)
                .reshape(S, self.cols, L).transpose(1, 0, 2))
            .reshape(self.cols, S * L)
            for i in K])
        msg = np.zeros((self.B, S * L), dtype=np.uint8)
        if self.technique == "pm_msr":
            self._decode_msr(K, R, msg)
        else:
            self._decode_mbr(K, R, msg)
        return msg, S, L

    def _decode_mbr(self, K: Sequence[int], R: np.ndarray,
                    msg: np.ndarray) -> None:
        k, d = self.k, self.d
        SL = R.shape[-1]
        phi = self.psi[list(K), :k]
        inv_phi = gf_invert_matrix(phi)
        C1 = R[:, :k, :]
        if d > k:
            delta = self.psi[list(K), k:d]
            C2 = R[:, k:, :]
            T = gf_matvec_bytes(
                inv_phi, np.ascontiguousarray(C2).reshape(
                    k, (d - k) * SL)).reshape(k, d - k, SL)
            Tt = np.ascontiguousarray(T.transpose(1, 0, 2))
            DTt = gf_matvec_bytes(
                delta, Tt.reshape(d - k, k * SL)).reshape(k, k, SL)
            C1 = C1 ^ DTt
        else:
            T = np.zeros((k, 0, SL), dtype=np.uint8)
        Smat = gf_matvec_bytes(
            inv_phi, np.ascontiguousarray(C1).reshape(
                k, k * SL)).reshape(k, k, SL)
        c = 0
        for i in range(k):
            for j in range(i, k):
                msg[c] = Smat[i, j]
                c += 1
        for i in range(k):
            for j in range(d - k):
                msg[c] = T[i, j]
                c += 1

    def _decode_msr(self, K: Sequence[int], R: np.ndarray,
                    msg: np.ndarray) -> None:
        k, alpha = self.k, self.alpha
        SL = R.shape[-1]
        phi = self.psi[list(K), :alpha]             # (k, α)
        lam = [int(self._lambda[i]) for i in K]
        # P[i,j] = Ψ_i M Φ_j^T: project each received row onto Φ_K
        P = np.stack([gf_matvec_bytes(phi, R[i]) for i in range(k)])
        A = np.zeros((k, k, SL), dtype=np.uint8)    # Φ_i S1 Φ_j^T
        Bm = np.zeros((k, k, SL), dtype=np.uint8)   # Φ_i S2 Φ_j^T
        from ..gf.tables import gf_inv
        for i in range(k):
            for j in range(i + 1, k):
                inv_l = gf_inv(lam[i] ^ lam[j])
                b = MUL_TABLE[inv_l][P[i, j] ^ P[j, i]]
                a = P[i, j] ^ MUL_TABLE[lam[i]][b]
                A[i, j] = A[j, i] = a
                Bm[i, j] = Bm[j, i] = b
        # row i's projections against the other k-1 = α+1... exactly α
        # rows pin v_i = Φ_i S: solve G_i v_i = a_i over the pair grid
        V1 = np.zeros((alpha, alpha * SL), dtype=np.uint8)
        V2 = np.zeros((alpha, alpha * SL), dtype=np.uint8)
        for ii in range(alpha):
            others = [j for j in range(k) if j != ii]
            inv_g = gf_invert_matrix(phi[others, :])
            V1[ii] = gf_matvec_bytes(
                inv_g, np.ascontiguousarray(A[ii][others])).reshape(-1)
            V2[ii] = gf_matvec_bytes(
                inv_g, np.ascontiguousarray(Bm[ii][others])).reshape(-1)
        inv_phi_a = gf_invert_matrix(phi[:alpha, :])
        S1 = gf_matvec_bytes(inv_phi_a, V1).reshape(alpha, alpha, SL)
        S2 = gf_matvec_bytes(inv_phi_a, V2).reshape(alpha, alpha, SL)
        c = 0
        for half in (S1, S2):
            for i in range(alpha):
                for j in range(i, alpha):
                    msg[c] = half[i, j]
                    c += 1

    def decode_payload_batch(self, chunks: Dict[int, np.ndarray]
                             ) -> np.ndarray:
        """Available shard chunks {id: (S, C)} -> logical payload
        (S, B·L) — the read path's decode_concat core."""
        msg, S, L = self._structured_message(chunks)
        data = msg.reshape(self.B, S, L).transpose(1, 0, 2)
        return np.ascontiguousarray(data.reshape(S, self.B * L))

    def decode_batch(self, chunks: Dict[int, np.ndarray],
                     want) -> Dict[int, np.ndarray]:
        """Reconstruct whole shard rows (the recovery shape).  With ≥d
        survivors this is the plain [[I],[Ψ]] matrix path (device-
        eligible, breaker-gated); below d the product-matrix structure
        recovers the message from any k and re-projects."""
        avail = sorted(chunks)
        if len(avail) < self.k:
            raise IOError(
                f"need at least k={self.k} chunks, have {len(avail)}")
        some = np.asarray(chunks[avail[0]])
        S, C = some.shape
        out: Dict[int, np.ndarray] = {
            i: np.asarray(chunks[i], dtype=np.uint8)
            for i in want if i in chunks}
        miss = [i for i in want if i not in chunks]
        if not miss:
            return out
        if len(avail) >= self.d:
            srcs = avail[:self.d]
            row_ids = tuple(self.rows + h for h in srcs)

            # meshed reconstruct: the Ψ-survivor solve shards across
            # the chip mesh (rateless-protected, its own guard) before
            # the single-device guard — outside device_path so the two
            # fault guards never nest; None keeps today's path
            mesh_rows = None
            if self._use_device():
                from ..mesh import g_mesh
                survivors = np.stack(
                    [np.asarray(chunks[i], dtype=np.uint8)
                     for i in srcs], axis=1)
                mesh_rows = g_mesh.decode_stacked(
                    self, survivors, row_ids, tuple(range(self.rows)))

            def device_path() -> Dict[int, np.ndarray]:
                dev = self.device()
                if mesh_rows is not None:
                    m_rows = mesh_rows
                else:
                    survivors = np.stack(
                        [np.asarray(chunks[i], dtype=np.uint8)
                         for i in srcs], axis=1)
                    m_rows = dev.decode_data(survivors, row_ids,
                                             tuple(range(self.rows)))
                allc = dev.encode(m_rows)
                got = dict(out)
                for i in miss:
                    got[i] = allc[:, i, :]
                return got

            if self._use_device():
                try:
                    return run_device_call(self.codec_signature(),
                                           "device.decode_batch",
                                           device_path)
                except DeviceUnavailable:
                    self._note_cpu_fallback("device.decode_batch")
            stacked = np.stack([
                np.asarray(chunks[i], dtype=np.uint8).reshape(-1)
                for i in srcs])
            inv = gf_invert_matrix(self.psi[srcs, :])
            m_flat = gf_matvec_bytes(inv, stacked)
            rows = gf_matvec_bytes(self.psi[miss, :], m_flat)
            for idx, i in enumerate(miss):
                out[i] = rows[idx].reshape(S, C)
            return out
        # fewer than d survivors: structured decode, then re-project
        msg, S2, L = self._structured_message(chunks)
        m_rows = self._message_to_rows(msg, S2, L)
        rows = gf_matvec_bytes(self.psi[miss, :], m_rows)
        for idx, i in enumerate(miss):
            out[i] = rows[idx].reshape(S, C)
        return out

    def decode(self, want_to_read: Set[int],
               chunks: Dict[int, np.ndarray],
               chunk_size: int = 0) -> Dict[int, np.ndarray]:
        from .base import as_chunk
        arrs = {i: as_chunk(c) for i, c in chunks.items()}
        if want_to_read <= set(arrs):
            return {i: arrs[i] for i in want_to_read}
        got = self.decode_batch({i: a[None, :] for i, a in arrs.items()},
                                sorted(want_to_read))
        return {i: np.ascontiguousarray(b).reshape(-1)
                for i, b in got.items()}

    def decode_chunks(self, want_to_read, chunks, decoded) -> None:
        got = self.decode(set(want_to_read), chunks)
        for i, buf in got.items():
            decoded[i][...] = buf

    def decode_concat(self, chunks: Dict[int, np.ndarray]) -> bytes:
        from .base import as_chunk
        arrs = {i: as_chunk(c)[None, :] for i, c in chunks.items()}
        return self.decode_payload_batch(arrs)[0].tobytes()

    # ---- repair (the point of the family) ---------------------------------
    def repair_mu(self, lost: int) -> np.ndarray:
        """The combination vector helpers project their stored row
        onto: Ψ_f for MBR (M is d×d), Φ_f for MSR (M is 2α×α)."""
        return self.psi[lost, :self.cols].copy()

    def repair_contribution(self, helper: int, lost: int,
                            body: np.ndarray) -> np.ndarray:
        """Helper-side repair math: stored chunk rows (S, C) -> the β·L
        bytes this shard contributes, (Ψ_h M)·μ_f per stripe."""
        S, C = body.shape
        L = self._sub_l(C)
        blocks = np.ascontiguousarray(
            np.asarray(body, dtype=np.uint8)
            .reshape(S, self.cols, L).transpose(1, 0, 2)
        ).reshape(self.cols, S * L)
        mu = self.repair_mu(lost)
        out = gf_matvec_bytes(mu[None, :], blocks)
        return np.ascontiguousarray(out.reshape(S, L))

    def repair_bytes_per_shard(self, chunk_size: int) -> int:
        """Helper bytes moved to repair one shard of *chunk_size*."""
        return self.d * self.beta * self._sub_l(chunk_size)

    def repair(self, lost: int, contributions: Dict[int, np.ndarray]
               ) -> np.ndarray:
        """Collector-side repair: d helper contributions {helper:
        (S, L)} -> the lost shard's chunk rows (S, C).  The d×d solve
        runs on the device backend when available (guarded,
        breaker-gated) with the byte-identical MUL_TABLE twin as the
        fallback — same discipline as every other codec call."""
        helpers = sorted(contributions)
        if len(helpers) != self.d:
            raise IOError(
                f"repair needs exactly d={self.d} contributions, "
                f"have {len(helpers)}")
        if lost in contributions:
            raise ValueError("lost shard cannot help repair itself")
        some = np.asarray(contributions[helpers[0]])
        S, L = some.shape
        stacked = np.stack([
            np.asarray(contributions[h], dtype=np.uint8).reshape(-1)
            for h in helpers])                      # (d, S·L)
        row_ids = tuple(self.rows + h for h in helpers)

        def device_path() -> np.ndarray:
            dev = self.device()
            return dev.decode_data(stacked[None], row_ids,
                                   tuple(range(self.rows)))[0]

        u = None
        if self._use_device():
            # meshed repair solve: the (1, d, S·L) stack is byte-axis-
            # folded by the runtime so even this single "stripe"
            # spreads across the chips (repair=True for the counters);
            # computed before the single-device guard, never nested
            from ..mesh import g_mesh
            mesh_u = g_mesh.decode_stacked(
                self, stacked[None], row_ids, tuple(range(self.rows)),
                repair=True)
            if mesh_u is not None:
                u = mesh_u[0]
        if u is None and self._use_device():
            try:
                u = run_device_call(self.codec_signature(),
                                    "device.decode_batch", device_path)
            except DeviceUnavailable:
                self._note_cpu_fallback("device.decode_batch")
        if u is None:
            inv = gf_invert_matrix(self.psi[helpers, :])
            u = gf_matvec_bytes(inv, stacked)       # (rows, S·L) = M·μ
        u = np.asarray(u, dtype=np.uint8).reshape(self.rows, S, L)
        if self.technique == "pm_msr":
            lam_f = int(self._lambda[lost])
            rep = u[:self.alpha] ^ MUL_TABLE[lam_f][u[self.alpha:]]
        else:
            # M symmetric: M·Ψ_f^T IS the lost row's sub-chunk vector
            rep = u
        chunk = np.ascontiguousarray(
            rep.transpose(1, 0, 2).reshape(S, self.cols * L))
        return chunk
