"""'jerasure' plugin: RS/Cauchy matrix techniques with jerasure semantics.

Mirrors the reference jerasure plugin's technique set
(src/erasure-code/jerasure/ErasureCodeJerasure.h:82-258; defaults k=7 m=3
w=8 at :90-92):

- reed_sol_van: Vandermonde-derived systematic matrix
  (reed_sol_vandermonde_coding_matrix; ErasureCodeJerasure.cc:155).
- reed_sol_r6_op: RAID6 optimization — coding rows [1,1,..] and [1,2,4,..]
  (m is forced to 2).
- cauchy_orig: original Cauchy matrix, row i col j = 1/(i ^ (m+j)).
- cauchy_good / liberation / blaum_roth / liber8tion: bitmatrix+schedule
  codes; scheduled-XOR execution is not yet implemented in this round and
  raises NotImplementedError at init.

Only w=8 is supported on the device path (the reference default); other w
values raise.
"""
from __future__ import annotations

import numpy as np

from ..gf.tables import gf_inv, gf_pow
from ..gf.matrices import jerasure_reed_sol_van_matrix
from .matrix_plugin import ErasureCodeMatrixRS
from .rs_codec import MatrixRSCodec

DEFAULT_K = 7
DEFAULT_M = 3
DEFAULT_W = 8

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
              "liberation", "blaum_roth", "liber8tion")


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """RAID6 coding rows: parity row of ones, Q row of powers of 2."""
    m = np.zeros((2, k), dtype=np.uint8)
    m[0, :] = 1
    for j in range(k):
        m[1, j] = gf_pow(2, j)
    return m


def cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """jerasure cauchy_original_coding_matrix: row i col j = 1/(i ^ (m+j))."""
    a = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            a[i, j] = gf_inv(i ^ (m + j))
    return a


def _systematic(coding: np.ndarray) -> np.ndarray:
    m, k = coding.shape
    full = np.zeros((k + m, k), dtype=np.uint8)
    full[:k] = np.eye(k, dtype=np.uint8)
    full[k:] = coding
    return full


class ErasureCodeJerasure(ErasureCodeMatrixRS):
    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.w = DEFAULT_W
        self.packetsize = 0
        self.per_chunk_alignment = False

    def init(self, profile) -> None:
        super().init(profile)
        self.parse_mapping(profile)
        self.technique = profile.get("technique", self.technique)
        if self.technique not in TECHNIQUES:
            raise ValueError(f"technique={self.technique} not in {TECHNIQUES}")
        self.k = self.to_int("k", profile, DEFAULT_K)
        self.m = self.to_int("m", profile, DEFAULT_M)
        self.w = self.to_int("w", profile, DEFAULT_W)
        self.packetsize = self.to_int("packetsize", profile, 0)
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, False)
        self.sanity_check_k(self.k)
        if self.w != 8:
            raise ValueError(f"w={self.w}: only w=8 is supported "
                             "(device GF(2^8) kernels)")
        self._init_backend(profile)
        if self.technique == "reed_sol_van":
            coding = jerasure_reed_sol_van_matrix(self.k, self.m)
        elif self.technique == "reed_sol_r6_op":
            self.m = 2
            coding = reed_sol_r6_matrix(self.k)
        elif self.technique == "cauchy_orig":
            coding = cauchy_orig_matrix(self.k, self.m)
        else:
            raise NotImplementedError(
                f"technique={self.technique}: bitmatrix/scheduled codes "
                "planned for a later round")
        self.codec = MatrixRSCodec(_systematic(coding))
        self._profile.update({"k": str(self.k), "m": str(self.m),
                              "w": str(self.w),
                              "technique": self.technique})

    def get_alignment(self) -> int:
        # reference ErasureCodeJerasureReedSolomonVandermonde::get_alignment:
        # k*w*sizeof(int) when not per-chunk (w=8 => 32k), else
        # w*LARGEST_VECTOR_WORDSIZE (=16) per chunk
        if self.per_chunk_alignment:
            return self.w * 16
        return self.k * self.w * 4

    def get_chunk_size(self, object_size: int) -> int:
        # jerasure semantics (ErasureCodeJerasure.cc get_chunk_size): pad the
        # whole object to alignment, then divide by k — different from isa.
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (object_size + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k
