"""'jerasure' plugin: RS/Cauchy matrix + bitmatrix-schedule techniques.

Mirrors the reference jerasure plugin's technique set
(src/erasure-code/jerasure/ErasureCodeJerasure.h:82-258; defaults k=7 m=3
w=8 at :90-92):

- reed_sol_van: Vandermonde-derived systematic matrix
  (reed_sol_vandermonde_coding_matrix; ErasureCodeJerasure.cc:155).
- reed_sol_r6_op: RAID6 optimization — coding rows [1,1,..] and [1,2,4,..]
  (m is forced to 2).
- cauchy_orig / cauchy_good: Cauchy coefficient matrices (original /
  density-improved) executed as bitmatrix packet codes
  (ErasureCodeJerasure.cc:259-269 jerasure_schedule_encode role).
- liberation / blaum_roth / liber8tion: minimal-density RAID-6 bitmatrix
  codes (m=2), same packet execution (ErasureCodeJerasure.cc:340-348).

The bitmatrix family runs through gf/bitmatrix.BitmatrixPacketCodec: XOR
of byte packets with 0/1 coefficients is GF(2^8)-linear, so the device
path is the same MXU bit-matmul the RS codes use, over virtual packet
chunks.  reed_sol_* supports w=8 (byte path), and w=16/32 through the
LE-word codec (gf/word_codec.py host split tables; companion-bitmatrix
MXU matmul on device).
"""
from __future__ import annotations

import numpy as np

from ..gf.tables import gf_inv, gf_pow
from ..gf.matrices import jerasure_reed_sol_van_matrix
from ..gf.word_codec import reed_sol_r6_matrix_w, reed_sol_van_matrix_w
from ..gf.bitmatrix import (
    BitmatrixPacketCodec, blaum_roth_bitmatrix, cauchy_good_matrix,
    cauchy_original_matrix, liber8tion_bitmatrix, liberation_bitmatrix,
    matrix_to_bitmatrix, _is_prime,
)
from .matrix_plugin import ErasureCodeMatrixRS
from .rs_codec import MatrixRSCodec

DEFAULT_K = 7
DEFAULT_M = 3
DEFAULT_W = 8
DEFAULT_PACKETSIZE = 2048  # ErasureCodeJerasure.h:141 DEFAULT_PACKETSIZE

TECHNIQUES = ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
              "liberation", "blaum_roth", "liber8tion")
BITMATRIX_TECHNIQUES = ("cauchy_orig", "cauchy_good", "liberation",
                        "blaum_roth", "liber8tion")


def reed_sol_r6_matrix(k: int) -> np.ndarray:
    """RAID6 coding rows: parity row of ones, Q row of powers of 2."""
    m = np.zeros((2, k), dtype=np.uint8)
    m[0, :] = 1
    for j in range(k):
        m[1, j] = gf_pow(2, j)
    return m


def _systematic(coding: np.ndarray) -> np.ndarray:
    m, k = coding.shape
    full = np.zeros((k + m, k), dtype=np.uint8)
    full[:k] = np.eye(k, dtype=np.uint8)
    full[k:] = coding
    return full


class ErasureCodeJerasure(ErasureCodeMatrixRS):
    # jerasure matrices differ from isa's for the same (technique, k,
    # m), so the family keeps its requests in their own dispatch groups
    signature_family = "jerasure"

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.w = DEFAULT_W
        self.packetsize = 0
        self.per_chunk_alignment = False

    @property
    def is_bitmatrix(self) -> bool:
        return self.technique in BITMATRIX_TECHNIQUES

    def init(self, profile) -> None:
        super().init(profile)
        self.parse_mapping(profile)
        self.technique = profile.get("technique", self.technique)
        if self.technique not in TECHNIQUES:
            raise ValueError(f"technique={self.technique} not in {TECHNIQUES}")
        # per-technique defaults (ErasureCodeJerasure.h constructors)
        def_k, def_m, def_w = DEFAULT_K, DEFAULT_M, DEFAULT_W
        if self.technique == "liberation":
            def_k, def_m, def_w = 2, 2, 7
        elif self.technique in ("blaum_roth", "liber8tion"):
            def_k, def_m, def_w = 2, 2, 8 if self.technique == "liber8tion" \
                else 6
        self.k = self.to_int("k", profile, def_k)
        self.m = self.to_int("m", profile, def_m)
        self.w = self.to_int("w", profile, def_w)
        self.packetsize = self.to_int("packetsize", profile,
                                      DEFAULT_PACKETSIZE
                                      if self.is_bitmatrix else 0)
        self.per_chunk_alignment = self.to_bool(
            "jerasure-per-chunk-alignment", profile, False)
        self.sanity_check_k(self.k)
        self._init_backend(profile)
        if self.technique == "reed_sol_van":
            if self.w == 8:
                coding = jerasure_reed_sol_van_matrix(self.k, self.m)
                self.codec = MatrixRSCodec(_systematic(coding))
            elif self.w in (16, 32):
                self._init_word_codec(
                    reed_sol_van_matrix_w(self.k, self.m, self.w))
            else:
                raise ValueError(f"reed_sol_van: w={self.w} not in 8|16|32")
        elif self.technique == "reed_sol_r6_op":
            self.m = 2
            if self.w == 8:
                coding = reed_sol_r6_matrix(self.k)
                self.codec = MatrixRSCodec(_systematic(coding))
            elif self.w in (16, 32):
                self._init_word_codec(reed_sol_r6_matrix_w(self.k, self.w))
            else:
                raise ValueError(f"reed_sol_r6_op: w={self.w} not in 8|16|32")
        else:
            self._init_bitmatrix()
        self._profile.update({"k": str(self.k), "m": str(self.m),
                              "w": str(self.w),
                              "technique": self.technique})
        if self.is_bitmatrix:
            self._profile["packetsize"] = str(self.packetsize)

    def _init_bitmatrix(self) -> None:
        if self.packetsize <= 0:
            raise ValueError(
                f"technique={self.technique} requires packetsize > 0")
        if self.packetsize % 4:
            # ErasureCodeJerasure.cc:390-397 check_packetsize
            raise ValueError("packetsize must be a multiple of 4")
        if self.technique == "cauchy_orig":
            bm = matrix_to_bitmatrix(
                cauchy_original_matrix(self.k, self.m, self.w), self.w)
        elif self.technique == "cauchy_good":
            bm = matrix_to_bitmatrix(
                cauchy_good_matrix(self.k, self.m, self.w), self.w)
        elif self.technique == "liberation":
            self.m = 2
            if self.k > self.w or not _is_prime(self.w):
                raise ValueError(
                    f"liberation needs prime w >= k (k={self.k} w={self.w})")
            bm = liberation_bitmatrix(self.k, self.w)
        elif self.technique == "blaum_roth":
            self.m = 2
            if self.k > self.w or not _is_prime(self.w + 1):
                raise ValueError(
                    f"blaum_roth needs w+1 prime, w >= k "
                    f"(k={self.k} w={self.w})")
            bm = blaum_roth_bitmatrix(self.k, self.w)
        else:  # liber8tion
            self.m = 2
            self.w = 8
            if self.k > 8:
                raise ValueError("liber8tion needs k <= 8")
            bm = liber8tion_bitmatrix(self.k)
        self.codec = BitmatrixPacketCodec(bm, self.k, self.m, self.w,
                                          self.packetsize)

    def _init_word_codec(self, coding: np.ndarray) -> None:
        """w=16/32: LE-word layout codec (jerasure_matrix_encode role)."""
        from ..gf.word_codec import WordMatrixCodec
        full = np.zeros((self.k + self.m, self.k), dtype=np.int64)
        full[:self.k] = np.eye(self.k, dtype=np.int64)
        full[self.k:] = coding
        self.codec = WordMatrixCodec(full, self.w)

    @property
    def is_word_code(self) -> bool:
        from ..gf.word_codec import WordMatrixCodec
        return isinstance(self.codec, WordMatrixCodec)

    def device(self):
        if self.is_word_code:
            if self._device is None:
                from ..ops.gf_matmul import DeviceWordRSBackend
                self._device = DeviceWordRSBackend(self.codec.matrix, self.w)
            return self._device
        return super().device()

    def _stripe_block(self) -> int:
        if self.is_bitmatrix:
            return self.w * self.packetsize
        if self.is_word_code:
            return self.w // 8
        return 1

    @property
    def mesh_row_shardable(self) -> bool:
        # bitmatrix/word layouts reshape data into a virtual layout
        # before the backend matmul; the mesh plan runs the PLAIN
        # row-independent matmul, so only the plain-matrix techniques
        # may shard (the mesh runtime declines the rest)
        return not (self.is_bitmatrix or self.is_word_code)

    @property
    def _device_decode_supported(self) -> bool:
        # bitmatrix/word layouts decode through the host codec (their
        # device backends consume virtual/word layouts, not whole chunks)
        return not (self.is_bitmatrix or self.is_word_code)

    def _device_encode(self, data: np.ndarray) -> np.ndarray:
        if self.is_word_code:
            return self.device().encode(data[None])[0]
        if not self.is_bitmatrix:
            return super()._device_encode(data)
        dv = self.codec.to_virtual(data)
        cv = self.device().encode(dv[None])[0]
        return self.codec.from_virtual(cv, self.m)

    def _device_encode_batch(self, data: np.ndarray) -> np.ndarray:
        if self.is_word_code:
            return self.device().encode(data)
        if not self.is_bitmatrix:
            return super()._device_encode_batch(data)
        # batch virtual reshape: (S, k, C) -> (S, k*w, C/w)
        s, k, c = data.shape
        w, ps = self.w, self.packetsize
        nb = c // (w * ps)
        dv = np.ascontiguousarray(
            data.reshape(s, k, nb, w, ps).transpose(0, 1, 3, 2, 4)
        ).reshape(s, k * w, nb * ps)
        cv = self.device().encode(dv)                # (S, m*w, C/w)
        m = self.m
        return np.ascontiguousarray(
            cv.reshape(s, m, w, nb, ps).transpose(0, 1, 3, 2, 4)
        ).reshape(s, m, c)

    def get_alignment(self) -> int:
        if self.is_bitmatrix:
            # ErasureCodeJerasureCauchy::get_alignment
            # (ErasureCodeJerasure.cc:272-283): per-chunk = w*packetsize;
            # whole-object = k*w*packetsize*sizeof(int), widened to the
            # vector word size when misaligned
            if self.per_chunk_alignment:
                return self.w * self.packetsize
            alignment = self.k * self.w * self.packetsize * 4
            if (self.w * self.packetsize * 4) % 16:
                alignment = self.k * self.w * self.packetsize * 16
            return alignment
        # reference ErasureCodeJerasureReedSolomonVandermonde::get_alignment:
        # k*w*sizeof(int) when not per-chunk (w=8 => 32k), else
        # w*LARGEST_VECTOR_WORDSIZE (=16) per chunk
        if self.per_chunk_alignment:
            return self.w * 16
        return self.k * self.w * 4

    def get_chunk_size(self, object_size: int) -> int:
        # jerasure semantics (ErasureCodeJerasure.cc get_chunk_size): pad the
        # whole object to alignment, then divide by k — different from isa.
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = (object_size + self.k - 1) // self.k
            modulo = chunk_size % alignment
            if modulo:
                chunk_size += alignment - modulo
            return chunk_size
        tail = object_size % alignment
        padded = object_size + (alignment - tail if tail else 0)
        assert padded % self.k == 0
        return padded // self.k
