"""journal-lite: ordered append/replay log over rados (src/journal +
src/cls/journal at lite scale — the engine under rbd mirroring).
"""
from . import cls_journal  # noqa: F401  (registers the cls methods)
from .journaler import Journaler, JournalError

__all__ = ["Journaler", "JournalError"]
