"""cls_journal-lite: journal metadata methods (src/cls/journal/
cls_journal.cc in the reference).

A journal's control state lives in one metadata object
(``journal.<id>``): immutable shape (order, splay_width), the active
object-set watermark, and the registered clients with their commit
positions.  Mutations are class methods so concurrent journal users
(e.g. an rbd-mirror daemon and the primary image) get atomic
read-modify-write, exactly like the reference's cls_journal.
"""
from __future__ import annotations

import json

from ..osd.cls import CLS_METHOD_WR, ClsContext, register_cls_method


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _parse(inp: bytes):
    try:
        return json.loads(inp.decode()) if inp else {}
    except ValueError:
        return {}


@register_cls_method("journal", "create", CLS_METHOD_WR)
def _create(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    if ctx.exists and ctx.omap_get():
        return -17, b""
    ctx.omap_set({
        "order": str(int(req.get("order", 24))),
        "splay_width": str(int(req.get("splay_width", 4))),
        "minimum_set": "0",
        "active_set": "0",
    })
    return 0, b""


@register_cls_method("journal", "get_metadata")
def _get_metadata(ctx: ClsContext, inp: bytes):
    om = ctx.omap_get()
    if "order" not in om:
        return -2, b""
    clients = {k[len("client_"):]: json.loads(v)
               for k, v in om.items() if k.startswith("client_")}
    return 0, _j({"order": int(om["order"]),
                  "splay_width": int(om["splay_width"]),
                  "minimum_set": int(om["minimum_set"]),
                  "active_set": int(om["active_set"]),
                  "clients": clients})


@register_cls_method("journal", "client_register", CLS_METHOD_WR)
def _client_register(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = f"client_{req['id']}"
    if key in ctx.omap_get():
        return -17, b""
    ctx.omap_set({key: _j({"commit_tid": -1,
                           "data": req.get("data", "")})})
    return 0, b""


@register_cls_method("journal", "client_unregister", CLS_METHOD_WR)
def _client_unregister(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = f"client_{req['id']}"
    if key not in ctx.omap_get():
        return -2, b""
    ctx.omap_rm_keys([key])
    return 0, b""


@register_cls_method("journal", "client_commit", CLS_METHOD_WR)
def _client_commit(ctx: ClsContext, inp: bytes):
    """Advance a client's commit position; never moves backwards
    (cls_journal client_commit semantics)."""
    req = _parse(inp)
    key = f"client_{req['id']}"
    om = ctx.omap_get()
    if key not in om:
        return -2, b""
    cl = json.loads(om[key])
    cl["commit_tid"] = max(cl["commit_tid"], int(req["commit_tid"]))
    ctx.omap_set({key: _j(cl)})
    return 0, b""


@register_cls_method("journal", "set_active_set", CLS_METHOD_WR)
def _set_active_set(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    om = ctx.omap_get()
    if "active_set" not in om:
        return -2, b""
    if int(req["set"]) < int(om["active_set"]):
        return -22, b""
    ctx.omap_set({"active_set": str(int(req["set"]))})
    return 0, b""


@register_cls_method("journal", "set_minimum_set", CLS_METHOD_WR)
def _set_minimum_set(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    om = ctx.omap_get()
    if "minimum_set" not in om:
        return -2, b""
    if int(req["set"]) < int(om["minimum_set"]):
        return -22, b""
    ctx.omap_set({"minimum_set": str(int(req["set"]))})
    return 0, b""
