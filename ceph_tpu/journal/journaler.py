"""Journaler-lite: ordered append/replay log over rados (src/journal
in the reference — the engine under rbd journaling/mirroring).

Entries are framed and appended round-robin across ``splay_width``
data objects per object set (``journal_data.<id>.<objno>``, objno =
set * splay + tid % splay — the reference's splay layout,
journal/JournalMetadata.cc), so sequential appends spread over
``splay_width`` PGs while replay re-interleaves by tid.  Each frame
carries a crc so replay stops cleanly at a torn tail (Entry.cc uses
the same preamble+crc framing).  Registered clients track commit
positions in the metadata object (cls_journal); trimming deletes
whole object sets once every client has committed past them.

Scope-outs vs the reference: tag-based demultiplexing, prefetch
watermarks, and the librbd integration daemon (rbd-mirror).
"""
from __future__ import annotations

import json
import struct
from typing import Iterator, List, Optional, Tuple

from ..client.rados import RadosClient
from ..utils.crc32c import crc32c
from . import cls_journal  # noqa: F401

PREAMBLE = 0x3141_5926            # frame magic (Entry.cc preamble role)
_HDR = struct.Struct("<IQI")      # magic, tid, payload length


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


class JournalError(IOError):
    def __init__(self, api: str, result: int):
        super().__init__(f"journal {api}: error {result}")
        self.result = result


def _absent(e: IOError) -> bool:
    return getattr(e, "errno", None) == 2


class Journaler:
    """One journal (create/open + append/replay/commit/trim)."""

    def __init__(self, client: RadosClient, pool: str, journal_id: str,
                 entries_per_object: int = 64):
        self.client = client
        self.pool = pool
        self.jid = journal_id
        self.meta_oid = f"journal.{journal_id}"
        self.entries_per_object = entries_per_object
        self.order = 0
        self.splay = 0
        self._next_tid = 0
        self._pushed_active_set = -1

    # ---- metadata ----------------------------------------------------------
    def _exec(self, method: str, payload=None) -> bytes:
        ret, out = self.client.exec(self.pool, self.meta_oid, "journal",
                                    method, _j(payload or {}))
        if ret < 0:
            raise JournalError(method, ret)
        return out

    def create(self, order: int = 24, splay_width: int = 4) -> None:
        self._exec("create", {"order": order,
                              "splay_width": splay_width})
        self.open()

    def get_metadata(self) -> dict:
        """Decoded journal metadata (shape, watermarks, clients)."""
        return json.loads(self._exec("get_metadata"))

    def open(self) -> dict:
        md = self.get_metadata()
        self.order = md["order"]
        self.splay = md["splay_width"]
        self._next_tid = self._scan_next_tid(md)
        # never try to move the stored watermark backwards: after a
        # crash in the write-ahead window the metadata set can be one
        # AHEAD of where the next append lands (empty set), and
        # set_active_set refuses regressions
        self._pushed_active_set = md["active_set"]
        return md

    def register_client(self, client_id: str, data: str = "") -> None:
        self._exec("client_register", {"id": client_id, "data": data})

    def unregister_client(self, client_id: str) -> None:
        self._exec("client_unregister", {"id": client_id})

    def commit(self, client_id: str, tid: int) -> None:
        self._exec("client_commit", {"id": client_id, "commit_tid": tid})

    # ---- layout ------------------------------------------------------------
    def _entries_per_set(self) -> int:
        return self.splay * self.entries_per_object

    def _objno(self, tid: int) -> int:
        oset = tid // self._entries_per_set()
        return oset * self.splay + tid % self.splay

    def _data_oid(self, objno: int) -> str:
        return f"journal_data.{self.jid}.{objno:x}"

    # ---- append ------------------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Frame + append one entry; returns its tid.  The frame crc
        covers header+payload so a torn tail write is detectable."""
        tid = self._next_tid
        active_set = tid // self._entries_per_set()
        if active_set > self._pushed_active_set:
            # write-AHEAD the watermark (once per object set): if the
            # frame landed first and we crashed before the bump, the
            # entry would be invisible to both replay() and the next-tid
            # scan (both bounded by metadata active_set) — the reused
            # tid could then be applied locally yet never replayed to a
            # mirror.  Bumping first merely costs replay a scan over an
            # empty set on the crash path.
            self._exec("set_active_set", {"set": active_set})
            self._pushed_active_set = active_set
        hdr = _HDR.pack(PREAMBLE, tid, len(payload))
        frame = hdr + payload + struct.pack("<I", crc32c(hdr + payload))
        r = self.client.append(self.pool, self._data_oid(self._objno(tid)),
                               frame)
        if r < 0:
            raise JournalError("append", r)
        self._next_tid = tid + 1
        return tid

    # ---- replay ------------------------------------------------------------
    def _read_object_entries(self, objno: int
                             ) -> List[Tuple[int, bytes]]:
        try:
            blob = self.client.read(self.pool, self._data_oid(objno))
        except IOError as e:
            if _absent(e):
                return []
            raise
        out, off = [], 0
        while off + _HDR.size + 4 <= len(blob):
            magic, tid, ln = _HDR.unpack_from(blob, off)
            if magic != PREAMBLE:
                break                     # torn/garbage tail: stop
            end = off + _HDR.size + ln + 4
            if end > len(blob):
                break                     # truncated tail frame
            body = blob[off:off + _HDR.size + ln]
            (crc,) = struct.unpack_from("<I", blob, off + _HDR.size + ln)
            if crc != crc32c(body):
                break                     # torn write: stop replay here
            out.append((tid, body[_HDR.size:]))
            off = end
        return out

    def replay(self, after_tid: int = -1
               ) -> Iterator[Tuple[int, bytes]]:
        """Yield (tid, payload) in tid order for every intact entry
        after ``after_tid`` (JournalPlayer's committed-position replay).
        Stops at the first gap — entries past a torn/missing tid are
        not safe to apply in order."""
        md = self.get_metadata()
        entries = {}
        for oset in range(md["minimum_set"], md["active_set"] + 1):
            for s in range(self.splay):
                for tid, payload in self._read_object_entries(
                        oset * self.splay + s):
                    entries[tid] = payload
        tid = after_tid + 1
        while tid in entries:
            yield tid, entries[tid]
            tid += 1

    def scan_entries(self):
        """Every intact retained entry, ascending tid, WITHOUT the
        replay gap rule: for membership scans (e.g. dedup-id recovery)
        where ordering safety doesn't apply."""
        md = self.get_metadata()
        out = []
        for oset in range(md["minimum_set"], md["active_set"] + 1):
            for s in range(self.splay):
                out.extend(self._read_object_entries(
                    oset * self.splay + s))
        return sorted(out)

    def _scan_next_tid(self, md: dict) -> int:
        """Highest tid on disk + 1, walking DOWN from active_set until
        a set with entries appears (tids grow with set number, so the
        first non-empty set holds the maximum).  active_set itself can
        be empty — the watermark is written ahead of the first frame —
        and with trimming lagging there can be several live sets, so
        stopping after active_set+minimum_set alone would resurrect a
        stale tid from the bottom of the window."""
        last = -1
        for oset in range(md["active_set"], md["minimum_set"] - 1, -1):
            for s in range(self.splay):
                for tid, _ in self._read_object_entries(
                        oset * self.splay + s):
                    last = max(last, tid)
            if last >= 0:
                break
        return last + 1

    # ---- trim --------------------------------------------------------------
    def committed_tid(self) -> int:
        """min over registered clients (nothing may be trimmed past the
        slowest consumer)."""
        md = self.get_metadata()
        if not md["clients"]:
            return -1
        return min(c["commit_tid"] for c in md["clients"].values())

    def trim(self) -> int:
        """Delete object sets wholly below every client's commit
        position; returns the new minimum set."""
        md = self.get_metadata()
        safe_tid = self.committed_tid()
        eps = self._entries_per_set()
        # a set is trimmable when its LAST tid is committed
        new_min = min((safe_tid + 1) // eps, md["active_set"])
        for oset in range(md["minimum_set"], new_min):
            for s in range(self.splay):
                self.client.remove(self.pool,
                                   self._data_oid(oset * self.splay + s))
        if new_min > md["minimum_set"]:
            self._exec("set_minimum_set", {"set": new_min})
        return new_min

    def remove(self) -> None:
        md = self.get_metadata()
        for oset in range(md["minimum_set"], md["active_set"] + 1):
            for s in range(self.splay):
                self.client.remove(self.pool,
                                   self._data_oid(oset * self.splay + s))
        self.client.remove(self.pool, self.meta_oid)
