"""cls_rbd-lite: server-side image-metadata methods (src/cls/rbd/
cls_rbd.cc in the reference).

librbd never mutates image metadata with raw omap writes — every header
update is a class method executed ON the OSD inside the op transaction,
so concurrent clients get atomic read-modify-write semantics (e.g. two
snapshot_adds can't both claim the same name).  Same shape here: the
image header, the pool's ``rbd_directory`` and the ``rbd_children``
index are all mutated through ``(rbd, <method>)`` calls.

Payloads are JSON (the lite stand-in for the reference's binary
bufferlist encodings) over the header object's omap:
  size / order / object_prefix / snap_seq  — image shape
  snapshot_<id>                            — per-snap {name, size, protected}
  parent                                   — {pool, image_id, snapid, overlap}
"""
from __future__ import annotations

import json
from typing import Dict

from ..osd.cls import (
    CLS_METHOD_RD, CLS_METHOD_WR, ClsContext, register_cls_method,
)

RBD_HEADER_PREFIX = "rbd_header."
RBD_DATA_PREFIX = "rbd_data."
RBD_DIRECTORY = "rbd_directory"
RBD_CHILDREN = "rbd_children"


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _parse(inp: bytes) -> Dict:
    try:
        return json.loads(inp.decode()) if inp else {}
    except ValueError:
        return {}


def _snap_key(snapid: int) -> str:
    return f"snapshot_{snapid:016x}"


# ---- image header ----------------------------------------------------------

@register_cls_method("rbd", "create", CLS_METHOD_WR)
def _create(ctx: ClsContext, inp: bytes):
    """Initialize a header object (cls_rbd create): -EEXIST if this
    header was already created."""
    req = _parse(inp)
    if ctx.exists and ctx.omap_get():
        return -17, b""                               # EEXIST
    kv = {
        "size": str(int(req["size"])),
        "order": str(int(req.get("order", 22))),
        "object_prefix": str(req["object_prefix"]),
        "snap_seq": "0",
    }
    if req.get("data_pool"):
        # image data lives in a separate (typically EC) pool while the
        # header stays omap-capable (librbd RBD_FEATURE_DATA_POOL)
        kv["data_pool"] = str(req["data_pool"])
    if req.get("journaling"):
        kv["journaling"] = "1"     # RBD_FEATURE_JOURNALING
    if req.get("exclusive_lock"):
        kv["exclusive_lock"] = "1"  # RBD_FEATURE_EXCLUSIVE_LOCK
    if req.get("object_map"):
        kv["object_map"] = "1"     # RBD_FEATURE_OBJECT_MAP (fast-diff)
    ctx.omap_set(kv)
    return 0, b""


@register_cls_method("rbd", "get_image")
def _get_image(ctx: ClsContext, inp: bytes):
    om = ctx.omap_get()
    if "size" not in om:
        return -2, b""                                # ENOENT
    out = {
        "size": int(om["size"]),
        "order": int(om["order"]),
        "object_prefix": om["object_prefix"].decode()
        if isinstance(om["object_prefix"], bytes) else om["object_prefix"],
        "snap_seq": int(om["snap_seq"]),
    }
    if "data_pool" in om:
        out["data_pool"] = om["data_pool"].decode()
    if "journaling" in om:
        out["journaling"] = True
    if "exclusive_lock" in om:
        out["exclusive_lock"] = True
    if "object_map" in om:
        out["object_map"] = True
    return 0, _j(out)


@register_cls_method("rbd", "set_size", CLS_METHOD_WR)
def _set_size(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    if "size" not in ctx.omap_get():
        return -2, b""
    ctx.omap_set({"size": str(int(req["size"]))})
    return 0, b""


# ---- snapshots -------------------------------------------------------------

@register_cls_method("rbd", "snapshot_add", CLS_METHOD_WR)
def _snapshot_add(ctx: ClsContext, inp: bytes):
    """Record a mon-allocated snap id on the image (cls_rbd
    snapshot_add): name collisions and stale ids are refused
    atomically, which is the point of doing this server-side."""
    req = _parse(inp)
    om = ctx.omap_get()
    if "size" not in om:
        return -2, b""
    snapid, name = int(req["snapid"]), str(req["name"])
    if snapid <= int(om["snap_seq"]):
        return -116, b""                              # ESTALE
    for k, v in om.items():
        if k.startswith("snapshot_") and json.loads(v)["name"] == name:
            return -17, b""                           # EEXIST
    ctx.omap_set({
        _snap_key(snapid): _j({"name": name,
                               "size": int(req["size"]),
                               "protected": False}),
        "snap_seq": str(snapid),
    })
    return 0, b""


@register_cls_method("rbd", "snapshot_remove", CLS_METHOD_WR)
def _snapshot_remove(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = _snap_key(int(req["snapid"]))
    om = ctx.omap_get()
    if key not in om:
        return -2, b""
    if json.loads(om[key])["protected"]:
        return -16, b""                               # EBUSY
    ctx.omap_rm_keys([key])
    return 0, b""


@register_cls_method("rbd", "get_snapcontext")
def _get_snapcontext(ctx: ClsContext, inp: bytes):
    om = ctx.omap_get()
    if "size" not in om:
        return -2, b""
    snaps = {}
    for k, v in om.items():
        if k.startswith("snapshot_"):
            snaps[int(k[len("snapshot_"):], 16)] = json.loads(v)
    return 0, _j({"seq": int(om["snap_seq"]),
                  "snaps": {str(k): v for k, v in snaps.items()}})


def _set_protected(ctx: ClsContext, inp: bytes, value: bool):
    req = _parse(inp)
    key = _snap_key(int(req["snapid"]))
    om = ctx.omap_get()
    if key not in om:
        return -2, b""
    info = json.loads(om[key])
    if info["protected"] == value:
        return (-16 if value else -22), b""           # EBUSY / EINVAL
    info["protected"] = value
    ctx.omap_set({key: _j(info)})
    return 0, b""


@register_cls_method("rbd", "snapshot_protect", CLS_METHOD_WR)
def _snapshot_protect(ctx: ClsContext, inp: bytes):
    return _set_protected(ctx, inp, True)


@register_cls_method("rbd", "snapshot_unprotect", CLS_METHOD_WR)
def _snapshot_unprotect(ctx: ClsContext, inp: bytes):
    return _set_protected(ctx, inp, False)


# ---- clone parent link -----------------------------------------------------

@register_cls_method("rbd", "set_parent", CLS_METHOD_WR)
def _set_parent(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    om = ctx.omap_get()
    if "size" not in om:
        return -2, b""
    if "parent" in om:
        return -17, b""
    ctx.omap_set({"parent": _j({
        "pool": str(req["pool"]), "image_id": str(req["image_id"]),
        "snapid": int(req["snapid"]), "overlap": int(req["overlap"]),
    })})
    return 0, b""


@register_cls_method("rbd", "get_parent")
def _get_parent(ctx: ClsContext, inp: bytes):
    om = ctx.omap_get()
    if "parent" not in om:
        return -2, b""
    return 0, bytes(om["parent"])


@register_cls_method("rbd", "remove_parent", CLS_METHOD_WR)
def _remove_parent(ctx: ClsContext, inp: bytes):
    if "parent" not in ctx.omap_get():
        return -2, b""
    ctx.omap_rm_keys(["parent"])
    return 0, b""


@register_cls_method("rbd", "set_parent_overlap", CLS_METHOD_WR)
def _set_parent_overlap(ctx: ClsContext, inp: bytes):
    """Shrink the parent overlap (resize below overlap keeps the
    smaller value — cls_rbd set_parent on resize)."""
    req = _parse(inp)
    om = ctx.omap_get()
    if "parent" not in om:
        return -2, b""
    p = json.loads(om["parent"])
    p["overlap"] = min(p["overlap"], int(req["overlap"]))
    ctx.omap_set({"parent": _j(p)})
    return 0, b""


# ---- pool image directory (cls_rbd dir_*) ----------------------------------

@register_cls_method("rbd", "dir_add_image", CLS_METHOD_WR)
def _dir_add_image(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    name, iid = str(req["name"]), str(req["id"])
    om = ctx.omap_get()
    if f"name_{name}" in om:
        return -17, b""
    ctx.omap_set({f"name_{name}": iid.encode(),
                  f"id_{iid}": name.encode()})
    return 0, b""


@register_cls_method("rbd", "dir_remove_image", CLS_METHOD_WR)
def _dir_remove_image(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    name, iid = str(req["name"]), str(req["id"])
    om = ctx.omap_get()
    if om.get(f"name_{name}", b"").decode() != iid:
        return -2, b""
    ctx.omap_rm_keys([f"name_{name}", f"id_{iid}"])
    return 0, b""


@register_cls_method("rbd", "dir_get_id")
def _dir_get_id(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    v = ctx.omap_get().get(f"name_{req['name']}")
    if v is None:
        return -2, b""
    return 0, bytes(v)


@register_cls_method("rbd", "dir_list")
def _dir_list(ctx: ClsContext, inp: bytes):
    names = sorted(k[len("name_"):] for k in ctx.omap_get()
                   if k.startswith("name_"))
    return 0, _j(names)


@register_cls_method("rbd", "dir_rename_image", CLS_METHOD_WR)
def _dir_rename_image(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    src, dst, iid = str(req["src"]), str(req["dst"]), str(req["id"])
    om = ctx.omap_get()
    if om.get(f"name_{src}", b"").decode() != iid:
        return -2, b""
    if f"name_{dst}" in om:
        return -17, b""
    ctx.omap_rm_keys([f"name_{src}"])
    ctx.omap_set({f"name_{dst}": iid.encode(),
                  f"id_{iid}": dst.encode()})
    return 0, b""


# ---- clone children index (cls_rbd add_child/remove_child/get_children) ----

def _child_key(pool: str, image_id: str, snapid: int) -> str:
    return f"{pool}\x00{image_id}\x00{snapid:016x}"


@register_cls_method("rbd", "add_child", CLS_METHOD_WR)
def _add_child(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = _child_key(req["pool"], req["image_id"], int(req["snapid"]))
    kids = json.loads(ctx.omap_get().get(key, b"[]"))
    if req["child_id"] not in kids:
        kids.append(req["child_id"])
    ctx.omap_set({key: _j(sorted(kids))})
    return 0, b""


@register_cls_method("rbd", "remove_child", CLS_METHOD_WR)
def _remove_child(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = _child_key(req["pool"], req["image_id"], int(req["snapid"]))
    om = ctx.omap_get()
    kids = json.loads(om.get(key, b"[]"))
    if req["child_id"] not in kids:
        return -2, b""
    kids.remove(req["child_id"])
    if kids:
        ctx.omap_set({key: _j(kids)})
    else:
        ctx.omap_rm_keys([key])
    return 0, b""


@register_cls_method("rbd", "get_children")
def _get_children(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = _child_key(req["pool"], req["image_id"], int(req["snapid"]))
    return 0, bytes(ctx.omap_get().get(key, b"[]"))
