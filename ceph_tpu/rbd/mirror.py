"""rbd-mirror-lite: journal-based one-way image replication
(src/tools/rbd_mirror in the reference).

The reference's rbd-mirror daemon registers as a client on the primary
image's journal, replays its IO events against a secondary cluster's
copy of the image, and advances its commit position so the primary can
trim.  Same shape here: ``ImageMirror`` pulls the source journal's
entries past its own commit position, applies them to the destination
image through the shared event table (``apply_image_event``), and
commits per event — a killed mirror resumes exactly where it stopped,
and the source's trim is gated on the slowest client (the mirror) by
the journal's committed_tid.

Scope-outs: promotion/demotion (two-way failover), the bootstrap
image-sync for pre-existing data (mirrors must attach at create time
or the caller syncs first), and pool-level mirroring policy.
"""
from __future__ import annotations

import json

from ..journal import Journaler
from .image import Image, RBD, RBDError, apply_image_event

MIRROR_CLIENT = "mirror"


class ImageMirror:
    """One directed (src image -> dst image) replication relationship."""

    def __init__(self, src_client, src_pool: str, image_name: str,
                 dst_client, dst_pool: str,
                 dst_data_pool: str = None):
        self.src = Image(src_client, src_pool, image_name)
        if not self.src.journaling:
            raise RBDError("mirror", -22)   # journaling required
        self.journal = Journaler(src_client, src_pool, self.src.id)
        self.journal.open()
        md = self.journal.get_metadata()
        if MIRROR_CLIENT not in md["clients"]:
            self.journal.register_client(MIRROR_CLIENT)
        dst_rbd = RBD(dst_client)
        if image_name not in dst_rbd.list(dst_pool):
            dst_rbd.create(dst_pool, image_name, self.src.size(),
                           self.src.order_log2, data_pool=dst_data_pool)
        self.dst = Image(dst_client, dst_pool, image_name)

    def _commit_position(self) -> int:
        md = self.journal.get_metadata()
        return md["clients"][MIRROR_CLIENT]["commit_tid"]

    def run_once(self) -> int:
        """Replay every new source event onto the destination; returns
        the number applied (ImageReplayer::handle_replay_ready)."""
        pos = self._commit_position()
        n = 0
        for tid, payload in self.journal.replay(after_tid=pos):
            apply_image_event(self.dst, json.loads(payload))
            self.journal.commit(MIRROR_CLIENT, tid)
            n += 1
        return n

    def trim_source(self) -> int:
        """Reclaim source journal sets every consumer has passed."""
        return self.journal.trim()


class PoolMirror:
    """Pool-mode mirroring (rbd mirror pool enable + the rbd-mirror
    daemon's pool watcher): every JOURNALED image in the source pool
    gets an ImageMirror to the destination; images that appear later
    are picked up on the next run.  Non-journaled images are skipped,
    like the reference skips images without the journaling feature."""

    def __init__(self, src_client, src_pool: str, dst_client,
                 dst_pool: str, dst_data_pool: str = None):
        self.src_client = src_client
        self.src_pool = src_pool
        self.dst_client = dst_client
        self.dst_pool = dst_pool
        self.dst_data_pool = dst_data_pool
        self.mirrors: dict = {}

    def run_once(self) -> dict:
        """Scan the pool, attach new journaled images, replay every
        mirror; returns {image: events_applied}."""
        import json as _json
        from .cls_rbd import RBD_DIRECTORY
        applied = {}
        for name in RBD(self.src_client).list(self.src_pool):
            m = self.mirrors.get(name)
            if m is not None:
                ret, out = self.src_client.exec(
                    self.src_pool, RBD_DIRECTORY, "rbd", "dir_get_id",
                    _json.dumps({"name": name}).encode())
                cur_id = out.decode() if ret == 0 else None
                if cur_id != m.src.id:
                    # deleted-and-recreated under the same name: the
                    # cached mirror replays a dead journal forever, and
                    # the old-generation DESTINATION must go too or
                    # replaying the new stream onto it leaves offsets
                    # the new generation never wrote reading old bytes
                    del self.mirrors[name]
                    try:
                        RBD(self.dst_client).remove(self.dst_pool,
                                                    name)
                    except RBDError as e:
                        if e.result != -2:
                            raise    # dst has snapshots/children:
                    m = None         # operator must resolve first
            if m is None:
                try:
                    m = ImageMirror(self.src_client, self.src_pool,
                                    name, self.dst_client,
                                    self.dst_pool, self.dst_data_pool)
                except RBDError as e:
                    if e.result == -22:      # journaling off: skip
                        continue
                    raise
                self.mirrors[name] = m
            applied[name] = m.run_once()
        # forget images that vanished from the source
        for name in list(self.mirrors):
            if name not in applied:
                del self.mirrors[name]
        return applied

    def trim_sources(self) -> None:
        for m in self.mirrors.values():
            m.trim_source()
