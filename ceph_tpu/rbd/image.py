"""librbd-lite: block images striped over rados objects.

The reference's librbd (src/librbd, 57k LoC) maps a virtual block device
onto 2^order-byte rados objects ``rbd_data.<id>.<objno:%016x>``, with
image metadata in a header object mutated only through cls_rbd methods
and per-pool indexes (``rbd_directory``, ``rbd_children``).  This module
reimplements that core on the framework's own primitives:

- striping: image offset -> (objno, in-object offset); absent objects
  read as zeros (sparse), like ImageCtx::prune_parent_extents + ObjectMap
  absence semantics.
- snapshots: mon-allocated selfmanaged snap ids recorded on the header
  (cls snapshot_add); every data mutation rides the image's SnapContext
  so the OSD clones pre-write state (librbd ImageCtx::snapc).
- clones: child images carry a (pool, image_id, snapid, overlap) parent
  link; reads fall through to the parent below the overlap and writes
  copy-up the parent object first (AbstractObjectWriteRequest copyup).
- flatten/resize/rollback mirror Operations.cc semantics at lite scale.

The write-ahead image journal + mirroring live in ``mirror.py`` /
``ceph_tpu.journal``.  The exclusive lock (auto-acquire on first write,
cooperative surrender over the header watch, dead-owner break —
librbd::ExclusiveLock) and the object map / fast-diff existence bitmap
(librbd::ObjectMap) are implemented on cls_lock + watch/notify.
Scope-outs vs the reference: the qemu block driver surface.
"""
from __future__ import annotations

import json
import uuid
from typing import Dict, List, Optional, Tuple

from ..client.rados import NotifyTimeout, ObjectOperation, \
    RadosClient
from .cls_rbd import (
    RBD_CHILDREN, RBD_DATA_PREFIX, RBD_DIRECTORY, RBD_HEADER_PREFIX,
)


class RBDError(IOError):
    def __init__(self, api: str, result: int):
        super().__init__(f"rbd {api}: error {result}")
        self.result = result


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _absent(e: IOError) -> bool:
    return getattr(e, "errno", None) == 2


class RBD:
    """Pool-level image admin (librbd::RBD): create/clone/list/remove."""

    def __init__(self, client: RadosClient):
        self.client = client

    def _exec(self, pool: str, oid: str, method: str, payload=None
              ) -> bytes:
        ret, out = self.client.exec(pool, oid, "rbd", method,
                                    _j(payload or {}))
        if ret < 0:
            raise RBDError(method, ret)
        return out

    def create(self, pool: str, name: str, size: int,
               order: int = 22, data_pool: str = None,
               journaling: bool = False,
               exclusive_lock: bool = False,
               object_map: bool = False) -> str:
        """Create an image; returns its id (librbd::RBD::create).

        ``data_pool`` puts the data objects in a separate — typically
        erasure-coded — pool while the header/directory stay in the
        omap-capable base pool (librbd RBD_FEATURE_DATA_POOL; EC pools
        cannot hold omap, in the reference or here).  ``journaling``
        enables the write-ahead image journal (RBD_FEATURE_JOURNALING)
        that rbd-mirror replays."""
        if size < 0 or not (12 <= order <= 26):
            raise RBDError("create", -22)
        iid = uuid.uuid4().hex[:12]
        self._exec(pool, RBD_DIRECTORY, "dir_add_image",
                   {"name": name, "id": iid})
        try:
            self._exec(pool, RBD_HEADER_PREFIX + iid, "create",
                       {"size": size, "order": order,
                        "object_prefix": RBD_DATA_PREFIX + iid,
                        "data_pool": data_pool,
                        "journaling": journaling,
                        # journaling REQUIRES the exclusive lock in the
                        # reference (mutations must be single-writer or
                        # the journal interleaves) — imply it
                        "exclusive_lock": exclusive_lock or journaling
                        or object_map,
                        "object_map": object_map})
        except RBDError:
            self._exec(pool, RBD_DIRECTORY, "dir_remove_image",
                       {"name": name, "id": iid})
            raise
        if journaling:
            from ..journal import Journaler
            try:
                jr = Journaler(self.client, pool, iid)
                jr.create(order=order, splay_width=4)
                jr.register_client("local")  # the primary's own replay
            except Exception as e:
                # roll the half-created image back out — a registered
                # image whose journal never materialized would fail
                # every mutation with no visible defect in list()
                try:
                    jr.remove()           # any journal objects written
                except Exception:
                    pass
                self.client.remove(pool, RBD_HEADER_PREFIX + iid)
                self._exec(pool, RBD_DIRECTORY, "dir_remove_image",
                           {"name": name, "id": iid})
                raise RBDError("create journal", -5) from e
        return iid

    def list(self, pool: str) -> List[str]:
        try:
            return json.loads(self._exec(pool, RBD_DIRECTORY, "dir_list"))
        except RBDError as e:
            if e.result == -2:
                return []
            raise

    def rename(self, pool: str, src: str, dst: str) -> None:
        iid = self._exec(pool, RBD_DIRECTORY, "dir_get_id",
                         {"name": src}).decode()
        self._exec(pool, RBD_DIRECTORY, "dir_rename_image",
                   {"src": src, "dst": dst, "id": iid})

    def remove(self, pool: str, name: str) -> None:
        """Remove an image: refused while it has snapshots or clone
        children (librbd returns -ENOTEMPTY / -EBUSY)."""
        img = Image(self.client, pool, name)
        if img.snap_list():
            raise RBDError("remove", -39)             # ENOTEMPTY
        if img.parent():
            pool_p, pid, psnap, _ = img.parent()
            self._exec(pool_p, RBD_CHILDREN, "remove_child",
                       {"pool": pool_p, "image_id": pid, "snapid": psnap,
                        "child_id": img.id})
        # a stale pool-wide write ctx from another image must not
        # manufacture whiteout clones for these deletes
        self.client.set_write_ctx(img.data_pool, 0, [])
        for objno in range(img._objects_in(img.size())):
            self.client.remove(img.data_pool, img._obj(objno))
        if img.journaling:
            from ..journal import Journaler
            jr = Journaler(self.client, pool, img.id)
            try:
                jr.open()
                jr.remove()
            except Exception:
                pass                  # journal already gone: fine
        self.client.remove(pool, RBD_HEADER_PREFIX + img.id)
        self._exec(pool, RBD_DIRECTORY, "dir_remove_image",
                   {"name": name, "id": img.id})

    def copy(self, src_pool: str, src_name: str, dst_pool: str,
             dst_name: str, src_snap: Optional[str] = None,
             data_pool: str = None) -> str:
        """Full image copy (rbd cp / deep-copy of one point in time):
        a new independent image with the source's bytes."""
        src = Image(self.client, src_pool, src_name, snapshot=src_snap)
        iid = self.create(dst_pool, dst_name, src.size(),
                          src.order_log2, data_pool)
        dst = Image(self.client, dst_pool, dst_name)
        for objno in range(src._objects_in(src.size())):
            off = objno * src.object_size
            ln = min(src.object_size, src.size() - off)
            data = src.read(off, ln)
            if data.strip(b"\x00"):
                dst.write(off, data)
        return iid

    def clone(self, parent_pool: str, parent_name: str, snap_name: str,
              child_pool: str, child_name: str,
              data_pool: str = None) -> str:
        """COW child of a protected parent snapshot (librbd clone v1
        semantics: protect -> clone -> children index)."""
        parent = Image(self.client, parent_pool, parent_name)
        sid, info = parent._snap_by_name(snap_name)
        if not info["protected"]:
            raise RBDError("clone", -22)
        iid = self.create(child_pool, child_name, info["size"],
                          parent.order_log2, data_pool)
        self._exec(child_pool, RBD_HEADER_PREFIX + iid, "set_parent",
                   {"pool": parent_pool, "image_id": parent.id,
                    "snapid": sid, "overlap": info["size"]})
        self._exec(parent_pool, RBD_CHILDREN, "add_child",
                   {"pool": parent_pool, "image_id": parent.id,
                    "snapid": sid, "child_id": iid})
        return iid


# open handles per (client, header): same-client lock transitions are
# coordinated HERE — the OSD excludes the notifier's own watches from a
# notify fan-out, so a sibling handle can never be reached that way
_LOCAL_HANDLES: Dict[Tuple[int, str], object] = {}


def _register_handle(img: "Image") -> None:
    import weakref
    key = (id(img.client), img._header)
    ws = _LOCAL_HANDLES.get(key)
    if ws is None:
        ws = _LOCAL_HANDLES[key] = weakref.WeakSet()
    ws.add(img)


def _mutating(fn):
    """Every mutating entry point runs under the exclusive lock when
    the feature is on (librbd::ExclusiveLock auto-acquire on first
    write), and is marked in-op so a concurrent surrender request is
    answered 'busy' instead of letting the lock break mid-mutation."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        self._op_depth += 1
        self._in_op = True
        try:
            if self._op_depth == 1:
                self._ensure_exclusive_lock()
            return fn(self, *a, **kw)
        finally:
            # unwound on EVERY exit (a failed lock acquire included):
            # leaked depth would skip future acquisitions and answer
            # every surrender request 'busy' forever
            self._op_depth -= 1
            self._in_op = self._op_depth > 0
    return wrapper


class Image:
    """An open image (librbd::Image): data I/O + snapshot/clone ops.

    ``snapshot=`` opens a read-only view at that snap, like
    rbd_open_read_only with a snap set on the ioctx.
    """

    def __init__(self, client: RadosClient, pool: str, name: str,
                 snapshot: Optional[str] = None):
        self.client = client
        self.pool = pool
        self.name = name
        ret, out = client.exec(pool, RBD_DIRECTORY, "rbd", "dir_get_id",
                               _j({"name": name}))
        if ret < 0:
            raise RBDError("open", ret)
        self._load_header(out.decode())
        if snapshot is not None:
            sid, _ = self._snap_by_name(snapshot)
            self.read_snap = sid

    def _load_header(self, iid: str) -> None:
        """Load the immutable image shape + parent link once at open
        (ImageCtx caches parent_md the same way; librbd invalidates via
        header watch/notify, which this lite layer scopes out — reopen
        after another handle's flatten to observe it)."""
        self.id = iid
        self._header = RBD_HEADER_PREFIX + iid
        meta = self._call("get_image")
        self.order_log2 = meta["order"]
        self.object_size = 1 << meta["order"]
        self.object_prefix = meta["object_prefix"]
        self.data_pool = meta.get("data_pool") or self.pool
        self.journaling = bool(meta.get("journaling"))
        self.exclusive_lock_feature = bool(meta.get("exclusive_lock"))
        self.object_map_feature = bool(meta.get("object_map"))
        self._journal = None
        self.read_snap: Optional[int] = None
        self._parent_link = self._fetch_parent()
        self._parent_handle: Optional["Image"] = None
        # exclusive-lock state (librbd::ExclusiveLock): acquired lazily
        # on the first mutation, surrendered cooperatively on another
        # handle's request (the header watch round)
        self._lock_cookie = f"auto {uuid.uuid4().hex[:8]}"
        self._lock_owned = False
        self._lock_surrendered = False
        self._watch_cookie: Optional[int] = None
        self._in_op = False
        self._op_depth = 0
        self._omap_cache: Optional[bytearray] = None
        _register_handle(self)

    # ---- header helpers ---------------------------------------------------
    def _call(self, method: str, payload=None, parse: bool = True):
        ret, out = self.client.exec(self.pool, self._header, "rbd",
                                    method, _j(payload or {}))
        if ret < 0:
            raise RBDError(method, ret)
        return json.loads(out) if (parse and out) else out

    def _snapcontext(self) -> Tuple[int, Dict[int, Dict]]:
        sc = self._call("get_snapcontext")
        return sc["seq"], {int(k): v for k, v in sc["snaps"].items()}

    def _snap_by_name(self, name: str) -> Tuple[int, Dict]:
        for sid, info in sorted(self._snapcontext()[1].items()):
            if info["name"] == name:
                return sid, info
        raise RBDError("snap lookup", -2)

    def _apply_write_ctx(self) -> None:
        """Install this image's SnapContext on the pool before a data
        mutation (ImageCtx::snapc -> ioctx write ctx)."""
        seq, snaps = self._snapcontext()
        self.client.set_write_ctx(self.data_pool, seq, list(snaps))

    def journal(self):
        """The image's write-ahead journal (librbd::Journal), lazily
        opened; None when the feature is off."""
        if not self.journaling:
            return None
        if self._journal is None:
            from ..journal import Journaler
            self._journal = Journaler(self.client, self.pool, self.id)
            self._journal.open()
        return self._journal

    def _journal_event(self, event: Dict) -> None:
        """Append one mutation event BEFORE applying it (write-ahead,
        librbd::Journal::append_io_event): a crash between append and
        apply is healed by replay_local(), and rbd-mirror replays the
        same stream remotely."""
        jr = self.journal()
        if jr is not None:
            if getattr(self, "_applied_tid", None) is not None:
                # a previous append never reached commit (its apply
                # failed mid-op): heal it first, or committing this
                # op's later tid would bury the unapplied event forever
                # (commit is monotonic) while the mirror still replays
                # it — local/remote divergence
                self.replay_local()
            self._applied_tid = jr.append(_j(event))

    def _journal_commit_applied(self) -> None:
        """Commit exactly the tid of the event this op appended — NOT
        the journal head: if an earlier op failed between append and
        apply, advancing past its tid would stop replay_local() from
        ever healing it (commit never regresses, so re-committing an
        older tid after such a failure is a no-op, which is correct:
        the unapplied event stays below the next replay window only if
        we never skip it)."""
        jr = self.journal()
        tid = getattr(self, "_applied_tid", None)
        if jr is not None and tid is not None:
            jr.commit("local", tid)
            self._applied_tid = None

    def replay_local(self) -> int:
        """Re-apply journal events past the local commit position (the
        primary's crash-recovery replay, librbd::Journal::replay).
        Events are idempotent (absolute offsets/extents), so re-applying
        an already-applied tail is safe.  Returns events replayed."""
        jr = self.journal()
        if jr is None:
            return 0
        md = jr.get_metadata()
        pos = md["clients"].get("local", {}).get("commit_tid", -1)
        n = 0
        for tid, payload in jr.replay(after_tid=pos):
            apply_image_event(self, json.loads(payload))
            jr.commit("local", tid)
            n += 1
        self._applied_tid = None     # nothing outstanding after a heal
        return n

    def parent(self) -> Optional[Tuple[str, str, int, int]]:
        return self._parent_link

    def _fetch_parent(self) -> Optional[Tuple[str, str, int, int]]:
        try:
            p = json.loads(self._call("get_parent", parse=False))
        except RBDError as e:
            if e.result == -2:
                return None
            raise
        return p["pool"], p["image_id"], p["snapid"], p["overlap"]

    def _parent_image(self) -> "Image":
        if self._parent_handle is None:
            ppool, pid = self._parent_link[0], self._parent_link[1]
            self._parent_handle = Image._open_by_id(self.client, ppool,
                                                    pid)
        return self._parent_handle

    # ---- geometry ---------------------------------------------------------
    def size(self) -> int:
        if self.read_snap is not None:
            return self._snapcontext()[1][self.read_snap]["size"]
        return self._call("get_image")["size"]

    def _obj(self, objno: int) -> str:
        return f"{self.object_prefix}.{objno:016x}"

    def _objects_in(self, nbytes: int) -> int:
        return (nbytes + self.object_size - 1) // self.object_size

    def _extents(self, offset: int, length: int
                 ) -> List[Tuple[int, int, int]]:
        """(objno, in-object offset, length) covering [offset, +length)
        (Striper::file_to_extents for the rbd flat layout)."""
        out = []
        while length > 0:
            objno, off = divmod(offset, self.object_size)
            take = min(length, self.object_size - off)
            out.append((objno, off, take))
            offset += take
            length -= take
        return out

    # ---- data path --------------------------------------------------------
    def _read_object(self, objno: int, off: int, ln: int,
                     snapid: Optional[int]) -> bytes:
        try:
            data = self.client.read(self.data_pool, self._obj(objno),
                                    offset=off, length=ln, snap=snapid)
        except IOError as e:
            if not _absent(e):
                raise
            data = b""
        return data.ljust(ln, b"\x00")

    def _parent_read(self, objno: int, off: int, ln: int) -> bytes:
        """Fall through to the parent below the overlap (ImageCtx::
        aio_read parent path)."""
        p = self.parent()
        if p is None:
            return b"\x00" * ln
        ppool, pid, psnap, overlap = p
        pos = objno * self.object_size + off
        if pos >= overlap:
            return b"\x00" * ln
        take = min(ln, overlap - pos)
        data = self._parent_image()._read_at(pos, take, psnap)
        return data.ljust(ln, b"\x00")

    @classmethod
    def _open_by_id(cls, client: RadosClient, pool: str,
                    iid: str) -> "Image":
        img = object.__new__(cls)
        img.client, img.pool, img.name = client, pool, f"#{iid}"
        img._load_header(iid)
        return img

    def _read_at(self, offset: int, length: int,
                 snapid: Optional[int]) -> bytes:
        chunks = []
        has_parent = self.parent() is not None
        for objno, off, ln in self._extents(offset, length):
            data = self._read_object(objno, off, ln, snapid)
            if has_parent and not data.strip(b"\x00"):
                # object may be wholly absent: only then fall through
                try:
                    self.client.stat(self.data_pool, self._obj(objno),
                                     snap=snapid)
                except IOError as e:
                    if _absent(e):
                        data = self._parent_read(objno, off, ln)
                    else:
                        raise
            chunks.append(data)
        return b"".join(chunks)

    def read(self, offset: int, length: int) -> bytes:
        end = self.size()
        if offset >= end:
            return b""
        length = min(length, end - offset)
        return self._read_at(offset, length, self.read_snap)

    @_mutating
    def write(self, offset: int, data: bytes) -> int:
        """Write-through with copy-up for clones; grows never — writes
        past the end are clipped like librbd returns -EINVAL."""
        if self.read_snap is not None:
            raise RBDError("write", -30)              # EROFS
        end = self.size()
        if offset + len(data) > end:
            raise RBDError("write", -22)
        if self.journaling:
            import base64
            self._journal_event({
                "op": "write", "offset": offset,
                "data": base64.b64encode(data).decode()})
        self._om_mark([objno for objno, _, _ in
                       self._extents(offset, len(data))], self.OM_EXISTS)
        self._apply_write_ctx()
        pos = 0
        has_parent = self.parent() is not None
        for objno, off, ln in self._extents(offset, len(data)):
            piece = data[pos:pos + ln]
            pos += ln
            oid = self._obj(objno)
            if has_parent and self._needs_copyup(objno):
                op = self._copyup_op(objno).write(piece, off)
                r, _ = self.client.operate(self.data_pool, oid, op)
                if r == -17:    # lost the copyup race: object exists now
                    r = self.client.write(self.data_pool, oid, piece,
                                          off)
            else:
                r = self.client.write(self.data_pool, oid, piece, off)
            if r < 0:
                raise RBDError("write", r)
        self._journal_commit_applied()
        return len(data)

    def _needs_copyup(self, objno: int) -> bool:
        p = self.parent()
        if p is None or objno * self.object_size >= p[3]:
            return False
        try:
            self.client.stat(self.data_pool, self._obj(objno))
            return False
        except IOError as e:
            if _absent(e):
                return True
            raise

    def _copyup_data(self, objno: int) -> bytes:
        """The parent's bytes for this child object, clipped to the
        overlap (CopyupRequest)."""
        ln = min(self.object_size,
                 self.parent()[3] - objno * self.object_size)
        return self._parent_read(objno, 0, ln).rstrip(b"\x00")

    def _copyup_op(self, objno: int) -> ObjectOperation:
        """Vector prefix materializing the parent bytes in the child
        object, to be extended with the triggering mutation so both
        commit atomically.  The exclusive create guards the
        stat-then-copyup window: if another client copied up (and
        possibly wrote) since our stat, the vector aborts -EEXIST and
        the caller retries as a plain mutation instead of smearing
        parent bytes over committed data (the reference's guarded
        CopyupRequest)."""
        cdata = self._copyup_data(objno)
        op = ObjectOperation().create(exclusive=True)
        if cdata:
            op.write(cdata, 0)
        return op

    @_mutating
    def discard(self, offset: int, length: int) -> None:
        """Punch a hole (rbd_discard): whole objects are removed, edges
        are zeroed.  Inside a clone's parent overlap a hole must STAY a
        hole — removing the child object (or zeroing an absent one)
        would re-expose parent bytes on the next read, so there the
        discard materializes an explicit zero state instead (librbd
        turns such discards into truncate/zero whiteouts)."""
        if self.read_snap is not None:
            raise RBDError("discard", -30)
        if self.journaling:
            self._journal_event({"op": "discard", "offset": offset,
                                 "length": length})
        self._apply_write_ctx()
        p = self.parent()
        overlap = p[3] if p else 0
        for objno, off, ln in self._extents(offset, length):
            oid = self._obj(objno)
            in_overlap = objno * self.object_size < overlap
            if off == 0 and ln == self.object_size:
                if in_overlap:
                    op = ObjectOperation().create(exclusive=False)
                    r, _ = self.client.operate(self.data_pool, oid,
                                               op.truncate(0))
                    self._om_mark([objno], self.OM_EXISTS)
                else:
                    r = self.client.remove(self.data_pool, oid)
                    if r in (0, -2):
                        self._om_mark([objno], self.OM_NONE)
            elif in_overlap and self._needs_copyup(objno):
                self._om_mark([objno], self.OM_EXISTS)
                op = self._copyup_op(objno).zero(off, ln)
                r, _ = self.client.operate(self.data_pool, oid, op)
                if r == -17:
                    r = self.client.zero(self.data_pool, oid, off, ln)
            else:
                r = self.client.zero(self.data_pool, oid, off, ln)
                if r == 0:
                    # zeroing an EXISTING object changed its bytes:
                    # fast-diff must see it dirty (CLEAN would make
                    # export-diff skip the punched hole)
                    self._om_mark([objno], self.OM_EXISTS)
            if r < 0 and r != -2:
                raise RBDError("discard", r)
        self._journal_commit_applied()

    @_mutating
    def resize(self, new_size: int) -> None:
        """Grow adjusts metadata only (sparse); shrink removes/truncates
        objects beyond the new end (Operations::resize)."""
        old = self.size()
        if self.read_snap is not None:
            raise RBDError("resize", -30)
        if self.journaling:
            self._journal_event({"op": "resize", "size": new_size})
        if new_size < old:
            self._apply_write_ctx()
            keep_objs = self._objects_in(new_size)
            for objno in range(keep_objs, self._objects_in(old)):
                r = self.client.remove(self.data_pool, self._obj(objno))
                if r < 0 and r != -2:
                    raise RBDError("resize", r)
            tail = new_size - (keep_objs - 1) * self.object_size
            if keep_objs and tail < self.object_size:
                r = self.client.truncate(self.data_pool,
                                         self._obj(keep_objs - 1), tail)
                if r < 0 and r != -2:
                    raise RBDError("resize", r)
            if self.parent() is not None:
                self._call("set_parent_overlap", {"overlap": new_size},
                           parse=False)
                self._parent_link = self._fetch_parent()
        self._call("set_size", {"size": new_size}, parse=False)
        if self.object_map_feature:
            # shrink truncates the bitmap; grow extends with NONE
            m = self._om_load()
            n = self._objects_in(new_size)
            if len(m) > n:
                m = m[:n]
                # a partially-truncated tail object CHANGED: dirty it
                # or export-diff would skip it as CLEAN
                if n and new_size % self.object_size and \
                        m[n - 1] != self.OM_NONE:
                    m[n - 1] = self.OM_EXISTS
                self._om_save(m)
            elif len(m) < n:
                m.extend(b"\x00" * (n - len(m)))
                self._om_save(m)
        self._journal_commit_applied()

    # ---- snapshots --------------------------------------------------------
    @_mutating
    def snap_create(self, name: str) -> int:
        if self.journaling:
            self._journal_event({"op": "snap_create", "name": name})
        sid = self.client.selfmanaged_snap_create(self.data_pool)
        self._call("snapshot_add",
                   {"snapid": sid, "name": name, "size": self.size()},
                   parse=False)
        if self.object_map_feature:
            # freeze the bitmap as the snap's map, then mark every
            # existing head object CLEAN: fast-diff reads 'dirty since
            # the latest snap' straight off the head map
            m = self._om_load()
            self._om_save(bytearray(m), snapid=sid)
            self._om_save(bytearray(
                self.OM_CLEAN if b != self.OM_NONE else self.OM_NONE
                for b in m))
        self._journal_commit_applied()
        return sid

    @_mutating
    def snap_remove(self, name: str) -> None:
        sid, info = self._snap_by_name(name)
        if self.journaling:
            self._journal_event({"op": "snap_remove", "name": name})
        was_latest = sid == max(self._snapcontext()[1], default=sid)
        self._call("snapshot_remove", {"snapid": sid}, parse=False)
        self.client.selfmanaged_snap_remove(self.data_pool, sid)
        if self.object_map_feature:
            self.client.remove(self.pool, self._om_oid(sid))
            if was_latest:
                # CLEAN meant 'unchanged since sid'; with sid gone the
                # reference point is an OLDER snap we did not track
                # against — over-claim dirtiness (safe) rather than
                # let export-diff skip changed objects
                m = self._om_load()
                self._om_save(bytearray(
                    self.OM_EXISTS if b != self.OM_NONE else
                    self.OM_NONE for b in m))
        self._journal_commit_applied()

    def snap_list(self) -> Dict[str, Dict]:
        return {info["name"]: dict(info, id=sid)
                for sid, info in self._snapcontext()[1].items()}

    def snap_protect(self, name: str) -> None:
        sid, _ = self._snap_by_name(name)
        self._call("snapshot_protect", {"snapid": sid}, parse=False)

    def snap_unprotect(self, name: str) -> None:
        sid, _ = self._snap_by_name(name)
        kids = json.loads(self.client.exec(
            self.pool, RBD_CHILDREN, "rbd", "get_children",
            _j({"pool": self.pool, "image_id": self.id,
                "snapid": sid}))[1] or b"[]")
        if kids:
            raise RBDError("snap unprotect", -16)     # EBUSY
        self._call("snapshot_unprotect", {"snapid": sid}, parse=False)

    @_mutating
    def snap_rollback(self, name: str) -> None:
        """Restore the head to the snapshot's content (Operations::
        snap_rollback): resize to the snap size, then per-object restore
        reads at the snap and rewrites the head under the current ctx.

        Journaled as ONE semantic op event (the reference records an
        OpEvent, librbd/journal/Types.h SnapRollbackEvent) with the
        inner resize/write journaling suppressed: a mirror replays
        "roll back to snap X" against its own replicated snapshot, so
        primary and secondary converge even though the per-object
        restore I/O never crosses the journal."""
        sid, info = self._snap_by_name(name)
        if self.journaling:
            self._journal_event({"op": "snap_rollback", "name": name})
        was = self.journaling
        self.journaling = False
        try:
            self.resize(info["size"])
            self._apply_write_ctx()
            for objno in range(self._objects_in(info["size"])):
                oid = self._obj(objno)
                try:
                    snap_data = self.client.read(self.data_pool, oid,
                                                 snap=sid)
                    at_snap = True
                except IOError as e:
                    if not _absent(e):
                        raise
                    at_snap = False
                if at_snap:
                    r = self.client.write_full(self.data_pool, oid,
                                               snap_data)
                    if r < 0:
                        raise RBDError("snap rollback", r)
                else:
                    r = self.client.remove(self.data_pool, oid)
                    if r < 0 and r != -2:
                        raise RBDError("snap rollback", r)
        finally:
            self.journaling = was
        self.rebuild_object_map()
        self._journal_commit_applied()

    # ---- clone management -------------------------------------------------
    @_mutating
    def flatten(self) -> None:
        """Copy every parent-backed object into the child, then sever
        the parent link (Operations::flatten)."""
        p = self.parent()
        if p is None:
            raise RBDError("flatten", -22)
        ppool, pid, psnap, overlap = p
        self._apply_write_ctx()
        for objno in range(self._objects_in(min(overlap, self.size()))):
            if self._needs_copyup(objno):
                # same exclusive-create guard as write(): losing the
                # copyup race to a concurrent writer must skip, not
                # smear parent bytes over committed data
                r, _ = self.client.operate(
                    self.data_pool, self._obj(objno),
                    self._copyup_op(objno))
                if r < 0 and r != -17:
                    raise RBDError("flatten", r)
                self._om_mark([objno], self.OM_EXISTS)
        self._call("remove_parent", parse=False)
        self._parent_link = None
        self._parent_handle = None
        ret, _ = self.client.exec(
            ppool, RBD_CHILDREN, "rbd", "remove_child",
            _j({"pool": ppool, "image_id": pid, "snapid": psnap,
                "child_id": self.id}))
        if ret < 0 and ret != -2:
            raise RBDError("flatten", ret)

    # ---- diff export/import (rbd export-diff / import-diff; the
    # "rbd diff v1" stream: s=size, w=data extent, z=zero extent) ------
    def export_diff(self, from_snap: Optional[str] = None,
                    to_snap: Optional[str] = None) -> bytes:
        """Serialize the changes between two points in time (snap or
        head) as a record stream: [("s", size), ("w", off, b64data),
        ("z", off, len), ...].  Applying it with import_diff onto a
        copy taken at ``from_snap`` reproduces the ``to_snap`` state —
        the incremental-backup workflow (rbd export-diff)."""
        import base64
        src_from = (Image(self.client, self.pool, self.name,
                          snapshot=from_snap) if from_snap else None)
        src_to = (Image(self.client, self.pool, self.name,
                        snapshot=to_snap) if to_snap else self)
        records: List = [("s", src_to.size())]
        # fast-diff (librbd::ObjectMap): when diffing HEAD against the
        # LATEST snapshot, the head bitmap already says which objects
        # changed since it — CLEAN objects are skipped unread
        skip_clean = None
        if (self.object_map_feature and from_snap and to_snap is None
                and self.read_snap is None):
            snaps = self._snapcontext()[1]
            latest = max(snaps) if snaps else None
            if latest is not None and \
                    snaps[latest]["name"] == from_snap:
                skip_clean = self._om_load()
        # extents beyond the target size need no records: import_diff's
        # leading resize truncates them
        for objno in range(self._objects_in(src_to.size())):
            if skip_clean is not None and objno < len(skip_clean) \
                    and skip_clean[objno] == self.OM_CLEAN:
                continue
            off = objno * self.object_size
            ln = min(self.object_size, src_to.size() - off)
            new = src_to.read(off, ln) if ln > 0 else b""
            old = (src_from.read(off, min(self.object_size,
                                          src_from.size() - off))
                   if src_from and off < src_from.size() else b"")
            if new == old:
                continue
            if not new.strip(b"\x00"):
                if old:              # content became zeros: punch
                    records.append(("z", off, len(old)))
                continue
            records.append(("w", off,
                            base64.b64encode(new).decode()))
        return _j(records)

    def import_diff(self, blob: bytes) -> None:
        """Apply an export_diff stream (rbd import-diff)."""
        import base64
        for rec in json.loads(blob):
            kind = rec[0]
            if kind == "s":
                self.resize(rec[1])
            elif kind == "w":
                data = base64.b64decode(rec[2])
                self.write(rec[1], data)
            elif kind == "z":
                self.discard(rec[1], rec[2])

    # ---- advisory image locks (rbd lock add/ls/rm -> cls_lock on the
    # header object, librbd list_lockers/lock_exclusive) ---------------
    RBD_LOCK_NAME = "rbd_lock"

    def lock_exclusive(self, cookie: str = "") -> int:
        return self.client.lock_exclusive(self.pool, self._header,
                                          self.RBD_LOCK_NAME, cookie)

    def lock_shared(self, cookie: str = "", tag: str = "") -> int:
        return self.client.lock_shared(self.pool, self._header,
                                       self.RBD_LOCK_NAME, cookie, tag)

    def unlock(self, cookie: str = "") -> int:
        return self.client.unlock(self.pool, self._header,
                                  self.RBD_LOCK_NAME, cookie)

    def break_lock(self, entity: str, cookie: str = "") -> int:
        return self.client.break_lock(self.pool, self._header,
                                      self.RBD_LOCK_NAME, entity, cookie)

    def list_lockers(self) -> List[Dict]:
        return self.client.list_lockers(self.pool, self._header,
                                        self.RBD_LOCK_NAME)["lockers"]

    # ---- exclusive lock (librbd::ExclusiveLock) -----------------------
    def _watch_cb(self, _notify_id, payload) -> bytes:
        """Header watch callback — runs INSIDE a network pump, so it
        must not issue rados ops.  A lock request is answered by
        surrendering the lock state locally and letting the REQUESTER
        break the now-promised lock (the cooperative transition of
        ExclusiveLock::handle_peer_notification); 'busy' defers while
        a mutation is mid-flight."""
        try:
            req = json.loads(payload)
        except Exception:
            return b""
        if req.get("op") == "request_lock":
            if self._in_op and self._lock_owned:
                # only the OWNER mid-mutation defers; a fellow WAITER
                # being in-op must not veto breaking a dead owner's
                # lock (two waiters would deadlock each other)
                return b"busy"
            if self._lock_owned:
                self._lock_owned = False
                self._lock_surrendered = True
                # the next owner will mutate the object map: our
                # cached copy is stale the moment we surrender
                self._omap_cache = None
            return b"released"
        return b""

    def _ensure_exclusive_lock(self) -> None:
        """Auto-acquire on first mutation (ExclusiveLock.cc): try the
        cls lock; if another handle owns it, request a cooperative
        surrender over the header watch, breaking the lock once the
        owner promised (acked 'released') or proved dead (silent past
        the notify timeout)."""
        if not self.exclusive_lock_feature or self.read_snap is not None:
            return
        if self._lock_owned:
            return
        for attempt in range(30):
            r = self.lock_exclusive(self._lock_cookie)
            if r == -16:
                # a sibling handle on THIS client?  notify cannot reach
                # it (the OSD excludes the notifier's own watches), so
                # run the surrender round locally
                handled = False
                for lk in self.list_lockers():
                    if lk["entity"] != self.client.name:
                        continue
                    handled = True
                    import weakref
                    peers = _LOCAL_HANDLES.get(
                        (id(self.client), self._header),
                        weakref.WeakSet())
                    owner = next((img for img in peers
                                  if img is not self and
                                  img._lock_cookie == lk["cookie"]), None)
                    if owner is None or owner._watch_cb(
                            0, _j({"op": "request_lock"})) == b"released":
                        self.break_lock(lk["entity"], lk["cookie"])
                    # else: mid-op -> retry the round
                if handled:
                    continue
            if r == 0:
                self._lock_owned = True
                self._lock_surrendered = False
                if self._watch_cookie is None:
                    self._watch_cookie = self.client.watch(
                        self.pool, self._header, self._watch_cb)
                # another owner may have advanced the journal and the
                # object map while we were away: drop cached state so
                # the next use re-reads (a stale journal position
                # would reuse tids — the corruption this lock exists
                # to prevent)
                self._journal = None
                self._omap_cache = None
                return
            try:
                replies = self.client.notify(
                    self.pool, self._header,
                    _j({"op": "request_lock"}), timeout=5)
                # an EMPTY reply set means nobody is watching the
                # header: the owner's client is gone (the OSD pruned
                # its dead watch) — safe to break
                promised = (not replies) or any(
                    v == b"released" for v in replies.values())
            except NotifyTimeout as e:
                # the owner's client is dead (its watch never acked):
                # safe to break (ExclusiveLock's blacklist-and-break,
                # minus the blacklist)
                promised = True
                del e
            if promised:
                for lk in self.list_lockers():
                    self.break_lock(lk["entity"], lk["cookie"])
            # else: owner answered 'busy' mid-op — retry the round
        raise RBDError("exclusive lock", -110)

    def close(self) -> None:
        """Release the exclusive lock and the header watch (the
        ImageCtx close path).  Handles that acquired the lock pin
        themselves through the client's watch table until closed —
        long-lived clients should close handles they drop."""
        if self._lock_owned:
            try:
                self.unlock(self._lock_cookie)
            except Exception:
                pass
            self._lock_owned = False
        if self._watch_cookie is not None:
            try:
                self.client.unwatch(self.pool, self._header,
                                    self._watch_cookie)
            except Exception:
                pass
            self._watch_cookie = None

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- object map (librbd::ObjectMap; fast-diff substrate) ----------
    OM_NONE = 0          # OBJECT_NONEXISTENT
    OM_EXISTS = 1        # OBJECT_EXISTS (dirty since the last snap)
    OM_CLEAN = 3         # OBJECT_EXISTS_CLEAN (unchanged since it)

    def _om_oid(self, snapid: Optional[int] = None) -> str:
        base = f"rbd_object_map.{self.id}"
        return f"{base}.{snapid}" if snapid is not None else base

    def _om_load(self, snapid: Optional[int] = None) -> bytearray:
        if snapid is None and self._omap_cache is not None:
            return self._omap_cache
        try:
            data = self.client.read(self.pool, self._om_oid(snapid))
        except IOError as e:
            if not _absent(e):
                raise
            data = b""
        m = bytearray(data)
        if snapid is None:
            n = self._objects_in(self.size())
            if len(m) < n:
                m.extend(b"\x00" * (n - len(m)))
            self._omap_cache = m
        return m

    def _om_save(self, m: bytearray,
                 snapid: Optional[int] = None) -> None:
        self.client.write_full(self.pool, self._om_oid(snapid),
                               bytes(m))
        if snapid is None:
            self._omap_cache = m

    def _om_mark(self, objnos, state: int) -> None:
        """Update-before-write discipline: existence flips are
        persisted BEFORE the data mutation they describe, so a crash
        can only ever leave the map OVER-claiming (safe: fast-diff
        then includes an unchanged object, never misses a changed
        one)."""
        if not self.object_map_feature or self.read_snap is not None:
            return
        m = self._om_load()
        changed = False
        for o in objnos:
            if o >= len(m):
                m.extend(b"\x00" * (o + 1 - len(m)))
            if m[o] != state:
                m[o] = state
                changed = True
        if changed:
            self._om_save(m)

    def rebuild_object_map(self) -> None:
        """rbd object-map rebuild: re-derive the bitmap from reality."""
        if not self.object_map_feature:
            return
        n = self._objects_in(self.size())
        m = bytearray(n)
        for objno in range(n):
            try:
                self.client.stat(self.data_pool, self._obj(objno))
                m[objno] = self.OM_EXISTS
            except IOError as e:
                if not _absent(e):
                    raise
        self._om_save(m)

    def object_map(self, snap_name: Optional[str] = None) -> bytes:
        """The existence bitmap (one byte per object)."""
        sid = self._snap_by_name(snap_name)[0] if snap_name else None
        return bytes(self._om_load(sid))

    def du(self) -> Dict:
        """Provisioned vs used bytes (rbd du).  With the object-map
        feature this is O(map): existing objects contribute their full
        object span (the reference's fast-diff accounting); without
        it, each object is stat'ed."""
        provisioned = self.size()
        nobj = self._objects_in(provisioned)
        if self.object_map_feature:
            m = self._om_load(self.read_snap)
            used = 0
            for objno in range(min(nobj, len(m))):
                if m[objno] != self.OM_NONE:
                    used += min(self.object_size,
                                provisioned - objno * self.object_size)
            return {"provisioned": provisioned, "used": used}
        used = 0
        for objno in range(nobj):
            try:
                used += self.client.stat(self.data_pool,
                                         self._obj(objno),
                                         snap=self.read_snap)
            except IOError as e:
                if not _absent(e):
                    raise
        return {"provisioned": provisioned, "used": used}

    def stat(self) -> Dict:
        meta = self._call("get_image")
        return {"size": self.size(), "order": meta["order"],
                "data_pool": self.data_pool,
                "object_prefix": meta["object_prefix"],
                "num_objs": self._objects_in(meta["size"]),
                "parent": self.parent(),
                "snaps": sorted(self.snap_list())}


def apply_image_event(img: "Image", event: Dict) -> None:
    """Apply one journal event to an image (the librbd journal Replay
    handler's op table).  Events carry absolute extents, so re-applying
    is idempotent; journaling is suppressed on the target handle to
    avoid re-journaling replayed ops."""
    import base64
    was = img.journaling
    img.journaling = False          # never re-journal a replay
    try:
        op = event["op"]
        if op == "write":
            data = base64.b64decode(event["data"])
            end = event["offset"] + len(data)
            if end > img.size():
                img.resize(end)
            img.write(event["offset"], data)
        elif op == "discard":
            img.discard(event["offset"], event["length"])
        elif op == "resize":
            img.resize(event["size"])
        elif op == "snap_create":
            if event["name"] not in img.snap_list():
                img.snap_create(event["name"])
        elif op == "snap_remove":
            if event["name"] in img.snap_list():
                img.snap_remove(event["name"])
        elif op == "snap_rollback":
            # the snap replicated earlier in the same stream (its
            # snap_create event precedes this one), so rolling back by
            # name reproduces the primary's semantic rollback exactly
            img.snap_rollback(event["name"])
    finally:
        img.journaling = was
