"""librbd-lite: rados block images (src/librbd + src/cls/rbd).

Importing the package registers the ``rbd`` object class so any OSD in
the process can execute header methods, mirroring how the reference
loads libcls_rbd.so into every OSD.
"""
from . import cls_rbd  # noqa: F401  (registers the cls methods)
from .image import Image, RBD, RBDError, apply_image_event
from .mirror import ImageMirror, PoolMirror

__all__ = ["Image", "ImageMirror", "PoolMirror", "RBD", "RBDError",
           "apply_image_event"]
