"""Compressor plugin registry — src/compressor/ analog.

The reference ships a compression plugin framework that mirrors the EC
plugin registry (compressor/CompressionPlugin.h; registry/factory like
ErasureCodePlugin.cc:126-184, used by BlueStore's compress-on-write and
the messenger).  Same shape here: named plugins registered in a
singleton, a factory resolving name -> instance, and a stable
Compressor interface (compressor/Compressor.h: compress/decompress over
buffers).

Plugins: zlib (always present — stdlib), and snappy/zstd/lz4 which
register only when their python bindings exist in the image (the
reference similarly builds plugins conditionally).  The "none"
passthrough matches Compressor::COMP_ALG_NONE.

Compression is host-side by design: it serves the storage/wire path,
not the device compute path (BlueStore itself is out of scope per
SURVEY §2.9; the consumer here is checkpoint/export files and any
TCP-messenger payload compression).
"""
from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional


class Compressor:
    """compressor/Compressor.h interface."""

    name = "none"

    def compress(self, data: bytes) -> bytes:
        return bytes(data)

    def decompress(self, data: bytes) -> bytes:
        return bytes(data)


class NoneCompressor(Compressor):
    name = "none"


class ZlibCompressor(Compressor):
    """compressor/zlib plugin (the reference's default alongside snappy)."""

    name = "zlib"

    def __init__(self, level: int = 5):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(bytes(data), self.level)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(bytes(data))


def _try_snappy() -> Optional[type]:
    try:
        import snappy

        class SnappyCompressor(Compressor):
            name = "snappy"

            def compress(self, data: bytes) -> bytes:
                return snappy.compress(bytes(data))

            def decompress(self, data: bytes) -> bytes:
                return snappy.decompress(bytes(data))

        return SnappyCompressor
    except ImportError:
        return None


def _try_zstd() -> Optional[type]:
    try:
        import zstandard

        class ZstdCompressor(Compressor):
            name = "zstd"

            def __init__(self):
                self._c = zstandard.ZstdCompressor()
                self._d = zstandard.ZstdDecompressor()

            def compress(self, data: bytes) -> bytes:
                return self._c.compress(bytes(data))

            def decompress(self, data: bytes) -> bytes:
                return self._d.decompress(bytes(data))

        return ZstdCompressor
    except ImportError:
        return None


def _try_lz4() -> Optional[type]:
    try:
        import lz4.frame

        class Lz4Compressor(Compressor):
            name = "lz4"

            def compress(self, data: bytes) -> bytes:
                return lz4.frame.compress(bytes(data))

            def decompress(self, data: bytes) -> bytes:
                return lz4.frame.decompress(bytes(data))

        return Lz4Compressor
    except ImportError:
        return None


class CompressorRegistry:
    """CompressionPluginRegistry analog: names -> factories, preloaded
    with whatever this environment can supply."""

    def __init__(self):
        self._factories: Dict[str, Callable[[], Compressor]] = {}
        self.register("none", NoneCompressor)
        self.register("zlib", ZlibCompressor)
        for probe in (_try_snappy, _try_zstd, _try_lz4):
            cls = probe()
            if cls is not None:
                self.register(cls.name, cls)

    def register(self, name: str,
                 factory: Callable[[], Compressor]) -> None:
        self._factories[name] = factory

    def supported(self) -> List[str]:
        return sorted(self._factories)

    def create(self, name: str) -> Compressor:
        factory = self._factories.get(name)
        if factory is None:
            raise KeyError(
                f"unsupported compressor {name!r}; "
                f"available: {self.supported()}")
        return factory()


g_compressor_registry = CompressorRegistry()


def create_compressor(name: str) -> Compressor:
    """Factory (Compressor::create role)."""
    return g_compressor_registry.create(name)
