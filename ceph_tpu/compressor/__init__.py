from .registry import (
    Compressor, CompressorRegistry, create_compressor, g_compressor_registry,
)

__all__ = ["Compressor", "CompressorRegistry", "create_compressor",
           "g_compressor_registry"]
