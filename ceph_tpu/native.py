"""ctypes bindings to the native C++ runtime (native/libceph_tpu_native.so).

The native library supplies:
- an independent CRUSH map evaluator (cross-validates the Python mapper and
  serves as the threaded CPU batch baseline, the ParallelPGMapper analog);
- GF(2^8) region encode (the isa-l ec_encode_data-class CPU path used as
  the benchmark baseline);
- crc32c for chunk HashInfo.

Builds on demand with the repo's Makefile (g++ -O3 -march=native).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .common.lockdep import DebugLock
from typing import List, Optional, Sequence

import numpy as np

from .crush.constants import (
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM,
)
from .crush.types import CrushMap

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_ROOT, "native")
_SO = os.path.join(_NATIVE_DIR, "libceph_tpu_native.so")

_lock = DebugLock("native::load")
_lib: Optional[ctypes.CDLL] = None


def build_native() -> str:
    subprocess.run(["make", "-s", "-C", _NATIVE_DIR], check=True)
    return _SO


def get_lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < max(
                    os.path.getmtime(os.path.join(_NATIVE_DIR, f))
                    for f in ("crush_mapper.cpp", "gf_rs.cpp"))):
            build_native()
        lib = ctypes.CDLL(_SO)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        # argtypes are mandatory: passing python ints for int64_t params
        # without them leaves the upper register bits undefined (SysV ABI)
        lib.crush_set_ln_tables.argtypes = [i64p, i64p]
        lib.crush_do_rule_c.restype = ctypes.c_int
        lib.crush_do_rule_c.argtypes = [
            i64p, ctypes.c_int64, ctypes.c_int, ctypes.c_int64, i64p,
            ctypes.c_int, u32p, ctypes.c_int64]
        lib.crush_do_rule_batch.restype = ctypes.c_int
        lib.crush_do_rule_batch.argtypes = [
            i64p, ctypes.c_int64, ctypes.c_int, i64p, ctypes.c_int64, i64p,
            ctypes.c_int, i32p, u32p, ctypes.c_int64]
        lib.gf_rs_encode.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, u8p, u8p, ctypes.c_int64]
        lib.gf_region_xor.argtypes = [u8p, u8p, u8p, ctypes.c_int64]
        lib.ceph_crc32c.restype = ctypes.c_uint32
        lib.ceph_crc32c.argtypes = [ctypes.c_uint32, u8p, ctypes.c_int64]
        lib.gf_mul_c.restype = ctypes.c_uint8
        lib.gf_mul_c.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
        # inject the ln tables once
        from .crush.ln import RH_LH_NP, LL_NP
        rh = RH_LH_NP.astype(np.int64)
        llt = LL_NP.astype(np.int64)
        lib.crush_set_ln_tables(
            rh.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            llt.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        get_lib()
        return True
    except Exception:
        return False


# ---- crush ----------------------------------------------------------------

def serialize_map(m: CrushMap, choose_args=None) -> np.ndarray:
    """Flatten a CrushMap into the int64 blob the native parser reads.

    ``choose_args`` (crush.h crush_choose_arg: per-bucket id overrides
    for hashing plus per-position weight_set replacements) serialize as
    a trailing section; absent section == no overrides."""
    out: List[int] = [
        m.max_devices, m.choose_local_tries, m.choose_local_fallback_tries,
        m.choose_total_tries, m.chooseleaf_descend_once,
        m.chooseleaf_vary_r, m.chooseleaf_stable,
        m.max_buckets, m.max_rules,
    ]
    for b in m.buckets:
        if b is None:
            out.append(0)
            continue
        out += [1, b.id, b.alg, b.type, b.size]
        out += list(b.items)
        if b.alg == CRUSH_BUCKET_UNIFORM:
            out.append(b.item_weight)
        elif b.alg == CRUSH_BUCKET_LIST:
            out += list(b.item_weights) + list(b.sum_weights)
        elif b.alg == CRUSH_BUCKET_TREE:
            out.append(b.num_nodes)
            out += list(b.node_weights)
        elif b.alg == CRUSH_BUCKET_STRAW:
            out += list(b.item_weights) + list(b.straws)
        elif b.alg == CRUSH_BUCKET_STRAW2:
            out += list(b.item_weights)
        else:
            raise ValueError(f"bucket alg {b.alg}")
    for r in m.rules:
        if r is None:
            out.append(0)
            continue
        out += [1, r.ruleset, r.type, r.min_size, r.max_size, len(r.steps)]
        for s in r.steps:
            out += [s.op, s.arg1, s.arg2]
    entries = []
    if choose_args is not None:
        for bno, arg in enumerate(choose_args):
            if arg is None or (not arg.ids and not arg.weight_set):
                continue
            b = m.buckets[bno] if bno < len(m.buckets) else None
            if b is None:
                continue
            # the C++ parser advances by b.size per row — a mismatched
            # arg (e.g. from an externally decoded binary map) must
            # fail LOUDLY here, not parse misaligned and silently
            # return wrong placements
            if arg.ids and len(arg.ids) != b.size:
                raise ValueError(
                    f"choose_args ids len {len(arg.ids)} != bucket "
                    f"size {b.size} (bucket index {bno})")
            for ws in arg.weight_set or []:
                if len(ws.weights) != b.size:
                    raise ValueError(
                        f"choose_args weight_set row len "
                        f"{len(ws.weights)} != bucket size {b.size} "
                        f"(bucket index {bno})")
            ent = [bno, 1 if arg.ids else 0, b.size]
            if arg.ids:
                ent += list(arg.ids)
            npos = len(arg.weight_set) if arg.weight_set else 0
            ent.append(npos)
            for ws in arg.weight_set or []:
                ent += list(ws.weights)
            entries.append(ent)
    out.append(len(entries))
    for ent in entries:
        out += ent
    return np.array(out, dtype=np.int64)


class NativeCrushMapper:
    """Batch CRUSH evaluation through the C++ engine."""

    def __init__(self, m: CrushMap, choose_args=None):
        self.lib = get_lib()
        self.blob = serialize_map(m, choose_args)

    def do_rule(self, ruleno: int, x: int, result_max: int,
                weight: Sequence[int]) -> List[int]:
        res = np.zeros(result_max, dtype=np.int64)
        w = np.asarray(weight, dtype=np.uint32)
        n = self.lib.crush_do_rule_c(
            self.blob.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(self.blob), ruleno, x,
            res.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), result_max,
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(w))
        if n < 0:
            raise RuntimeError("native map parse failed")
        return res[:n].tolist()

    def do_rule_batch(self, ruleno: int, xs: Sequence[int], result_max: int,
                      weight: Sequence[int]):
        """Returns (out (nx, result_max) int64 NONE-padded, lens (nx,))."""
        xs = np.asarray(xs, dtype=np.int64)
        out = np.zeros((len(xs), result_max), dtype=np.int64)
        lens = np.zeros(len(xs), dtype=np.int32)
        w = np.asarray(weight, dtype=np.uint32)
        rc = self.lib.crush_do_rule_batch(
            self.blob.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(self.blob), ruleno,
            xs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(xs),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), result_max,
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(w))
        if rc < 0:
            raise RuntimeError("native map parse failed")
        return out, lens


# ---- gf -------------------------------------------------------------------

def native_rs_encode(matrix_rows: np.ndarray, data: np.ndarray) -> np.ndarray:
    """rows (r, k) x data (k, n) -> (r, n) over GF(2^8), C++ path."""
    lib = get_lib()
    r, k = matrix_rows.shape
    kk, n = data.shape
    assert k == kk
    mat = np.ascontiguousarray(matrix_rows, dtype=np.uint8)
    dat = np.ascontiguousarray(data, dtype=np.uint8)
    out = np.zeros((r, n), dtype=np.uint8)
    lib.gf_rs_encode(
        mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), r, k,
        dat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(n))
    return out


def crc32c(data: bytes, crc: int = 0xFFFFFFFF) -> int:
    """Ceph-convention crc32c: raw castagnoli update, no pre/post inversion
    (reference include/crc32c.h); Ceph callers seed with -1."""
    lib = get_lib()
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray) else data
    return int(lib.ceph_crc32c(
        ctypes.c_uint32(crc),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(len(buf))))
