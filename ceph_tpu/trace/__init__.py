"""Unified observability layer: spans, perf histograms, flight recorder.

The runtime-side counterpart of the bench subsystem's measurement rigor
(PR 1): cross-daemon span trees keyed by the trace id every message
already carries (msg/Message.h:254's ZTracer slot), reference-shaped 2D
PerfHistograms (src/common/perf_histogram.h), and a slow-op flight
recorder feeding ``dump_historic_slow_ops``.  Export rides the admin
socket, the mgr's Prometheus renderer, and ``python -m ceph_tpu.bench``.

Everything here is sync-free by construction: spans and histogram
increments never touch the device, so the default-off tracer adds zero
``block_until_ready``/drain calls to any hot path.
"""
from .span import Span, SpanCollector, Tracer, build_tree, g_tracer
from .histogram import (
    PerfHistogram, PerfHistogramAxis, PerfHistogramCollection,
    SCALE_LINEAR, SCALE_LOG2, g_perf_histograms, latency_axes,
    latency_in_bytes_axes, occupancy_axes, pipeline_axes,
)
from .flight import FlightEntry, FlightRecorder, g_flight_recorder
from .devprof import (DevFlowProfiler, devflow_delta,
                      devprof_perf_counters, g_devprof,
                      transfer_size_axes)
from .oplat import (OpLedger, OpLatAccumulator, STAGES, g_oplat,
                    oplat_perf_counters)
from .journal import (EVENT_TYPES, EventJournal, g_journal,
                      journal_perf_counters)

__all__ = [
    "Span", "SpanCollector", "Tracer", "build_tree", "g_tracer",
    "PerfHistogram", "PerfHistogramAxis", "PerfHistogramCollection",
    "SCALE_LINEAR", "SCALE_LOG2", "g_perf_histograms", "latency_axes",
    "latency_in_bytes_axes", "occupancy_axes", "pipeline_axes",
    "FlightEntry", "FlightRecorder", "g_flight_recorder",
    "DevFlowProfiler", "devflow_delta", "devprof_perf_counters",
    "g_devprof", "transfer_size_axes",
    "OpLedger", "OpLatAccumulator", "STAGES", "g_oplat",
    "oplat_perf_counters",
    "EVENT_TYPES", "EventJournal", "g_journal",
    "journal_perf_counters",
]
