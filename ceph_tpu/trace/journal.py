"""Cluster event journal — the always-on black box.

Every daemon appends typed structured events (health transitions,
breaker trips, chip SUSPECT verdicts, control actuations, fault
injections, OSD state changes, mon elections, slow ops, SLO streaks)
to a bounded per-daemon ring.  Each event is stamped with the
deterministic cluster clock — set once per mgr tick, never read from
the wall — plus a per-daemon monotone sequence number and a
process-global sequence number ``gseq``.

``gseq`` is the causal merge key: the cluster is a single process, so
emission order IS causal order; the clock is a human-readable stamp,
not the sort key.  ``merged()`` returns one cluster timeline ordered
by ``gseq`` — the same rollup discipline as ``Telemetry.rollup``, but
for discrete events instead of gauges.

Emission is pure host work: one lock (``EventJournal::lock``, taken
last — emitters may hold their own lock, the journal never takes
theirs) and a list append.  Zero device syncs by construction.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..common.config import g_conf
from ..common.lockdep import DebugLock
from ..common.perf_counters import PerfCounters, PerfCountersBuilder

# ---------------------------------------------------------------------------
# event catalog — every type the cluster can journal (docs/OBSERVABILITY.md
# "Event journal & incident forensics" documents each one)

EVENT_TYPES = (
    "health_raise",        # mgr: a health check entered health_checks
    "health_clear",        # mgr: a health check left health_checks
    "breaker_trip",        # fault: consecutive failures opened a breaker
    "breaker_half_open",   # fault: half-open probe failed, cooldown re-armed
    "breaker_restore",     # fault: success closed an open breaker
    "chip_suspect_mark",   # mesh: chip crossed the skew streak threshold
    "chip_suspect_clear",  # mesh: chip produced enough clean probes
    "control_actuate",     # mgr: controller applied a knob move
    "control_restore",     # mgr: controller teardown restored a knob to base
    "control_pinned",      # mgr: a reflex wanted to move a hand-pinned knob
    "fault_arm",           # fault: a FaultSpec was injected at a site
    "fault_fire",          # fault: an armed spec fired at its site
    "fault_clear",         # fault: spec(s) cleared from a site
    "osd_up",              # mon: osd marked up
    "osd_down",            # mon: osd marked down
    "osd_out",             # mon: osd marked out
    "osd_in",              # mon: osd marked in
    "mon_election",        # mon: election decided, quorum formed
    "slow_op",             # osd: op exceeded complaint_time
    "slo_streak",          # mgr: SLO sustain/clear streak opened
    "incident_capture",    # mgr: incident bundle captured into the archive
    "incident_drop",       # mgr: capture failed, bundle dropped
    "incident_resolve",    # mgr: open incident's triggering check cleared
    "mesh_chip_add",       # mesh: elastic membership grew the dispatch mesh
    "mesh_chip_retire",    # mesh: elastic membership retired mesh chip(s)
    "mesh_decode_degraded",  # mesh: meshed decode/repair fell back to
                             # the single-device path (guard exhausted)
    "chaos_scenario_start",  # chaos: a composed storyline began executing
    "chaos_event",         # chaos: one scheduled storyline step fired
    "chaos_scenario_end",  # chaos: storyline finished, acceptance judged
)

_EVENT_SET = frozenset(EVENT_TYPES)

# ---------------------------------------------------------------------------
# perf counters — logger "journal" (rendered ceph_daemon_journal_*)

JOURNAL_FIRST = 95100
l_journal_events = 95101       # events appended across all daemon rings
l_journal_evictions = 95102    # events evicted by the bounded ring
l_journal_resets = 95103       # operator journal resets
JOURNAL_LAST = 95110

_journal_pc: Optional[PerfCounters] = None
_journal_pc_lock = DebugLock("journal_pc::init")


def journal_perf_counters() -> PerfCounters:
    global _journal_pc
    if _journal_pc is None:
        with _journal_pc_lock:
            if _journal_pc is None:
                b = PerfCountersBuilder("journal", JOURNAL_FIRST,
                                        JOURNAL_LAST)
                b.add_u64_counter(l_journal_events, "events",
                                  "Events appended to daemon journals")
                b.add_u64_counter(l_journal_evictions, "evictions",
                                  "Events evicted from bounded rings")
                b.add_u64_counter(l_journal_resets, "resets",
                                  "Operator journal resets")
                _journal_pc = b.create_perf_counters()
    return _journal_pc


class EventJournal:
    """Bounded per-daemon rings of typed events, merged on demand.

    The ring bound is read live from ``mgr_journal_ring_size`` on
    every append, so ``injectargs`` takes effect immediately — a
    shrink evicts down to the new bound on the next emit.
    """

    def __init__(self) -> None:
        self._lock = DebugLock("EventJournal::lock")
        self._rings: Dict[str, List[dict]] = {}
        self._seq: Dict[str, int] = {}
        self._gseq = 0
        self._clock = 0.0

    # -- clock ----------------------------------------------------------
    def set_clock(self, now: float) -> None:
        """Stamp clock for subsequent events (mgr tick sets this)."""
        with self._lock:
            self._clock = float(now)

    def clock(self) -> float:
        with self._lock:
            return self._clock

    # -- emission -------------------------------------------------------
    def emit(self, daemon: str, etype: str, **fields: Any) -> dict:
        """Append one typed event to *daemon*'s ring.

        Takes only the journal's own lock — callers may already hold
        theirs (ChipStat::lock, OpTracker::lock, ...).  Never raises
        past a bad event type; unknown types mean a coding error.
        """
        if etype not in _EVENT_SET:
            raise ValueError(f"unknown journal event type '{etype}'")
        try:
            cap = int(g_conf.get_val("mgr_journal_ring_size"))
        except KeyError:
            cap = 256
        evicted = 0
        with self._lock:
            self._gseq += 1
            seq = self._seq.get(daemon, 0) + 1
            self._seq[daemon] = seq
            ev = {"gseq": self._gseq, "seq": seq, "daemon": daemon,
                  "clock": round(self._clock, 3), "type": etype}
            ev.update(fields)
            ring = self._rings.setdefault(daemon, [])
            ring.append(ev)
            if cap > 0 and len(ring) > cap:
                evicted = len(ring) - cap
                del ring[:evicted]
        pc = journal_perf_counters()
        pc.inc(l_journal_events)
        if evicted:
            pc.inc(l_journal_evictions, evicted)
        return ev

    # -- read side ------------------------------------------------------
    def merged(self, tail: int = 0) -> List[dict]:
        """One cluster timeline, causally ordered by ``gseq``."""
        with self._lock:
            events: List[dict] = []
            for ring in self._rings.values():
                events.extend(ring)
        events.sort(key=lambda e: e["gseq"])
        if tail > 0:
            events = events[-tail:]
        return [dict(e) for e in events]

    def merged_since(self, gseq: int, tail: int = 0) -> List[dict]:
        """Events with ``gseq`` strictly greater than *gseq*."""
        events = [e for e in self.merged() if e["gseq"] > gseq]
        if tail > 0:
            events = events[-tail:]
        return events

    def last_gseq(self) -> int:
        with self._lock:
            return self._gseq

    def dump(self, daemon: str = "") -> dict:
        """asok ``journal dump`` shape."""
        with self._lock:
            names = [daemon] if daemon else sorted(self._rings)
            out = {
                "clock": round(self._clock, 3),
                "gseq": self._gseq,
                "daemons": {
                    d: {"seq": self._seq.get(d, 0),
                        "events": [dict(e)
                                   for e in self._rings.get(d, [])]}
                    for d in names
                },
            }
        return out

    def reset(self) -> dict:
        """Operator ``journal reset`` — drop all rings, keep sequences
        (they are monotone per daemon for the process lifetime)."""
        with self._lock:
            dropped = sum(len(r) for r in self._rings.values())
            self._rings.clear()
        journal_perf_counters().inc(l_journal_resets)
        return {"dropped": dropped}


# process-wide journal, like g_tracer / g_faults
g_journal = EventJournal()
