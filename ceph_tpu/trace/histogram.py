"""PerfHistogram — log2-bucketed 1D/2D distributions.

The reference's 2D latency×bytes surface (src/common/perf_histogram.h,
dumped as ``op_w_latency_in_bytes_histogram`` under ``perf histogram
dump``): each axis declares (name, min, quant_size, buckets,
scale_type), a sample lands in one cell, and the dump carries the axis
configs next to the full count grid so a consumer can reconstruct
bucket bounds without out-of-band knowledge.

Bucketing matches the reference's get_bucket_for_axis: values below
``min`` land in bucket 0; otherwise ``d = (value - min) // quant_size``
and log2 axes place d in bucket ``min(1 + bit_length(d), buckets-1)``
(linear: ``min(1 + d, buckets-1)``).  The last bucket is the overflow.

Histograms are always-on like perf counters: incrementing is host-side
integer math under a lock — no device syncs, no allocation per sample —
so the write path keeps them hot in production.
"""
from __future__ import annotations

import math
import threading

from ..common.lockdep import DebugLock
from typing import Dict, Iterable, List, Optional, Tuple

SCALE_LINEAR = "linear"
SCALE_LOG2 = "log2"


class PerfHistogramAxis:
    __slots__ = ("name", "min", "quant_size", "buckets", "scale_type")

    def __init__(self, name: str, min: int = 0, quant_size: int = 1,
                 buckets: int = 32, scale_type: str = SCALE_LOG2):
        assert buckets >= 2, "need at least an underflow + one bucket"
        assert quant_size >= 1
        self.name = name
        self.min = min
        self.quant_size = quant_size
        self.buckets = buckets
        self.scale_type = scale_type

    def bucket_for(self, value: float) -> int:
        v = int(value)
        if v < self.min:
            return 0
        d = (v - self.min) // self.quant_size
        if self.scale_type == SCALE_LINEAR:
            return min(1 + d, self.buckets - 1)
        return min(1 + int(d).bit_length(), self.buckets - 1)

    def upper_edges(self) -> List[float]:
        """Exclusive upper bound of every bucket, in the axis's raw
        unit; the last bucket's bound is +inf (overflow)."""
        edges: List[float] = [float(self.min)]          # bucket 0: < min
        for b in range(1, self.buckets - 1):
            if self.scale_type == SCALE_LINEAR:
                edges.append(float(self.min + b * self.quant_size))
            else:
                edges.append(float(self.min
                                   + self.quant_size * (1 << (b - 1))))
        edges.append(float("inf"))
        return edges

    def dump_config(self) -> dict:
        return {"name": self.name, "min": self.min,
                "quant_size": self.quant_size, "buckets": self.buckets,
                "scale_type": self.scale_type}


class PerfHistogram:
    """N-dimensional counts grid (1D and 2D used here), thread-safe."""

    def __init__(self, axes: List[PerfHistogramAxis]):
        assert axes, "at least one axis"
        self.axes = list(axes)
        n = 1
        for ax in self.axes:
            n *= ax.buckets
        self._counts = [0] * n
        self._lock = DebugLock("PerfHistogram::lock")
        # axis-0 raw-value accounting for _sum/_count exposition
        self.total_count = 0
        self.axis0_sum = 0.0

    def inc(self, *values: float) -> None:
        assert len(values) == len(self.axes)
        idx = 0
        for ax, v in zip(self.axes, values):
            idx = idx * ax.buckets + ax.bucket_for(v)
        with self._lock:
            self._counts[idx] += 1
            self.total_count += 1
            self.axis0_sum += float(values[0])

    # ---- views ------------------------------------------------------------
    def _grid(self) -> list:
        """Counts as nested lists matching the axis order."""
        with self._lock:
            flat = list(self._counts)
        shape = [ax.buckets for ax in self.axes]

        def nest(offset: int, dims: List[int]):
            if len(dims) == 1:
                return flat[offset:offset + dims[0]]
            stride = 1
            for d in dims[1:]:
                stride *= d
            return [nest(offset + i * stride, dims[1:])
                    for i in range(dims[0])]

        return nest(0, shape)

    def marginal_axis0(self) -> List[int]:
        """Per-bucket counts over axis 0, summed across all other axes."""
        with self._lock:
            flat = list(self._counts)
        b0 = self.axes[0].buckets
        stride = len(flat) // b0
        return [sum(flat[i * stride:(i + 1) * stride]) for i in range(b0)]

    def cumulative_axis0(self) -> List[Tuple[float, int]]:
        """(upper_edge, cumulative_count) per axis-0 bucket — the
        Prometheus ``le`` series shape (monotone by construction)."""
        counts = self.marginal_axis0()
        edges = self.axes[0].upper_edges()
        out: List[Tuple[float, int]] = []
        cum = 0
        for edge, cnt in zip(edges, counts):
            cum += cnt
            out.append((edge, cum))
        return out

    def dump(self) -> dict:
        """The reference's dump shape: axis configs + full count grid."""
        return {"axes": [ax.dump_config() for ax in self.axes],
                "values": self._grid(),
                "count": self.total_count,
                "axis0_sum": self.axis0_sum}

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self.total_count = 0
            self.axis0_sum = 0.0


class PerfHistogramCollection:
    """(logger, histogram-name) registry dumped by ``perf histogram
    dump`` and scraped by the mgr's Prometheus renderer."""

    def __init__(self):
        self._hists: Dict[Tuple[str, str], PerfHistogram] = {}
        self._lock = DebugLock("PerfHistogramRegistry::lock")

    def get(self, logger: str, name: str,
            axes_factory=None) -> PerfHistogram:
        """Fetch-or-create; *axes_factory* is a zero-arg callable
        returning the axis list (only invoked on first creation, so a
        restarted daemon reattaches to its existing histogram)."""
        key = (logger, name)
        with self._lock:
            hist = self._hists.get(key)
            if hist is None:
                if axes_factory is None:
                    raise KeyError(f"histogram {key!r} not registered")
                hist = self._hists[key] = PerfHistogram(axes_factory())
            return hist

    def items(self) -> List[Tuple[Tuple[str, str], PerfHistogram]]:
        with self._lock:
            return sorted(self._hists.items())

    def dump(self, logger: str = "", name: str = ""
             ) -> Dict[str, Dict[str, dict]]:
        out: Dict[str, Dict[str, dict]] = {}
        for (lg, nm), hist in self.items():
            if (logger and lg != logger) or (name and nm != name):
                continue
            out.setdefault(lg, {})[nm] = hist.dump()
        return out

    def reset(self) -> None:
        for _key, hist in self.items():
            hist.reset()


g_perf_histograms = PerfHistogramCollection()


# ---- percentile helpers (shared by load.traffic and trace.oplat) ----------
def decumulate(pts: List[Tuple[float, int]]) -> List[int]:
    """Cumulative (edge, count) series -> raw per-bucket counts."""
    counts, prev = [], 0
    for _edge, cum in pts:
        counts.append(cum - prev)
        prev = cum
    return counts


def percentiles_from_counts(counts: List[int], edges: List[float],
                            qs=(0.5, 0.99),
                            suffix: str = "") -> Dict[str, float]:
    """``{"p50<suffix>": edge, ...}`` over raw per-bucket counts: each
    value is the EXCLUSIVE upper edge of the bucket the quantile falls
    in; the overflow bucket reports the last finite edge (a lower
    bound).  One implementation for every percentile consumer
    (``latency dump``, the bench stage_breakdown deltas, the traffic
    harness's per-client series) so the quantile rule cannot drift."""
    total = sum(counts)
    finite = [e for e in edges if e != float("inf")]
    out: Dict[str, float] = {}
    for q in qs:
        key = "p" + format(q * 100, "g").replace(".", "") + suffix
        if total <= 0:
            out[key] = 0.0
            continue
        target = math.ceil(q * total)
        cum = 0
        for edge, cnt in zip(edges, counts):
            cum += cnt
            if cum >= target:
                out[key] = edge if edge != float("inf") \
                    else (finite[-1] if finite else 0.0)
                break
    return out


def hist_percentiles(hist, qs=(0.5, 0.99, 0.999)) -> Dict[str, float]:
    """``{"p50": value, ...}`` read from anything exposing the
    ``cumulative_axis0()`` series shape (a PerfHistogram, or a merged
    stand-in).  THE percentile reader every consumer shares — the
    traffic harness's per-client tables, ``latency dump``, the bench
    stage_breakdown deltas, and the mgr telemetry rollup — so the
    quantile rule cannot drift between surfaces."""
    pts = hist.cumulative_axis0()
    return percentiles_from_counts(decumulate(pts),
                                   [e for e, _c in pts], qs)


def merge_axis0(hists) -> Tuple[List[float], List[int]]:
    """The cluster-rollup merge core: per-bucket axis-0 counts summed
    across *hists* (the union distribution).  Every histogram must
    share the axis-0 edge layout — same-named families across daemons
    do by construction (one axes factory per family); a mismatch is a
    programming error and raises rather than silently mis-bucketing.
    Returns ``(upper_edges, summed_counts)``; percentiles of the
    merged series are EXACTLY the percentiles of the union of the
    per-daemon samples (same edges, so no re-bucketing error)."""
    edges: List[float] = []
    counts: List[int] = []
    for h in hists:
        e = h.axes[0].upper_edges()
        c = h.marginal_axis0()
        if not edges:
            edges, counts = e, list(c)
            continue
        if e != edges:
            raise ValueError(
                f"cannot merge histograms with different axis-0 edges "
                f"({h.axes[0].dump_config()})")
        counts = [a + b for a, b in zip(counts, c)]
    return edges, counts


def merged_percentiles(hists, qs=(0.5, 0.99, 0.999),
                       suffix: str = "") -> Dict[str, float]:
    """Percentiles of the union of same-edged histograms (cluster-level
    tail: ONE number per quantile, not one per daemon)."""
    edges, counts = merge_axis0(hists)
    return percentiles_from_counts(counts, edges, qs, suffix=suffix)


# ---- standard axis shapes (the reference's l_osd histogram configs) ------
def latency_in_bytes_axes() -> List[PerfHistogramAxis]:
    """2D latency(usec, log2) x request-size(bytes, log2) — the
    ``op_w_latency_in_bytes_histogram`` shape (OSD.cc histogram setup:
    latency quant 100 usec, size quant 512 B, 32 log2 buckets each)."""
    return [PerfHistogramAxis("latency_usec", min=0, quant_size=100,
                              buckets=32, scale_type=SCALE_LOG2),
            PerfHistogramAxis("request_size_bytes", min=0, quant_size=512,
                              buckets=32, scale_type=SCALE_LOG2)]


def latency_axes() -> List[PerfHistogramAxis]:
    """1D latency(usec, log2) — request-handling paths with no natural
    byte axis (MDS requests, CRUSH batch mapping)."""
    return [PerfHistogramAxis("latency_usec", min=0, quant_size=100,
                              buckets=32, scale_type=SCALE_LOG2)]


def occupancy_axes() -> List[PerfHistogramAxis]:
    """1D batch occupancy (requests per coalesced device flush) —
    linear unit buckets.  Occupancies 0..64 are individually visible
    (value v lands in bucket 1+v, the last bucket is overflow), so a
    FULL default-sized batch (ec_dispatch_batch_max = 64) has its own
    bucket instead of vanishing into +Inf."""
    return [PerfHistogramAxis("batch_occupancy", min=0, quant_size=1,
                              buckets=67, scale_type=SCALE_LINEAR)]


def pipeline_axes() -> List[PerfHistogramAxis]:
    """1D EC write-pipeline occupancy (ops in flight in the per-PG
    window at encode-submit time) — linear unit buckets, dimensionless
    like occupancy_axes (the mgr renderer exports raw bucket edges).
    Depths 0..32 are individually visible; deeper windows overflow into
    the last bucket."""
    return [PerfHistogramAxis("pipeline_inflight", min=0, quant_size=1,
                              buckets=35, scale_type=SCALE_LINEAR)]
