"""Stage-latency ledger — always-on per-stage time attribution for
every op.

The device-flow profiler (devprof.py) answers "where did the bytes
go"; this module answers "where did the MICROSECONDS go".  An op's
end-to-end latency decomposes into the handoff boundaries it crosses:

    client submit -> OSD intake -> admission -> mClock class dequeue
    -> client-lane dequeue -> op-thread start -> codec submit ->
    dispatch batch-window expiry -> device call return -> d2h
    materialization -> sub-op fan-out -> last shard ack -> reply

Every boundary stamps a monotonic timestamp on the op's ``OpLedger``;
the interval ending at each stamp is a named STAGE, recorded into a
per-daemon log2 ``PerfHistogram`` family
(``oplat_<stage>_latency_histogram``).  Accounting is pure host-side
counter/timestamp bumps — **zero added device syncs**, mirroring
devprof's discipline (the fence-count test in
tests/test_observability.py enforces it); a mark is one clock read,
one list append, and one histogram increment.

Stage catalog (``STAGES``, canonical write-path order; each name is
the interval that ENDS at that boundary):

- ``client_flight``   client submit -> OSD intake (in-process clock;
                      absent when the op arrived over real TCP)
- ``admission``       intake -> admission-control verdict
- ``class_queue``     queue entry -> the mClock CLASS tier picks this
                      op's class (covers both tiers' queueing)
- ``client_lane``     class pick -> the per-client dmClock lane hands
                      the op over (the lane's own arbitration)
- ``dequeue_handoff`` lane pop -> an op thread starts executing
- ``op_service``      op-thread work up to the codec submit (the
                      write path's "encode enqueue")
- ``batch_window``    dispatch-queue entry -> coalesced flush starts
                      (only exists when a collection window is open)
- ``device_call``     flush start -> the batched device call returns
- ``d2h``             device return -> outputs materialized on host
- ``fan_out``         sub-op fan-out built and sent
- ``ack_gather``      fan-out sent -> last shard ack arrives
- ``reply``           last ack -> client reply sent

Reads mark the same checkpoints in the order THEY cross them (sub-read
``fan_out``/``ack_gather`` precede the decode's device stages), and an
rmw write marks ``fan_out``/``ack_gather`` twice (pre-read round, then
the write round) — a ledger is an append-only record of boundaries
crossed, so stage sums always reconcile with the op's wall time by
construction.

Export surfaces (the PR 2 trio): admin socket ``latency dump`` /
``latency reset``; mgr Prometheus (the ``oplat_*`` histogram families
render automatically as ``ceph_oplat_<stage>_latency_histogram`` with
a ``daemon`` label); and bench JSON, where every fenced workload
carries a ``stage_breakdown`` block (per-stage share-of-wall, per-op
time, p50/p99) whose ``usec_per_op`` figures are gated by
bench/regress.py's stage-budget gate.  With span tracing on, every
mark also lands on the op's span as a ``stage_ledger`` tag, so one
traced write shows its full time ledger next to its copy ledger.
"""
from __future__ import annotations

import contextlib
import contextvars
import threading

from ..common.lockdep import DebugLock
import time
from typing import Any, Dict, List, Optional, Tuple

from .histogram import (PerfHistogram, decumulate, g_perf_histograms,
                        latency_axes, percentiles_from_counts)
from .span import g_tracer

# canonical write-path stage order (reads/rmw cross a subset, possibly
# repeated — see module docstring); the bench's fenced regions reuse
# device_call/d2h for their dispatch-loop/drain split and add
# host_compute for the native host baseline
STAGES = (
    "client_flight", "admission", "class_queue", "client_lane",
    "dequeue_handoff", "op_service", "batch_window", "device_call",
    "d2h", "fan_out", "ack_gather", "reply",
)

_HIST_PREFIX = "oplat_"
_HIST_SUFFIX = "_latency_histogram"


def stage_hist_name(stage: str) -> str:
    return f"{_HIST_PREFIX}{stage}{_HIST_SUFFIX}"


def stage_of_hist_name(name: str) -> Optional[str]:
    if name.startswith(_HIST_PREFIX) and name.endswith(_HIST_SUFFIX):
        return name[len(_HIST_PREFIX):-len(_HIST_SUFFIX)]
    return None


# ---- perf counters (perf dump / Prometheus ceph_daemon_oplat_*) ------------
OPLAT_FIRST = 97000
l_oplat_ops = 97001            # ops whose ledger reached the reply mark
l_oplat_stage_samples = 97002  # individual stage durations recorded
OPLAT_LAST = 97005

_oplat_pc = None
_oplat_pc_lock = DebugLock("oplat_pc::init")


def oplat_perf_counters():
    """The stage-latency ledger's counter logger (perf dump /
    Prometheus ``ceph_daemon_oplat_*``)."""
    global _oplat_pc
    if _oplat_pc is not None:
        return _oplat_pc
    with _oplat_pc_lock:
        if _oplat_pc is None:
            from ..common.perf_counters import PerfCountersBuilder
            b = PerfCountersBuilder("oplat", OPLAT_FIRST, OPLAT_LAST)
            b.add_u64_counter(l_oplat_ops, "ops",
                              "ops whose stage ledger reached reply")
            b.add_u64_counter(l_oplat_stage_samples, "stage_samples",
                              "per-stage durations recorded")
            _oplat_pc = b.create_perf_counters()
    return _oplat_pc


# the op whose stages the current thread of control is executing
# (contextvars, like the tracer's current span: OSD worker threads and
# dispatch-flush continuations each carry their own)
_current: contextvars.ContextVar[Optional["OpLedger"]] = \
    contextvars.ContextVar("ceph_tpu_oplat_current", default=None)


class OpLedger:
    """One op's append-only record of handoff boundaries.

    ``mark(stage)`` stamps now, records the interval since the previous
    stamp into the per-daemon stage histogram, and — with span tracing
    on — appends the entry to the op's span ``stage_ledger`` tag.
    CPython's GIL makes the append/swap safe for the op path's
    hand-off pattern (one thread of control at a time per op).
    """

    __slots__ = ("daemon", "span", "t0", "_last_t", "marks")

    def __init__(self, daemon: str = "", t0: Optional[float] = None,
                 span=None):
        self.daemon = daemon
        self.span = span
        self.t0 = time.perf_counter() if t0 is None else t0
        self._last_t = self.t0
        # (stage, t, dt_s) in the order the op crossed the boundaries
        self.marks: List[Tuple[str, float, float]] = []

    def mark(self, stage: str, t: Optional[float] = None) -> None:
        if t is None:
            t = time.perf_counter()
        dt = max(t - self._last_t, 0.0)
        self._last_t = max(self._last_t, t)
        self.marks.append((stage, t, dt))
        g_oplat.record(self.daemon or "unattributed", stage, dt * 1e6)
        if g_tracer.enabled and self.span is not None:
            self.span.tags.setdefault("stage_ledger", []).append(
                {"stage": stage, "t": t, "usec": round(dt * 1e6, 1)})

    @property
    def total_s(self) -> float:
        return self._last_t - self.t0

    def dump(self) -> Dict[str, Any]:
        """The per-op breakdown shape dump_historic_slow_ops carries:
        each stage with its duration and its offset from the ledger's
        open (monotone by construction)."""
        return {
            "daemon": self.daemon,
            "total_usec": round(self.total_s * 1e6, 1),
            "stages": [{"stage": s,
                        "at_usec": round((t - self.t0) * 1e6, 1),
                        "usec": round(dt * 1e6, 1)}
                       for s, t, dt in self.marks],
        }


# ---- message plumbing ------------------------------------------------------
# The ledger rides the MOSDOp as a non-wire annotation (``_oplat``):
# the in-process fabric passes message objects by reference, so the
# client's submit stamp reaches the OSD; msg/wire.py pops the key
# before encoding, so real-TCP frames and the pinned corpus are
# byte-identical (the OSD then opens the ledger at intake and
# client_flight is simply absent).

def stamp_client(msg, daemon: str = "") -> "OpLedger":
    """Open an op's ledger at client submit time (attached to the
    message; the receiving OSD re-homes it at intake)."""
    led = OpLedger(daemon)
    if g_tracer.enabled:
        led.span = g_tracer.current()
    msg._oplat = led
    return led


def intake_ledger(msg, daemon: str) -> "OpLedger":
    """The OSD-intake boundary: adopt the client's ledger (recording
    the flight stage) or open a fresh one for ops that arrived without
    a stamp (real TCP, internal senders)."""
    led = getattr(msg, "_oplat", None)
    if led is None:
        led = OpLedger(daemon)
        msg._oplat = led
    else:
        led.daemon = daemon
        led.mark("client_flight")
    return led


def item_ledger(item) -> Optional["OpLedger"]:
    """The ledger riding a work-queue item, if any — queue tiers know
    nothing about op structure, so the lookup lives here: op items are
    ``("op", msg)`` tuples with the ledger on the message."""
    if isinstance(item, tuple):
        if len(item) > 1:
            return getattr(item[1], "_oplat", None)
        return None
    return getattr(item, "_oplat", None)


def mark_item(item, stage: str, t: Optional[float] = None) -> None:
    led = item_ledger(item)
    if led is not None:
        led.mark(stage, t)


# ---- aggregate accumulator -------------------------------------------------
class OpLatAccumulator:
    """Per-daemon per-stage aggregation over the shared PerfHistogram
    registry, plus the contextvar threading that lets deep layers
    (queue tiers, the dispatch scheduler, ecutil's codec funnels) find
    the op they are serving."""

    def __init__(self):
        self._lock = DebugLock("OplatRegistry::lock")
        self._hists: Dict[Tuple[str, str], PerfHistogram] = {}

    # ---- context ----------------------------------------------------------
    def current(self) -> Optional[OpLedger]:
        return _current.get()

    @contextlib.contextmanager
    def activate(self, ledger: Optional[OpLedger]):
        """Make *ledger* the thread's current op (None = no-op)."""
        if ledger is None:
            yield None
            return
        token = _current.set(ledger)
        try:
            yield ledger
        finally:
            _current.reset(token)

    def checkpoint(self, stage: str, t: Optional[float] = None) -> None:
        """Mark *stage* on the thread's current ledger; a no-op when
        no op is active (direct library calls, recovery paths)."""
        led = _current.get()
        if led is not None:
            led.mark(stage, t)

    # ---- recording --------------------------------------------------------
    def _hist(self, daemon: str, stage: str) -> PerfHistogram:
        key = (daemon, stage)
        h = self._hists.get(key)
        if h is None:
            h = g_perf_histograms.get(daemon, stage_hist_name(stage),
                                      latency_axes)
            with self._lock:
                self._hists[key] = h
        return h

    def record(self, daemon: str, stage: str, usec: float) -> None:
        """One stage duration — the always-on aggregate bump every
        ``OpLedger.mark`` (and the bench fence) lands here."""
        self._hist(daemon, stage).inc(usec)
        oplat_perf_counters().inc(l_oplat_stage_samples)

    def note_op(self) -> None:
        """An op's ledger reached its reply mark."""
        oplat_perf_counters().inc(l_oplat_ops)

    # ---- views ------------------------------------------------------------
    def _stage_hists(self):
        """[(daemon, stage, hist)] for every oplat family registered."""
        out = []
        for (logger, name), hist in g_perf_histograms.items():
            stage = stage_of_hist_name(name)
            if stage is not None:
                out.append((logger, stage, hist))
        return out

    def dump(self, daemon: str = "") -> Dict[str, Any]:
        """The ``latency dump`` admin-socket shape: per daemon, each
        stage's count/total/mean/share + p50/p99 from the histogram's
        cumulative series.  The ``ops``/``stage_samples`` header
        counts are process-wide (one counter logger per process), so
        they only appear on the unfiltered dump — a daemon-filtered
        dump must not look like that daemon owns every op."""
        daemons: Dict[str, Dict[str, Any]] = {}
        for lg, stage, hist in self._stage_hists():
            if daemon and lg != daemon:
                continue
            if not hist.total_count:
                continue
            d = daemons.setdefault(lg, {"stages": {}, "total_usec": 0.0})
            pts = hist.cumulative_axis0()
            edges = [e for e, _c in pts]
            ps = percentiles_from_counts(decumulate(pts), edges,
                                         suffix="_usec")
            d["stages"][stage] = {
                "count": hist.total_count,
                "total_usec": round(hist.axis0_sum, 1),
                "avg_usec": round(hist.axis0_sum
                                  / max(hist.total_count, 1), 1),
                **ps,
            }
            d["total_usec"] += hist.axis0_sum
        for d in daemons.values():
            tot = d["total_usec"]
            d["total_usec"] = round(tot, 1)
            for st in d["stages"].values():
                st["share"] = round(st["total_usec"] / tot, 4) \
                    if tot > 0 else 0.0
        out: Dict[str, Any] = {"stage_catalog": list(STAGES),
                               "daemons": daemons}
        if not daemon:
            pc = oplat_perf_counters().dump()
            out["ops"] = pc.get("ops", 0)
            out["stage_samples"] = pc.get("stage_samples", 0)
        return out

    def reset(self) -> None:
        """``latency reset``: zero every oplat stage family and the
        ledger counters (other histogram families untouched)."""
        for _lg, _stage, hist in self._stage_hists():
            hist.reset()
        pc = oplat_perf_counters()
        for idx in (l_oplat_ops, l_oplat_stage_samples):
            try:
                pc.set(idx, 0)
            except (KeyError, AssertionError):
                pass

    # ---- bench deltas ------------------------------------------------------
    def snapshot(self) -> Dict[str, Tuple[int, float, Tuple[int, ...]]]:
        """Per-stage (count, sum_usec, bucket_counts) collapsed across
        daemons — the before/after handle the bench's
        ``stage_breakdown`` blocks diff against."""
        out: Dict[str, List] = {}
        for _lg, stage, hist in self._stage_hists():
            counts = hist.marginal_axis0()
            cur = out.get(stage)
            if cur is None:
                out[stage] = [hist.total_count, hist.axis0_sum,
                              list(counts)]
            else:
                cur[0] += hist.total_count
                cur[1] += hist.axis0_sum
                cur[2] = [a + b for a, b in zip(cur[2], counts)]
        return {s: (c, t, tuple(b)) for s, (c, t, b) in out.items()}

    def breakdown_since(self, before, wall_s: float,
                        n_ops: int) -> Dict[str, Any]:
        """The bench ``stage_breakdown`` block: per-stage time over a
        measured region, share of total stage time, per-op time, and
        p50/p99 from the bucket-count deltas.

        ``coverage`` is stage-sum over wall: ~1.0 for a serial region
        (the reconciliation receipt), above 1.0 under concurrency —
        N ops waiting on one coalesced device call each accrue the full
        call, so coverage ~ occupancy is the occupancy story in time
        units, not an error.
        """
        after = self.snapshot()
        edges = latency_axes()[0].upper_edges()
        stages: Dict[str, Any] = {}
        total_usec = 0.0
        for stage, (c1, s1, b1) in sorted(after.items()):
            c0, s0, b0 = before.get(stage, (0, 0.0, None))
            dc, ds = c1 - c0, s1 - s0
            if dc <= 0:
                continue
            db = [max(a - b, 0) for a, b in zip(b1, b0)] if b0 \
                else list(b1)
            stages[stage] = {
                "count": dc,
                "total_usec": round(ds, 1),
                "usec_per_op": round(ds / max(n_ops, 1), 2),
                **percentiles_from_counts(db, edges, suffix="_usec"),
            }
            total_usec += ds
        for st in stages.values():
            st["share"] = round(st["total_usec"] / total_usec, 4) \
                if total_usec > 0 else 0.0
        return {
            "wall_s": round(float(wall_s), 4),
            "stage_sum_s": round(total_usec / 1e6, 4),
            "coverage": round(total_usec / 1e6 / wall_s, 3)
            if wall_s > 0 else 0.0,
            "n_ops": int(n_ops),
            "stages": stages,
        }


g_oplat = OpLatAccumulator()
