"""Parent/child spans — the ZTracer/blkin slot, end to end.

The reference threads a blkin trace through every op (the
``ZTracer::Trace`` member on ``msg/Message.h:254``): each daemon opens
child spans off the parent id the message carried, and a collector
reassembles the tree.  Here the same contract rides the mini-cluster
fabric: every message already carries ``trace_id``; this module adds
``parent_span_id`` propagation, per-daemon bounded ring buffers, and
tree reassembly for the admin socket's ``dump_tracing``.

Cost contract (why production can leave this importable): with the
tracer disabled — the default — ``begin()`` is one attribute check and
returns ``None``; no span objects, no clock reads, and critically **no
device syncs** are introduced anywhere.  Device drain time only appears
as child spans when the kernel timer (``tracing_kernels``) is also on,
because only then does a sync exist to measure.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading

from ..common.lockdep import DebugLock
import time
from collections import deque
from typing import Deque, Dict, List, Optional

_span_ids = itertools.count(1)

# the active span of this thread of control (contextvars so the OSD's
# worker threads each carry their own chain)
_current: contextvars.ContextVar[Optional["Span"]] = \
    contextvars.ContextVar("ceph_tpu_trace_current", default=None)


class Span:
    """One named interval in one daemon, linked to its parent."""

    __slots__ = ("span_id", "parent_span_id", "trace_id", "name",
                 "daemon", "start", "end", "tags")

    def __init__(self, name: str, daemon: str, trace_id: int,
                 parent_span_id: int):
        self.span_id = next(_span_ids)
        self.parent_span_id = parent_span_id
        self.trace_id = trace_id
        self.name = name
        self.daemon = daemon
        self.start = time.monotonic()
        self.end: Optional[float] = None
        self.tags: Dict[str, object] = {}

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def event(self, name: str, **tags) -> None:
        """Append a timestamped point event (the OpenTracing log slot:
        retries, breaker trips, fallbacks).  Rides the tags dict so the
        dump shape is unchanged for consumers that ignore events."""
        self.tags.setdefault("events", []).append(
            {"event": name, "t": time.monotonic(), **tags})

    def dump(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "trace_id": self.trace_id,
            "name": self.name,
            "daemon": self.daemon,
            "start": self.start,
            "end": self.end,
            "duration": self.duration,
            "tags": dict(self.tags),
        }


def build_tree(spans: List[Span]) -> List[dict]:
    """Nest spans by parent_span_id; roots are spans whose parent is
    absent from the set (e.g. 0, or evicted from the ring)."""
    by_id = {s.span_id: s.dump() for s in spans}
    for d in by_id.values():
        d["children"] = []
    roots: List[dict] = []
    for d in sorted(by_id.values(), key=lambda d: d["start"]):
        parent = by_id.get(d["parent_span_id"])
        if parent is not None and parent is not d:
            parent["children"].append(d)
        else:
            roots.append(d)
    return roots


class SpanCollector:
    """Per-daemon bounded ring buffers of recent spans.

    Spans are recorded at ``begin`` time (so in-flight spans are
    dumpable, like ``dump_ops_in_flight``) and mutate in place when
    finished; ring eviction only drops the collector's reference — a
    flight-recorder entry pinning the span keeps its tree intact.
    """

    def __init__(self, ring_size: int = 2048):
        self.ring_size = ring_size
        self._rings: Dict[str, Deque[Span]] = {}
        self._lock = DebugLock("Tracer::lock")

    def record(self, span: Span) -> None:
        with self._lock:
            ring = self._rings.get(span.daemon)
            if ring is None:
                ring = self._rings[span.daemon] = deque(
                    maxlen=self.ring_size)
            ring.append(span)

    def spans_for_trace(self, trace_id: int) -> List[Span]:
        with self._lock:
            return [s for ring in self._rings.values() for s in ring
                    if s.trace_id == trace_id]

    def tree(self, trace_id: int) -> List[dict]:
        return build_tree(self.spans_for_trace(trace_id))

    def dump(self, daemon: str = "") -> Dict[str, List[dict]]:
        with self._lock:
            return {name: [s.dump() for s in ring]
                    for name, ring in self._rings.items()
                    if not daemon or name == daemon}

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()


class Tracer:
    """The process-wide span factory (all mini-cluster daemons share
    one process, so one tracer covers every daemon; spans carry their
    daemon name)."""

    def __init__(self):
        self.enabled = False
        self.collector = SpanCollector()

    def enable(self, on: bool = True) -> None:
        self.enabled = on

    # ---- explicit begin/finish (ops spanning callbacks) -------------------
    def begin(self, name: str, daemon: str = "", trace_id: int = 0,
              parent_id: int = 0) -> Optional[Span]:
        """Open a span, or None when disabled.  Parent resolution:
        explicit *parent_id* (the message header) wins; otherwise the
        thread's current span; trace_id inherits the same way."""
        if not self.enabled:
            return None
        cur = _current.get()
        if not parent_id and cur is not None:
            parent_id = cur.span_id
        if not trace_id and cur is not None:
            trace_id = cur.trace_id
        span = Span(name, daemon, trace_id, parent_id)
        self.collector.record(span)
        return span

    def finish(self, span: Optional[Span]) -> None:
        if span is not None and span.end is None:
            span.end = time.monotonic()

    # ---- context helpers --------------------------------------------------
    @contextlib.contextmanager
    def activate(self, span: Optional[Span]):
        """Make *span* the thread's current span (children attach to it)."""
        if span is None:
            yield None
            return
        token = _current.set(span)
        try:
            yield span
        finally:
            _current.reset(token)

    @contextlib.contextmanager
    def span(self, name: str, daemon: str = "", trace_id: int = 0,
             parent_id: int = 0):
        """begin + activate + finish in one block."""
        sp = self.begin(name, daemon, trace_id, parent_id)
        if sp is None:
            yield None
            return
        token = _current.set(sp)
        try:
            yield sp
        finally:
            _current.reset(token)
            self.finish(sp)

    def current(self) -> Optional[Span]:
        return _current.get()

    def event(self, name: str, **tags) -> None:
        """Record a point event on the thread's current span; a no-op
        when disabled or no span is active (host-side only — the
        degradation machinery calls this from hot paths)."""
        if not self.enabled:
            return
        cur = _current.get()
        if cur is not None:
            cur.event(name, **tags)

    def current_span_id(self) -> int:
        cur = _current.get()
        return cur.span_id if cur is not None else 0

    def current_trace_id(self) -> int:
        cur = _current.get()
        return cur.trace_id if cur is not None else 0


g_tracer = Tracer()
