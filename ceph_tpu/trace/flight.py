"""Slow-op flight recorder — span trees pinned past ring eviction.

When an op exceeds ``complaint_time`` the OpTracker hands its trace's
spans to this recorder.  The entry pins the Span *objects* (not dumps):
spans still open at completion time — e.g. the client's root span,
which only closes after the reply crosses back — finish in place, so a
later ``dump_historic_slow_ops`` shows the complete, closed tree even
after the collector's ring buffers recycled.
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock
from collections import deque
from typing import Deque, List

from .span import Span, build_tree


class FlightEntry:
    __slots__ = ("trace_id", "description", "duration", "spans")

    def __init__(self, trace_id: int, description: str, duration: float,
                 spans: List[Span]):
        self.trace_id = trace_id
        self.description = description
        self.duration = duration
        self.spans = list(spans)

    def tree(self) -> List[dict]:
        return build_tree(self.spans)

    def dump(self) -> dict:
        return {"trace_id": self.trace_id,
                "description": self.description,
                "duration": self.duration,
                "span_tree": self.tree()}


class FlightRecorder:
    def __init__(self, size: int = 64):
        self._ring: Deque[FlightEntry] = deque(maxlen=size)
        self._lock = DebugLock("FlightRecorder::lock")

    def record(self, trace_id: int, description: str, duration: float,
               spans: List[Span]) -> FlightEntry:
        entry = FlightEntry(trace_id, description, duration, spans)
        with self._lock:
            self._ring.append(entry)
        return entry

    def dump(self) -> dict:
        with self._lock:
            entries = list(self._ring)
        return {"slow_ops": [e.dump() for e in entries]}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


g_flight_recorder = FlightRecorder()
