"""Device-flow profiler — host↔device transfer, compile, and memory
accounting with per-call-site resolution.

The zero-copy ROADMAP item needs a ruler before it needs a knife: the
XOR-EC program-optimization literature (arxiv 2108.02692) shows memory
movement, not GF math, dominates small-chunk EC, yet nothing in the
tree could *see* a ``jax.device_put``, an implicit host fetch, an XLA
recompile, or a padded-buffer copy on the write path.  This module
makes bytes-moved-per-op a first-class metric:

- every host↔device boundary the hot paths cross is wrapped by a thin
  accounting call (``account_h2d`` / ``account_d2h`` /
  ``account_host_copy``) recording per call-site direction, bytes and
  count — pure host-side counter bumps, **zero added device syncs**
  (the fence-count test in tests/test_observability.py enforces it);
- fresh XLA compiles are detected via jit cache-miss observation: a
  ``jax.monitoring`` duration listener fires on
  ``/jax/core/compile/backend_compile_duration`` (a cache HIT emits
  nothing), and the compile is attributed to whichever call-site's
  ``stage()`` scope was active;
- device-memory high-water is sampled from the backend's
  ``memory_stats()`` (``peak_bytes_in_use``) where exposed, falling
  back to summing ``jax.live_arrays()`` — sampled only at dump/scrape
  time, never on the op path;
- when span tracing (PR 2) is on, every accounted copy is also
  appended to the active span's ``copy_ledger`` tag, so one traced EC
  write shows its full copy ledger: bufferlist→numpy pad/stack →
  device → host → sub-op messages.

Export surfaces (the PR 2 trio): admin socket ``prof dump`` / ``prof
reset``; mgr Prometheus (``ceph_daemon_devprof_{h2d,d2h}_bytes``,
``_transfers``, ``_compiles``, ``_device_mem_highwater_bytes``, plus
the ``ceph_devprof_transfer_size_histogram`` log2 family); and bench
JSON, where every fenced workload carries a ``devflow`` block whose
``copies_per_op`` / ``bytes_per_op`` are gated metrics
(bench/regress.py's copy-budget gate).
"""
from __future__ import annotations

import contextlib
import contextvars
import threading

from ..common.lockdep import DebugLock
from typing import Any, Dict, List, Optional

from .histogram import (PerfHistogramAxis, SCALE_LOG2, g_perf_histograms)
from .span import g_tracer

H2D = "h2d"
D2H = "d2h"
HOST = "host"        # host-side buffer copy (pad/stack/message build)

# calibration-flow sites: accounted like any other boundary crossing
# (they show in `prof dump` and the counter logger), but EXCLUDED from
# the bench `devflow` snapshots the copy-budget gate compares — their
# one-element readbacks are measurement instrumentation, not a per-op
# copy chain, the same policy that keeps the bench drain fences off
# the ledger entirely (parallel/ec.drain_sharded).  The mesh skew
# probe (mesh/chipstat.py) accounts here so the fence-count test can
# assert EXACTLY the probe's per-chip readbacks and nothing else.
CALIBRATION_SITES = frozenset({"mesh.skew_probe"})

# ---- perf counters (perf dump / Prometheus ceph_daemon_devprof_*) ----------
DEVPROF_FIRST = 96000
l_devprof_h2d_bytes = 96001       # bytes moved host -> device
l_devprof_h2d_transfers = 96002   # host -> device transfers
l_devprof_d2h_bytes = 96003       # bytes moved device -> host
l_devprof_d2h_transfers = 96004   # device -> host transfers
l_devprof_compiles = 96005        # fresh XLA compiles (jit cache misses)
l_devprof_host_copy_bytes = 96006  # host-side staging copies, bytes
l_devprof_host_copies = 96007     # host-side staging copies
l_devprof_device_mem_highwater = 96008  # gauge: peak device bytes seen
DEVPROF_LAST = 96010

_devprof_pc = None
_devprof_pc_lock = DebugLock("devprof_pc::init")


def devprof_perf_counters():
    """The device-flow profiler's counter logger (perf dump /
    Prometheus ``ceph_daemon_devprof_*``)."""
    global _devprof_pc
    if _devprof_pc is not None:
        return _devprof_pc
    with _devprof_pc_lock:
        if _devprof_pc is None:
            from ..common.perf_counters import PerfCountersBuilder
            b = PerfCountersBuilder("devprof", DEVPROF_FIRST,
                                    DEVPROF_LAST)
            b.add_u64_counter(l_devprof_h2d_bytes, "h2d_bytes",
                              "bytes moved host to device")
            b.add_u64_counter(l_devprof_h2d_transfers, "h2d_transfers",
                              "host to device transfers")
            b.add_u64_counter(l_devprof_d2h_bytes, "d2h_bytes",
                              "bytes moved device to host")
            b.add_u64_counter(l_devprof_d2h_transfers, "d2h_transfers",
                              "device to host transfers")
            b.add_u64_counter(l_devprof_compiles, "compiles",
                              "fresh XLA compiles (jit cache misses)")
            b.add_u64_counter(l_devprof_host_copy_bytes,
                              "host_copy_bytes",
                              "host-side staging copy bytes "
                              "(pad/stack/message build)")
            b.add_u64_counter(l_devprof_host_copies, "host_copies",
                              "host-side staging copies")
            b.add_u64(l_devprof_device_mem_highwater,
                      "device_mem_highwater_bytes",
                      "peak device memory observed at sample time")
            _devprof_pc = b.create_perf_counters()
    return _devprof_pc


def transfer_size_axes() -> List[PerfHistogramAxis]:
    """1D transfer-size(bytes, log2) — the distribution of individual
    host↔device transfer sizes.  Dimensionless axis name (no ``_usec``
    suffix), so the mgr renderer exports raw byte edges."""
    return [PerfHistogramAxis("transfer_size_bytes", min=0,
                              quant_size=512, buckets=32,
                              scale_type=SCALE_LOG2)]


# the stage whose device work is currently being attributed (compile
# events carry no call-site; the innermost stage() scope claims them)
_stage: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("ceph_tpu_devprof_stage", default=None)

# jax.monitoring listeners cannot be unregistered individually:
# exactly ONE is ever installed per process, bound to the singleton
_compile_listener_installed = False


class DevFlowProfiler:
    """Per-call-site host↔device flow accounting.

    Always-on like perf counters: an account call is dict/int math
    under a lock — no device syncs, no per-sample allocation beyond
    the ledger entry when span tracing is enabled.

    ``mirror_counters``: only the process singleton (``g_devprof``)
    mirrors into the process-wide ``devprof`` perf-counter logger and
    transfer-size histogram; a standalone instance (unit tests) keeps
    its accounting to itself so it cannot pollute the exported
    surfaces, and its ``dump()`` omits the counter block it does not
    own.
    """

    def __init__(self, mirror_counters: bool = False):
        self._lock = DebugLock("DeviceFlowProfiler::lock")
        # site -> {h2d_bytes, h2d_count, d2h_bytes, d2h_count,
        #          host_copy_bytes, host_copies, compiles}
        self._sites: Dict[str, Dict[str, int]] = {}
        self._mem_highwater = 0
        self._mirror = mirror_counters

    # ---- core accounting ---------------------------------------------------
    def _site(self, site: str) -> Dict[str, int]:
        s = self._sites.get(site)
        if s is None:
            s = self._sites[site] = {
                "h2d_bytes": 0, "h2d_count": 0,
                "d2h_bytes": 0, "d2h_count": 0,
                "host_copy_bytes": 0, "host_copies": 0,
                "compiles": 0,
            }
        return s

    @property
    def _hist(self):
        return g_perf_histograms.get(
            "devprof", "devprof_transfer_size_histogram",
            transfer_size_axes)

    def _ledger(self, site: str, direction: str, nbytes: int) -> None:
        """Append a copy-ledger entry to the active span (host-side
        only; a no-op unless PR 2's tracer is enabled)."""
        cur = g_tracer.current()
        if cur is not None:
            cur.tags.setdefault("copy_ledger", []).append(
                {"stage": site, "dir": direction, "bytes": int(nbytes)})

    def account_h2d(self, site: str, nbytes: int) -> None:
        """One host→device transfer of *nbytes* at *site*."""
        nbytes = int(nbytes)
        if self._mirror:
            pc = devprof_perf_counters()
            pc.inc(l_devprof_h2d_bytes, nbytes)
            pc.inc(l_devprof_h2d_transfers)
            self._hist.inc(nbytes)
        with self._lock:
            s = self._site(site)
            s["h2d_bytes"] += nbytes
            s["h2d_count"] += 1
        if g_tracer.enabled:
            self._ledger(site, H2D, nbytes)

    def account_d2h(self, site: str, nbytes: int) -> None:
        """One device→host materialization of *nbytes* at *site*."""
        nbytes = int(nbytes)
        if self._mirror:
            pc = devprof_perf_counters()
            pc.inc(l_devprof_d2h_bytes, nbytes)
            pc.inc(l_devprof_d2h_transfers)
            self._hist.inc(nbytes)
        with self._lock:
            s = self._site(site)
            s["d2h_bytes"] += nbytes
            s["d2h_count"] += 1
        if g_tracer.enabled:
            self._ledger(site, D2H, nbytes)

    def account_host_copy(self, site: str, nbytes: int) -> None:
        """One host-side staging copy (pad, stack, message build) —
        counted toward the per-op copy ledger but not toward transfer
        bytes (nothing crossed the PCIe/tunnel boundary)."""
        nbytes = int(nbytes)
        if self._mirror:
            pc = devprof_perf_counters()
            pc.inc(l_devprof_host_copy_bytes, nbytes)
            pc.inc(l_devprof_host_copies)
        with self._lock:
            s = self._site(site)
            s["host_copy_bytes"] += nbytes
            s["host_copies"] += 1
        if g_tracer.enabled:
            self._ledger(site, HOST, nbytes)

    # ---- compile detection (jit cache-miss observation) --------------------
    def install_compile_listener(self) -> None:
        """Register the jax.monitoring duration listener once,
        process-wide, targeting the SINGLETON (``g_devprof``).  A jit
        cache HIT emits no compile event, so every
        ``backend_compile_duration`` event IS a fresh XLA compile.
        Deferred (not at import) so modules that never touch a device
        don't pull jax in.  jax offers no unregister, so the listener
        must never close over a discardable instance — standalone
        profilers don't get compile attribution by design."""
        global _compile_listener_installed
        if _compile_listener_installed:
            return
        with self._lock:
            if _compile_listener_installed:
                return
            try:
                from jax import monitoring
            except Exception:
                return

            def _on_duration(event: str, duration: float, **kw) -> None:
                if event != "/jax/core/compile/backend_compile_duration":
                    return
                g_devprof._note_compile()

            monitoring.register_event_duration_secs_listener(_on_duration)
            _compile_listener_installed = True

    def _note_compile(self) -> None:
        if self._mirror:
            devprof_perf_counters().inc(l_devprof_compiles)
        site = _stage.get() or "unattributed"
        with self._lock:
            self._site(site)["compiles"] += 1
        if g_tracer.enabled:
            g_tracer.event("xla_compile", site=site)

    @contextlib.contextmanager
    def stage(self, site: str):
        """Attribute compiles inside the block to *site* (the compile
        event carries no call-site of its own)."""
        token = _stage.set(site)
        try:
            yield
        finally:
            _stage.reset(token)

    # ---- device memory (sampled at dump/scrape time, never per-op) ---------
    def sample_device_mem(self) -> Dict[str, Any]:
        """Update the high-water gauge from the backend's memory view.
        ``memory_stats()`` where the backend exposes it (real chips),
        else the sum of live array bytes.  Never raises, never syncs."""
        out: Dict[str, Any] = {"source": "none", "bytes_in_use": 0,
                               "peak_bytes_in_use": 0}
        try:
            import jax
            dev = jax.devices()[0]
            stats = None
            ms = getattr(dev, "memory_stats", None)
            if ms is not None:
                try:
                    stats = ms()
                except Exception:
                    stats = None
            if stats:
                out["source"] = "memory_stats"
                out["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
                out["peak_bytes_in_use"] = int(
                    stats.get("peak_bytes_in_use",
                              out["bytes_in_use"]))
            else:
                live = sum(int(getattr(a, "nbytes", 0))
                           for a in jax.live_arrays())
                out["source"] = "live_arrays"
                out["bytes_in_use"] = live
                out["peak_bytes_in_use"] = live
        except Exception:
            return out
        with self._lock:
            self._mem_highwater = max(self._mem_highwater,
                                      out["peak_bytes_in_use"])
            out["highwater_bytes"] = self._mem_highwater
        if self._mirror:
            devprof_perf_counters().set(l_devprof_device_mem_highwater,
                                        self._mem_highwater)
        return out

    # ---- views -------------------------------------------------------------
    @staticmethod
    def _totals_of(sites: Dict[str, Dict[str, int]]) -> Dict[str, int]:
        t = {"h2d_bytes": 0, "h2d_count": 0, "d2h_bytes": 0,
             "d2h_count": 0, "host_copy_bytes": 0, "host_copies": 0,
             "compiles": 0}
        for s in sites.values():
            for k in t:
                t[k] += s[k]
        t["transfers"] = t["h2d_count"] + t["d2h_count"]
        return t

    def totals(self) -> Dict[str, int]:
        with self._lock:
            sites = {k: dict(v) for k, v in self._sites.items()}
        return self._totals_of(sites)

    def snapshot(self) -> Dict[str, int]:
        """Cheap totals snapshot for before/after deltas (the bench
        workloads' devflow blocks).  CALIBRATION_SITES are excluded
        here — and therefore from the copy-budget gate — so a skew
        probe firing inside a measured region cannot read as a new
        per-op copy chain; ``totals()``/``dump()`` keep every site."""
        with self._lock:
            sites = {k: dict(v) for k, v in self._sites.items()
                     if k not in CALIBRATION_SITES}
        return self._totals_of(sites)

    def dump(self) -> Dict[str, Any]:
        """The ``prof dump`` admin-socket shape: per-site table,
        totals, the counter logger, transfer-size summary, and a fresh
        device-memory sample.  The full histogram grid stays on
        ``perf histogram dump`` (logger ``devprof``)."""
        with self._lock:
            sites = {k: dict(v) for k, v in sorted(self._sites.items())}
        # totals derive from the SAME snapshot as the sites table, so
        # one dump is internally consistent under concurrent accounting
        out: Dict[str, Any] = {
            "sites": sites,
            "totals": self._totals_of(sites),
            "device_mem": self.sample_device_mem(),
        }
        if self._mirror:
            # the counter/histogram surfaces are process-wide; only
            # the singleton that feeds them may report them as its own
            hist = self._hist
            out["counters"] = devprof_perf_counters().dump()
            out["transfer_size_histogram"] = {
                "count": hist.total_count, "sum_bytes": hist.axis0_sum}
        return out

    def reset(self) -> None:
        """``prof reset``: zero the per-site table, the counter logger
        and the transfer-size histogram (high-water restarts too)."""
        with self._lock:
            self._sites.clear()
            self._mem_highwater = 0
        if not self._mirror:
            return
        pc = devprof_perf_counters()
        for idx in range(DEVPROF_FIRST + 1, DEVPROF_LAST):
            try:
                pc.set(idx, 0)
            except (KeyError, AssertionError):
                pass
        self._hist.reset()


g_devprof = DevFlowProfiler(mirror_counters=True)


def devflow_delta(before: Dict[str, int], after: Dict[str, int],
                  n_ops: int) -> Dict[str, Any]:
    """The bench ``devflow`` block: flow deltas over a measured region
    normalized per op.  ``copies_per_op`` counts every accounted copy
    (transfers + host staging copies) — the number the zero-copy
    refactors must drive down; ``bytes_per_op`` counts boundary bytes
    only."""
    d = {k: int(after.get(k, 0)) - int(before.get(k, 0))
         for k in ("h2d_bytes", "d2h_bytes", "h2d_count", "d2h_count",
                   "host_copies", "host_copy_bytes", "compiles")}
    transfers = d["h2d_count"] + d["d2h_count"]
    ops = max(int(n_ops), 1)
    return {
        "h2d_bytes": d["h2d_bytes"],
        "d2h_bytes": d["d2h_bytes"],
        "transfers": transfers,
        "compiles": d["compiles"],
        "host_copies": d["host_copies"],
        "copies_per_op": round((transfers + d["host_copies"]) / ops, 4),
        "bytes_per_op": round(
            (d["h2d_bytes"] + d["d2h_bytes"]) / ops, 2),
    }
