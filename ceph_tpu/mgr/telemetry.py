"""mgr telemetry rollup — cluster time-series, merged percentiles,
and SLO burn-rate health.

The per-daemon observability layers (trace/: oplat stage histograms,
devprof flow counters, qos admission counters) answer "what is THIS
daemon doing"; nothing answered "is the FLEET inside its latency
budget right now".  Tail effects in distributed work are exactly what
per-daemon views hide (arxiv 1804.10331: the straggler dominates the
job) — the cluster p99 of a stage is the percentile of the UNION of
every daemon's samples, which no individual daemon's histogram shows.
This module is the mgr's DaemonPerfCounters-collection role
(pybind/mgr/: the status/prometheus modules' stats plumbing) over the
process-global registries:

- **Collection** (``collect``, driven from ``Manager.tick`` on the
  cluster's deterministic clock): every histogram family is merged
  across daemons (``trace.histogram.merge_axis0`` — same-edged log2
  series, so cluster percentiles are exact) and snapshotted with the
  relevant counter totals into a bounded, timestamped ring
  (``mgr_telemetry_retention`` samples).  Collection is pure host-side
  reads — zero added device syncs (fence-count enforced).
- **Rollup** (``rollup``, THE shared snapshot function): per-family
  cluster p50/p99/p999 and rates (ops/s, h2d/d2h bytes/s, admission
  rejections/s) derived from ring DELTAS over a window, so every
  surface — ``telemetry dump``, ``tpu status``, the Prometheus
  ``ceph_cluster_*`` families, and the bench ``cluster_rollup``
  block — renders from one function and cannot drift.
- **SLO engine** (``mgr_slo_*`` options): objectives evaluated over a
  fast and a slow burn-rate window.  A check RAISES only after the
  fast-window burn has breached for ``mgr_slo_sustain_ticks``
  consecutive collects AND the slow window confirms (a single-tick
  spike never flaps it); it CLEARS only after
  ``mgr_slo_clear_ticks`` clean collects (hysteresis).  Raise/clear
  transitions ride the same health path as
  ``check_degraded_codecs`` — ``Manager.health_checks`` + the mon
  cluster log — so ``TPU_SLO_*`` shows in ``ceph -s``, ``health()``
  and ``ceph_health_check{check=...}``.

This converts the PR 7/9 budgets (copy budget, stage budget) from
CI-only bench gates into live cluster health: the same per-stage p99s
and copies-per-op figures the gates watch offline are now objectives
a running cluster raises health checks on.
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock
from typing import Any, Dict, List, Optional, Tuple

from ..common.config import g_conf
from ..trace.histogram import (g_perf_histograms, merge_axis0,
                               percentiles_from_counts)
from ..trace.journal import g_journal

# the three SLO health checks (mon health / `ceph -s` / Prometheus
# ceph_health_check{check=...} via Manager.health_checks)
SLO_OPLAT = "TPU_SLO_OPLAT"
SLO_COPY = "TPU_SLO_COPY"
SLO_ADMISSION = "TPU_SLO_ADMISSION"
SLO_CHECKS = (SLO_OPLAT, SLO_COPY, SLO_ADMISSION)

QUANTILES = (0.5, 0.99, 0.999)

# counter catalog sampled into every ring entry; rates derive from
# deltas between entries, never from instantaneous values
RATE_KEYS = ("ops", "h2d_bytes", "d2h_bytes", "admission_rejections")


def _counter_sample() -> Dict[str, float]:
    """Cluster-wide counter totals for the rate/SLO series: op
    completions (oplat), boundary bytes + accounted copies (devprof),
    admission rejections (qos).  Deferred imports keep mgr-only users
    from pulling the whole trace package at module import."""
    from ..common.work_queue import qos_perf_counters
    from ..trace.devprof import devprof_perf_counters
    from ..trace.oplat import oplat_perf_counters
    op = oplat_perf_counters().dump()
    dv = devprof_perf_counters().dump()
    qs = qos_perf_counters().dump()
    return {
        "ops": float(op.get("ops", 0)),
        "h2d_bytes": float(dv.get("h2d_bytes", 0)),
        "d2h_bytes": float(dv.get("d2h_bytes", 0)),
        "copies": float(dv.get("h2d_transfers", 0)
                        + dv.get("d2h_transfers", 0)
                        + dv.get("host_copies", 0)),
        "admission_rejections": float(qs.get("admission_rejections", 0)),
    }


def _oplat_stage(name: str) -> Optional[str]:
    from ..trace.oplat import stage_of_hist_name
    return stage_of_hist_name(name)


class Telemetry:
    """The mgr's cluster telemetry module (ring + rollup + SLO)."""

    def __init__(self):
        self._lock = DebugLock("MgrTelemetry::lock")
        # ring entries: {"t", "counters": {...},
        #                "families": {name: [axis0 counts]}}
        self._ring: List[Dict[str, Any]] = []
        # family name -> axis-0 upper edges (fixed per family)
        self._edges: Dict[str, List[float]] = {}
        # check -> {"active", "streak", "clean", "burn_fast",
        #           "burn_slow", "message"}
        self._slo: Dict[str, Dict[str, Any]] = {}
        # clock of the newest sample the SLO engine has judged — a
        # re-tick at the same clock (repeated `tpu status` calls)
        # must not double-count the sustain/clear streaks
        self._last_eval_t: Optional[float] = None

    # ---- options -----------------------------------------------------------
    @staticmethod
    def objectives() -> Dict[str, Any]:
        """The SLO option table, parsed fresh each evaluation so
        injectargs changes take effect on the next tick."""
        oplat: Dict[str, float] = {}
        raw = str(g_conf.get_val("mgr_slo_oplat_p99_usec") or "")
        for part in raw.split(","):
            stage, _, v = part.strip().partition(":")
            if not stage or not v:
                continue
            try:
                oplat[stage.strip()] = float(v)
            except ValueError:
                continue        # a typo'd pair must not arm garbage
        return {
            "oplat_p99_usec": oplat,
            "copies_per_op_max":
                float(g_conf.get_val("mgr_slo_copies_per_op_max") or 0.0),
            "admission_rate_max":
                float(g_conf.get_val("mgr_slo_admission_rate_max") or 0.0),
            "fast_window_s":
                float(g_conf.get_val("mgr_slo_fast_window_s") or 30.0),
            "slow_window_s":
                float(g_conf.get_val("mgr_slo_slow_window_s") or 300.0),
            "sustain_ticks":
                int(g_conf.get_val("mgr_slo_sustain_ticks") or 2),
            "clear_ticks":
                int(g_conf.get_val("mgr_slo_clear_ticks") or 2),
        }

    # ---- collection --------------------------------------------------------
    def collect(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Snapshot the cluster-merged histogram families + counter
        totals into the ring at clock *now* (monotone; a stale or
        repeated clock value is a no-op so a mid-tick scrape cannot
        add zero-dt samples that blow up rate math).  ``now=None``
        self-advances one second past the newest sample — direct
        callers without a cluster clock stay monotone."""
        with self._lock:
            last_t = self._ring[-1]["t"] if self._ring else None
        if now is None:
            now = 0.0 if last_t is None else last_t + 1.0
        if last_t is not None and now <= last_t:
            with self._lock:
                return self._ring[-1]
        families: Dict[str, List[int]] = {}
        by_name: Dict[str, List] = {}
        for (_logger, name), hist in g_perf_histograms.items():
            by_name.setdefault(name, []).append(hist)
        for name, hists in by_name.items():
            # merge across daemons: same-named families share an axes
            # factory, so the edges agree and the union is exact
            try:
                edges, counts = merge_axis0(hists)
            except ValueError:
                continue        # mismatched edges: skip, never guess
            families[name] = counts
            self._edges.setdefault(name, edges)
        entry = {"t": float(now), "counters": _counter_sample(),
                 "families": families}
        retention = int(g_conf.get_val("mgr_telemetry_retention") or 360)
        with self._lock:
            if self._ring and entry["t"] <= self._ring[-1]["t"]:
                return self._ring[-1]       # lost a race: keep monotone
            self._ring.append(entry)
            del self._ring[:-max(retention, 2)]
        return entry

    def tick(self, mgr, now: Optional[float] = None) -> None:
        """One mgr tick: collect a sample, then run the SLO engine
        against *mgr*'s health surface — once per distinct sample
        (an extra tick at an unmoved clock is a pure no-op, so
        ``tpu status`` calls between cluster ticks cannot
        double-count the streaks)."""
        entry = self.collect(now)
        if entry["t"] == self._last_eval_t:
            return
        self._last_eval_t = entry["t"]
        self.evaluate_slo(mgr)

    def reset(self) -> None:
        """``telemetry reset``: drop the rings and the SLO streaks
        (the underlying per-daemon histograms/counters belong to
        ``latency reset`` / ``prof reset``, not to us)."""
        with self._lock:
            self._ring.clear()
            self._edges.clear()
            self._slo.clear()
            self._last_eval_t = None

    # ---- windows -----------------------------------------------------------
    @staticmethod
    def _delta(start: Dict[str, Any], cur: Dict[str, Any],
               samples: int) -> Dict[str, Any]:
        dt = max(cur["t"] - start["t"], 0.0)
        counters = {k: max(cur["counters"].get(k, 0.0)
                           - start["counters"].get(k, 0.0), 0.0)
                    for k in cur["counters"]}
        fams: Dict[str, List[int]] = {}
        for name, counts in cur["families"].items():
            base = start["families"].get(name)
            if base is None:
                fams[name] = list(counts)
            else:
                # clamp: a `latency reset` mid-window must read as
                # empty, not as negative counts
                fams[name] = [max(a - b, 0)
                              for a, b in zip(counts, base)]
        return {"t": cur["t"], "dt": dt, "counters": counters,
                "families": fams, "samples": samples}

    def _window(self, window_s: float) -> Optional[Dict[str, Any]]:
        """Deltas between the newest sample and the newest sample at
        least *window_s* older (falling back to the OLDEST sample —
        until the ring spans the window, the window is "since the
        first sample", which for a fresh cluster is the mgr's boot
        baseline, i.e. "everything this cluster did")."""
        with self._lock:
            entries = list(self._ring)
        if not entries:
            return None
        cur = entries[-1]
        start = entries[0]
        for e in reversed(entries[:-1]):
            if e["t"] <= cur["t"] - window_s:
                start = e
                break
        return self._delta(start, cur, len(entries))

    def _last_tick(self) -> Optional[Dict[str, Any]]:
        """Delta between the newest two samples — "what happened this
        tick", the signal the SLO sustain/clear streaks count so a
        quiet tick reads as clean even while an old spike still sits
        inside the fast window."""
        with self._lock:
            entries = list(self._ring[-2:])
        if len(entries) < 2:
            return None
        return self._delta(entries[0], entries[1], 2)

    def _family_pcts(self, win: Dict[str, Any],
                     name: str) -> Optional[Dict[str, float]]:
        counts = win["families"].get(name)
        edges = self._edges.get(name)
        if not counts or not edges or not sum(counts):
            return None
        out = percentiles_from_counts(counts, edges, QUANTILES)
        out["count"] = sum(counts)
        return out

    @staticmethod
    def _rates(win: Dict[str, Any]) -> Dict[str, float]:
        dt = win["dt"]
        if dt <= 0:
            return {k: 0.0 for k in RATE_KEYS}
        return {k: round(win["counters"].get(k, 0.0) / dt, 4)
                for k in RATE_KEYS}

    # ---- the shared rollup snapshot ---------------------------------------
    def rollup(self, window_s: Optional[float] = None) -> Dict[str, Any]:
        """THE cluster rollup: every surface (``telemetry dump``,
        ``tpu status``, the Prometheus ``ceph_cluster_*`` families,
        the bench ``cluster_rollup`` block) renders from this one
        function so they cannot drift.  Default window is the SLO
        fast window."""
        obj = self.objectives()
        if window_s is None:
            window_s = obj["fast_window_s"]
        win = self._window(window_s)
        out: Dict[str, Any] = {
            "clock": None, "samples": 0, "window_s": float(window_s),
            "span_s": 0.0, "oplat_p99_usec": {}, "oplat": {},
            "families": {}, "rates": {k: 0.0 for k in RATE_KEYS},
            "copies_per_op": 0.0,
            "slo": self.slo_state(),
            "objectives": {"oplat_p99_usec": obj["oplat_p99_usec"],
                           "copies_per_op_max": obj["copies_per_op_max"],
                           "admission_rate_max":
                               obj["admission_rate_max"]},
        }
        if win is None:
            return out
        out["clock"] = win["t"]
        out["samples"] = win["samples"]
        out["span_s"] = round(win["dt"], 3)
        for name in sorted(win["families"]):
            p = self._family_pcts(win, name)
            if p is None:
                continue
            out["families"][name] = p
            stage = _oplat_stage(name)
            if stage is not None:
                out["oplat"][stage] = p
                out["oplat_p99_usec"][stage] = p["p99"]
        out["rates"] = self._rates(win)
        ops = win["counters"].get("ops", 0.0)
        if ops > 0:
            out["copies_per_op"] = round(
                win["counters"].get("copies", 0.0) / ops, 4)
        return out

    def dump(self) -> Dict[str, Any]:
        """The ``telemetry dump`` admin-socket shape: the shared
        rollup plus ring metadata."""
        out = self.rollup()
        out["retention"] = int(
            g_conf.get_val("mgr_telemetry_retention") or 360)
        return out

    def slo_state(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {check: {
                "state": "breach" if st["active"] else "ok",
                "burn_fast": st["burn_fast"],
                "burn_slow": st["burn_slow"],
                "streak": st["streak"],
                "message": st["message"],
            } for check, st in sorted(self._slo.items())}

    # ---- SLO engine --------------------------------------------------------
    def _oplat_burn(self, win: Optional[Dict[str, Any]],
                    ceilings: Dict[str, float]
                    ) -> Tuple[float, str]:
        """Worst stage burn over one window: max(p99/ceiling)."""
        from ..trace.oplat import stage_hist_name
        worst, msgs = 0.0, []
        if win is None:
            return 0.0, ""
        for stage, ceiling in sorted(ceilings.items()):
            if ceiling <= 0:
                continue
            p = self._family_pcts(win, stage_hist_name(stage))
            if p is None:
                continue
            burn = p["p99"] / ceiling
            if burn > worst:
                worst = burn
            if burn >= 1.0:
                msgs.append(f"{stage} p99 {p['p99']:.0f}us > "
                            f"{ceiling:.0f}us")
        return worst, "; ".join(msgs)

    def _copy_burn(self, win: Optional[Dict[str, Any]],
                   ceiling: float) -> Tuple[float, str]:
        if win is None or ceiling <= 0:
            return 0.0, ""
        ops = win["counters"].get("ops", 0.0)
        if ops <= 0:
            return 0.0, ""      # no ops: nothing to judge
        cpo = win["counters"].get("copies", 0.0) / ops
        return cpo / ceiling, (f"{cpo:.2f} copies/op > "
                               f"{ceiling:.2f}")

    def _admission_burn(self, win: Optional[Dict[str, Any]],
                        ceiling: float) -> Tuple[float, str]:
        if win is None or ceiling <= 0 or win["dt"] <= 0:
            return 0.0, ""
        rate = win["counters"].get("admission_rejections", 0.0) \
            / win["dt"]
        return rate / ceiling, (f"{rate:.2f} rejections/s > "
                                f"{ceiling:.2f}/s")

    def evaluate_slo(self, mgr) -> None:
        """Burn-rate evaluation: the fast/slow windows measure the
        burn (observed/objective), the per-tick delta drives the
        sustain/clear streaks — raise only after
        ``mgr_slo_sustain_ticks`` consecutive breaching ticks with
        both windows confirming, clear only after
        ``mgr_slo_clear_ticks`` consecutive clean ticks (hysteresis).
        A single-tick spike breaches one tick delta, the next is
        clean, the streak resets: it never raises.  Disabled
        objectives tear their check down."""
        obj = self.objectives()
        tick = self._last_tick()
        fast = self._window(obj["fast_window_s"])
        slow = self._window(obj["slow_window_s"])
        verdicts: List[Tuple[str, float, float, float, str]] = []
        if obj["oplat_p99_usec"]:
            bn, _m = self._oplat_burn(tick, obj["oplat_p99_usec"])
            bf, msg = self._oplat_burn(fast, obj["oplat_p99_usec"])
            bs, _m = self._oplat_burn(slow, obj["oplat_p99_usec"])
            verdicts.append((SLO_OPLAT, bn, bf, bs,
                             f"cluster stage p99 over budget: {msg}"))
        if obj["copies_per_op_max"] > 0:
            bn, _m = self._copy_burn(tick, obj["copies_per_op_max"])
            bf, msg = self._copy_burn(fast, obj["copies_per_op_max"])
            bs, _m = self._copy_burn(slow, obj["copies_per_op_max"])
            verdicts.append((SLO_COPY, bn, bf, bs,
                             f"cluster copy budget exceeded: {msg}"))
        if obj["admission_rate_max"] > 0:
            bn, _m = self._admission_burn(tick,
                                          obj["admission_rate_max"])
            bf, msg = self._admission_burn(fast,
                                           obj["admission_rate_max"])
            bs, _m = self._admission_burn(slow,
                                          obj["admission_rate_max"])
            verdicts.append((SLO_ADMISSION, bn, bf, bs,
                             f"admission shedding over budget: {msg}"))
        active_objs = {v[0] for v in verdicts}
        # objectives removed at runtime: drop state + clear the check
        for check in list(self._slo):
            if check not in active_objs:
                with self._lock:
                    st = self._slo.pop(check, None)
                if st and st["active"]:
                    mgr.health_checks.pop(check, None)
                    mgr._cluster_log(
                        "INF", f"Health check cleared: {check} "
                        f"(objective removed)")
        for check, burn_now, burn_fast, burn_slow, message in verdicts:
            with self._lock:
                st = self._slo.setdefault(check, {
                    "active": False, "streak": 0, "clean": 0,
                    "burn_fast": 0.0, "burn_slow": 0.0, "message": ""})
                st["burn_fast"] = round(burn_fast, 3)
                st["burn_slow"] = round(burn_slow, 3)
                if burn_now >= 1.0:
                    st["streak"] += 1
                    st["clean"] = 0
                    streak_opened = st["streak"] == 1
                    clean_opened = False
                else:
                    st["streak"] = 0
                    st["clean"] += 1
                    streak_opened = False
                    clean_opened = st["clean"] == 1 and st["active"]
                raise_now = (not st["active"]
                             and st["streak"] >= obj["sustain_ticks"]
                             and burn_fast >= 1.0
                             and burn_slow >= 1.0)
                clear_now = (st["active"]
                             and st["clean"] >= obj["clear_ticks"])
                if raise_now:
                    st["active"] = True
                    st["message"] = message
                elif clear_now:
                    st["active"] = False
                    st["message"] = ""
                elif st["active"] and burn_fast >= 1.0:
                    # refresh the detail only while the fast window —
                    # which the message's figures come from — still
                    # breaches, so the health text never shows a
                    # "1.50 > 2.00" non-comparison
                    st["message"] = message
            if streak_opened:
                # a sustain streak opened: the first breaching tick of
                # a possible raise — journal it so the incident bundle
                # shows when the pressure began, not just when it won
                g_journal.emit("mgr", "slo_streak", check=check,
                               phase="sustain")
            elif clean_opened:
                g_journal.emit("mgr", "slo_streak", check=check,
                               phase="clean")
            if raise_now:
                mgr.health_checks[check] = message
                mgr._cluster_log(
                    "WRN", f"Health check failed: {check} ({message})")
            elif clear_now:
                mgr.health_checks.pop(check, None)
                mgr._cluster_log(
                    "INF", f"Health check cleared: {check} "
                    f"(burn rate back under budget)")
            elif st["active"]:
                mgr.health_checks[check] = st["message"] or message
        # invariant sweep: a TPU_SLO_* entry in health_checks must be
        # backed by an ACTIVE streak state.  `telemetry reset` and
        # objective disabling can land in any order between ticks —
        # whatever state they erased, a raised check with no active
        # backing must clear here, or health() and slo_state() would
        # disagree forever
        for check in SLO_CHECKS:
            st = self._slo.get(check)
            if (st is None or not st["active"]) \
                    and check in mgr.health_checks:
                mgr.health_checks.pop(check, None)
                mgr._cluster_log(
                    "INF", f"Health check cleared: {check} "
                    f"(telemetry state reset)")
