"""ceph-mgr-lite — the manager daemon's module host.

The reference mgr (src/mgr/, ~9k LoC) subscribes to cluster maps and
hosts python modules (pybind/mgr/: balancer, prometheus, status...).
This is the same shape over the mini-cluster fabric:

- ``balancer``: periodically runs calc_pg_upmaps (the device-batched
  upmap optimizer, osdmap/balancer.py — OSDMap::calc_pg_upmaps role) and
  proposes the resulting pg_upmap_items to the monitor as an
  Incremental, exactly how pybind/mgr/balancer/module.py feeds the mon.
- ``prometheus``: renders cluster gauges + every registered perf counter
  in the Prometheus text exposition format
  (pybind/mgr/prometheus/module.py role).
- ``status``: health / pg / pool summaries for the admin socket.

The mgr is a map subscriber like any daemon: it keeps its own OSDMap
copy current from MOSDMap broadcasts.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..msg import Dispatcher, MOSDMap, Message, Network
from ..osdmap import Incremental, OSDMap
from ..osdmap.balancer import calc_pg_upmaps


class Manager(Dispatcher):
    def __init__(self, network: Network, mon, name: str = "mgr",
                 all_mons=None):
        """*mon* is either a Monitor or a zero-arg resolver returning the
        current leader (failover-safe); *all_mons* subscribes the mgr on
        every monitor so map updates keep flowing after an election."""
        self.network = network
        self._mon = mon
        self.name = name
        self.messenger = network.create_messenger(name)
        self.messenger.add_dispatcher_head(self)
        self.osdmap = OSDMap()
        self.modules = ["balancer", "prometheus", "status"]
        self.balancer_active = False     # 'ceph balancer on' equivalent
        self.last_optimize_result = 0
        for m in (all_mons if all_mons is not None else [self.mon]):
            m.subscribe(name)
        self.mon.send_full_map(name)
        network.pump()

    @property
    def mon(self):
        return self._mon() if callable(self._mon) else self._mon

    # ---- dispatch ----------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        if isinstance(msg, MOSDMap):
            for inc in msg.incrementals:
                if inc.epoch == self.osdmap.epoch + 1:
                    self.osdmap.apply_incremental(inc)

    # ---- balancer module ---------------------------------------------------
    def balancer_optimize(self, max_deviation: float = 0.01,
                          max_iterations: int = 10) -> int:
        """One optimization pass: compute pg_upmap_items on our map copy
        and propose them to the mon (balancer/module.py:optimize ->
        OSDMonitor upmap commands).  Returns the number of changes."""
        import copy
        inc = Incremental()
        work = copy.deepcopy(self.osdmap)
        n = calc_pg_upmaps(work, max_deviation=max_deviation,
                           max_iterations=max_iterations, inc=inc)
        self.last_optimize_result = n
        if n:
            self.mon.publish(inc)
            self.network.pump()
        return n

    def balancer_optimize_crush_compat(self, pool_id: int,
                                       max_iterations: int = 30
                                       ) -> "tuple[float, float]":
        """crush-compat mode (balancer/module.py do_crush_compat):
        optimize a per-position weight_set on the MON's map — the
        choose_args ride the crush map, so the change publishes as a
        topology epoch, no upmap entries involved."""
        from ..osdmap.balancer import calc_weight_set
        before, after = calc_weight_set(self.mon.osdmap, pool_id,
                                        max_iterations=max_iterations)
        if after < before:
            self.mon._topology_dirty = True
            self.mon.publish()
            self.network.pump()
        return before, after

    def tick(self) -> None:
        """Periodic module work (the mgr's serve loops)."""
        if self.balancer_active:
            self.balancer_optimize()

    # ---- status module -----------------------------------------------------
    def status(self) -> Dict:
        m = self.osdmap
        n_up = sum(1 for o in range(m.max_osd) if m.is_up(o))
        n_in = sum(1 for o in range(m.max_osd)
                   if m.exists(o) and m.osd_weight[o] > 0)
        return {
            "epoch": m.epoch,
            "num_osds": m.max_osd,
            "num_up_osds": n_up,
            "num_in_osds": n_in,
            "num_pools": len(m.pools),
            "num_pgs": sum(p.pg_num for p in m.pools.values()),
            "num_pg_upmap_items": len(m.pg_upmap_items),
            "balancer_active": self.balancer_active,
            "last_optimize_result": self.last_optimize_result,
        }

    # ---- prometheus module -------------------------------------------------
    def prometheus_metrics(self, perf_collection=None) -> str:
        """Prometheus text exposition of cluster gauges + perf counters
        (pybind/mgr/prometheus/module.py role)."""
        s = self.status()
        lines: List[str] = []

        def gauge(name: str, value, help_: str, labels: str = "") -> None:
            lines.append(f"# HELP ceph_{name} {help_}")
            lines.append(f"# TYPE ceph_{name} gauge")
            lines.append(f"ceph_{name}{labels} {value}")

        gauge("osdmap_epoch", s["epoch"], "Current osdmap epoch")
        gauge("osd_up", s["num_up_osds"], "OSDs up")
        gauge("osd_in", s["num_in_osds"], "OSDs in")
        gauge("pools", s["num_pools"], "Pools")
        gauge("pgs", s["num_pgs"], "Placement groups")
        if perf_collection is not None:
            dump = perf_collection.dump()
            for logger, counters in sorted(dump.items()):
                if not isinstance(counters, dict):
                    continue
                for cname, val in sorted(counters.items()):
                    if not isinstance(val, (int, float)):
                        continue
                    metric = f"{logger}_{cname}".replace(".", "_")
                    lines.append(
                        f"ceph_daemon_{metric} {val}")
        return "\n".join(lines) + "\n"
