"""ceph-mgr-lite — the manager daemon's module host.

The reference mgr (src/mgr/, ~9k LoC) subscribes to cluster maps and
hosts python modules (pybind/mgr/: balancer, prometheus, status...).
This is the same shape over the mini-cluster fabric:

- ``balancer``: periodically runs calc_pg_upmaps (the device-batched
  upmap optimizer, osdmap/balancer.py — OSDMap::calc_pg_upmaps role) and
  proposes the resulting pg_upmap_items to the monitor as an
  Incremental, exactly how pybind/mgr/balancer/module.py feeds the mon.
- ``prometheus``: renders cluster gauges + every registered perf counter
  in the Prometheus text exposition format
  (pybind/mgr/prometheus/module.py role).
- ``status``: health / pg / pool summaries for the admin socket.

The mgr is a map subscriber like any daemon: it keeps its own OSDMap
copy current from MOSDMap broadcasts.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..msg import Dispatcher, MOSDMap, Message, Network
from ..osdmap import Incremental, OSDMap
from ..osdmap.balancer import calc_pg_upmaps


class Manager(Dispatcher):
    def __init__(self, network: Network, mon, name: str = "mgr",
                 all_mons=None):
        """*mon* is either a Monitor or a zero-arg resolver returning the
        current leader (failover-safe); *all_mons* subscribes the mgr on
        every monitor so map updates keep flowing after an election."""
        self.network = network
        self._mon = mon
        self.name = name
        self.messenger = network.create_messenger(name)
        self.messenger.add_dispatcher_head(self)
        self.osdmap = OSDMap()
        self.modules = ["balancer", "prometheus", "status",
                        "pg_autoscaler"]
        self.balancer_active = False     # 'ceph balancer on' equivalent
        self.last_optimize_result = 0
        # every optimize pass appended here (the restful module's
        # /request history role): (mode, changes_proposed)
        self.proposal_log: List[Dict] = []
        # per-PG usage from primaries' MPGStats reports (newest epoch
        # wins — only the current primary reports a PG, so no double
        # counting):  (pool, ps) -> (epoch, objects, bytes)
        self.pg_stats: Dict[tuple, tuple] = {}
        # osd -> (store_bytes, store_capacity) from MPGStats osd_stat
        self.osd_stats: Dict[int, tuple] = {}
        self.autoscaler_active = False
        self.health_checks: Dict[str, str] = {}
        # cluster telemetry rollup + SLO burn-rate engine
        # (telemetry.py); the boot-time baseline sample makes every
        # window "since this cluster booted" until the ring spans it
        from .telemetry import Telemetry
        self.telemetry = Telemetry()
        self.telemetry.collect(0.0)
        # damped SLO feedback controller (ceph_tpu/control,
        # docs/CONTROL.md): steps after telemetry each tick; with
        # mgr_control_enable off (default) it returns before sensing
        from ..control import Controller
        self.control = Controller()
        # incident forensics (incident.py): bundles auto-captured on
        # health raises, finalized on the matching clear.  The diff
        # baseline below is what the tick compares health_checks
        # against to journal raise/clear transitions — it covers every
        # raise path (the check_* methods, the SLO engine, health()
        # between ticks) with one mechanism
        from .incident import IncidentManager
        self.incident = IncidentManager(self)
        self._journal_health: Dict[str, str] = {}
        for m in (all_mons if all_mons is not None else [self.mon]):
            m.subscribe(name)
        self.mon.send_full_map(name)
        network.pump()

    @property
    def mon(self):
        return self._mon() if callable(self._mon) else self._mon

    # ---- dispatch ----------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        from ..msg.messages import MPGStats
        if isinstance(msg, MOSDMap):
            for inc in msg.incrementals:
                if inc.epoch == self.osdmap.epoch + 1:
                    self.osdmap.apply_incremental(inc)
        elif isinstance(msg, MPGStats):
            for pool, ps, n_obj, n_bytes in msg.pg_stats:
                cur = self.pg_stats.get((pool, ps))
                if cur is not None and cur[0] > msg.epoch:
                    # a map-lagged ex-primary (blackholed from the
                    # mons but not from us) must not clobber the
                    # current primary's numbers
                    continue
                self.pg_stats[(pool, ps)] = (msg.epoch, n_obj, n_bytes)
            if msg.osd >= 0:
                self.osd_stats[msg.osd] = (msg.store_bytes,
                                           msg.store_capacity)

    # ---- balancer module ---------------------------------------------------
    def balancer_optimize(self, max_deviation: float = 0.01,
                          max_iterations: int = 10) -> int:
        """One optimization pass: compute pg_upmap_items on our map copy
        and propose them to the mon (balancer/module.py:optimize ->
        OSDMonitor upmap commands).  Returns the number of changes."""
        import copy
        inc = Incremental()
        work = copy.deepcopy(self.osdmap)
        n = calc_pg_upmaps(work, max_deviation=max_deviation,
                           max_iterations=max_iterations, inc=inc)
        self.last_optimize_result = n
        self.proposal_log.append({"mode": "upmap", "changes": n,
                                  "epoch": self.osdmap.epoch})
        if n:
            self.mon.publish(inc)
            self.network.pump()
        return n

    def balancer_optimize_crush_compat(self, pool_id: int,
                                       max_iterations: int = 30
                                       ) -> "tuple[float, float]":
        """crush-compat mode (balancer/module.py do_crush_compat):
        optimize a per-position weight_set on the MON's map — the
        choose_args ride the crush map, so the change publishes as a
        topology epoch, no upmap entries involved."""
        from ..osdmap.balancer import calc_weight_set
        before, after = calc_weight_set(self.mon.osdmap, pool_id,
                                        max_iterations=max_iterations)
        if after < before:
            self.mon._topology_dirty = True
            self.mon.publish()
            self.network.pump()
        return before, after

    def tick(self, now: Optional[float] = None) -> None:
        """Periodic module work (the mgr's serve loops).  *now* is the
        cluster's deterministic clock (MiniCluster.tick passes it);
        None self-advances the telemetry clock one second per tick."""
        from ..trace.journal import g_journal
        if now is not None:
            # stamp the journal's deterministic clock before any event
            # this tick can emit (no wall clock anywhere in the layer)
            g_journal.set_clock(now)
        if self.balancer_active:
            self.balancer_optimize()
        if self.autoscaler_active:
            self.pg_autoscale(apply=True)
        self.check_quotas_and_fullness()
        self.check_degraded_codecs()
        self.check_mesh_skew()
        # cluster rollup collection + SLO burn-rate evaluation — pure
        # host-side histogram/counter reads, zero added device syncs
        # (the fence-count test in tests/test_observability.py covers
        # this tick)
        self.telemetry.tick(self, now)
        # health transition journal + incident forensics: diff the
        # check set against the last tick's baseline so every raise
        # path lands one health_raise (+ auto-capture) and every clear
        # one health_clear (+ finalize), in tick order.  This runs
        # BEFORE the control step so a raise is journaled ahead of the
        # actuation it provokes — the bundle timeline reads causally
        # (raise -> actuate -> ... -> clear); the actuations land in
        # the bundle when the clear finalizes it
        prev = self._journal_health
        cur = dict(self.health_checks)
        for check in sorted(set(cur) - set(prev)):
            g_journal.emit("mgr", "health_raise", check=check,
                           message=cur[check])
            self.incident.capture(check, cur[check],
                                  reason="health_raise")
        for check in sorted(set(prev) - set(cur)):
            g_journal.emit("mgr", "health_clear", check=check)
            self.incident.resolve(check)
        self._journal_health = cur
        # the control plane closes the loop on the streak state the
        # telemetry tick just refreshed: at most ONE bounded knob step
        # per tick (no-op unless mgr_control_enable)
        self.control.step(self, now if now is not None
                          else self.telemetry._last_eval_t)

    # ---- codec degradation (circuit-breaker board -> health) ---------------
    def check_degraded_codecs(self) -> None:
        """TPU_CODEC_DEGRADED: raised while any codec signature's
        circuit breaker is tripped to the CPU matrix path
        (ceph_tpu/fault), cleared when every breaker restores via its
        half-open probe.  Transitions land in the mon cluster log, the
        check itself rides health/`ceph -s` like OSD_FULL."""
        from ..fault import g_breakers
        deg = g_breakers.degraded()
        had = "TPU_CODEC_DEGRADED" in self.health_checks
        if deg:
            sigs = ", ".join(
                "/".join(d["signature"][:4]) for d in deg)
            self.health_checks["TPU_CODEC_DEGRADED"] = (
                f"{len(deg)} codec signature(s) serving from the CPU "
                f"matrix path: {sigs}")
            if not had:
                self._cluster_log("WRN",
                                  f"Health check failed: "
                                  f"TPU_CODEC_DEGRADED ({sigs})")
        elif had:
            self.health_checks.pop("TPU_CODEC_DEGRADED", None)
            self._cluster_log("INF",
                              "Health check cleared: TPU_CODEC_DEGRADED "
                              "(device path restored)")

    # ---- mesh chip skew (chip-health scoreboard -> health) ------------------
    def check_mesh_skew(self) -> None:
        """TPU_MESH_SKEW: raised while the mesh chip-health scoreboard
        (ceph_tpu/mesh/chipstat) holds any SUSPECT chip — a chip whose
        EWMA probe service time sustained ``ec_mesh_skew_threshold``
        times the mesh median — naming the worst chip and its ratio.
        The hysteresis lives in the scoreboard (the breaker's
        sustain/clear discipline, counted in probes), so this check
        raises the moment a suspect is marked and clears the moment
        the last one sustains clean; transitions ride the same
        health/cluster-log path as check_degraded_codecs."""
        from ..mesh import g_chipstat
        suspects = g_chipstat.suspects()
        had = "TPU_MESH_SKEW" in self.health_checks
        if suspects:
            worst = suspects[0]
            msg = (f"{len(suspects)} mesh chip(s) over the skew "
                   f"threshold: worst chip {worst['chip']} at "
                   f"{worst['skew_ratio']:.1f}x the mesh median "
                   f"service time")
            self.health_checks["TPU_MESH_SKEW"] = msg
            if not had:
                self._cluster_log(
                    "WRN", f"Health check failed: TPU_MESH_SKEW "
                    f"({msg})")
        elif had:
            self.health_checks.pop("TPU_MESH_SKEW", None)
            self._cluster_log(
                "INF", "Health check cleared: TPU_MESH_SKEW (chip "
                "service times back inside the skew threshold)")

    def _cluster_log(self, level: str, message: str) -> None:
        """Best-effort mon cluster-log entry (clog->warn role); a
        mid-election mon must not fail the health pass itself."""
        try:
            self.mon.log_entry(self.name, level, message)
        except (RuntimeError, AttributeError, IndexError):
            pass

    # ---- quota / full-ratio enforcement (the mon's PGMap-driven
    # OSDMonitor::tick role, fed from mgr-side usage digests) --------------
    def check_quotas_and_fullness(self) -> None:
        from ..common.config import g_conf
        from ..osdmap.osdmap import CEPH_OSDMAP_FULL, CEPH_OSDMAP_NEARFULL
        from ..osdmap.types import FLAG_FULL, FLAG_FULL_QUOTA
        mon = self.mon
        if mon is None or not mon.is_leader():
            # only the quorum leader's working map may be staged on —
            # flags pushed at a peon would sit diverged until IT was
            # elected, resurrecting stale state; skip and re-derive
            # from fresh usage next tick instead
            return
        dirty = False
        # pool quotas -> FLAG_FULL_QUOTA|FLAG_FULL (OSDMonitor
        # check_pool_quota semantics: exceed -> full, clear -> unfull)
        usage = self.pool_stats()
        for pid, pool in self.osdmap.pools.items():
            st = usage.get(pid, {"objects": 0, "bytes": 0})
            over = ((pool.quota_max_objects and
                     st["objects"] >= pool.quota_max_objects) or
                    (pool.quota_max_bytes and
                     st["bytes"] >= pool.quota_max_bytes))
            if over:
                dirty |= mon.set_pool_flags(
                    pid, set_mask=FLAG_FULL | FLAG_FULL_QUOTA)
            elif pool.has_flag(FLAG_FULL_QUOTA):
                # only clear FULL we set ourselves (quota-driven)
                dirty |= mon.set_pool_flags(
                    pid, clear_mask=FLAG_FULL | FLAG_FULL_QUOTA)
        # osd fill ratios -> cluster FULL/NEARFULL flags + health
        full_r = float(g_conf.get_val("mon_osd_full_ratio") or 0.95)
        near_r = float(g_conf.get_val("mon_osd_nearfull_ratio") or 0.85)
        full_osds, near_osds = [], []
        for osd, (used, cap) in self.osd_stats.items():
            if not cap:
                continue
            if not self.osdmap.exists(osd) or not self.osdmap.is_up(osd):
                # a dead/removed OSD's last report must not pin the
                # cluster full forever; its data is re-placed anyway
                continue
            ratio = used / cap
            if ratio >= full_r:
                full_osds.append(osd)
            elif ratio >= near_r:
                near_osds.append(osd)
        # both health entries are recomputed every pass so neither can
        # go stale while the other branch is active
        if full_osds:
            dirty |= mon.set_cluster_flags(set_mask=CEPH_OSDMAP_FULL |
                                           CEPH_OSDMAP_NEARFULL)
            self.health_checks["OSD_FULL"] = (
                f"osd(s) {sorted(full_osds)} are full; writes blocked")
        else:
            dirty |= mon.set_cluster_flags(clear_mask=CEPH_OSDMAP_FULL)
            self.health_checks.pop("OSD_FULL", None)
        if near_osds:
            if not full_osds:
                dirty |= mon.set_cluster_flags(
                    set_mask=CEPH_OSDMAP_NEARFULL)
            self.health_checks["OSD_NEARFULL"] = (
                f"osd(s) {sorted(near_osds)} are near full")
        else:
            self.health_checks.pop("OSD_NEARFULL", None)
            if not full_osds:
                dirty |= mon.set_cluster_flags(
                    clear_mask=CEPH_OSDMAP_NEARFULL)
        if dirty:
            try:
                mon.publish()
            except RuntimeError:
                # mid-election / not the leader: flags are staged on
                # this mon's working map; the next elected leader's
                # publish (or our next tick) lands them
                return
            self.network.pump()

    # ---- pg_autoscaler module ----------------------------------------------
    def pool_stats(self) -> Dict[int, Dict[str, int]]:
        """Per-pool usage aggregated from primaries' MPGStats reports
        (the mgr's PGMap-digest role).  Stale entries for PGs a pool no
        longer has (pre-split parents never re-report) are skipped."""
        out: Dict[int, Dict[str, int]] = {}
        stale = []
        for (pool, ps), (_e, n_obj, n_bytes) in self.pg_stats.items():
            p = self.osdmap.pools.get(pool)
            if p is None or ps >= p.pg_num:
                stale.append((pool, ps))   # deleted pool / split parent
                continue
            d = out.setdefault(pool, {"objects": 0, "bytes": 0,
                                      "pgs_reporting": 0})
            d["objects"] += n_obj
            d["bytes"] += n_bytes
            d["pgs_reporting"] += 1
        for key in stale:
            del self.pg_stats[key]
        return out

    def pg_autoscale(self, target_pgs_per_osd: int = 100,
                     threshold: float = 3.0,
                     apply: bool = False) -> List[Dict]:
        """Recommend (and optionally apply) per-pool pg_num targets
        (pybind/mgr/pg_autoscaler/module.py): each pool's share of the
        cluster's used bytes earns it a share of the PG budget
        (target_pgs_per_osd x in-OSDs), divided by its replication
        cost, rounded to a power of two.  A change is recommended only
        when the pool is off by *threshold* in either direction; only
        growth can be applied (splitting exists, merging does not — a
        shrink recommendation is report-only, like the reference's
        warn mode)."""
        m = self.osdmap
        n_in = self.num_in_osds()
        stats = self.pool_stats()
        total_bytes = sum(d["bytes"] for d in stats.values())
        budget = target_pgs_per_osd * max(n_in, 1)
        out: List[Dict] = []
        for pid, pool in sorted(m.pools.items()):
            used = stats.get(pid, {}).get("bytes", 0)
            if total_bytes <= 0:
                # empty cluster: spread the budget evenly
                ratio = 1.0 / max(len(m.pools), 1)
            else:
                ratio = used / total_bytes
            raw = ratio * budget / max(pool.size, 1)
            target = 1
            while target * 2 <= max(raw, 1):
                target *= 2
            target = max(target, 4)      # pg_num_min floor
            action = "ok"
            if target >= pool.pg_num * threshold:
                action = "grow"
            elif target * threshold <= pool.pg_num:
                action = "shrink (report-only)"
            ent = {"pool_id": pid,
                   "pool": m.pool_name.get(pid, str(pid)),
                   "bytes": used, "ratio": round(ratio, 4),
                   "pg_num": pool.pg_num, "target": target,
                   "action": action}
            if apply and action == "grow":
                # grow like an operator would: pg_num first (children
                # split in place), then pgp_num (children spread to
                # their own CRUSH positions, pg_temp-primed)
                name = m.pool_name[pid]
                self.mon.set_pool_pg_num(name, target)
                self.mon.publish()
                self.mon.set_pool_pgp_num(name, target)
                self.mon.publish()
                self.network.pump()
                ent["applied"] = True
            out.append(ent)
        return out

    def num_in_osds(self) -> int:
        m = self.osdmap
        return sum(1 for o in range(m.max_osd)
                   if m.exists(o) and m.osd_weight[o] > 0)

    # ---- status module -----------------------------------------------------
    def status(self) -> Dict:
        m = self.osdmap
        n_up = sum(1 for o in range(m.max_osd) if m.is_up(o))
        n_in = self.num_in_osds()
        return {
            "epoch": m.epoch,
            "num_osds": m.max_osd,
            "num_up_osds": n_up,
            "num_in_osds": n_in,
            "num_pools": len(m.pools),
            "num_pgs": sum(p.pg_num for p in m.pools.values()),
            "num_pg_upmap_items": len(m.pg_upmap_items),
            "balancer_active": self.balancer_active,
            "last_optimize_result": self.last_optimize_result,
            "osdmap_flags": m.flags,
            "health_checks": dict(self.health_checks),
        }

    # ---- prometheus module -------------------------------------------------
    @staticmethod
    def _prom_name(raw: str) -> str:
        """Sanitize to the exposition-format name charset."""
        import re
        return re.sub(r"[^a-zA-Z0-9_:]", "_", raw)

    def prometheus_metrics(self, perf_collection=None, histograms=None,
                           kernel_timer=None, slow_ops=None,
                           breakers=None) -> str:
        """Prometheus text exposition of cluster gauges + perf counters
        (pybind/mgr/prometheus/module.py role), grown the observability
        surfaces: ``histograms`` (a PerfHistogramCollection) renders as
        real ``# TYPE ... histogram`` families with cumulative
        ``_bucket{le=...}`` series over the latency axis (usec buckets
        exported as seconds), ``kernel_timer`` as dispatch-total
        counters, and ``slow_ops`` ({daemon: count}) as gauges."""
        s = self.status()
        lines: List[str] = []

        def gauge(name: str, value, help_: str, labels: str = "") -> None:
            lines.append(f"# HELP ceph_{name} {help_}")
            lines.append(f"# TYPE ceph_{name} gauge")
            lines.append(f"ceph_{name}{labels} {value}")

        gauge("osdmap_epoch", s["epoch"], "Current osdmap epoch")
        gauge("osd_up", s["num_up_osds"], "OSDs up")
        gauge("osd_in", s["num_in_osds"], "OSDs in")
        gauge("pools", s["num_pools"], "Pools")
        gauge("pgs", s["num_pgs"], "Placement groups")
        if self.health_checks:
            lines.append("# HELP ceph_health_check active cluster "
                         "health checks (1 = raised)")
            lines.append("# TYPE ceph_health_check gauge")
            for check in sorted(self.health_checks):
                lines.append(f'ceph_health_check'
                             f'{{check="{self._prom_name(check)}"}} 1')
        if breakers is not None:
            deg = breakers.degraded()
            gauge("tpu_codec_degraded", len(deg),
                  "codec signatures tripped to the CPU matrix path")
            if deg:
                lines.append("# HELP ceph_tpu_codec_breaker_open per-"
                             "signature breaker state (1 = open)")
                lines.append("# TYPE ceph_tpu_codec_breaker_open gauge")
                for d in deg:
                    sig = self._prom_name("_".join(d["signature"][:4]))
                    lines.append(f'ceph_tpu_codec_breaker_open'
                                 f'{{signature="{sig}"}} 1')
        # the ceph_cluster_* families render from the SAME rollup
        # snapshot function `telemetry dump` and `tpu status` serve
        # (telemetry.rollup), so the scrape surfaces cannot drift
        lines.extend(self._render_cluster_rollup(self.telemetry))
        # control-plane rollup: total actuations this mgr has applied
        # (the per-kind breakdown rides ceph_daemon_control_*)
        lines.append("# HELP ceph_cluster_control_moves knob "
                     "actuations applied by the mgr control plane")
        lines.append("# TYPE ceph_cluster_control_moves gauge")
        lines.append(f"ceph_cluster_control_moves "
                     f"{self.control.moves_total}")
        lines.append("# HELP ceph_cluster_incidents_total incident "
                     "bundles captured by this mgr")
        lines.append("# TYPE ceph_cluster_incidents_total gauge")
        lines.append(f"ceph_cluster_incidents_total "
                     f"{self.incident.captures_total}")
        # chaos rollup: storylines executed / accepted in this process
        # (the full per-scenario breakdown rides the chaos logger below)
        from ..chaos.engine import (chaos_perf_counters,
                                    l_chaos_accept_pass, l_chaos_scenarios)
        cpc = chaos_perf_counters()
        lines.append("# HELP ceph_cluster_chaos_scenarios composed-"
                     "chaos storylines executed end to end")
        lines.append("# TYPE ceph_cluster_chaos_scenarios gauge")
        lines.append(f"ceph_cluster_chaos_scenarios "
                     f"{cpc.get(l_chaos_scenarios)}")
        lines.append("# HELP ceph_cluster_chaos_accepted composed-"
                     "chaos storylines that passed universal acceptance")
        lines.append("# TYPE ceph_cluster_chaos_accepted gauge")
        lines.append(f"ceph_cluster_chaos_accepted "
                     f"{cpc.get(l_chaos_accept_pass)}")
        if perf_collection is not None:
            dump = perf_collection.dump()
            for logger, counters in sorted(dump.items()):
                if not isinstance(counters, dict):
                    continue
                for cname, val in sorted(counters.items()):
                    if not isinstance(val, (int, float)):
                        continue
                    metric = self._prom_name(f"{logger}_{cname}")
                    lines.append(
                        f"ceph_daemon_{metric} {val}")
        if histograms is not None:
            lines.extend(self._render_histograms(histograms))
        if kernel_timer is not None:
            stats = kernel_timer.dump()
            if stats:
                lines.append("# HELP ceph_kernel_dispatch_seconds_total "
                             "cumulative device dispatch wall time")
                lines.append(
                    "# TYPE ceph_kernel_dispatch_seconds_total counter")
                for kname, st in sorted(stats.items()):
                    lines.append(
                        f'ceph_kernel_dispatch_seconds_total'
                        f'{{kernel="{self._prom_name(kname)}"}} '
                        f'{st["total_s"]}')
                lines.append("# HELP ceph_kernel_dispatch_calls_total "
                             "device dispatches timed")
                lines.append(
                    "# TYPE ceph_kernel_dispatch_calls_total counter")
                for kname, st in sorted(stats.items()):
                    lines.append(
                        f'ceph_kernel_dispatch_calls_total'
                        f'{{kernel="{self._prom_name(kname)}"}} '
                        f'{st["calls"]}')
        if slow_ops is not None:
            lines.append("# HELP ceph_daemon_slow_ops ops slower than "
                         "complaint_time in the flight recorder")
            lines.append("# TYPE ceph_daemon_slow_ops gauge")
            for daemon, n in sorted(slow_ops.items()):
                lines.append(f'ceph_daemon_slow_ops'
                             f'{{daemon="{self._prom_name(daemon)}"}} {n}')
        return "\n".join(lines) + "\n"

    def _render_cluster_rollup(self, telemetry) -> List[str]:
        """The ``ceph_cluster_*`` families: per-stage cluster
        percentiles + rates out of THE shared rollup snapshot
        (telemetry.rollup — the same function ``telemetry dump`` and
        ``tpu status`` render from, so the surfaces cannot drift).
        SLO breach state itself rides ``ceph_health_check`` via
        ``health_checks`` like every other check; the burn-rate
        gauges here carry the continuous signal."""
        roll = telemetry.rollup()
        out: List[str] = []
        for q in ("p50", "p99", "p999"):
            fam = f"ceph_cluster_oplat_{q}_usec"
            out.append(f"# HELP {fam} cluster-merged oplat stage "
                       f"{q} (union of every daemon's buckets, "
                       f"rollup window)")
            out.append(f"# TYPE {fam} gauge")
            for stage in sorted(roll["oplat"]):
                out.append(f'{fam}{{stage='
                           f'"{self._prom_name(stage)}"}} '
                           f'{roll["oplat"][stage][q]}')
        for key in sorted(roll["rates"]):
            fam = f"ceph_cluster_rate_{self._prom_name(key)}"
            out.append(f"# HELP {fam} cluster {key} per second over "
                       f"the rollup window")
            out.append(f"# TYPE {fam} gauge")
            out.append(f"{fam} {roll['rates'][key]}")
        slo = roll.get("slo", {})
        if slo:
            out.append("# HELP ceph_cluster_slo_burn SLO burn rate "
                       "(observed/objective) per check and window")
            out.append("# TYPE ceph_cluster_slo_burn gauge")
            for check in sorted(slo):
                c = self._prom_name(check)
                out.append(f'ceph_cluster_slo_burn{{check="{c}",'
                           f'window="fast"}} {slo[check]["burn_fast"]}')
                out.append(f'ceph_cluster_slo_burn{{check="{c}",'
                           f'window="slow"}} {slo[check]["burn_slow"]}')
        return out

    def _render_histograms(self, histograms) -> List[str]:
        """One Prometheus histogram family per histogram NAME, a series
        per daemon (label), buckets cumulative over the latency axis."""
        by_name: Dict[str, List] = {}
        for (logger, hname), hist in histograms.items():
            by_name.setdefault(hname, []).append((logger, hist))
        out: List[str] = []
        for hname in sorted(by_name):
            base = self._prom_name(f"ceph_{hname}")
            # axis-0 unit drives the exported scale: usec axes render
            # as seconds (Prometheus convention); dimensionless axes
            # (e.g. the dispatcher's batch occupancy) render raw
            ax0 = by_name[hname][0][1].axes[0]
            usec = ax0.name.endswith("_usec")
            scale = 1e6 if usec else 1.0
            unit = "seconds" if usec else ax0.name
            out.append(f"# HELP {base} {ax0.name} distribution "
                       f"(axis buckets exported as {unit})")
            out.append(f"# TYPE {base} histogram")
            for logger, hist in sorted(by_name[hname]):
                label = self._prom_name(logger)
                for edge, cum in hist.cumulative_axis0():
                    le = "+Inf" if edge == float("inf") \
                        else repr(edge / scale)
                    out.append(f'{base}_bucket{{daemon="{label}",'
                               f'le="{le}"}} {cum}')
                out.append(f'{base}_sum{{daemon="{label}"}} '
                           f'{hist.axis0_sum / scale}')
                out.append(f'{base}_count{{daemon="{label}"}} '
                           f'{hist.total_count}')
        return out
