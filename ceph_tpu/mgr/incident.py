"""Incident forensics — auto-captured diagnostic bundles.

The event journal (trace/journal.py) answers "what happened, in
order"; this module answers "what did the cluster LOOK like at the
moment it went wrong".  On any health-check RAISE the mgr's tick calls
:meth:`IncidentManager.capture`, which snapshots one bundle — the
triggering check and its SLO streak state, the merged timeline tail,
the cluster rollup, the worst historic slow ops with their stage and
copy ledgers, the open breakers, the chip scoreboard, and the control
plane's episode/ledger state — into a bounded archive
(``mgr_incident_retention``).  When the triggering check later CLEARS,
the open incident is finalized: the timeline grows every event since
capture (actuations, restores, the clear itself), so a resolved
bundle tells the whole raise→react→recover story by itself.

Capture runs under the bounded fault site ``mgr.incident_capture``: a
failing capture drops the bundle (counted, journaled) and the tick
proceeds — forensics must never wedge the cluster it is documenting.
Everything here is pure host-side dict assembly: zero device syncs
(fence-count-pinned in tests/test_observability.py).
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, Dict, List, Optional

from ..common.config import g_conf
from ..common.lockdep import DebugLock
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.journal import g_journal

# ---- incident perf counters (perf dump / Prometheus
# ceph_daemon_incident_*) --------------------------------------------------
INCIDENT_FIRST = 95200
l_inc_captures = 95201       # bundles captured (any reason)
l_inc_operator = 95202       # captures requested via the asok verb
l_inc_dropped = 95203        # captures dropped by a failure/injection
l_inc_resolved = 95204       # open incidents finalized by their clear
l_inc_pruned = 95205         # bundles evicted by the retention bound
l_inc_open = 95206           # gauge: incidents awaiting their clear
INCIDENT_LAST = 95210

_inc_pc: Optional[PerfCounters] = None
_inc_pc_lock = DebugLock("incident_pc::init")


def incident_perf_counters() -> PerfCounters:
    global _inc_pc
    if _inc_pc is not None:
        return _inc_pc
    with _inc_pc_lock:
        if _inc_pc is None:
            b = PerfCountersBuilder("incident", INCIDENT_FIRST,
                                    INCIDENT_LAST)
            b.add_u64_counter(l_inc_captures, "captures",
                              "incident bundles captured")
            b.add_u64_counter(l_inc_operator, "operator_captures",
                              "captures requested by 'tpu incident "
                              "capture'")
            b.add_u64_counter(l_inc_dropped, "dropped",
                              "captures dropped by a failure or "
                              "injection")
            b.add_u64_counter(l_inc_resolved, "resolved",
                              "incidents finalized by their check's "
                              "clear")
            b.add_u64_counter(l_inc_pruned, "pruned",
                              "bundles evicted by mgr_incident_"
                              "retention")
            b.add_u64(l_inc_open, "open",
                      "incidents awaiting their clear (gauge)")
            _inc_pc = b.create_perf_counters()
    return _inc_pc


# every live archive, so ONE config observer can prune all of them the
# moment an operator shrinks mgr_incident_retention (injectargs-live)
_managers: "weakref.WeakSet[IncidentManager]" = weakref.WeakSet()
_observer_registered = False
_observer_lock = DebugLock("incident_observer::init")


def _on_retention_change(_name: str, _value: Any) -> None:
    for m in list(_managers):
        m.prune()


def _register_observer() -> None:
    global _observer_registered
    with _observer_lock:
        if not _observer_registered:
            g_conf.add_observer("mgr_incident_retention",
                                _on_retention_change)
            _observer_registered = True


class IncidentManager:
    """One mgr's bounded incident archive.

    Per-Manager (not process-global) so every MiniCluster starts with
    a clean archive while the journal singleton keeps the process-wide
    event record the bundles index into.
    """

    def __init__(self, mgr) -> None:
        self._mgr = weakref.ref(mgr)
        self._lock = DebugLock("IncidentManager::lock")
        self._archive: List[dict] = []
        self._next_id = 1
        self._captures_total = 0
        # MiniCluster wires this to the OSDs' trackers; the mgr itself
        # holds no daemon references (it is a map subscriber)
        self.slow_ops_source: Optional[
            Callable[[], Dict[str, dict]]] = None
        _managers.add(self)
        _register_observer()

    # ---- options (read live) -------------------------------------------
    @staticmethod
    def _retention() -> int:
        return int(g_conf.get_val("mgr_incident_retention"))

    @staticmethod
    def _tail() -> int:
        return int(g_conf.get_val("mgr_incident_timeline_tail"))

    # ---- capture --------------------------------------------------------
    def capture(self, trigger: str, message: str = "",
                reason: str = "health_raise") -> Optional[dict]:
        """Snapshot one bundle; returns it, or None when the capture
        was dropped.  Runs under the ``mgr.incident_capture`` fault
        site and a broad except: a failing capture loses THIS bundle,
        never the tick — the next raise captures normally."""
        from ..fault import g_faults
        pc = incident_perf_counters()
        try:
            g_faults.check("mgr.incident_capture", trigger)
            bundle = self._build_bundle(trigger, message, reason)
        except Exception as e:
            pc.inc(l_inc_dropped)
            g_journal.emit("mgr", "incident_drop", trigger=trigger,
                           error=str(e))
            return None
        with self._lock:
            bundle["id"] = self._next_id
            self._next_id += 1
            self._captures_total += 1
            self._archive.append(bundle)
        pc.inc(l_inc_captures)
        if reason == "operator":
            pc.inc(l_inc_operator)
        g_journal.emit("mgr", "incident_capture", id=bundle["id"],
                       trigger=trigger, reason=reason)
        self.prune()
        self._set_open_gauge()
        return bundle

    def _build_bundle(self, trigger: str, message: str,
                      reason: str) -> dict:
        mgr = self._mgr()
        tail = self._tail()
        slow_ops = self._worst_slow_ops()
        from ..fault import g_breakers
        from ..mesh import g_chipstat
        bundle: Dict[str, Any] = {
            "id": 0,                       # assigned under the lock
            "clock": g_journal.clock(),
            "state": "open" if reason == "health_raise" else "manual",
            "reason": reason,
            "trigger": {"check": trigger, "message": message},
            "slo": mgr.telemetry.slo_state() if mgr else {},
            "health_checks": dict(mgr.health_checks) if mgr else {},
            "timeline": g_journal.merged(tail=tail),
            "timeline_gseq": g_journal.last_gseq(),
            "rollup": mgr.telemetry.rollup() if mgr else {},
            "slow_ops": slow_ops,
            "breakers_open": g_breakers.degraded(),
            "chip_scoreboard": g_chipstat.summary(),
            "control": mgr.control.dump() if mgr else {},
        }
        return bundle

    def _worst_slow_ops(self, worst: int = 3) -> List[dict]:
        """The worst historic slow ops across the wired daemons, with
        their stage + copy ledgers (the forensics payload; span trees
        stay behind ``dump_historic_slow_ops`` — bundles index, they
        do not duplicate the whole trace store)."""
        if self.slow_ops_source is None:
            return []
        rows: List[dict] = []
        for daemon, dump in sorted(self.slow_ops_source().items()):
            for op in dump.get("ops", []):
                rows.append({
                    "daemon": daemon,
                    "description": op.get("description", ""),
                    "age": op.get("age", 0.0),
                    "stage_ledger": op.get("stage_ledger"),
                    "copy_ledger": op.get("copy_ledger"),
                })
        rows.sort(key=lambda r: r["age"], reverse=True)
        return rows[:worst]

    # ---- resolve --------------------------------------------------------
    def resolve(self, check: str) -> Optional[dict]:
        """The triggering check cleared: finalize the newest open
        incident for it — grow the timeline with every event since
        capture (the reaction and the clear), mark it resolved."""
        with self._lock:
            target = None
            for bundle in reversed(self._archive):
                if bundle["state"] == "open" \
                        and bundle["trigger"]["check"] == check:
                    target = bundle
                    break
            if target is None:
                return None
            since = g_journal.merged_since(target["timeline_gseq"],
                                           tail=self._tail())
            target["timeline"].extend(since)
            if since:
                target["timeline_gseq"] = since[-1]["gseq"]
            target["state"] = "resolved"
            target["resolved_clock"] = g_journal.clock()
            bid = target["id"]
        incident_perf_counters().inc(l_inc_resolved)
        g_journal.emit("mgr", "incident_resolve", id=bid, trigger=check)
        self._set_open_gauge()
        return target

    # ---- bounds ---------------------------------------------------------
    def prune(self) -> int:
        """Evict past the retention bound (oldest first); called on
        capture and by the config observer so an injectargs shrink
        takes effect immediately."""
        keep = max(self._retention(), 0)
        with self._lock:
            over = len(self._archive) - keep
            if over > 0:
                del self._archive[:over]
        if over > 0:
            incident_perf_counters().inc(l_inc_pruned, over)
            self._set_open_gauge()
        return max(over, 0)

    def _set_open_gauge(self) -> None:
        with self._lock:
            n = sum(1 for b in self._archive if b["state"] == "open")
        incident_perf_counters().set(l_inc_open, n)

    # ---- views ----------------------------------------------------------
    @property
    def captures_total(self) -> int:
        with self._lock:
            return self._captures_total

    def list(self) -> dict:
        """asok ``tpu incident list`` — one row per archived bundle."""
        with self._lock:
            rows = [{"id": b["id"], "clock": b["clock"],
                     "state": b["state"], "reason": b["reason"],
                     "trigger": b["trigger"]["check"],
                     "events": len(b["timeline"])}
                    for b in self._archive]
            total = self._captures_total
        return {"captures_total": total,
                "retention": self._retention(),
                "incidents": rows}

    def dump(self, incident_id: int = 0) -> dict:
        """asok ``tpu incident dump [id]`` — the full bundle (newest
        when *incident_id* is 0)."""
        with self._lock:
            if not self._archive:
                return {"incident": None}
            if incident_id:
                for b in self._archive:
                    if b["id"] == incident_id:
                        return {"incident": dict(b)}
                raise ValueError(f"no incident with id {incident_id}")
            return {"incident": dict(self._archive[-1])}

    def receipt(self) -> dict:
        """The bench workloads' ``incidents`` receipt block: compact
        per-incident rows plus the causal skeleton of the newest
        bundle's timeline (type+daemon only — receipts diff cleanly)."""
        with self._lock:
            rows = [{"id": b["id"], "state": b["state"],
                     "reason": b["reason"],
                     "trigger": b["trigger"]["check"],
                     "events": len(b["timeline"])}
                    for b in self._archive]
            skeleton = [f'{e["daemon"]}:{e["type"]}'
                        for e in self._archive[-1]["timeline"]] \
                if self._archive else []
            total = self._captures_total
        return {"captures_total": total, "incidents": rows,
                "newest_timeline": skeleton}
